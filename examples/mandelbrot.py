"""Render the Mandelbrot set with Worker actors and write a P4 PBM
(≙ reference examples/mandelbrot writing its bitmap through files).

    python examples/mandelbrot.py [width] [out.pbm]
"""
import sys

sys.path.insert(0, ".")

from ponyc_tpu.models import mandelbrot  # noqa: E402
from ponyc_tpu.platforms import auto_backend  # noqa: E402


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    width = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    out = sys.argv[2] if len(sys.argv) > 2 else "/tmp/mandelbrot.pbm"
    grid = mandelbrot.render(width, width)
    mandelbrot.write_pbm(out, grid, width)
    inside = sum(bin(b).count("1") for b in grid.tobytes())
    print(f"{width}x{width}: {inside} pixels in the set -> {out}")


if __name__ == "__main__":
    main()
