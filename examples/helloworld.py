"""Hello world (≙ examples/helloworld): one actor, one message."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.platforms import auto_backend  # noqa: E402


@actor
class Main:
    HOST = True          # prints → host actor (≙ env.out)

    @behaviour
    def create(self, st, _: I32):
        print("Hello, world!")
        self.exit(0)
        return st


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    rt = Runtime(RuntimeOptions(msg_words=1)).declare(Main, 1).start()
    rt.send(rt.spawn(Main), Main.create, 0)
    sys.exit(rt.run())


if __name__ == "__main__":
    main()
