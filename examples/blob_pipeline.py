"""Rich message payloads on-device: the blob pool end to end.

  python examples/blob_pipeline.py

≙ the reference idiom of shipping `String iso` / `Array[U32] val`
payloads between actors (pony_alloc_msg object graphs): here payloads
live in the DEVICE blob pool and ride messages as capability-checked
handles — no host round-trip per message.

Three stages:
  1. the host stores UTF-8 lines as blobs (`rt.blob_store_str`) and
     sends each to a Tokenizer — an ISO move: the host loses the handle;
  2. each Tokenizer computes a checksum + length from the words, frees
     its input, and publishes ONE frozen summary blob (`blob_freeze`)
     broadcast to BOTH reviewers — a VAL alias, legal for frozen blobs;
  3. Reviewers accumulate from the shared summaries; nobody frees them
     (val has no owner) — `rt.gc()` reclaims the replicas at the end.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import (Blob, BlobVal, I32, Ref, Runtime,  # noqa: E402
                       RuntimeOptions, actor, behaviour)
from ponyc_tpu.platforms import auto_backend  # noqa: E402

W = 16          # pool width: up to 64 UTF-8 bytes per line


@actor
class Tokenizer:
    a: Ref["Reviewer"]
    b: Ref["Reviewer"]
    MAX_BLOBS = 1       # one alloc per dispatch...
    BATCH = 2           # ...and up to 2 dispatches per tick reserve
    #   2×1 pool slots per runnable tokenizer (BLOB_DISPATCHES defaults
    #   to BATCH; see docs/MIGRATION.md on sizing)
    MAX_SENDS = 2

    @behaviour
    def take(self, st, line: Blob):
        import jax.numpy as jnp
        ln = self.blob_length(line)
        s = jnp.int32(0)
        for i in range(W):
            s = s + jnp.where(i < ln, self.blob_get(line, i), 0)
        self.blob_free(line)                     # consumed the input
        out = self.blob_alloc(length=2)
        self.blob_set(out, 0, s)                 # checksum
        self.blob_set(out, 1, ln)                # word count
        summary = self.blob_freeze(out)          # shared-immutable now
        self.send(st["a"], Reviewer.review, summary)
        self.send(st["b"], Reviewer.review, summary)   # alias: val
        return st


@actor
class Reviewer:
    checks: I32
    words: I32
    n: I32

    @behaviour
    def review(self, st, summary: BlobVal):
        return {"checks": st["checks"] + self.blob_get(summary, 0),
                "words": st["words"] + self.blob_get(summary, 1),
                "n": st["n"] + 1}


def main():
    auto_backend()
    lines = ["hello pony", "actors all the way down",
             "payloads live on the device now"]
    rt = Runtime(RuntimeOptions(blob_slots=32, blob_words=W, msg_words=2,
                                max_sends=2))
    rt.declare(Tokenizer, 4).declare(Reviewer, 4).start()
    r1 = rt.spawn(Reviewer, checks=0, words=0, n=0)
    r2 = rt.spawn(Reviewer, checks=0, words=0, n=0)
    tok = rt.spawn(Tokenizer, a=r1, b=r2)
    for text in lines:
        rt.send(tok, Tokenizer.take, rt.blob_store_str(text))
    rt.run()
    s1, s2 = rt.state_of(r1), rt.state_of(r2)
    assert s1 == s2, (s1, s2)            # both saw every shared summary
    print(f"{s1['n']} lines: checksum {s1['checks'] & 0xFFFFFFFF:#x}, "
          f"{s1['words']} payload words")
    print("blobs in use before gc:", rt.blobs_in_use)   # frozen summaries
    rt.gc()
    print("blobs in use after gc: ", rt.blobs_in_use)   # reclaimed
    assert rt.blobs_in_use == 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
