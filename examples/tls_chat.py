"""TLS chat server — the round-trip showcase: one program using the
net layer with TLS, iso payload handles, device actors for fan-out
bookkeeping, and host actors for I/O (≙ the reference's chat-server
idiom: a TCPListener whose notify spawns per-connection actors,
upgraded with the SSL filter layer).

Architecture:
  - `Hub` (HOST): owns the listener; on_accept registers the client,
    on_data broadcasts the line to every connected client (payloads
    ride the HostHeap), on_closed unregisters.
  - `Stats` (device): a device actor counting messages/joins — the
    device world observing host traffic (every broadcast pings it).

Run plainly and it drives itself: spawns the server on an ephemeral
loopback port, connects three TLS clients, has them chat, and prints
the transcript. With `--port` it serves the ephemeral port until
Ctrl-C (connect with: openssl s_client -connect 127.0.0.1:<printed>).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.platforms import auto_backend  # noqa: E402
from ponyc_tpu.net.tls import (TLSClientConfig,  # noqa: E402
                               TLSServerConfig)


def selfsigned_cert():
    """Generate a throwaway localhost cert (demo only)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost")]), critical=False)
            .sign(key, hashes.SHA256()))
    d = tempfile.mkdtemp(prefix="tlschat")
    cf, kf = os.path.join(d, "cert.pem"), os.path.join(d, "key.pem")
    with open(cf, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(kf, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    return cf, kf


@actor
class Stats:
    """Device-side bookkeeping: the host hub pings it per event."""
    joins: I32
    lines: I32

    @behaviour
    def joined(self, st, _: I32):
        return {**st, "joins": st["joins"] + 1}

    @behaviour
    def chatted(self, st, _: I32):
        return {**st, "lines": st["lines"] + 1}


@actor
class Hub:
    HOST = True
    stats: I32
    n: I32

    @behaviour
    def on_accept(self, st, cid: I32):
        MEMBERS.add(int(cid))
        self.rt.net.send(int(cid), b"* welcome to tls-chat\n")
        self.send(st["stats"], Stats.joined, 0)
        return {**st, "n": st["n"] + 1}

    @behaviour
    def on_data(self, st, cid: I32, h: I32, n: I32):
        line = self.rt.heap.unbox(int(h))          # iso payload: ours now
        TRANSCRIPT.append((int(cid), bytes(line)))
        out = b"[%d] " % int(cid) + bytes(line)
        for m in list(MEMBERS):
            try:
                self.rt.net.send(m, out)           # encrypted per member
            except KeyError:
                MEMBERS.discard(m)
        self.send(st["stats"], Stats.chatted, 0)
        return st

    @behaviour
    def on_closed(self, st, cid: I32):
        MEMBERS.discard(int(cid))
        return st


@actor
class Client:
    HOST = True
    got: I32

    @behaviour
    def on_connect(self, st, cid: I32, err: I32):
        return st

    @behaviour
    def on_data(self, st, cid: I32, h: I32, n: I32):
        RECEIVED.setdefault(int(cid), []).append(
            self.rt.heap.unbox(int(h)))
        return {**st, "got": st["got"] + 1}

    @behaviour
    def on_closed(self, st, cid: I32):
        return st


MEMBERS = set()
TRANSCRIPT = []
RECEIVED = {}


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    certfile, keyfile = selfsigned_cert()
    rt = Runtime(RuntimeOptions(mailbox_cap=16, batch=4, max_sends=1,
                                msg_words=3, inject_slots=64))
    rt.declare(Hub, 1).declare(Client, 4).declare(Stats, 1).start()
    stats = rt.spawn(Stats)
    hub = rt.spawn(Hub, stats=int(stats))
    net = rt.attach_net()
    lid = net.listen_tcp("127.0.0.1", 0, hub,
                         on_accept=Hub.on_accept, on_data=Hub.on_data,
                         on_closed=Hub.on_closed,
                         tls=TLSServerConfig(certfile, keyfile))
    port = net.listen_port(lid)
    print(f"tls-chat listening on 127.0.0.1:{port}")

    try:
        if "--port" in sys.argv:
            # Serve mode: stay up until Ctrl-C; connect with
            #   openssl s_client -connect 127.0.0.1:<port>
            rt.add_noisy()             # a server is never "done"
            try:
                rt.run()
            except KeyboardInterrupt:
                print("\nshutting down")
            return

        # Scripted session: three TLS clients join and chat.
        ccfg = TLSClientConfig("localhost", cafile=certfile)
        cids = []
        for _ in range(3):
            c = rt.spawn(Client)
            cids.append(net.connect_tcp("127.0.0.1", port, c,
                                        on_connect=Client.on_connect,
                                        on_data=Client.on_data,
                                        on_closed=Client.on_closed,
                                        tls=ccfg))
        net.send(cids[0], b"hello from alice\n")
        net.send(cids[1], b"hi, bob here\n")
        net.send(cids[2], b"carol joining in\n")

        def lines_seen():
            # TLS coalesces records: count NEWLINES, not deliveries.
            return sum(chunk.count(b"\n")
                       for v in RECEIVED.values() for chunk in v)

        for _ in range(4000):
            rt.run(max_steps=4)
            if len(TRANSCRIPT) >= 3 and lines_seen() >= 12:
                break                  # (welcome + 3 lines) × 3 members
        st = rt.state_of(stats)
        print(f"joins={st['joins']} lines={st['lines']} "
              f"members={len(MEMBERS)}")
        for cid, line in TRANSCRIPT:
            print(f"  [{cid}] {line.decode().strip()}")
        assert st["joins"] == 3 and st["lines"] == 3
        assert lines_seen() >= 12
        print("chat session over (all lines broadcast over TLS)")
    finally:
        net.close_all()
        rt.stop()


if __name__ == "__main__":
    main()
