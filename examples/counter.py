"""Counter (≙ examples/counter): N device actors accumulate increments;
a final query behaviour reports the total via a host actor."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor,  # noqa
                       behaviour, options_from_env)
from ponyc_tpu.platforms import auto_backend  # noqa: E402


@actor
class Counter:
    count: I32

    @behaviour
    def increment(self, st, by: I32):
        return {**st, "count": st["count"] + by}

    @behaviour
    def report(self, st, to: Ref):
        self.send(to, Reporter.result, st["count"])
        return st


@actor
class Reporter:
    HOST = True
    seen: I32
    expected: I32

    @behaviour
    def result(self, st, count: I32):
        total = st["seen"] + count
        print(f"partial={count} running_total={total}")
        self.exit(0, when=total >= st["expected"])
        return {**st, "seen": total}


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    n, incs = 8, 100
    # options_from_env so `python -m ponyc_tpu run examples/counter.py
    # --ponyanalysis=2` (or any --pony* flag) reaches this runtime —
    # the profiler smoke test drives the example exactly that way.
    rt = Runtime(options_from_env(RuntimeOptions(
        msg_words=2, inject_slots=256, batch=16)))
    rt.declare(Counter, n).declare(Reporter, 1).start()
    counters = rt.spawn_many(Counter, n)
    rep = rt.spawn(Reporter, expected=n * incs)
    for c in counters:
        for _ in range(incs // 4):
            rt.send(int(c), Counter.increment, 4)
    rt.run()                      # drain increments
    for c in counters:
        rt.send(int(c), Counter.report, rep)
    code = rt.run()
    rt.stop()     # analysis summary + writer-thread flush (≙ pony_stop)
    print("exit:", code)
    sys.exit(code)


if __name__ == "__main__":
    main()
