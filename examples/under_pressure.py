"""Programmatic backpressure (≙ reference examples/under_pressure):
a producer floods a slow TCP-like sink; the sink declares pressure via
the backpressure package when its internal buffer backs up, muting the
producer until it drains and releases.

    python examples/under_pressure.py
"""
import sys

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor,  # noqa
                       behaviour)
from ponyc_tpu.platforms import auto_backend  # noqa: E402
from ponyc_tpu.stdlib import backpressure as bp  # noqa: E402


@actor
class SlowSink:
    """Stands in for the reference's TCPConnection whose socket stalls:
    the runtime can't see its external buffer, so the HOST applies
    pressure on its behalf (≙ Backpressure.apply in TCPConnectionNotify
    throttled callback)."""
    got: I32

    BATCH = 1

    @behaviour
    def data(self, st, v: I32):
        return {**st, "got": st["got"] + 1}


@actor
class Send:
    """≙ the Send TimerNotify: keeps sending chunks until told to stop."""
    out: Ref[SlowSink]
    sent: I32

    MAX_SENDS = 2

    @behaviour
    def tick(self, st, n: I32):
        self.send(st["out"], SlowSink.data, n)
        self.send(self.actor_id, Send.tick, n + 1)
        return {**st, "sent": st["sent"] + 1}


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, msg_words=1,
                                max_sends=2, spill_cap=256,
                                inject_slots=8))
    rt.declare(Send, 1).declare(SlowSink, 1).start()
    sink = rt.spawn(SlowSink)
    sender = rt.spawn(Send, out=sink)
    rt.send(sender, Send.tick, 0)

    auth = bp.ApplyReleaseBackpressureAuth(rt.ambient_auth())
    st, inj = rt.state, rt._empty_inject
    st, _ = rt._step(st, *rt._drain_inject())
    phase = []
    for step in range(40):
        st, aux = rt._step(st, *inj)
        rt.state = st
        muted = bool(np.asarray(st.muted)[sender])
        if step == 9:
            bp.apply(auth, sink)    # the "socket stalled" moment
            st = rt.state           # pick up the pressured column
            phase.append(f"step {step}: pressure APPLIED")
        if step == 29:
            bp.release(auth, sink)  # drained: release
            st = rt.state
            phase.append(f"step {step}: pressure RELEASED")
        if step in (8, 15, 35):
            phase.append(f"step {step}: sender muted={muted}, "
                         f"sink got={rt.state_of(sink)['got']}")
    for line in phase:
        print(line)
    assert bool(np.asarray(rt.state.muted)[sender]) is False
    g1 = rt.state_of(sink)["got"]
    print(f"done: sink received {g1} chunks; sender muted while "
          "pressured, released after")


if __name__ == "__main__":
    main()
