"""Heartbeat: OS timers driving a device actor (≙ examples/timers).

  python examples/heartbeat.py

The stdlib timer hub (≙ packages/time Timers) arms a native timerfd in
the C++ event loop; each firing becomes an ordinary behaviour message
on a device actor, which accumulates beats and exits the program after
the fifth — the reference's Timer/TimerNotify cancel-after-N pattern.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import (I32, Runtime, RuntimeOptions,  # noqa: E402
                       actor, behaviour)
from ponyc_tpu.platforms import auto_backend  # noqa: E402
from ponyc_tpu.stdlib.timers import Timers  # noqa: E402

BEATS = 5


@actor
class Heart:
    beats: I32

    @behaviour
    def beat(self, st, kind: I32, n: I32, flags: I32):
        # Uniform asio event signature: n = coalesced firings.
        total = st["beats"] + n
        self.exit(0, when=total >= BEATS)
        return {**st, "beats": total}


def main() -> int:
    auto_backend()
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, msg_words=3,
                                inject_slots=8))
    rt.declare(Heart, 1).start()
    h = rt.spawn(Heart, beats=0)
    timers = Timers(rt)
    timers.timer(int(h), Heart.beat, interval_s=0.05, count=BEATS)
    code = rt.run()                 # exits from the device on beat #5
    beats = rt.state_of(h)["beats"]
    print(f"exit {code} after {beats} heartbeats")
    assert code == 0 and beats >= BEATS, (code, beats)
    # (no dispose needed: a count=N timer self-cancels on its last fire)
    return code


if __name__ == "__main__":
    sys.exit(main())
