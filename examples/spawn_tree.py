"""Dynamic spawn tree (≙ the reference's pervasive actor-creates-actor
pattern, e.g. examples/circle): each node spawns two children down to a
depth, then counts leaves back up through parent refs."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.platforms import auto_backend  # noqa: E402


@actor
class Node:
    parent: Ref
    acc: I32
    pending: I32

    SPAWNS = {"Node": 2}
    SPAWN_DISPATCHES = 1      # grow arrives once per node: tight windows
    MAX_SENDS = 3

    @behaviour
    def grow(self, st, depth: I32, parent: Ref):
        leaf = depth <= 0
        a = self.spawn(Node.grow, depth - 1, self.actor_id, when=~leaf)
        b = self.spawn(Node.grow, depth - 1, self.actor_id, when=~leaf)
        self.send(parent, Node.leaf_up, 1, when=leaf)
        return {**st, "parent": parent, "pending": 2}

    @behaviour
    def leaf_up(self, st, n: I32):
        import jax.numpy as jnp
        acc = st["acc"] + n
        pending = st["pending"] - 1
        done = pending == 0
        root = st["parent"] < 0
        self.send(st["parent"], Node.leaf_up, acc, when=done & ~root)
        self.exit(acc, when=done & root)
        return {**st, "acc": jnp.where(done, 0, acc), "pending": pending}


# The host only ever injects grow (main() below); declaring the inject
# site lets `python -m ponyc_tpu lint examples.spawn_tree` run the
# ROOTED rules too — R1 reachability from grow, R2's nothing-spawns-it
# check (Node spawns itself on device, so the program is clean).
LINT_ROOTS = (Node.grow,)


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    depth = 6                     # 2^6 = 64 leaves, 127 nodes
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=4, max_sends=3,
                                msg_words=2, inject_slots=8,
                                spill_cap=512))
    rt.declare(Node, 256).start()
    root = rt.spawn(Node)
    rt.send(root, Node.grow, depth, -1)
    code = rt.run(max_steps=10000)
    print(f"leaves counted: {code} (expected {2**depth}); "
          f"spawned {rt.counter('n_spawned')} actors on device")
    assert code == 2 ** depth
    sys.exit(0)


if __name__ == "__main__":
    main()
