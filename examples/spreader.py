"""Spreader: device-side actor tree fan-out, promise-joined completion.

  python examples/spreader.py [depth]

≙ the reference's examples/spreader (each actor spawns two children
until the countdown ends; leaves report back) — here the tree spawns
ON DEVICE (`ctx.spawn`, reservation windows), the leaf count funnels
into a HOST collector actor, and the host waits on a stdlib Promise it
fulfils — the promises package doing the reference's env.out "done"
signalling.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions,  # noqa: E402
                       actor, behaviour)
from ponyc_tpu.platforms import auto_backend  # noqa: E402
from ponyc_tpu.stdlib.promises import Promise  # noqa: E402


def main(depth: int = 6) -> int:
    auto_backend()
    expect = 1 << depth
    done = Promise()            # fulfilled by the HOST actor below

    # Host behaviours run real Python, so the collector can close over
    # the promise and fulfil it from inside the actor world — the
    # promises idiom: the ACTOR resolves, the host blocks on value(),
    # which drives the runtime while waiting (stdlib/promises.py).
    @actor
    class Collect:
        HOST = True
        got: I32

        @behaviour
        def leaf(self, st, n: I32):
            total = st["got"] + n
            if total >= expect:
                done.fulfil(total)
            return {**st, "got": total}

    @actor
    class Spread:
        col: Ref["Collect"]

        SPAWNS = {"Spread": 2}
        SPAWN_DISPATCHES = 1   # go() arrives once per actor: one
        #   spawning dispatch per tick keeps each frontier actor's
        #   reservation window at 2 slots (program._resolve_spawns on
        #   the static worst-case price)
        MAX_SENDS = 5       # 2 constructor sends + 2 go + 1 leaf report

        @behaviour
        def go(self, st, level: I32):
            leaf = level <= 0
            # Children get the collector ref through their constructor
            # message (FIFO per sender pair: init lands before go).
            a = self.spawn(Spread.init, st["col"], when=~leaf)
            b = self.spawn(Spread.init, st["col"], when=~leaf)
            self.send(a, Spread.go, level - 1, when=~leaf)
            self.send(b, Spread.go, level - 1, when=~leaf)
            self.send(st["col"], Collect.leaf, 1, when=leaf)
            return st

        @behaviour
        def init(self, st, c: Ref["Collect"]):
            return {**st, "col": c}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, max_sends=5,
                                msg_words=2, spill_cap=4096,
                                inject_slots=8))
    done.rt = rt
    rt.declare(Spread, 4 * expect).declare(Collect, 1).start()
    col = rt.spawn(Collect, got=0)
    root = rt.spawn(Spread, col=int(col))
    rt.send(root, Spread.go, depth)
    got = done.value(timeout=120)   # drives rt.run() until fulfilled
    print(f"depth {depth}: {got} leaves (expected {expect})")
    assert got == expect, (got, expect)
    return 0


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 6))
