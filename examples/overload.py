"""Automatic backpressure under fan-in overload (≙ reference
examples/overload: many Senders flood one Receiver; the runtime mutes
senders instead of letting the mailbox grow without bound).

    python examples/overload.py
"""
import sys

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor,  # noqa
                       behaviour)
from ponyc_tpu.platforms import auto_backend  # noqa: E402

N_SENDERS, ITEMS = 64, 50


@actor
class Receiver:
    msgs: I32

    BATCH = 2          # deliberately slower than the senders' aggregate

    @behaviour
    def rush(self, st, v: I32):
        return {**st, "msgs": st["msgs"] + 1}


@actor
class Sender:
    out: Ref[Receiver]
    left: I32

    MAX_SENDS = 2

    @behaviour
    def go(self, st, _: I32):
        self.send(st["out"], Receiver.rush, 1, when=st["left"] > 0)
        self.send(self.actor_id, Sender.go, 0, when=st["left"] > 1)
        return {**st, "left": st["left"] - 1}


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, msg_words=1,
                                max_sends=2, spill_cap=4096,
                                inject_slots=64))
    rt.declare(Sender, N_SENDERS).declare(Receiver, 1).start()
    recv = rt.spawn(Receiver)
    senders = rt.spawn_many(Sender, N_SENDERS, out=recv, left=ITEMS)
    rt.bulk_send(senders, Sender.go, np.zeros(N_SENDERS, np.int64))

    peak_muted = 0
    st, inj = rt.state, rt._empty_inject
    st, _ = rt._step(st, *rt._drain_inject())
    steps = 0
    while True:
        st, aux = rt._step(st, *inj)
        steps += 1
        peak_muted = max(peak_muted, int(np.asarray(st.muted).sum()))
        rt.state = st
        if (rt.state_of(recv)["msgs"] == N_SENDERS * ITEMS
                or steps > 20000):
            break
    got = rt.state_of(recv)["msgs"]
    assert got == N_SENDERS * ITEMS, (got, N_SENDERS * ITEMS)
    print(f"receiver got all {got} messages in {steps} ticks; "
          f"peak concurrently-muted senders: {peak_muted}/{N_SENDERS} "
          "(mailbox stayed bounded — no runaway growth)")


if __name__ == "__main__":
    main()
