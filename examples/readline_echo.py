"""Interactive line editor — ≙ the reference's term package demo
(packages/term: ANSITerm + Readline over stdin).

Type lines with full editing (arrows, home/end, ctrl-a/e/k/u,
history via up/down, tab completion over a few commands); each line is
echoed back by a HOST actor. Ctrl-D or `quit` exits.

Run without a terminal (CI, pipes) and it feeds itself a scripted
session instead, exercising the same code path.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.platforms import auto_backend  # noqa: E402
from ponyc_tpu.stdlib.term import (ANSITerm, Readline,  # noqa: E402
                                   ReadlineNotify, attach_stdin)

COMMANDS = ["help", "history", "quit"]


@actor
class Echo:
    HOST = True
    lines: I32

    @behaviour
    def line(self, st, n: I32):
        print(f"echo #{n}: {LINES[n]}")
        return {**st, "lines": st["lines"] + 1}


LINES = {}          # line number → text (host-side payload table)


class Shell(ReadlineNotify):
    def __init__(self, rt, echo_id, term_holder):
        self.rt = rt
        self.echo_id = echo_id
        self.term_holder = term_holder
        self.n = 0

    def apply(self, line, prompt):
        if line == "quit":
            prompt.reject("bye")
            self.rt.request_exit(0)
            return
        LINES[self.n] = line
        self.rt.send(self.echo_id, Echo.line, self.n)
        self.n += 1
        prompt.fulfil("edit> ")

    def tab(self, line):
        return [c for c in COMMANDS if c.startswith(line)]


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    rt = Runtime(RuntimeOptions(msg_words=1)).declare(Echo, 1).start()
    echo = rt.spawn(Echo)
    holder = {}
    shell = Shell(rt, echo, holder)
    rl = Readline(shell, sys.stdout)
    term = ANSITerm(rl, sys.stdout)
    holder["term"] = term

    if sys.stdin.isatty():
        attach_stdin(rt, term)
        term.prompt("edit> ")
        rt.run()
    else:
        # Scripted session: same byte path as a real tty.
        term.prompt("edit> ")
        term.apply(b"helo\x1b[Dl\x01X\x7f\x05!\n")   # edits -> "hello!"
        term.apply(b"h\t")                           # completes "help"? no:
        term.apply(b"\x15")                          # ambiguous; kill line
        term.apply(b"his\tory extra\n")              # no unique completion
        term.apply(b"\x1b[A\n")                      # history repeat
        term.apply(b"quit\n")
        rt.run(max_steps=2000)
    print(f"\nsession over: {rt.state_of(echo)['lines']} lines echoed")


if __name__ == "__main__":
    main()
