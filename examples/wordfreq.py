"""Word-frequency tool: the stdlib packages working together.

  python examples/wordfreq.py count --top=3 "the quick the lazy the dog"
  python examples/wordfreq.py help

cli parses the command line (≙ packages/cli), a fan-out of Counter
actors tallies shards of the word list on device, and json renders the
result (≙ packages/json). The aggregation itself is the fan-in pattern
(≙ examples/fan-in) running on the actor runtime.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.platforms import auto_backend  # noqa: E402
from ponyc_tpu.stdlib.cli import (ArgSpec, CliSyntaxError, CommandHelp,
                                  CommandParser, CommandSpec, OptionSpec)
from ponyc_tpu.stdlib.itertools import Iter
from ponyc_tpu.stdlib.json import JsonArray, JsonDoc, JsonObject


@actor
class Tally:
    """One actor per distinct word; counts arrivals (device-side)."""
    hits: I32

    @behaviour
    def hit(self, st, _: I32):
        return {**st, "hits": st["hits"] + 1}


def build_spec() -> CommandSpec:
    spec = CommandSpec.parent("wordfreq", "Count word frequencies")
    spec.add_command(CommandSpec.leaf("count", "Count words", options=[
        OptionSpec.i64("top", "How many top words to print", short="t",
                       default=10),
        OptionSpec.bool("pretty", "Pretty-print the JSON", short="p",
                        default=False),
    ], args=[ArgSpec.string("text", "Text to analyse")]))
    spec.add_help()
    return spec


def main(argv):
    auto_backend()      # never hang on a wedged TPU plugin
    cmd = CommandParser(build_spec()).parse(argv)
    if isinstance(cmd, CliSyntaxError):
        print(cmd.string(), file=sys.stderr)
        return 1
    if isinstance(cmd, CommandHelp):
        print(cmd.help_string())
        return 0

    words = cmd.arg("text").split()
    vocab = sorted(set(words))
    index = {w: i for i, w in enumerate(vocab)}

    rt = Runtime(RuntimeOptions(mailbox_cap=64, batch=16, max_sends=1,
                                msg_words=1, spill_cap=1024,
                                inject_slots=256))
    rt.declare(Tally, max(1, len(vocab))).start()
    ids = rt.spawn_many(Tally, len(vocab))
    for w in words:
        rt.send(int(ids[index[w]]), Tally.hit, 0)
    rt.run()

    hits = rt.cohort_state(Tally)["hits"]
    ranked = (Iter(vocab).enum()
              .map(lambda iw: (iw[1], int(hits[iw[0]])))
              .collect())
    ranked.sort(key=lambda p: (-p[1], p[0]))
    doc = JsonDoc()
    doc.data = JsonObject({
        "total": len(words),
        "distinct": len(vocab),
        "top": JsonArray([
            JsonObject({"word": w, "count": c})
            for w, c in ranked[:cmd.option("top")]]),
    })
    print(doc.string(indent="  ", pretty_print=cmd.option("pretty")))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
