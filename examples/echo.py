"""TCP echo server (≙ examples/echo + packages/net usage): run, then
`nc localhost <port>` — lines come back upper-cased. Ctrl-C to stop."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.platforms import auto_backend  # noqa: E402


@actor
class Echo:
    HOST = True
    n_conns: I32

    @behaviour
    def on_accept(self, st, conn: I32):
        print(f"connection {conn} accepted")
        return {**st, "n_conns": st["n_conns"] + 1}

    @behaviour
    def on_data(self, st, conn: I32, data: I32, n: I32):
        payload = self.rt.heap.unbox(data)
        self.rt.net.send(conn, payload.upper())
        return st

    @behaviour
    def on_closed(self, st, conn: I32):
        print(f"connection {conn} closed")
        return st


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    rt = Runtime(RuntimeOptions(msg_words=4, inject_slots=64))
    rt.declare(Echo, 1).start()
    net = rt.attach_net()
    srv = rt.spawn(Echo)
    lid = net.listen_tcp("127.0.0.1", port, srv,
                         on_accept=Echo.on_accept, on_data=Echo.on_data,
                         on_closed=Echo.on_closed)
    print(f"echo listening on 127.0.0.1:{net.listen_port(lid)}")
    try:
        rt.run()
    except KeyboardInterrupt:
        pass
    finally:
        net.close_all()
        rt.stop()


if __name__ == "__main__":
    main()
