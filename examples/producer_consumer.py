"""Producer/consumer with backpressure (≙ examples/producer-consumer +
examples/overload): fast producers flood one consumer; the runtime's
overload → mute → unmute machinery throttles them, nothing is lost."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.platforms import auto_backend  # noqa: E402


@actor
class Producer:
    sink: Ref
    left: I32

    @behaviour
    def produce(self, st, _: I32):
        go = st["left"] > 0
        self.send(st["sink"], Consumer.consume, st["left"], when=go)
        self.send(self.actor_id, Producer.produce, 0, when=go)
        return {**st, "left": st["left"] - 1}


@actor
class Consumer:
    BATCH = 2                     # deliberately slow drain
    seen: I32

    @behaviour
    def consume(self, st, item: I32):
        return {**st, "seen": st["seen"] + 1}


def main():
    auto_backend()      # never hang on a wedged TPU plugin
    n_prod, items = 8, 200
    rt = Runtime(RuntimeOptions(mailbox_cap=16, batch=8, max_sends=2,
                                msg_words=2, spill_cap=512,
                                inject_slots=64))
    rt.declare(Producer, n_prod).declare(Consumer, 1).start()
    sink = rt.spawn(Consumer)
    prods = rt.spawn_many(Producer, n_prod, sink=int(sink),
                          left=items)
    for p in prods:
        rt.send(int(p), Producer.produce, 0)
    rt.run()
    seen = rt.state_of(sink)["seen"]
    mutes = rt.counter("n_mutes")
    print(f"consumed {seen}/{n_prod * items} "
          f"(mute transitions: {mutes}, rejected→spill: "
          f"{rt.counter('n_rejected')})")
    assert seen == n_prod * items
    sys.exit(0)


if __name__ == "__main__":
    main()
