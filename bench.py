#!/usr/bin/env python
"""Headline benchmark: message-ubench throughput on one chip.

Reproduces the reference's `examples/message-ubench` metric
(actor-messages/sec; BASELINE.md) at benchmark scale: N pingers in one
shuffled cycle, one message in flight per actor, sustained. Each jitted
tick dispatches exactly N behaviours and routes N messages, so

    msgs/sec = N × ticks / elapsed.

vs_baseline: the reference publishes no absolute numbers (BASELINE.md —
"published: {}"); the driver-set north star is ≥10× message-ubench on a
32-core CPU. We use 3.0e8 msgs/s as the 32-core CPU estimate (Pony's
ubench sustains O(10M) msgs/core/s on modern x86), so vs_baseline 10.0
== the north-star 10× target.

Usage: python bench.py  [--actors N] [--ticks K] (defaults 2^20, 200)
Env:   PONY_TPU_BENCH_ACTORS / PONY_TPU_BENCH_TICKS override.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CPU32_BASELINE_MSGS_PER_SEC = 3.0e8


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--actors", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_ACTORS",
                                               1 << 20)))
    ap.add_argument("--ticks", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_TICKS", 200)))
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--cap", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_CAP", 4)))
    args = ap.parse_args()
    args.warmup = max(1, args.warmup)   # the first step pays the jit

    import jax
    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.models import ubench

    # cap 4 suffices for the 1-in-flight steady state and keeps the ring
    # rebuild (cap-proportional) lean.
    opts = RuntimeOptions(mailbox_cap=args.cap, batch=1, max_sends=1,
                          msg_words=1, spill_cap=1024, inject_slots=8)
    t0 = time.time()
    rt, ids = ubench.build(args.actors, opts)
    ubench.seed_all(rt, ids, hops=1 << 30)   # effectively infinite
    build_s = time.time() - t0

    # Drive the jitted tick directly (the run() loop's quiescence polling
    # is for applications; the bench measures the engine's steady state).
    inj = rt._empty_inject
    state = rt.state
    t0 = time.time()
    for _ in range(args.warmup):
        state, aux = rt._step(state, *inj)
    jax.block_until_ready(aux)
    warm_s = time.time() - t0

    t0 = time.time()
    for _ in range(args.ticks):
        state, aux = rt._step(state, *inj)
    jax.block_until_ready(aux)
    elapsed = time.time() - t0
    rt.state = state

    processed = rt.counter("n_processed") & 0xFFFFFFFF
    expect = (args.warmup + args.ticks) * args.actors
    msgs_per_sec = args.actors * args.ticks / elapsed

    result = {
        "metric": "ubench_actor_messages_per_sec",
        "value": round(msgs_per_sec, 1),
        "unit": "msgs/sec/chip",
        "vs_baseline": round(msgs_per_sec / CPU32_BASELINE_MSGS_PER_SEC, 3),
        "detail": {
            "actors": args.actors,
            "ticks": args.ticks,
            "elapsed_s": round(elapsed, 4),
            "tick_ms": round(1e3 * elapsed / args.ticks, 3),
            "processed_counter_ok": bool(processed == expect % (1 << 32)),
            "build_s": round(build_s, 1),
            "warmup_s": round(warm_s, 1),
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
