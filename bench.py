#!/usr/bin/env python
"""Headline benchmark: message-ubench throughput + p50 dispatch latency.

Reproduces the reference's `examples/message-ubench` metric
(actor-messages/sec; BASELINE.md) at benchmark scale: N pingers in one
shuffled cycle, `--pings` messages in flight per actor (≙ the reference's
--initial-pings, default 5 there), sustained. Each jitted tick dispatches
exactly N×pings behaviours and routes N×pings messages, so

    msgs/sec = N × pings × ticks / elapsed.

Also measures the second tracked BASELINE metric: p50 behaviour-dispatch
latency, via a single-token 1024-actor ring (≙ examples/ring/main.pony) —
each tick is one hop, timed individually with a device sync.

vs_baseline: the reference publishes no absolute numbers (BASELINE.md —
"published: {}"); the driver-set north star is ≥10× message-ubench on a
32-core CPU. We use 3.0e8 msgs/s as the 32-core CPU estimate (Pony's
ubench sustains O(10M) msgs/core/s on modern x86), so vs_baseline 10.0
== the north-star 10× target.

Platform handling (round-2 fix): the TPU backend behind the axon tunnel
can fail or hang on init, and the plugin re-asserts itself over
JAX_PLATFORMS. The backend is therefore probed in a *subprocess* with a
timeout (a hung in-process jax.devices() would wedge this process's
backend lock forever), retried against a total time budget (--probe-budget, default 900s), and on
persistent failure the bench falls
back to CPU — loudly, with the TPU error in the JSON detail — so a run
always captures a parseable result. Set PONY_TPU_BENCH_ALLOW_CPU=0 to
make TPU-init failure fatal instead, or --platform cpu for smoke runs.

Delivery/dispatch formulation defaults to "auto": Runtime.start()
calibrates every eligible variant in-executable (ponyc_tpu/tuning.py),
the JSON gains a `tuning` block with the per-variant tick_ms table, and
the decision persists in the on-disk tuning cache (steady-state runs
skip calibration). The jax persistent compile cache is enabled too on
accelerator backends (CPU reload is unsound on jaxlib 0.4.37 —
PROFILE.md §6), so a second identical run's warmup_s drops to
executable-reload time.

Every run also embeds a `telemetry` block: a headline-shaped pass at
analysis=1 whose per-behaviour runs, queue-wait percentiles and GC
stats (Runtime.profile(), the per-behaviour profiler of PROFILE.md §8)
attribute the ticks, so the BENCH trajectory records where the time
went, not just totals. The timed headline pass itself stays level 0.

Usage: python bench.py  [--actors N] [--ticks K] [--platform auto|tpu|cpu]
                        [--delivery auto|plan|cosort|pallas_mega]
                        [--fused auto|on|off]
                        [--trace-smoke] [--metrics-smoke]
                        [--checkpoint-smoke] [--serve-smoke]
                        [--kernel-smoke] [--no-fallback]

--trace-smoke adds a `tracing` block: one sampled causal-tracing pass
(analysis=3, trace_sample=1, PROFILE.md §10) reassembled and checked
(spans_ok/span_count_ok — attribution_ok style). --metrics-smoke adds
a `metrics` block: a scrape-under-load round-trip through the real
HTTP exporter (RuntimeOptions.metrics_port, PROFILE.md §11) whose
final counters must equal Runtime.profile(). --checkpoint-smoke adds
a `checkpoint` block: checkpoint cost per window, per-checkpoint
capture/write costs and restore-fast-start time (durable worlds,
PROFILE.md §12). --serve-smoke adds a `serving` block: the real socket
front door (serve.py) driven by loadgen.py at ~2x measured capacity —
p50/p99 end-to-end latency of admitted requests, shed rate at the
edge, goodput, and the rings-never-sticky-fail check (PROFILE.md
§13). Every run records
`backend_init_s`, and a failed TPU init — including --platform tpu,
which now probes in a subprocess instead of hanging in-process — emits
an explicit `tpu_init_error` with the probed env snapshot (`tpu_env`)
PLUS a flight-recorder `postmortem` (probe timeline + env) and the
doctor's one-line diagnosis on stderr, so CPU-fallback rounds carry
their stall evidence (`doctor --postmortem BENCH_rNN.json`).
--no-fallback makes that failure fatal (exit 1 with the postmortem in
the JSON) instead of publishing a CPU number. Every run embeds a
`kernel` block with the packed bytes/msg model (ops/megakernel.py) at
the measured escape rate; --kernel-smoke extends it with a bit-for-bit
plan-vs-pallas_mega A/B on a small world (PROFILE.md §14).
Env:   PONY_TPU_BENCH_ACTORS / PONY_TPU_BENCH_TICKS /
       PONY_TPU_BENCH_PLATFORM / PONY_TPU_BENCH_ALLOW_CPU /
       PONY_TPU_BENCH_DELIVERY / PONY_TPU_BENCH_FUSED /
       PONY_TPU_BENCH_KERNEL_SMOKE override; PONY_TPU_MEGA_AUTO=1 is
       set by main() so delivery=auto enumerates the megakernel;
       PONY_TPU_TUNING_CACHE / PONY_TPU_COMPILE_CACHE relocate ("off"
       disables) the persistent caches.
"""

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CPU32_BASELINE_MSGS_PER_SEC = 3.0e8

# Standing perf-regression scoreboard (ISSUE 19): every bench run
# appends one flattened line here; `python -m ponyc_tpu perf [--check]`
# renders the trajectory and gates CI on regressions.
HISTORY_PATH = os.environ.get("PONY_TPU_BENCH_HISTORY",
                              "BENCH_HISTORY.jsonl")


def history_entry(result):
    """Flatten one bench result json into a perf-trajectory row: the
    headline number, enough context to interpret it (platform,
    delivery, world size, CPU-fallback marker), and the measured
    numbers the scoreboard tracks alongside the modelled ones."""
    detail = result.get("detail") or {}
    kernel = result.get("kernel") or {}
    measured = result.get("measured") or {}
    step = (measured.get("executables") or {}).get("step") or {}
    div = measured.get("model_divergence") or {}
    return {
        "time": round(time.time(), 1),
        "metric": result.get("metric"),
        "value": result.get("value"),
        "unit": result.get("unit"),
        "vs_baseline": result.get("vs_baseline"),
        "platform": detail.get("platform"),
        "delivery": detail.get("delivery"),
        "actors": detail.get("actors"),
        "tpu_init_error": detail.get("tpu_init_error"),
        "packed_bytes_per_msg": detail.get("packed_bytes_per_msg"),
        "kernel_ratio": (kernel.get("bytes_per_msg") or {}).get("ratio"),
        "measured_step_bytes": step.get("bytes_accessed"),
        "measured_step_flops": step.get("flops"),
        "measured_step_peak_bytes": step.get("peak_bytes"),
        "model_divergence": div.get("diverged"),
        "divergence_ratio": div.get("ratio"),
    }


def append_history(result, path=None):
    """Append the run's scoreboard row to BENCH_HISTORY.jsonl (best
    effort: a read-only checkout must not sink the bench)."""
    path = path or HISTORY_PATH
    try:
        with open(path, "a") as f:
            f.write(json.dumps(history_entry(result)) + "\n")
    except OSError as e:
        print(f"bench: history append failed ({e})", file=sys.stderr)
        return None
    return path


def probe_tpu(timeout_s: float, budget_s: float):
    """Claim-retry queue: keep probing the TPU (subprocess + timeout,
    ponyc_tpu.platforms.probe_accelerator) until it answers or a total
    time budget runs out, so a transiently-wedged tunnel yields a LATE
    TPU number rather than none (round-3 lesson: one 3×180s probe window
    erased the round's on-chip headline metric; observed wedges clear
    after tens of minutes).

    Returns (platform_or_None, last_error, probe_timeline) — the
    timeline is the attempt-by-attempt stall evidence the flight-
    recorder postmortem embeds in every tpu_init_error BENCH json."""
    from ponyc_tpu.platforms import probe_accelerator
    deadline = time.monotonic() + budget_s
    err = None
    attempt = 0
    timeline = []
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining <= 5.0:
            return None, err or "probe budget exhausted", timeline
        # First attempt: the configured timeout. Later attempts wait as
        # long as the budget allows (a claim that queues for minutes and
        # then succeeds beats five fast kills — killing a claim-waiting
        # client has been observed to re-wedge the tunnel).
        t = min(remaining, timeout_s if attempt == 1 else max(
            timeout_s, 300.0))
        t0 = time.monotonic()
        plat, err = probe_accelerator(t)
        timeline.append({"attempt": attempt, "timeout_s": round(t, 1),
                         "t_s": round(time.monotonic() - t0, 1),
                         "error": err})
        if plat is not None:
            return plat, None, timeline
        if err and err.startswith("backend initialised as"):
            # Deterministic outcome — JAX resolved to CPU; retrying
            # would just re-init the same backend.
            print(f"bench: TPU probe: {err}", file=sys.stderr)
            return None, err, timeline
        print(f"bench: TPU probe attempt {attempt} failed "
              f"({remaining - t:.0f}s of budget left): {err}",
              file=sys.stderr)
        time.sleep(min(10.0, max(0.0, deadline - time.monotonic())))


def force_cpu():
    from ponyc_tpu.platforms import force_cpu as _force
    _force()


def tpu_env_details():
    """The probed-environment snapshot that rides every tpu_init_error
    (satellite of ROADMAP item 2: benches r03–r05 regressed to CPU
    with nothing in the JSON saying WHY the backend died — this block
    makes the failure diagnosable from the BENCH record alone). Now
    the shared flight-recorder snapshot (ponyc_tpu.flight): one
    definition for BENCH jsons and runtime postmortems."""
    from ponyc_tpu.flight import env_snapshot
    return env_snapshot()


def tpu_init_postmortem(timeline):
    """Build the flight-recorder postmortem for a failed TPU init
    (probe timeline + env snapshot), print the doctor's one-line
    diagnosis to stderr (fail LOUDLY — a CPU-fallback round must not
    read like a clean one), and return the postmortem dict for the
    BENCH json."""
    from ponyc_tpu.flight import diagnose_postmortem, probe_postmortem
    pm = probe_postmortem(timeline, tpu_env_details())
    line, _detail = diagnose_postmortem(pm)
    print(f"bench: doctor: {line}", file=sys.stderr)
    return pm


def tristate(v):
    """CLI/env spelling of a bool-or-"auto" runtime option."""
    v = str(v).lower()
    if v == "auto":
        return "auto"
    return v in ("1", "true", "yes", "on")


def cpu_fallback_allowed(no_fallback: bool) -> bool:
    """CPU-fallback policy for --platform auto: --no-fallback (or the
    legacy PONY_TPU_BENCH_ALLOW_CPU=0 kill switch) makes a failed TPU
    init exit non-zero with the probe postmortem instead of quietly
    publishing a CPU number — a TPU regression must never masquerade
    as a (slower) healthy run."""
    if no_fallback:
        return False
    return os.environ.get("PONY_TPU_BENCH_ALLOW_CPU", "1") != "0"


def bench_ubench(args):
    import jax
    import jax.numpy as jnp
    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.models import ubench

    # cap must hold the sustained in-flight pings per pinger (≙ the
    # reference's --initial-pings, default 5 there); the ring rebuild is
    # cap-proportional so keep it at the smallest power of two that fits.
    pings = args.pings
    cap = ubench.cap_for_pings(pings, floor=args.cap)
    opts = RuntimeOptions(mailbox_cap=cap, batch=pings, max_sends=1,
                          msg_words=1, spill_cap=1024, inject_slots=8,
                          delivery=args.delivery,
                          pallas=tristate(args.pallas),
                          pallas_fused=tristate(args.fused))
    t0 = time.time()
    rt, ids = ubench.build(args.actors, opts, pings=pings)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=pings)  # ~infinite
    build_s = time.time() - t0

    # Drive the fused window directly (engine.build_multi_step): one
    # device dispatch advances `fuse` ticks, so the measurement sees the
    # engine's steady state, not per-dispatch overhead. ubench never
    # quiesces, so every window runs its full `fuse` ticks (asserted via
    # the processed counter below).
    K = max(1, min(args.fuse, args.ticks))   # small --ticks shrinks windows
    limit = jnp.int32(K)
    inj = rt._empty_inject
    state = rt.state
    t0 = time.time()
    warm_windows = -(-args.warmup // K)      # warmup >= 1 (main() clamps)
    for _ in range(warm_windows):
        state, aux, _k = rt._multi(state, *inj, limit)
    jax.block_until_ready(aux)
    warm_s = time.time() - t0

    windows = max(1, args.ticks // K)
    ticks = windows * K
    t0 = time.time()
    for _ in range(windows):
        state, aux, _k = rt._multi(state, *inj, limit)
    jax.block_until_ready(aux)
    elapsed = time.time() - t0
    rt.state = state

    processed = rt.counter("n_processed") & 0xFFFFFFFF
    expect = (warm_windows * K + ticks) * args.actors * pings
    # The bandwidth-diet model at this run's MEASURED escape rate
    # (ops/megakernel.py): packed bytes per ring record on the hot
    # path — recorded in every run so the standing telemetry shows
    # whether real payloads stay inside the int16 lanes.
    from ponyc_tpu.ops import megakernel as _mk
    bytes_model = _mk.modelled_bytes_per_msg(
        rt.opts, _mk.escape_rate_state(rt.state))
    # Measured, not modelled (ISSUE 19): XLA's own cost/memory analysis
    # of THIS run's compiled executables plus the record-move probe,
    # judged against bytes_model — the `measured` block every BENCH
    # json carries next to the modelled number. Never sinks a run.
    from ponyc_tpu import costs as _costs
    if getattr(args, "skip_measured", False):
        # --skip-measured: dev-iteration knob only — runs for the
        # record must keep the capture (the scoreboard reads it).
        measured = {"skipped": True}
    else:
        try:
            measured = _costs.measured_block(rt, modelled=bytes_model)
            # Per-executable wall from the headline timing itself: the
            # measured windows above ARE this executable.
            win_rec = (measured.get("executables") or {}).get("window")
            if isinstance(win_rec, dict):
                win_rec["wall_ms_per_window"] = round(
                    1e3 * elapsed / windows, 4)
                win_rec["wall_ms_per_tick"] = round(
                    1e3 * elapsed / ticks, 4)
        except Exception as e:                   # noqa: BLE001
            measured = {"error": str(e)}
    if getattr(args, "xprof", 0):
        # --xprof N: wrap N retired fused windows in a jax.profiler
        # trace for op-level device wall attribution.
        try:
            measured["xprof_trace"] = rt.profile_device(
                windows=args.xprof, ticks=K)
        except Exception as e:                   # noqa: BLE001
            measured["xprof_error"] = str(e)
    return {
        "measured": measured,
        "packed_bytes_per_msg": bytes_model["packed_bytes"],
        "bytes_model": bytes_model,
        "msgs_per_sec": args.actors * pings * ticks / elapsed,
        "pings": pings,
        "elapsed_s": elapsed,
        "tick_ms": 1e3 * elapsed / ticks,
        "ticks": ticks,
        "fuse": K,
        "processed_counter_ok": bool(processed == expect % (1 << 32)),
        "build_s": build_s,
        "warmup_s": warm_s,
        # The A/B record: what "auto" measured and picked (tuning.py);
        # None when every formulation flag was forced.
        "tuning": rt.tuning_record,
        "delivery": rt.opts.delivery,
        "pallas": rt.opts.pallas,
        "pallas_fused": rt.opts.pallas_fused,
    }


def bench_kernel_smoke(args):
    """The --kernel-smoke `kernel` A/B block (PROFILE.md §14): the same
    seeded ubench world advanced through the XLA window
    (delivery="plan") and through the persistent fused window
    megakernel (delivery="pallas_mega"), compared BIT-FOR-BIT over
    every state leaf, with per-variant in-executable tick timings and
    the bandwidth-diet model at the measured escape rate. On CPU the
    megakernel runs interpreted — there the timing is a wiring check,
    not a perf claim (`interpret: true` in the block says so)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ponyc_tpu import RuntimeOptions, serialise
    from ponyc_tpu.models import ubench
    from ponyc_tpu.ops import megakernel as mk

    actors = max(4, min(args.actors, 64))    # interpret-mode friendly
    pings = args.pings
    cap = ubench.cap_for_pings(pings, floor=args.cap)
    ticks = max(2, min(args.ticks, 16))
    K = max(1, min(args.fuse, ticks))
    windows = max(1, ticks // K)
    tick_ms = {}
    named = {}
    esc_rate = 0.0
    for delivery in ("plan", "pallas_mega"):
        opts = RuntimeOptions(mailbox_cap=cap, batch=pings, max_sends=1,
                              msg_words=1, spill_cap=64, inject_slots=8,
                              delivery=delivery)
        rt, ids = ubench.build(actors, opts, pings=pings)
        # Representative small-payload traffic: hops fits the int16
        # lane (and outlives the smoke's few ticks), so the diet model
        # here shows the packed ratio on clean payloads. The headline
        # run keeps its ~2^30 hops counter and records the honest
        # (escape-heavy) rate for THAT traffic in detail/bytes_model.
        ubench.seed_all(rt, ids, hops=1 << 12, pings=pings)
        st, inj = rt.state, rt._empty_inject
        limit = jnp.int32(K)
        st, aux, _k = rt._multi(st, *inj, limit)      # pays the jit
        jax.block_until_ready(aux)
        t0 = time.time()
        for _ in range(windows):
            st, aux, _k = rt._multi(st, *inj, limit)
        jax.block_until_ready(aux)
        rt.state = st
        tick_ms[delivery] = round(
            1e3 * (time.time() - t0) / (windows * K), 4)
        named[delivery] = serialise._named_state_arrays(rt.state)
        esc_rate = mk.escape_rate_state(rt.state)
        model_opts = rt.opts
    a, b = named["plan"], named["pallas_mega"]
    mismatched = [k for k in a if not np.array_equal(np.asarray(a[k]),
                                                     np.asarray(b[k]))]
    return {
        "equal_ok": not mismatched,
        "mismatched": mismatched[:4],
        "tick_ms": tick_ms,
        "interpret": mk.interpret_mode(),
        "actors": actors,
        "ticks": (windows + 1) * K,
        "bytes_per_msg": mk.modelled_bytes_per_msg(model_opts, esc_rate),
    }


def bench_telemetry(args, delivery="plan", fused=False):
    """One headline-shaped pass at analysis=1: the per-behaviour
    profiler (engine.profile_lanes / Runtime.profile()) attributes the
    run so the BENCH json records WHERE the ticks went, not just
    totals — per-behaviour runs, queue-wait percentiles, gc passes.
    Runs after the timed pass on its own runtime (analysis is a
    trace-time constant; the headline numbers stay level-0) at a
    bounded world size so the extra jit never dominates a run."""
    import jax.numpy as jnp
    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.models import ubench

    actors = min(args.actors, 1 << 16)
    ticks = 64
    pings = args.pings
    cap = ubench.cap_for_pings(pings, floor=args.cap)
    opts = RuntimeOptions(mailbox_cap=cap, batch=pings, max_sends=1,
                          msg_words=1, spill_cap=1024, inject_slots=8,
                          delivery=delivery, pallas_fused=fused,
                          analysis=1)
    rt, ids = ubench.build(actors, opts, pings=pings)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=pings)
    state, aux, _k = rt._multi(rt.state, *rt._empty_inject,
                               jnp.int32(ticks))
    rt.state = state
    rt.steps_run += ticks
    prof = rt.profile()
    return {
        "actors": actors,
        "ticks": ticks,
        "analysis": 1,
        "behaviours": prof["behaviours"],
        "queue_wait_ticks": {
            c: {"p50": v["queue_wait_p50"], "p99": v["queue_wait_p99"]}
            for c, v in prof["cohorts"].items()},
        "mute_ticks": {c: v["mute_ticks"]
                       for c, v in prof["cohorts"].items()},
        "gc_passes": prof["gc"]["passes"],
        "attribution_ok": bool(
            sum(b["runs"] for b in prof["behaviours"].values())
            == prof["totals"]["processed"]),
    }


def bench_runloop(args, delivery="plan", fused=False):
    """Run-loop overhead study (PROFILE.md §9): drive the REAL
    Runtime.run() — not rt._multi — over a seeded ubench world twice,
    once with the forced synchronous fixed-window loop and once with
    the pipelined adaptive loop, and record each mode's host_gap_us
    (wall time the host left the device idle between windows) plus the
    window-length histogram and controller trajectory. The pipelined/
    sync ratio is THIS PR's acceptance number, re-measured by every
    bench run so a regression shows up as a recorded value, not a
    vibe. World size is bounded: the study measures loop overhead, not
    throughput (the headline pass above owns that)."""
    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.models import ubench

    actors = min(args.actors, 1 << 12)
    steps = 1024
    pings = args.pings
    cap = ubench.cap_for_pings(pings, floor=args.cap)
    out = {"actors": actors, "max_steps": steps}
    for mode in ("sync", "pipelined"):
        opts = RuntimeOptions(
            mailbox_cap=cap, batch=pings, max_sends=1, msg_words=1,
            spill_cap=1024, inject_slots=8, delivery=delivery,
            pallas_fused=fused,
            pipeline=(mode == "pipelined"),
            quiesce_interval=("auto" if mode == "pipelined" else 64),
            # The gap study must neither inherit nor publish converged
            # windows — both modes start cold every run.
            tuning_cache="off")
        rt, ids = ubench.build(actors, opts, pings=pings)
        ubench.seed_all(rt, ids, hops=1 << 30, pings=pings)
        t0 = time.time()
        rt.run(max_steps=steps)
        elapsed = time.time() - t0
        rl = rt.run_loop_stats()
        out[mode] = {
            "elapsed_s": round(elapsed, 3),
            "steps": rt.steps_run,
            "windows": rl["windows"],
            "pipelined_dispatches": rl["pipelined_dispatches"],
            "sync_dispatches": rl["sync_dispatches"],
            "host_gap_us_mean": round(rl["host_gap_us_mean"], 1),
            "host_gap_us_total": round(rl["host_gap_us_total"], 1),
            "window_hist": rl["window_hist"],
            "controller": rl["controller"],
        }
    s = out["sync"]["host_gap_us_mean"]
    p = out["pipelined"]["host_gap_us_mean"]
    # ∞-safe: a fully-pipelined run can expose literally zero gap.
    out["host_gap_ratio"] = round(s / p, 2) if p > 0 else None
    out["host_gap_2x_ok"] = bool(p * 2 <= s)
    return out


def bench_trace_smoke(args, delivery="plan", fused=False):
    """Causal-tracing smoke (PROFILE.md §10; --trace-smoke): one
    sampled injection through a small ring at analysis=3 /
    trace_sample=1, run to quiescence and reassembled — the BENCH
    json's standing record (attribution_ok style) that trace
    propagation, span-tick consistency and reassembly hold on THIS
    platform. Bounded world, never allowed to sink a headline run
    (main() guards with try/except)."""
    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.models import ring
    from ponyc_tpu.tracing import consistent

    hops = 24
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                          msg_words=1, spill_cap=64, inject_slots=8,
                          delivery=delivery, pallas_fused=fused,
                          analysis=3, trace_sample=1,
                          analysis_path="/tmp/pony_tpu.bench_trace.csv")
    rt, ids = ring.build(64, opts)
    t0 = time.time()
    rt.send(int(ids[0]), ring.RingNode.token, hops)
    rt.run()
    elapsed = time.time() - t0
    trees = rt.traces()
    rt.stop()
    spans = sum(t["n_spans"] for t in trees.values())
    return {
        "analysis": 3,
        "trace_sample": 1,
        "traces": len(trees),
        "spans": spans,
        "max_latency_ticks": max(
            (t["latency"] for t in trees.values()), default=0),
        "elapsed_s": round(elapsed, 3),
        # The acceptance predicates: enq <= disp <= retire on every
        # span with children nested under parents, and a single-token
        # ring reassembling to exactly inject + one span per hop.
        "spans_ok": bool(trees) and all(consistent(t)
                                        for t in trees.values()),
        "span_count_ok": bool(spans == hops + 1),
    }


def bench_metrics_smoke(args, delivery="plan", fused=False):
    """Metrics-export smoke (PROFILE.md §11; --metrics-smoke): a small
    seeded world served on an ephemeral metrics port, scraped OVER HTTP
    while run() is live and again at quiescence — the standing record
    that the scrape surface round-trips under load: /healthz answers
    mid-run, the final Prometheus counters equal Runtime.profile(),
    and the text parses. Bounded world, never allowed to sink a
    headline run (main() guards with try/except)."""
    import threading
    import urllib.request

    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.metrics import parse_prometheus
    from ponyc_tpu.models import ring

    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                          msg_words=1, spill_cap=64, inject_slots=8,
                          delivery=delivery, pallas_fused=fused,
                          analysis=1, metrics_port=0,
                          analysis_path="/tmp/pony_tpu.bench_metrics.csv")
    rt, ids = ring.build(64, opts)
    hops = 5000
    rt.send(int(ids[0]), ring.RingNode.token, hops)
    base = f"http://127.0.0.1:{rt._metrics.port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=5.0) as r:
            return r.read().decode()

    live_status = None
    live_scrapes = 0

    def scrape_live():
        nonlocal live_status, live_scrapes
        while not done.is_set():
            try:
                live_status = json.loads(get("/healthz"))["status"]
                parse_prometheus(get("/metrics"))
                live_scrapes += 1
            except OSError:
                pass
            time.sleep(0.02)

    done = threading.Event()
    t = threading.Thread(target=scrape_live, daemon=True)
    t.start()
    t0 = time.time()
    rt.run()
    elapsed = time.time() - t0
    done.set()
    t.join(timeout=5.0)
    final = parse_prometheus(get("/metrics"))
    hz = json.loads(get("/healthz"))
    prof = rt.profile()
    rt.stop()
    counters_match = (
        final.get(("pony_tpu_processed_total", ()))
        == prof["totals"]["processed"]
        and final.get(("pony_tpu_delivered_total", ()))
        == prof["totals"]["delivered"]
        and final.get(("pony_tpu_behaviour_runs_total",
                       (("behaviour", "RingNode.token"),)))
        == prof["behaviours"]["RingNode.token"]["runs"])
    return {
        "port": rt.opts.metrics_port,
        "hops": hops,
        "elapsed_s": round(elapsed, 3),
        "live_scrapes": live_scrapes,
        "live_status": live_status,
        "final_status": hz["status"],
        "scrape_ok": bool(live_scrapes > 0),
        "counters_match": bool(counters_match),
    }


def bench_checkpoint_smoke(args, delivery="plan", fused=False):
    """Durable-worlds smoke (PROFILE.md §12; --checkpoint-smoke): the
    standing record of what crash-safe checkpointing costs and buys on
    this platform — (a) steady-state overhead of a cadence-checkpointed
    run vs the same run with checkpointing off (µs/window), (b) the
    per-checkpoint capture (run-loop-blocking) and write (background)
    costs from Runtime.checkpoint_stats(), (c) restore-fast-start: time
    to restore the soaked terminal world into a fresh runtime, with the
    outcome asserted equal. Bounded world; never sinks a headline run
    (main() guards with try/except)."""
    import shutil
    import tempfile

    import numpy as np
    from ponyc_tpu import Runtime, RuntimeOptions, serialise
    from ponyc_tpu.models import ring

    tmp = tempfile.mkdtemp(prefix="pony_ckpt_bench_")
    hops = int(getattr(args, "checkpoint_hops", 20_000))
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8, delivery=delivery,
                pallas_fused=fused)
    try:
        # (a) baseline: checkpointing off
        rt, ids = ring.build(128, RuntimeOptions(**base))
        rt.send(int(ids[0]), ring.RingNode.token, hops)
        t0 = time.perf_counter()
        rt.run()
        off_s = time.perf_counter() - t0
        windows_off = max(1, rt._rl_windows)
        want = np.asarray(rt.cohort_state(ring.RingNode)["passes"])
        rt.stop()

        # (b) the same run with the cadence checkpointer armed
        prefix = tmp + "/ring"
        rt2, ids2 = ring.build(128, RuntimeOptions(
            **base, checkpoint_every_s=0.02, checkpoint_path=prefix,
            checkpoint_keep=3))
        rt2.send(int(ids2[0]), ring.RingNode.token, hops)
        t0 = time.perf_counter()
        rt2.run()
        on_s = time.perf_counter() - t0
        windows_on = max(1, rt2._rl_windows)
        stats = rt2.checkpoint_stats()
        equal_ok = bool((np.asarray(
            rt2.cohort_state(ring.RingNode)["passes"]) == want).all())
        rt2.stop()                      # final fast-start checkpoint

        # (c) restore-fast-start: soaked world into a fresh runtime
        newest = serialise.newest_intact(prefix)
        ring_files = serialise.list_checkpoints(prefix)
        intact_ok = True
        for _seq, f in ring_files:
            try:
                serialise.verify_snapshot(f)
            except Exception:            # noqa: BLE001
                intact_ok = False
        rt3, _ = ring.build(128, RuntimeOptions(**base))
        t0 = time.perf_counter()
        serialise.restore(rt3, newest)
        restore_s = time.perf_counter() - t0
        restore_equal_ok = bool((np.asarray(
            rt3.cohort_state(ring.RingNode)["passes"]) == want).all())

        n_ckpt = max(1, stats["checkpoints"])
        return {
            "hops": hops,
            "checkpoints": stats["checkpoints"],
            "ring_files": len(ring_files),
            "ring_intact_ok": intact_ok,
            "run_off_s": round(off_s, 4),
            "run_on_s": round(on_s, 4),
            # per-window tax of the armed checkpointer (wall-clock delta
            # over the baseline; noisy at smoke scale — the capture/
            # write costs below are the per-event truth)
            "ckpt_cost_us_per_window": round(
                max(0.0, on_s - off_s) / windows_on * 1e6, 1),
            "windows": windows_on,
            "windows_off": windows_off,
            "capture_ms_mean": round(
                stats["capture_ms_total"] / n_ckpt, 3),
            "write_ms_mean": round(stats["write_ms_total"]
                                   / max(1, stats["written"]), 3),
            "write_failures": stats["failures"],
            "bytes_last": stats["bytes_last"],
            "restore_fast_start_s": round(restore_s, 4),
            "equal_ok": bool(equal_ok and restore_equal_ok),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_latency(args, delivery="plan", fused=False):
    """p50 behaviour-dispatch latency: single token on a 1024-actor ring,
    one hop per tick. The headline number is the DEVICE-RESIDENT per-hop
    latency — window-of-K hops in one fused dispatch, divided by K — the
    analog of the reference's scheduler-internal dispatch latency (its
    number contains no host RPC either). The per-call host round-trip
    (which adds the tunnel/dispatch overhead on top) is reported
    alongside as host_roundtrip_us."""
    import jax
    import jax.numpy as jnp
    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.models import ring

    # The latency ring reuses the headline run's RESOLVED formulation
    # (auto calibrating again on the tiny ring layout would measure the
    # wrong program and pay a second calibration).
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                          spill_cap=64, inject_slots=8,
                          delivery=delivery, pallas_fused=fused)
    rt, ids = ring.build(args.lat_actors, opts)
    rt.send(int(ids[0]), ring.RingNode.token, 1 << 30)
    inj = rt._drain_inject()
    state, aux = rt._step(rt.state, *inj)     # pays the jit + injects token
    jax.block_until_ready(aux)
    inj = rt._empty_inject
    K = 32
    limit = jnp.int32(K)
    state, aux, _k = rt._multi(state, *inj, limit)   # fused-window jit
    jax.block_until_ready(aux)
    # Enough windows that the p90 over window means is a real quantile,
    # not the max of a handful of samples.
    windows = max(20, args.lat_ticks // K)
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        state, aux, _k = rt._multi(state, *inj, limit)
        jax.block_until_ready(aux)
        times.append((time.perf_counter() - t0) / K)
    # host round-trip: one hop per individually-synced dispatch.
    rtt = []
    for _ in range(20):
        t0 = time.perf_counter()
        state, aux = rt._step(state, *inj)
        jax.block_until_ready(aux)
        rtt.append(time.perf_counter() - t0)
    rt.state = state
    hops = int(rt.cohort_state(ring.RingNode)["passes"].sum())
    # inject step delivers but doesn't dispatch (dispatch precedes
    # delivery in the step): hops = warm window + timed windows + rtt.
    expect = K + windows * K + 20
    return {
        "p50_us": 1e6 * statistics.median(times),
        "p90_us": 1e6 * sorted(times)[int(0.9 * len(times))],
        "host_roundtrip_us": 1e6 * statistics.median(rtt),
        "hops_ok": bool(hops == expect),
    }


def bench_serve_smoke(args, delivery="plan", fused=False):
    """Serving front door smoke (ISSUE 9; --serve-smoke): the standing
    `serving` BENCH block. Phase 1 measures service capacity with a
    gentle closed loop; phase 2 offers ~2x that in concurrent demand
    (conns x depth far past the worker pool) for a fixed window and
    records what the north-star claim needs a number for: p50/p99
    end-to-end latency of ADMITTED requests, shed rate at the edge,
    and goodput under overload — then drains gracefully and asserts
    the mailbox rings never hit a sticky-fail state. Bounded world;
    never sinks a headline run (main() guards with try/except)."""
    import threading

    from ponyc_tpu import loadgen, serve

    workers = 16
    opts = serve.default_options(workers, delivery=delivery,
                                 pallas_fused=fused)
    rt, server = serve.build(workers, opts)
    port = server.listen("127.0.0.1", 0)
    out = {}

    def client():
        try:
            out["calib"] = loadgen.run_load(
                "127.0.0.1", port, conns=2, depth=2, requests=30)
            out["load"] = loadgen.run_load(
                "127.0.0.1", port, conns=4, depth=4 * workers,
                requests=1 << 30, duration_s=2.0,
                busy_backoff_s=0.005)
        finally:
            server.begin_drain()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    code = rt.run()
    t.join(timeout=60.0)
    stats = server.stats()
    sticky = {f"{cls}:{c}": int(n)
              for (cls, c), n in rt._error_counts.items()
              if cls in ("SpillOverflowError", "SpawnCapacityError",
                         "BlobCapacityError")}
    rt.stop()
    calib = out.get("calib") or {}
    load = out.get("load") or {}
    capacity = max(1.0, calib.get("goodput_rps", 0.0))
    return {
        "workers": workers,
        "capacity_rps_est": round(capacity, 1),
        "offered_rps": load.get("offered_rps", 0.0),
        "overload_x": round(load.get("offered_rps", 0.0) / capacity, 2),
        "sent": load.get("sent", 0),
        "ok": load.get("ok", 0),
        "busy": load.get("busy", 0),
        "unanswered": load.get("unanswered", 0),
        "bad_value": load.get("bad_value", 0),
        "p50_us": load.get("p50_us", 0),
        "p99_us": load.get("p99_us", 0),
        "goodput_rps": load.get("goodput_rps", 0.0),
        "shed_rate": load.get("shed_rate", 0.0),
        "shed_by_reason": stats["shed"],
        "admission": stats["admission"],
        "batches": stats["batches"],
        "submitted": stats["submitted"],
        "rings_sticky_fail": sticky,          # must stay empty: the
        #   edge shed BEFORE the device could wedge
        "rings_ok": bool(not sticky and code == 0),
        "drained_ok": bool(stats["drained"] and code == 0),
        "shed_ok": bool(load.get("busy", 0) > 0),
        "replies_accounted": bool(
            load.get("unanswered", 0) == 0 and calib.get(
                "unanswered", 0) == 0),
    }


def bench_perf_smoke(args):
    """--perf-smoke (ISSUE 19): the observatory end-to-end in seconds —
    a tiny headline-shaped ubench run whose json carries the `measured`
    block (XLA cost/memory analysis of the real executables, the
    record-move probe, the model_divergence verdict) and appends the
    scoreboard row to BENCH_HISTORY.jsonl. CPU by default (CI shape);
    --platform tpu probes like the full bench. Returns the process
    exit code (1 only when the measured capture itself failed)."""
    if args.platform != "tpu":
        force_cpu()
    # Smoke shape: small enough for the unit-test clock, big enough
    # that the executables are the real plan/window pair.
    args.actors = min(args.actors, 256)
    args.ticks = min(args.ticks, 32)
    args.fuse = min(args.fuse, 8)
    args.warmup = min(args.warmup, 8)
    import jax
    plat = jax.devices()[0].platform
    ub = bench_ubench(args)
    msgs_per_sec = ub["msgs_per_sec"]
    result = {
        "metric": "ubench_actor_messages_per_sec",
        "value": round(msgs_per_sec, 1),
        "unit": "msgs/sec/chip",
        "vs_baseline": round(msgs_per_sec / CPU32_BASELINE_MSGS_PER_SEC,
                             3),
        "detail": {
            "perf_smoke": True,
            "actors": args.actors,
            "ticks": ub["ticks"],
            "delivery": ub["delivery"],
            "platform": plat,
            "packed_bytes_per_msg": ub["packed_bytes_per_msg"],
        },
        "kernel": {"bytes_per_msg": ub["bytes_model"]},
        "measured": ub["measured"],
    }
    result["history_path"] = append_history(result)
    print(json.dumps(result))
    return 1 if "error" in (ub["measured"] or {}) else 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--actors", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_ACTORS",
                                               1 << 20)))
    ap.add_argument("--ticks", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_TICKS", 256)))
    ap.add_argument("--fuse", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_FUSE", 64)))
    ap.add_argument("--warmup", type=int, default=64)
    ap.add_argument("--cap", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_CAP", 4)))
    ap.add_argument("--pings", type=int,
                    default=int(os.environ.get("PONY_TPU_BENCH_PINGS", 4)))
    ap.add_argument("--delivery",
                    default=os.environ.get("PONY_TPU_BENCH_DELIVERY",
                                           "auto"),
                    choices=["plan", "cosort", "pallas_mega", "auto"],
                    help="delivery formulation; 'auto' (default) "
                    "calibrates plan vs cosort (and the pallas_mega "
                    "persistent window kernel where eligible) "
                    "in-executable at start and records the table in "
                    "the JSON (tuning.py)")
    ap.add_argument("--fused", nargs="?", const="on",
                    default=os.environ.get("PONY_TPU_BENCH_FUSED", "0"),
                    choices=["on", "off", "auto", "0", "1"],
                    help="fused Pallas dispatch: on/off/auto "
                    "(auto adds it to the calibrated variants)")
    ap.add_argument("--pallas", nargs="?", const="on",
                    default=os.environ.get("PONY_TPU_BENCH_PALLAS", "0"),
                    choices=["on", "off", "auto", "0", "1"],
                    help="Pallas drain kernel: on/off/auto")
    ap.add_argument("--lat-actors", type=int, default=1024)
    ap.add_argument("--lat-ticks", type=int, default=200)
    ap.add_argument("--platform",
                    default=os.environ.get("PONY_TPU_BENCH_PLATFORM",
                                           "auto"),
                    choices=["auto", "tpu", "cpu"])
    ap.add_argument("--probe-timeout", type=float,
                    default=float(os.environ.get(
                        "PONY_TPU_BENCH_PROBE_TIMEOUT", 180.0)))
    ap.add_argument("--probe-budget", type=float,
                    default=float(os.environ.get(
                        "PONY_TPU_BENCH_PROBE_BUDGET", 900.0)))
    ap.add_argument("--trace-smoke", action="store_true",
                    default=os.environ.get(
                        "PONY_TPU_BENCH_TRACE_SMOKE", "0") == "1",
                    help="run one sampled causal-tracing window "
                    "(analysis=3, trace_sample=1) and embed a "
                    "`tracing` block in the JSON (PROFILE.md §10)")
    ap.add_argument("--metrics-smoke", action="store_true",
                    default=os.environ.get(
                        "PONY_TPU_BENCH_METRICS_SMOKE", "0") == "1",
                    help="scrape-under-load round-trip: serve a small "
                    "world on an ephemeral metrics_port, scrape "
                    "/metrics+/healthz over HTTP during run(), and "
                    "embed a `metrics` block asserting the final "
                    "counters equal Runtime.profile() (PROFILE.md §11)")
    ap.add_argument("--checkpoint-smoke", action="store_true",
                    default=os.environ.get(
                        "PONY_TPU_BENCH_CHECKPOINT_SMOKE", "0") == "1",
                    help="durable-worlds smoke: a cadence-checkpointed "
                    "run vs the same run with checkpointing off "
                    "(ckpt_cost_us_per_window), per-checkpoint capture/"
                    "write costs, and restore-fast-start time — "
                    "embedded as a `checkpoint` block (PROFILE.md §12)")
    ap.add_argument("--no-fallback", action="store_true",
                    default=os.environ.get(
                        "PONY_TPU_BENCH_ALLOW_CPU", "1") == "0",
                    help="with --platform auto, exit non-zero (with "
                    "the flight-recorder probe postmortem in the "
                    "JSON) when TPU init fails, instead of quietly "
                    "publishing a CPU-fallback number")
    ap.add_argument("--kernel-smoke", action="store_true",
                    default=os.environ.get(
                        "PONY_TPU_BENCH_KERNEL_SMOKE", "0") == "1",
                    help="megakernel A/B smoke: the same seeded world "
                    "through delivery=plan and delivery=pallas_mega, "
                    "compared bit-for-bit, with per-variant tick "
                    "timings and the packed bytes/msg model — "
                    "embedded as the `kernel` block (PROFILE.md §14)")
    ap.add_argument("--serve-smoke", action="store_true",
                    default=os.environ.get(
                        "PONY_TPU_BENCH_SERVE_SMOKE", "0") == "1",
                    help="serving front door smoke (ISSUE 9): drive "
                    "the real socket ingress tier (serve.py) with "
                    "loadgen at ~2x measured capacity and embed a "
                    "`serving` block — p50/p99 end-to-end latency of "
                    "admitted requests, shed rate, goodput, and the "
                    "rings-never-sticky-fail check")
    ap.add_argument("--xprof", type=int, default=int(os.environ.get(
                        "PONY_TPU_BENCH_XPROF", 0)), metavar="N",
                    help="wrap N retired fused windows in a "
                    "jax.profiler trace (Runtime.profile_device) and "
                    "record the trace dir in the `measured` block")
    ap.add_argument("--skip-measured", action="store_true",
                    help="skip the measured cost capture (dev "
                    "iteration only — runs for the record keep it; "
                    "the BENCH json says `skipped` instead)")
    ap.add_argument("--perf-smoke", action="store_true",
                    default=os.environ.get(
                        "PONY_TPU_BENCH_PERF_SMOKE", "0") == "1",
                    help="device-cost observatory smoke (ISSUE 19): a "
                    "tiny headline-shaped run emitting the `measured` "
                    "block (XLA cost/memory analysis + record-move "
                    "probe + model_divergence) and appending the "
                    "scoreboard row to BENCH_HISTORY.jsonl — seconds, "
                    "not minutes; for tests and CI")
    args = ap.parse_args()
    args.warmup = max(1, args.warmup)   # the first step pays the jit
    args.lat_ticks = max(1, args.lat_ticks)
    if args.perf_smoke:
        sys.exit(bench_perf_smoke(args))

    allow_cpu = cpu_fallback_allowed(args.no_fallback)
    # BENCH runs always enumerate the persistent megakernel in the
    # delivery=auto A/B table (ops/megakernel.auto_enumerable gates it
    # off by default on CPU so the unit suite stays lean):
    os.environ.setdefault("PONY_TPU_MEGA_AUTO", "1")
    tpu_error = None
    tpu_pm = None        # flight-recorder postmortem of a failed init
    # Backend init wall-time: probe + first jax.devices(), the number
    # ROADMAP item 2's hang diagnosis needs in every BENCH record.
    t_init = time.monotonic()
    if args.platform == "cpu":
        force_cpu()
    elif args.platform == "auto":
        plat, tpu_error, timeline = probe_tpu(args.probe_timeout,
                                              args.probe_budget)
        if plat is None:
            tpu_pm = tpu_init_postmortem(timeline)
            if not allow_cpu:
                print(json.dumps({
                    "error": "tpu_init_failed", "detail": tpu_error,
                    "backend_init_s": round(
                        time.monotonic() - t_init, 1),
                    "tpu_env": tpu_env_details(),
                    "postmortem": tpu_pm}))
                sys.exit(1)
            print("bench: TPU unavailable — falling back to CPU "
                  "(PONY_TPU_BENCH_ALLOW_CPU=0 to make this fatal). "
                  f"Last error: {tpu_error}", file=sys.stderr)
            force_cpu()
            # A 1M-actor world on the CPU backend takes minutes per
            # window; shrink the default size so a wedged-tunnel run
            # still records a bounded (clearly-labelled) result.
            if args.actors >= 1 << 18:
                args.actors = 1 << 17
                print("bench: CPU fallback shrinks --actors to "
                      f"{args.actors}", file=sys.stderr)
    else:
        # --platform tpu used to let jax.devices() init in-process —
        # the silent 90s hang of r03–r05. Probe in a subprocess with a
        # timeout instead, and make failure FAST and EXPLICIT: a
        # parseable tpu_init_error carrying the flight-recorder
        # postmortem (probe timeline + env snapshot) and the doctor's
        # one-line diagnosis on stderr.
        plat, tpu_error, timeline = probe_tpu(args.probe_timeout,
                                              args.probe_budget)
        if plat is None:
            tpu_pm = tpu_init_postmortem(timeline)
            print(json.dumps({
                "error": "tpu_init_failed", "detail": tpu_error,
                "backend_init_s": round(time.monotonic() - t_init, 1),
                "tpu_env": tpu_env_details(),
                "postmortem": tpu_pm}))
            sys.exit(1)

    import jax
    plat = jax.devices()[0].platform
    backend_init_s = time.monotonic() - t_init

    # Persistent compile cache (tuning.enable_compile_cache): the
    # second run of an identical bench reloads its executables instead
    # of re-lowering — the warmup_s delta is the measurement.
    from ponyc_tpu import tuning as _tuning
    compile_cache = _tuning.enable_compile_cache()

    ub = bench_ubench(args)
    lat = bench_latency(args, delivery=ub["delivery"],
                        fused=ub["pallas_fused"])
    # Attribution pass (analysis=1): records per-behaviour runs +
    # queue-wait percentiles so the perf trajectory carries attribution,
    # not just totals. Never allowed to sink a headline run.
    try:
        telemetry = bench_telemetry(args, delivery=ub["delivery"],
                                    fused=ub["pallas_fused"])
    except Exception as e:                       # noqa: BLE001
        telemetry = {"error": str(e)}
    # Run-loop overhead study (PROFILE.md §9): pipelined adaptive vs
    # forced synchronous host_gap_us through the real run() loop.
    try:
        run_loop = bench_runloop(args, delivery=ub["delivery"],
                                 fused=ub["pallas_fused"])
    except Exception as e:                       # noqa: BLE001
        run_loop = {"error": str(e)}
    # Causal-tracing smoke (--trace-smoke): the standing record that
    # trace propagation + reassembly hold on this platform.
    tracing_block = None
    if args.trace_smoke:
        try:
            tracing_block = bench_trace_smoke(
                args, delivery=ub["delivery"], fused=ub["pallas_fused"])
        except Exception as e:                   # noqa: BLE001
            tracing_block = {"error": str(e)}
    # Metrics-export smoke (--metrics-smoke): the scrape-under-load
    # round-trip record (PROFILE.md §11).
    metrics_block = None
    if args.metrics_smoke:
        try:
            metrics_block = bench_metrics_smoke(
                args, delivery=ub["delivery"], fused=ub["pallas_fused"])
        except Exception as e:                   # noqa: BLE001
            metrics_block = {"error": str(e)}
    # Durable-worlds smoke (--checkpoint-smoke): checkpoint cost per
    # window + restore-fast-start time (PROFILE.md §12).
    checkpoint_block = None
    if args.checkpoint_smoke:
        try:
            checkpoint_block = bench_checkpoint_smoke(
                args, delivery=ub["delivery"], fused=ub["pallas_fused"])
        except Exception as e:                   # noqa: BLE001
            checkpoint_block = {"error": str(e)}
    # Serving front door smoke (--serve-smoke): the standing 2x-
    # overload record of ISSUE 9 — p50/p99 of admitted requests, shed
    # rate, goodput, rings-never-sticky-fail.
    serving_block = None
    if args.serve_smoke:
        try:
            serving_block = bench_serve_smoke(
                args, delivery=ub["delivery"], fused=ub["pallas_fused"])
        except Exception as e:                   # noqa: BLE001
            serving_block = {"error": str(e)}
    # Megakernel block (PROFILE.md §14): the bandwidth-diet model at
    # the headline run's measured escape rate rides EVERY json;
    # --kernel-smoke adds the bit-for-bit plan-vs-pallas_mega A/B.
    kernel_block = {"bytes_per_msg": ub["bytes_model"]}
    if args.kernel_smoke:
        try:
            kernel_block.update(bench_kernel_smoke(args))
        except Exception as e:                   # noqa: BLE001
            kernel_block["error"] = str(e)
    msgs_per_sec = ub["msgs_per_sec"]

    result = {
        "metric": "ubench_actor_messages_per_sec",
        "value": round(msgs_per_sec, 1),
        "unit": "msgs/sec/chip",
        "vs_baseline": round(msgs_per_sec / CPU32_BASELINE_MSGS_PER_SEC, 3),
        "detail": {
            "actors": args.actors,
            "ticks": ub["ticks"],
            "pings": ub["pings"],
            "delivery": ub["delivery"],
            "delivery_requested": args.delivery,
            "pallas": ub["pallas"],
            "pallas_fused": ub["pallas_fused"],
            "fused_ticks_per_dispatch": ub["fuse"],
            "elapsed_s": round(ub["elapsed_s"], 4),
            "tick_ms": round(ub["tick_ms"], 3),
            "processed_counter_ok": ub["processed_counter_ok"],
            "packed_bytes_per_msg": ub["packed_bytes_per_msg"],
            "build_s": round(ub["build_s"], 1),
            "warmup_s": round(ub["warmup_s"], 1),
            "platform": plat,
            "backend_init_s": round(backend_init_s, 1),
            "p50_dispatch_latency_us": round(lat["p50_us"], 1),
            "p90_dispatch_latency_us": round(lat["p90_us"], 1),
            "host_roundtrip_us": round(lat["host_roundtrip_us"], 1),
            "latency_ring_actors": args.lat_actors,
            "latency_hops_ok": lat["hops_ok"],
            "compile_cache": compile_cache,
        },
        # In-executable tick_ms per eligible variant + the decision —
        # every bench run IS the A/B record (PROFILE.md §6).
        "tuning": ub["tuning"],
        # Per-behaviour attribution of a headline-shaped pass at
        # analysis=1 (Runtime.profile(), PROFILE.md §8): the perf
        # trajectory records WHERE the ticks went, not just totals.
        "telemetry": telemetry,
        # host_gap_us: pipelined adaptive run loop vs the forced
        # synchronous loop through the real Runtime.run() (PROFILE.md
        # §9) — the standing record of this PR's win.
        "run_loop": run_loop,
        # Persistent megakernel + mailbox bandwidth diet (PROFILE.md
        # §14): packed bytes/msg model at the measured escape rate,
        # plus the --kernel-smoke bit-for-bit A/B when requested.
        "kernel": kernel_block,
        # Measured device costs (costs.py, ISSUE 19): XLA's own
        # cost/memory analysis of the headline run's compiled
        # executables, the record-move probe, and the loud
        # model_divergence verdict against the modelled bytes/msg.
        "measured": ub["measured"],
    }
    if tracing_block is not None:
        result["tracing"] = tracing_block
    if metrics_block is not None:
        result["metrics"] = metrics_block
    if checkpoint_block is not None:
        result["checkpoint"] = checkpoint_block
    if serving_block is not None:
        result["serving"] = serving_block
    if tpu_error is not None:
        result["detail"]["tpu_init_error"] = tpu_error
        result["detail"]["tpu_env"] = tpu_env_details()
        # CPU-fallback rounds carry the stall evidence (probe timeline
        # + env snapshot) INSIDE the BENCH record, so a degraded round
        # is diagnosable from the json alone:
        #   python -m ponyc_tpu doctor --postmortem BENCH_rNN.json
        result["postmortem"] = tpu_pm
    append_history(result)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
