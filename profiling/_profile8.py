"""Round-3 planar profile, in-executable: each component chained K times
inside ONE jitted fori_loop so the axon-tunnel launch latency (~11ms/call
observed) divides out. Prints ms per iteration of each component."""
import sys
import time

sys.path.insert(0, "/root/repo")
from ponyc_tpu.platforms import force_cpu
if "tpu" not in sys.argv:
    force_cpu()

import jax
import jax.numpy as jnp
from jax import lax

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import ubench
from ponyc_tpu.runtime import engine, delivery
from ponyc_tpu.ops.segment import stable_sort_by

N = 1 << 20
CAP = 4
K = 20


def timeit_loop(name, body, init, reps=3):
    """body: carry -> carry, chained K times in one executable."""
    @jax.jit
    def run(c):
        return lax.fori_loop(0, K, lambda i, c: body(c), c)

    out = run(init)
    jax.block_until_ready(out)
    best = 1e9
    for _ in range(reps):
        t0 = time.time()
        out = run(init)
        jax.block_until_ready(out)
        best = min(best, time.time() - t0)
    print(f"{name:52s} {best / K * 1e3:8.3f} ms/iter")
    return out


SEL = set(a for a in sys.argv[1:] if a != "tpu") or None


def want(tag):
    return SEL is None or tag in SEL


opts = RuntimeOptions(mailbox_cap=CAP, batch=1, max_sends=1, msg_words=1,
                      spill_cap=1024, inject_slots=8)
rt, ids = ubench.build(N, opts)
ubench.seed_all(rt, ids, hops=1 << 30)
print("platform:", jax.devices()[0].platform)

inj = rt._empty_inject
st, aux = rt._step(rt.state, *inj)
jax.block_until_ready(aux)
rt.state = st

# 0. full step chained (ground truth per-tick device cost)
if want("step"):
    timeit_loop("FULL STEP (chained in-executable)",
                lambda s: engine.build_step(rt.program, opts)(s, *inj)[0],
                st)

# 1. dispatch only
ch = rt.program.device_cohorts[0]
LAYOUT = tuple((c.atype.__name__, c.local_start, c.local_stop,
                1 + c.msg_words) for c in rt.program.cohorts)
disp = engine._cohort_dispatch(ch, opts, opts.noyield, rt.program)
idsj = jnp.arange(N, dtype=jnp.int32)


def disp_body(s):
    occ = s.tail - s.head
    runnable = s.alive & ~s.muted
    out = disp(s.type_state[ch.atype.__name__], s.buf[ch.atype.__name__],
               s.head, occ, runnable, idsj, {})
    # chain: fold outbox into head so the loop carries a dependency
    return s._replace(head=out[2])


if want("disp"):
    timeit_loop("dispatch only", disp_body, st)

# one real outbox for delivery inputs
occ = st.tail - st.head
runnable = st.alive & ~st.muted
out = jax.jit(lambda s: disp(s.type_state[ch.atype.__name__],
                             s.buf[ch.atype.__name__],
                             s.head, occ, runnable, idsj, {}))(st)
ent = out[1]
tgt, sender, words = (jnp.asarray(ent.tgt), jnp.asarray(ent.sender),
                      jnp.asarray(ent.words))
E = tgt.shape[0]
inj_t = jnp.full((opts.inject_slots,), -1, jnp.int32)
inj_w = jnp.zeros((words.shape[0], opts.inject_slots), jnp.int32)
tgt_f = jnp.concatenate([st.dspill_tgt, inj_t, st.rspill_tgt, tgt])
snd_f = jnp.concatenate([st.dspill_sender, inj_t, st.rspill_sender, sender])
wrd_f = jnp.concatenate([st.dspill_words, inj_w, st.rspill_words, words],
                        axis=1)


def deliver_body(plan):
    def go(s, use_plan):
        e = delivery.Entries(tgt=tgt_f, sender=snd_f, words=wrd_f)
        res = delivery.deliver(
            s.buf, s.head, s.tail, s.alive, e,
            n_local=N, mailbox_cap=CAP, spill_cap=1024,
            overload_occ=opts.overload_occ, shard_base=jnp.int32(0),
            cohort_layout=LAYOUT, mute_slots=opts.mute_slots,
            plan=(s.plan_key, s.plan_perm, s.plan_bounds) if use_plan
            else None)
        return s._replace(buf=res.buf, plan_key=res.plan_key,
                          plan_perm=res.plan_perm,
                          plan_bounds=res.plan_bounds)
    return go


if want("delc"):
    timeit_loop("delivery (plan cached)",
                lambda s: deliver_body(True)(s, True), st)
if want("deln"):
    timeit_loop("delivery (no plan cache)",
                lambda s: deliver_body(False)(s, False), st)

# sub-pieces, chained
key = jnp.where(tgt_f >= 0, tgt_f, N).astype(jnp.int32)
if want("sub"):
    timeit_loop("stable_sort [E]",
                lambda k: stable_sort_by(k) + k * 0, key)
perm = stable_sort_by(key)
if want("sub"):
    timeit_loop("payload gather words[:, perm] (planar)",
                lambda w: w[:, perm] + w * 0, wrd_f)
ks = key[perm]
bounds = jnp.searchsorted(ks, jnp.arange(N + 1, dtype=jnp.int32),
                          side="left").astype(jnp.int32)
seg = bounds[:-1]
wds = wrd_f[:, perm]
EF = tgt_f.shape[0]


def plane_rebuild(buf, head, tail):
    space = jnp.maximum(CAP - (tail - head), 0)
    cnt = bounds[1:] - seg
    acc = jnp.minimum(cnt, space)
    planes = []
    for ci in range(CAP):
        rel = (ci - tail) % CAP
        wmask = rel < acc
        src = jnp.minimum(seg + rel, EF - 1)
        planes.append(jnp.where(wmask[None, :],
                                jnp.take(wds, src, axis=1),
                                buf[ci]))
    return jnp.stack(planes)


if want("sub"):
    timeit_loop("plane rebuild (CAP planes)",
                lambda b: plane_rebuild(b, st.head, st.tail),
                st.buf[ch.atype.__name__])
    timeit_loop("_ring_take (cap select chain)",
                lambda b: b.at[0].set(engine._ring_take(b, st.head % CAP)),
                st.buf[ch.atype.__name__])
    timeit_loop("1-D lane gather wds[0][src]",
                lambda s: wds[0][jnp.minimum(seg + s[0] * 0, EF - 1)] + s,
                jnp.zeros((N,), jnp.int32))
    timeit_loop("plan key compare", lambda a: a + jnp.all(a == key), key)
    timeit_loop("searchsorted bounds",
                lambda b: jnp.searchsorted(
                    ks, jnp.arange(N + 1, dtype=jnp.int32) + b[0] * 0,
                    side="left").astype(jnp.int32) + b * 0, bounds)
