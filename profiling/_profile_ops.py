"""Primitive-op facts on the real chip: what do gather / sort / select
chains / searchsorted actually cost at [1M] on TPU? One small jit per
op, each chained K times in-executable so tunnel launch latency divides
out. These numbers decide the delivery design (gather-based vs
sort-based vs reshape fast path)."""
import sys
import time

sys.path.insert(0, "/root/repo")
from ponyc_tpu.platforms import force_cpu
if "tpu" not in sys.argv:
    force_cpu()

import jax
import jax.numpy as jnp
from jax import lax

N = 1 << 20
K = 32
print("platform:", jax.devices()[0].platform, flush=True)

key = jax.random.PRNGKey(0)
perm = jax.random.permutation(key, N).astype(jnp.int32)
x = jnp.arange(N, dtype=jnp.int32)
xf = x.astype(jnp.float32)


def timeit_loop(name, body, init, reps=3):
    @jax.jit
    def run(c):
        return lax.fori_loop(0, K, lambda i, c: body(c), c)
    out = run(init)
    jax.block_until_ready(out)
    best = 1e9
    for _ in range(reps):
        t0 = time.time()
        out = run(init)
        jax.block_until_ready(out)
        best = min(best, time.time() - t0)
    print(f"{name:46s} {best / K * 1e3:8.3f} ms/iter", flush=True)
    return out


timeit_loop("vector add [1M] i32 (baseline)", lambda v: v + 1, x)
timeit_loop("gather x[perm] [1M] i32", lambda v: v[perm] + 1, x)
timeit_loop("gather x[perm] [1M] f32", lambda v: v[perm] + 1, xf)
timeit_loop("gather 2-row [2,1M][:,perm]",
            lambda v: v[:, perm] + 1, jnp.stack([x, x]))
timeit_loop("sort [1M] i32 (keys only)",
            lambda v: lax.sort(v) + 1, x)
timeit_loop("sort [1M] 2-operand (argsort)",
            lambda v: lax.sort((v, x), num_keys=1)[0] + 1, x)
timeit_loop("sort [1M] 4-operand (co-sort payload)",
            lambda v: lax.sort((v, x, x, x), num_keys=1)[0] + 1, x)
timeit_loop("searchsorted [1M] into [1M]",
            lambda v: jnp.searchsorted(
                x, v, side="left").astype(jnp.int32), x)
timeit_loop("select chain x8 [1M]",
            lambda v: sum(jnp.where(v % 8 == c, v + c, 0)
                          for c in range(8)), x)
timeit_loop("scatter .at[perm].set [1M]",
            lambda v: jnp.zeros((N,), jnp.int32).at[perm].set(v) + 1, x)
timeit_loop("cumsum [1M] i32", lambda v: jnp.cumsum(v) + 1, x)
# the reshape/strided fast-path candidate: [4, N] planes read by static idx
b4 = jnp.stack([x, x + 1, x + 2, x + 3])
timeit_loop("4-plane where-select rebuild",
            lambda v: jnp.stack([jnp.where((x + c) % 4 == 0, v[c], v[c] + 1)
                                 for c in range(4)]), b4)
