"""Capture a per-op TPU trace of the ubench fused window via
jax.profiler, then parse the xplane with xprof/tensorboard-plugin-profile
and print the op-time table. Usage: python _profile_xprof.py tpu [pings]"""
import glob
import os
import sys
import time

sys.path.insert(0, "/root/repo")
from ponyc_tpu.platforms import force_cpu
if "tpu" not in sys.argv:
    force_cpu()

PINGS = 4 if "pings" in sys.argv else 1

import jax
import jax.numpy as jnp

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import ubench

N = 1 << 20
CAP = 4
opts = RuntimeOptions(mailbox_cap=CAP, batch=PINGS, max_sends=1,
                      msg_words=1, spill_cap=1024, inject_slots=8)
rt, ids = ubench.build(N, opts, pings=PINGS)
ubench.seed_all(rt, ids, hops=1 << 30, pings=PINGS)
print("platform:", jax.devices()[0].platform, "pings:", PINGS, flush=True)

K = 16
limit = jnp.int32(K)
inj = rt._empty_inject
state = rt.state
t0 = time.time()
state, aux, _k = rt._multi(state, *inj, limit)
jax.block_until_ready(aux)
print(f"compile+first window: {time.time() - t0:.1f}s", flush=True)

logdir = "/tmp/xprof_ubench"
os.system(f"rm -rf {logdir}")
jax.profiler.start_trace(logdir)
for _ in range(2):
    state, aux, _k = rt._multi(state, *inj, limit)
jax.block_until_ready(aux)
jax.profiler.stop_trace()
t0 = time.time()
state, aux, _k = rt._multi(state, *inj, limit)
jax.block_until_ready(aux)
print(f"tick_ms (post-trace window): {(time.time() - t0) / K * 1e3:.3f}",
      flush=True)

planes = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
print("xplanes:", planes, flush=True)
if planes:
    try:
        from tensorboard_plugin_profile.convert import raw_to_tool_data
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [planes[0]], "op_profile", {})
        open("/tmp/xprof_op_profile.json", "wb").write(
            data if isinstance(data, bytes) else data.encode())
        print("wrote /tmp/xprof_op_profile.json", flush=True)
    except Exception as e:
        print("op_profile failed:", e, flush=True)
    try:
        from tensorboard_plugin_profile.convert import raw_to_tool_data
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [planes[0]], "hlo_stats", {})
        open("/tmp/xprof_hlo_stats.json", "wb").write(
            data if isinstance(data, bytes) else data.encode())
        print("wrote /tmp/xprof_hlo_stats.json", flush=True)
    except Exception as e:
        print("hlo_stats failed:", e, flush=True)
