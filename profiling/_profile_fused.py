"""Follow-up to _profile_all.py: the A/B rows it doesn't cover —
pallas_fused (the north-star fused dispatch kernel, ops/fused_dispatch.py)
and dispatch_gating — plus a cap sweep on the winner axis. Appends to the
same /tmp/p9_results.txt. Run after _profile_all.py releases the claim:
    nohup python -u _profile_fused.py > /tmp/p9_fused.log 2>&1 &
"""
import sys
import time

sys.path.insert(0, "/root/repo")

RES = "/tmp/p9_results.txt"


def note(line):
    with open(RES, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


t0 = time.time()
print("waiting for TPU claim...", flush=True)
import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402

dev = jax.devices()[0]
note(f"# fused-campaign claimed {dev} after {time.time() - t0:.0f}s")

from ponyc_tpu import RuntimeOptions          # noqa: E402
from ponyc_tpu.models import ubench           # noqa: E402
from ponyc_tpu.runtime import engine          # noqa: E402

N = 1 << 20


def run_variant(variant, pings=1, cap=4, **optkw):
    opts = RuntimeOptions(mailbox_cap=cap, batch=pings, max_sends=1,
                          msg_words=1, spill_cap=1024, inject_slots=8,
                          **optkw)
    rt, ids = ubench.build(N, opts, pings=pings)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=pings)
    KT = 64
    limit = jnp.int32(KT)
    inj = rt._empty_inject
    multi = engine.jit_multi_step(rt.program, opts)
    state = rt.state
    t1 = time.time()
    state, aux, _k = multi(state, *inj, limit)
    jax.block_until_ready(aux)
    compile_s = time.time() - t1
    best = 1e9
    for _ in range(4):
        t1 = time.time()
        state, aux, _k = multi(state, *inj, limit)
        jax.block_until_ready(aux)
        best = min(best, time.time() - t1)
    tick_ms = best / KT * 1e3
    note(f"{variant} tick_ms = {tick_ms:.3f} (compile {compile_s:.0f}s, "
         f"msgs/s = {N * pings / tick_ms * 1e3:.3e})")
    return tick_ms


for name, kw in [
    ("fused", dict(pallas_fused=True)),
    ("fused-pings4", dict(pallas_fused=True)),
    ("gating", dict(dispatch_gating=True)),
    ("cosort-fused", dict(pallas_fused=True, delivery="cosort")),
    ("cap8", dict()),
    ("cap2", dict()),
]:
    pings = 4 if "pings4" in name else 1
    cap = {"cap8": 8, "cap2": 2}.get(name, 4)
    try:
        run_variant(name, pings=pings, cap=cap, **kw)
    except Exception as e:                    # noqa: BLE001
        note(f"{name} FAILED: {type(e).__name__}: {str(e)[:300]}")

# Blob-pipeline throughput (models/records at scale): the rich-payload
# path's on-chip cost — alloc/write/migrate-free dispatch + pool churn.
# First full run warms the jit cache (same world shapes); the timed run
# is a FRESH world so only warm execution is measured, like the
# best-of-N rows above.
try:
    from ponyc_tpu.models import records

    n_src, n_per = 4096, 8
    records.run_records(n_sources=n_src, n_records=n_per)   # warm/compile
    t1 = time.time()
    rt, st = records.run_records(n_sources=n_src, n_records=n_per)
    el = time.time() - t1
    n_rec = n_src * n_per
    note(f"records[{n_src}x{n_per}] warm {el:.2f}s = "
         f"{n_rec / el:.3e} records/s (steps {rt.steps_run})")
except Exception as e:                        # noqa: BLE001
    note(f"records FAILED: {type(e).__name__}: {str(e)[:300]}")
note("FUSED_DONE")
