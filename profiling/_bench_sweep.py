"""One-claim bench config sweep: fused-window ubench tick_ms for every
(delivery, pings, pallas) combination, in a single TPU session. Appends
to /tmp/p9_sweep.txt. Run detached; waits for the claim as long as it
takes."""
import sys
import time

sys.path.insert(0, "/root/repo")

OUT = "/tmp/p9_sweep.txt"


def note(line):
    with open(OUT, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


t0 = time.time()
print("waiting for TPU claim...", flush=True)
import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402

note(f"# claimed {jax.devices()[0]} after {time.time() - t0:.0f}s")

from ponyc_tpu import RuntimeOptions          # noqa: E402
from ponyc_tpu.models import ubench           # noqa: E402
from ponyc_tpu.runtime import engine          # noqa: E402

N = 1 << 20
K = 64


def run_cfg(tag, pings, delivery, pallas, fused=False):
    cap = ubench.cap_for_pings(pings)
    opts = RuntimeOptions(mailbox_cap=cap, batch=pings, max_sends=1,
                          msg_words=1, spill_cap=1024, inject_slots=8,
                          delivery=delivery, pallas=pallas,
                          pallas_fused=fused)
    rt, ids = ubench.build(N, opts, pings=pings)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=pings)
    multi = engine.jit_multi_step(rt.program, opts)
    inj = rt._empty_inject
    limit = jnp.int32(K)
    state = rt.state
    t1 = time.time()
    state, aux, _k = multi(state, *inj, limit)
    jax.block_until_ready(aux)
    comp = time.time() - t1
    best = 1e9
    for _ in range(4):
        t1 = time.time()
        state, aux, _k = multi(state, *inj, limit)
        jax.block_until_ready(aux)
        best = min(best, time.time() - t1)
    tick = best / K * 1e3
    note(f"{tag:24s} tick_ms={tick:8.3f}  msgs/s={N * pings / tick * 1e3:.3e}"
         f"  (compile {comp:.0f}s)")


for delivery in ("plan", "cosort"):
    for pings in (1, 4):
        run_cfg(f"{delivery}-p{pings}", pings, delivery, False)
run_cfg("plan-p4-pallas", 4, "plan", True)
run_cfg("cosort-p4-pallas", 4, "cosort", True)
run_cfg("plan-p4-fused", 4, "plan", False, fused=True)
run_cfg("cosort-p4-fused", 4, "cosort", False, fused=True)
note("SWEEP_DONE")
