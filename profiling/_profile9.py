"""Round-3 bisection profile: measure tick_ms of the fused window with
individual step components stubbed out (monkeypatched), to localise the
46 ms. Usage: python _profile9.py tpu <variant>

Variants:
  full      - unmodified step
  nodeliver - delivery returns buf/tail unchanged; head also frozen so
              dispatch stays busy on the same message every tick
  norebuild - delivery runs sort/gather/bounds but skips the plane rebuild
  nogather  - delivery skips the payload gather (rebuild reads unsorted)
  nodisp    - dispatch emits an empty outbox (delivery cond goes idle):
              measures the fixed per-tick frame
  nocounts  - counts_by_key (dspill pending histogram) stubbed to zeros
  pallas    - opts.pallas=True (Pallas mailbox drain kernel)
  pings4    - 4 in-flight pings per pinger, batch=4 (bench --pings 4)
  mutes1    - mute_slots=1 (shrinks the [K,N] merge traffic)
  cap2      - mailbox_cap=2
"""
import sys
import time

sys.path.insert(0, "/root/repo")
from ponyc_tpu.platforms import force_cpu
if "tpu" not in sys.argv:
    force_cpu()

VARIANT = ([a for a in sys.argv[1:] if a != "tpu"] or ["full"])[0]
PINGS = 4 if VARIANT == "pings4" else 1

import jax
import jax.numpy as jnp

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import ubench
from ponyc_tpu.runtime import engine, delivery

N = 1 << 20
CAP = 2 if VARIANT == "cap2" else 4
MUTE_SLOTS = 1 if VARIANT == "mutes1" else 4

real_deliver = delivery.deliver


def deliver_nodeliver(buf, head, tail, alive, entries, **kw):
    res = real_deliver(buf, head, tail, alive, entries, **kw)
    return res._replace(buf=buf, tail=tail)


def make_deliver_patch(skip_rebuild=False, skip_gather=False):
    from jax import lax
    from ponyc_tpu.ops.segment import stable_sort_by

    def deliver(buf, head, tail, alive, entries, *, n_local, mailbox_cap,
                spill_cap, overload_occ, shard_base, cohort_layout,
                mute_slots=4, level=None, n_levels=1, plan=None,
                pressured=None, cosort=False):
        n, c = n_local, mailbox_cap
        tgt, sender, words = entries
        e = tgt.shape[0]
        in_range = (tgt >= 0) & (tgt < n)
        tgt_c = jnp.minimum(jnp.maximum(tgt, 0), n - 1)
        to_dead = in_range & ~alive[tgt_c]
        valid = in_range & ~to_dead
        if level is None:
            level = jnp.zeros((e,), jnp.int32)
            n_levels = 1
        key = jnp.where(valid, tgt * n_levels + level,
                        n * n_levels).astype(jnp.int32)

        def _compute_plan(k):
            p_ = stable_sort_by(k)
            b_ = jnp.searchsorted(
                k[p_], jnp.arange(n + 1, dtype=jnp.int32) * n_levels,
                side="left").astype(jnp.int32)
            return p_, b_

        if plan is None:
            perm, bounds = _compute_plan(key)
        else:
            plan_key, plan_perm, plan_bounds = plan
            perm, bounds = lax.cond(
                jnp.all(key == plan_key),
                lambda _: (plan_perm, plan_bounds),
                lambda _: _compute_plan(key), operand=None)
        w1 = words.shape[0]
        if skip_gather:
            wds = words
        else:
            wds = words[:, perm]
        seg_start = bounds[:-1]
        cnt = bounds[1:] - seg_start
        occ = tail - head
        space = jnp.maximum(c - occ, 0)
        acc = jnp.minimum(cnt, space)
        new_tail = tail + acc
        if skip_rebuild:
            buf2 = buf
        else:
            # Per-cohort tables at their own widths (delivery.py).
            buf2 = {}
            for cname, s0, s1, w1c in cohort_layout:
                planes = []
                for ci in range(c):
                    rel = (ci - tail[s0:s1]) % c
                    wmask = rel < acc[s0:s1]
                    src = jnp.minimum(seg_start[s0:s1] + rel, e - 1)
                    planes.append(jnp.where(wmask[None, :],
                                            jnp.take(wds[:w1c], src, axis=1),
                                            buf[cname][ci]))
                buf2[cname] = jnp.stack(planes)
        refs, ovf = delivery.empty_mute_slots(n, mute_slots)
        return delivery.DeliveryResult(
            buf=buf2, tail=new_tail,
            spill=delivery.Entries(
                tgt=jnp.full((spill_cap,), -1, jnp.int32),
                sender=jnp.full((spill_cap,), -1, jnp.int32),
                words=jnp.zeros((w1, spill_cap), jnp.int32)),
            spill_count=jnp.int32(0),
            spill_overflow=jnp.bool_(False),
            newly_muted=jnp.zeros((n,), jnp.bool_),
            new_mute_refs=refs, new_mute_ovf=ovf,
            n_delivered=jnp.sum(acc), n_rejected=jnp.int32(0),
            n_deadletter=jnp.sum(to_dead.astype(jnp.int32)),
            plan_key=key, plan_perm=perm, plan_bounds=bounds)
    return deliver


if VARIANT == "nodeliver":
    engine.deliver = deliver_nodeliver
elif VARIANT == "norebuild":
    engine.deliver = make_deliver_patch(skip_rebuild=True)
elif VARIANT == "nogather":
    engine.deliver = make_deliver_patch(skip_gather=True)
elif VARIANT == "nocounts":
    engine.counts_by_key = (
        lambda keys, vals, n: jnp.zeros((n,), jnp.int32))
elif VARIANT == "nodisp":
    real_cd = engine._cohort_dispatch

    def patched_cd(cohort, opts, noyield, program):
        inner = real_cd(cohort, opts, noyield, program)

        def run_cohort(ts, buf_rows, head_rows, occ_rows, runnable_rows,
                       ids, resv, blob=None):
            out = inner(ts, buf_rows, head_rows, occ_rows,
                        jnp.zeros_like(runnable_rows), ids, resv,
                        blob=blob)
            return out
        return run_cohort
    engine._cohort_dispatch = patched_cd

opts = RuntimeOptions(mailbox_cap=CAP, batch=PINGS, max_sends=1,
                      msg_words=1, spill_cap=1024, inject_slots=8,
                      mute_slots=MUTE_SLOTS,
                      pallas=(VARIANT == "pallas"))
rt, ids = ubench.build(N, opts, pings=PINGS)
ubench.seed_all(rt, ids, hops=1 << 30, pings=PINGS)
print("platform:", jax.devices()[0].platform, "variant:", VARIANT,
      flush=True)

K = 64
limit = jnp.int32(K)
inj = rt._empty_inject
multi = engine.jit_multi_step(rt.program, opts)
state = rt.state
t0 = time.time()
state, aux, _k = multi(state, *inj, limit)
jax.block_until_ready(aux)
print(f"compile+first window: {time.time() - t0:.1f}s", flush=True)
best = 1e9
for _ in range(4):
    t0 = time.time()
    state, aux, _k = multi(state, *inj, limit)
    jax.block_until_ready(aux)
    best = min(best, time.time() - t0)
print(f"{VARIANT:10s} tick_ms = {best / K * 1e3:.3f}  (ticks/window={int(_k)})",
      flush=True)
