"""One long-lived TPU profiling session: wait for the tunnel claim as
long as it takes (no timeout — killing a claim-waiting client re-wedges
the tunnel), then run every measurement in-process, appending results to
/tmp/p9_results.txt incrementally. Run detached:
    nohup python -u _profile_all.py > /tmp/p9_all.log 2>&1 &
"""
import os
import sys
import time

sys.path.insert(0, "/root/repo")

RES = "/tmp/p9_results.txt"


def note(line):
    with open(RES, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


t0 = time.time()
print("waiting for TPU claim...", flush=True)
import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402
from jax import lax                           # noqa: E402

dev = jax.devices()[0]
note(f"# claimed {dev} after {time.time() - t0:.0f}s")

# ---------------- primitive op facts ----------------
N = 1 << 20
K = 32
key = jax.random.PRNGKey(0)
perm = jax.random.permutation(key, N).astype(jnp.int32)
x = jnp.arange(N, dtype=jnp.int32)


def timeit_loop(name, body, init, reps=3):
    @jax.jit
    def run(c):
        return lax.fori_loop(0, K, lambda i, c: body(c), c)
    out = run(init)
    jax.block_until_ready(out)
    best = 1e9
    for _ in range(reps):
        t1 = time.time()
        out = run(init)
        jax.block_until_ready(out)
        best = min(best, time.time() - t1)
    note(f"op {name:42s} {best / K * 1e3:8.3f} ms/iter")
    return out


timeit_loop("vector add [1M] i32", lambda v: v + 1, x)
timeit_loop("gather x[perm] [1M]", lambda v: v[perm] + 1, x)
timeit_loop("gather 2row [2,1M][:,perm]",
            lambda v: v[:, perm] + 1, jnp.stack([x, x]))
timeit_loop("sort [1M] keys", lambda v: lax.sort(v) + 1, x)
timeit_loop("sort [1M] argsort2op",
            lambda v: lax.sort((v, x), num_keys=1)[0] + 1, x)
timeit_loop("sort [1M] co-sort4op",
            lambda v: lax.sort((v, x, x, x), num_keys=1)[0] + 1, x)
timeit_loop("searchsorted 1M into 1M",
            lambda v: jnp.searchsorted(x, v, side="left").astype(jnp.int32),
            x)
timeit_loop("select chain x8 [1M]",
            lambda v: sum(jnp.where(v % 8 == c, v + c, 0)
                          for c in range(8)), x)
timeit_loop("scatter at[perm].set [1M]",
            lambda v: jnp.zeros((N,), jnp.int32).at[perm].set(v) + 1, x)
timeit_loop("cumsum [1M]", lambda v: jnp.cumsum(v) + 1, x)
timeit_loop("roll [1M]", lambda v: jnp.roll(v, 1) + 1, x)
note("OPS_DONE")

# ---------------- step variants ----------------
from ponyc_tpu import RuntimeOptions          # noqa: E402
from ponyc_tpu.models import ubench           # noqa: E402
from ponyc_tpu.runtime import engine, delivery  # noqa: E402


def run_variant(variant, pings=1, cap=4, pallas=False, patch=None,
                delivery="plan"):
    if patch:
        patch()
    opts = RuntimeOptions(mailbox_cap=cap, batch=pings, max_sends=1,
                          msg_words=1, spill_cap=1024, inject_slots=8,
                          pallas=pallas, delivery=delivery)
    rt, ids = ubench.build(N, opts, pings=pings)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=pings)
    KT = 64
    limit = jnp.int32(KT)
    inj = rt._empty_inject
    multi = engine.jit_multi_step(rt.program, opts)
    state = rt.state
    t1 = time.time()
    state, aux, _k = multi(state, *inj, limit)
    jax.block_until_ready(aux)
    compile_s = time.time() - t1
    best = 1e9
    for _ in range(4):
        t1 = time.time()
        state, aux, _k = multi(state, *inj, limit)
        jax.block_until_ready(aux)
        best = min(best, time.time() - t1)
    tick_ms = best / KT * 1e3
    note(f"{variant} tick_ms = {tick_ms:.3f} (compile {compile_s:.0f}s, "
         f"msgs/s = {N * pings / tick_ms * 1e3:.3e})")


real_deliver = delivery.deliver


def patch_nodeliver():
    def deliver_nd(buf, head, tail, alive, entries, **kw):
        res = real_deliver(buf, head, tail, alive, entries, **kw)
        return res._replace(buf=buf, tail=tail)
    engine.deliver = deliver_nd


def patch_restore():
    engine.deliver = real_deliver


def patch_nodisp():
    real_cd = engine._cohort_dispatch

    def patched_cd(cohort, opts, noyield, program):
        inner = real_cd(cohort, opts, noyield, program)

        def run_cohort(ts, buf_rows, head_rows, occ_rows, runnable_rows,
                       ids, resv, blob=None):
            return inner(ts, buf_rows, head_rows, occ_rows,
                         jnp.zeros_like(runnable_rows), ids, resv,
                         blob=blob)
        return run_cohort
    engine._cohort_dispatch = patched_cd
    return real_cd


run_variant("full")
run_variant("cosort", delivery="cosort")
run_variant("pings4", pings=4)
run_variant("pings4-cosort", pings=4, delivery="cosort")
run_variant("pallas", pallas=True)
patch_nodeliver()
run_variant("nodeliver")
patch_restore()
real_cd = patch_nodisp()
run_variant("nodisp")
engine._cohort_dispatch = real_cd
note("VARIANTS_DONE")

# ---------------- xprof trace of the full step ----------------
try:
    import glob
    opts = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1,
                          msg_words=1, spill_cap=1024, inject_slots=8)
    rt, ids = ubench.build(N, opts)
    ubench.seed_all(rt, ids, hops=1 << 30)
    multi = engine.jit_multi_step(rt.program, opts)
    inj = rt._empty_inject
    limit = jnp.int32(16)
    state, aux, _k = multi(rt.state, *inj, limit)
    jax.block_until_ready(aux)
    logdir = "/tmp/xprof_ubench"
    os.system(f"rm -rf {logdir}")
    jax.profiler.start_trace(logdir)
    state, aux, _k = multi(state, *inj, limit)
    jax.block_until_ready(aux)
    jax.profiler.stop_trace()
    planes = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    note(f"xprof planes: {planes}")
except Exception as e:                        # noqa: BLE001
    note(f"xprof failed: {e}")
note("ALL_DONE")
