#!/bin/bash
# Retry the measurement campaign until it completes OR the deadline
# passes (so an orphaned campaign can never contend with the driver's
# end-of-round bench run for the single TPU claim).
DEADLINE=${CAMPAIGN_DEADLINE:-$(date -d '2026-07-30 15:30 UTC' +%s)}
for i in $(seq 1 300); do
  [ "$(date +%s)" -ge "$DEADLINE" ] && { echo "[$(date +%H:%M:%S)] deadline reached, stopping" >> /tmp/p9_campaign.log; break; }
  grep -q "ALL_DONE" /tmp/p9_results.txt 2>/dev/null && break
  echo "[$(date +%H:%M:%S)] attempt $i" >> /tmp/p9_campaign.log
  python -u /root/repo/profiling/_profile_all.py >> /tmp/p9_all.log 2>&1
  echo "[$(date +%H:%M:%S)] attempt $i exited rc=$?" >> /tmp/p9_campaign.log
  grep -q "ALL_DONE" /tmp/p9_results.txt 2>/dev/null && break
  sleep 120
done
echo "[$(date +%H:%M:%S)] campaign loop ended" >> /tmp/p9_campaign.log
