"""Round-2 profile: where does the ubench tick go? (component timings)

HISTORICAL — written against the round-2 actor-major layout and the old
_cohort_dispatch/buf APIs (superseded twice: planar relayout in round 3,
per-cohort mailbox widths in round 5). Kept as the record of the §3
PROFILE.md measurements; use _profile8.py/_profile9.py for current
component timings."""
import sys
import time

sys.path.insert(0, "/root/repo")
from ponyc_tpu.platforms import force_cpu
if "tpu" not in sys.argv:
    force_cpu()

import jax
import jax.numpy as jnp

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import ubench
from ponyc_tpu.runtime import engine

N = 1 << 20
CAP = 4


def timeit(name, fn, *args, reps=10, jit=True):
    r = jax.jit(fn) if jit else fn
    out = r(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(reps):
        out = r(*args)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / reps * 1e3
    print(f"{name:44s} {dt:8.3f} ms")
    return out


opts = RuntimeOptions(mailbox_cap=CAP, batch=1, max_sends=1, msg_words=1,
                      spill_cap=1024, inject_slots=8)
rt, ids = ubench.build(N, opts)
ubench.seed_all(rt, ids, hops=1 << 30)
st = rt.state
print("platform:", jax.devices()[0].platform)

# full step (donated arg: carry the chain forward)
inj = rt._empty_inject
s2, aux = rt._step(st, *inj)
jax.block_until_ready(aux)
t0 = time.time()
for _ in range(10):
    s2, aux = rt._step(s2, *inj)
jax.block_until_ready(aux)
print(f"{'FULL STEP':44s} {(time.time() - t0) / 10 * 1e3:8.3f} ms")
st = s2
rt.state = s2

# dispatch only
ch = rt.program.device_cohorts[0]
disp = engine._cohort_dispatch(ch, opts, opts.noyield)
idsj = jnp.arange(N, dtype=jnp.int32)


def dispatch_only(state):
    occ = state.tail - state.head
    runnable = state.alive & ~state.muted
    return disp(state.type_state[ch.atype.__name__], state.buf,
                state.head, occ, runnable, idsj, {})


out = timeit("dispatch (gather+scan+switch+outbox)", dispatch_only, st)

# delivery parts
tgt = jnp.asarray(out[1].tgt)
words = jnp.asarray(out[1].words)
E = tgt.shape[0]
print("outbox E =", E)
key = jnp.where(tgt >= 0, tgt, N).astype(jnp.int32)

timeit("argsort(stable) of keys", lambda k: jnp.argsort(k, stable=True), key)
perm = jnp.argsort(key, stable=True)
timeit("payload gather words[perm]",
       lambda w, p: w[p], words, perm)
ks = key[perm]
timeit("searchsorted bounds",
       lambda s: jnp.searchsorted(s, jnp.arange(N + 1, dtype=jnp.int32),
                                  side="left"), ks)
bounds = jnp.searchsorted(ks, jnp.arange(N + 1, dtype=jnp.int32),
                          side="left").astype(jnp.int32)
seg = bounds[:-1]
wds = words[perm]


def ring_rebuild(buf, tail, seg_start, wds2):
    slots = jnp.arange(CAP, dtype=jnp.int32)[None, :]
    rel = (slots - tail[:, None]) % CAP
    acc = jnp.minimum(bounds[1:] - seg_start, 1)
    wmask = rel < acc[:, None]
    src = jnp.minimum(seg_start[:, None] + rel, E - 1)
    return jnp.where(wmask[:, :, None], wds2[src], buf)


timeit("ring rebuild (dense gather+where)", ring_rebuild,
       st.buf, st.tail, seg, wds)

timeit("key equality check (cache validate)",
       lambda a, b: jnp.all(a == b), key, key)
