#!/bin/bash
# Retry profile variants until the axon tunnel recovers. Appends results
# to /tmp/p9_results.txt; skips variants that already have a line there.
RES=/tmp/p9_results.txt
touch "$RES"
for round in $(seq 1 200); do
  all_done=1
  if ! grep -q "^OPS_DONE" "$RES"; then
    all_done=0
    echo "[$(date +%H:%M:%S)] trying ops" >> /tmp/p9_runner.log
    timeout 560 python /root/repo/_profile_ops.py tpu > /tmp/p9_ops.txt 2>&1
    if grep -q "ms/iter" /tmp/p9_ops.txt; then
      grep "ms/iter" /tmp/p9_ops.txt >> "$RES"
      echo "OPS_DONE" >> "$RES"
      echo "[$(date +%H:%M:%S)] ops done" >> /tmp/p9_runner.log
    else
      echo "[$(date +%H:%M:%S)] ops failed/hung" >> /tmp/p9_runner.log
      sleep 30
      continue
    fi
  fi
  for v in full nodeliver nodisp nocounts norebuild nogather pallas pings4; do
    grep -q "^$v " "$RES" && continue
    all_done=0
    echo "[$(date +%H:%M:%S)] trying $v" >> /tmp/p9_runner.log
    out=$(timeout 560 python /root/repo/_profile9.py tpu "$v" 2>&1 |
          grep "tick_ms")
    if [ -n "$out" ]; then
      echo "$out" >> "$RES"
      echo "[$(date +%H:%M:%S)] got: $out" >> /tmp/p9_runner.log
    else
      echo "[$(date +%H:%M:%S)] $v failed/hung" >> /tmp/p9_runner.log
      sleep 30
      break   # tunnel likely down; restart the variant loop
    fi
  done
  [ "$all_done" = 1 ] && break
done
echo "DONE" >> "$RES"
