#!/bin/bash
# Campaign supervisor: keep exactly ONE _profile_all.py alive until the
# results file says ALL_DONE or the deadline passes. Never kills a
# claim-waiting client (that re-wedges the tunnel) — only relaunches
# after the previous attempt exits on its own.
DEADLINE=${CAMPAIGN_DEADLINE:?set CAMPAIGN_DEADLINE (epoch s)}
LOG=/tmp/p9_campaign.log
while true; do
  now=$(date +%s)
  [ "$now" -ge "$DEADLINE" ] && { echo "[$(date -u +%H:%M:%S)] deadline, supervisor exit" >> "$LOG"; break; }
  if grep -q "ALL_DONE" /tmp/p9_results.txt 2>/dev/null; then
    # Chain the second campaign (pallas_fused × pings × gating × cap)
    # once the primary A/B has fully landed; then exit.
    if grep -q "FUSED_DONE" /tmp/p9_results.txt 2>/dev/null; then
      echo "[$(date -u +%H:%M:%S)] ALL_DONE+FUSED_DONE, supervisor exit" >> "$LOG"
      break
    fi
    # (launch is synchronous — one attempt at a time, like the primary)
    echo "[$(date -u +%H:%M:%S)] launching _profile_fused.py" >> "$LOG"
    python -u /root/repo/profiling/_profile_fused.py >> /tmp/p9_fused.log 2>&1
    echo "[$(date -u +%H:%M:%S)] fused attempt exited rc=$?" >> "$LOG"
    sleep 60
    continue
  fi
  if ! pgrep -f "_profile_all.py" > /dev/null; then
    echo "[$(date -u +%H:%M:%S)] launching _profile_all.py" >> "$LOG"
    python -u /root/repo/profiling/_profile_all.py >> /tmp/p9_all.log 2>&1
    echo "[$(date -u +%H:%M:%S)] attempt exited rc=$?" >> "$LOG"
    sleep 60
  else
    sleep 60
  fi
done
