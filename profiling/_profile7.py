"""Round-3 planar-layout profile on TPU: where does the 46 ms tick go?"""
import sys
import time

sys.path.insert(0, "/root/repo")
from ponyc_tpu.platforms import force_cpu
if "tpu" not in sys.argv:
    force_cpu()

import jax
import jax.numpy as jnp
from jax import lax

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import ubench
from ponyc_tpu.runtime import engine, delivery
from ponyc_tpu.ops.segment import stable_sort_by

N = 1 << 20
CAP = 4


def timeit(name, fn, *args, reps=10, jit=True):
    r = jax.jit(fn) if jit else fn
    out = r(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(reps):
        out = r(*args)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / reps * 1e3
    print(f"{name:48s} {dt:8.3f} ms")
    return out


opts = RuntimeOptions(mailbox_cap=CAP, batch=1, max_sends=1, msg_words=1,
                      spill_cap=1024, inject_slots=8)
rt, ids = ubench.build(N, opts)
ubench.seed_all(rt, ids, hops=1 << 30)
st = rt.state
print("platform:", jax.devices()[0].platform)

inj = rt._empty_inject
s2, aux = rt._step(st, *inj)
jax.block_until_ready(aux)
t0 = time.time()
for _ in range(10):
    s2, aux = rt._step(s2, *inj)
jax.block_until_ready(aux)
print(f"{'FULL STEP (unfused)':48s} {(time.time() - t0) / 10 * 1e3:8.3f} ms")
st = s2
rt.state = s2

# --- dispatch only (planar)
ch = rt.program.device_cohorts[0]
# Per-cohort mailbox widths (delivery rebuilds each table at its own
# width; ubench has the one Pinger cohort).
LAYOUT = tuple((c.atype.__name__, c.local_start, c.local_stop,
                1 + c.msg_words) for c in rt.program.cohorts)
disp = engine._cohort_dispatch(ch, opts, opts.noyield, rt.program)
idsj = jnp.arange(N, dtype=jnp.int32)


def dispatch_only(state):
    occ = state.tail - state.head
    runnable = state.alive & ~state.muted
    return disp(state.type_state[ch.atype.__name__],
                state.buf[ch.atype.__name__],
                state.head, occ, runnable, idsj, {})


out = timeit("dispatch (ring_take+scan+planar branches)", dispatch_only, st)

# --- outbox from dispatch: Entries planar [w1, E]
ent = out[1]
tgt, words = jnp.asarray(ent.tgt), jnp.asarray(ent.words)
E = tgt.shape[0]
print("outbox E =", E, "words shape:", words.shape)


# --- delivery only, with plan cache hit and miss
def deliver_cached(state, tgt, sender, words):
    e = delivery.Entries(tgt=tgt, sender=sender, words=words)
    return delivery.deliver(
        state.buf, state.head, state.tail, state.alive, e,
        n_local=N, mailbox_cap=CAP, spill_cap=1024,
        overload_occ=opts.overload_occ, shard_base=jnp.int32(0),
        cohort_layout=LAYOUT, mute_slots=opts.mute_slots,
        plan=(state.plan_key, state.plan_perm, state.plan_bounds))


def deliver_nocache(state, tgt, sender, words):
    e = delivery.Entries(tgt=tgt, sender=sender, words=words)
    return delivery.deliver(
        state.buf, state.head, state.tail, state.alive, e,
        n_local=N, mailbox_cap=CAP, spill_cap=1024,
        overload_occ=opts.overload_occ, shard_base=jnp.int32(0),
        cohort_layout=LAYOUT, mute_slots=opts.mute_slots, plan=None)


sender = jnp.asarray(ent.sender)
# Compose the full delivery list like the engine: dspill, inject, rspill,
# then the dispatch outbox (matches state.plan_key length).
inj_t = jnp.full((opts.inject_slots,), -1, jnp.int32)
inj_w = jnp.zeros((words.shape[0], opts.inject_slots), jnp.int32)
tgt_f = jnp.concatenate([st.dspill_tgt, inj_t, st.rspill_tgt, tgt])
snd_f = jnp.concatenate([st.dspill_sender, inj_t, st.rspill_sender, sender])
wrd_f = jnp.concatenate([st.dspill_words, inj_w, st.rspill_words, words],
                        axis=1)
timeit("delivery (plan cached)", deliver_cached, st, tgt_f, snd_f, wrd_f)
timeit("delivery (no plan cache)", deliver_nocache, st, tgt_f, snd_f, wrd_f)

# --- sub-pieces
key = jnp.where(tgt >= 0, tgt, N).astype(jnp.int32)
timeit("stable_sort_by(key) [E]", stable_sort_by, key)
perm = stable_sort_by(key)
timeit("planar payload gather words[:, perm]",
       lambda w, p: w[:, p], words, perm)
ks = key[perm]
bounds = jnp.searchsorted(ks, jnp.arange(N + 1, dtype=jnp.int32),
                          side="left").astype(jnp.int32)
seg = bounds[:-1]
wds = words[:, perm]


def plane_rebuild(buf, head, tail):
    occ = tail - head
    space = jnp.maximum(CAP - occ, 0)
    cnt = bounds[1:] - seg
    acc = jnp.minimum(cnt, space)
    planes = []
    for ci in range(CAP):
        rel = (ci - tail) % CAP
        wmask = rel < acc
        src = jnp.minimum(seg + rel, E - 1)
        planes.append(jnp.where(wmask[None, :],
                                jnp.take(wds, src, axis=1),
                                buf[ci]))
    return jnp.stack(planes)


timeit("plane rebuild (CAP planes)", plane_rebuild,
       st.buf[ch.atype.__name__], st.head, st.tail)

# --- ring take chain (dispatch input read)
def ring_take_all(buf, head):
    return engine._ring_take(buf, head % CAP)


timeit("_ring_take (select chain over cap)", ring_take_all,
       st.buf[ch.atype.__name__], st.head)

# --- key equality (cache validate)
timeit("plan key compare", lambda a, b: jnp.all(a == b), key, key)

# --- spawn-free pure carriers
timeit("tail-head etc (occ, runnable)",
       lambda s: (s.tail - s.head, s.alive & ~s.muted), st)

# --- XLA cost analysis of the full step
c = jax.jit(rt._step_fn, donate_argnums=()).lower(st, *inj).compile()
ca = c.cost_analysis()
if ca:
    d = ca if isinstance(ca, dict) else ca[0]
    print("cost analysis: flops=%.3g bytes=%.3g" % (
        d.get("flops", -1), d.get("bytes accessed", -1)))
