"""Blob-pool overhead A/B on the ubench tick (CPU-relative evidence for
the structural claim: a program that never touches the pool pays nothing
— the threading is gated per cohort (engine.use_blob), and a merely
ENABLED pool only adds the per-tick free-slot compaction when some
cohort allocates. Run:
    env -u PYTHONPATH JAX_PLATFORMS=cpu python profiling/_blob_overhead.py
"""
import sys
import time

sys.path.insert(0, "/root/repo")

import jax                                     # noqa: E402
import jax.numpy as jnp                        # noqa: E402

from ponyc_tpu import RuntimeOptions           # noqa: E402
from ponyc_tpu.models import ubench            # noqa: E402
from ponyc_tpu.runtime import engine           # noqa: E402

N = 4096
KT = 64


def tick_ms(**optkw):
    opts = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1, msg_words=1,
                          spill_cap=1024, inject_slots=8, **optkw)
    rt, ids = ubench.build(N, opts)
    ubench.seed_all(rt, ids, hops=1 << 30)
    multi = engine.jit_multi_step(rt.program, opts)
    inj = rt._empty_inject
    limit = jnp.int32(KT)
    state, aux, _k = multi(rt.state, *inj, limit)
    jax.block_until_ready(aux)
    best = 1e9
    for _ in range(5):
        t1 = time.time()
        state, aux, _k = multi(state, *inj, limit)
        jax.block_until_ready(aux)
        best = min(best, time.time() - t1)
    return best / KT * 1e3


base = tick_ms()
pool = tick_ms(blob_slots=4096, blob_words=16)
print(f"ubench tick_ms: pool-disabled {base:.3f}  "
      f"pool-enabled-unused {pool:.3f}  "
      f"(delta {100 * (pool - base) / base:+.1f}%)")
