"""Host-bridge ceiling: loopback pump through the net layer.

≙ the reference's ASIO thread at wire speed (asio/epoll.c:207-230) —
this measures the equivalent ceiling of THIS runtime's host plane:
C loopback TCP connections ping-ponging M messages each through
host-cohort actors (socket → bridge → host dispatch → socket). The
result is the msgs/s bound a chatty-net program hits BEFORE the device
ever matters (the host plane is single-threaded Python by design —
VERDICT r4 weak #6); recorded in benchmarks.md.

Usage: python profiling/_bridge_pump.py [clients] [msgs_per_client]
"""

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour


@actor
class PumpServer:
    HOST = True
    n_msgs: I32
    n_closed: I32
    n_conns: I32

    @behaviour
    def on_accept(self, st, conn: I32):
        return st

    @behaviour
    def on_data(self, st, conn: I32, data: I32, n: I32):
        payload = self.rt.heap.unbox(data)
        self.rt.net.send(conn, payload)          # echo
        return {**st, "n_msgs": st["n_msgs"] + 1}

    @behaviour
    def on_closed(self, st, conn: I32):
        # All clients hung up -> measurement over (the listener holds
        # the runtime alive otherwise and run() would spin out its
        # step budget -- the round-5 mis-measurement).
        done = st["n_closed"] + 1
        self.exit(0, when=done >= st["n_conns"])
        return {**st, "n_closed": done}


def make_client(m_msgs: int):
    @actor
    class PumpClient:
        HOST = True
        conn: I32
        sent: I32

        @behaviour
        def on_connect(self, st, conn: I32, err: I32):
            assert err == 0, err
            self.rt.net.send(conn, b"x" * 64)
            return {**st, "conn": conn, "sent": 1}

        @behaviour
        def on_data(self, st, conn: I32, data: I32, n: I32):
            self.rt.heap.unbox(data)
            if st["sent"] >= m_msgs:
                self.rt.net.close(conn)
                return st
            self.rt.net.send(conn, b"x" * 64)
            return {**st, "sent": st["sent"] + 1}

        @behaviour
        def on_closed(self, st, conn: I32):
            return st

    return PumpClient


def main(clients: int, m_msgs: int):
    cli_t = make_client(m_msgs)
    rt = Runtime(RuntimeOptions(mailbox_cap=32, batch=8, max_sends=2,
                                msg_words=4, inject_slots=256))
    rt.declare(PumpServer, 1).declare(cli_t, clients).start()
    net = rt.attach_net()
    srv = rt.spawn(PumpServer, n_conns=clients)
    lid = net.listen_tcp("127.0.0.1", 0, srv,
                         on_accept=PumpServer.on_accept,
                         on_data=PumpServer.on_data,
                         on_closed=PumpServer.on_closed)
    port = net.listen_port(lid)
    t0 = time.perf_counter()
    for _ in range(clients):
        c = rt.spawn(cli_t)
        net.connect_tcp("127.0.0.1", port, c,
                        on_connect=cli_t.on_connect,
                        on_data=cli_t.on_data,
                        on_closed=cli_t.on_closed)
    rt.run(max_steps=clients * m_msgs * 40 + 4000)
    dt = time.perf_counter() - t0
    served = int(rt.state_of(srv)["n_msgs"])
    # One "message" = one socket payload crossing the bridge into a
    # host-actor dispatch; count both directions.
    total = served * 2
    print(f"clients={clients} msgs/conn={m_msgs} served={served} "
          f"elapsed={dt:.2f}s bridge_msgs_per_sec={total / dt:,.0f}",
          flush=True)
    net.close_all()
    rt.stop()


if __name__ == "__main__":
    c = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    m = int(sys.argv[2]) if len(sys.argv) > 2 else 500
    main(c, m)
