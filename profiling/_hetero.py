"""The dispatch-heterogeneity cliff: tick cost vs behaviours-per-type.

Planar dispatch evaluates every behaviour of a cohort per batch slot
(engine.py scan_body) where the reference's generated switch costs one
indirect jump (genfun.c) — this measures the resulting curve and A/Bs
the branch-gating countermeasure (RuntimeOptions.dispatch_gating: skip
a behaviour's planar evaluation under a scalar cond when no lane's
current message selects it).

Usage: python profiling/_hetero.py [actors] [--platform cpu|tpu]
Writes one line per (B, traffic, gating) config; CPU numbers give the
curve SHAPE (the go/no-go signal); on-chip numbers decide promotion.
"""

import os
import sys
import time

if "--platform" in sys.argv:
    plat = sys.argv[sys.argv.index("--platform") + 1]
else:
    plat = "cpu"
if plat == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                            # noqa: E402
import jax.numpy as jnp                               # noqa: E402

from ponyc_tpu import RuntimeOptions                  # noqa: E402
from ponyc_tpu.models import mixed                    # noqa: E402


def measure(actors: int, n_beh: int, hot, gating: bool,
            ticks: int = 64, fuse: int = 16, work: int = 0):
    opts = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1,
                          msg_words=1, spill_cap=256, inject_slots=8,
                          dispatch_gating=gating)
    rt, ids, wt = mixed.build(actors, n_beh, opts, hot=hot, work=work)
    mixed.seed_all(rt, ids, wt, hops=1 << 30)
    K = fuse
    limit = jnp.int32(K)
    inj = rt._empty_inject
    state = rt.state
    state, aux, _ = rt._multi(state, *inj, limit)      # jit + warm
    jax.block_until_ready(aux)
    windows = max(1, ticks // K)
    t0 = time.perf_counter()
    for _ in range(windows):
        state, aux, _ = rt._multi(state, *inj, limit)
    jax.block_until_ready(aux)
    dt = (time.perf_counter() - t0) / (windows * K)
    rt.state = state
    processed = int(rt.counter("n_processed"))
    return 1e3 * dt, processed


if __name__ == "__main__":
    actors = int(sys.argv[1]) if len(sys.argv) > 1 and \
        not sys.argv[1].startswith("-") else 1 << 13
    print(f"platform={jax.default_backend()} actors={actors}", flush=True)
    for work in (0, 64):
        for gating in (False, True):
            for n_beh in (1, 2, 4, 8, 16):
                for hot in (None, 1):
                    label = "one-hot" if hot == 1 else "all-hot"
                    ms, proc = measure(actors, n_beh, hot, gating,
                                       work=work)
                    print(f"work={work:3d} B={n_beh:2d} {label:7s} "
                          f"gating={int(gating)} tick_ms={ms:8.3f} "
                          f"processed={proc}", flush=True)
