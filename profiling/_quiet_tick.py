"""Measure the quiet-mesh tick: an 8-shard world with NO traffic vs the
same world on 1 shard. The idle-collective tax (VERDICT r4 weak #3) is
the gap between them; the world-bits gating (engine.py) is the fix.

Runs on the CPU backend with a virtual 8-device mesh (same harness as
tests/conftest.py). In-executable timing: a fused window of K ticks per
dispatch, wall / K.
"""

import os
import sys
import time

# FORCE cpu (the ambient env pins JAX_PLATFORMS=axon — the TPU tunnel;
# a CPU-mesh measurement must never queue on the tunnel claim).
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                            # noqa: E402
import jax.numpy as jnp                               # noqa: E402

from ponyc_tpu import RuntimeOptions                  # noqa: E402
from ponyc_tpu.models import ubench                   # noqa: E402


def measure(shards: int, actors: int, busy: bool, ticks: int = 64):
    opts = RuntimeOptions(mailbox_cap=4, batch=4, max_sends=1,
                          msg_words=1, spill_cap=256, inject_slots=8,
                          mesh_shards=shards)
    rt, ids = ubench.build(actors, opts, pings=4)
    if busy:
        ubench.seed_all(rt, ids, hops=1 << 30, pings=4)
        rt.run(max_steps=2)
    K = 64
    limit = jnp.int32(K)
    inj = rt._empty_inject
    state = rt.state
    # A quiet world quiesces instantly; force full windows by measuring
    # the step fn directly tick by tick inside the fused window via
    # occupancy: for the quiet case the while cond exits after 1 tick,
    # so time single steps in a loop instead.
    if busy:
        state, aux, _ = rt._multi(state, *inj, limit)
        jax.block_until_ready(aux)
        t0 = time.perf_counter()
        for _ in range(max(1, ticks // K)):
            state, aux, _ = rt._multi(state, *inj, limit)
        jax.block_until_ready(aux)
        dt = (time.perf_counter() - t0) / (max(1, ticks // K) * K)
    else:
        state, aux = rt._step(state, *inj)
        jax.block_until_ready(aux)
        t0 = time.perf_counter()
        for _ in range(ticks):
            state, aux = rt._step(state, *inj)
        jax.block_until_ready(aux)
        dt = (time.perf_counter() - t0) / ticks
    rt.state = state
    return 1e3 * dt


if __name__ == "__main__":
    actors = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 14
    for shards in (1, 8):
        q = measure(shards, actors, busy=False)
        print(f"shards={shards} actors={actors} quiet_tick_ms={q:.3f}",
              flush=True)
