"""Narrow down the 9ms dispatch: which part of run_cohort is slow?"""
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import ubench
from ponyc_tpu.runtime import engine

N = 1 << 20
CAP = 8


def timeit(name, fn, *args, reps=20):
    r = jax.jit(fn)
    out = r(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(reps):
        out = r(*args)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / reps * 1e3
    print(f"{name:44s} {dt:8.3f} ms")
    return dt


opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                      spill_cap=1024, inject_slots=8)
rt, ids = ubench.build(N, opts)
ubench.seed_all(rt, ids, hops=1 << 30)
st = rt.state
ch = rt.program.device_cohorts[0]
print("platform:", jax.devices()[0].platform)

disp = engine._cohort_dispatch(ch, opts, opts.noyield)
idsj = jnp.arange(N, dtype=jnp.int32)

# Precompute msgs/valids outside
def gather_msgs(state):
    k = jnp.arange(1, dtype=jnp.int32)
    idx = (state.head[:, None] + k[None, :]) % CAP
    msgs = jnp.take_along_axis(state.buf, idx[:, :, None], axis=1)
    occ = state.tail - state.head
    n_run = jnp.minimum(occ, 1)
    valids = k[None, :] < n_run[:, None]
    return msgs, valids

msgs, valids = jax.jit(gather_msgs)(st)
jax.block_until_ready(msgs)
timeit("gather msgs+valids", gather_msgs, st)

# build vfn manually (mirror _cohort_dispatch internals)
from ponyc_tpu.ops import pack
field_dtypes = {f: jnp.int32 for f in ch.atype.field_specs}
branches = [engine._make_branch(b, 1, 1, field_dtypes)
            for b in ch.behaviours]
branches.append(engine._make_noop_branch(1, 1))
nb = len(ch.behaviours)
base = ch.behaviours[0].global_id


def actor_fn(st_row, msg, valid, actor_id):
    local = msg[0, 0] - base
    in_range = (local >= 0) & (local < nb)
    do = valid[0] & in_range
    bid = jnp.where(do, local, nb)
    st2, (stgt, swords), (ef, ec), yf = jax.lax.switch(
        bid, branches, (st_row, msg[0, 1:], actor_id))
    return st2, stgt, swords, ef, ec, do


vfn = jax.vmap(actor_fn)


def switch_only(ts, msgs, valids):
    return vfn(ts, msgs, valids, idsj)

ts = st.type_state[ch.atype.__name__]
timeit("vmapped switch (no scan)", switch_only, ts, msgs, valids)


def branch_direct(ts, msgs, valids):
    # no switch at all: call the behaviour branch directly, vmapped
    def one(st_row, msg, valid, actor_id):
        return branches[0]((st_row, msg[0, 1:], actor_id))
    return jax.vmap(one)(ts, msgs, valids, idsj)

timeit("vmapped behaviour direct (no switch)", branch_direct, ts, msgs, valids)


def full_cohort(state):
    occ = state.tail - state.head
    return disp(state.type_state[ch.atype.__name__], state.buf,
                state.head, occ, state.alive, idsj)

timeit("full run_cohort (scan+switch)", full_cohort, st)

# scan with batch=1 vs no scan: isolate scan overhead
def with_scan(ts, msgs, valids):
    def body(carry, x):
        st_row = carry
        msg, valid = x
        st2, stgt, swords, ef, ec, do = actor_fn(st_row, msg[None], valid[None], jnp.int32(0))
        return st2, (stgt, swords)
    def per_actor(st_row, msgs_row, valids_row):
        return jax.lax.scan(body, st_row, (msgs_row, valids_row))
    return jax.vmap(per_actor)(ts, msgs, valids)

timeit("vmapped scan(batch=1) of switch", with_scan, ts, msgs, valids)
