"""Component-level timing of the ubench tick on the real TPU."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

N = 1 << 20
CAP = 8
W1 = 2     # 1+msg_words
E = N      # out entries for batch=1 max_sends=1


def timeit(name, fn, *args, reps=20):
    r = jax.jit(fn)
    out = r(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(reps):
        out = r(*args)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / reps * 1e3
    print(f"{name:40s} {dt:8.3f} ms")
    return dt


def main():
    key = jax.random.PRNGKey(0)
    tgt = jax.random.permutation(key, jnp.arange(N, dtype=jnp.int32))
    words = jnp.zeros((E, W1), jnp.int32)
    buf = jnp.zeros((N, CAP, W1), jnp.int32)
    head = jnp.zeros((N,), jnp.int32)
    tail = jnp.ones((N,), jnp.int32)
    vals = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, N, jnp.int32)

    print("platform:", jax.devices()[0].platform)
    timeit("argsort 1M i32 (stable)",
           lambda k: jnp.argsort(k, stable=True), vals)
    timeit("sort 1M i32", lambda k: jnp.sort(k), vals)
    timeit("sort_key_val 1M (k,v)",
           lambda k: jax.lax.sort_key_val(k, jnp.arange(N, dtype=jnp.int32)), vals)
    timeit("gather 1M rows from [1M,2]",
           lambda w, p: w[p], words, tgt)
    timeit("scatter set [1M] rows into [1M,8,2]",
           lambda b, t, w: b.at[t, jnp.zeros((E,), jnp.int32)].set(
               w, mode="drop"), buf, tgt, words)
    timeit("scatter-add counts 1M into 1M",
           lambda t: jnp.zeros((N,), jnp.int32).at[t].add(1, mode="drop"),
           tgt)
    timeit("assoc-scan max 1M", lambda v: jax.lax.associative_scan(
        jnp.maximum, v), vals)
    timeit("cumsum 1M", lambda v: jnp.cumsum(v), vals)
    timeit("take_along_axis [1M,8,2] b=1",
           lambda b, h: jnp.take_along_axis(
               b, (h[:, None] % CAP)[:, :, None], axis=1), buf, head)

    # dispatch-only: the vmapped scan/switch part of the engine
    from ponyc_tpu import RuntimeOptions
    from ponyc_tpu.models import ubench
    from ponyc_tpu.runtime import engine
    from ponyc_tpu.runtime.delivery import deliver, Entries

    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                          spill_cap=1024, inject_slots=8)
    rt, ids = ubench.build(N, opts)
    ubench.seed_all(rt, ids, hops=1 << 30)
    st = rt.state

    ch = rt.program.device_cohorts[0]
    disp = engine._cohort_dispatch(ch, opts, opts.noyield)
    idsj = jnp.arange(N, dtype=jnp.int32)

    def dispatch_only(state):
        occ = state.tail - state.head
        return disp(state.type_state[ch.atype.__name__], state.buf,
                    state.head, occ, state.alive, idsj, {})

    timeit("dispatch only (drain+switch+outbox)", dispatch_only, st)

    entries = Entries(tgt=tgt, sender=idsj, words=words)

    def deliver_only(state):
        return deliver(state.buf, state.head, state.tail, state.alive,
                       entries, n_local=N, mailbox_cap=CAP,
                       spill_cap=1024, overload_occ=6, shard_base=0)

    timeit("deliver only (sort+rank+scatter)", deliver_only, st)

    step = engine.jit_step(rt.program, rt.opts, None)
    inj = rt._empty_inject
    s2, aux = step(st, *inj)
    jax.block_until_ready(aux)
    t0 = time.time()
    s = s2
    for _ in range(20):
        s, aux = step(s, *inj)
    jax.block_until_ready(aux)
    print(f"{'full step':40s} {(time.time()-t0)/20*1e3:8.3f} ms")


main()
