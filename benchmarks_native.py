#!/usr/bin/env python
"""Native host-runtime microbenchmarks (≙ `make benchmark` over the
reference's benchmark/libponyrt suite). Prints one row per metric."""
import json
import sys

sys.path.insert(0, ".")

from ponyc_tpu import native  # noqa: E402

res = native.microbench(scale=float(sys.argv[1]) if len(sys.argv) > 1
                        else 1.0)
for k, v in res.items():
    print(f"{k:28s} {v:10.1f} ns/op")
print(json.dumps({k: round(v, 1) for k, v in res.items()}))
