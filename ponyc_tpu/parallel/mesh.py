"""Device mesh construction + state sharding for the actor world.

The reference scales by adding scheduler threads over cores
(src/libponyrt/sched/scheduler.c:1273-1309, one scheduler_t per core);
this framework scales by sharding the actor-row axis of every runtime
array over a 1-D `jax.sharding.Mesh` axis named 'actors'. Messages whose
target lives on another shard ride one `lax.all_to_all` per tick
(engine._route) — ICI between chips of a slice, DCN between hosts, with
XLA choosing the transport (the reference's lock-free queues have no
cross-process analog; this is the distributed communication backend built
in its place).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(n_shards: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D mesh over the actor axis. n_shards defaults to all devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_shards or len(devices)
    if len(devices) < n:
        raise RuntimeError(
            f"need {n} devices for {n} actor shards, have {len(devices)}")
    return Mesh(np.array(devices[:n]), ("actors",))


def shard_state(state, mesh: Mesh):
    """Place every runtime array with its LAST axis over 'actors' (the
    actor-lane axis — see runtime/state.py's layout note)."""
    def put(x):
        spec = PartitionSpec(*([None] * (x.ndim - 1) + ["actors"]))
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree.map(put, state)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, PartitionSpec())
