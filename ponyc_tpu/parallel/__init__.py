"""Mesh/sharding layer: the scale-out axis the single-process reference
never had (SURVEY.md §2.4) — actor rows shard over an 'actors' mesh axis,
messages route via all_to_all collectives over ICI/DCN."""

from . import distributed  # noqa: F401
from .mesh import make_mesh, shard_state  # noqa: F401
