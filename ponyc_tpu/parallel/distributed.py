"""Multi-host execution — the DCN tier of the communication backend.

The reference is strictly single-process (SURVEY.md §2.4: no NCCL/MPI
anywhere); its only cross-machine story is application-level TCP
(lang/socket.c). This framework's scale-out axis extends across hosts
the JAX-native way: every host in the job calls `initialize()`, the
actor mesh is built over *global* devices, and the engine's
`all_to_all`/`psum` collectives ride ICI within a slice and DCN between
slices — XLA picks the transport per edge, no hand-written NCCL/MPI
(the "pick a mesh, annotate, let XLA insert collectives" recipe).

Typical multi-host launch (one command per host):

    import ponyc_tpu.parallel.distributed as dist
    dist.initialize(coordinator="host0:9876", num_processes=4,
                    process_id=<rank>)
    opts = RuntimeOptions(mesh_shards=dist.device_count())
    ...                       # identical program on every host

Host-resident subsystems (bridge/net/process) stay per-host: OS events
enter through *this host's* inject lane and reach any shard through
routing — the same pattern the reference uses to funnel ASIO events
through one thread (asio.c), generalised across hosts.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or start) a multi-host JAX job. No-ops on single-host.

    Arguments may come from the environment instead
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID),
    matching how cluster launchers inject rank info.
    """
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None:
        return                      # single-host: nothing to do
    num_processes = int(num_processes
                        or os.environ.get("JAX_NUM_PROCESSES", 1))
    process_id = int(process_id
                     if process_id is not None
                     else os.environ.get("JAX_PROCESS_ID", 0))
    # The CPU backend refuses cross-process computations ("Multiprocess
    # computations aren't implemented on the CPU backend") unless a
    # collectives implementation is selected; gloo ships with jaxlib.
    # Must land BEFORE the backend initialises — harmless for
    # accelerator backends, which ignore the knob.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass          # the knob moved (older/newer jax): leave default
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def device_count() -> int:
    """Global device count across every host in the job."""
    return jax.device_count()


def process_index() -> int:
    return jax.process_index()


def is_leader() -> bool:
    """True on exactly one host — put driver-only side effects (bench
    prints, checkpoint writes) behind this, as each host runs the same
    program."""
    return jax.process_index() == 0
