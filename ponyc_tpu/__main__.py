"""Unified CLI driver — ``python -m ponyc_tpu <command>``.

≙ the reference's ``ponyc`` driver (src/ponyc/main.c:111: option
processing via the shared runtime parser, then compile/run), adapted to
a trace-time framework: there is no ahead-of-time binary, so "compile
and run a package" becomes "strip the --pony* runtime flags, set the
backend, and execute the program script" — with the same one-entry-point
ergonomics the reference gets from its binary.

Commands:
  run <script.py> [args...]   strip --pony* flags into the environment
                              (config.strip_runtime_flags), pick a
                              backend (platforms.auto_backend), exec the
                              script with the remaining argv.
  bench [args...]             the headline benchmark (bench.py).
  test [pytest args...]       the test suite (≙ ponytest aggregate).
  doc <module[:ATTR]> [-o D]  generate docs for actor types reachable
                              from a module (≙ docgen pass, docgen.c).
  verify <module> [--json]    probe-trace every behaviour's effect
                              signature; fail on budget violations
                              (≙ the verify stage, verify/fun.c).
                              Exit: 0 ok, 1 violations, 2 usage,
                              3 no actor types in the module.
  lint <target...> [--json]   whole-program static analysis. A target
      [--format github]       is a MODULE NAME (message-flow graph
      [--roots A.go,B.tick]   rules R1–R5 over probe traces PLUS the
                              pure-AST behaviour-body rules R6–R9) or
                              a FILE/DIRECTORY (`lint examples/`
                              sweeps the tree with the body rules
                              only — no import, no JAX; files that
                              don't even import still lint).
                              --json emits one finding object per
                              line ({rule, severity, type, behaviour,
                              message, file, line}); --format github
                              emits ::warning/::error workflow
                              annotations. Exit codes as for verify
                              (1 = findings at error or warning
                              severity).
  trace <csv> [-o F]          analysis CSVs → Chrome-trace/Perfetto
        [--spans F.jsonl]     JSON (counter tracks + causal-trace span
  trace --tree <spans.jsonl>  slices with sender→receiver flow arrows);
                              --tree prints reassembled causal trees
                              with per-trace critical-path latency.
  top [<analytics.csv>]       live terminal view of a running runtime's
      [--interval S] [--once]  window stream (the level-2 CSV at
                              RuntimeOptions.analysis_path): window
                              throughput, queue pressure, GC stats,
                              the per-behaviour run table and
                              per-cohort queue-wait percentiles,
                              refreshed every --interval seconds
                              (--once renders a single frame).
  doctor --postmortem FILE    render a flight-recorder postmortem
  doctor <host:port|url>      (crash/SIGQUIT/watchdog dump, or the
                              probe evidence in a BENCH json) or a
                              live /metrics+/healthz endpoint
                              (RuntimeOptions.metrics_port) into a
                              one-line verdict + diagnosis. Exit:
                              0 ok/snapshot, 1 stalled/crashed/
                              degraded, 2 usage or unreadable.
  perf [--check]              standing perf-regression scoreboard
       [--tolerance F]        (ISSUE 19): the headline trajectory from
       [--root DIR] [--json]  BENCH_HISTORY.jsonl (appended by every
       [--history FILE]       bench.py run) + committed BENCH_r*.json
                              rounds, vs per-group best-so-far and the
                              10x north star. --check exits 1 when a
                              comparable group's newest run sits more
                              than --tolerance (0.2) below its best,
                              or measured costs diverged from the
                              model — runnable as a CI gate. Exit:
                              0 ok, 1 regression, 2 no history/usage.
  supervise [--retries N]     run a workload script under restart-from-
            [--backoff S]     checkpoint supervision (supervise.py): on
            --prefix P        any nonzero/killed exit the child is
            <script.py> [...]  restarted with PONY_TPU_RESTORE pointing
                              at the newest intact ring checkpoint
                              under --prefix (falling back past corrupt
                              ones), with exponential backoff and the
                              deterministic-poison refusal. The script
                              opts in via supervise.maybe_restore(rt).
                              Exit: the workload's final code (0 on
                              recovery), 3 on poison, 2 usage.
  snapshot <file|prefix>      inspect a world snapshot / checkpoint
           [--json]           ring: header summary (format, program
                              fingerprint, geometry, counters, age) +
                              checksum verdict. Exit: 0 intact,
                              1 corrupt/unreadable, 2 usage.
  restore <file|prefix>       deep-verify restorability (every array
                              checksummed, format gate) and print the
                              verdict; a prefix resolves to the newest
                              intact ring snapshot. In-program restore
                              is serialise.restore(rt, path). Exit
                              codes as for snapshot.
  serve [--host H] [--port P] run the serving front door (serve.py):
        [--workers N]         batched TCP(/TLS) ingress over the
        [--tls-cert C]        default ServeWorker compute service,
        [--tls-key K]         telemetry-driven admission control,
        [--pending-limit B]   graceful SIGTERM drain. Length-prefixed
        [--drain-grace S]     i32-word frames (README "Serving
                              traffic"); --pony* runtime flags
                              accepted. Pair with the load generator:
                              python -m ponyc_tpu.loadgen HOST PORT.
                              Exit: 0 drained, the error code on a
                              coded failure (supervise restarts it).
  version                     print version + backend info.

Runtime flags accepted anywhere in `run` argv, exactly like the
reference stripping --pony* before the app sees argv (start.c:185-261):
  python -m ponyc_tpu run app.py --ponymailboxcap=128 --input data.txt
"""

from __future__ import annotations

import json
import os
import runpy
import subprocess
import sys
import time


def _usage(code: int = 2) -> int:
    print(__doc__, file=sys.stderr)
    return code


def cmd_run(argv) -> int:
    # --safe pkg1:pkg2 / --safe=pkg1:pkg2 (≙ ponyc --safe,
    # package.c:685-692): restrict FFI-reaching packages for the
    # program being run.
    cleaned = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--safe":
            if i + 1 >= len(argv):
                print("ponyc_tpu run: --safe needs a value "
                      "(e.g. --safe files:net)", file=sys.stderr)
                return 2
            os.environ["PONY_TPU_SAFE"] = argv[i + 1]
            i += 2
            continue
        if a.startswith("--safe="):
            os.environ["PONY_TPU_SAFE"] = a[len("--safe="):]
            i += 1
            continue
        cleaned.append(a)
        i += 1
    from .config import strip_runtime_flags
    opts, rest = strip_runtime_flags(cleaned)
    if not rest:
        print("ponyc_tpu run: missing script path", file=sys.stderr)
        return 2
    # Hand the parsed runtime options to the script via the env channel
    # every Runtime() constructor honours (config.options_from_env), so
    # `run app.py --ponybatch 4` configures app.py's runtime without the
    # script doing anything (≙ pony_init eating --pony* from argv).
    import dataclasses
    defaults = type(opts)()
    for f in dataclasses.fields(opts):
        v = getattr(opts, f.name)
        if v != getattr(defaults, f.name) and v is not None:
            os.environ["PONY_TPU_" + f.name.upper()] = str(v)
    script, *args = rest
    if not os.path.exists(script):
        print(f"ponyc_tpu run: no such script: {script}", file=sys.stderr)
        return 2
    from .platforms import auto_backend
    auto_backend()
    sys.argv = [script] + args
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)) or ".")
    runpy.run_path(script, run_name="__main__")
    return 0


def cmd_bench(argv) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench = os.path.join(root, "bench.py")
    if not os.path.exists(bench):
        print("ponyc_tpu bench: bench.py not found (installed package "
              "without the repo harness)", file=sys.stderr)
        return 2
    return subprocess.call([sys.executable, bench] + list(argv))


def cmd_test(argv) -> int:
    import pytest
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(root, "tests")
    target = [tests] if os.path.isdir(tests) else ["--pyargs", "ponyc_tpu"]
    return pytest.main(target + list(argv))


def cmd_doc(argv) -> int:
    if not argv:
        print("ponyc_tpu doc: missing module[:ATTR]", file=sys.stderr)
        return 2
    out_dir = "docs_out"
    if "-o" in argv:
        i = argv.index("-o")
        out_dir = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    import importlib

    from .api import ActorTypeMeta
    from .docgen import document_types
    modname, _, attr = argv[0].partition(":")
    sys.path.insert(0, os.getcwd())
    mod = importlib.import_module(modname)
    objs = [getattr(mod, attr)] if attr else [
        v for v in vars(mod).values() if isinstance(v, ActorTypeMeta)]
    if not objs:
        print(f"ponyc_tpu doc: no actor types in {modname}",
              file=sys.stderr)
        return 1
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, modname.replace(".", "_") + ".md")
    with open(path, "w") as f:
        f.write(document_types(*objs, title=modname))
    print(path)
    return 0


def _load_module_types(cmd: str, modname: str):
    """Import a module and collect its concrete actor types (shared by
    verify/lint). Returns (module, types) or (None, exit_code)."""
    import importlib

    from .api import ActorTypeMeta
    sys.path.insert(0, os.getcwd())
    mod = importlib.import_module(modname)
    atypes = [v for v in vars(mod).values()
              if isinstance(v, ActorTypeMeta)
              and v.behaviour_defs
              and not getattr(v, "_type_params", ())]
    if not atypes:
        print(f"ponyc_tpu {cmd}: no concrete actor types in {modname}",
              file=sys.stderr)
        return None, 3
    return mod, atypes


def cmd_verify(argv) -> int:
    """Run the verify pass over a module's actor types (≙ the verify
    stage of the compile pipeline, verify/fun.c): print each
    behaviour's effect signature, fail on budget violations.

    Exit codes: 0 all behaviours verify, 1 budget/trace violations,
    2 usage error, 3 module has no concrete actor types. `--json`
    emits failures in the lint finding format (one object per line)."""
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if not argv:
        print("ponyc_tpu verify: missing module", file=sys.stderr)
        return 2
    mod, atypes = _load_module_types("verify", argv[0])
    if mod is None:
        return atypes
    from .lint.rules import Finding
    from .verify import VerifyError, behaviour_location, verify_behaviour
    bad = 0
    for atype in atypes:
        for bdef in atype.behaviour_defs:
            try:
                eff = verify_behaviour(bdef)
            except (VerifyError, TypeError, RuntimeError) as e:
                # Budget violations AND trace-time failures
                # (sendability/capability errors) report as FAILs, not
                # tracebacks, and the sweep continues.
                file, line = behaviour_location(bdef)
                if as_json:
                    print(Finding("VERIFY", "error", atype.__name__,
                                  bdef.name, str(e), file=file,
                                  line=line).json_line())
                else:
                    print(f"FAIL {atype.__name__}.{bdef.name}: {e}")
                bad += 1
                continue
            if not as_json:
                marks = eff.marks() or "pure state update"
                print(f"ok   {atype.__name__}.{bdef.name}: {marks}")
    return 1 if bad else 0


def cmd_lint(argv) -> int:
    """Whole-program lint (≙ reach/paint + the capability checks run
    program-wide, plus the compiler's syntactic body checks;
    ponyc_tpu/lint). Targets are module names (graph rules R1–R5 from
    probe traces + body rules R6–R9) and/or file/directory paths
    (`lint examples/` — body rules only, pure AST: the files are
    PARSED, never imported, so a file whose imports are broken still
    lints, with no JAX in the loop).

    Roots (host inject sites) come from --roots / the module's
    LINT_ROOTS / actor-type LINT_ROOTS; with none declared, any
    behaviour is assumed injectable (R1 and the rooted R2 sub-rule
    stay quiet). Output: human (default), --json (one object per
    line), --format github (::warning/::error annotations). Exit
    codes: 0 clean (info-severity findings are advisory), 1 findings
    at warning/error, 2 usage, 3 no actor types found."""
    fmt = "human"
    if "--json" in argv:
        fmt = "json"
        argv = [a for a in argv if a != "--json"]
    if "--format" in argv:
        i = argv.index("--format")
        if i + 1 >= len(argv) or argv[i + 1] not in ("human", "json",
                                                     "github"):
            print("ponyc_tpu lint: --format takes human|json|github",
                  file=sys.stderr)
            return 2
        fmt = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    roots = None
    if "--roots" in argv:
        i = argv.index("--roots")
        if i + 1 >= len(argv):
            print("ponyc_tpu lint: --roots needs a value "
                  "(e.g. --roots Main.create,Ring.token)",
                  file=sys.stderr)
            return 2
        roots = [r for r in argv[i + 1].split(",") if r]
        argv = argv[:i] + argv[i + 2:]
    if not argv:
        print("ponyc_tpu lint: missing module or path", file=sys.stderr)
        return 2
    from .lint import (check_paths, findings_to_github,
                       findings_to_json, format_findings, lint_types)
    findings = []
    n_types = n_beh = 0
    paths = [a for a in argv if os.path.exists(a)]
    modules = [a for a in argv if a not in paths]
    if paths:
        pf, pt, pb = check_paths(paths)
        findings += pf
        n_types += pt
        n_beh += pb
    for modname in modules:
        mod, atypes = _load_module_types("lint", modname)
        if mod is None:
            return atypes
        mroots = roots if roots is not None else getattr(
            mod, "LINT_ROOTS", None)
        try:
            findings += lint_types(*atypes, roots=mroots)
        except (TypeError, ValueError) as e:
            print(f"ponyc_tpu lint: {e}", file=sys.stderr)
            return 2
        n_types += len(atypes)
        n_beh += sum(len(t.behaviour_defs) for t in atypes)
    if not n_types:
        print("ponyc_tpu lint: no actor types found in "
              + ", ".join(argv), file=sys.stderr)
        return 3
    if fmt == "json":
        out = findings_to_json(findings)
        if out:
            print(out)
    elif fmt == "github":
        out = findings_to_github(findings)
        if out:
            print(out)
    else:
        if findings:
            print(format_findings(findings))
        by_sev = {}
        for f in findings:
            by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
        summary = (", ".join(f"{n} {s}" for s, n in sorted(by_sev.items()))
                   or "clean")
        print(f"lint: {n_types} type(s), {n_beh} behaviour(s): "
              f"{summary}")
    return 1 if any(f.severity in ("error", "warning")
                    for f in findings) else 0


def cmd_trace(argv) -> int:
    """Convert analysis CSVs to a Chrome-trace/Perfetto JSON (≙ the
    dtrace/systemtap timeline scripts, examples/dtrace/telemetry.d):

        ponyc_tpu trace <analytics.csv> [-o out.trace.json]
                        [--spans <spans.jsonl>]
        ponyc_tpu trace --tree <spans.jsonl>

    The first form merges the window/counter tracks with the causal-
    trace span slices + sender→receiver flow arrows (PROFILE.md §10;
    `--spans` overrides the `<csv>.spans.jsonl` default). The second
    prints the reassembled causal trees — one indented tree per
    sampled trace with its critical-path latency in device ticks."""
    if "--tree" in argv:
        argv = [a for a in argv if a != "--tree"]
        if not argv:
            print("ponyc_tpu trace: --tree needs a <spans.jsonl> path",
                  file=sys.stderr)
            return 2
        from .tracing import format_trace, load_spans, reassemble
        try:
            trees = reassemble(load_spans(argv[0]))
        except OSError as e:
            print(f"ponyc_tpu trace: {e}", file=sys.stderr)
            return 2
        if not trees:
            print("(no spans recorded — is tracing on? "
                  "RuntimeOptions(analysis=3, trace_sample=N))")
            return 0
        for tid in sorted(trees):
            print(format_trace(tid, trees[tid]))
        return 0
    out = "trace.json"
    spans = None
    if "--spans" in argv:
        i = argv.index("--spans")
        if i + 1 >= len(argv):
            print("ponyc_tpu trace: --spans needs a path",
                  file=sys.stderr)
            return 2
        spans = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if "-o" in argv:
        i = argv.index("-o")
        if i + 1 >= len(argv):
            print("ponyc_tpu trace: -o needs a path", file=sys.stderr)
            return 2
        out = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if not argv:
        print("ponyc_tpu trace: missing <analytics.csv> "
              "(RuntimeOptions.analysis_path)", file=sys.stderr)
        return 2
    from .analysis import chrome_trace
    try:
        print(chrome_trace(argv[0], out, spans_path=spans))
    except OSError as e:
        print(f"ponyc_tpu trace: {e}", file=sys.stderr)
        return 2
    return 0


def cmd_top(argv) -> int:
    """Live profiler view (≙ watching the fork's analytics CSV, but
    pre-digested like top(1)): tails the level-2 window CSV a running
    runtime's writer thread appends to and reprints one frame per
    interval — throughput, queue pressure, GC, per-behaviour runs,
    per-cohort queue-wait percentiles (analysis.top_frame).

    ponyc_tpu top [<analytics.csv>] [--interval S] [--once]"""
    import time as _time
    interval, once = 1.0, False
    path = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--interval":
            if i + 1 >= len(argv):
                print("ponyc_tpu top: --interval needs seconds",
                      file=sys.stderr)
                return 2
            try:
                interval = float(argv[i + 1])
            except ValueError:
                print(f"ponyc_tpu top: bad interval {argv[i + 1]!r}",
                      file=sys.stderr)
                return 2
            i += 2
            continue
        if a == "--once":
            once = True
            i += 1
            continue
        if path is not None:
            print("ponyc_tpu top: one CSV path only", file=sys.stderr)
            return 2
        path = a
        i += 1
    if path is None:
        from .config import RuntimeOptions
        path = RuntimeOptions().analysis_path
    from .analysis import top_frame
    try:
        while True:
            try:
                frame = top_frame(path)
            except FileNotFoundError:
                frame = (f"ponyc_tpu top — {path}\n(waiting for a "
                         "runtime with analysis>=2 to write windows)")
            if once:
                print(frame)
                return 0
            # Clear + home, then the frame: a plain-ANSI live view.
            print("\x1b[2J\x1b[H" + frame, flush=True)
            _time.sleep(max(0.05, interval))
    except KeyboardInterrupt:
        return 0


def cmd_doctor(argv) -> int:
    """Operational diagnosis (PROFILE.md §11): read stall/crash
    evidence and lead with a one-line verdict.

        ponyc_tpu doctor --postmortem <file.postmortem.json|BENCH.json>
        ponyc_tpu doctor <host:port | http://host:port>

    The first form renders a flight-recorder postmortem (also accepts
    a BENCH json whose `postmortem`/`tpu_init` evidence rides inside);
    the second GETs /healthz + /metrics from a live runtime
    (RuntimeOptions.metrics_port). Exit codes: 0 the world looks
    healthy (ok / plain snapshot), 1 stalled/crashed/degraded, 2 usage
    error or unreadable target."""
    from .flight import diagnose_postmortem, load_postmortem
    if "--postmortem" in argv:
        i = argv.index("--postmortem")
        if i + 1 >= len(argv):
            print("ponyc_tpu doctor: --postmortem needs a file",
                  file=sys.stderr)
            return 2
        path = argv[i + 1]
        try:
            pm = load_postmortem(path)
        except (OSError, ValueError) as e:
            # A BENCH json carries the probe postmortem nested under
            # "postmortem" — accept the wrapper file directly.
            import json as _json
            try:
                with open(path) as f:
                    obj = _json.load(f)
                pm = obj["postmortem"]
                if not isinstance(pm, dict) or "reason" not in pm:
                    raise KeyError("postmortem")
            except (OSError, ValueError, KeyError, TypeError):
                print(f"ponyc_tpu doctor: {e}", file=sys.stderr)
                return 2
        line, detail = diagnose_postmortem(pm)
        print(line)
        print(detail)
        return 0 if line.startswith(("OK", "SNAPSHOT")) else 1
    if not argv or argv[0].startswith("-"):
        print("ponyc_tpu doctor: need --postmortem FILE or a live "
              "host:port / URL (RuntimeOptions.metrics_port)",
              file=sys.stderr)
        return 2
    from .metrics import diagnose_endpoint
    try:
        status, line, detail = diagnose_endpoint(argv[0])
    except (OSError, ValueError) as e:
        print(f"ponyc_tpu doctor: endpoint {argv[0]} unreachable: {e}",
              file=sys.stderr)
        return 2
    print(line)
    print(detail)
    return 0 if status == "ok" else 1


def _resolve_snapshot_target(target: str):
    """A snapshot CLI target is a file OR a checkpoint-ring prefix;
    returns (path, err). Prefixes resolve to the newest intact ring
    file (falling back past corrupt ones, like the supervisor)."""
    from . import serialise
    if os.path.exists(target):
        return target, None
    ring = serialise.list_checkpoints(target)
    if not ring:
        return None, (f"no such snapshot file and no checkpoint ring "
                      f"under prefix {target!r}")
    path = serialise.newest_intact(target)
    if path is None:
        return None, (f"all {len(ring)} ring snapshot(s) under "
                      f"{target!r} are corrupt")
    return path, None


def cmd_snapshot(argv) -> int:
    """Inspect a world snapshot (serialise.py): header summary +
    checksum verdict. Exit 0 intact, 1 corrupt/unreadable, 2 usage."""
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if len(argv) != 1:
        print("ponyc_tpu snapshot: need exactly one <file|ring-prefix>",
              file=sys.stderr)
        return 2
    from . import serialise
    path, err = _resolve_snapshot_target(argv[0])
    if err:
        print(f"ponyc_tpu snapshot: {err}", file=sys.stderr)
        return 1 if "corrupt" in err else 2
    try:
        header = serialise.verify_snapshot(path)
    except (serialise.SnapshotCorruptError,
            serialise.SnapshotFormatError, OSError) as e:
        print(f"ponyc_tpu snapshot: CORRUPT — {e}", file=sys.stderr)
        return 1
    geo = header.get("geometry", {})
    info = {
        "path": path,
        "format": header.get("format"),
        "intact": True,
        "fingerprint": header.get("fingerprint"),
        "age_s": (round(time.time() - header["time"], 1)
                  if header.get("time") else None),
        "steps_run": header.get("steps_run"),
        "actors_total": geo.get("total"),
        "shards": geo.get("shards"),
        "mailbox_cap": geo.get("mailbox_cap"),
        "cohorts": {c["name"]: c["capacity"]
                    for c in geo.get("cohorts", [])},
        "totals": header.get("totals", {}),
    }
    if as_json:
        print(json.dumps(info))
    else:
        print(f"{path}: INTACT (format v{info['format']}, "
              f"fingerprint {info['fingerprint']})")
        print(f"  steps_run={info['steps_run']} "
              f"actors={info['actors_total']} shards={info['shards']} "
              f"mailbox_cap={info['mailbox_cap']}"
              + (f" age={info['age_s']}s"
                 if info["age_s"] is not None else ""))
        if info["cohorts"]:
            print("  cohorts: " + ", ".join(
                f"{n}[{c}]" for n, c in info["cohorts"].items()))
    return 0


def cmd_restore(argv) -> int:
    """Deep restorability check: full verification of every array plus
    the format gate — what serialise.restore() would accept. Exit 0
    restorable, 1 corrupt/unreadable, 2 usage."""
    if len(argv) != 1:
        print("ponyc_tpu restore: need exactly one <file|ring-prefix> "
              "(in-program restore is serialise.restore(rt, path))",
              file=sys.stderr)
        return 2
    from . import serialise
    path, err = _resolve_snapshot_target(argv[0])
    if err:
        print(f"ponyc_tpu restore: {err}", file=sys.stderr)
        return 1 if "corrupt" in err else 2
    try:
        header = serialise.verify_snapshot(path)
    except (serialise.SnapshotCorruptError,
            serialise.SnapshotFormatError, OSError) as e:
        print(f"ponyc_tpu restore: NOT RESTORABLE — {e}",
              file=sys.stderr)
        return 1
    geo = header.get("geometry", {})
    print(f"{path}: RESTORABLE (format v{header.get('format')}, "
          f"{geo.get('total', '?')} actor rows, "
          f"step {header.get('steps_run', '?')}; restore with "
          "serialise.restore(rt, path) — geometry may differ since v3)")
    return 0


def cmd_supervise(argv) -> int:
    """Run a workload script under restart-from-checkpoint supervision
    (supervise.Supervisor subprocess mode)."""
    retries, backoff, prefix = 5, 0.25, None
    rest: list = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if rest:                      # after the script: its own argv
            rest.append(a)
            i += 1
            continue
        if a in ("--retries", "--backoff", "--prefix"):
            if i + 1 >= len(argv):
                print(f"ponyc_tpu supervise: {a} needs a value",
                      file=sys.stderr)
                return 2
            try:
                if a == "--retries":
                    retries = int(argv[i + 1])
                elif a == "--backoff":
                    backoff = float(argv[i + 1])
                else:
                    prefix = argv[i + 1]
            except ValueError:
                print(f"ponyc_tpu supervise: bad value for {a}: "
                      f"{argv[i + 1]!r}", file=sys.stderr)
                return 2
            i += 2
            continue
        rest.append(a)
        i += 1
    if not rest or prefix is None:
        print("ponyc_tpu supervise: need --prefix <checkpoint-prefix> "
              "and a <script.py> (the script should set "
              "RuntimeOptions(checkpoint_every_s=..., checkpoint_path="
              "<prefix>) and call supervise.maybe_restore(rt))",
              file=sys.stderr)
        return 2
    script = rest[0]
    if not os.path.exists(script):
        print(f"ponyc_tpu supervise: no such script: {script}",
              file=sys.stderr)
        return 2
    from .supervise import PoisonError, Supervisor
    # The child must find THIS ponyc_tpu whatever directory its script
    # lives in: append our package root to PYTHONPATH (append, not
    # replace — the TPU env's sitecustomize path must stay first).
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    os.environ["PYTHONPATH"] = (existing + os.pathsep + pkg_root
                                if existing else pkg_root)
    sup = Supervisor(argv=[sys.executable, script] + rest[1:],
                     prefix=prefix, retries=retries, backoff_s=backoff)
    try:
        code = sup.run()
    except PoisonError as e:
        print(f"ponyc_tpu supervise: POISON — {e}", file=sys.stderr)
        return 3
    if sup.restarts:
        print(f"ponyc_tpu supervise: recovered after {sup.restarts} "
              f"restart(s); final exit {code}", file=sys.stderr)
    return code


def cmd_serve(argv) -> int:
    """Run the serving front door (serve.py: batched socket ingress,
    admission control, graceful drain) over the default compute
    service."""
    from .serve import main as serve_main
    return serve_main(argv)


def cmd_perf(argv) -> int:
    """Standing perf-regression scoreboard (costs.py, ISSUE 19):

        ponyc_tpu perf [--check] [--tolerance F] [--root DIR]
                       [--history FILE] [--json]

    Ingests BENCH_HISTORY.jsonl (appended by every bench.py run) plus
    the committed BENCH_r*.json round records, renders the headline
    trajectory against per-group best-so-far and the 10x north star,
    and with --check gates on regression: newest row of each
    comparable (metric, unit, platform, actors) group more than
    --tolerance (default 0.2) below that group's best, or any row
    whose measured costs diverged from the model. Exit: 0 ok,
    1 regression/divergence (--check), 2 usage or no history."""
    from . import costs
    root, history, tol = ".", None, costs.PERF_TOLERANCE
    check = json_out = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--check":
            check = True
        elif a == "--json":
            json_out = True
        elif a in ("--tolerance", "--root", "--history"):
            if i + 1 >= len(argv):
                print(f"ponyc_tpu perf: {a} needs a value",
                      file=sys.stderr)
                return 2
            i += 1
            if a == "--tolerance":
                try:
                    tol = float(argv[i])
                except ValueError:
                    print(f"ponyc_tpu perf: bad --tolerance "
                          f"{argv[i]!r}", file=sys.stderr)
                    return 2
            elif a == "--root":
                root = argv[i]
            else:
                history = argv[i]
        else:
            print(f"ponyc_tpu perf: unknown argument {a!r}",
                  file=sys.stderr)
            return 2
        i += 1
    rows = costs.load_history(root, history_path=history)
    verdict = costs.perf_check(rows, tolerance=tol) if check else None
    if json_out:
        import json as _json
        print(_json.dumps({"rows": rows, "check": verdict}))
    else:
        print(costs.render_perf(rows, verdict))
    if not rows:
        return 2
    if check and not verdict["ok"]:
        return 1
    return 0


def cmd_version(_argv) -> int:
    from . import __version__
    print(f"ponyc_tpu {__version__}")
    try:
        from .platforms import probe_accelerator
        plat, err = probe_accelerator(10.0)
        print(f"backend: {plat or 'cpu'}"
              + (f" (accelerator unavailable: {err})" if err else ""))
    except Exception as e:                     # noqa: BLE001
        print(f"backend probe failed: {e}")
    return 0


COMMANDS = {"run": cmd_run, "bench": cmd_bench, "test": cmd_test,
            "doc": cmd_doc, "verify": cmd_verify, "lint": cmd_lint,
            "trace": cmd_trace, "top": cmd_top, "doctor": cmd_doctor,
            "supervise": cmd_supervise, "snapshot": cmd_snapshot,
            "restore": cmd_restore, "serve": cmd_serve,
            "perf": cmd_perf, "version": cmd_version}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        return _usage(0 if argv else 2)
    cmd = COMMANDS.get(argv[0])
    if cmd is None:
        print(f"ponyc_tpu: unknown command {argv[0]!r} "
              f"(expected one of {', '.join(COMMANDS)})", file=sys.stderr)
        return 2
    return cmd(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
