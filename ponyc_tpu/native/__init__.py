"""ctypes bindings for the native host runtime (libponyx_host.so).

The native layer is the TPU framework's C++ counterpart of the
reference's host-side runtime services (SURVEY.md §2.1): the pool
allocator (mem/pool.c), the MPSC staging queue (actor/messageq.c) and
the ASIO event loop (asio/asio.c + asio/epoll.c). Device-side execution
(mailbox table, dispatch, routing) lives in XLA; this library covers the
pieces that must stay on the host — OS events, timers, signals, sockets
— exactly where the reference keeps its ASIO thread.

The shared library builds on first import with g++ if missing (the
toolchain is part of the environment; there is no wheel step).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "build", "libponyx_host.so")

_lib = None
_lib_lock = threading.Lock()


class NativeBuildError(RuntimeError):
    pass


def _build() -> None:
    res = subprocess.run(["make", "-C", _DIR, "-s"],
                         capture_output=True, text=True)
    if res.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{res.stdout}\n{res.stderr}")


def lib() -> ctypes.CDLL:
    """Load (building if necessary) the native library, once per process."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        srcs = [os.path.join(_DIR, "src", f)
                for f in os.listdir(os.path.join(_DIR, "src"))]
        if (not os.path.exists(_SO)
                or any(os.path.getmtime(s) > os.path.getmtime(_SO)
                       for s in srcs)):
            _build()
        l = ctypes.CDLL(_SO)
        c = ctypes
        l.ponyx_pool_alloc.restype = c.c_void_p
        l.ponyx_pool_alloc.argtypes = [c.c_size_t]
        l.ponyx_pool_free.argtypes = [c.c_size_t, c.c_void_p]
        l.ponyx_pool_allocated.restype = c.c_uint64
        l.ponyx_pool_recycled.restype = c.c_uint64
        l.ponyx_pool_index.restype = c.c_int
        l.ponyx_pool_index.argtypes = [c.c_size_t]

        l.ponyx_mpscq_create.restype = c.c_void_p
        l.ponyx_mpscq_destroy.argtypes = [c.c_void_p]
        l.ponyx_mpscq_push.argtypes = [c.c_void_p,
                                       c.POINTER(c.c_int32), c.c_int32]
        l.ponyx_mpscq_pop.restype = c.c_int32
        l.ponyx_mpscq_pop.argtypes = [c.c_void_p,
                                      c.POINTER(c.c_int32), c.c_int32]
        l.ponyx_mpscq_count.restype = c.c_int64
        l.ponyx_mpscq_count.argtypes = [c.c_void_p]

        l.ponyx_asio_create.restype = c.c_void_p
        l.ponyx_asio_destroy.argtypes = [c.c_void_p]
        l.ponyx_asio_setaffinity.restype = c.c_int32
        l.ponyx_asio_setaffinity.argtypes = [
            c.c_void_p, c.POINTER(c.c_int32), c.c_int32]
        l.ponyx_asio_timer.restype = c.c_int32
        l.ponyx_asio_timer.argtypes = [c.c_void_p, c.c_int64, c.c_int64,
                                       c.c_int32, c.c_int32, c.c_int32,
                                       c.c_int32]
        l.ponyx_bench_pool.restype = c.c_double
        l.ponyx_bench_pool.argtypes = [c.c_uint64, c.c_uint64]
        l.ponyx_bench_pool_burst.restype = c.c_double
        l.ponyx_bench_pool_burst.argtypes = [c.c_uint64, c.c_uint64,
                                             c.c_uint64]
        l.ponyx_bench_mpscq.restype = c.c_double
        l.ponyx_bench_mpscq.argtypes = [c.c_uint64, c.c_uint64]
        l.ponyx_bench_mpscq_mt.restype = c.c_double
        l.ponyx_bench_mpscq_mt.argtypes = [c.c_uint64, c.c_uint64,
                                           c.c_uint64]
        l.ponyx_asio_signal.restype = c.c_int32
        l.ponyx_asio_signal.argtypes = [c.c_void_p, c.c_int32, c.c_int32,
                                        c.c_int32, c.c_int32]
        l.ponyx_asio_fd.restype = c.c_int32
        l.ponyx_asio_fd.argtypes = [c.c_void_p, c.c_int32, c.c_int32,
                                    c.c_int32, c.c_int32, c.c_int32,
                                    c.c_int32]
        l.ponyx_asio_unsubscribe.restype = c.c_int32
        l.ponyx_asio_unsubscribe.argtypes = [c.c_void_p, c.c_int32]
        l.ponyx_asio_fd_interest.restype = c.c_int32
        l.ponyx_asio_fd_interest.argtypes = [c.c_void_p, c.c_int32,
                                             c.c_int32]
        l.ponyx_asio_drain.restype = c.c_int32
        l.ponyx_asio_drain.argtypes = [c.c_void_p,
                                       c.POINTER(c.c_int32), c.c_int32]
        l.ponyx_asio_pending.restype = c.c_int64
        l.ponyx_asio_pending.argtypes = [c.c_void_p]
        l.ponyx_asio_wait.restype = c.c_int32
        l.ponyx_asio_wait.argtypes = [c.c_void_p, c.c_int32]
        l.ponyx_asio_noisy_add.argtypes = [c.c_void_p]
        l.ponyx_asio_noisy_remove.argtypes = [c.c_void_p]
        l.ponyx_asio_noisy_count.restype = c.c_int64
        l.ponyx_asio_noisy_count.argtypes = [c.c_void_p]

        u8p = c.POINTER(c.c_uint8)
        l.ponyx_os_listen_tcp.restype = c.c_int32
        l.ponyx_os_listen_tcp.argtypes = [c.c_char_p, c.c_int32, c.c_int32]
        l.ponyx_os_connect_tcp.restype = c.c_int32
        l.ponyx_os_connect_tcp.argtypes = [c.c_char_p, c.c_int32]
        l.ponyx_os_accept.restype = c.c_int32
        l.ponyx_os_accept.argtypes = [c.c_int32]
        l.ponyx_os_connect_result.restype = c.c_int32
        l.ponyx_os_connect_result.argtypes = [c.c_int32]
        l.ponyx_os_recv.restype = c.c_int32
        l.ponyx_os_recv.argtypes = [c.c_int32, u8p, c.c_int32]
        l.ponyx_os_send.restype = c.c_int32
        l.ponyx_os_send.argtypes = [c.c_int32, u8p, c.c_int32]
        l.ponyx_os_udp.restype = c.c_int32
        l.ponyx_os_udp.argtypes = [c.c_char_p, c.c_int32]
        l.ponyx_os_sendto.restype = c.c_int32
        l.ponyx_os_sendto.argtypes = [c.c_int32, u8p, c.c_int32,
                                      c.c_char_p, c.c_int32]
        l.ponyx_os_recvfrom.restype = c.c_int32
        l.ponyx_os_recvfrom.argtypes = [c.c_int32, u8p, c.c_int32,
                                        c.c_char_p, c.c_int32,
                                        c.POINTER(c.c_int32)]
        l.ponyx_os_sockname_port.restype = c.c_int32
        l.ponyx_os_sockname_port.argtypes = [c.c_int32]
        l.ponyx_os_peername_port.restype = c.c_int32
        l.ponyx_os_peername_port.argtypes = [c.c_int32]
        l.ponyx_os_nodelay.restype = c.c_int32
        l.ponyx_os_nodelay.argtypes = [c.c_int32, c.c_int32]
        l.ponyx_os_keepalive.restype = c.c_int32
        l.ponyx_os_keepalive.argtypes = [c.c_int32, c.c_int32]
        l.ponyx_os_shutdown.restype = c.c_int32
        l.ponyx_os_shutdown.argtypes = [c.c_int32]
        l.ponyx_os_close.restype = c.c_int32
        l.ponyx_os_close.argtypes = [c.c_int32]
        l.ponyx_os_writev.restype = c.c_int32
        l.ponyx_os_writev.argtypes = [c.c_int32, c.POINTER(u8p),
                                      c.POINTER(c.c_int32), c.c_int32]
        l.ponyx_os_multicast_join.restype = c.c_int32
        l.ponyx_os_multicast_join.argtypes = [c.c_int32, c.c_char_p,
                                              c.c_char_p]
        l.ponyx_os_multicast_leave.restype = c.c_int32
        l.ponyx_os_multicast_leave.argtypes = [c.c_int32, c.c_char_p,
                                               c.c_char_p]
        l.ponyx_os_multicast_ttl.restype = c.c_int32
        l.ponyx_os_multicast_ttl.argtypes = [c.c_int32, c.c_int32]
        l.ponyx_os_multicast_loopback.restype = c.c_int32
        l.ponyx_os_multicast_loopback.argtypes = [c.c_int32, c.c_int32]
        l.ponyx_os_broadcast.restype = c.c_int32
        l.ponyx_os_broadcast.argtypes = [c.c_int32, c.c_int32]
        l.ponyx_os_setsockopt_int.restype = c.c_int32
        l.ponyx_os_setsockopt_int.argtypes = [c.c_int32, c.c_int32,
                                              c.c_int32, c.c_int32]
        l.ponyx_os_getsockopt_int.restype = c.c_int32
        l.ponyx_os_getsockopt_int.argtypes = [c.c_int32, c.c_int32,
                                              c.c_int32,
                                              c.POINTER(c.c_int32)]
        l.ponyx_os_sockname.restype = c.c_int32
        l.ponyx_os_sockname.argtypes = [c.c_int32, c.c_char_p, c.c_int32,
                                        c.POINTER(c.c_int32)]
        l.ponyx_os_peername.restype = c.c_int32
        l.ponyx_os_peername.argtypes = [c.c_int32, c.c_char_p, c.c_int32,
                                        c.POINTER(c.c_int32)]

        l.ponyx_os_process_spawn.restype = c.c_int64
        l.ponyx_os_process_spawn.argtypes = [
            c.c_char_p, c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
            c.POINTER(c.c_int32)]
        l.ponyx_os_process_check.restype = c.c_int32
        l.ponyx_os_process_check.argtypes = [c.c_int64]
        l.ponyx_os_process_kill.restype = c.c_int32
        l.ponyx_os_process_kill.argtypes = [c.c_int64, c.c_int32]
        _lib = l
        return _lib


def pool_stats() -> Tuple[int, int]:
    """(live blocks, parked blocks) from the native pool allocator."""
    l = lib()
    return int(l.ponyx_pool_allocated()), int(l.ponyx_pool_recycled())


class sockets:
    """Thin typed façade over the native socket ops (socket.cc ≙
    src/libponyrt/lang/socket.c). All fds are non-blocking; -errno return
    convention is translated to OSError except EAGAIN → None/b''."""

    EAGAIN = 11
    ESHUTDOWN = 108

    @staticmethod
    def _ck(r: int) -> int:
        if r < 0:
            raise OSError(-r, os.strerror(-r))
        return r

    @classmethod
    def listen_tcp(cls, host: str, port: int, backlog: int = 64) -> int:
        return cls._ck(lib().ponyx_os_listen_tcp(
            host.encode(), port, backlog))

    @classmethod
    def connect_tcp(cls, host: str, port: int) -> int:
        return cls._ck(lib().ponyx_os_connect_tcp(host.encode(), port))

    @classmethod
    def accept(cls, listen_fd: int) -> Optional[int]:
        r = lib().ponyx_os_accept(listen_fd)
        if r == -cls.EAGAIN:
            return None
        return cls._ck(r)

    @classmethod
    def connect_result(cls, fd: int) -> int:
        """0 = connected; else positive errno."""
        return -int(lib().ponyx_os_connect_result(fd))

    @classmethod
    def recv(cls, fd: int, max_bytes: int = 65536):
        """bytes (possibly empty=-EAGAIN → None) or b'' on orderly EOF."""
        buf = (ctypes.c_uint8 * max_bytes)()
        r = lib().ponyx_os_recv(fd, buf, max_bytes)
        if r == -cls.EAGAIN:
            return None
        if r == -cls.ESHUTDOWN:
            return b""
        cls._ck(r)
        return bytes(bytearray(buf[:r]))

    @classmethod
    def send(cls, fd: int, data: bytes) -> int:
        """Bytes accepted (may be short); 0 when the kernel buffer is
        full (wait for a write event)."""
        n = len(data)
        arr = (ctypes.c_uint8 * n).from_buffer_copy(data)
        r = lib().ponyx_os_send(fd, arr, n)
        if r == -cls.EAGAIN:
            return 0
        return cls._ck(r)

    @classmethod
    def udp(cls, host: str = "", port: int = 0) -> int:
        return cls._ck(lib().ponyx_os_udp(host.encode(), port))

    @classmethod
    def sendto(cls, fd: int, data: bytes, host: str, port: int) -> int:
        n = len(data)
        arr = (ctypes.c_uint8 * n).from_buffer_copy(data)
        r = lib().ponyx_os_sendto(fd, arr, n, host.encode(), port)
        if r == -cls.EAGAIN:
            return 0
        return cls._ck(r)

    @classmethod
    def recvfrom(cls, fd: int, max_bytes: int = 65536):
        """(data, host, port) or None when drained."""
        buf = (ctypes.c_uint8 * max_bytes)()
        addr = ctypes.create_string_buffer(64)
        port = ctypes.c_int32(0)
        r = lib().ponyx_os_recvfrom(fd, buf, max_bytes, addr, 64,
                                    ctypes.byref(port))
        if r == -cls.EAGAIN:
            return None
        cls._ck(r)
        return (bytes(bytearray(buf[:r])), addr.value.decode(),
                int(port.value))

    @classmethod
    def sockname_port(cls, fd: int) -> int:
        return cls._ck(lib().ponyx_os_sockname_port(fd))

    @classmethod
    def peername_port(cls, fd: int) -> int:
        return cls._ck(lib().ponyx_os_peername_port(fd))

    @classmethod
    def nodelay(cls, fd: int, on: bool = True) -> None:
        cls._ck(lib().ponyx_os_nodelay(fd, int(on)))

    @classmethod
    def keepalive(cls, fd: int, secs: int) -> None:
        cls._ck(lib().ponyx_os_keepalive(fd, secs))

    @classmethod
    def writev(cls, fd: int, chunks) -> int:
        """Scatter-gather send of a chunk list without flattening
        (≙ the reference's iovec writev path, lang/socket.c): one
        sendmsg carries up to 64 chunks straight out of the caller's
        buffers. Returns bytes accepted (may end mid-chunk); 0 when the
        kernel buffer is full."""
        chunks = [bytes(c) for c in chunks if c]
        if not chunks:
            return 0
        n = min(len(chunks), 64)
        c = ctypes
        # Zero-copy: bytes are immutable and kept alive by `chunks` for
        # the duration of the (read-only) sendmsg, so point straight at
        # their buffers instead of memcpy-ing every retry.
        ptrs = (c.POINTER(c.c_uint8) * n)(
            *[c.cast(c.c_char_p(ch), c.POINTER(c.c_uint8))
              for ch in chunks[:n]])
        lens = (c.c_int32 * n)(*[len(ch) for ch in chunks[:n]])
        r = lib().ponyx_os_writev(fd, ptrs, lens, n)
        if r == -cls.EAGAIN:
            return 0
        return cls._ck(r)

    @classmethod
    def multicast_join(cls, fd: int, group: str, iface: str = "") -> None:
        """Join a multicast group, IPv4 or IPv6 by the group address
        (≙ pony_os_multicast_join)."""
        cls._ck(lib().ponyx_os_multicast_join(fd, group.encode(),
                                              iface.encode()))

    @classmethod
    def multicast_leave(cls, fd: int, group: str, iface: str = "") -> None:
        cls._ck(lib().ponyx_os_multicast_leave(fd, group.encode(),
                                               iface.encode()))

    @classmethod
    def multicast_ttl(cls, fd: int, ttl: int) -> None:
        cls._ck(lib().ponyx_os_multicast_ttl(fd, ttl))

    @classmethod
    def multicast_loopback(cls, fd: int, on: bool = True) -> None:
        cls._ck(lib().ponyx_os_multicast_loopback(fd, int(on)))

    @classmethod
    def broadcast(cls, fd: int, on: bool = True) -> None:
        cls._ck(lib().ponyx_os_broadcast(fd, int(on)))

    @classmethod
    def set_option(cls, fd: int, level: int, name: int,
                   value: int) -> None:
        """Generic int socket option (≙ the reference's per-option
        pony_os_getsockopt surface collapsed to (level, name, int));
        levels/names are the OS constants (socket module)."""
        cls._ck(lib().ponyx_os_setsockopt_int(fd, level, name, value))

    @classmethod
    def get_option(cls, fd: int, level: int, name: int) -> int:
        out = ctypes.c_int32(0)
        cls._ck(lib().ponyx_os_getsockopt_int(fd, level, name,
                                              ctypes.byref(out)))
        return int(out.value)

    @classmethod
    def sockname(cls, fd: int):
        """(addr, port) of the local end — IPv4 dotted or IPv6 hex."""
        addr = ctypes.create_string_buffer(64)
        port = ctypes.c_int32(0)
        cls._ck(lib().ponyx_os_sockname(fd, addr, 64, ctypes.byref(port)))
        return addr.value.decode(), int(port.value)

    @classmethod
    def peername(cls, fd: int):
        """(addr, port) of the remote end."""
        addr = ctypes.create_string_buffer(64)
        port = ctypes.c_int32(0)
        cls._ck(lib().ponyx_os_peername(fd, addr, 64, ctypes.byref(port)))
        return addr.value.decode(), int(port.value)

    @classmethod
    def shutdown(cls, fd: int) -> None:
        lib().ponyx_os_shutdown(fd)

    @classmethod
    def close(cls, fd: int) -> None:
        lib().ponyx_os_close(fd)


class processes:
    """Native child-process ops (process.cc ≙ lang/process.c)."""

    @staticmethod
    def spawn(path: str, argv, env=None):
        """Returns (pid, stdin_w, stdout_r, stderr_r); fds non-blocking."""
        c = ctypes
        av = (c.c_char_p * (len(argv) + 1))(
            *[a.encode() if isinstance(a, str) else a for a in argv], None)
        ev = None
        if env is not None:
            pairs = [f"{k}={v}".encode() for k, v in env.items()]
            ev = (c.c_char_p * (len(pairs) + 1))(*pairs, None)
        fds = (c.c_int32 * 3)()
        pid = lib().ponyx_os_process_spawn(path.encode(), av, ev, fds)
        if pid < 0:
            raise OSError(-pid, os.strerror(-pid))
        return int(pid), int(fds[0]), int(fds[1]), int(fds[2])

    @staticmethod
    def check(pid: int):
        """None while running; exit code 0..255; 256+signum if killed."""
        r = lib().ponyx_os_process_check(pid)
        if r == -1:
            return None
        if r < -1:
            raise OSError(-r, os.strerror(-r))
        return int(r)

    @staticmethod
    def kill(pid: int, signum: int = 15) -> None:
        r = lib().ponyx_os_process_kill(pid, signum)
        if r < 0:
            raise OSError(-r, os.strerror(-r))


class HostQueue:
    """MPSC staging queue of int32-word messages (native-backed)."""

    def __init__(self):
        self._l = lib()
        self._q = self._l.ponyx_mpscq_create()

    def push(self, words) -> None:
        arr = np.ascontiguousarray(words, np.int32)
        self._l.ponyx_mpscq_push(
            self._q, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            arr.size)

    def pop(self, max_words: int = 64) -> Optional[np.ndarray]:
        out = np.empty((max_words,), np.int32)
        n = self._l.ponyx_mpscq_pop(
            self._q, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            max_words)
        if n == 0:
            return None
        if n < 0:
            return self.pop(-n)
        return out[:n].copy()

    def __len__(self) -> int:
        return int(self._l.ponyx_mpscq_count(self._q))

    def close(self) -> None:
        if self._q:
            self._l.ponyx_mpscq_destroy(self._q)
            self._q = None


# Event kinds in drained records (see asio.cc header comment).
TIMER, SIGNAL, FD_READ, FD_WRITE, FD_HUP = 1, 2, 3, 4, 5


class AsioEvent:
    """One drained event: (sub_id, owner, behaviour, kind, arg, flags)."""

    __slots__ = ("sub_id", "owner", "behaviour", "kind", "arg", "flags")

    def __init__(self, row):
        (self.sub_id, self.owner, self.behaviour,
         self.kind, self.arg, self.flags) = (int(x) for x in row)

    def __repr__(self):
        return (f"AsioEvent(sub={self.sub_id} owner={self.owner} "
                f"beh={self.behaviour} kind={self.kind} arg={self.arg})")


class AsioLoop:
    """The native epoll event loop (one dedicated thread).

    ≙ ponyint_asio_start / the backend dispatch thread
    (asio/asio.c:47-56, asio/epoll.c:207-230). Owned by the bridge
    package; applications use the stdlib actors (timers, net) instead.
    """

    def __init__(self):
        self._l = lib()
        self._h = self._l.ponyx_asio_create()

    def set_affinity(self, cores) -> None:
        """Set the event-loop thread's core set (≙ --ponypinasio,
        start.c:75-94 / ponyint_cpu_affinity, cpu.c:278); one core =
        a pin, the original full mask = an unpin."""
        cs = [int(x) for x in cores]
        arr = (ctypes.c_int32 * len(cs))(*cs)
        r = self._l.ponyx_asio_setaffinity(self._h, arr, len(cs))
        if r < 0:
            raise OSError(-r, os.strerror(-r))

    def pin(self, core: int) -> None:
        self.set_affinity([core])

    def timer(self, first_ns: int, interval_ns: int, owner: int,
              behaviour: int, *, oneshot: bool = False,
              noisy: bool = True) -> int:
        r = self._l.ponyx_asio_timer(self._h, first_ns, interval_ns,
                                     owner, behaviour, int(oneshot),
                                     int(noisy))
        if r < 0:
            raise OSError(-r, os.strerror(-r))
        return r

    def signal(self, signum: int, owner: int, behaviour: int,
               *, noisy: bool = False) -> int:
        r = self._l.ponyx_asio_signal(self._h, signum, owner, behaviour,
                                      int(noisy))
        if r < 0:
            raise OSError(-r, os.strerror(-r))
        return r

    def fd(self, fd: int, owner: int, behaviour: int, *,
           read: bool = True, write: bool = False, oneshot: bool = False,
           noisy: bool = True) -> int:
        interest = (1 if read else 0) | (2 if write else 0)
        r = self._l.ponyx_asio_fd(self._h, fd, interest, owner, behaviour,
                                  int(oneshot), int(noisy))
        if r < 0:
            raise OSError(-r, os.strerror(-r))
        return r

    def unsubscribe(self, sub_id: int) -> bool:
        return bool(self._l.ponyx_asio_unsubscribe(self._h, sub_id))

    def fd_interest(self, sub_id: int, *, read: bool = True,
                    write: bool = False) -> None:
        """Re-arm a live fd subscription's interest set (epoll MOD)."""
        interest = (1 if read else 0) | (2 if write else 0)
        r = self._l.ponyx_asio_fd_interest(self._h, sub_id, interest)
        if r < 0:
            raise OSError(-r, os.strerror(-r))

    def drain(self, max_events: int = 256) -> List[AsioEvent]:
        out = np.empty((max_events, 6), np.int32)
        n = self._l.ponyx_asio_drain(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            max_events)
        return [AsioEvent(out[i]) for i in range(n)]

    def pending(self) -> int:
        return int(self._l.ponyx_asio_pending(self._h))

    def wait(self, timeout_s: float) -> bool:
        """Block until the event queue is non-empty or the timeout
        passes (≙ a quiescing scheduler suspended until the ASIO thread
        wakes it, scheduler.c:1427-1476); True if events are pending.
        Releases the GIL for the duration (plain ctypes call)."""
        return bool(self._l.ponyx_asio_wait(
            self._h, max(0, int(timeout_s * 1e3))))

    def noisy_add(self) -> None:
        self._l.ponyx_asio_noisy_add(self._h)

    def noisy_remove(self) -> None:
        self._l.ponyx_asio_noisy_remove(self._h)

    @property
    def noisy(self) -> int:
        return int(self._l.ponyx_asio_noisy_count(self._h))

    def close(self) -> None:
        if self._h:
            self._l.ponyx_asio_destroy(self._h)
            self._h = None


def microbench(scale: float = 1.0) -> dict:
    """Native-runtime microbenchmarks, timed entirely in C++ (≙ the
    reference's Google-Benchmark suite over pool/queues,
    benchmark/libponyrt/mem/pool.cc, benchmark/README.md). Returns
    {name: ns_per_op}."""
    l = lib()
    it = max(1, int(200_000 * scale))
    return {
        "pool_alloc_free_64B_ns": l.ponyx_bench_pool(it, 64),
        "pool_alloc_free_4KB_ns": l.ponyx_bench_pool(it, 4096),
        "pool_burst32_64B_ns": l.ponyx_bench_pool_burst(
            max(1, it // 32), 64, 32),
        "mpscq_push_pop_4w_ns": l.ponyx_bench_mpscq(it, 4),
        "mpscq_mt_4prod_4w_ns": l.ponyx_bench_mpscq_mt(it, 4, 4),
    }
