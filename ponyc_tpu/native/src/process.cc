// Child-process spawning with piped stdio for process-monitor actors.
//
// ≙ the reference's lang/process.c (pony_os_process_create/wait/kill —
// fork/exec with nonblocking pipes wired to ASIO, backing
// packages/process's ProcessMonitor actor). Same design: three
// O_NONBLOCK pipes, close-on-exec everywhere, the child execs via
// execve, and the parent learns about exit via waitpid(WNOHANG) polls
// (the host polls at step boundaries, where the reference polls from
// the ASIO loop).

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <spawn.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdint>

extern char** environ;

extern "C" {

// Spawn argv[0] with argv/envp (NULL-terminated arrays of C strings).
// out_fds receives {stdin_w, stdout_r, stderr_r}, all non-blocking.
// Returns pid or -errno.
int64_t ponyx_os_process_spawn(const char* path, char* const argv[],
                               char* const envp[], int32_t out_fds[3]) {
  int in_pipe[2], out_pipe[2], err_pipe[2];
  if (pipe2(in_pipe, O_CLOEXEC) != 0) return -errno;
  if (pipe2(out_pipe, O_CLOEXEC) != 0) {
    close(in_pipe[0]); close(in_pipe[1]);
    return -errno;
  }
  if (pipe2(err_pipe, O_CLOEXEC) != 0) {
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    return -errno;
  }

  posix_spawn_file_actions_t fa;
  posix_spawn_file_actions_init(&fa);
  posix_spawn_file_actions_adddup2(&fa, in_pipe[0], 0);
  posix_spawn_file_actions_adddup2(&fa, out_pipe[1], 1);
  posix_spawn_file_actions_adddup2(&fa, err_pipe[1], 2);

  // Own process group so kill() reaches grandchildren too (a shell that
  // forks instead of execing would otherwise keep the stdio pipes open
  // past the direct child's death).
  posix_spawnattr_t at;
  posix_spawnattr_init(&at);
  posix_spawnattr_setpgroup(&at, 0);
  posix_spawnattr_setflags(&at, POSIX_SPAWN_SETPGROUP);

  pid_t pid = -1;
  int rc = posix_spawn(&pid, path, &fa, &at, argv,
                       envp != nullptr ? envp : environ);
  posix_spawnattr_destroy(&at);
  posix_spawn_file_actions_destroy(&fa);
  close(in_pipe[0]);
  close(out_pipe[1]);
  close(err_pipe[1]);
  if (rc != 0) {
    close(in_pipe[1]); close(out_pipe[0]); close(err_pipe[0]);
    return -rc;
  }
  // Parent ends non-blocking for the ASIO loop.
  const int parent_fds[3] = {in_pipe[1], out_pipe[0], err_pipe[0]};
  for (int fd : parent_fds) {
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  }
  out_fds[0] = in_pipe[1];
  out_fds[1] = out_pipe[0];
  out_fds[2] = err_pipe[0];
  return pid;
}

// waitpid(WNOHANG). Returns: -1 still running, exit code 0..255, or
// 256+signum when signalled; other -errno on error.
// ≙ pony_os_process_wait (lang/process.c).
int32_t ponyx_os_process_check(int64_t pid) {
  int status = 0;
  pid_t r = waitpid(pid_t(pid), &status, WNOHANG);
  if (r == 0) return -1;
  if (r < 0) return errno == ECHILD ? -ECHILD : -errno;
  if (WIFEXITED(status)) return WEXITSTATUS(status);
  if (WIFSIGNALED(status)) return 256 + WTERMSIG(status);
  return -1;
}

// ≙ pony_os_process_kill — signals the child's whole process group
// (it was spawned as a group leader), falling back to the pid alone.
int32_t ponyx_os_process_kill(int64_t pid, int32_t signum) {
  if (kill(-pid_t(pid), signum) == 0) return 0;
  if (kill(pid_t(pid), signum) != 0) return -errno;
  return 0;
}

}  // extern "C"
