#include "pool.h"

#include <atomic>
#include <cstdlib>
#include <mutex>

namespace {

constexpr int kMinBits = 5;    // 32 B
constexpr int kMaxBits = 20;   // 1 MiB
constexpr int kClasses = kMaxBits - kMinBits + 1;
constexpr int kLocalMax = 64;  // per-thread blocks kept before spilling

struct Node {
  Node* next;
};

// Global tier: one lock-guarded stack per class. The reference uses a
// lock-free central list; host-side traffic here is orders of magnitude
// lower (events and staged messages, not every actor message), so a
// mutex is the simpler correct choice.
struct GlobalTier {
  std::mutex mu;
  Node* head = nullptr;
  size_t count = 0;
};

GlobalTier g_global[kClasses];
std::atomic<uint64_t> g_live{0};
std::atomic<uint64_t> g_parked{0};

struct LocalTier {
  Node* head[kClasses] = {};
  int count[kClasses] = {};

  ~LocalTier() {
    // Thread exit: hand everything back to the global tier.
    for (int i = 0; i < kClasses; i++) {
      while (head[i]) {
        Node* n = head[i];
        head[i] = n->next;
        std::lock_guard<std::mutex> lock(g_global[i].mu);
        n->next = g_global[i].head;
        g_global[i].head = n;
        g_global[i].count++;
      }
      count[i] = 0;
    }
  }
};

thread_local LocalTier t_local;

int class_index(size_t size) {
  if (size <= (size_t{1} << kMinBits)) return 0;
  int bits = kMinBits;
  size_t c = size_t{1} << kMinBits;
  while (c < size) {
    c <<= 1;
    bits++;
  }
  return bits - kMinBits;
}

}  // namespace

extern "C" {

void* ponyx_pool_alloc(size_t size) {
  int idx = class_index(size);
  if (idx >= kClasses)  // oversize: straight malloc, no pooling
    return std::malloc(size);
  LocalTier& lt = t_local;
  if (lt.head[idx]) {
    Node* n = lt.head[idx];
    lt.head[idx] = n->next;
    lt.count[idx]--;
    g_live.fetch_add(1, std::memory_order_relaxed);
    g_parked.fetch_sub(1, std::memory_order_relaxed);
    return n;
  }
  {
    GlobalTier& gt = g_global[idx];
    std::lock_guard<std::mutex> lock(gt.mu);
    if (gt.head) {
      Node* n = gt.head;
      gt.head = n->next;
      gt.count--;
      g_live.fetch_add(1, std::memory_order_relaxed);
      g_parked.fetch_sub(1, std::memory_order_relaxed);
      return n;
    }
  }
  g_live.fetch_add(1, std::memory_order_relaxed);
  return std::malloc(size_t{1} << (kMinBits + idx));
}

void ponyx_pool_free(size_t size, void* p) {
  if (p == nullptr) return;
  int idx = class_index(size);
  if (idx >= kClasses) {
    std::free(p);
    return;
  }
  g_live.fetch_sub(1, std::memory_order_relaxed);
  g_parked.fetch_add(1, std::memory_order_relaxed);
  Node* n = static_cast<Node*>(p);
  LocalTier& lt = t_local;
  n->next = lt.head[idx];
  lt.head[idx] = n;
  lt.count[idx]++;
  if (lt.count[idx] > kLocalMax) {
    // Spill half to the global tier so bursty threads don't hoard.
    Node* keep = lt.head[idx];
    for (int i = 1; i < kLocalMax / 2; i++) keep = keep->next;
    Node* spill = keep->next;
    keep->next = nullptr;
    lt.count[idx] = kLocalMax / 2;
    GlobalTier& gt = g_global[idx];
    std::lock_guard<std::mutex> lock(gt.mu);
    while (spill) {
      Node* nx = spill->next;
      spill->next = gt.head;
      gt.head = spill;
      gt.count++;
      spill = nx;
    }
  }
}

uint64_t ponyx_pool_allocated() {
  return g_live.load(std::memory_order_relaxed);
}

uint64_t ponyx_pool_recycled() {
  return g_parked.load(std::memory_order_relaxed);
}

int ponyx_pool_index(size_t size) { return class_index(size); }

}  // extern "C"
