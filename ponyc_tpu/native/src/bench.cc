// In-process microbenchmarks for the native host-runtime components —
// the TPU framework's counterpart of the reference's Google-Benchmark
// suite over its allocator and queues (benchmark/libponyrt/mem/pool.cc,
// benchmark/libponyrt/ds/hash.cc). Timed loops run entirely in native
// code (one ctypes call per measurement), so Python call overhead never
// enters the measured region — the same property gbenchmark gives the
// reference.
#include <chrono>
#include <cstdint>
#include <cstring>
#include <atomic>
#include <thread>
#include <vector>

#include "mpscq.h"
#include "pool.h"

namespace {
double ns_per_op(std::chrono::steady_clock::time_point t0, uint64_t ops) {
  auto dt = std::chrono::steady_clock::now() - t0;
  return std::chrono::duration<double, std::nano>(dt).count() /
         static_cast<double>(ops);
}
}  // namespace

extern "C" {

// Alloc+free round-trips of `size`-byte blocks (free-list hit path after
// the first lap; ≙ BM_PoolAllocFree).
double ponyx_bench_pool(uint64_t iters, uint64_t size) {
  // Warm the class's free list so steady-state recycling is measured.
  void* warm = ponyx_pool_alloc(size);
  ponyx_pool_free(size, warm);
  auto t0 = std::chrono::steady_clock::now();
  for (uint64_t i = 0; i < iters; i++) {
    void* p = ponyx_pool_alloc(size);
    ponyx_pool_free(size, p);
  }
  return ns_per_op(t0, iters);
}

// Depth-`depth` alloc bursts then frees (exercises list growth;
// ≙ BM_PoolAllocMultiple).
double ponyx_bench_pool_burst(uint64_t iters, uint64_t size,
                              uint64_t depth) {
  std::vector<void*> held(depth);
  auto t0 = std::chrono::steady_clock::now();
  for (uint64_t i = 0; i < iters; i++) {
    for (uint64_t j = 0; j < depth; j++) held[j] = ponyx_pool_alloc(size);
    for (uint64_t j = 0; j < depth; j++) ponyx_pool_free(size, held[j]);
  }
  return ns_per_op(t0, iters * depth);
}

// Single-threaded push+pop round-trips of `nwords`-word messages
// through the MPSC staging queue (≙ messageq push/pop microbench).
double ponyx_bench_mpscq(uint64_t iters, uint64_t nwords) {
  ponyx_mpscq_t* q = ponyx_mpscq_create();
  std::vector<int32_t> msg(nwords, 7), out(nwords + 4);
  auto t0 = std::chrono::steady_clock::now();
  for (uint64_t i = 0; i < iters; i++) {
    ponyx_mpscq_push(q, msg.data(), static_cast<int32_t>(nwords));
    ponyx_mpscq_pop(q, out.data(), static_cast<int32_t>(out.size()));
  }
  double r = ns_per_op(t0, iters);
  ponyx_mpscq_destroy(q);
  return r;
}

// `nprod` producer threads flooding one consumer (the ASIO-loop →
// host-driver shape); returns ns per message consumed.
double ponyx_bench_mpscq_mt(uint64_t total_msgs, uint64_t nprod,
                            uint64_t nwords) {
  ponyx_mpscq_t* q = ponyx_mpscq_create();
  uint64_t per = total_msgs / nprod;
  if (per == 0) per = 1;                  // tiny scales: never measure 0 ops
  total_msgs = per * nprod;
  // Spawn first, time after a ready-barrier: thread-creation cost stays
  // outside the measured region (as gbenchmark's MT harness does).
  std::atomic<uint64_t> ready{0};
  std::atomic<bool> go{false};
  std::vector<std::thread> ts;
  for (uint64_t p = 0; p < nprod; p++) {
    ts.emplace_back([&, p]() {
      std::vector<int32_t> msg(nwords, static_cast<int32_t>(p));
      ready.fetch_add(1);
      while (!go.load(std::memory_order_acquire)) std::this_thread::yield();
      for (uint64_t i = 0; i < per; i++)
        ponyx_mpscq_push(q, msg.data(), static_cast<int32_t>(nwords));
    });
  }
  while (ready.load() < nprod) std::this_thread::yield();
  auto t0 = std::chrono::steady_clock::now();
  go.store(true, std::memory_order_release);
  std::vector<int32_t> out(nwords + 4);
  uint64_t got = 0;
  while (got < total_msgs) {
    if (ponyx_mpscq_pop(q, out.data(),
                        static_cast<int32_t>(out.size())) > 0)
      got++;
  }
  for (auto& t : ts) t.join();
  double r = ns_per_op(t0, total_msgs);
  ponyx_mpscq_destroy(q);
  return r;
}

}  // extern "C"
