// Size-class pool allocator for the host runtime.
//
// TPU-native counterpart of the reference's pool allocator
// (src/libponyrt/mem/pool.{c,h}): size classes from 2^5 to 2^20 bytes,
// thread-local free lists with a mutex-protected global recycling tier.
// On the TPU framework only *host-side* runtime objects live here (ASIO
// events, queue nodes, staged messages); device memory is managed by
// XLA, so the pagemap/virtual-alloc layers of the reference have no
// equivalent and are deliberately absent.
#pragma once

#include <cstddef>
#include <cstdint>

extern "C" {

// Round `size` up to its pool class and allocate (malloc-backed).
void* ponyx_pool_alloc(size_t size);
// Return a block allocated with ponyx_pool_alloc(size).
void ponyx_pool_free(size_t size, void* p);

// Telemetry (process-wide, approximate under concurrency).
uint64_t ponyx_pool_allocated();  // live blocks
uint64_t ponyx_pool_recycled();   // blocks parked on free lists

// Index of the size class serving `size` (for tests).
int ponyx_pool_index(size_t size);
}
