// Async-I/O event loop for the host side of the TPU actor runtime.
//
// TPU-native counterpart of the reference's ASIO subsystem
// (src/libponyrt/asio/asio.{c,h}, asio/epoll.c, asio/event.{c,h}):
// one dedicated thread runs epoll_wait (≙ ponyint_asio_backend_dispatch,
// epoll.c:207-230); timers are timerfd-backed (≙ epoll.c:328-375),
// signals use a process-wide handler writing the signum into a self-pipe
// the loop watches (the reference's exact scheme, epoll.c:54-133 — a
// signalfd would require every thread in the process to block the
// signal, which a Python host can't guarantee), and arbitrary fds
// (sockets, stdin) subscribe with read/write interest.
// Ready events become flat int32 messages on an MPSC queue that the
// Python host driver drains at step boundaries — replacing the
// ASIO-thread → scheduler mailbox hop (asio/event.c
// pony_asio_event_send → pony_sendv).
//
// The `noisy` count (≙ asio.c:80-91) keeps the runtime from reaching
// quiescence while subscriptions that can produce fresh work exist.
//
// Event record pushed to the queue (6 int32 words):
//   [0] event id  [1] owner actor id  [2] behaviour gid
//   [3] kind (1=timer 2=signal 3=fd-read 4=fd-write 5=fd-hup)
//   [4] arg (timer expiry count / signum / fd)
//   [5] flags (epoll events bitmask for fd kinds, else 0)

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <sched.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/timerfd.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_map>

#include "mpscq.h"
#include "pool.h"

namespace {

enum Kind : int32_t {
  kTimer = 1,
  kSignal = 2,
  kFdRead = 3,
  kFdWrite = 4,
  kFdHup = 5,
};

struct Sub {
  int32_t id;
  int32_t owner;
  int32_t behaviour;
  int fd;           // timerfd / user fd; -1 for signal subs
  Kind base_kind;   // kTimer, kSignal, or kFdRead for user fds
  bool owns_fd;     // close(fd) on unsubscribe (timers)
  bool oneshot;
  bool noisy;
  int signum;       // for signals
};

struct Loop {
  int epfd = -1;
  int wakefd = -1;   // eventfd: wake/stop the loop
  int sigpipe[2] = {-1, -1};  // handler → loop self-pipe (≙ epoll.c:54)
  std::thread thread;
  std::atomic<bool> running{false};
  ponyx_mpscq_t* events = nullptr;
  std::mutex mu;  // guards subs + next_id
  std::unordered_map<int32_t, Sub*> subs;
  std::unordered_map<int, int32_t> by_fd;
  std::unordered_map<int, int32_t> by_signum;
  int32_t next_id = 1;
  std::atomic<int64_t> noisy{0};
  // Event-arrival wait (ponyx_asio_wait): lets the host driver BLOCK
  // until the epoll thread queues an event instead of poll-sleeping —
  // ≙ a sleeping scheduler woken by the ASIO thread
  // (ponyint_sched_maybe_wakeup from asio, scheduler.c:1427-1476).
  std::mutex wmu;
  std::condition_variable wcv;
};

// Process-wide signal routing: the async-signal-safe handler writes the
// signum to the owning loop's pipe (one owner per signum). NSIG-sized
// flat arrays keep the handler free of locks and allocation.
std::atomic<int> g_sig_pipe_w[NSIG];
struct sigaction g_sig_prev[NSIG];

void signal_handler(int signum) {
  int fd = g_sig_pipe_w[signum].load(std::memory_order_relaxed);
  if (fd >= 0) {
    int32_t v = signum;
    (void)!write(fd, &v, sizeof(v));
  }
}

void push_event(Loop* l, const Sub* s, Kind kind, int32_t arg,
                int32_t flags) {
  int32_t w[6] = {s->id, s->owner, s->behaviour, kind, arg, flags};
  ponyx_mpscq_push(l->events, w, 6);
  // Wake a blocked ponyx_asio_wait. The empty critical section orders
  // the push before the waiter's predicate re-check (no lost wakeup).
  { std::lock_guard<std::mutex> g(l->wmu); }
  l->wcv.notify_one();
}

void loop_main(Loop* l) {
  constexpr int kMax = 64;
  struct epoll_event evs[kMax];
  while (l->running.load(std::memory_order_acquire)) {
    int n = epoll_wait(l->epfd, evs, kMax, -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      uint32_t e = evs[i].events;
      if (fd == l->wakefd) {
        uint64_t v;
        (void)!read(l->wakefd, &v, sizeof(v));
        continue;
      }
      if (fd == l->sigpipe[0]) {
        int32_t signum;
        while (read(l->sigpipe[0], &signum, sizeof(signum)) ==
               ssize_t(sizeof(signum))) {
          Sub copy;
          bool have = false;
          {
            std::lock_guard<std::mutex> lock(l->mu);
            auto it = l->by_signum.find(signum);
            if (it != l->by_signum.end()) {
              copy = *l->subs[it->second];
              have = true;
            }
          }
          if (have) push_event(l, &copy, kSignal, signum, 0);
        }
        continue;
      }
      Sub copy;
      Sub* retired = nullptr;
      {
        std::lock_guard<std::mutex> lock(l->mu);
        auto it = l->by_fd.find(fd);
        if (it == l->by_fd.end()) continue;
        Sub* s = l->subs[it->second];
        copy = *s;
        if (s->oneshot) {
          l->subs.erase(s->id);
          l->by_fd.erase(fd);
          epoll_ctl(l->epfd, EPOLL_CTL_DEL, fd, nullptr);
          retired = s;
        }
      }
      switch (copy.base_kind) {
        case kTimer: {
          uint64_t expirations = 0;
          (void)!read(copy.fd, &expirations, sizeof(expirations));
          push_event(l, &copy, kTimer, int32_t(expirations), 0);
          break;
        }
        case kSignal:  // unreachable: signals arrive via sigpipe
          break;
        default: {
          if (e & (EPOLLIN | EPOLLPRI))
            push_event(l, &copy, kFdRead, copy.fd, int32_t(e));
          if (e & EPOLLOUT)
            push_event(l, &copy, kFdWrite, copy.fd, int32_t(e));
          if (e & (EPOLLHUP | EPOLLERR))
            push_event(l, &copy, kFdHup, copy.fd, int32_t(e));
          break;
        }
      }
      if (retired != nullptr) {
        if (copy.owns_fd) close(copy.fd);
        if (copy.noisy) l->noisy.fetch_sub(1, std::memory_order_relaxed);
        ponyx_pool_free(sizeof(Sub), retired);
      }
    }
  }
}

int32_t add_sub(Loop* l, Sub* s, uint32_t epoll_flags) {
  std::lock_guard<std::mutex> lock(l->mu);
  s->id = l->next_id++;
  struct epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = epoll_flags;
  ev.data.fd = s->fd;
  if (epoll_ctl(l->epfd, EPOLL_CTL_ADD, s->fd, &ev) != 0) {
    int32_t err = -errno;
    if (s->owns_fd) close(s->fd);
    ponyx_pool_free(sizeof(Sub), s);
    return err;
  }
  l->subs[s->id] = s;
  l->by_fd[s->fd] = s->id;
  if (s->noisy) l->noisy.fetch_add(1, std::memory_order_relaxed);
  return s->id;
}

}  // namespace

extern "C" {

typedef struct Loop ponyx_asio_t;

ponyx_asio_t* ponyx_asio_create() {
  static std::once_flag once;
  std::call_once(once, [] {
    for (int i = 0; i < NSIG; i++) g_sig_pipe_w[i].store(-1);
  });
  auto* l = new Loop();
  l->epfd = epoll_create1(EPOLL_CLOEXEC);
  l->wakefd = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  if (pipe2(l->sigpipe, O_CLOEXEC | O_NONBLOCK) != 0)
    l->sigpipe[0] = l->sigpipe[1] = -1;
  l->events = ponyx_mpscq_create();
  struct epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLIN;
  ev.data.fd = l->wakefd;
  epoll_ctl(l->epfd, EPOLL_CTL_ADD, l->wakefd, &ev);
  if (l->sigpipe[0] >= 0) {
    ev.data.fd = l->sigpipe[0];
    epoll_ctl(l->epfd, EPOLL_CTL_ADD, l->sigpipe[0], &ev);
  }
  l->running.store(true, std::memory_order_release);
  l->thread = std::thread(loop_main, l);
  return l;
}

// Set the event-loop thread's affinity to a core set (≙ --ponypinasio,
// start.c:75-94 + ponyint_cpu_affinity, sched/cpu.c:278): latency-
// sensitive deployments keep the I/O thread off the busy cores — or
// restore the full mask when only the DRIVER thread is pinned (new
// threads inherit the creator's mask). Returns 0 on success, -errno
// otherwise (this file's convention).
int32_t ponyx_asio_setaffinity(ponyx_asio_t* l, const int32_t* cores,
                               int32_t n) {
  if (n <= 0 || cores == nullptr) return -EINVAL;
  cpu_set_t set;
  CPU_ZERO(&set);
  bool any = false;
  for (int32_t i = 0; i < n; i++) {
    if (cores[i] >= 0 && cores[i] < CPU_SETSIZE) {
      CPU_SET(cores[i], &set);
      any = true;
    }
  }
  if (!any) return -EINVAL;
  int err = pthread_setaffinity_np(l->thread.native_handle(),
                                   sizeof(set), &set);
  return err ? -err : 0;
}

void ponyx_asio_destroy(ponyx_asio_t* l) {
  l->running.store(false, std::memory_order_release);
  uint64_t one = 1;
  (void)!write(l->wakefd, &one, sizeof(one));
  l->thread.join();
  {
    std::lock_guard<std::mutex> lock(l->mu);
    for (auto& kv : l->subs) {
      Sub* s = kv.second;
      if (s->base_kind == kSignal) {
        g_sig_pipe_w[s->signum].store(-1, std::memory_order_relaxed);
        sigaction(s->signum, &g_sig_prev[s->signum], nullptr);
      } else {
        epoll_ctl(l->epfd, EPOLL_CTL_DEL, s->fd, nullptr);
      }
      if (s->owns_fd) close(s->fd);
      ponyx_pool_free(sizeof(Sub), s);
    }
    l->subs.clear();
    l->by_fd.clear();
    l->by_signum.clear();
  }
  close(l->wakefd);
  if (l->sigpipe[0] >= 0) {
    close(l->sigpipe[0]);
    close(l->sigpipe[1]);
  }
  close(l->epfd);
  ponyx_mpscq_destroy(l->events);
  delete l;
}

// Periodic or one-shot timer; interval in nanoseconds.
// ≙ the reference's timer events (epoll.c:328-375). Returns sub id (<0 =
// -errno).
int32_t ponyx_asio_timer(ponyx_asio_t* l, int64_t first_ns,
                         int64_t interval_ns, int32_t owner,
                         int32_t behaviour, int32_t oneshot,
                         int32_t noisy) {
  int fd = timerfd_create(CLOCK_MONOTONIC, TFD_CLOEXEC | TFD_NONBLOCK);
  if (fd < 0) return -errno;
  struct itimerspec its;
  its.it_value.tv_sec = first_ns / 1000000000;
  its.it_value.tv_nsec = first_ns % 1000000000;
  its.it_interval.tv_sec = oneshot ? 0 : interval_ns / 1000000000;
  its.it_interval.tv_nsec = oneshot ? 0 : interval_ns % 1000000000;
  if (timerfd_settime(fd, 0, &its, nullptr) != 0) {
    int e = -errno;
    close(fd);
    return e;
  }
  auto* s = static_cast<Sub*>(ponyx_pool_alloc(sizeof(Sub)));
  *s = Sub{0, owner, behaviour, fd, kTimer, true, oneshot != 0,
           noisy != 0, 0};
  return add_sub(l, s, EPOLLIN);
}

// Signal subscription: installs the self-pipe handler for `signum` and
// routes deliveries to this loop (≙ the reference's handler scheme,
// epoll.c:54-133). One subscriber per signum per process.
int32_t ponyx_asio_signal(ponyx_asio_t* l, int32_t signum, int32_t owner,
                          int32_t behaviour, int32_t noisy) {
  if (signum <= 0 || signum >= NSIG) return -EINVAL;
  auto* s = static_cast<Sub*>(ponyx_pool_alloc(sizeof(Sub)));
  *s = Sub{0, owner, behaviour, -1, kSignal, false, false, noisy != 0,
           signum};
  {
    std::lock_guard<std::mutex> lock(l->mu);
    if (l->by_signum.count(signum)) {
      ponyx_pool_free(sizeof(Sub), s);
      return -EBUSY;
    }
    s->id = l->next_id++;
    l->subs[s->id] = s;
    l->by_signum[signum] = s->id;
  }
  if (s->noisy) l->noisy.fetch_add(1, std::memory_order_relaxed);
  g_sig_pipe_w[signum].store(l->sigpipe[1], std::memory_order_relaxed);
  struct sigaction sa;
  std::memset(&sa, 0, sizeof(sa));
  sa.sa_handler = signal_handler;
  sigemptyset(&sa.sa_mask);
  sa.sa_flags = SA_RESTART;
  sigaction(signum, &sa, &g_sig_prev[signum]);
  return s->id;
}

// Arbitrary fd (socket, pipe, stdin). interest: 1=read 2=write 3=both.
// Edge-triggered (≙ the reference arming epoll with EPOLLET for sockets,
// epoll.c): one event per readiness *transition*, so a ready-but-undrained
// fd cannot storm the event queue between host polls. Consumers must
// drain to EAGAIN — which the net layer's accept/recv loops do.
int32_t ponyx_asio_fd(ponyx_asio_t* l, int32_t fd, int32_t interest,
                      int32_t owner, int32_t behaviour, int32_t oneshot,
                      int32_t noisy) {
  uint32_t flags = EPOLLET;
  if (interest & 1) flags |= EPOLLIN;
  if (interest & 2) flags |= EPOLLOUT;
  flags |= EPOLLRDHUP;
  auto* s = static_cast<Sub*>(ponyx_pool_alloc(sizeof(Sub)));
  *s = Sub{0, owner, behaviour, fd, kFdRead, false, oneshot != 0,
           noisy != 0, 0};
  return add_sub(l, s, flags);
}

// Change a live fd subscription's interest set (1=read 2=write 3=both);
// ≙ pony_asio_event_resubscribe_read/write (asio/event.c) — the
// reference's way of arming write-readiness only while writes are
// pending, which is also exactly what the net layer does here.
int32_t ponyx_asio_fd_interest(ponyx_asio_t* l, int32_t sub_id,
                               int32_t interest) {
  std::lock_guard<std::mutex> lock(l->mu);
  auto it = l->subs.find(sub_id);
  if (it == l->subs.end()) return -ENOENT;
  Sub* s = it->second;
  if (s->fd < 0) return -EINVAL;
  struct epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLET | EPOLLRDHUP;
  if (interest & 1) ev.events |= EPOLLIN;
  if (interest & 2) ev.events |= EPOLLOUT;
  ev.data.fd = s->fd;
  // MOD re-arms: if the fd is already ready for the new interest the
  // kernel delivers a fresh edge — the property the write path relies on.
  if (epoll_ctl(l->epfd, EPOLL_CTL_MOD, s->fd, &ev) != 0) return -errno;
  return 0;
}

// ≙ pony_asio_event_unsubscribe (asio/event.c).
int32_t ponyx_asio_unsubscribe(ponyx_asio_t* l, int32_t sub_id) {
  std::lock_guard<std::mutex> lock(l->mu);
  auto it = l->subs.find(sub_id);
  if (it == l->subs.end()) return 0;
  Sub* s = it->second;
  if (s->base_kind == kSignal) {
    g_sig_pipe_w[s->signum].store(-1, std::memory_order_relaxed);
    sigaction(s->signum, &g_sig_prev[s->signum], nullptr);
    l->by_signum.erase(s->signum);
  } else {
    epoll_ctl(l->epfd, EPOLL_CTL_DEL, s->fd, nullptr);
    l->by_fd.erase(s->fd);
  }
  l->subs.erase(it);
  if (s->noisy) l->noisy.fetch_sub(1, std::memory_order_relaxed);
  if (s->owns_fd) close(s->fd);
  ponyx_pool_free(sizeof(Sub), s);
  return 1;
}

// Drain up to `max_events` pending events into `out` ([max_events, 6]
// row-major int32). Returns the number of events written. Called by the
// host driver at step boundaries — the single consumer.
int32_t ponyx_asio_drain(ponyx_asio_t* l, int32_t* out,
                         int32_t max_events) {
  int32_t n = 0;
  while (n < max_events) {
    int32_t r = ponyx_mpscq_pop(l->events, out + n * 6, 6);
    if (r <= 0) break;
    n++;
  }
  return n;
}

int64_t ponyx_asio_pending(ponyx_asio_t* l) {
  return ponyx_mpscq_count(l->events);
}

// Block the calling (host-driver) thread until the event queue is
// non-empty or `timeout_ms` passes; returns 1 if events are pending.
// ≙ a quiescing scheduler blocking until the ASIO thread wakes it
// (perhaps_suspend_scheduler / ponyint_sched_maybe_wakeup) — the host
// loop uses this instead of backoff poll-sleeps when the only pending
// work is external I/O.
int32_t ponyx_asio_wait(ponyx_asio_t* l, int32_t timeout_ms) {
  if (ponyx_mpscq_count(l->events) > 0) return 1;
  std::unique_lock<std::mutex> lk(l->wmu);
  l->wcv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                  [l] { return ponyx_mpscq_count(l->events) > 0; });
  return ponyx_mpscq_count(l->events) > 0 ? 1 : 0;
}

// ≙ ponyint_asio_noisy_add/remove + count (asio.c:80-91): subscriptions
// register their own noisiness; apps may add manual holds too.
void ponyx_asio_noisy_add(ponyx_asio_t* l) {
  l->noisy.fetch_add(1, std::memory_order_relaxed);
}

void ponyx_asio_noisy_remove(ponyx_asio_t* l) {
  l->noisy.fetch_sub(1, std::memory_order_relaxed);
}

int64_t ponyx_asio_noisy_count(ponyx_asio_t* l) {
  return l->noisy.load(std::memory_order_relaxed);
}

}  // extern "C"
