// Non-blocking socket operations for host-resident network actors.
//
// TPU-native counterpart of the reference's OS socket layer
// (src/libponyrt/lang/socket.c, 5112 LoC): listen/accept/connect for TCP
// (≙ pony_os_listen_tcp socket.c:693, pony_os_accept, pony_os_connect_tcp),
// scatter-free recv/send (≙ pony_os_recv/send), UDP sockets with
// sendto/recvfrom (≙ pony_os_listen_udp/sendto/recvfrom), socket options
// (nodelay/keepalive ≙ pony_os_nodelay/keepalive), and local/peer name
// introspection. All sockets are created O_NONBLOCK and are meant to be
// subscribed to the asio loop (asio.cc) — the same split the reference
// uses (socket fd ←→ ASIO event ←→ owning actor).
//
// Error convention: >= 0 success value, < 0 is -errno. EAGAIN/EWOULDBLOCK
// surface as -EAGAIN so callers can wait for the next readiness event.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <net/if.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>

namespace {

int set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  if (fl < 0) return -errno;
  if (fcntl(fd, F_SETFL, fl | O_NONBLOCK) < 0) return -errno;
  return 0;
}

// Resolve host:port; tries each result until the operation succeeds.
// op: 0 = bind (listen/UDP), 1 = connect.
int resolve_and(int socktype, const char* host, int32_t port, int op,
                int backlog) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = socktype;
  hints.ai_flags = (op == 0) ? AI_PASSIVE : 0;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  struct addrinfo* res = nullptr;
  int rc = getaddrinfo((host && host[0]) ? host : nullptr, portstr, &hints,
                       &res);
  if (rc != 0) return -EHOSTUNREACH;
  int last_err = -ECONNREFUSED;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    int fd = socket(ai->ai_family, ai->ai_socktype | SOCK_CLOEXEC,
                    ai->ai_protocol);
    if (fd < 0) {
      last_err = -errno;
      continue;
    }
    int e = set_nonblock(fd);
    if (e < 0) {
      close(fd);
      last_err = e;
      continue;
    }
    if (op == 0) {
      int one = 1;
      setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      if (bind(fd, ai->ai_addr, ai->ai_addrlen) != 0) {
        last_err = -errno;
        close(fd);
        continue;
      }
      if (socktype == SOCK_STREAM && listen(fd, backlog) != 0) {
        last_err = -errno;
        close(fd);
        continue;
      }
      freeaddrinfo(res);
      return fd;
    }
    // connect: in-progress is success for a non-blocking socket — the
    // asio write-readiness event signals completion (≙ the reference's
    // connect flow, socket.c pony_os_connect_tcp + ASIO_WRITE).
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0 ||
        errno == EINPROGRESS) {
      freeaddrinfo(res);
      return fd;
    }
    last_err = -errno;
    close(fd);
  }
  freeaddrinfo(res);
  return last_err;
}

}  // namespace

extern "C" {

// ≙ pony_os_listen_tcp (socket.c:693). Returns listening fd or -errno.
int32_t ponyx_os_listen_tcp(const char* host, int32_t port,
                            int32_t backlog) {
  return resolve_and(SOCK_STREAM, host, port, 0, backlog > 0 ? backlog : 64);
}

// ≙ pony_os_connect_tcp: non-blocking connect, completion via ASIO write
// event. Returns fd (connection may still be in progress) or -errno.
int32_t ponyx_os_connect_tcp(const char* host, int32_t port) {
  return resolve_and(SOCK_STREAM, host, port, 1, 0);
}

// ≙ pony_os_accept: returns new non-blocking connection fd, -EAGAIN when
// the backlog is drained, other -errno on error.
int32_t ponyx_os_accept(int32_t listen_fd) {
  int fd = accept4(listen_fd, nullptr, nullptr,
                   SOCK_NONBLOCK | SOCK_CLOEXEC);
  if (fd < 0) {
    int e = errno;
    return (e == EAGAIN || e == EWOULDBLOCK) ? -EAGAIN : -e;
  }
  return fd;
}

// Did a non-blocking connect finish successfully? 0 yes, else -errno
// (≙ the reference checking SO_ERROR at the writeable event).
int32_t ponyx_os_connect_result(int32_t fd) {
  int err = 0;
  socklen_t len = sizeof(err);
  if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0) return -errno;
  return -err;
}

// ≙ pony_os_recv. Returns bytes read, 0 = orderly shutdown → -ESHUTDOWN,
// -EAGAIN when drained.
int32_t ponyx_os_recv(int32_t fd, uint8_t* buf, int32_t len) {
  ssize_t n = recv(fd, buf, size_t(len), 0);
  if (n > 0) return int32_t(n);
  if (n == 0) return -ESHUTDOWN;
  int e = errno;
  return (e == EAGAIN || e == EWOULDBLOCK) ? -EAGAIN : -e;
}

// ≙ pony_os_send. Returns bytes written (may be short) or -errno.
int32_t ponyx_os_send(int32_t fd, const uint8_t* buf, int32_t len) {
  ssize_t n = send(fd, buf, size_t(len), MSG_NOSIGNAL);
  if (n >= 0) return int32_t(n);
  int e = errno;
  return (e == EAGAIN || e == EWOULDBLOCK) ? -EAGAIN : -e;
}

// UDP socket bound to host:port (port 0 = ephemeral); ≙ pony_os_listen_udp.
int32_t ponyx_os_udp(const char* host, int32_t port) {
  return resolve_and(SOCK_DGRAM, host, port, 0, 0);
}

// ≙ pony_os_sendto (IPv4/IPv6 by dotted/numeric host).
int32_t ponyx_os_sendto(int32_t fd, const uint8_t* buf, int32_t len,
                        const char* host, int32_t port) {
  struct sockaddr_storage ss;
  socklen_t slen;
  memset(&ss, 0, sizeof(ss));
  struct sockaddr_in* a4 = reinterpret_cast<struct sockaddr_in*>(&ss);
  struct sockaddr_in6* a6 = reinterpret_cast<struct sockaddr_in6*>(&ss);
  if (inet_pton(AF_INET, host, &a4->sin_addr) == 1) {
    a4->sin_family = AF_INET;
    a4->sin_port = htons(uint16_t(port));
    slen = sizeof(*a4);
  } else if (inet_pton(AF_INET6, host, &a6->sin6_addr) == 1) {
    a6->sin6_family = AF_INET6;
    a6->sin6_port = htons(uint16_t(port));
    slen = sizeof(*a6);
  } else {
    return -EINVAL;
  }
  ssize_t n = sendto(fd, buf, size_t(len), MSG_NOSIGNAL,
                     reinterpret_cast<struct sockaddr*>(&ss), slen);
  if (n >= 0) return int32_t(n);
  int e = errno;
  return (e == EAGAIN || e == EWOULDBLOCK) ? -EAGAIN : -e;
}

// ≙ pony_os_recvfrom: fills buf; writes sender "ip" into addr_out
// (addr_cap bytes, NUL-terminated) and the port into *port_out.
int32_t ponyx_os_recvfrom(int32_t fd, uint8_t* buf, int32_t len,
                          char* addr_out, int32_t addr_cap,
                          int32_t* port_out) {
  struct sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  ssize_t n = recvfrom(fd, buf, size_t(len), 0,
                       reinterpret_cast<struct sockaddr*>(&ss), &slen);
  if (n < 0) {
    int e = errno;
    return (e == EAGAIN || e == EWOULDBLOCK) ? -EAGAIN : -e;
  }
  if (addr_out != nullptr && addr_cap > 0) {
    addr_out[0] = 0;
    if (ss.ss_family == AF_INET) {
      auto* a = reinterpret_cast<struct sockaddr_in*>(&ss);
      inet_ntop(AF_INET, &a->sin_addr, addr_out, addr_cap);
      if (port_out) *port_out = ntohs(a->sin_port);
    } else if (ss.ss_family == AF_INET6) {
      auto* a = reinterpret_cast<struct sockaddr_in6*>(&ss);
      inet_ntop(AF_INET6, &a->sin6_addr, addr_out, addr_cap);
      if (port_out) *port_out = ntohs(a->sin6_port);
    }
  }
  return int32_t(n);
}

// Local/peer port (useful for ephemeral listens); ≙ pony_os_sockname /
// pony_os_peername. Returns port or -errno.
int32_t ponyx_os_sockname_port(int32_t fd) {
  struct sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&ss), &slen) != 0)
    return -errno;
  if (ss.ss_family == AF_INET)
    return ntohs(reinterpret_cast<struct sockaddr_in*>(&ss)->sin_port);
  if (ss.ss_family == AF_INET6)
    return ntohs(reinterpret_cast<struct sockaddr_in6*>(&ss)->sin6_port);
  return -EAFNOSUPPORT;
}

int32_t ponyx_os_peername_port(int32_t fd) {
  struct sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&ss), &slen) != 0)
    return -errno;
  if (ss.ss_family == AF_INET)
    return ntohs(reinterpret_cast<struct sockaddr_in*>(&ss)->sin_port);
  if (ss.ss_family == AF_INET6)
    return ntohs(reinterpret_cast<struct sockaddr_in6*>(&ss)->sin6_port);
  return -EAFNOSUPPORT;
}

// ≙ pony_os_nodelay / pony_os_keepalive (socket.c).
int32_t ponyx_os_nodelay(int32_t fd, int32_t on) {
  int v = on ? 1 : 0;
  if (setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &v, sizeof(v)) != 0)
    return -errno;
  return 0;
}

int32_t ponyx_os_keepalive(int32_t fd, int32_t secs) {
  int on = secs > 0 ? 1 : 0;
  if (setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &on, sizeof(on)) != 0)
    return -errno;
  if (on) {
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &secs, sizeof(secs));
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &secs, sizeof(secs));
  }
  return 0;
}

// ≙ pony_os_socket_shutdown / close.
int32_t ponyx_os_shutdown(int32_t fd) {
  if (shutdown(fd, SHUT_WR) != 0) return -errno;
  return 0;
}

int32_t ponyx_os_close(int32_t fd) {
  if (close(fd) != 0) return -errno;
  return 0;
}

// Scatter-gather write (≙ pony_os_writev + the reference's iovec chunk
// lists, socket.c — stdlib writev sends a chunk list without flattening).
// bufs/lens describe n chunks; returns total bytes written (possibly
// short, ending mid-chunk) or -errno.
int32_t ponyx_os_writev(int32_t fd, const uint8_t** bufs,
                        const int32_t* lens, int32_t n) {
  if (n <= 0) return 0;
  if (n > 64) n = 64;                    // IOV_MAX-safe static bound
  struct iovec iov[64];
  for (int i = 0; i < n; i++) {
    iov[i].iov_base = const_cast<uint8_t*>(bufs[i]);
    iov[i].iov_len = size_t(lens[i]);
  }
  struct msghdr mh;
  memset(&mh, 0, sizeof(mh));
  mh.msg_iov = iov;
  mh.msg_iovlen = size_t(n);
  ssize_t w = sendmsg(fd, &mh, MSG_NOSIGNAL);
  if (w >= 0) return int32_t(w);
  int e = errno;
  return (e == EAGAIN || e == EWOULDBLOCK) ? -EAGAIN : -e;
}

namespace {

// Multicast group membership, IPv4 or IPv6 by the group address family
// (≙ pony_os_multicast_join / pony_os_multicast_leave, socket.c —
// which also dispatch on family). iface: interface address (IPv4) or
// index name (IPv6), empty = any.
int32_t multicast_op(int32_t fd, const char* group, const char* iface,
                     bool join) {
  struct in_addr g4;
  struct in6_addr g6;
  if (inet_pton(AF_INET, group, &g4) == 1) {
    struct ip_mreq req;
    memset(&req, 0, sizeof(req));
    req.imr_multiaddr = g4;
    if (iface && iface[0]) {
      if (inet_pton(AF_INET, iface, &req.imr_interface) != 1)
        return -EINVAL;
    } else {
      req.imr_interface.s_addr = htonl(INADDR_ANY);
    }
    int op = join ? IP_ADD_MEMBERSHIP : IP_DROP_MEMBERSHIP;
    if (setsockopt(fd, IPPROTO_IP, op, &req, sizeof(req)) != 0)
      return -errno;
    return 0;
  }
  if (inet_pton(AF_INET6, group, &g6) == 1) {
    struct ipv6_mreq req;
    memset(&req, 0, sizeof(req));
    req.ipv6mr_multiaddr = g6;
    if (iface && iface[0]) {
      unsigned idx = if_nametoindex(iface);
      if (idx == 0) return -EINVAL;
      req.ipv6mr_interface = idx;
    } else {
      req.ipv6mr_interface = 0;         // any
    }
    int op = join ? IPV6_JOIN_GROUP : IPV6_LEAVE_GROUP;
    if (setsockopt(fd, IPPROTO_IPV6, op, &req, sizeof(req)) != 0)
      return -errno;
    return 0;
  }
  return -EINVAL;
}

// The socket's address family (for v4/v6 option dispatch below).
int sock_family(int fd) {
  int dom = 0;
  socklen_t len = sizeof(dom);
  if (getsockopt(fd, SOL_SOCKET, SO_DOMAIN, &dom, &len) != 0)
    return -errno;
  return dom;
}

// Family-aware name formatting shared by sockname/peername.
int32_t format_name(struct sockaddr_storage* ss, char* addr_out,
                    int32_t addr_cap, int32_t* port_out) {
  if (addr_out == nullptr || addr_cap < 2) return -EINVAL;
  addr_out[0] = 0;
  if (ss->ss_family == AF_INET) {
    auto* a = reinterpret_cast<struct sockaddr_in*>(ss);
    inet_ntop(AF_INET, &a->sin_addr, addr_out, addr_cap);
    if (port_out) *port_out = ntohs(a->sin_port);
    return 0;
  }
  if (ss->ss_family == AF_INET6) {
    auto* a = reinterpret_cast<struct sockaddr_in6*>(ss);
    inet_ntop(AF_INET6, &a->sin6_addr, addr_out, addr_cap);
    if (port_out) *port_out = ntohs(a->sin6_port);
    return 0;
  }
  return -EAFNOSUPPORT;
}

}  // namespace

int32_t ponyx_os_multicast_join(int32_t fd, const char* group,
                                const char* iface) {
  return multicast_op(fd, group, iface, true);
}

int32_t ponyx_os_multicast_leave(int32_t fd, const char* group,
                                 const char* iface) {
  return multicast_op(fd, group, iface, false);
}

// ≙ pony_os_multicast_ttl / _loopback (socket.c): scope + self-delivery
// of outgoing multicast datagrams; dispatched on the socket family like
// the join path (IPv6 wants IPPROTO_IPV6 hop-limit/loop options).
int32_t ponyx_os_multicast_ttl(int32_t fd, int32_t ttl) {
  int fam = sock_family(fd);
  if (fam < 0) return fam;
  if (fam == AF_INET6) {
    int v = ttl;
    if (setsockopt(fd, IPPROTO_IPV6, IPV6_MULTICAST_HOPS, &v,
                   sizeof(v)) != 0)
      return -errno;
    return 0;
  }
  unsigned char v = (unsigned char)ttl;
  if (setsockopt(fd, IPPROTO_IP, IP_MULTICAST_TTL, &v, sizeof(v)) != 0)
    return -errno;
  return 0;
}

int32_t ponyx_os_multicast_loopback(int32_t fd, int32_t on) {
  int fam = sock_family(fd);
  if (fam < 0) return fam;
  if (fam == AF_INET6) {
    int v = on ? 1 : 0;
    if (setsockopt(fd, IPPROTO_IPV6, IPV6_MULTICAST_LOOP, &v,
                   sizeof(v)) != 0)
      return -errno;
    return 0;
  }
  unsigned char v = on ? 1 : 0;
  if (setsockopt(fd, IPPROTO_IP, IP_MULTICAST_LOOP, &v, sizeof(v)) != 0)
    return -errno;
  return 0;
}

// ≙ pony_os_broadcast.
int32_t ponyx_os_broadcast(int32_t fd, int32_t on) {
  int v = on ? 1 : 0;
  if (setsockopt(fd, SOL_SOCKET, SO_BROADCAST, &v, sizeof(v)) != 0)
    return -errno;
  return 0;
}

// Generic int-valued socket options (≙ the reference's ~600-line
// per-option get/getsockopt surface, socket.c pony_os_getsockopt* —
// collapsed to one pair since options are (level, name, int) triples).
int32_t ponyx_os_setsockopt_int(int32_t fd, int32_t level, int32_t name,
                                int32_t value) {
  if (setsockopt(fd, level, name, &value, sizeof(value)) != 0)
    return -errno;
  return 0;
}

int32_t ponyx_os_getsockopt_int(int32_t fd, int32_t level, int32_t name,
                                int32_t* value_out) {
  int v = 0;
  socklen_t len = sizeof(v);
  if (getsockopt(fd, level, name, &v, &len) != 0) return -errno;
  if (value_out) *value_out = v;
  return 0;
}

// Full local/peer names: "addr" string (IPv4 dotted or IPv6 hex) + port
// (≙ pony_os_sockname / pony_os_peername with their IPv6 handling).
int32_t ponyx_os_sockname(int32_t fd, char* addr_out, int32_t addr_cap,
                          int32_t* port_out) {
  struct sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&ss), &slen) != 0)
    return -errno;
  return format_name(&ss, addr_out, addr_cap, port_out);
}

int32_t ponyx_os_peername(int32_t fd, char* addr_out, int32_t addr_cap,
                          int32_t* port_out) {
  struct sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&ss), &slen) != 0)
    return -errno;
  return format_name(&ss, addr_out, addr_cap, port_out);
}

}  // extern "C"
