#include "mpscq.h"

#include <atomic>
#include <cstring>

#include "pool.h"

namespace {

struct Node {
  std::atomic<Node*> next;
  int32_t nwords;
  int32_t words[];  // flexible payload

  static size_t bytes(int32_t nwords) {
    return sizeof(Node) + size_t(nwords) * sizeof(int32_t);
  }
};

}  // namespace

struct ponyx_mpscq {
  // head = producer end, tail = consumer end; stub node makes the queue
  // intrusive and lock-free exactly as messageq.c:31-100 does (head tag
  // bit tricks are unnecessary here: emptiness is detected by the
  // consumer seeing next == nullptr, and the "empty" transition never
  // needs to reschedule anything — the host driver polls).
  std::atomic<Node*> head;
  Node* tail;
  Node* stub;
  std::atomic<int64_t> count;
};

extern "C" {

ponyx_mpscq_t* ponyx_mpscq_create() {
  auto* q = static_cast<ponyx_mpscq_t*>(
      ponyx_pool_alloc(sizeof(ponyx_mpscq_t)));
  q->stub = static_cast<Node*>(ponyx_pool_alloc(Node::bytes(0)));
  q->stub->next.store(nullptr, std::memory_order_relaxed);
  q->stub->nwords = 0;
  q->head.store(q->stub, std::memory_order_relaxed);
  q->tail = q->stub;
  q->count.store(0, std::memory_order_relaxed);
  return q;
}

void ponyx_mpscq_destroy(ponyx_mpscq_t* q) {
  int32_t sink[1];
  while (true) {
    int32_t r = ponyx_mpscq_pop(q, sink, 0);
    if (r == 0) break;
    if (r < 0) {  // drain oversized message by popping with enough room
      int32_t need = -r;
      auto* buf = static_cast<int32_t*>(
          ponyx_pool_alloc(size_t(need) * sizeof(int32_t)));
      ponyx_mpscq_pop(q, buf, need);
      ponyx_pool_free(size_t(need) * sizeof(int32_t), buf);
    }
  }
  if (q->tail != q->stub)  // last consumed node is retired lazily
    ponyx_pool_free(Node::bytes(q->tail->nwords), q->tail);
  ponyx_pool_free(Node::bytes(0), q->stub);
  ponyx_pool_free(sizeof(ponyx_mpscq_t), q);
}

void ponyx_mpscq_push(ponyx_mpscq_t* q, const int32_t* words,
                      int32_t nwords) {
  auto* n = static_cast<Node*>(ponyx_pool_alloc(Node::bytes(nwords)));
  n->nwords = nwords;
  std::memcpy(n->words, words, size_t(nwords) * sizeof(int32_t));
  n->next.store(nullptr, std::memory_order_relaxed);
  Node* prev = q->head.exchange(n, std::memory_order_acq_rel);
  prev->next.store(n, std::memory_order_release);
  q->count.fetch_add(1, std::memory_order_relaxed);
}

int32_t ponyx_mpscq_pop(ponyx_mpscq_t* q, int32_t* out, int32_t cap) {
  Node* tail = q->tail;
  Node* next = tail->next.load(std::memory_order_acquire);
  if (next == nullptr) return 0;
  if (next->nwords > cap) return -next->nwords;
  std::memcpy(out, next->words, size_t(next->nwords) * sizeof(int32_t));
  int32_t n = next->nwords;
  q->tail = next;
  if (tail != q->stub)
    ponyx_pool_free(Node::bytes(tail->nwords), tail);
  // `next` becomes the new stub-position node; freed on the following pop.
  q->count.fetch_sub(1, std::memory_order_relaxed);
  return n;
}

int64_t ponyx_mpscq_count(ponyx_mpscq_t* q) {
  return q->count.load(std::memory_order_relaxed);
}

}  // extern "C"
