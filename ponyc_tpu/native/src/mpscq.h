// Intrusive Vyukov MPSC queue with a message-count gauge.
//
// TPU-native counterpart of the reference's actor mailbox queue
// (src/libponyrt/actor/messageq.{c,h}): many producers (ASIO loop,
// application threads) and one consumer (the host driver draining at
// step boundaries). Here it stages *host-bound* messages only — the
// device-side mailboxes are the dense ring-buffer table in HBM
// (ponyc_tpu/runtime/state.py); this queue replaces the
// ASIO-thread → scheduler-thread hop of the reference
// (asio/event.c pony_asio_event_send → mailbox push).
//
// Messages are flat records of int32 words, pool-allocated:
//   [0] target actor id   [1] behaviour gid   [2..] payload words
#pragma once

#include <cstdint>

extern "C" {

typedef struct ponyx_mpscq ponyx_mpscq_t;

ponyx_mpscq_t* ponyx_mpscq_create();
void ponyx_mpscq_destroy(ponyx_mpscq_t* q);

// Push a message of `nwords` int32 words (copied). Thread-safe.
void ponyx_mpscq_push(ponyx_mpscq_t* q, const int32_t* words, int32_t nwords);

// Pop into `out` (capacity `cap` words); returns the message's word count,
// 0 if empty, or -needed if `cap` was too small (message stays queued).
// Single consumer only.
int32_t ponyx_mpscq_pop(ponyx_mpscq_t* q, int32_t* out, int32_t cap);

// Approximate queue depth (≙ the fork's messageq num_messages counter,
// used for load balancing / analysis).
int64_t ponyx_mpscq_count(ponyx_mpscq_t* q);
}
