"""Overload-resilient traffic front door — batched socket ingress,
telemetry-driven admission control, graceful drain (ROADMAP item 4; ≙
running the runtime as a *service*: the reference's stdlib TCP servers
built over packages/net, operated in the aggregation/coalescing posture
of the PGAS actor-runtime paper in PAPERS.md — survive high fan-in by
batching at the edge and shedding before the mailbox rings wedge).

The tier sits between the `net/` socket layer and the device world:

    TCP/TLS conns ──► FrontDoor (HOST actor: accept/frame)
        │ length-prefixed request frames (wire protocol below)
        ▼
    Server (runtime poller): admission control + deadline checks
        │ bulk_send batches sized by the PR 5 window controller
        ▼
    device worker cohort ── replies ──► Egress (HOST actor)
        │                                   │
        └──── on-device compute ────────────┘
                                            ▼
                         per-connection `Net` writes honouring
                         `pending()` egress backpressure

Robustness is the headline:

- **Admission control** (`AdmissionController`, MIMD like the PR 5
  window controller): a concurrency limit grown ×2 while the device
  telemetry is quiet and fully used, halved when the retired window aux
  votes pressure — qw_p99 past the window length, senders muted
  (mute/backpressure pressure), or spill occupancy climbing. Requests
  beyond the limit (or whose deadline the measured service rate cannot
  meet) are shed AT THE EDGE with a coded BUSY reply instead of being
  queued into a mailbox ring that would answer with a sticky
  SpillOverflow.
- **Deadlines**: every request carries deadline_ms (0 = none); a queued
  request whose deadline passes before submission is shed (DEADLINE
  status) without touching the device.
- **Egress backpressure**: replies ride `Net.send` per connection; a
  connection whose unflushed `pending()` bytes exceed `pending_limit`
  is *choked* — its further requests shed BUSY — and closed past 4×
  (a slow consumer pays, neighbours do not).
- **Causal tracing** (PR 6): with tracing on, each admitted request's
  tag becomes its trace id (`send(..., trace=tag)`), so
  `Runtime.traces()` attributes end-to-end request latency span by
  span. (The traced path submits per-request via the inject lane;
  untraced batches ride `bulk_send`.)
- **Graceful drain**: SIGTERM/`begin_drain()` stops accepting new
  connections and sheds new frames with BUSY while every ADMITTED
  request completes and its reply flushes; connections then close and
  the run loop exits — zero lost replies (tests/test_serve.py).
- **Supervision** (PR 7/8): a wedged world trips the watchdog (code 7)
  and `ponyc_tpu supervise` restarts the service from the newest
  checkpoint; `main()` re-listens on the same port so clients
  reconnect (`supervise.maybe_restore`).

Wire protocol (v1, little-endian i32 words, 4-byte big-endian length
prefix — ≙ the reference stdlib's framed TCP notify pattern):

    frame   := u32_be body_len | body
    request := req_id:i32 | deadline_ms:i32 | payload words...
    reply   := req_id:i32 | status:i32 | value words...

Status codes are `errors.ERROR_CODES` values: 0 OK, 12 BADFRAME
(FrameError), 13 BUSY (ServeBusyError — admission shed, drain, or a
choked connection), 14 DEADLINE (ServeDeadlineError). An undecodable
frame (bad length, non-word body) gets a BADFRAME reply with
req_id=-1 and the connection closes (stream desync is unrecoverable);
a well-framed but wrong-arity request gets BADFRAME and keeps the
connection.

`python -m ponyc_tpu serve` runs the default compute service
(`ServeWorker.handle(tag, x) → 2*x+1`); `ponyc_tpu/loadgen.py` is the
matching load generator + chaos/soak harness, and `bench.py
--serve-smoke` records the standing `serving` BENCH block (p50/p99
end-to-end latency, shed rate, goodput under 2× overload).
"""

from __future__ import annotations

import collections
import signal as _signal
import struct
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from .errors import ERROR_CODES

_HDR = struct.Struct(">I")

# Wire status codes (reply word 1) — the errors.ERROR_CODES values of
# the serve-tier error classes, so operators alert on ONE numbering.
ST_OK = 0
ST_BADFRAME = ERROR_CODES["FrameError"]
ST_BUSY = ERROR_CODES["ServeBusyError"]
ST_DEADLINE = ERROR_CODES["ServeDeadlineError"]

# A connection whose unflushed egress bytes exceed pending_limit is
# choked (requests shed BUSY); past CLOSE_FACTOR x it is closed.
CLOSE_FACTOR = 4

# Reply-latency reservoir (host wall clock, µs): bounded so a soak
# cannot grow it; quantiles come from the newest window.
LAT_RESERVOIR = 8192


class FrameError(RuntimeError):
    """Malformed ingress frame: bad length prefix, non-word body, or a
    body outside [2, 2 + payload] words. Wire status 12."""

    code = ERROR_CODES["FrameError"]


class ServeBusyError(RuntimeError):
    """Admission shed the request at the edge (overload, drain, or a
    choked slow-consumer connection). Wire status 13 — the BUSY reply;
    clients retry with backoff."""

    code = ERROR_CODES["ServeBusyError"]


class ServeDeadlineError(RuntimeError):
    """A request's deadline expired before it could be submitted to
    the device. Wire status 14."""

    code = ERROR_CODES["ServeDeadlineError"]


# ---- framing (shared with loadgen.py and tests) -------------------------

def encode_frame(words) -> bytes:
    """Length-prefix one frame of i32 words."""
    body = np.asarray(words, "<i4").tobytes()
    return _HDR.pack(len(body)) + body


def encode_request(req_id: int, deadline_ms: int, payload) -> bytes:
    return encode_frame([int(req_id), int(deadline_ms),
                         *[int(w) for w in payload]])


def encode_reply(req_id: int, status: int, values=()) -> bytes:
    return encode_frame([int(req_id), int(status),
                         *[int(w) for w in values]])


class Framer:
    """Incremental length-prefix decoder: feed() raw chunks (split or
    coalesced arbitrarily), take whole frames as i32 word arrays.
    Raises FrameError on an oversized or non-word frame — the stream
    is desynced and the connection must close."""

    def __init__(self, max_words: int = 64):
        self.max_bytes = 4 * int(max_words)
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[np.ndarray]:
        self._buf += data
        out: List[np.ndarray] = []
        while True:
            if len(self._buf) < _HDR.size:
                return out
            (n,) = _HDR.unpack_from(self._buf)
            if n > self.max_bytes or n % 4 or n < 4:
                raise FrameError(
                    f"frame body of {n} bytes (max {self.max_bytes}, "
                    "must be a positive multiple of 4)")
            if len(self._buf) < _HDR.size + n:
                return out
            body = bytes(self._buf[_HDR.size:_HDR.size + n])
            del self._buf[:_HDR.size + n]
            out.append(np.frombuffer(body, "<i4"))


# ---- admission control --------------------------------------------------

class AdmissionController:
    """MIMD concurrency limiter fed by on-device telemetry — the edge
    twin of runtime/controller.WindowController. `limit` is how many
    requests may be in flight (queued + on device) at once; observe()
    is deterministic in its arguments (tests replay pressure traces)."""

    def __init__(self, lo: int, hi: int,
                 initial: Optional[int] = None):
        if lo < 1 or hi < lo:
            raise ValueError(f"need 1 <= lo <= hi (got lo={lo}, hi={hi})")
        self.lo, self.hi = int(lo), int(hi)
        self.limit = min(self.hi, max(self.lo, int(initial or hi)))
        self.state = "steady"
        self.grows = self.shrinks = self.holds = 0
        self.recent: collections.deque = collections.deque(maxlen=32)

    def observe(self, *, qw_p99: int, window: int, muted: int,
                spill_frac: float, used: int) -> int:
        """Feed one boundary's facts: the newest retired aux's queue-
        wait p99 and muted-sender count, the spill occupancy fraction,
        and how much of the limit was actually in use. Returns the new
        limit."""
        pressure = (qw_p99 > max(1, window)) or muted > 0 \
            or spill_frac > 0.5
        if pressure:
            self.limit = max(self.lo, self.limit // 2)
            self.state = "shrink"
            self.shrinks += 1
        elif used >= self.limit and self.limit < self.hi:
            # The edge is limit-bound while the device is quiet: grow.
            self.limit = min(self.hi, self.limit * 2)
            self.state = "grow"
            self.grows += 1
        else:
            self.state = "steady"
            self.holds += 1
        self.recent.append((int(qw_p99), int(muted),
                            round(float(spill_frac), 3), int(used),
                            self.limit, self.state))
        return self.limit

    def snapshot(self) -> Dict[str, Any]:
        return {"limit": self.limit, "state": self.state,
                "lo": self.lo, "hi": self.hi, "grows": self.grows,
                "shrinks": self.shrinks, "holds": self.holds}


# ---- the actor types of the default service -----------------------------

@actor
class Egress:
    """HOST reply router: device workers send done(tag, value) here;
    the behaviour hands the reply to the Server, which frames it onto
    the owning connection (honouring Net pending() backpressure)."""

    HOST = True
    n_replies: I32

    @behaviour
    def done(self, st, tag: I32, value: I32):
        srv = getattr(self.rt, "_serve", None)
        if srv is not None:
            srv.complete(int(tag), int(value))
        return {**st, "n_replies": st["n_replies"] + 1}


@actor
class FrontDoor:
    """HOST ingress actor: the net layer's accept/data/close events
    land here and delegate to the Server (acceptor + framer worker)."""

    HOST = True
    n_conns: I32

    @behaviour
    def on_accept(self, st, conn: I32):
        srv = getattr(self.rt, "_serve", None)
        if srv is not None:
            srv._on_accept(int(conn))
        return {**st, "n_conns": st["n_conns"] + 1}

    @behaviour
    def on_data(self, st, conn: I32, data: I32, n: I32):
        srv = getattr(self.rt, "_serve", None)
        payload = self.rt.heap.unbox(data)
        if srv is not None:
            srv._on_data(int(conn), payload)
        return st

    @behaviour
    def on_closed(self, st, conn: I32):
        srv = getattr(self.rt, "_serve", None)
        if srv is not None:
            srv._on_closed(int(conn))
        return st


@actor
class ServeWorker:
    """Default device service: handle(tag, x) replies 2*x+1 (i32 wrap)
    to the egress actor — enough arithmetic that loadgen can verify
    every reply value end-to-end."""

    egress: Ref
    served: I32
    MAX_SENDS = 1

    @behaviour
    def handle(self, st, tag: I32, x: I32):
        self.send(st["egress"], Egress.done, tag, 2 * x + 1)
        return {**st, "served": st["served"] + 1}


class _Request:
    __slots__ = ("tag", "cid", "rid", "deadline_t", "words", "t_in")

    def __init__(self, tag, cid, rid, deadline_t, words, t_in):
        self.tag = tag
        self.cid = cid
        self.rid = rid
        self.deadline_t = deadline_t
        self.words = words
        self.t_in = t_in


class _ConnState:
    __slots__ = ("framer", "choked", "n_req", "n_replies")

    def __init__(self, framer):
        self.framer = framer
        self.choked = False
        self.n_req = 0
        self.n_replies = 0


class Server:
    """The front door: owns the listener, the per-connection framers,
    the request queue, the worker lease pool and the admission
    controller. Registered as a runtime poller — poll(rt) runs at every
    host boundary and is where batching/shedding/drain decisions land
    (the same cadence the bridge and analysis writer already use)."""

    def __init__(self, rt: Runtime, workers, request_beh, *,
                 front_door: int, max_frame_words: int = 64,
                 pending_limit: int = 256 * 1024,
                 admit_lo: int = 1, admit_hi: Optional[int] = None,
                 drain_grace_s: float = 0.5, reclaim_factor: float = 4.0,
                 drain_exit: bool = True):
        self.rt = rt
        self.net = rt.attach_net()
        self.workers = [int(w) for w in np.asarray(workers).reshape(-1)]
        if not self.workers:
            raise ValueError("Server needs at least one worker actor")
        self.request_beh = request_beh
        self.front_door = int(front_door)
        # Request arity: behaviour args are (tag, *payload).
        self.n_payload = len(request_beh.arg_specs) - 1
        self.max_frame_words = int(max_frame_words)
        self.pending_limit = int(pending_limit)
        self.drain_grace_s = float(drain_grace_s)
        self.reclaim_factor = float(reclaim_factor)
        self.drain_exit = bool(drain_exit)
        self.admission = AdmissionController(
            admit_lo, admit_hi or len(self.workers), len(self.workers))
        self._conns: Dict[int, _ConnState] = {}
        self._queue: collections.deque = collections.deque()
        self._inflight: Dict[int, _Request] = {}
        self._free: collections.deque = collections.deque(self.workers)
        self._lease: Dict[int, int] = {}      # tag → worker gid
        self._next_tag = 1
        self._lid: Optional[int] = None
        self.draining = False
        self._drain_t: Optional[float] = None
        self.drained = False
        # Counters (stats() / metrics "serving" block / postmortems).
        self.c = collections.Counter()
        self._lat_us: collections.deque = collections.deque(
            maxlen=LAT_RESERVOIR)
        self._rate_ema = 0.0          # replies/s, EMA
        self._rate_t = time.monotonic()
        self._rate_n = 0
        self._spill_frac = 0.0
        self._spill_t = 0.0
        self._adm_t = 0.0             # last admission decision time
        self._occ_hwm = 0             # occupancy high-water mark since
        rt._serve = self
        rt.register_poller(self)

    # -- lifecycle --------------------------------------------------------
    def listen(self, host: str = "127.0.0.1", port: int = 0,
               tls=None) -> int:
        """Bind and start accepting; returns the bound port."""
        self._lid = self.net.listen_tcp(
            host, port, self.front_door,
            on_accept=FrontDoor.on_accept, on_data=FrontDoor.on_data,
            on_closed=FrontDoor.on_closed, tls=tls)
        return self.net.listen_port(self._lid)

    def install_signals(self) -> None:
        """SIGTERM → graceful drain (the flag is consumed at the next
        host boundary; admitted requests complete before exit). SIGINT
        is deliberately left alone: KeyboardInterrupt stays the
        operator's hard stop AND the stall watchdog's trip-delivery
        channel (flight.Watchdog signals the main thread with SIGINT —
        swallowing it here would turn a code-7 stall back into a
        silent hang)."""
        def _drain(_signum, _frame):
            self.begin_drain()
        try:
            _signal.signal(_signal.SIGTERM, _drain)
        except ValueError:            # not the main thread
            pass

    def begin_drain(self) -> None:
        """Stop accepting, shed new frames BUSY, complete admitted
        requests, flush replies, then close and (drain_exit) stop the
        run loop. Idempotent; callable from signal handlers."""
        if self.draining:
            return
        self.draining = True
        self._drain_t = time.monotonic()
        self.c["drains"] += 1

    # -- socket-event half (called from FrontDoor behaviours) -------------
    def _on_accept(self, cid: int) -> None:
        # A connection the kernel accepted during drain still gets a
        # framer: its frames are answered BUSY by the shed path below.
        self._conns[cid] = _ConnState(Framer(self.max_frame_words))
        self.c["conns_accepted"] += 1

    def _on_closed(self, cid: int) -> None:
        self._conns.pop(cid, None)
        self.c["conns_closed"] += 1
        # Abandon this connection's queued requests (nobody to reply
        # to); in-flight ones complete and drop at reply time.
        if self._queue:
            kept = [r for r in self._queue if r.cid != cid]
            dropped = len(self._queue) - len(kept)
            if dropped:
                self._queue = collections.deque(kept)
                self.c["abandoned"] += dropped

    def _on_data(self, cid: int, data: bytes) -> None:
        cs = self._conns.get(cid)
        if cs is None:
            return
        try:
            frames = cs.framer.feed(data)
        except FrameError as e:
            self.c["badframe"] += 1
            self.rt._error_counts[("FrameError", ST_BADFRAME)] += 1
            self._reply_raw(cid, -1, ST_BADFRAME)
            fl = getattr(self.rt, "_flight", None)
            if fl is not None:
                fl.event("badframe", conn=cid, message=str(e))
            self._close_conn(cid)
            return
        for words in frames:
            self._on_request(cid, cs, words)

    def _on_request(self, cid: int, cs: _ConnState,
                    words: np.ndarray) -> None:
        rid, deadline_ms = int(words[0]), int(words[1])
        cs.n_req += 1
        self.c["frames"] += 1
        if len(words) - 2 != self.n_payload:
            self.c["badframe"] += 1
            self.rt._error_counts[("FrameError", ST_BADFRAME)] += 1
            self._reply_raw(cid, rid, ST_BADFRAME)
            return
        now = time.monotonic()
        if self.draining:
            self.c["shed_drain"] += 1
            self._reply_raw(cid, rid, ST_BUSY)
            return
        if cs.choked:
            self.c["shed_choked"] += 1
            self._reply_raw(cid, rid, ST_BUSY)
            return
        occupancy = len(self._queue) + len(self._inflight)
        self._occ_hwm = max(self._occ_hwm, occupancy + 1)
        if occupancy >= self.admission.limit:
            self.c["shed_busy"] += 1
            self._reply_raw(cid, rid, ST_BUSY)
            return
        if deadline_ms > 0 and self._rate_ema > 0.0:
            est_wait_ms = 1e3 * occupancy / self._rate_ema
            if est_wait_ms > deadline_ms:
                # The measured service rate cannot meet the deadline:
                # shedding NOW costs the client less than a doomed wait.
                self.c["shed_deadline"] += 1
                self._reply_raw(cid, rid, ST_BUSY)
                return
        tag = self._next_tag
        self._next_tag = (self._next_tag + 1) & 0x7FFFFFFF or 1
        ddl = now + deadline_ms / 1e3 if deadline_ms > 0 else None
        self._queue.append(_Request(tag, cid, rid, ddl,
                                    [int(w) for w in words[2:]], now))
        self.c["accepted"] += 1

    # -- device half ------------------------------------------------------
    def complete(self, tag: int, value: int) -> None:
        """Egress.done lands here: route the reply to the owning
        connection and return the worker to the lease pool."""
        req = self._inflight.pop(tag, None)
        w = self._lease.pop(tag, None)
        if w is not None:
            self._free.append(w)
        if req is None:
            self.c["stale_replies"] += 1      # reclaimed or unknown tag
            return
        self.c["replied"] += 1
        self._rate_n += 1
        self._lat_us.append(int((time.monotonic() - req.t_in) * 1e6))
        self._reply_raw(req.cid, req.rid, ST_OK, (value,))

    def _reply_raw(self, cid: int, rid: int, status: int,
                   values=()) -> None:
        cs = self._conns.get(cid)
        if cs is None:
            self.c["replies_dropped"] += 1    # connection went away
            return
        try:
            self.net.send(cid, encode_reply(rid, status, values))
        except KeyError:
            self.c["replies_dropped"] += 1
            return
        cs.n_replies += 1
        # Egress backpressure (≙ throttled): a consumer that stops
        # reading accumulates pending() bytes — choke it (its requests
        # shed BUSY) and close it past CLOSE_FACTOR x.
        pend = self.net.pending(cid)
        if pend > self.pending_limit * CLOSE_FACTOR:
            self.c["conns_killed_slow"] += 1
            self._close_conn(cid)
        elif pend > self.pending_limit:
            if not cs.choked:
                self.c["choked"] += 1
            cs.choked = True
        elif cs.choked and pend <= self.pending_limit // 2:
            cs.choked = False                 # hysteresis release

    def _close_conn(self, cid: int) -> None:
        self._conns.pop(cid, None)
        try:
            self.net.close(cid)
        except KeyError:
            pass

    # -- the boundary hook ------------------------------------------------
    def poll(self, rt) -> int:
        """Runtime-poller hook: admission update, deadline expiry,
        lease reclaim, the bulk_send flush, drain completion."""
        now = time.monotonic()
        self._observe(rt, now)
        n = self._expire(now)
        n += self._flush(rt)
        self._finish_drain(now)
        return n

    def _observe(self, rt, now: float) -> None:
        # Reply-rate EMA (the deadline estimator's denominator).
        dt = now - self._rate_t
        if dt >= 0.1:
            inst = self._rate_n / dt
            self._rate_ema = inst if self._rate_ema == 0.0 \
                else 0.7 * self._rate_ema + 0.3 * inst
            self._rate_n = 0
            self._rate_t = now
        # Spill occupancy: two tiny per-shard counters, fetched at a
        # bounded cadence (0.25 s) — never per boundary.
        if rt.state is not None and now - self._spill_t >= 0.25:
            self._spill_t = now
            try:
                parked = int(rt._fetch(rt.state.dspill_count).sum()) \
                    + int(rt._fetch(rt.state.rspill_count).sum())
                cap = max(1, 2 * rt.opts.spill_cap * rt.program.shards)
                self._spill_frac = parked / cap
            except Exception:        # noqa: BLE001 — mid-teardown
                pass
        # Admission decisions run at a bounded cadence (50 ms), not per
        # boundary — a pipelined loop retires windows every few tens of
        # µs and a per-boundary MIMD would slam between lo and hi.
        if now - self._adm_t < 0.05:
            return
        self._adm_t = now
        aux = getattr(rt, "_last_aux", None)
        ctrl = rt._controller
        self.admission.observe(
            qw_p99=int(aux.qw_p99) if aux is not None else 0,
            window=ctrl.window if ctrl is not None else 1,
            muted=int(aux.n_muted_now) if aux is not None else 0,
            spill_frac=self._spill_frac,
            used=self._occ_hwm)
        self._occ_hwm = len(self._queue) + len(self._inflight)

    def _expire(self, now: float) -> int:
        n = 0
        # Queued past deadline: shed without touching the device.
        while self._queue and self._queue[0].deadline_t is not None \
                and self._queue[0].deadline_t < now:
            req = self._queue.popleft()
            self.c["shed_deadline"] += 1
            self._reply_raw(req.cid, req.rid, ST_DEADLINE)
            n += 1
        # In-flight far past deadline: the worker is presumed wedged or
        # its reply lost — reclaim the lease (a late reply for the tag
        # is dropped as stale) so one bad request cannot leak a worker.
        if self._inflight:
            dead = [t for t, r in self._inflight.items()
                    if r.deadline_t is not None
                    and now > r.deadline_t + self.reclaim_factor
                    * max(0.05, r.deadline_t - r.t_in)]
            for t in dead:
                req = self._inflight.pop(t)
                w = self._lease.pop(t, None)
                if w is not None:
                    self._free.append(w)
                self.c["reclaimed"] += 1
                self._reply_raw(req.cid, req.rid, ST_DEADLINE)
                n += 1
        return n

    def _flush(self, rt) -> int:
        """Coalesce queued requests into ONE bulk_send batch per
        boundary — one message per free worker, batch size additionally
        capped by the PR 5 window controller's current window (the
        device's own vote on how much uninterrupted work it wants)."""
        if not self._queue or not self._free:
            return 0
        ctrl = rt._controller
        cap = ctrl.window if ctrl is not None else len(self._free)
        k = min(len(self._queue), len(self._free), max(1, cap))
        reqs = [self._queue.popleft() for _ in range(k)]
        tgts = [self._free.popleft() for _ in range(k)]
        for req, w in zip(reqs, tgts):
            self._lease[req.tag] = w
            self._inflight[req.tag] = req
        self.c["submitted"] += k
        self.c["batches"] += 1
        if rt.opts.tracing:
            # Traced path: one inject-lane send per request so each
            # carries ITS OWN trace id (= the tag) end to end.
            for req, w in zip(reqs, tgts):
                rt.send(w, self.request_beh, req.tag, *req.words,
                        trace=req.tag)
            return k
        cols = [np.fromiter((r.tag for r in reqs), np.int64, k)]
        for j in range(self.n_payload):
            cols.append(np.fromiter((r.words[j] for r in reqs),
                                    np.int64, k))
        rt.bulk_send(np.asarray(tgts, np.int64), self.request_beh, *cols)
        return k

    def _finish_drain(self, now: float) -> None:
        if not self.draining or self.drained:
            return
        if self._lid is not None:
            self.net.close_listener(self._lid)
            self._lid = None
        if self._queue or self._inflight:
            return
        # Admitted work is done. Hold the door open for drain_grace_s
        # (in-flight client frames still get BUSY answers) and until
        # every reply byte is flushed, then close out. Peers all gone
        # already = nothing left to answer: complete immediately.
        if self._conns:
            if now - (self._drain_t or now) < self.drain_grace_s:
                return
            if any(self.net.pending(cid) for cid in self._conns):
                return
        for cid in list(self._conns):
            self._close_conn(cid)
        self.drained = True
        if self.drain_exit:
            self.rt.request_exit(0)

    # -- observability ----------------------------------------------------
    def net_pending_bytes(self) -> int:
        return self.net.pending_total()

    def latency_us(self) -> Dict[str, int]:
        lat = sorted(self._lat_us)
        if not lat:
            return {"p50": 0, "p99": 0, "n": 0}
        return {"p50": lat[len(lat) // 2],
                "p99": lat[min(len(lat) - 1, int(len(lat) * 0.99))],
                "n": len(lat)}

    def stats(self) -> Dict[str, Any]:
        """The `serving` block (metrics snapshot, flight postmortems,
        bench.py --serve-smoke)."""
        c = self.c
        shed = (c["shed_busy"] + c["shed_deadline"] + c["shed_drain"]
                + c["shed_choked"])
        return {
            "conns": len(self._conns),
            "conns_accepted": c["conns_accepted"],
            "frames": c["frames"],
            "accepted": c["accepted"],
            "submitted": c["submitted"],
            "batches": c["batches"],
            "replied": c["replied"],
            "shed": {"busy": c["shed_busy"],
                     "deadline": c["shed_deadline"],
                     "drain": c["shed_drain"],
                     "choked": c["shed_choked"]},
            "shed_total": shed,
            "shed_rate": round(shed / max(1, c["frames"]), 4),
            "badframe": c["badframe"],
            "choked_events": c["choked"],
            "conns_killed_slow": c["conns_killed_slow"],
            "reclaimed": c["reclaimed"],
            "abandoned": c["abandoned"],
            "replies_dropped": c["replies_dropped"],
            "queue": len(self._queue),
            "inflight": len(self._inflight),
            "free_workers": len(self._free),
            "admission": self.admission.snapshot(),
            "rate_rps": round(self._rate_ema, 1),
            "latency_us": self.latency_us(),
            "net_pending_bytes": self.net_pending_bytes(),
            "draining": self.draining,
            # A drain is complete once nothing admitted remains and no
            # peer is owed bytes — whether the run loop exited via the
            # server's own request_exit or via quiescence after the
            # last client hung up (the close events can land after the
            # final poll).
            "drained": bool(self.drained
                            or (self.draining and not self._conns
                                and not self._queue
                                and not self._inflight)),
        }


# ---- world builder + CLI ------------------------------------------------

def default_options(n_workers: int, **overrides) -> RuntimeOptions:
    from .config import options_from_env
    base = dict(mailbox_cap=16, batch=4, max_sends=1, msg_words=3,
                inject_slots=max(64, min(1024, 2 * n_workers)),
                host_out_slots=max(64, min(1024, 2 * n_workers)))
    base.update(overrides)
    return options_from_env(RuntimeOptions(**base))


def build(n_workers: int = 64, opts: Optional[RuntimeOptions] = None,
          **server_kw):
    """Construct the default service world: a ServeWorker device
    cohort wired to one Egress + one FrontDoor host actor, fronted by
    a Server. Returns (rt, server); call server.listen(...) then
    rt.run()."""
    rt = Runtime(opts or default_options(n_workers))
    rt.declare(ServeWorker, n_workers)
    rt.declare(Egress, 1)
    rt.declare(FrontDoor, 1)
    rt.start()
    workers = rt.spawn_many(ServeWorker, n_workers)
    eg = rt.spawn(Egress)
    fd = rt.spawn(FrontDoor)
    rt.set_fields(ServeWorker, workers, egress=int(eg))
    server = Server(rt, workers, ServeWorker.handle, front_door=fd,
                    **server_kw)
    return rt, server


def main(argv: Optional[List[str]] = None) -> int:
    """`python -m ponyc_tpu serve [--host H] [--port P] [--workers N]
    [--tls-cert C --tls-key K] [--pending-limit B] [--drain-grace S]
    [--pony* runtime flags]` — run the default compute service until
    SIGTERM (graceful drain) or a coded failure (exit = error code, so
    `ponyc_tpu supervise` restarts from the newest checkpoint)."""
    import argparse

    from .config import strip_runtime_flags
    from .errors import error_code
    from .platforms import auto_backend
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        opts_env, rest = strip_runtime_flags(["x"] + argv)
    except ValueError as e:
        print(f"ponyc_tpu serve: {e}", file=sys.stderr)
        return 2
    ap = argparse.ArgumentParser(prog="ponyc_tpu serve")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--workers", type=int, default=64)
    ap.add_argument("--tls-cert")
    ap.add_argument("--tls-key")
    ap.add_argument("--pending-limit", type=int, default=256 * 1024)
    ap.add_argument("--drain-grace", type=float, default=0.5)
    args = ap.parse_args(rest[1:])
    if bool(args.tls_cert) != bool(args.tls_key):
        print("ponyc_tpu serve: --tls-cert and --tls-key go together",
              file=sys.stderr)
        return 2
    auto_backend()
    import dataclasses as _dc
    base = default_options(args.workers)
    opts = _dc.replace(base, **{
        f.name: getattr(opts_env, f.name)
        for f in _dc.fields(opts_env)
        if getattr(opts_env, f.name) != getattr(type(opts_env)(), f.name)})
    rt, server = build(args.workers, opts,
                       pending_limit=args.pending_limit,
                       drain_grace_s=args.drain_grace)
    from . import supervise
    restored = supervise.maybe_restore(rt)
    if restored:
        print(f"serve: restored world from {restored}", file=sys.stderr)
    tls = None
    if args.tls_cert:
        from .net.tls import TLSServerConfig
        tls = TLSServerConfig(certfile=args.tls_cert,
                              keyfile=args.tls_key)
    port = server.listen(args.host, args.port, tls=tls)
    server.install_signals()
    print(f"serving on {args.host}:{port} "
          f"({args.workers} workers{', tls' if tls else ''})",
          flush=True)
    code = 0
    try:
        code = rt.run()
    except Exception as e:                     # noqa: BLE001
        c = error_code(e)
        print(f"serve: FAILED {type(e).__name__} (code {c}): {e}",
              file=sys.stderr)
        rt.stop()
        return c or 1
    import json as _json
    print("serve: drained " + _json.dumps(server.stats()),
          file=sys.stderr)
    rt.stop()
    return code


if __name__ == "__main__":
    sys.exit(main())
