"""Child processes as actor messages — ≙ packages/process over
lang/process.c.

The reference's ProcessMonitor actor (packages/process/process_monitor.
pony) spawns a child with piped stdio over the native layer
(lang/process.c) and turns pipe readiness into notify callbacks. Same
split here: native/src/process.cc owns posix_spawn + pipes; this layer
subscribes the pipes to the ASIO bridge and delivers to the owning
host actor:

    on_stdout(proc: I32, data: I32, n: I32)   ≙ ProcessNotify.stdout
    on_stderr(proc: I32, data: I32, n: I32)   ≙ ProcessNotify.stderr
    on_exit(proc: I32, code: I32)             ≙ ProcessNotify.dispose
        (code 0..255 = exit status; 256+signum = killed by signal)

`data` is a HostHeap handle (unbox → bytes). Exit is detected by a
waitpid(WNOHANG) sweep at poll boundaries, after both output pipes have
reported EOF — so no output is ever lost to a fast-exiting child.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from .. import native
from ..api import BehaviourDef
from ..native import processes as P
from ..native import sockets as S


class _Proc:
    __slots__ = ("pid", "owner", "on_stdout", "on_stderr", "on_exit",
                 "stdin_fd", "stdin_buf", "stdin_closing", "fds", "subs",
                 "eofs", "exit_code", "done")

    def __init__(self, pid, owner, on_stdout, on_stderr, on_exit,
                 stdin_fd, out_fd, err_fd):
        self.pid = pid
        self.owner = owner
        self.on_stdout = on_stdout
        self.on_stderr = on_stderr
        self.on_exit = on_exit
        self.stdin_fd = stdin_fd
        self.stdin_buf = bytearray()  # unwritten tail, flushed at polls
        self.stdin_closing = False  # close_stdin() called, buffer pending
        self.fds = {"out": out_fd, "err": err_fd}
        self.subs: Dict[str, int] = {}
        self.eofs = 0
        self.exit_code: Optional[int] = None
        self.done = False


class Processes:
    """One runtime's process monitor (create via rt.attach_processes())."""

    CHUNK = 65536

    def __init__(self, rt):
        self.rt = rt
        self.bridge = rt.attach_bridge()
        self._procs: Dict[int, _Proc] = {}
        self._next = 1
        rt.register_poller(self)

    def _check(self, bdef, n, what):
        if not isinstance(bdef, BehaviourDef) or bdef.global_id is None:
            raise TypeError(f"{what} must be a program-registered behaviour")
        if not bdef.actor_type.HOST:
            raise TypeError(f"{what} must live on a HOST=True actor type")
        if len(bdef.arg_specs) != n:
            raise TypeError(f"{what} must take {n} i32 args")

    def spawn(self, path: str, argv, owner: int, *,
              on_stdout: BehaviourDef, on_stderr: BehaviourDef,
              on_exit: BehaviourDef, env=None) -> int:
        """≙ ProcessMonitor.create. Returns the proc id used in events."""
        self._check(on_stdout, 3, "on_stdout")
        self._check(on_stderr, 3, "on_stderr")
        self._check(on_exit, 2, "on_exit")
        pid, stdin_w, stdout_r, stderr_r = P.spawn(path, argv, env)
        proc_id = self._next
        self._next += 1
        p = _Proc(pid, owner, on_stdout, on_stderr, on_exit,
                  stdin_w, stdout_r, stderr_r)
        for stream in ("out", "err"):
            p.subs[stream] = self.bridge.fd_callback(
                p.fds[stream],
                (lambda s: lambda ev: self._ready(proc_id, s, ev))(stream),
                read=True, noisy=True)
        self._procs[proc_id] = p
        return proc_id

    def _ready(self, proc_id: int, stream: str, ev) -> None:
        p = self._procs.get(proc_id)
        if p is None or p.done:
            return
        if ev.kind == native.FD_READ or ev.kind == native.FD_HUP:
            self._drain_stream(p, proc_id, stream)

    def _drain_stream(self, p: _Proc, proc_id: int, stream: str) -> None:
        fd = p.fds.get(stream)
        if fd is None:
            return
        bdef = p.on_stdout if stream == "out" else p.on_stderr
        while True:
            try:
                data = os.read(fd, self.CHUNK)   # pipes: read, not recv
            except BlockingIOError:
                return                     # drained, pipe still open
            except OSError:
                data = b""
            if data == b"":                # EOF
                self.bridge.unsubscribe(p.subs.pop(stream))
                S.close(fd)
                p.fds[stream] = None
                p.eofs += 1
                return
            h = self.rt.heap.box(data)
            self.rt.send(p.owner, bdef, proc_id, h, len(data))

    # -- stdin (≙ ProcessMonitor.write/done_writing) --
    def write(self, proc_id: int, data: bytes) -> None:
        """Queue bytes for the child's stdin. The whole buffer is always
        accepted: whatever the pipe can't take now is kept host-side and
        flushed at poll boundaries (as Net does for sockets), so a full
        pipe never loses or duplicates data."""
        p = self._procs[proc_id]
        if p.stdin_fd is None or p.stdin_closing:
            raise ValueError("stdin already closed")
        p.stdin_buf += data
        self._flush_stdin(p)

    def _flush_stdin(self, p: _Proc) -> None:
        written = 0
        view = memoryview(p.stdin_buf)
        try:
            while written < len(view) and p.stdin_fd is not None:
                try:
                    n = os.write(p.stdin_fd, view[written:])  # pipe: write
                except BlockingIOError:
                    return             # pipe full; retry at next poll
                except OSError:
                    # Child closed its end (EPIPE): drop the buffer and
                    # close our side so the next write() raises (≙
                    # ProcessMonitor's failed-write shutdown) instead of
                    # silently discarding.
                    written = len(view)
                    S.close(p.stdin_fd)
                    p.stdin_fd = None
                    return
                written += n
        finally:
            view.release()
            del p.stdin_buf[:written]
        if p.stdin_closing and not p.stdin_buf and p.stdin_fd is not None:
            S.close(p.stdin_fd)
            p.stdin_fd = None

    def close_stdin(self, proc_id: int) -> None:
        """≙ ProcessMonitor.done_writing: close once queued bytes flush."""
        p = self._procs[proc_id]
        if p.stdin_fd is None:
            return
        p.stdin_closing = True
        self._flush_stdin(p)

    def kill(self, proc_id: int, signum: int = 15) -> None:
        """≙ ProcessMonitor.dispose."""
        P.kill(self._procs[proc_id].pid, signum)

    # -- poller protocol: reap exits at host boundaries --
    def poll(self, rt) -> int:
        n = 0
        for proc_id, p in list(self._procs.items()):
            if p.done:
                continue
            if p.stdin_buf:
                self._flush_stdin(p)
            if p.exit_code is None:
                p.exit_code = P.check(p.pid)
            # Once the child has exited, sweep both streams: everything it
            # wrote is already buffered in the pipes, so the sweep drains
            # all of it. Then finish — without waiting for pipe EOF, which
            # a surviving grandchild holding the write end could postpone
            # indefinitely (its later output is dropped, matching the
            # reference closing fds at dispose).
            if p.exit_code is not None:
                for stream in ("out", "err"):
                    if p.fds.get(stream) is not None:
                        self._drain_stream(p, proc_id, stream)
                p.done = True
                for stream in ("out", "err"):
                    if p.fds.get(stream) is not None:
                        self.bridge.unsubscribe(p.subs.pop(stream))
                        S.close(p.fds[stream])
                        p.fds[stream] = None
                if p.stdin_fd is not None:
                    S.close(p.stdin_fd)
                    p.stdin_fd = None
                rt.send(p.owner, p.on_exit, proc_id, p.exit_code)
                del self._procs[proc_id]
                n += 1
        return n

    def close_all(self) -> None:
        for proc_id, p in list(self._procs.items()):
            for stream, sub in list(p.subs.items()):
                self.bridge.unsubscribe(sub)
                if p.fds.get(stream) is not None:
                    S.close(p.fds[stream])
            if p.stdin_fd is not None:
                S.close(p.stdin_fd)
            if p.exit_code is None:
                try:
                    P.kill(p.pid, 9)
                    P.check(p.pid)
                except OSError:
                    pass
            del self._procs[proc_id]
