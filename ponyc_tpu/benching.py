"""Micro-benchmark harness — ≙ packages/ponybench.

The reference's ponybench runs `MicroBenchmark`s (name/before/apply/
after) with automatic iteration scaling until the measurement is stable,
then reports name, mean time and ops/s; `OverheadBenchmark` subtracts
harness overhead. The TPU twin measures *jitted device work*: it warms
the compile out of the measurement, scales repetitions to a minimum
measured window, synchronises with block_until_ready (device work is
async — wall-clocking an unsynchronised dispatch measures nothing), and
reports mean/p50/p95 per call plus derived ops/s.

    b = BenchRunner()
    b.bench("tick", fn, *args, items_per_call=N)   # fn jitted or plain
    b.report()                                      # table + JSON rows
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, Dict, List, Optional

import jax


class BenchResult:
    __slots__ = ("name", "reps", "mean_s", "p50_s", "p95_s",
                 "items_per_call", "ops_per_s")

    def __init__(self, name, reps, times, items_per_call):
        self.name = name
        self.reps = reps
        self.mean_s = sum(times) / len(times)
        srt = sorted(times)
        self.p50_s = srt[len(srt) // 2]
        self.p95_s = srt[min(len(srt) - 1, int(len(srt) * 0.95))]
        self.items_per_call = items_per_call
        self.ops_per_s = (items_per_call / self.mean_s
                          if self.mean_s > 0 else float("inf"))

    def row(self) -> Dict[str, Any]:
        return {"name": self.name, "reps": self.reps,
                "mean_us": self.mean_s * 1e6, "p50_us": self.p50_s * 1e6,
                "p95_us": self.p95_s * 1e6, "ops_per_s": self.ops_per_s}


class BenchRunner:
    """≙ ponybench's PonyBench runner with auto-scaling iterations."""

    def __init__(self, *, min_window_s: float = 0.2, max_reps: int = 10000,
                 warmup: int = 3, out=None):
        self.min_window_s = min_window_s
        self.max_reps = max_reps
        self.warmup = warmup
        self.out = out or sys.stdout
        self.results: List[BenchResult] = []

    def bench(self, name: str, fn: Callable, *args,
              items_per_call: int = 1,
              setup: Optional[Callable] = None,
              teardown: Optional[Callable] = None) -> BenchResult:
        """Measure fn(*args). If setup is given it produces fresh args per
        measurement batch (≙ MicroBenchmark.before/after)."""
        if setup is not None:
            args = setup()
            if not isinstance(args, tuple):
                args = (args,)
        for _ in range(self.warmup):                 # compile + caches
            jax.block_until_ready(fn(*args))
        # Scale reps until one timing window is long enough to trust
        # (≙ ponybench's auto-scaling loop).
        reps = 1
        while True:
            t0 = time.perf_counter()
            out = None
            for _ in range(reps):
                out = fn(*args)
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            if dt >= self.min_window_s or reps >= self.max_reps:
                break
            reps = min(self.max_reps,
                       max(reps * 2, int(reps * self.min_window_s
                                         / max(dt, 1e-9))))
        # Measurement: several windows for percentiles.
        times: List[float] = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = None
            for _ in range(reps):
                out = fn(*args)
            jax.block_until_ready(out)
            times.append((time.perf_counter() - t0) / reps)
        if teardown is not None:
            teardown(args)
        r = BenchResult(name, reps, times, items_per_call)
        self.results.append(r)
        return r

    def report(self, json_lines: bool = False) -> None:
        w = self.out
        if json_lines:
            for r in self.results:
                print(json.dumps(r.row()), file=w)
            return
        name_w = max((len(r.name) for r in self.results), default=4)
        print(f"{'Benchmark'.ljust(name_w)}  {'mean':>12} {'p50':>12} "
              f"{'p95':>12} {'ops/s':>14}  reps", file=w)
        for r in self.results:
            print(f"{r.name.ljust(name_w)}  {r.mean_s*1e6:>10.2f}us "
                  f"{r.p50_s*1e6:>10.2f}us {r.p95_s*1e6:>10.2f}us "
                  f"{r.ops_per_s:>14.0f}  {r.reps}", file=w)


def compare(base: BenchResult, new: BenchResult) -> float:
    """Speedup of new over base (≙ eyeballing two ponybench rows)."""
    return base.mean_s / new.mean_s if new.mean_s else float("inf")
