"""Device-resident runtime state: the struct-of-arrays actor world.

≙ the reference's per-actor structs flattened across all actors:
  - pony_actor_t fields (flags, priority, batch, mute counters —
    src/libponyrt/actor/actor.h:35-69) become columns over [N] actors;
  - each actor's messageq_t (intrusive MPSC list, actor/messageq.c) becomes
    one row of a dense [N, cap, words] ring-buffer table with monotonically
    increasing head/tail counts (occupancy = tail - head; physical slot =
    count % cap);
  - the scheduler's unbounded pool-backed queues have no static-shape
    analog, so overflow goes to a bounded *spill* table retried next step
    (SURVEY.md §7 hard part (a): capacity-bounded mailboxes with spill).

Everything lives in one pytree so a whole scheduler tick is a single jitted
function application; host↔device traffic per step is a handful of scalars.

Counts are int32: a single actor overflows after 2^31 lifetime messages —
acceptable for now, and noted here deliberately.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..config import RuntimeOptions
from ..program import Program


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class RtState:
    """The complete device state of the actor world (one pytree)."""

    # Mailboxes (≙ messageq.c): one row per actor, device and host cohorts.
    buf: jnp.ndarray          # [N, cap, 1+W] int32 — word0 = behaviour gid
    head: jnp.ndarray         # [N] int32, monotonic pop count
    tail: jnp.ndarray         # [N] int32, monotonic push count

    # Per-actor scheduling flags (≙ actor.h:59-69 flag bits).
    alive: jnp.ndarray        # [N] bool — slot occupied (≙ !PENDINGDESTROY)
    muted: jnp.ndarray        # [N] bool — ≙ FLAG_MUTED; skipped by dispatch
    mute_ref: jnp.ndarray     # [N] int32 — the receiver that muted us (-1)

    # Overflow spill (bounded; retried first every step, preserving order).
    spill_tgt: jnp.ndarray    # [S] int32 target id, -1 = empty slot
    spill_sender: jnp.ndarray  # [S] int32 sender id (N = host/no sender)
    spill_words: jnp.ndarray  # [S, 1+W] int32
    spill_count: jnp.ndarray  # [] int32
    spill_overflow: jnp.ndarray  # [] bool — spill itself overflowed (fatal)

    # Program-wide control (≙ pony_exitcode / quiescence token state).
    exit_flag: jnp.ndarray    # [] bool
    exit_code: jnp.ndarray    # [] int32
    step_no: jnp.ndarray      # [] int32

    # Telemetry accumulators, reset by host on fetch (≙ --ponyanalysis
    # counters, analysis.c; i32 windows accumulated to python ints host-side).
    n_processed: jnp.ndarray  # [] int32 — behaviours dispatched
    n_delivered: jnp.ndarray  # [] int32 — messages accepted into mailboxes
    n_rejected: jnp.ndarray   # [] int32 — capacity rejections (→ spill)
    n_badmsg: jnp.ndarray     # [] int32 — wrong-type behaviour ids dropped
    n_deadletter: jnp.ndarray  # [] int32 — sends to dead/unspawned slots
    n_mutes: jnp.ndarray      # [] int32 — mute transitions

    # Per-type state columns: {type_name: {field: [cap_T] array}}.
    type_state: Dict[str, Dict[str, jnp.ndarray]]


def init_state(program: Program, opts: RuntimeOptions) -> RtState:
    """Allocate the zeroed actor world for a finalized program."""
    assert program.frozen, "finalize() the Program first"
    n = program.total
    w1 = 1 + opts.msg_words
    c = opts.mailbox_cap
    s = opts.spill_cap
    i32 = jnp.int32

    type_state: Dict[str, Dict[str, Any]] = {}
    for cohort in program.cohorts:
        fields = {}
        for fname, spec in cohort.atype.field_specs.items():
            from ..ops.pack import F32
            dtype = jnp.float32 if spec is F32 else jnp.int32
            fields[fname] = jnp.zeros((cohort.capacity,), dtype)
        type_state[cohort.atype.__name__] = fields

    return RtState(
        buf=jnp.zeros((n, c, w1), i32),
        head=jnp.zeros((n,), i32),
        tail=jnp.zeros((n,), i32),
        alive=jnp.zeros((n,), jnp.bool_),
        muted=jnp.zeros((n,), jnp.bool_),
        mute_ref=jnp.full((n,), -1, i32),
        spill_tgt=jnp.full((s,), -1, i32),
        spill_sender=jnp.full((s,), n, i32),
        spill_words=jnp.zeros((s, w1), i32),
        spill_count=jnp.zeros((), i32),
        spill_overflow=jnp.zeros((), jnp.bool_),
        exit_flag=jnp.zeros((), jnp.bool_),
        exit_code=jnp.zeros((), i32),
        step_no=jnp.zeros((), i32),
        n_processed=jnp.zeros((), i32),
        n_delivered=jnp.zeros((), i32),
        n_rejected=jnp.zeros((), i32),
        n_badmsg=jnp.zeros((), i32),
        n_deadletter=jnp.zeros((), i32),
        n_mutes=jnp.zeros((), i32),
        type_state=type_state,
    )
