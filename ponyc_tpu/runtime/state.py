"""Device-resident runtime state: the struct-of-arrays actor world.

≙ the reference's per-actor structs flattened across all actors:
  - pony_actor_t fields (flags, priority, batch, mute counters —
    src/libponyrt/actor/actor.h:35-69) become columns over [N] actors;
  - each actor's messageq_t (intrusive MPSC list, actor/messageq.c) becomes
    one row of a dense [N, cap, words] ring-buffer table with monotonically
    increasing head/tail counts (occupancy = tail - head; physical slot =
    count % cap);
  - the scheduler's unbounded pool-backed queues have no static-shape
    analog, so overflow goes to bounded *spill* tables retried next step
    (SURVEY.md §7 hard part (a): capacity-bounded mailboxes with spill).

TPU-first memory layout (the round-3 redesign): the actor/entry axis is
the MINOR-MOST (last) dimension of every multi-dimensional array. XLA:TPU
maps the last dim onto the 128 vector lanes and pads it up — a
[N, cap, words] mailbox table (actor-major, the CPU-obvious layout) pads
its `words`-sized minor dim to 128 lanes, inflating physical traffic up
to 64× and making the dispatch/delivery path run at ~1/30 of HBM speed
(measured on-chip, round 3). With [cap, words, N] the million-actor axis
fills the lanes, small static dims (ring slot, payload word) become the
major axes iterated at trace time, and every hot op is a full-width
vector op over [N]. Sharding therefore also rides the LAST axis (see
state_partition_specs): actor rows are shard-major within it
(program.py), per-shard scalars are [P] vectors, spill tables per-shard
[P*S]. With P == 1 this is exactly the single-chip layout. Two spills
exist because a message can be stuck in two different places on a mesh:

  - rspill ("route spill", sender side): the per-destination all_to_all
    bucket was full — the message hasn't left its source shard yet; targets
    are global ids.
  - dspill ("delivery spill", receiver side): it reached the target shard
    but the target mailbox was full; targets are local rows. This is the
    only spill that exists on a single chip.

Everything lives in one pytree so a whole scheduler tick is a single jitted
function application; host↔device traffic per step is a handful of scalars.

Counts are int32: a single actor overflows after 2^31 lifetime messages —
acceptable for now, and noted here deliberately.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..config import RuntimeOptions
from ..program import Program

# Queue-wait histogram geometry (the profiler, engine.profile_lanes):
# bucket k counts dispatched messages that waited [2^k, 2^(k+1)) ticks
# between delivery (enqueue stamp) and dispatch; the last bucket is
# open-ended (>= 2^(QW_BUCKETS-1)). Power-of-two buckets keep the
# on-device update a handful of compares (≙ the DTrace scripts'
# quantize() aggregations over the fork's USDT probes).
QW_BUCKETS = 16

# Per-phase window telemetry (the device-cost observatory, ISSUE 19):
# one work-unit counter per scheduler-tick phase, accumulated on device
# in engine.phase_cost_lanes. Work units are DETERMINISTIC per-phase
# tallies (delivery-list entries gathered, ring slots drained,
# behaviours dispatched, GC bookkeeping rows touched) — not wall time —
# so the XLA scan window and the megakernel's jaxpr replay produce
# bit-identical lanes by construction; wall/bytes attribution is the
# measured layer's job (costs.py).
PHASE_NAMES = ("delivery", "drain", "dispatch", "gc_mark")
N_PHASES = len(PHASE_NAMES)

# Span-ring record rows (causal tracing, PROFILE.md §10): the layout is
# owned by tracing.py so the host reassembler and the device writer can
# never drift. (trace_id, span_id, parent_span, behaviour_gid,
# actor_gid, enqueue_tick, dispatch_tick, retire_tick.)
from ..tracing import SPAN_ROWS  # noqa: E402  (after QW_BUCKETS on purpose)


def layout_sizes(program: Program, opts: RuntimeOptions):
    """Static per-shard sizes shared by build_step and init_state:
    (e_out, bucket, n_delivery_entries).

    e_out — outbox entries one shard can emit per tick;
    bucket — per-destination all_to_all bucket (mesh only);
    n_delivery_entries — rows in one shard's delivery list
    (receiver-spill + host inject + incoming), which is also the length
    of the cached delivery plan (see delivery.py)."""
    e_out = sum(ch.local_capacity * ch.batch * ch.max_sends
                for ch in program.device_cohorts)
    s = opts.spill_cap
    p = program.shards
    if p > 1:
        if opts.route_bucket > 0:
            bucket = opts.route_bucket
        else:
            # Worst case one shard receives everything; keep buckets at
            # outbox-size/shards ×4 (overflow is safe — it parks in the
            # route spill; opts.route_bucket overrides).
            bucket = max(16, min(e_out + s, 4 * (e_out + s) // p))
        incoming = p * bucket
    else:
        bucket = 0
        incoming = s + e_out          # route-spill passthrough + outbox
    return e_out, bucket, s + opts.inject_slots + incoming


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class RtState:
    """The complete device state of the actor world (one pytree)."""

    # Mailboxes (≙ messageq.c): one lane per actor, device and host
    # cohorts; ring slot and payload word are the (small, static) major
    # axes — see the layout note in the module docstring. PER-COHORT
    # word width (≙ per-type pony_msg_t sizes, genfun.c): each type's
    # table is [cap, 1+W_c, capacity] where W_c = min(opts.msg_words,
    # the cohort's widest behaviour) — a narrow type's million mailboxes
    # stop paying the widest type's HBM footprint. Keys = type names;
    # the last axis is the cohort's shard-major slot axis (like
    # type_state columns). Spills/inject/outbox keep the global width.
    buf: Dict[str, jnp.ndarray]  # {type: [cap, 1+W_c, capacity]} int32
    head: jnp.ndarray         # [N] int32, monotonic pop count
    tail: jnp.ndarray         # [N] int32, monotonic push count

    # Per-actor scheduling flags (≙ actor.h:59-69 flag bits).
    alive: jnp.ndarray        # [N] bool — slot occupied (≙ !PENDINGDESTROY)
    muted: jnp.ndarray        # [N] bool — ≙ FLAG_MUTED; skipped by dispatch
    mute_refs: jnp.ndarray    # [K, N] int32 — global ids of the muting
    #                              receivers (possibly off-shard), slotted
    #                              by ref % K; -1 = empty slot. ≙ the
    #                              mutemap receiver-set per sender
    #                              (mutemap.c; scheduler.c:1478-1635):
    #                              release only when all recover.
    mute_age: jnp.ndarray     # [N] int32 — consecutive ticks spent muted
    #                              (0 when unmuted). Past opts.mute_age_limit
    #                              the unmute pass force-releases: the
    #                              lockstep deadlock-breaker for
    #                              mutual-mute cycles/chains (the
    #                              reference's pre-0.36 backpressure can
    #                              deadlock here; bounded queues + spill
    #                              make periodic release safe for us)
    mute_ovf: jnp.ndarray     # [N] bool — more distinct muters than slots
    #                              (hash collision); release deferred until
    #                              the shard is globally quiet
    pinned: jnp.ndarray       # [N] bool — host holds a ref (GC root,
    #                              ≙ ORCA external rc; see runtime/gc.py)
    pressured: jnp.ndarray    # [N] bool — ≙ FLAG_UNDER_PRESSURE
    #                              (pony_apply_backpressure,
    #                              actor.c:1137-1162): the actor declared
    #                              itself under external pressure; its
    #                              senders mute on send until released

    # Receiver-side overflow spill (local-row targets).
    dspill_tgt: jnp.ndarray    # [P*S] int32 local row, -1 = empty slot
    dspill_sender: jnp.ndarray  # [P*S] int32 sender *global* id (-1 = host)
    dspill_words: jnp.ndarray  # [1+W, P*S] int32
    dspill_count: jnp.ndarray  # [P] int32

    # Sender-side routing spill (global-id targets; used when P > 1).
    rspill_tgt: jnp.ndarray    # [P*S] int32 global id, -1 = empty slot
    rspill_sender: jnp.ndarray  # [P*S] int32 sender global id
    rspill_words: jnp.ndarray  # [1+W, P*S] int32
    rspill_count: jnp.ndarray  # [P] int32

    spill_overflow: jnp.ndarray  # [P] bool — a spill overflowed (fatal)

    # Program-wide control (≙ pony_exitcode / quiescence token state).
    exit_flag: jnp.ndarray    # [P] bool
    exit_code: jnp.ndarray    # [P] int32
    step_no: jnp.ndarray      # [P] int32

    # Telemetry accumulators (≙ --ponyanalysis counters, analysis.c);
    # int32 per shard, host accumulates mod-2^32 deltas.
    n_processed: jnp.ndarray  # [P] int32 — behaviours dispatched
    n_delivered: jnp.ndarray  # [P] int32 — messages accepted into mailboxes
    n_rejected: jnp.ndarray   # [P] int32 — capacity rejections (→ spill)
    n_badmsg: jnp.ndarray     # [P] int32 — wrong-type behaviour ids dropped
    n_deadletter: jnp.ndarray  # [P] int32 — sends to dead/unspawned slots
    n_mutes: jnp.ndarray      # [P] int32 — mute transitions
    n_spawned: jnp.ndarray    # [P] int32 — device-side ctx.spawn() claims
    n_destroyed: jnp.ndarray  # [P] int32 — ctx.destroy() completions
    spawn_fail: jnp.ndarray   # [P] bool — sticky: a wanted spawn had no slot
    n_collected: jnp.ndarray  # [P] int32 — actors freed by GC (gc.py)
    last_error: jnp.ndarray   # [N] int32 — latest ctx.error_int code
    #                              (0 = none; ≙ fork's pony_error_code)
    last_error_loc: jnp.ndarray  # [N] int32 — trace-site id of that
    #                              error (errors.error_site resolves it;
    #                              ≙ fork's __error_loc string table)
    n_errors: jnp.ndarray     # [P] int32 — error_int events

    # Per-event trace ring (analysis level 3; ≙ the fork's per-event
    # analysis rows, analysis.c:587-692): row0 = event id (analysis.py
    # EVENT_NAMES), row1 = actor gid, row2 = step. Zero-length when
    # analysis < 3 (the lanes compile away).
    ev_data: jnp.ndarray      # [3, P*EV] int32
    ev_count: jnp.ndarray     # [P] int32 — valid entries since last drain
    ev_dropped: jnp.ndarray   # [P] int32 — lifetime overflow drops

    # Per-behaviour profiler (analysis level >= 1; ≙ the fork's
    # per-actor --ponyanalysis records, analysis.h:16-31 — per
    # (cohort, behaviour) here because the cohort IS the TPU unit of
    # attribution). All cumulative int32, indexed by GLOBAL behaviour
    # id (which encodes the cohort: each type owns a contiguous gid
    # range) or by device-cohort index. Zero-length when analysis < 1
    # so every lane compiles away (engine.profile_lanes is never even
    # traced at level 0 — the zero-cost-when-off discipline).
    beh_runs: jnp.ndarray       # [P*NB] int32 — dispatches per behaviour
    beh_delivered: jnp.ndarray  # [P*NB] int32 — mailbox acceptances per
    #                               behaviour (host-cohort deliveries
    #                               included: the host drains them)
    beh_rejected: jnp.ndarray   # [P*NB] int32 — capacity rejections by
    #                               target behaviour (per-tick semantics
    #                               match n_rejected: a parked message
    #                               re-rejected next tick counts again)
    coh_mute_ticks: jnp.ndarray  # [P*ND] int32 — muted actor-ticks per
    #                               device cohort (the integral of
    #                               muted_now over ticks)
    qwait_hist: jnp.ndarray     # [P*ND*QW_BUCKETS] int32 — queue-wait
    #                               histogram per device cohort: bucket k
    #                               = waited [2^k, 2^(k+1)) ticks from
    #                               delivery to dispatch
    qwait_enq: Dict[str, jnp.ndarray]  # {type: [cap, capacity]} int32 —
    #                               enqueue-step stamp per ring slot
    #                               (device cohorts; {} when analysis<1)
    phase_cost: jnp.ndarray     # [P*N_PHASES] int32 — cumulative
    #                               per-phase work units (PHASE_NAMES
    #                               order: delivery gather entries,
    #                               mailbox ring slots drained,
    #                               behaviours dispatched, GC-mark
    #                               bookkeeping rows). Zero-length when
    #                               analysis < 1

    # Causal tracing (analysis >= 3 AND trace_sample > 0; PROFILE.md
    # §10; ≙ the fork's per-event rows following one message
    # send→dispatch, analysis.c:587-692). {} / zero-length when off —
    # the whole subsystem compiles away (engine.trace_span_lanes is
    # never traced; tests/test_tracing.py pins jaxpr identity).
    trace_buf: Dict[str, jnp.ndarray]  # {type: [cap, 2, capacity]}
    #                               per-ring-slot (trace_id,
    #                               parent_span) side lanes, written by
    #                               delivery with the SAME gather as the
    #                               payload rebuild; -1 = untraced.
    #                               ALL cohorts (the host drain reads
    #                               host-cohort lanes to continue
    #                               traces through host behaviours)
    span_data: jnp.ndarray    # [SPAN_ROWS, P*TS] int32 — span ring
    #                               (tracing.SPAN_ROWS rows; TS =
    #                               opts.trace_slots), drained by the
    #                               analysis writer / Runtime.traces()
    span_count: jnp.ndarray   # [P] int32 — valid entries since drain
    span_dropped: jnp.ndarray  # [P] int32 — lifetime overflow drops
    span_next: jnp.ndarray    # [P] int32 — monotonic span-id counter
    #                               (device ids: even, unique across
    #                               shards — see tracing.py)

    # Cached delivery plan (see delivery.py): when consecutive ticks carry
    # the same (target, level) key vector — any topology-stable traffic —
    # the sort permutation and segment bounds are reused instead of
    # re-sorted. The TPU analog of the reference's O(1) pointer-based
    # mailbox push (messageq.c:102-160): the "pointer" is a delivery plan
    # amortised across ticks.
    plan_key: jnp.ndarray     # [P*E] int32, -1 = invalid (forces replan)
    plan_perm: jnp.ndarray    # [P*E] int32 stable-sort permutation
    plan_bounds: jnp.ndarray  # [P*(n_local+1)] int32 segment bounds

    # Device blob pool (≙ actor-heap message payloads — pony_alloc_msg
    # and per-type object graphs, pony.h:332-360; see ops.pack.Blob and
    # api.Context.blob_*): message payloads wider than msg_words live
    # here and ride messages as moved-unique HANDLES (global id =
    # shard * blob_slots + slot; -1 null). Planar layout like every hot
    # array: word index major, blob slot minor (lanes). Zero-size when
    # RuntimeOptions.blob_slots == 0 — all plumbing compiles away.
    blob_data: jnp.ndarray    # [blob_words, P*BS] int32 payload words
    blob_used: jnp.ndarray    # [P*BS] bool — slot allocated
    blob_len: jnp.ndarray     # [P*BS] int32 — logical word count
    blob_gen: jnp.ndarray     # [P*BS] int32 — slot generation, bumped on
    #   each alloc and carried in the HANDLE's high bits (ops.pack
    #   BLOB_GEN_SHIFT): a stale handle to a recycled slot mismatches
    #   and reads null — ABA protection for the iso discipline's
    #   dynamic escape hatches (forged ints, post-sweep stragglers)
    blob_fail: jnp.ndarray    # [P] bool — sticky: an alloc found the
    #   POOL exhausted (no free slot in the compacted free list —
    #   raise RuntimeOptions.blob_slots or free faster)
    blob_budget_fail: jnp.ndarray  # [P] bool — sticky: an alloc fell
    #   outside the actor's per-tick reservation BUDGET (more
    #   allocating dispatches than BLOB_DISPATCHES, with free slots
    #   possibly plentiful — raise the class's BLOB_DISPATCHES). Kept
    #   separate from blob_fail so the host error names the right knob
    #   (≙ SpawnCapacityError naming its own)
    n_blob_alloc: jnp.ndarray   # [P] int32 — lifetime allocs
    n_blob_free: jnp.ndarray    # [P] int32 — lifetime frees
    n_blob_remote: jnp.ndarray  # [P] int32 — Blob args that arrived
    #   undereferenceable: host-injected off-shard handles (allocate
    #   with blob_store(near=...)), or migration drops when the
    #   receiving shard's pool was full (loud data loss, never
    #   corruption)
    n_blob_moved: jnp.ndarray   # [P] int32 — blobs that MIGRATED in
    #   with a routed message (engine._route: payload rides the
    #   all_to_all, fresh local slot + generation at the receiver)

    # Mesh-wide world facts from the previous tick's packed vote, stored
    # shard-uniform: bit0 = any pressured, bit1 = any muted, bit2 = any
    # route-spill entries. They gate the per-tick all_gathers/psums the
    # backpressure machinery needs only when those states exist — a quiet
    # mesh tick runs collective-free except routing + one vote
    # (≙ idle costing ~nothing, the fork's README.md:8-10 thesis).
    world_bits: jnp.ndarray   # [P] int32

    # Per-type state columns: {type_name: {field: [cohort.capacity] array}}
    # (leading axis shard-major; see Cohort.slot_to_col).
    type_state: Dict[str, Dict[str, jnp.ndarray]]


# The int32 word tables eligible for the narrow-dtype "bandwidth diet"
# (ops/megakernel.py): mailbox ring records, both spill word tables and
# the per-message trace lanes. These are the hot-path bytes-per-message
# — behaviour ids and small payload words travel as int16 lanes with an
# int32 escape plane at the megakernel boundary, and serialise.py can
# store snapshots in the same packed form (save(packed=True)). Listed
# here, next to the layout they describe, so the kernel boundary and the
# snapshot codec can never disagree about WHICH tables pack.
PACKED_WORD_FIELDS = ("buf", "dspill_words", "rspill_words", "trace_buf")


def init_state(program: Program, opts: RuntimeOptions) -> RtState:
    """Allocate the zeroed actor world for a finalized program."""
    assert program.frozen, "finalize() the Program first"
    n = program.total
    p = program.shards
    # Spill tables carry the full in-flight word width: payload plus
    # the (trace_id, parent_span) lanes when tracing is on — a parked
    # message must keep its causal context across the retry.
    w1 = 1 + opts.msg_words + opts.trace_lanes
    c = opts.mailbox_cap
    s = opts.spill_cap * p
    _, _, n_entries = layout_sizes(program, opts)
    i32 = jnp.int32
    # Profiler matrix sizes: zero when analysis < 1 (lanes compile away).
    nb = len(program.behaviour_table) if opts.analysis >= 1 else 0
    nd = len(program.device_cohorts) if opts.analysis >= 1 else 0

    type_state: Dict[str, Dict[str, Any]] = {}
    for cohort in program.cohorts:
        fields = {}
        for fname, spec in cohort.atype.field_specs.items():
            from ..ops.pack import F32, null_word
            dtype = jnp.float32 if spec is F32 else jnp.int32
            # Ref/blob fields default to -1 ("no actor"/"no blob" — id 0
            # is real for both; the GC tracer treats >= 0 as an edge).
            fields[fname] = jnp.full((cohort.capacity,),
                                     null_word(spec), dtype)
        type_state[cohort.atype.__name__] = fields

    return RtState(
        buf={cohort.atype.__name__:
             jnp.zeros((c, 1 + cohort.msg_words, cohort.capacity), i32)
             for cohort in program.cohorts},
        head=jnp.zeros((n,), i32),
        tail=jnp.zeros((n,), i32),
        alive=jnp.zeros((n,), jnp.bool_),
        muted=jnp.zeros((n,), jnp.bool_),
        mute_refs=jnp.full((opts.mute_slots, n), -1, i32),
        mute_age=jnp.zeros((n,), i32),
        mute_ovf=jnp.zeros((n,), jnp.bool_),
        pinned=jnp.zeros((n,), jnp.bool_),
        pressured=jnp.zeros((n,), jnp.bool_),
        dspill_tgt=jnp.full((s,), -1, i32),
        dspill_sender=jnp.full((s,), -1, i32),
        dspill_words=jnp.zeros((w1, s), i32),
        dspill_count=jnp.zeros((p,), i32),
        rspill_tgt=jnp.full((s,), -1, i32),
        rspill_sender=jnp.full((s,), -1, i32),
        rspill_words=jnp.zeros((w1, s), i32),
        rspill_count=jnp.zeros((p,), i32),
        spill_overflow=jnp.zeros((p,), jnp.bool_),
        exit_flag=jnp.zeros((p,), jnp.bool_),
        exit_code=jnp.zeros((p,), i32),
        step_no=jnp.zeros((p,), i32),
        n_processed=jnp.zeros((p,), i32),
        n_delivered=jnp.zeros((p,), i32),
        n_rejected=jnp.zeros((p,), i32),
        n_badmsg=jnp.zeros((p,), i32),
        n_deadletter=jnp.zeros((p,), i32),
        n_mutes=jnp.zeros((p,), i32),
        n_spawned=jnp.zeros((p,), i32),
        n_destroyed=jnp.zeros((p,), i32),
        spawn_fail=jnp.zeros((p,), jnp.bool_),
        n_collected=jnp.zeros((p,), i32),
        last_error=jnp.zeros((n,), i32),
        last_error_loc=jnp.zeros((n,), i32),
        n_errors=jnp.zeros((p,), i32),
        ev_data=jnp.zeros(
            (3, p * (opts.analysis_events if opts.analysis >= 3 else 0)),
            i32),
        ev_count=jnp.zeros((p,), i32),
        ev_dropped=jnp.zeros((p,), i32),
        beh_runs=jnp.zeros((p * nb,), i32),
        beh_delivered=jnp.zeros((p * nb,), i32),
        beh_rejected=jnp.zeros((p * nb,), i32),
        coh_mute_ticks=jnp.zeros((p * nd,), i32),
        qwait_hist=jnp.zeros((p * nd * QW_BUCKETS,), i32),
        qwait_enq=({ch.atype.__name__: jnp.zeros((c, ch.capacity), i32)
                    for ch in program.device_cohorts}
                   if opts.analysis >= 1 else {}),
        phase_cost=jnp.zeros(
            (p * (N_PHASES if opts.analysis >= 1 else 0),), i32),
        trace_buf=({ch.atype.__name__:
                    jnp.full((c, 2, ch.capacity), -1, i32)
                    for ch in program.cohorts}
                   if opts.tracing else {}),
        span_data=jnp.zeros(
            (SPAN_ROWS, p * (opts.trace_slots if opts.tracing else 0)),
            i32),
        span_count=jnp.zeros((p,), i32),
        span_dropped=jnp.zeros((p,), i32),
        span_next=jnp.zeros((p,), i32),
        plan_key=jnp.full((p * n_entries,), -1, i32),
        plan_perm=jnp.zeros((p * n_entries,), i32),
        plan_bounds=jnp.zeros((p * (program.n_local + 1),), i32),
        world_bits=jnp.zeros((p,), i32),
        blob_data=jnp.zeros((opts.blob_words, p * opts.blob_slots), i32),
        blob_used=jnp.zeros((p * opts.blob_slots,), jnp.bool_),
        blob_len=jnp.zeros((p * opts.blob_slots,), i32),
        blob_gen=jnp.zeros((p * opts.blob_slots,), i32),
        blob_fail=jnp.zeros((p,), jnp.bool_),
        blob_budget_fail=jnp.zeros((p,), jnp.bool_),
        n_blob_alloc=jnp.zeros((p,), i32),
        n_blob_free=jnp.zeros((p,), i32),
        n_blob_remote=jnp.zeros((p,), i32),
        n_blob_moved=jnp.zeros((p,), i32),
        type_state=type_state,
    )


def geometry_descriptor(program: Program, opts: RuntimeOptions):
    """The layout facts a snapshot must carry so a restore can re-lay-out
    the SoA arrays into a DIFFERENT geometry (serialise.py): everything
    that sizes an array without changing program STRUCTURE. Cohorts are
    in declaration order (behaviour gids depend on it — covered by the
    structural fingerprint); slots are the geometry-independent actor
    identity (slot s of cohort C is the same actor whatever the shard
    count or capacity)."""
    assert program.frozen
    return {
        "shards": program.shards,
        "n_local": program.n_local,
        "total": program.total,
        "mailbox_cap": opts.mailbox_cap,
        "msg_words": opts.msg_words,
        "trace_lanes": opts.trace_lanes,
        "spill_cap": opts.spill_cap,
        "mute_slots": opts.mute_slots,
        "blob_slots": opts.blob_slots,
        "blob_words": opts.blob_words,
        "analysis": opts.analysis,
        "trace_slots": opts.trace_slots if opts.tracing else 0,
        "analysis_events": (opts.analysis_events
                            if opts.analysis >= 3 else 0),
        "cohorts": [{
            "name": c.atype.__name__,
            "capacity": c.capacity,
            "local_capacity": c.local_capacity,
            "local_start": c.local_start,
            "host": bool(c.host),
            "msg_words": c.msg_words,
        } for c in program.cohorts],
    }


def state_partition_specs(program: Program, opts: RuntimeOptions):
    """PartitionSpec pytree matching RtState: every array shards its
    LAST axis over the 'actors' mesh axis (the lane/actor dimension —
    see the layout note above); leading static dims replicate."""
    from jax.sharding import PartitionSpec as P
    shapes = jax.eval_shape(lambda: init_state(program, opts))
    return jax.tree.map(
        lambda leaf: P(*([None] * (len(leaf.shape) - 1) + ["actors"])),
        shapes)
