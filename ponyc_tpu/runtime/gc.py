"""Actor garbage collection: whole-world parallel reachability tracing.

≙ the reference's actor-collection machinery, re-designed for TPU:

- ORCA deferred reference counting (src/libponyrt/gc/gc.c:38-435,
  actormap/objectmap) exists because *distributed tracing is impractical
  on CPUs* — actors would have to pause each other. On a TPU the whole
  actor world is one address space of SoA columns, so the idiomatic
  equivalent is a synchronous parallel trace: mark everything reachable
  from the roots with a vectorised frontier propagation, one masked
  scatter per hop, `lax.while_loop` to fixpoint.
- The cycle detector (gc/cycle.c:345-651 scan_grey/collect + CNF/ACK)
  exists because reference counting can't see cycles. Tracing collects
  cycles for free — a cycle of blocked actors unreachable from any root
  is simply never marked.

Roots (≙ "rc > 0" in ORCA terms):
  - host-pinned actors (Runtime.spawn pins; release() unpins) ≙ the
    external/application reference an actor is born with (actor.c:688);
  - actors with queued or in-flight (spilled) messages ≙ messages hold
    rc while in flight (ORCA's send-increment rule);
  - muted actors (they have rejected traffic parked in a spill);
  - host-cohort rows (host actors are host-managed, never collected);
  - extra host-side roots passed per collection: refs held in host-actor
    state dicts and in the pending inject queue.

Edges: Ref-typed state fields of live actors, and Ref-typed arguments of
every queued/spilled message (the behaviour signature's Ref annotations
are the trace functions ≙ the compiler-generated gentrace.c ones).

Termination: each iteration extends reachability by one hop, so the loop
runs at most graph-diameter times; `gc_max_iters` (0 = unbounded) caps
pathological chains — if the cap is hit before fixpoint, *nothing* is
collected that round (conservative, always safe).

Collection frees the slot (alive=False) — the row becomes claimable by
ctx.spawn / Runtime.spawn. Sends to a collected actor dead-letter, which
Pony's type system makes unrepresentable; here it is a counted drop.

The same pass sweeps the device blob pool (≙ an actor's heap dying with
the actor, mem/heap.c): a pool slot survives iff a surviving actor's
Blob field holds its handle, a queued/spilled/injected message's Blob
argument carries it, or the host owns it (blob_store not yet sent).
Marking is shard-local by design — after migration (engine._route moves
a blob WITH its routed message) every reachable handle is local to its
pool's shard; the rare off-shard handle (host injection without
near=, or a migration drop) is undereferenceable and is collected.

Megakernel cadence note (PR 11, ops/megakernel.py): GC keeps its own
host-cadence dispatch (Runtime.run fires jit_gc between windows, gated
by gc_interval) rather than fusing into the persistent window kernel —
the mark loop's fixpoint trip count is data-dependent and its masked
scatters want XLA's full scatter lowering, and the windows the kernel
fuses never spawn or collect mid-window. The megakernel therefore
reads/writes the same alive/pin/spill tables this pass does, in int32;
the int16 bandwidth-diet packing exists only at the kernel operand
boundary and is invisible here.
"""

from __future__ import annotations



import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import RuntimeOptions
from ..program import Program
from .state import RtState


def build_ref_arg_mask(program: Program, msg_words: int) -> np.ndarray:
    """Static [n_gids, msg_words] bool: which payload words of each
    behaviour message are actor refs (≙ the per-type trace function the
    compiler emits, gentrace.c — here derived from Ref annotations)."""
    from ..ops.pack import is_ref, spec_width
    n = len(program.behaviour_table)
    mask = np.zeros((max(n, 1), msg_words), bool)
    for gid, bdef in enumerate(program.behaviour_table):
        off = 0
        for spec in bdef.arg_specs:
            if is_ref(spec) and off < msg_words:
                mask[gid, off] = True
            off += spec_width(spec)
    return mask


def _ref_fields(cohort):
    from ..ops.pack import is_ref
    return [f for f, spec in cohort.atype.field_specs.items()
            if is_ref(spec)]


def build_blob_arg_mask(program: Program, msg_words: int,
                        mode: str | None = None) -> np.ndarray:
    """Static [n_gids, msg_words] bool: which payload words of each
    behaviour message are device blob handles (the Blob twin of
    build_ref_arg_mask — ≙ gentrace.c tracing message object fields).
    `mode` narrows to one capability ("iso": owned/moving handles,
    "val": shared-immutable); None = both."""
    from ..ops.pack import is_blob, spec_width
    n = len(program.behaviour_table)
    mask = np.zeros((max(n, 1), msg_words), bool)
    for gid, bdef in enumerate(program.behaviour_table):
        off = 0
        for spec in bdef.arg_specs:
            if (is_blob(spec) and off < msg_words
                    and (mode is None or spec.mode == mode)):
                mask[gid, off] = True
            off += spec_width(spec)
    return mask


def _blob_fields(cohort):
    from ..ops.pack import is_blob
    return [f for f, spec in cohort.atype.field_specs.items()
            if is_blob(spec)]


def build_gc(program: Program, opts: RuntimeOptions):
    """Trace the collection pass; returns local_gc(state, extra_roots)
    → (state, (n_collected_total, converged, iters)) in per-shard
    coordinates (wrap like the step: jit for P=1, shard_map for P>1)."""
    assert program.frozen
    p = program.shards
    nl = program.n_local
    ntot = p * nl
    fh = program.first_host_row
    cap = opts.mailbox_cap
    ref_mask_np = build_ref_arg_mask(program, opts.msg_words)
    any_ref_args = bool(ref_mask_np.any())
    n_gids = ref_mask_np.shape[0]
    max_iters = opts.gc_max_iters
    bsl = opts.blob_slots
    blob_mask_np = build_blob_arg_mask(program, opts.msg_words)
    any_blob_args = bool(blob_mask_np.any())
    # Sweep whenever the pool is live and ANY cohort can allocate or
    # carry handles: a program whose handles never escape the allocating
    # behaviour (no Blob fields/args) makes every unfreed blob garbage
    # by construction — exactly what the sweep must reclaim.
    sweep_blobs = bsl > 0 and (any_blob_args
                               or any(_blob_fields(c)
                                      for c in program.cohorts)
                               or any(c.blob_sites
                                      for c in program.cohorts))

    def local_gc(st: RtState, extra_roots, blob_roots):
        if p > 1:
            shard = lax.axis_index("actors").astype(jnp.int32)
        else:
            shard = jnp.int32(0)
        base = shard * nl
        occ = st.tail - st.head
        rows = jnp.arange(nl, dtype=jnp.int32)

        # --- roots ---
        roots = (st.pinned | extra_roots | (occ > 0) | st.muted
                 | (rows >= fh))

        # Initial global marks: local roots + in-flight spill traffic.
        marks0 = jnp.zeros((ntot,), jnp.bool_).at[
            jnp.where(roots, base + rows, ntot)].max(True, mode="drop")
        for tgt_arr, words_arr in (
                (jnp.where(st.dspill_tgt >= 0, base + st.dspill_tgt, -1),
                 st.dspill_words),                 # words planar [w1, S]
                (st.rspill_tgt, st.rspill_words)):
            marks0 = marks0.at[jnp.where(tgt_arr >= 0, tgt_arr, ntot)].max(
                True, mode="drop")
            if any_ref_args:
                gid = words_arr[0]
                g = jnp.clip(gid, 0, n_gids - 1)
                inr = (gid >= 0) & (gid < n_gids) & (tgt_arr >= 0)
                # Payload words only: with tracing on the spill tables
                # carry two trailing (trace_id, parent_span) rows that
                # are never refs.
                for w in range(min(words_arr.shape[0] - 1,
                                   opts.msg_words)):
                    rm = jnp.asarray(ref_mask_np)[g, w] & inr
                    refs = jnp.where(rm, words_arr[1 + w], -1)
                    marks0 = marks0.at[
                        jnp.where(refs >= 0, refs, ntot)].max(
                        True, mode="drop")

        # Pre-extract edges (targets are global ids; sources are local).
        # State-field edges, one [local_cap] target column per Ref field.
        field_edges = []   # (src_slice_start, src_slice_stop, targets)
        for cohort in program.device_cohorts:
            for fname in _ref_fields(cohort):
                col = st.type_state[cohort.atype.__name__][fname]
                field_edges.append((cohort.local_start, cohort.local_stop,
                                    col.astype(jnp.int32)))
        # Mailbox edges: ref args of queued messages. Planar over each
        # cohort's [cap, w1_c, rows] table (per-cohort widths): ring slot
        # ci holds a live message iff (ci - head) mod cap < occupancy;
        # each payload word that the static ref mask marks contributes a
        # [rows_c]-wide plane padded into an [nl] lane (targets are -1
        # outside the cohort's rows).
        # ONE walk serves both masks (ref args feed the actor trace,
        # Blob args feed the blob sweep) — the ring-validity and gid
        # computations are shared per (cohort, slot).
        mb_planes = []                                    # [nl] each
        mbb_planes = []                                   # blob handles
        if any_ref_args or (sweep_blobs and any_blob_args):
            rmask = jnp.asarray(ref_mask_np)
            bmask = jnp.asarray(blob_mask_np)
            for cohort in program.cohorts:
                cbuf = st.buf[cohort.atype.__name__]
                s0, s1 = cohort.local_start, cohort.local_stop
                if cbuf.shape[1] <= 1:
                    continue                   # gid-only mailboxes: no refs
                for ci in range(cap):
                    valid = ((ci - st.head[s0:s1]) % cap) < occ[s0:s1]
                    gid = cbuf[ci, 0]
                    g = jnp.clip(gid, 0, n_gids - 1)
                    inr = valid & (gid >= 0) & (gid < n_gids)
                    for w in range(cbuf.shape[1] - 1):
                        if any_ref_args:
                            rm = rmask[g, w] & inr
                            plane = jnp.full((nl,), -1, jnp.int32).at[
                                s0 + jnp.arange(s1 - s0)].set(
                                jnp.where(rm, cbuf[ci, 1 + w], -1))
                            mb_planes.append(plane)
                        if sweep_blobs and any_blob_args:
                            bmm = bmask[g, w] & inr
                            mbb_planes.append(
                                jnp.where(bmm, cbuf[ci, 1 + w], -1))
        mb_tgt = jnp.stack(mb_planes) if mb_planes else None

        def propagate(live):
            """One hop: mark every target referenced by a live source."""
            marks = jnp.zeros((ntot,), jnp.bool_).at[
                jnp.where(live, base + rows, ntot)].max(True, mode="drop")
            for s0, s1, tgt in field_edges:
                src_ok = live[s0:s1] & st.alive[s0:s1] & (tgt >= 0)
                marks = marks.at[jnp.where(src_ok, tgt, ntot)].max(
                    True, mode="drop")
            if mb_tgt is not None:
                src_ok = live[None, :] & (mb_tgt >= 0)
                marks = marks.at[
                    jnp.where(src_ok, mb_tgt, ntot).reshape(-1)].max(
                    True, mode="drop")
            return marks

        def glob(marks):
            if p > 1:
                marks = lax.psum(marks.astype(jnp.int32), "actors") > 0
            return lax.dynamic_slice(marks, (base,), (nl,))

        live0 = glob(marks0)

        def cond(carry):
            _, changed, it = carry
            going = changed
            if max_iters:
                going = going & (it < max_iters)
            return going

        def body(carry):
            live, _, it = carry
            new_live = live | glob(propagate(live))
            ch = jnp.any(new_live != live)
            if p > 1:
                ch = lax.psum(ch.astype(jnp.int32), "actors") > 0
            return new_live, ch, it + 1

        live, changed, iters = lax.while_loop(
            cond, body, (live0, jnp.bool_(True), jnp.int32(0)))
        converged = ~changed

        # --- collect (only on a converged trace; ≙ cycle.c `collect`) ---
        dead = st.alive & ~live & (rows < fh) & converged
        n_dead = jnp.sum(dead.astype(jnp.int32))

        # --- blob sweep (≙ an actor's heap dying with it, gc.c/heap.c):
        # a pool slot stays allocated iff a surviving actor's Blob FIELD
        # holds it, a queued/spilled message's Blob ARG carries it, or
        # the host declared it a root (rt.blob_store handles not yet
        # sent). Marking is shard-LOCAL on purpose: migration
        # (engine._route) re-homes a payload WITH its routed message,
        # so every resting reachable handle is local to its pool's
        # shard; the rare off-shard handle (host injection without
        # near=, migration drop) is undereferenceable and collects.
        n_swept = jnp.int32(0)
        blob_used2, blob_len2 = st.blob_used, st.blob_len
        nbf2 = st.n_blob_free
        if sweep_blobs:
            bbase = shard * bsl
            alive2 = st.alive & ~dead

            from ..ops import pack as _pk

            def bmark(marks, handles, ok):
                """Mark gen-MATCHING local handles only: a stale handle
                to a recycled slot is dead and must not keep the new
                occupant alive (ops.pack handle encoding)."""
                hl = _pk.blob_slot(handles) - bbase
                good = ok & (handles >= 0) & (hl >= 0) & (hl < bsl)
                hs = jnp.where(good, hl, bsl)
                good = good & (jnp.take(st.blob_gen, hs, mode="fill",
                                        fill_value=-1)
                               == _pk.blob_gen_of(handles))
                return marks.at[jnp.where(good, hl, bsl)].max(
                    True, mode="drop")

            bm = blob_roots
            for cohort in program.device_cohorts:
                s0, s1 = cohort.local_start, cohort.local_stop
                for fname in _blob_fields(cohort):
                    col = st.type_state[cohort.atype.__name__][fname]
                    bm = bmark(bm, col.astype(jnp.int32), alive2[s0:s1])
            if any_blob_args:
                bmask2 = jnp.asarray(blob_mask_np)
                for tgt_arr, words_arr in (
                        (st.dspill_tgt, st.dspill_words),
                        (st.rspill_tgt, st.rspill_words)):
                    gid = words_arr[0]
                    g = jnp.clip(gid, 0, n_gids - 1)
                    inr = (gid >= 0) & (gid < n_gids) & (tgt_arr >= 0)
                    for w in range(min(words_arr.shape[0] - 1,
                                       opts.msg_words)):
                        bm = bmark(bm, words_arr[1 + w],
                                   bmask2[g, w] & inr)
                # Queued-message handles: planes collected by the shared
                # mailbox walk above (-1 where not a valid Blob arg).
                for bplane in mbb_planes:
                    bm = bmark(bm, bplane, bplane >= 0)
            swept = st.blob_used & ~bm
            n_swept = jnp.sum(swept.astype(jnp.int32))
            blob_used2 = st.blob_used & bm
            blob_len2 = jnp.where(swept, 0, st.blob_len)
            nbf2 = st.n_blob_free + n_swept.reshape(1)

        st2 = RtState(
            buf=st.buf,
            head=jnp.where(dead, st.tail, st.head),
            tail=st.tail,
            alive=st.alive & ~dead,
            muted=st.muted & ~dead,
            mute_refs=jnp.where(dead[None, :], -1, st.mute_refs),
            mute_age=jnp.where(dead, 0, st.mute_age),
            mute_ovf=st.mute_ovf & ~dead,
            pinned=st.pinned & ~dead,
            pressured=st.pressured & ~dead,
            dspill_tgt=st.dspill_tgt, dspill_sender=st.dspill_sender,
            dspill_words=st.dspill_words, dspill_count=st.dspill_count,
            rspill_tgt=st.rspill_tgt, rspill_sender=st.rspill_sender,
            rspill_words=st.rspill_words, rspill_count=st.rspill_count,
            spill_overflow=st.spill_overflow,
            exit_flag=st.exit_flag, exit_code=st.exit_code,
            step_no=st.step_no,
            n_processed=st.n_processed, n_delivered=st.n_delivered,
            n_rejected=st.n_rejected, n_badmsg=st.n_badmsg,
            n_deadletter=st.n_deadletter, n_mutes=st.n_mutes,
            n_spawned=st.n_spawned, n_destroyed=st.n_destroyed,
            spawn_fail=st.spawn_fail,
            n_collected=st.n_collected + n_dead.reshape(1),
            last_error=jnp.where(dead, 0, st.last_error),
            last_error_loc=jnp.where(dead, 0, st.last_error_loc),
            n_errors=st.n_errors,
            ev_data=st.ev_data, ev_count=st.ev_count,
            ev_dropped=st.ev_dropped,
            # Profiler lanes pass through untouched: collection frees
            # actors, it dispatches nothing — the window stats the
            # profiler reports about GC itself (passes run, actors
            # collected, blob slots swept) ride this function's return
            # values into Runtime.gc()'s host accounting.
            beh_runs=st.beh_runs, beh_delivered=st.beh_delivered,
            beh_rejected=st.beh_rejected,
            coh_mute_ticks=st.coh_mute_ticks,
            qwait_hist=st.qwait_hist, qwait_enq=st.qwait_enq,
            phase_cost=st.phase_cost,
            # Trace lanes/span ring pass through: collection dispatches
            # nothing, so no spans; dead rows' ring-slot lanes are
            # unreadable (head := tail) and re-stamped on next delivery.
            trace_buf=st.trace_buf, span_data=st.span_data,
            span_count=st.span_count, span_dropped=st.span_dropped,
            span_next=st.span_next,
            # Plan cache passes through: next step's key vector is
            # computed against the new `alive`, so deliveries to
            # collected actors invalidate it by comparison, not here.
            plan_key=st.plan_key, plan_perm=st.plan_perm,
            plan_bounds=st.plan_bounds,
            # Collection can only CLEAR muted/pressured bits (dead rows);
            # stale-high world bits cost one extra gather next tick and
            # the vote then corrects them.
            world_bits=st.world_bits,
            # Blob pool: swept by the mark pass above (data words left in
            # place — a freed slot zeroes on its next alloc).
            blob_data=st.blob_data, blob_used=blob_used2,
            blob_len=blob_len2, blob_gen=st.blob_gen,
            blob_fail=st.blob_fail,
            blob_budget_fail=st.blob_budget_fail,
            n_blob_alloc=st.n_blob_alloc, n_blob_free=nbf2,
            n_blob_remote=st.n_blob_remote,
            n_blob_moved=st.n_blob_moved,
            type_state=st.type_state,
        )
        if p > 1:
            n_dead = lax.psum(n_dead, "actors")
            n_swept = lax.psum(n_swept, "actors")
        return st2, (n_dead, converged, iters, n_swept)

    return local_gc


def jit_gc(program: Program, opts: RuntimeOptions, mesh=None):
    """Jit the collection pass (shard_map over 'actors' when meshed)."""
    gc = build_gc(program, opts)
    if program.shards == 1:
        return jax.jit(gc, donate_argnums=(0,))
    from jax.sharding import PartitionSpec as P
    from .state import state_partition_specs
    sharded = P("actors")
    repl = P()
    state_spec = state_partition_specs(program, opts)
    from ..compat import shard_map
    mapped = shard_map(
        gc, mesh=mesh,
        in_specs=(state_spec, sharded, sharded),
        out_specs=(state_spec, (repl, repl, repl, repl)))
    return jax.jit(mapped, donate_argnums=(0,))
