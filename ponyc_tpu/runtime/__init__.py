"""Runtime core: device state, dispatch engine, delivery, host driver."""

from .runtime import Runtime, SpillOverflowError  # noqa: F401
