"""Adaptive quiesce-window controller — the TPU analog of the fork's
adaptive scheduler sleeping (DIVERGENCE.md: schedulers size their
idle/active windows to observed load instead of a fixed cadence;
scheduler.c:918-935 scaling_sleep is the shrink side, the suspend
threshold the grow side).

Here the "window" is the tick budget of one fused device dispatch
(engine.build_multi_step_gated): long windows amortise the per-dispatch
host/RPC overhead (the round-2 60 ms/tick headline was almost all
dispatch), short windows keep host reaction latency low. Neither is
right statically — the right length is a function of observed load, so
the run loop feeds every retired window's facts into this controller
and dispatches the next window at whatever it says.

Policy (MIMD — multiplicative increase, multiplicative decrease, the
same shape as the fork's exponential sleep scaling):

  - a window that ran its FULL budget with zero host attention is
    evidence the device is busy and the host idle → GROW geometrically
    (×2) toward `hi`;
  - a window cut short by host attention (host-cohort mail, exit,
    fatal flags) is evidence the host needs the boundary sooner →
    SHRINK (×½) toward `lo`; likewise when the device's queue-wait p99
    (StepAux.qw_p99, the PR 4 on-device histograms) climbs past the
    window length — messages are waiting longer than a whole window,
    so amortisation is no longer the bottleneck;
  - a window that quiesced early (device went idle mid-window) is
    evidence of neither → HOLD.

The controller is a pure host object: `observe()` is deterministic in
its arguments (tests replay recorded attention traces and assert the
exact decision sequence), never touches the device, and `window` is
always an int in [lo, hi]. With lo == hi it degrades to the fixed
window of a concrete `quiesce_interval=N` — one code path either way.
"""

from __future__ import annotations

import collections

GROW_FACTOR = 2.0
SHRINK_FACTOR = 0.5
# Consecutive full-budget quiet windows at the SAME length before the
# controller reports "steady" (it keeps growing before that; at hi the
# count runs against the clamp).
STEADY_AFTER = 3

# Recent decisions kept for the flight-recorder postmortem (flight.py):
# enough to show a shrink storm or oscillation around a stall without
# growing with run length.
RECENT_DECISIONS = 32


class WindowController:
    """Per-runtime adaptive window sizer. `state` is one of "grow",
    "shrink", "steady" — surfaced by Runtime dump()/top for
    observability, and "steady" additionally gates the tuning-cache
    write-back of a converged window (tuning.store_quiesce_interval)."""

    def __init__(self, initial: int, lo: int, hi: int,
                 grow: float = GROW_FACTOR, shrink: float = SHRINK_FACTOR):
        if lo < 1 or hi < lo:
            raise ValueError(f"window bounds must satisfy 1 <= lo <= hi "
                             f"(got lo={lo}, hi={hi})")
        self.lo = int(lo)
        self.hi = int(hi)
        self.grow = float(grow)
        self.shrink = float(shrink)
        self.window = min(self.hi, max(self.lo, int(initial)))
        self.state = "steady"
        self.grows = 0          # lifetime decision counts (observability)
        self.shrinks = 0
        self.holds = 0
        self._same = 0          # consecutive full-quiet windows here
        # Bounded decision trail for postmortems: one small tuple per
        # observe(), evicted FIFO — negligible against the window cost.
        self.recent: collections.deque = collections.deque(
            maxlen=RECENT_DECISIONS)

    def clamp(self, v: int) -> int:
        return min(self.hi, max(self.lo, int(v)))

    def observe(self, ran: int, budget: int, attention: bool,
                qw_p99: int = 0) -> int:
        """Feed one retired window's facts; returns the next window
        budget. `ran` = ticks executed, `budget` = ticks granted,
        `attention` = the window ended because the host had to act
        (host-cohort mail / exit / fatal — NOT early quiescence),
        `qw_p99` = the device queue-wait p99 in ticks (0 = unknown)."""
        pressured = qw_p99 > self.window > self.lo
        if attention or pressured:
            nxt = self.clamp(int(self.window * self.shrink))
            self.state = "shrink"
            self.shrinks += 1
            self._same = 0
        elif ran >= budget and budget >= self.window:
            # Full-budget exit with a quiet host: grow. (budget <
            # window means the caller clamped the grant — e.g. a
            # max_steps remainder — which says nothing about load.)
            nxt = self.clamp(int(self.window * self.grow))
            if nxt == self.window:
                self._same += 1
                self.state = "steady" if self._same >= STEADY_AFTER \
                    else self.state
                self.holds += 1
            else:
                self.state = "grow"
                self.grows += 1
                self._same = 0
        else:
            # Early quiescence (or a clamped grant): hold.
            nxt = self.window
            self.holds += 1
            self._same += 1
            if self._same >= STEADY_AFTER:
                self.state = "steady"
        self.window = nxt
        self.recent.append((int(ran), int(budget), bool(attention),
                            int(qw_p99), nxt, self.state))
        return nxt

    def recent_decisions(self) -> list:
        """The bounded decision trail, newest last, as dicts — the
        controller section of a flight-recorder postmortem."""
        return [{"ran": r, "budget": b, "attention": a, "qw_p99": q,
                 "window": w, "state": s}
                for (r, b, a, q, w, s) in self.recent]

    def snapshot(self) -> dict:
        """Observable controller state (dump()/top/bench)."""
        return {"window": self.window, "state": self.state,
                "lo": self.lo, "hi": self.hi, "grows": self.grows,
                "shrinks": self.shrinks, "holds": self.holds}
