"""The dispatch step: one scheduler tick over the whole actor world, jitted.

≙ the reference's hot loop (SURVEY.md §3.3): scheduler `run`
(src/libponyrt/sched/scheduler.c:953-1090) popping actors and
`ponyint_actor_run` (src/libponyrt/actor/actor.c:383-549) draining up to
`batch` messages per actor through `type->dispatch`. On TPU there is no
work-stealing — the entire world advances in lockstep:

  per device cohort (actors of one type, contiguous per-shard rows):
      gather  ≤batch messages per actor from the mailbox table
      scan    over batch slots; per slot a `lax.switch` over the type's
              behaviours (≙ the generated dispatch switch, genfun.c),
              vmapped over the cohort's actors
      collect sends / exit / yield effects functionally
  route   (mesh only) bucket every produced message by target shard and
          exchange with one `lax.all_to_all` over the ICI — the
          communication backend the single-process reference never needed
          (SURVEY.md §2.4); bucket overflow parks messages in the sender
          shard's route-spill, muting the sender
  deliver one stable sort + scatter per shard writes every message whose
          target lives here (see delivery.py), mute/unmute updates
  vote    quiescence = psum over shards of pending-work bits — the
          collective analog of the CNF/ACK token protocol
          (scheduler.c:303-480)

Work-stealing, victim selection and scaling-sleep (scheduler.c:485-935)
have no TPU analog — idle actors cost one masked lane, not a core.

The same traced function serves single-chip (P=1: no collectives, plain
jit) and meshed execution (shard_map over an 'actors' axis); per-shard
"scalars" are [1]-shaped so local and global layouts coincide.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..api import Context
from ..config import RuntimeOptions
from ..ops import pack
from ..ops.segment import compact_mask, counts_by_key, stable_sort_by
from ..program import Cohort, Program
from .delivery import (Entries, deliver, empty_mute_slots, mute_ref_slots)
from .state import PHASE_NAMES, QW_BUCKETS, RtState, layout_sizes


class StepAux(NamedTuple):
    """Small per-step scalars fetched by the host driver (≙ the scheduler's
    control-message reads + quiescence vote, scheduler.c:303-480). All
    entries are mesh-wide aggregates (replicated when sharded)."""
    device_pending: jnp.ndarray  # bool — any device mailbox/spill work left
    host_pending: jnp.ndarray    # bool — host-cohort mailboxes non-empty
    exit_flag: jnp.ndarray       # bool — some behaviour called ctx.exit
    exit_code: jnp.ndarray       # int32
    spill_overflow: jnp.ndarray  # bool — fatal: a spill buffer exceeded
    spawn_fail: jnp.ndarray      # bool — fatal: ctx.spawn found no slot
    blob_fail: jnp.ndarray       # bool — fatal: ctx.blob_alloc found the
    #   POOL exhausted (≙ pony_alloc exhausting the heap; raise
    #   RuntimeOptions.blob_slots)
    blob_budget_fail: jnp.ndarray  # bool — fatal: ctx.blob_alloc ran
    #   past the actor's per-tick BLOB_DISPATCHES reservation budget
    #   (free slots may remain; raise the class's BLOB_DISPATCHES)
    any_muted: jnp.ndarray       # bool — some actor still carries a mute
    #   flag; run() uses it for bounded CLEANUP ticks at quiescence so a
    #   terminated world ends unmuted (the unmute pass lags the drain
    #   that satisfies it by one tick)
    n_processed: jnp.ndarray     # int32 — *cumulative* behaviours run
    n_delivered: jnp.ndarray     # int32 — *cumulative* deliveries
    # (cumulative = state counters; the host accumulates mod-2^32 deltas,
    # so fetches may be arbitrarily far apart as long as fewer than 2^31
    # events occur between two fetches.)
    # Telemetry aggregates (≙ --ponyanalysis, analysis.c): traced as real
    # reductions only when opts.analysis >= 1, else constant zeros that
    # XLA folds away — opt-in observability at zero steady-state cost.
    occ_sum: jnp.ndarray         # int32 — total queued messages
    occ_max: jnp.ndarray         # int32 — deepest mailbox
    n_muted_now: jnp.ndarray     # int32 — actors currently muted
    n_overloaded_now: jnp.ndarray  # int32 — occupancy > overload threshold
    # Cumulative mesh-wide counters (zeros unless analysis >= 1) so the
    # CSV window writer needs no extra device fetches.
    n_rejected: jnp.ndarray      # int32
    n_badmsg: jnp.ndarray        # int32
    n_deadletter: jnp.ndarray    # int32
    n_mutes: jnp.ndarray         # int32
    qw_p99: jnp.ndarray          # int32 — worst per-cohort queue-wait
    #   p99 (ticks, 2^k bucket lower bound) of the CUMULATIVE on-device
    #   histograms (profile_lanes), mesh max. Zero unless analysis >= 1.
    #   The adaptive window controller (runtime/controller.py) shrinks
    #   the quiesce window when this climbs past the window length —
    #   long windows trade host-event latency for dispatch amortisation,
    #   and this lane is the device's vote that the trade went bad.


def _ring_take(buf_rows, slot):
    """Pull ring-slot `slot[r]` of every actor r: [cap, w1, R] × [R] →
    [w1, R]. The per-lane index varies only over the small static `cap`
    axis, so a static select chain keeps every op a full-width vector op
    (a gather along a tiny major axis would defeat the lane layout —
    see state.py's layout note)."""
    cap = buf_rows.shape[0]
    out = buf_rows[0]
    for c in range(1, cap):
        out = jnp.where((slot == c)[None, :], buf_rows[c], out)
    return out


def _bcast_lanes(v, dtype, lanes: int):
    """Canonicalise a behaviour output to a [lanes] vector (user code may
    return trace-time constants — Python scalars — for some lanes-wide
    quantities)."""
    return jnp.broadcast_to(jnp.asarray(v, dtype), (lanes,))



def eval_behaviour(bdef, st, payload, ids_vec, *, msg_words: int,
                   field_specs, field_dtypes, lanes: int, max_sends: int,
                   spawn_resv=None, spawn_meta=None, blob=None):
    """Shared behaviour-evaluation core: build the Context, tag typed
    refs, run the traced body, validate + broadcast the state update,
    and collect when-masked send planes padded to the send budget.
    Used by BOTH dispatch formulations (the planar XLA branch below and
    ops/fused_dispatch's kernel) so their semantics cannot drift.
    `blob` (device pool enabled only): an api.BlobPoolView the blob ops
    mutate eagerly — see its docstring for why sequential application
    is exact. Returns (ctx, st2, tgts, words)."""
    w1 = 1 + msg_words
    ctx = Context(ids_vec, msg_words, spawn_resv=spawn_resv,
                  spawn_meta=spawn_meta, blob=blob)
    args = pack.unpack_args(bdef.arg_specs, payload)
    if blob is not None:
        # Blob handles are dereferenceable only on their pool's shard;
        # migration (engine._route) re-homes payloads with their routed
        # messages, so mailbox handles are local by the time they
        # dispatch. The residue — host injections without near=, or
        # migration drops — reads as null (-1) and counts: defined,
        # loud, never a wrong read. ≙ nothing in the reference (it is
        # single-node; there is no remote heap to dereference).
        nulled = []
        for spec, a in zip(bdef.arg_specs, args):
            if pack.is_blob(spec):
                a = jnp.asarray(a, jnp.int32)
                slot = pack.blob_slot(a)
                local_ok = ((a >= 0) & (slot >= blob.base)
                            & (slot < blob.base + blob.nslots))
                remote = (a >= 0) & ~local_ok
                blob.n_remote = blob.n_remote + jnp.sum(
                    (remote & blob.take).astype(jnp.int32))
                a = jnp.where(local_ok, a, jnp.int32(-1))
            nulled.append(a)
        args = nulled
    # Typed Ref[T] state fields and args enter the behaviour as PLAIN
    # arrays whose trace-time identity is tagged with the declared
    # type (pack.RefTypes), so Context.send verifies wiring at trace
    # time (≙ type/safeto.c sendability; the verify pass of the
    # build) without touching how refs behave under jnp ops.
    for k, v in st.items():
        ctx.ref_types.tag(v, pack.ref_target(field_specs[k]))
        ctx.cap_types.tag(v, pack.cap_mode(field_specs[k]))
    for spec, a in zip(bdef.arg_specs, args):
        ctx.ref_types.tag(a, pack.ref_target(spec))
        ctx.cap_types.tag(a, pack.cap_mode(spec))
    st2 = bdef.fn(ctx, dict(st), *args)
    if st2 is None:
        raise TypeError(
            f"behaviour {bdef} must return the (possibly updated) state "
            "dict")
    if set(st2.keys()) != set(st.keys()):
        raise TypeError(
            f"behaviour {bdef} changed the state fields: "
            f"{sorted(st2)} vs {sorted(st)}")
    for k, v in st2.items():
        want = pack.ref_target(field_specs[k])
        got = ctx.ref_types.lookup(v)
        if want is not None and got is not None and got != want:
            raise TypeError(
                f"sendability: behaviour {bdef} stores a Ref[{got}] "
                f"into field {k!r} declared Ref[{want}]")
        # Iso payloads are moved-unique (≙ cap.c/safeto.c): a handle the
        # behaviour just moved (sent as an Iso parameter) may not ALSO
        # be retained in state — including leaving an Iso field
        # untouched after moving it (overwrite with -1 to consume).
        moved = (None if pack.concrete_null_handle(v)
                 else ctx.cap_moves.was_moved(v))
        if moved is not None:
            raise TypeError(
                f"capability: behaviour {bdef} retains a moved iso "
                f"payload in field {k!r} (moved by {moved}); an iso is "
                "moved-unique — clear the field (e.g. -1) or use Val "
                "for shared-immutable payloads")
        # Store lattice (≙ is_cap_sub_cap): the stored value's
        # capability provenance must cover the field's declared mode
        # (a shared val cannot become a unique iso; a tag cannot
        # become readable).
        src = (None if pack.concrete_null_handle(v)
               else ctx.cap_types.lookup(v))
        dst = pack.cap_mode(field_specs[k])
        if not pack.cap_store_ok(src, dst):
            raise TypeError(
                f"capability: behaviour {bdef} stores a {src} payload "
                f"into field {k!r} declared {dst.capitalize()} — a "
                f"{src} value cannot grant the rights {dst} requires "
                "(is_cap_sub_cap, type/cap.c)")
    # An iso-provenance value stored into MORE THAN ONE field aliases a
    # unique (≙ alias.c): every field keeping it is a distinct owner.
    # A trn is WRITE-unique (cap.c): keeping it in the field it came
    # from is free, and Box/Tag stores alias it (read views — Pony's
    # trn+box sharing); but a CONSUMING store into a *different*
    # Trn/Mut/Val field (ownership/freeze, ≙ consume) must be the
    # value's only remaining appearance.
    origin_field = {}
    for k, v in st.items():
        origin_field.setdefault(id(v), k)
    iso_seen = {}
    trn_consumed = {}
    trn_retained = {}      # keeps + aliases (anything but the consume)
    for k, v in st2.items():
        if pack.concrete_null_handle(v):
            continue
        src = ctx.cap_types.lookup(v)
        if src == "iso":
            first = iso_seen.get(id(v))
            if first is not None:
                raise TypeError(
                    f"capability: behaviour {bdef} stores one iso "
                    f"payload into BOTH fields {first!r} and {k!r} — "
                    "an iso has exactly one owner (alias.c)")
            iso_seen[id(v)] = k
        elif src == "trn":
            dst = pack.cap_mode(field_specs[k])
            consuming = (dst in pack.CONSUMING_DSTS
                         and origin_field.get(id(v)) != k)
            if consuming:
                first = trn_consumed.get(id(v))
                if first is not None:
                    raise TypeError(
                        f"capability: behaviour {bdef} consumes one trn "
                        f"payload into BOTH fields {first!r} and {k!r} "
                        "— a trn is write-unique (cap.c); alias it Box "
                        "for read sharing")
                trn_consumed[id(v)] = k
            else:
                trn_retained.setdefault(id(v), k)
    for idv, kc in trn_consumed.items():
        ka = trn_retained.get(idv)
        if ka is not None:
            raise TypeError(
                f"capability: behaviour {bdef} consumes a trn payload "
                f"into field {kc!r} and ALSO retains it in {ka!r} — "
                "use-after-consume (alias.c)")
    st2 = {k: _bcast_lanes(v, field_dtypes[k], lanes)
           for k, v in st2.items()}
    if len(ctx.sends) > max_sends:
        raise RuntimeError(
            f"behaviour {bdef} performs {len(ctx.sends)} sends but the "
            f"type's send budget is {max_sends}; set MAX_SENDS = "
            f"{len(ctx.sends)} on the actor class")
    tgts, words = [], []
    for (t, w, when) in ctx.sends:
        t = _bcast_lanes(t, jnp.int32, lanes)
        when = _bcast_lanes(when, jnp.bool_, lanes)
        w = jnp.broadcast_to(w.reshape(w1, -1), (w1, lanes))
        tgts.append(jnp.where(when, t, jnp.int32(-1)))
        words.append(w)
    for _ in range(max_sends - len(ctx.sends)):
        tgts.append(jnp.full((lanes,), -1, jnp.int32))
        words.append(jnp.zeros((w1, lanes), jnp.int32))
    return ctx, st2, tgts, words


def _make_branch(bdef, msg_words: int, max_sends: int, field_dtypes,
                 field_specs, spawn_sites, spawn_meta, effects,
                 lanes: int):
    """Wrap one behaviour as a *planar* evaluator: it runs on ALL `lanes`
    actors of the cohort at once (state fields, args, and effect masks
    are [lanes] vectors) and the dispatcher selects its outputs where the
    message's behaviour id matches. This is exactly what `vmap` over
    `lax.switch` executes (batched switch runs every branch and selects),
    but written planar so no actor-major [lanes, small] intermediate is
    ever materialised (see state.py's layout note).

    spawn_sites: ordered (target_name, n_sites) static budget — every
    branch emits claims in this exact layout. effects: trace-time mutable
    record of which effects any behaviour of the cohort used (lets the
    engine skip dead scatters)."""
    w1 = 1 + msg_words

    def branch(st, payload, ids_vec, resv_k, blob_in=None, take=None):
        bv = None
        if blob_in is not None:
            # (pool arrays threaded sequentially through the branches —
            # see api.BlobPoolView for why no cross-branch select is
            # needed; resv row may be zero-sites for receive-only types.)
            from ..api import BlobPoolView
            bdata, bused, blen, bgen, bbase, bresv, bover = blob_in
            bv = BlobPoolView(bdata, bused, blen, bgen, bbase,
                              (take if take is not None
                               else jnp.ones((lanes,), jnp.bool_)),
                              bresv if (bresv is not None
                                        and bresv.shape[0]) else None,
                              budget_over=bover)
        ctx, st2, tgts, words = eval_behaviour(
            bdef, st, payload, ids_vec, msg_words=msg_words,
            field_specs=field_specs, field_dtypes=field_dtypes,
            lanes=lanes, max_sends=max_sends, spawn_resv=resv_k,
            spawn_meta=spawn_meta, blob=bv)
        effects["destroy"] = effects["destroy"] or ctx.destroy_called
        effects["error"] = effects["error"] or ctx.error_called
        effects["sync_init"] = (effects["sync_init"]
                                or bool(ctx.sync_inits))
        claims = []
        inits = []
        for tname, n in spawn_sites:
            got = [_bcast_lanes(g, jnp.int32, lanes)
                   for g in ctx.spawn_claims.get(tname, [])]
            got += [jnp.full((lanes,), -1, jnp.int32)] * (n - len(got))
            claims.append(got)
            # Sync-constructor field values per site (spawn_sync): the
            # `has` mask selects them over zero-defaults at claim time.
            t_specs = spawn_meta[tname]
            t_dt = {f: (jnp.float32 if s is pack.F32 else jnp.int32)
                    for f, s in t_specs.items()}
            site_map = ctx.sync_inits.get(tname, {})
            has_l, vals_l = [], {f: [] for f in t_specs}
            for s_i in range(n):
                ent = site_map.get(s_i)
                if ent is None:
                    has_l.append(jnp.zeros((lanes,), jnp.bool_))
                    for f, sp in t_specs.items():
                        d = pack.null_word(sp)
                        vals_l[f].append(jnp.full((lanes,), d, t_dt[f]))
                else:
                    ist, ok = ent
                    has_l.append(_bcast_lanes(ok, jnp.bool_, lanes))
                    for f in t_specs:
                        vals_l[f].append(
                            _bcast_lanes(ist[f], t_dt[f], lanes))
            inits.append((has_l, vals_l))
        b = jnp.bool_
        blob_out = None
        if bv is not None:
            blob_out = (bv.data, bv.used, bv.len_, bv.gen, bv.fail,
                        bv.budget_fail, bv.n_alloc, bv.n_free,
                        bv.n_remote,
                        _bcast_lanes(bv.alloced, jnp.bool_, lanes))
        return (st2, (tgts, words),
                (_bcast_lanes(ctx.exit_flag, b, lanes),
                 _bcast_lanes(ctx.exit_code, jnp.int32, lanes)),
                _bcast_lanes(ctx.yield_flag, b, lanes),
                claims, inits,
                _bcast_lanes(ctx.spawn_fail, b, lanes),
                _bcast_lanes(ctx.destroy_flag, b, lanes),
                (_bcast_lanes(ctx.error_flag, b, lanes),
                 _bcast_lanes(ctx.error_code, jnp.int32, lanes),
                 _bcast_lanes(ctx.error_loc, jnp.int32, lanes)),
                blob_out)

    return branch


def _qwait_bucket(delta):
    """Power-of-two bucket index of a queue-wait delta (in ticks):
    bucket k ↔ [2^k, 2^(k+1)) with deltas clipped to >= 1 and the last
    bucket open-ended — floor(log2) spelled as QW_BUCKETS-1 vector
    compares, which XLA fuses into the surrounding reductions."""
    d = jnp.maximum(delta, 1)
    b = jnp.zeros(d.shape, jnp.int32)
    for k in range(1, QW_BUCKETS):
        b = b + (d >= (1 << k)).astype(jnp.int32)
    return b


def profile_lanes(program: Program, opts: RuntimeOptions, st: RtState,
                  tail0, res, drain_facts, muted2):
    """The per-behaviour profiler lanes (≙ the fork's per-actor
    --ponyanalysis records, analysis.h:16-31, re-based on the cohort —
    the TPU unit of attribution). ONLY traced when opts.analysis >= 1:
    the caller gates the call itself, so at level 0 none of this exists
    in the jaxpr (the zero-cost test traps this function to prove it).

    All facts are recomputed from the ring head/tail advances rather
    than threaded out of the dispatch kernels, so ONE implementation
    covers both dispatch formulations (the XLA scan and the fused
    Pallas kernel) and their semantics cannot drift:

      - beh_runs[g]       += messages of behaviour g dispatched this
                             tick (ring slots [head0, head1) — the
                             drained prefix, yield-shortened included);
      - qwait_hist[c*QW+k] += dispatched messages of device cohort c
                             whose delivery→dispatch wait fell in
                             bucket k (deltas against the qwait_enq
                             stamps written at delivery);
      - coh_mute_ticks[c] += actors of device cohort c muted at end of
                             tick (actor-ticks: the integral of
                             muted_now);
      - beh_delivered[g]  += messages of behaviour g accepted into
                             mailboxes this tick (tail advance over the
                             post-delivery tables; host cohorts count —
                             the host drains those rows);
      - beh_rejected[g]   += this tick's capacity rejections by target
                             behaviour (the compacted spill's gid
                             words — per-tick semantics match
                             n_rejected: a parked message re-rejected
                             next tick counts again);
      - qwait_enq[type]    = enqueue-step stamps for freshly delivered
                             ring slots (read back by the next ticks'
                             deltas above).

    `drain_facts` = [(cohort, head_before, head_after)] in
    device-cohort order. Returns the six updated state fields."""
    cap = opts.mailbox_cap
    s_now = st.step_no[0]
    beh_runs = st.beh_runs
    beh_del = st.beh_delivered
    beh_rej = st.beh_rejected
    coh_mt = st.coh_mute_ticks
    qw_hist = st.qwait_hist
    qw_enq = dict(st.qwait_enq)
    ci = jnp.arange(cap, dtype=jnp.int32)[:, None]   # ring-slot planes

    def _count(mask):
        return jnp.sum(mask.astype(jnp.int32))

    # --- dispatch side: runs per behaviour + queue-wait histogram.
    for di, (ch, head0, head1) in enumerate(drain_facts):
        cname = ch.atype.__name__
        n_con = head1 - head0
        # Ring slot ci held a message drained this tick iff its
        # monotonic count fell in [head0, head0 + n_con).
        drained = ((ci - head0[None, :]) % cap) < n_con[None, :]
        gid = st.buf[cname][:, 0, :]                 # [cap, rows]
        for b in ch.behaviours:
            beh_runs = beh_runs.at[b.global_id].add(
                _count(drained & (gid == b.global_id)))
        bidx = _qwait_bucket(s_now - qw_enq[cname])
        for k in range(QW_BUCKETS):
            qw_hist = qw_hist.at[di * QW_BUCKETS + k].add(
                _count(drained & (bidx == k)))
        coh_mt = coh_mt.at[di].add(
            _count(muted2[ch.local_start:ch.local_stop]))

    # --- delivery side: acceptances per behaviour + enqueue stamps.
    for ch in program.cohorts:
        cname = ch.atype.__name__
        s0, s1 = ch.local_start, ch.local_stop
        n_new = res.tail[s0:s1] - tail0[s0:s1]
        fresh = ((ci - tail0[None, s0:s1]) % cap) < n_new[None, :]
        gid = res.buf[cname][:, 0, :]
        for b in ch.behaviours:
            beh_del = beh_del.at[b.global_id].add(
                _count(fresh & (gid == b.global_id)))
        if cname in qw_enq:                          # device cohorts
            qw_enq[cname] = jnp.where(fresh, s_now, qw_enq[cname])

    # --- rejects by target behaviour (the compacted spill is exactly
    # this tick's rejections, re-rejections of parked entries included).
    sp_gid = res.spill.words[0]
    sp_ok = res.spill.tgt >= 0
    for g in range(len(program.behaviour_table)):
        beh_rej = beh_rej.at[g].add(_count(sp_ok & (sp_gid == g)))

    return beh_runs, beh_del, beh_rej, coh_mt, qw_hist, qw_enq


def phase_cost_lanes(st: RtState, all_e, drain_facts, nproc_total,
                     n_spawned, n_destroyed):
    """Per-phase window telemetry (the device-cost observatory, ISSUE
    19): accumulate one deterministic work-unit tally per scheduler-tick
    phase into st.phase_cost (state.PHASE_NAMES order). ONLY traced when
    opts.analysis >= 1 — the caller gates the call itself, so at level 0
    none of this exists in the jaxpr (the zero-cost test traps this
    function exactly like profile_lanes).

    The tallies are recomputed from facts every dispatch formulation
    already produces (the profile_lanes recomputation trick), so the XLA
    scan window and the megakernel's jaxpr replay yield bit-identical
    lanes by construction:

      - delivery += valid delivery-list entries gathered this tick
                    (spill retries + host injections + routed sends);
      - drain    += mailbox ring slots consumed (head advances, the
                    yield-shortened prefix included — >= dispatch:
                    drained-but-dropped badmsg rows count here only);
      - dispatch += behaviours actually run (the n_processed increment);
      - gc_mark  += spawn/destroy bookkeeping rows touched (claimed
                    spawns + completed destroys — the slot-lifecycle
                    work the GC pass marks from).

    Work units, not wall time: wall/bytes attribution is the measured
    layer's job (costs.py)."""
    pc = st.phase_cost
    delivery = jnp.sum((all_e.tgt >= 0).astype(jnp.int32))
    drained = jnp.int32(0)
    for _ch, head0, head1 in drain_facts:
        drained = drained + jnp.sum(head1 - head0)
    pc = pc.at[PHASE_NAMES.index("delivery")].add(delivery)
    pc = pc.at[PHASE_NAMES.index("drain")].add(drained)
    pc = pc.at[PHASE_NAMES.index("dispatch")].add(nproc_total)
    pc = pc.at[PHASE_NAMES.index("gc_mark")].add(n_spawned + n_destroyed)
    return pc


def trace_span_lanes(program: Program, opts: RuntimeOptions, st: RtState,
                     drain_facts, base, shard):
    """Causal-tracing lanes (PROFILE.md §10; ≙ the fork's per-event
    analysis rows following one message send→dispatch,
    analysis.c:587-692 — per MESSAGE here, where profile_lanes is per
    aggregate). ONLY traced when opts.tracing: the caller gates the
    call itself, so with tracing off none of this exists in the jaxpr
    (tests/test_tracing.py traps this function to prove it).

    Works entirely from the ring-advance facts (profile_lanes'
    recomputation trick), so ONE implementation covers both dispatch
    formulations (the XLA scan and the fused Pallas kernel) and both
    delivery formulations (plan and cosort):

      - every drained ring slot whose trace_id side lane is >= 0
        becomes a SPAN: a fresh even span id from the per-shard
        monotonic counter (host spans are odd — tracing.py owns the
        scheme), recorded in the bounded span ring as (trace_id,
        span_id, parent_span, behaviour_gid, actor_gid, enqueue_tick
        [the qwait_enq delivery stamp], dispatch_tick, retire_tick);
        overflow between two host drains drops and counts;
      - outbox PROPAGATION rows: entry (b, m, r) of the cohort's
        outbox inherits (trace_id, span_id) of the message batch slot
        b dispatched on lane r — sends AND spawns (constructor
        messages ride the same outbox) continue the causal chain; the
        rows-minor [batch, ms, rows] flatten matches both the scan's
        stack and the fused kernel's layout, so neither dispatch path
        needs to know tracing exists.

    `drain_facts` = [(cohort, head_before, head_after)] in
    device-cohort order. Returns (span_data, span_count, span_dropped,
    span_next, [per-cohort [2, e_c] propagation rows])."""
    cap = opts.mailbox_cap
    p = program.shards
    ts_cap = opts.trace_slots
    s_now = st.step_no[0]
    span_data = st.span_data
    span_count = st.span_count[0]
    span_dropped = st.span_dropped[0]
    span_next = st.span_next[0]
    ci = jnp.arange(cap, dtype=jnp.int32)[:, None]
    tr_out = []
    for (ch, head0, head1) in drain_facts:
        cname = ch.atype.__name__
        rows = ch.local_capacity
        batch, ms = ch.batch, ch.max_sends
        n_con = head1 - head0
        drained = ((ci - head0[None, :]) % cap) < n_con[None, :]
        tid = st.trace_buf[cname][:, 0, :]            # [cap, rows]
        tparent = st.trace_buf[cname][:, 1, :]
        traced = drained & (tid >= 0)
        e = rows * batch * ms

        def busy(_):
            """Span allocation + ring write + propagation — runs under
            a cond so ticks where this COHORT dispatched no traced
            message skip the compaction sort and scatters entirely
            (the ev-ring discipline, §5b: the structural cost of
            tracing scales with traced traffic, not with enabling the
            knob)."""
            sd = span_data
            flat = traced.reshape(-1)                 # cap-major order
            rank = jnp.cumsum(flat.astype(jnp.int32)) - 1
            total = jnp.sum(flat.astype(jnp.int32))
            sid_flat = jnp.where(
                flat, ((span_next + rank) * p + shard) * 2 + 2,
                jnp.int32(0))
            k_sp = min(ts_cap, cap * rows)
            perm, valid2, _tot = compact_mask(flat, k_sp)
            pos = span_count + jnp.arange(k_sp, dtype=jnp.int32)
            ok = valid2 & (pos < ts_cap)
            posc = jnp.where(ok, pos, ts_cap)
            actor = jnp.broadcast_to(
                (base + ch.local_start
                 + jnp.arange(rows, dtype=jnp.int32))[None, :],
                (cap, rows)).reshape(-1)
            vals = (tid.reshape(-1), sid_flat, tparent.reshape(-1),
                    st.buf[cname][:, 0, :].reshape(-1), actor,
                    st.qwait_enq[cname].reshape(-1),
                    jnp.broadcast_to(s_now, (cap * rows,)),
                    jnp.broadcast_to(s_now + 1, (cap * rows,)))
            for ri, v in enumerate(vals):
                sd = sd.at[ri, posc].set(
                    jnp.where(ok, v[perm], 0), mode="drop")
            # --- propagation rows for this cohort's outbox.
            sid = sid_flat.reshape(cap, rows)
            tid_b, sid_b = [], []
            for b in range(batch):
                slot = (head0 + b) % cap
                tb, sb = tid[0], sid[0]
                for cslot in range(1, cap):   # static select chain,
                    sel = slot == cslot       # like _ring_take
                    tb = jnp.where(sel, tid[cslot], tb)
                    sb = jnp.where(sel, sid[cslot], sb)
                okb = (b < n_con) & (tb >= 0)
                tid_b.append(jnp.where(okb, tb, jnp.int32(-1)))
                sid_b.append(jnp.where(okb, sb, jnp.int32(0)))
            if ms:
                tid_e = jnp.broadcast_to(
                    jnp.stack(tid_b)[:, None, :],
                    (batch, ms, rows)).reshape(e)
                sid_e = jnp.broadcast_to(
                    jnp.stack(sid_b)[:, None, :],
                    (batch, ms, rows)).reshape(e)
            else:
                tid_e = jnp.full((0,), -1, jnp.int32)
                sid_e = jnp.zeros((0,), jnp.int32)
            return (sd,
                    jnp.minimum(span_count + total, ts_cap),
                    span_dropped + jnp.maximum(
                        0, span_count + total - ts_cap),
                    span_next + total,
                    jnp.stack([tid_e, sid_e]))

        def quiet(_):
            return (span_data, span_count, span_dropped, span_next,
                    jnp.stack([jnp.full((e,), -1, jnp.int32),
                               jnp.zeros((e,), jnp.int32)]))

        (span_data, span_count, span_dropped, span_next,
         tr_pair) = lax.cond(jnp.any(traced), busy, quiet, operand=None)
        tr_out.append(tr_pair)
    return span_data, span_count, span_dropped, span_next, tr_out


def _cohort_dispatch(cohort: Cohort, opts: RuntimeOptions, noyield: bool,
                     program: Program):
    """Build the planar per-cohort drain loop.

    ≙ ponyint_actor_run (actor.c:383-549): pop ≤batch app messages,
    dispatch each, honour yield (fork: actor.c:675-679), count
    consumption — for every actor of the cohort at once, as [rows]-wide
    vector ops (actors on the 128 TPU lanes, batch slots iterated by a
    lax.scan whose carries are all lane-shaped).
    """
    msg_words = opts.msg_words          # OUTBOX width (program-wide max)
    ms = cohort.max_sends
    batch = cohort.batch
    cap = opts.mailbox_cap
    rows = cohort.local_capacity
    w1 = 1 + msg_words
    # This cohort's own mailbox width (≙ per-type pony_msg_t, genfun.c):
    # the drain reads [cap, w1_in, rows]; sends still emit the global
    # width (they may target any cohort — delivery narrows per target).
    w1_in = 1 + cohort.msg_words
    field_dtypes = {}
    for fname, spec in cohort.atype.field_specs.items():
        field_dtypes[fname] = (jnp.float32 if spec is pack.F32
                               else jnp.int32)
    spawn_sites = tuple(sorted(cohort.spawns.items()))
    # Field specs of every spawn-target type, for synchronous
    # construction (Context.spawn_sync).
    spawn_meta = {t: program.by_type_name(t).atype.field_specs
                  for t, _ in spawn_sites}
    effects = {"destroy": False, "error": False, "sync_init": False}
    # Device blob pool (≙ actor-heap message payloads; see ops.pack.Blob):
    # a cohort that allocates (MAX_BLOBS) or receives/holds Blob handles
    # threads the pool arrays through its dispatch; everything else keeps
    # the blob-free structure (and fused-kernel eligibility) untouched.
    use_blob = opts.blob_slots > 0 and cohort.uses_blobs

    def _zero_inits():
        """Zero sync-init structure — shared by the fused busy path and
        idle_fn so the lax.cond branch pytrees can never drift."""
        return tuple(
            (jnp.zeros((batch * n * rows,), jnp.bool_),
             {f: jnp.zeros((batch * n * rows,),
                           jnp.float32 if sp is pack.F32 else jnp.int32)
              for f, sp in spawn_meta[tname].items()})
            for tname, n in spawn_sites)
    branches = [_make_branch(b, msg_words, ms, field_dtypes,
                             cohort.atype.field_specs, spawn_sites,
                             spawn_meta, effects, rows)
                for b in cohort.behaviours]
    nb = len(cohort.behaviours)
    base = cohort.behaviours[0].global_id if nb else 0
    sd = cohort.spawn_dispatches
    fused = None
    if opts.pallas_fused and nb >= 1 and not use_blob:
        from ..ops import fused_dispatch as fd
        from ..ops import mailbox_kernel as mk
        if rows <= fd.LANE_BLOCK or rows % fd.LANE_BLOCK == 0:
            # Probe-trace every branch so `effects` is discovered BEFORE
            # the path decision (the fused kernel hosts destroy/error/
            # spawn claims as lane planes but cannot host
            # sync-construction packaging).
            for br in branches:
                jax.eval_shape(
                    br,
                    {f: jax.ShapeDtypeStruct((rows,), field_dtypes[f])
                     for f in cohort.atype.field_specs},
                    jax.ShapeDtypeStruct((cohort.msg_words, rows),
                                         jnp.int32),
                    jax.ShapeDtypeStruct((rows,), jnp.int32),
                    {t: jax.ShapeDtypeStruct((n, rows), jnp.int32)
                     for t, n in spawn_sites})
            if fd.eligible(cohort, effects, opts):
                fnames = tuple(cohort.atype.field_specs.keys())
                fused = (fd.build_fused_dispatch(
                    cohort.behaviours, base_gid=base,
                    field_names=fnames, field_dtypes=field_dtypes,
                    field_specs=cohort.atype.field_specs, batch=batch,
                    cap=cap, msg_words=msg_words,
                    msg_words_in=cohort.msg_words, ms=ms, rows=rows,
                    noyield=noyield, interpret=mk.interpret_mode(),
                    spawn_sites=spawn_sites, spawn_meta=spawn_meta,
                    spawn_dispatches=sd),
                    fnames)

    def run_cohort(type_state_rows, buf_rows, head_rows, occ_rows,
                   runnable_rows, ids, resv, blob=None):
        # buf_rows: [cap, w1, rows]; resv: {target: [sd, sites, rows]};
        # blob (pool-using cohorts only): dict(data [W,B], used [B],
        # len [B], base i32, resv [batch, sites, rows] global handles).
        e = rows * batch * ms
        if use_blob and blob is None:
            raise RuntimeError(
                f"cohort {cohort.atype.__name__} uses the blob pool but "
                "run_cohort got blob=None (engine wiring)")

        def scan_body(carry, x):
            (st, stopped, ef, ec, sfail, dstr, errf, errc, errl, used,
             nproc, nbad, blb, bused_c) = carry
            msg, valid = x                    # msg [w1, rows], valid [rows]
            # Blob reservation window for this dispatch: a used-counter
            # walk over the [blob_dispatches, sites, rows] windows — only
            # dispatches that actually allocate consume one (the
            # spawn_dispatches pattern; exhausted budget yields -1 refs
            # -> sticky blob_fail, never a double claim).
            rblob = None
            rblob_over = None
            if blb is not None:
                rt_b = blob["resv"]
                rblob = jnp.full(rt_b.shape[1:], -1, jnp.int32)
                for d in range(rt_b.shape[0]):
                    rblob = jnp.where((bused_c == d)[None, :], rt_b[d],
                                      rblob)
                # Lanes whose window was withheld for BUDGET (allocating
                # dispatch count past BLOB_DISPATCHES) — an alloc failure
                # there blames the budget knob, not the pool size.
                rblob_over = bused_c >= rt_b.shape[0]
            # Hand one dispatch-worth of spawn reservations to this batch
            # slot: a `used` counter walks the SPAWN_DISPATCHES axis;
            # exhausted budget yields -1 refs (→ sticky spawn_fail,
            # never a double claim).
            resv_k = {}
            for t, n_sites in spawn_sites:
                rt_ = resv[t]                 # [sd, sites, rows]
                sel = jnp.full((n_sites, rows), -1, jnp.int32)
                for d in range(sd):
                    sel = jnp.where((used == d)[None, :], rt_[d], sel)
                resv_k[t] = sel
            local = msg[0] - base
            in_range = (local >= 0) & (local < nb)
            do = valid & ~stopped
            # Planar dispatch: evaluate every behaviour on all lanes and
            # select per lane by behaviour id (what a vmapped lax.switch
            # executes, without the actor-major materialisations).
            st_n = dict(st)
            tgt_n = [jnp.full((rows,), -1, jnp.int32) for _ in range(ms)]
            wrd_n = [jnp.zeros((w1, rows), jnp.int32) for _ in range(ms)]
            ef_n = jnp.zeros((rows,), jnp.bool_)
            ec_n = jnp.zeros((rows,), jnp.int32)
            yf_n = jnp.zeros((rows,), jnp.bool_)
            sf_n = jnp.zeros((rows,), jnp.bool_)
            ds_n = jnp.zeros((rows,), jnp.bool_)
            erf_n = jnp.zeros((rows,), jnp.bool_)
            erc_n = jnp.zeros((rows,), jnp.int32)
            erl_n = jnp.zeros((rows,), jnp.int32)
            clm_n = [[jnp.full((rows,), -1, jnp.int32)
                      for _ in range(n)] for _, n in spawn_sites]
            ini_n = []
            for tname, n in spawn_sites:
                t_specs = spawn_meta[tname]
                t_dt = {f: (jnp.float32 if sp is pack.F32 else jnp.int32)
                        for f, sp in t_specs.items()}
                ini_n.append((
                    [jnp.zeros((rows,), jnp.bool_) for _ in range(n)],
                    {f: [jnp.full((rows,),
                                  pack.null_word(sp), t_dt[f])
                         for _ in range(n)]
                     for f, sp in t_specs.items()}))
            def _merge(br, take, acc):
                """Evaluate one behaviour planar and select its outputs
                where the slot's message id matches. Blob pool arrays
                thread SEQUENTIALLY (no select): branch take-masks are
                disjoint and every blob op is already take-masked inside
                the branch (api.BlobPoolView)."""
                (st_a, tgt_a, wrd_a, ef_a, ec_a, yf_a, sf_a, ds_a,
                 erf_a, erc_a, erl_a, clm_a, ini_a, blb_a) = acc
                blob_in = None
                if blb_a is not None:
                    blob_in = (blb_a[0], blb_a[1], blb_a[2], blb_a[3],
                               blob["base"], rblob, rblob_over)
                (st2, (btgt, bwrd), (bef, bec), byf, bclm, bini, bsf,
                 bds, (berf, berc, berl), bl_o) = br(
                    st, msg[1:], ids, resv_k, blob_in, take)
                if blb_a is not None:
                    blb_o = (bl_o[0], bl_o[1], bl_o[2], bl_o[3],
                             blb_a[4] | bl_o[4], blb_a[5] | bl_o[5],
                             blb_a[6] + bl_o[6], blb_a[7] + bl_o[7],
                             blb_a[8] + bl_o[8], blb_a[9] | bl_o[9])
                else:
                    blb_o = None
                st_o = {k: jnp.where(take, st2[k], st_a[k]) for k in st_a}
                tgt_o = [jnp.where(take, btgt[m], tgt_a[m])
                         for m in range(ms)]
                wrd_o = [jnp.where(take[None, :], bwrd[m], wrd_a[m])
                         for m in range(ms)]
                clm_o = [[jnp.where(take, bclm[si][s], clm_a[si][s])
                          for s in range(len(clm_a[si]))]
                         for si in range(len(spawn_sites))]
                ini_o = []
                for si in range(len(spawn_sites)):
                    bh, bv = bini[si]
                    hh, vv = ini_a[si]
                    ini_o.append((
                        [jnp.where(take, bh[s], hh[s])
                         for s in range(len(hh))],
                        {f: [jnp.where(take, bv[f][s], vv[f][s])
                             for s in range(len(vv[f]))] for f in vv}))
                return (st_o, tgt_o, wrd_o,
                        jnp.where(take, bef, ef_a),
                        jnp.where(take, bec, ec_a),
                        jnp.where(take, byf, yf_a),
                        jnp.where(take, bsf, sf_a),
                        jnp.where(take, bds, ds_a),
                        jnp.where(take, berf, erf_a),
                        jnp.where(take, berc, erc_a),
                        jnp.where(take, berl, erl_a),
                        clm_o, ini_o, blb_o)

            blb_acc = (blb + (jnp.zeros((rows,), jnp.bool_),)
                       if blb is not None else None)
            acc = (st_n, tgt_n, wrd_n, ef_n, ec_n, yf_n, sf_n, ds_n,
                   erf_n, erc_n, erl_n, clm_n, ini_n, blb_acc)
            for j, br in enumerate(branches):
                take = (do & in_range & (local == j))
                if opts.dispatch_gating:
                    # Skip a cold behaviour's whole planar evaluation
                    # under a scalar cond (≙ the generated dispatch
                    # switch running only the selected case, genfun.c).
                    # Behaviour bodies are lane-local by contract, so a
                    # shard-divergent predicate is safe.
                    acc = lax.cond(
                        jnp.any(take),
                        lambda a, _br=br, _t=take: _merge(_br, _t, a),
                        lambda a: a, acc)
                else:
                    acc = _merge(br, take, acc)
            (st_n, tgt_n, wrd_n, ef_n, ec_n, yf_n, sf_n, ds_n,
             erf_n, erc_n, erl_n, clm_n, ini_n, blb_acc) = acc
            if blb_acc is not None:
                blb = blb_acc[:9]
                bused_c = bused_c + blb_acc[9].astype(jnp.int32)
            spawned_here = sf_n
            for si in range(len(spawn_sites)):
                for s in range(len(clm_n[si])):
                    spawned_here = spawned_here | (clm_n[si][s] >= 0)
            new_ef = ef | ef_n
            new_ec = jnp.where(ef_n & ~ef, ec_n, ec)
            stopped2 = stopped if noyield else (stopped | yf_n)
            stgt = jnp.stack(tgt_n) if ms else jnp.zeros((0, rows),
                                                         jnp.int32)
            swrd = jnp.stack(wrd_n) if ms else jnp.zeros((0, w1, rows),
                                                         jnp.int32)
            claims = tuple(
                (jnp.stack(c) if c else jnp.zeros((0, rows), jnp.int32))
                for c in clm_n)
            inits = tuple(
                ((jnp.stack(hh) if hh else jnp.zeros((0, rows), jnp.bool_)),
                 {f: (jnp.stack(vs) if vs
                      else jnp.zeros((0, rows), jnp.int32))
                  for f, vs in vv.items()})
                for hh, vv in ini_n)
            return ((st_n, stopped2, new_ef, new_ec, sfail | sf_n,
                     dstr | ds_n, errf | erf_n,
                     jnp.where(erf_n, erc_n, errc),
                     jnp.where(erf_n, erl_n, errl),
                     used + spawned_here.astype(jnp.int32),
                     nproc + (do & in_range).astype(jnp.int32),
                     nbad + (do & ~in_range).astype(jnp.int32), blb,
                     bused_c),
                    (stgt, swrd, do, claims, inits))

        def busy_fn(_):
            n_run = jnp.where(runnable_rows,
                              jnp.minimum(occ_rows, batch), 0)
            if fused is not None:
                kernel_fn, fnames = fused
                fields = tuple(type_state_rows[f] for f in fnames)
                resv_in = tuple(resv[t].reshape(sd * n, rows)
                                for t, n in spawn_sites)
                (nf_out, out_tgt, out_words, new_head, nproc_l, nbad_l,
                 ef_l, ec_l, ds_l, erf_l, erc_l, erl_l, claims_out,
                 sf_l) = kernel_fn(
                    fields, buf_rows, head_rows, n_run, ids, resv_in)
                stf = dict(zip(fnames, nf_out))
                any_exit = jnp.any(ef_l)
                code = ec_l[jnp.argmax(ef_l)]
                # Claims flatten (k, site, lane) exactly like the XLA
                # scan's stack; inits are the zero structure (the fused
                # path never hosts sync-construction — eligibility).
                claims_t = tuple(c.reshape(-1) for c in claims_out)
                return (stf, out_tgt, out_words, new_head, any_exit,
                        code, jnp.sum(nproc_l), jnp.sum(nbad_l),
                        claims_t, _zero_inits(), jnp.any(sf_l), ds_l,
                        erf_l, erc_l, erl_l, None)
            if opts.pallas:          # gate BEFORE importing pallas/mosaic
                from ..ops import mailbox_kernel as mk
            if opts.pallas and (rows <= mk.LANE_BLOCK
                                or rows % mk.LANE_BLOCK == 0):
                msgs, valids = mk.drain_msgs(
                    buf_rows, head_rows, n_run, batch=batch,
                    interpret=mk.interpret_mode())
            else:
                msgs = jnp.stack(
                    [_ring_take(buf_rows, (head_rows + k) % cap)
                     for k in range(batch)])            # [batch, w1, rows]
                valids = (jnp.arange(batch, dtype=jnp.int32)[:, None]
                          < n_run[None, :])             # [batch, rows]
            z = lambda d: jnp.zeros((rows,), d)         # noqa: E731
            if use_blob:
                blb0 = (blob["data"], blob["used"], blob["len"],
                        blob["gen"], jnp.bool_(False), jnp.bool_(False),
                        jnp.int32(0), jnp.int32(0), jnp.int32(0))
            else:
                blb0 = None
            carry0 = (type_state_rows, z(jnp.bool_), z(jnp.bool_),
                      z(jnp.int32), z(jnp.bool_), z(jnp.bool_),
                      z(jnp.bool_), z(jnp.int32), z(jnp.int32),
                      z(jnp.int32), z(jnp.int32), z(jnp.int32), blb0,
                      z(jnp.int32))
            ((stf, _, ef, ec, sfail, dstr, errf, errc, errl, _used, nproc,
              nbad, blbf, _bused),
             (stgt, swrd, consumed, claims, inits)) = lax.scan(
                scan_body, carry0, (msgs, valids))
            # stgt [batch, ms, rows] → flat [e] with rows minor;
            # swrd [batch, ms, w1, rows] → [w1, e] planar.
            n_consumed = jnp.sum(consumed.astype(jnp.int32), axis=0)
            out_tgt = stgt.reshape(e)
            out_words = jnp.moveaxis(swrd, 2, 0).reshape(w1, e)
            any_exit = jnp.any(ef)
            code = ec[jnp.argmax(ef)]
            return (stf, out_tgt, out_words, head_rows + n_consumed,
                    any_exit, code, jnp.sum(nproc), jnp.sum(nbad),
                    tuple(c.reshape(-1) for c in claims),
                    tuple((h.reshape(-1),
                           {f: v.reshape(-1) for f, v in vals.items()})
                          for h, vals in inits),
                    jnp.any(sfail), dstr, errf, errc, errl, blbf)

        def idle_fn(_):
            # ≙ the fork's whole point (README.md:8-10, scaling_sleep): a
            # scheduler with no work must cost ~nothing. A cohort with no
            # queued runnable messages skips gather/dispatch/outbox
            # entirely — one reduction decides.
            blb_idle = ((blob["data"], blob["used"], blob["len"],
                         blob["gen"], jnp.bool_(False), jnp.bool_(False),
                         jnp.int32(0), jnp.int32(0), jnp.int32(0))
                        if use_blob else None)
            return (type_state_rows,
                    jnp.full((e,), -1, jnp.int32),
                    jnp.zeros((w1, e), jnp.int32),
                    head_rows, jnp.bool_(False), jnp.int32(0),
                    jnp.int32(0), jnp.int32(0),
                    tuple(jnp.full((batch * n * rows,), -1, jnp.int32)
                          for _, n in spawn_sites),
                    _zero_inits(),
                    jnp.bool_(False),
                    jnp.zeros((rows,), jnp.bool_),
                    jnp.zeros((rows,), jnp.bool_),
                    jnp.zeros((rows,), jnp.int32),
                    jnp.zeros((rows,), jnp.int32), blb_idle)

        busy = jnp.any(runnable_rows & (occ_rows > 0))
        # (cond traces both branches here, so `effects` is fully
        # populated by the time the lines below read it.)
        (stf, out_tgt, out_words, new_head, any_exit, code, nproc, nbad,
         claims_t, inits_t, sfail, dstr, errf, errc, errl,
         blob_out) = lax.cond(
            busy, busy_fn, idle_fn, operand=None)
        sender = jnp.tile(ids, batch * ms)    # entry (b, m, r): sender=ids[r]
        out = Entries(tgt=out_tgt, sender=sender, words=out_words)
        flat_claims = {t: c for (t, _), c in zip(spawn_sites, claims_t)}
        flat_inits = {t: i for (t, _), i in zip(spawn_sites, inits_t)}
        return (stf, out, new_head, any_exit, code, nproc, nbad,
                flat_claims,
                flat_inits if effects["sync_init"] else None,
                sfail,
                dstr if effects["destroy"] else None,
                (errf, errc, errl) if effects["error"] else None,
                blob_out)

    return run_cohort


def _route(entries: Entries, *, shards: int, n_local: int, bucket: int,
           rspill_cap: int, overload_occ, head, tail, shard_base,
           mute_slots: int, pressured_global, pressured_local,
           blob=None):
    """Mesh routing: pack entries into per-destination-shard buckets and
    exchange them with one all_to_all over the actor axis (ICI).

    Returns (received Entries [shards*bucket], new route-spill, spill count,
    overflow flag, newly muted [n_local], their refs[, blob results]).
    Bucket overflow keeps messages on the source shard (route-spill,
    retried first next step) and mutes the sender — backpressure across
    the mesh without any receiver-side state (≙ the intent of
    ponyint_maybe_mute; the occupancy signal here is "the link to that
    shard is saturated").

    Blob MIGRATION (`blob` = dict(data, used, len, gen, bbase, bsl,
    shard, mask) when the program routes Blob args on a mesh): a blob
    rides its message across the ICI — per blob-arg word position, a
    length row + the payload words concatenate onto the exchanged
    words; the source shard frees the shipped slot, the receiving shard
    allocates a fresh local slot (new generation) and rewrites the
    handle word before delivery. Same-shard bucket blocks skip
    migration (the handle is already dereferenceable). A receive-side
    pool-full drop delivers the message with a null handle and counts
    in n_blob_remote — backpressure-safe data loss made visible, never
    corruption. Route-spilled entries keep their (still-local) blobs
    and migrate when the retry actually ships. ≙ nothing in the
    reference — libponyrt is single-node; this is the distributed half
    of pony_alloc_msg payload movement.
    """
    tgt, sender, words = entries
    e = tgt.shape[0]
    valid = tgt >= 0
    dest = jnp.where(valid, tgt // n_local, shards).astype(jnp.int32)
    perm = stable_sort_by(dest)
    dt = dest[perm]
    ts = tgt[perm]
    ss = sender[perm]
    ws = words[:, perm]                              # [w1, E] planar
    # Per-destination segment bounds via binary search; the bucket table
    # is then a dense gather [shards, bucket] from the sorted entries —
    # same scatter-free design as delivery.py (TPU scatters serialise).
    bounds = jnp.searchsorted(dt, jnp.arange(shards + 1, dtype=jnp.int32),
                              side="left").astype(jnp.int32)
    seg_start = bounds[:-1]
    cnt = bounds[1:] - seg_start                     # [shards]
    acc = jnp.minimum(cnt, bucket)
    j = jnp.arange(bucket, dtype=jnp.int32)[None, :]
    fill = j < acc[:, None]                          # [shards, bucket]
    src = jnp.minimum(seg_start[:, None] + j, e - 1)
    bt = jnp.where(fill, ts[src], -1).reshape(shards * bucket)
    bs = jnp.where(fill, ss[src], -1).reshape(shards * bucket)
    fill_f = fill.reshape(shards * bucket)
    bw = jnp.where(fill_f[None, :], ws[:, src.reshape(-1)], 0)

    blob_out = None
    if blob is not None:
        # --- migration, source side: for every blob-carrying bucketed
        # entry bound OFF-shard, append (len, payload...) rows and free
        # the local slot. Positions are static (the Blob-arg mask).
        bdata, bused, blen, bgen = (blob["data"], blob["used"],
                                    blob["len"], blob["gen"])
        bbase, bsl = blob["bbase"], blob["bsl"]
        mask_np = blob["mask"]                   # STATIC numpy masks
        mask = jnp.asarray(mask_np)
        mask_iso = jnp.asarray(blob["mask_iso"])
        wb = bdata.shape[0]
        n_gids = mask.shape[0]
        sb = shards * bucket
        gid = bw[0]
        g = jnp.clip(gid, 0, n_gids - 1)
        gid_ok = fill_f & (gid >= 0) & (gid < n_gids)
        # Off-shard only: bucket block s goes to shard s.
        off_shard = jnp.broadcast_to(
            (jnp.arange(shards, dtype=jnp.int32)[:, None]
             != blob["shard"]), (shards, bucket)).reshape(sb)
        extra_rows = []
        freed = jnp.zeros((bsl,), jnp.bool_)
        positions = [w for w in range(mask_np.shape[1])
                     if bool(mask_np[:, w].any())]
        for wpos in positions:
            h = bw[1 + wpos]
            hl = pack.blob_slot(h) - bbase
            hs = jnp.where((hl >= 0) & (hl < bsl), hl, bsl)
            okh = (gid_ok & off_shard & mask[g, wpos] & (h >= 0)
                   & (hs < bsl)
                   & (jnp.take(bgen, hs, mode="fill", fill_value=-1)
                      == pack.blob_gen_of(h))
                   & jnp.take(bused, hs, mode="fill", fill_value=False))
            hx = jnp.where(okh, hl, bsl)
            extra_rows.append(jnp.where(
                okh, jnp.take(blen, hx, mode="fill", fill_value=0),
                jnp.int32(-1))[None, :])             # -1 = no payload
            extra_rows.append(jnp.where(
                okh[None, :],
                jnp.take(bdata, hx, axis=1, mode="fill", fill_value=0),
                0))                                  # [wb, sb]
            # Iso handles MOVE (source freed); val handles COPY — the
            # receiver gets a replica, other readers keep the original.
            freed = freed.at[jnp.where(okh & mask_iso[g, wpos],
                                       hl, bsl)].set(True, mode="drop")
        bused = bused & ~freed
        blen = jnp.where(freed, 0, blen)
        n_shipped = jnp.sum(freed.astype(jnp.int32))
        bw = jnp.concatenate([bw] + extra_rows, axis=0)

    rt = lax.all_to_all(bt, "actors", split_axis=0, concat_axis=0,
                        tiled=True)
    rs = lax.all_to_all(bs, "actors", split_axis=0, concat_axis=0,
                        tiled=True)
    rw = lax.all_to_all(bw, "actors", split_axis=1, concat_axis=1,
                        tiled=True)

    if blob is not None:
        # --- migration, receive side: allocate a local slot per arrived
        # payload (disjoint ranks over the compacted free list), write
        # len+words, bump the slot generation, rewrite the handle word.
        w1b = words.shape[0]
        rw_main = rw[:w1b]
        sb = shards * bucket
        n_pos = len(positions)
        permf, vfree, _ = compact_mask(~bused, bsl)
        free_slots = jnp.where(vfree, permf.astype(jnp.int32), -1)
        has_all = jnp.stack(
            [(rw[w1b + k * (1 + wb)] >= 0).astype(jnp.int32)
             for k in range(n_pos)])
        rank = (jnp.cumsum(has_all.reshape(-1)) - 1).reshape(n_pos, sb)
        n_dropped = jnp.int32(0)
        new_words = [rw_main[i] for i in range(w1b)]
        for k, wpos in enumerate(positions):
            base_row = w1b + k * (1 + wb)
            lenr = rw[base_row]
            has = lenr >= 0
            slot_l = jnp.take(free_slots, jnp.where(has, rank[k], bsl),
                              mode="fill", fill_value=-1)
            ok = has & (slot_l >= 0)
            n_dropped = n_dropped + jnp.sum(
                (has & ~ok).astype(jnp.int32))
            sx = jnp.where(ok, slot_l, bsl)
            newgen = (jnp.take(bgen, sx, mode="fill", fill_value=0)
                      + 1) & pack.BLOB_GEN_MASK
            bgen = bgen.at[sx].set(newgen, mode="drop")
            bused = bused.at[sx].set(True, mode="drop")
            blen = blen.at[sx].set(jnp.where(ok, lenr, 0), mode="drop")
            bdata = bdata.at[:, sx].set(
                jnp.where(ok[None, :], rw[base_row + 1:base_row + 1 + wb],
                          jnp.take(bdata, sx, axis=1, mode="fill",
                                   fill_value=0)), mode="drop")
            newh = pack.blob_handle(bbase + slot_l, newgen)
            # has & ok → fresh local handle; has & ~ok → dropped (null);
            # ~has → original word untouched (not a blob for this gid,
            # or a same-shard handle that skipped migration).
            new_words[1 + wpos] = jnp.where(
                ok, newh, jnp.where(has, jnp.int32(-1),
                                    new_words[1 + wpos]))
        rw = jnp.stack(new_words)
        n_received = jnp.sum(has_all) - n_dropped
        blob_out = ((bdata, bused, blen, bgen),
                    n_shipped, n_received, n_dropped)

    nrej = jnp.sum(cnt - acc)
    w1 = words.shape[0]
    # Sends whose (possibly remote) target DECLARED pressure: the
    # cross-shard face of pony_apply_backpressure — every shard sees the
    # all-gathered pressured bits, so senders mute at routing time, not
    # only on the receiver's shard (≙ the reference muting any scheduler
    # that sends to an under-pressure actor).
    pr_t = (ts >= 0) & jnp.take(
        pressured_global, jnp.maximum(ts, 0), mode="clip")

    def pressure(_):
        # Bucket overflow → route spill (stays on this shard, ordered)
        # + mute the (always local) senders of parked or
        # pressured-targeted messages.
        rank = jnp.arange(e, dtype=jnp.int32) - seg_start[
            jnp.minimum(dt, shards - 1)]
        rej = (dt < shards) & (rank >= bucket)
        perm2, vsp, _ = compact_mask(rej, rspill_cap)
        spill = Entries(
            tgt=jnp.where(vsp, ts[perm2], -1),
            sender=jnp.where(vsp, ss[perm2], -1),
            words=jnp.where(vsp[None, :], ws[:, perm2], 0),
        )
        lsnd = ss - shard_base
        s_ok = (rej | pr_t) & (lsnd >= 0) & (lsnd < n_local)
        sc = jnp.minimum(jnp.maximum(lsnd, 0), n_local - 1)
        s_hot = (tail[sc] - head[sc]) > overload_occ
        # ≙ the reference's !OVERLOADED/UNDER_PRESSURE sender exemption
        # (actor.c mute rules): a sender that is itself hot or has
        # itself declared pressure never mutes — prevents two
        # host-pressured actors that message each other from
        # mutually muting into a stall.
        trig = s_ok & ~s_hot & ~pressured_local[sc]
        mute_row = jnp.where(trig, sc, n_local)
        newly_muted = jnp.zeros((n_local,), jnp.bool_).at[mute_row].max(
            trig, mode="drop")
        refs, ovf = mute_ref_slots(trig, mute_row, ts, n=n_local,
                                   k=mute_slots)
        return spill, newly_muted, refs, ovf

    def quiet(_):
        refs, ovf = empty_mute_slots(n_local, mute_slots)
        return (Entries(tgt=jnp.full((rspill_cap,), -1, jnp.int32),
                        sender=jnp.full((rspill_cap,), -1, jnp.int32),
                        words=jnp.zeros((w1, rspill_cap), jnp.int32)),
                jnp.zeros((n_local,), jnp.bool_), refs, ovf)

    new_rspill, newly_muted, new_refs, new_ovf = lax.cond(
        (nrej > 0) | jnp.any(pr_t), pressure, quiet, operand=None)

    received = Entries(tgt=rt, sender=rs, words=rw)
    return (received, new_rspill, jnp.minimum(nrej, rspill_cap),
            nrej > rspill_cap, newly_muted, new_refs, new_ovf, blob_out)


def build_step(program: Program, opts: RuntimeOptions):
    """Trace one whole-world scheduler tick; returns a function
    local_step(state, inject_tgt, inject_words) → (state, StepAux) in
    *per-shard* coordinates. Wrap with jit (P=1) or shard_map (P>1) via
    jit_step()."""
    assert program.frozen
    p = program.shards
    nl = program.n_local
    c = opts.mailbox_cap
    fh = program.first_host_row
    s_cap = opts.spill_cap
    tracing = opts.tracing   # static: causal trace lanes (PROFILE §10)
    dev_cohorts = program.device_cohorts
    dispatchers = [(_cohort_dispatch(ch, opts, opts.noyield, program), ch)
                   for ch in dev_cohorts]
    # Blob migration over the mesh: active iff some behaviour ROUTES a
    # Blob argument (static mask) and the pool is live (see _route).
    route_blobs = False
    if opts.blob_slots > 0 and p > 1:
        from .gc import build_blob_arg_mask
        _blob_route_mask = build_blob_arg_mask(program, opts.msg_words)
        # Iso-mode positions MOVE (source slot freed); val-mode (frozen,
        # shared) positions COPY — other readers keep the source.
        _blob_route_mask_iso = build_blob_arg_mask(
            program, opts.msg_words, mode="iso")
        route_blobs = bool(_blob_route_mask.any())
    e_out, bucket, _n_entries = layout_sizes(program, opts)
    # Delivery priority levels (see delivery.deliver): 0 = receiver
    # spill, 1 = host inject, 2+k = sender cohort with k-th highest
    # PRIORITY (≙ the fork's actor priority hint ordering contenders).
    import numpy as _np
    pri_sorted = sorted({ch.priority for ch in dev_cohorts}, reverse=True)
    pri_rank = {pv: i for i, pv in enumerate(pri_sorted)}
    n_levels = 2 + max(1, len(pri_sorted))
    prio_row_np = _np.zeros((nl,), _np.int32)
    for ch in dev_cohorts:
        prio_row_np[ch.local_start:ch.local_stop] = pri_rank[ch.priority]
    # Per-cohort mailbox widths tiling the local row space (ALL cohorts,
    # device + host) — delivery rebuilds each table at its own width.
    cohort_layout = tuple(
        (ch.atype.__name__, ch.local_start, ch.local_stop,
         1 + ch.msg_words) for ch in program.cohorts)

    def local_step(st: RtState, inject_tgt, inject_words
                   ) -> Tuple[RtState, StepAux]:
        if p > 1:
            shard = lax.axis_index("actors").astype(jnp.int32)
        else:
            shard = jnp.int32(0)
        base = shard * nl
        occ0 = st.tail - st.head
        # World bits (previous tick's mesh-wide vote, stored replicated
        # per shard): bit0 = any actor pressured anywhere, bit1 = any
        # muted anywhere, bit2 = any route-spill entries anywhere. They
        # are shard-uniform by construction (computed from the packed
        # psum vote below; host writes set every shard's entry), so they
        # can gate collectives — every shard takes the same cond branch,
        # the same uniformity argument as the fused window's while cond.
        # This is the fork's whole thesis applied to the mesh
        # (README.md:8-10): a quiet world must not pay per-tick gather
        # latency for backpressure machinery it isn't using.
        wb0 = st.world_bits[0]
        world_pressured = (wb0 & 1) > 0
        world_muted = (wb0 & 2) > 0
        world_rspill = (wb0 & 4) > 0
        # Mesh-wide pressured bits (≙ pony_apply_backpressure being
        # visible to every scheduler): one all_gather of the [nl] bool
        # column — it lets BOTH the routing mute and the remote unmute
        # guard see off-shard pressure. Gated: ticks on a mesh with no
        # declared pressure anywhere skip the gather (zeros are exact).
        if p > 1:
            pressured_global = lax.cond(
                world_pressured,
                lambda _: lax.all_gather(st.pressured, "actors",
                                         tiled=True),
                lambda _: jnp.zeros((p * nl,), jnp.bool_),
                operand=None)
        else:
            pressured_global = st.pressured

        # --- 1. unmute pass (≙ ponyint_sched_unmute_senders,
        # scheduler.c:1552-1635: receiver recovered → senders released).
        # The per-row pending histogram (a scatter-add, which serialises
        # on TPU) only runs when the spill actually holds messages — the
        # steady state skips it entirely.
        dspill_pending = lax.cond(
            st.dspill_count[0] > 0,
            lambda _: counts_by_key(
                jnp.minimum(jnp.maximum(st.dspill_tgt, 0), nl - 1),
                (st.dspill_tgt >= 0).astype(jnp.int32), nl),
            lambda _: jnp.zeros((nl,), jnp.int32), operand=None)
        # Mesh-wide muter-status bits for the aging veto below, packed
        # into one gather (bit 0: live-congested — shows congestion
        # evidence AND can run to drain it; bit 1: can-recover — alive
        # and unmuted, i.e. not itself deadlocked). Gathered OUTSIDE the
        # unmute cond (collectives must run collectively; jnp.any(
        # st.muted) is shard-local).
        can_recover = st.alive & ~st.muted
        live_cong = (((occ0 > opts.unmute_occ) | (dspill_pending > 0))
                     & can_recover)
        muter_bits = (live_cong.astype(jnp.int32)
                      | (can_recover.astype(jnp.int32) << 1))
        # Gated like the pressured gather: the bits feed only the unmute
        # pass, which has work only when someone (anywhere) is muted —
        # exactly what world bit1 reports from the previous tick's vote.
        if p > 1:
            muter_bits_global = lax.cond(
                world_muted,
                lambda _: lax.all_gather(muter_bits, "actors",
                                         tiled=True),
                lambda _: jnp.zeros((p * nl,), jnp.int32),
                operand=None)
        else:
            muter_bits_global = muter_bits
        live_cong_global = (muter_bits_global & 1) > 0
        can_recover_global = (muter_bits_global & 2) > 0
        def unmute_pass(_):
            # ≙ ponyint_sched_unmute_senders walking the mutemap
            # receiver-set (scheduler.c:1552-1635): a sender releases only
            # when EVERY tracked muting receiver has recovered.
            refs = st.mute_refs                       # [K, nl]
            has = refs >= 0
            lref = refs - base
            ref_local = (lref >= 0) & (lref < nl)
            mr = jnp.minimum(jnp.maximum(lref, 0), nl - 1)
            local_ok = (has & ref_local & (occ0[mr] <= opts.unmute_occ)
                        & (dspill_pending[mr] == 0)
                        & ~st.pressured[mr])
            # Remote muting ref: release once this shard's route-spill
            # drained (the local evidence of congestion is gone;
            # receiver-side pressure will re-mute via routing if it
            # persists) — unless the remote receiver still DECLARES
            # pressure (the all-gathered bits above), which holds the
            # sender muted exactly as a local pressured ref would.
            remote_pr = jnp.take(pressured_global,
                                 jnp.maximum(refs, 0),
                                 mode="clip") & has & ~ref_local
            remote_ok = (has & ~ref_local & (st.rspill_count[0] == 0)
                         & ~remote_pr)
            slot_ok = ~has | local_ok | remote_ok
            all_ok = jnp.all(slot_ok, axis=0)
            # Overflowed ref sets (more distinct muters than slots) defer
            # to a shard-wide quiet condition — conservative, never early.
            # Overflowed ref sets may have EVICTED a pressured ref
            # (slot collision), so the conservative release condition
            # consults the whole world's pressure bits, not just local.
            shard_quiet = (jnp.max(occ0) <= opts.unmute_occ) \
                & (st.dspill_count[0] == 0) & (st.rspill_count[0] == 0) \
                & ~jnp.any(pressured_global)
            # Aging deadlock-breaker: a sender muted for
            # mute_age_limit consecutive ticks force-releases even if
            # its muters look unrecovered. Mutual-mute cycles and
            # chains (A muted-by B muted-by C...) can otherwise never
            # drain — the known deadlock of the reference's pre-0.36
            # backpressure, where every muter must RUN to recover and
            # muted actors don't run. Bounded queues + spill make the
            # periodic release safe: each release round dispatches real
            # work, and overflow still fails loudly. Host-declared
            # pressure is exempt (never aged away).
            # Staggered by actor row (threshold in [limit, 2*limit)):
            # a fan-in that muted thousands of senders on one tick would
            # otherwise release them all on one tick too, and the
            # synchronized wave into the still-full receiver could blow
            # the bounded spill. Phasing spreads releases over `limit`
            # ticks, so the per-tick wave is ~n_muted/limit.
            if opts.mute_age_limit > 0:
                lim = opts.mute_age_limit
                threshold = lim + jnp.arange(nl, dtype=jnp.int32) % lim
                aged = st.mute_age >= threshold
                held_by_pressure = jnp.any(
                    (refs >= 0) & jnp.take(
                        pressured_global, jnp.maximum(refs, 0),
                        mode="clip"),
                    axis=0)
                # A tracked muter (on ANY shard — live_cong_global) that
                # still shows LIVE congestion evidence (occ above the
                # unmute threshold, or messages parked in its shard's
                # device spill) and that can still run to drain it
                # (alive, not itself muted) vetoes aging: releasing a
                # sender into a receiver that is actively being worked
                # just grows the bounded spill until overflow — the
                # reference never releases while the muter is
                # overloaded/pressured (scheduler.c:1552-1635). Aging
                # therefore only breaks TRUE mute-cycle deadlocks, where
                # every congested muter is itself muted or dead and can
                # never run to recover. A non-empty local route spill
                # additionally holds any sender with a remote muter that
                # can still RECOVER (alive, unmuted): the backlog bound
                # for that muter is still in flight here, so its
                # congestion state is not yet observable. A remote muter
                # that is itself muted/dead gives no such hold — its
                # route-spill backlog can never drain (muted receivers
                # don't run), and holding on it would re-create the
                # cross-shard mute-cycle deadlock aging exists to break.
                held_by_live = jnp.any(
                    has & jnp.take(live_cong_global,
                                   jnp.maximum(refs, 0), mode="clip"),
                    axis=0)
                if p > 1:
                    remote_recover = jnp.any(
                        has & ~ref_local
                        & jnp.take(can_recover_global,
                                   jnp.maximum(refs, 0), mode="clip"),
                        axis=0)
                    held_by_live = held_by_live | (
                        remote_recover & (st.rspill_count[0] > 0))
                # Overflowed ref sets may have EVICTED a pressured ref, so
                # aging defers while any pressure exists anywhere — the
                # same conservative rule as the non-aged ovf path.
                aged_ok = (aged & ~held_by_pressure & ~held_by_live
                           & (~st.mute_ovf | ~jnp.any(pressured_global)))
            else:
                # mute_age_limit <= 0: aging deadlock-breaker disabled
                # (reference mute semantics exactly — documented opt-out
                # in config.py).
                aged_ok = jnp.zeros((nl,), jnp.bool_)
            release = st.muted & (
                (all_ok & (~st.mute_ovf | shard_quiet))
                | aged_ok)
            return (st.muted & ~release,
                    jnp.where(release[None, :], -1, refs),
                    st.mute_ovf & ~release)

        # Nobody muted (the common case) → skip the pass entirely.
        muted, mute_refs, mute_ovf = lax.cond(
            jnp.any(st.muted), unmute_pass,
            lambda _: (st.muted, st.mute_refs, st.mute_ovf), operand=None)

        # --- 1b. spawn reservations (≙ pony_create's slot allocation,
        # actor.c:688-734, done ahead of dispatch): per spawn-target
        # cohort, compact this shard's free rows (dead, drained, no stale
        # spill) and hand each spawner cohort its statically-partitioned
        # window, reshaped to per-(actor, batch-slot, site) refs.
        free_rows: Dict[str, jnp.ndarray] = {}
        if program.spawn_target_names and p > 1:
            # A message parked in *another shard's* route-spill may still
            # be addressed to a locally dead row; reclaiming that row would
            # deliver the stale message to the newborn. Make every shard's
            # rspill targets globally visible (one psum over the mesh) —
            # the cross-shard twin of the dspill_pending guard below.
            # Gated on world bit2: with every shard's route-spill empty
            # (the steady state) the psum is skipped and zeros are exact.
            def _rhit(_):
                rhit = jnp.zeros((p * nl,), jnp.int32).at[
                    jnp.maximum(st.rspill_tgt, 0)].max(
                    (st.rspill_tgt >= 0).astype(jnp.int32), mode="drop")
                rhit = lax.psum(rhit, "actors")
                return lax.dynamic_slice(rhit, (base,), (nl,)) > 0
            rspill_hit = lax.cond(
                world_rspill, _rhit,
                lambda _: jnp.zeros((nl,), jnp.bool_), operand=None)
        else:
            rspill_hit = jnp.zeros((nl,), jnp.bool_)
        for tname in program.spawn_target_names:
            tc = program.by_type_name(tname)
            s0, s1 = tc.local_start, tc.local_stop
            free_ok = (~st.alive[s0:s1] & (occ0[s0:s1] == 0)
                       & (dspill_pending[s0:s1] == 0)
                       & ~rspill_hit[s0:s1])
            perm, vfree, _ = compact_mask(free_ok, tc.local_capacity)
            free_rows[tname] = jnp.where(vfree, s0 + perm.astype(jnp.int32),
                                         jnp.int32(-1))

        # --- 2. drain + dispatch per cohort (≙ actor run loop).
        runnable = st.alive & ~muted

        def cohort_resv(ch):
            """Per-actor spawn reservations: runnable actors get disjoint
            spawn_dispatches × sites windows into the target's free rows,
            ranked by a cumsum over the runnable mask (idle actors
            reserve nothing — see Program._resolve_spawns)."""
            resv = {}
            if not ch.spawns:
                return resv
            run_c = runnable[ch.local_start:ch.local_stop]
            rank = jnp.cumsum(run_c.astype(jnp.int32)) - 1
            sd = ch.spawn_dispatches
            for tname, sites in sorted(ch.spawns.items()):
                per = sd * sites
                off = ch.spawn_offsets[tname]
                widx = jnp.where(run_c, rank * per, 0)
                # Planar [sd, sites, rows]: the per-(dispatch, site)
                # offsets are the small major axes, actor lanes minor.
                idx = (off + widx[None, None, :]
                       + (jnp.arange(sd, dtype=jnp.int32)
                          * sites)[:, None, None]
                       + jnp.arange(sites, dtype=jnp.int32)[None, :, None])
                rows = jnp.take(free_rows[tname], idx, mode="fill",
                                fill_value=-1)
                refs = jnp.where((rows >= 0) & run_c[None, None, :],
                                 base + rows, jnp.int32(-1))
                resv[tname] = refs
            return resv

        # --- 2a'. device blob pool reservations (the spawn-reservation
        # pattern applied to the "actor heap": compact this shard's free
        # pool slots, hand each allocating cohort its statically-
        # partitioned window; ≙ pony_alloc on the owning actor's heap,
        # done race-free ahead of the planar dispatch).
        blob_en = opts.blob_slots > 0
        if blob_en:
            bsl = opts.blob_slots
            bbase = shard * bsl
            # Idle costs nothing (the fork's thesis, README.md:8-10):
            # the free-slot compaction feeds only reservation windows,
            # and no window is READ unless an allocating cohort
            # dispatches — so skip the sort when none has queued work.
            alloc_busy = jnp.bool_(False)
            for _ch in dev_cohorts:
                if _ch.blob_sites and _ch.blob_dispatches:
                    _sl = slice(_ch.local_start, _ch.local_stop)
                    alloc_busy = alloc_busy | jnp.any(
                        runnable[_sl] & (occ0[_sl] > 0))

            def _compact_free(_):
                bperm, bvfree, _n = compact_mask(~st.blob_used, bsl)
                return jnp.where(bvfree,
                                 bbase + bperm.astype(jnp.int32),
                                 jnp.int32(-1))
            free_blob = lax.cond(
                alloc_busy, _compact_free,
                lambda _: jnp.full((bsl,), -1, jnp.int32), operand=None)
        blob_cur = (st.blob_data, st.blob_used, st.blob_len, st.blob_gen)
        blob_fail = st.blob_fail[0]
        blob_budget = st.blob_budget_fail[0]
        nb_alloc = jnp.int32(0)
        nb_free = jnp.int32(0)
        nb_remote = jnp.int32(0)

        def cohort_blob_resv(ch):
            """[bd, sites, rows] reserved global blob handles: each
            runnable actor gets blob_dispatches×sites disjoint windows
            into the compacted free list (idle actors reserve nothing);
            a used-counter walk hands one window to each dispatch that
            actually allocates (the spawn_dispatches pattern)."""
            sites = ch.blob_sites
            bd = ch.blob_dispatches
            if not sites:
                return jnp.zeros((bd, 0, ch.local_capacity), jnp.int32)
            run_c = runnable[ch.local_start:ch.local_stop]
            rank = jnp.cumsum(run_c.astype(jnp.int32)) - 1
            per = bd * sites
            widx = jnp.where(run_c, rank * per, 0)
            idx = (ch.blob_offset + widx[None, None, :]
                   + (jnp.arange(bd, dtype=jnp.int32)
                      * sites)[:, None, None]
                   + jnp.arange(sites, dtype=jnp.int32)[None, :, None])
            handles = jnp.take(free_blob, idx, mode="fill", fill_value=-1)
            return jnp.where(run_c[None, None, :], handles, jnp.int32(-1))
        new_type_state: Dict[str, Dict[str, Any]] = dict(st.type_state)
        head_segments: List[jnp.ndarray] = []
        out_entries: List[Entries] = []
        claim_lists: Dict[str, List[jnp.ndarray]] = {
            t: [] for t in program.spawn_target_names}
        init_lists: Dict[str, List[Any]] = {
            t: [] for t in program.spawn_target_names}
        destroy_rows: List[Tuple[int, jnp.ndarray]] = []  # (s0, [rows] bool)
        error_rows: List[Tuple[int, Any]] = []   # (s0, ([rows] bool, codes))
        exit_f = st.exit_flag[0]
        exit_c = st.exit_code[0]
        spawn_fail = st.spawn_fail[0]
        nproc_total = jnp.int32(0)
        nbad_total = jnp.int32(0)
        drain_facts = []   # (cohort, head before, head after) — feeds
        #   the profiler lanes (profile_lanes) when analysis >= 1
        for run_cohort, ch in dispatchers:
            s0, s1 = ch.local_start, ch.local_stop
            ids = base + s0 + jnp.arange(ch.local_capacity, dtype=jnp.int32)
            if blob_en and ch.uses_blobs:
                blobd = {"data": blob_cur[0], "used": blob_cur[1],
                         "len": blob_cur[2], "gen": blob_cur[3],
                         "base": bbase, "resv": cohort_blob_resv(ch)}
            else:
                blobd = None
            (stf, out, new_head_rows, ef, ec, nproc, nbad, claims, inits,
             sfail, dstr, errs, blob_out) = run_cohort(
                st.type_state[ch.atype.__name__],
                st.buf[ch.atype.__name__], st.head[s0:s1], occ0[s0:s1],
                runnable[s0:s1], ids, cohort_resv(ch), blob=blobd)
            if blob_out is not None:
                blob_cur = blob_out[:4]
                blob_fail = blob_fail | blob_out[4]
                blob_budget = blob_budget | blob_out[5]
                nb_alloc = nb_alloc + blob_out[6]
                nb_free = nb_free + blob_out[7]
                nb_remote = nb_remote + blob_out[8]
            new_type_state[ch.atype.__name__] = stf
            head_segments.append(new_head_rows)
            if opts.analysis >= 1:
                drain_facts.append((ch, st.head[s0:s1], new_head_rows))
            out_entries.append(out)
            for t, cl in claims.items():
                claim_lists[t].append(cl)
                init_lists[t].append(None if inits is None else inits[t])
            if ch.spawns:
                spawn_fail = spawn_fail | sfail
            destroy_rows.append((s0, dstr))
            error_rows.append((s0, errs))
            exit_c = jnp.where(ef & ~exit_f, ec, exit_c)
            exit_f = exit_f | ef
            nproc_total = nproc_total + nproc
            nbad_total = nbad_total + nbad
        if fh < nl:  # host-cohort heads unchanged by device dispatch
            head_segments.append(st.head[fh:nl])
        new_head = (jnp.concatenate(head_segments) if head_segments
                    else st.head)

        # --- 2b. apply spawn claims (before delivery, so constructor
        # messages and same-step sends to the newborn land): claimed rows
        # become alive with a fresh empty mailbox and zeroed state fields
        # (the constructor behaviour initialises them — Pony's `create` is
        # itself the first message).
        alive = st.alive
        tail0 = st.tail
        n_spawned = jnp.int32(0)
        for tname, clist in claim_lists.items():
            if not clist:
                continue
            refs = jnp.concatenate(clist)
            any_sync = any(e is not None for e in init_lists[tname])
            rows = jnp.where(refs >= 0, refs - base, nl)  # row nl → dropped
            alive = alive.at[rows].set(True, mode="drop")
            new_head = new_head.at[rows].set(0, mode="drop")
            tail0 = tail0.at[rows].set(0, mode="drop")
            n_spawned = n_spawned + jnp.sum((refs >= 0).astype(jnp.int32))
            tc = program.by_type_name(tname)
            cols = jnp.where(refs >= 0, rows - tc.local_start,
                             tc.local_capacity)
            if any_sync:
                # Cohorts that never spawn_sync contribute constant-False
                # has-masks (the lanes cost only exists when some
                # behaviour of the program actually sync-constructs).
                has_init = jnp.concatenate(
                    [e[0] if e is not None
                     else jnp.zeros((cl.shape[0],), jnp.bool_)
                     for e, cl in zip(init_lists[tname], clist)])
            ts = dict(new_type_state[tname])
            for fname in ts:
                default = pack.null_word(tc.atype.field_specs[fname])
                if any_sync:
                    # Sync-constructed spawns (spawn_sync) land their
                    # constructor's field values; async spawns zero and
                    # let the constructor message initialise.
                    vals = jnp.concatenate(
                        [e[1][fname] if e is not None
                         else jnp.zeros((cl.shape[0],), ts[fname].dtype)
                         for e, cl in zip(init_lists[tname], clist)])
                    val = jnp.where(has_init,
                                    vals.astype(ts[fname].dtype), default)
                else:
                    val = default
                ts[fname] = ts[fname].at[cols].set(val, mode="drop")
            new_type_state[tname] = ts

        # --- 2c. causal-trace spans + context propagation (tracing on
        # only; the Python-level gate keeps the jaxpr bit-identical to
        # a tracer-free build otherwise — tests/test_tracing.py traps
        # trace_span_lanes to prove it). Every cohort's outbox gains
        # two trailing word rows carrying (trace_id, span_id) of the
        # dispatch that emitted each entry; spills, routing and
        # delivery move them with the payload from here on.
        if tracing:
            (span_data2, span_count2, span_dropped2, span_next2,
             tr_rows) = trace_span_lanes(program, opts, st, drain_facts,
                                         base, shard)
            out_entries = [
                o._replace(words=jnp.concatenate([o.words, t], axis=0))
                for o, t in zip(out_entries, tr_rows)]

        # --- 3. route (mesh) or pass through (single chip).
        rspill_e = Entries(st.rspill_tgt, st.rspill_sender, st.rspill_words)
        out_cat = Entries(
            tgt=jnp.concatenate([rspill_e.tgt] +
                                [o.tgt for o in out_entries]),
            sender=jnp.concatenate([rspill_e.sender] +
                                   [o.sender for o in out_entries]),
            words=jnp.concatenate([rspill_e.words] +
                                  [o.words for o in out_entries], axis=1),
        )
        route_muted = jnp.zeros((nl,), jnp.bool_)
        route_refs, route_ovf = empty_mute_slots(nl, opts.mute_slots)
        if p > 1:
            rblob = None
            if route_blobs:
                rblob = {"data": blob_cur[0], "used": blob_cur[1],
                         "len": blob_cur[2], "gen": blob_cur[3],
                         "bbase": bbase, "bsl": bsl, "shard": shard,
                         "mask": _blob_route_mask,
                         "mask_iso": _blob_route_mask_iso}
            (incoming, new_rspill, rsp_count, rsp_over, route_muted,
             route_refs, route_ovf, route_blob_out) = _route(
                out_cat, shards=p, n_local=nl, bucket=bucket,
                rspill_cap=s_cap, overload_occ=opts.overload_occ,
                head=new_head, tail=tail0, shard_base=base,
                mute_slots=opts.mute_slots,
                pressured_global=pressured_global,
                pressured_local=st.pressured, blob=rblob)
            if route_blob_out is not None:
                blob_cur, n_ship, n_recv, n_drop = route_blob_out
                nb_free = nb_free + n_ship
                nb_alloc = nb_alloc + n_recv
                nb_moved = n_recv
                nb_remote = nb_remote + n_drop
            else:
                nb_moved = jnp.int32(0)
            incoming = incoming._replace(
                tgt=jnp.where(incoming.tgt >= 0, incoming.tgt - base, -1))
        else:
            incoming = out_cat._replace(
                tgt=jnp.where(out_cat.tgt >= 0, out_cat.tgt - base, -1))
            new_rspill = Entries(st.rspill_tgt, st.rspill_sender,
                                 st.rspill_words)   # unused, stays empty
            rsp_count = st.rspill_count[0]
            rsp_over = jnp.bool_(False)
            nb_moved = jnp.int32(0)

        # --- 4. delivery list: receiver spill first (oldest), then host
        # injections, then routed messages. Injections are replicated to
        # all shards; each shard keeps only rows it owns.
        inj_l = inject_tgt - base
        inj_local = jnp.where((inj_l >= 0) & (inj_l < nl), inj_l, -1)
        dspill_e = Entries(st.dspill_tgt, st.dspill_sender, st.dspill_words)
        all_e = Entries(
            tgt=jnp.concatenate([dspill_e.tgt, inj_local, incoming.tgt]),
            sender=jnp.concatenate([dspill_e.sender,
                                    jnp.full_like(inj_local, -1),
                                    incoming.sender]),
            words=jnp.concatenate([dspill_e.words, inject_words,
                                   incoming.words], axis=1),
        )

        prio_row = jnp.asarray(prio_row_np)
        snd_in = incoming.sender
        srow = jnp.where(snd_in >= 0, snd_in, 0) % nl
        lvl_in = jnp.where(snd_in >= 0, 2 + prio_row[srow],
                           jnp.int32(2)).astype(jnp.int32)
        lvl_all = jnp.concatenate([
            jnp.zeros_like(dspill_e.tgt),
            jnp.ones_like(inj_local),
            lvl_in])
        res = deliver(st.buf, new_head, tail0, alive, all_e,
                      n_local=nl, mailbox_cap=c, spill_cap=s_cap,
                      overload_occ=opts.overload_occ, shard_base=base,
                      cohort_layout=cohort_layout,
                      mute_slots=opts.mute_slots,
                      level=lvl_all, n_levels=n_levels,
                      plan=(st.plan_key, st.plan_perm, st.plan_bounds),
                      pressured=st.pressured,
                      cosort=(opts.delivery == "cosort"),
                      trace_buf=st.trace_buf if tracing else None)

        # --- 4b. apply destroys (≙ ponyint_actor_setpendingdestroy +
        # ponyint_actor_destroy, actor.c:570-664): the slot dies at end of
        # step; its remaining queue is discarded (head := tail), flags
        # clear, and the row becomes reclaimable by a later spawn.
        new_tail = res.tail
        pinned = st.pinned
        pressured = st.pressured
        # Int-coded error residue (≙ pony_error_int/code, fork): latest
        # nonzero code per actor + a counter; zero-cost for cohorts whose
        # behaviours never call ctx.error_int (gated at trace).
        last_error = st.last_error
        last_error_loc = st.last_error_loc
        n_errors = jnp.int32(0)
        for s0, errs in error_rows:
            if errs is None:
                continue
            errf, errc, errl = errs
            rows = jnp.where(errf, s0 + jnp.arange(errf.shape[0],
                                                   dtype=jnp.int32), nl)
            last_error = last_error.at[rows].set(
                jnp.where(errf, errc, 0), mode="drop")
            last_error_loc = last_error_loc.at[rows].set(
                jnp.where(errf, errl, 0), mode="drop")
            n_errors = n_errors + jnp.sum(errf.astype(jnp.int32))
        n_destroyed = jnp.int32(0)
        for s0, dstr in destroy_rows:
            if dstr is None:
                continue
            rows = jnp.where(dstr, s0 + jnp.arange(dstr.shape[0],
                                                   dtype=jnp.int32), nl)
            alive = alive.at[rows].set(False, mode="drop")
            new_head = new_head.at[rows].set(
                jnp.take(new_tail, jnp.minimum(rows, nl - 1)), mode="drop")
            muted = muted.at[rows].set(False, mode="drop")
            mute_refs = mute_refs.at[:, rows].set(-1, mode="drop")
            mute_ovf = mute_ovf.at[rows].set(False, mode="drop")
            pinned = pinned.at[rows].set(False, mode="drop")
            pressured = pressured.at[rows].set(False, mode="drop")
            n_destroyed = n_destroyed + jnp.sum(dstr.astype(jnp.int32))

        # --- 5. mute bookkeeping (≙ ponyint_mute_actor + mutemap insert,
        # actor.c:1171-1207, mutemap.c): this tick's muting refs from
        # delivery and routing MERGE into each sender's slot table (a
        # re-muted sender keeps its older muters); a slot collision
        # between distinct refs sets the sticky overflow bit.
        def _merge_slots(a, b):
            both = (a >= 0) & (b >= 0)
            m = jnp.where(a < 0, b, jnp.where(b < 0, a, jnp.maximum(a, b)))
            return m, jnp.any(both & (a != b), axis=0)

        newly = (res.newly_muted | route_muted) & alive
        became_muted = newly & ~muted
        muted2 = muted | newly
        # Consecutive-muted-tick counter (see the aging release above):
        # +1 while muted, reset on release or fresh mute.
        mute_age2 = jnp.where(muted2,
                              jnp.where(became_muted, 0,
                                        st.mute_age + 1),
                              0)

        def merge_mutes(_):
            inc_refs, c1 = _merge_slots(res.new_mute_refs, route_refs)
            merged_refs, c2 = _merge_slots(mute_refs, inc_refs)
            return (jnp.where(newly[None, :], merged_refs, mute_refs),
                    jnp.where(newly,
                              mute_ovf | res.new_mute_ovf | route_ovf
                              | c1 | c2,
                              mute_ovf))

        # The [K, N] slot-table merge only runs on ticks that actually
        # muted someone (≙ mutemap inserts happening only on mute).
        mute_refs2, mute_ovf2 = lax.cond(
            jnp.any(newly), merge_mutes,
            lambda _: (mute_refs, mute_ovf), operand=None)

        # --- 5b. per-event trace ring (analysis level 3 only; ≙ the
        # fork's per-event analysis rows, analysis.c:587-692): record the
        # tick's TRANSITIONS (mute, unmute, overload-on, spawn, destroy,
        # error) as (event, actor, step) triples compacted into a bounded
        # ring the host drains at window boundaries. Traced only when
        # enabled; and under a cond so event-free ticks skip the
        # compaction sort.
        occ_after = new_tail - new_head
        ev_data, ev_count, ev_dropped = (st.ev_data, st.ev_count[0],
                                         st.ev_dropped[0])
        if opts.analysis >= 3:
            released_ev = st.muted & ~muted & alive
            over_ev = (occ_after > opts.overload_occ) \
                & ~(occ0 > opts.overload_occ)
            spawn_ev = alive & ~st.alive
            destroy_ev = st.alive & ~alive
            err_ev = jnp.zeros((nl,), jnp.bool_)
            for s0, errs in error_rows:
                if errs is None:
                    continue
                errf = errs[0]
                rows_ = s0 + jnp.arange(errf.shape[0], dtype=jnp.int32)
                err_ev = err_ev.at[rows_].max(errf)
            classes = [(1, became_muted), (2, released_ev), (3, over_ev),
                       (4, spawn_ev), (5, destroy_ev), (6, err_ev)]
            masks = jnp.concatenate([m for _, m in classes])
            ev_cap = opts.analysis_events

            # A tick can produce at most len(classes)*nl events.
            k_ev = min(ev_cap, masks.shape[0])

            def record(_):
                codes = jnp.concatenate(
                    [jnp.full((nl,), cde, jnp.int32) for cde, _ in classes])
                actors = base + jnp.tile(
                    jnp.arange(nl, dtype=jnp.int32), len(classes))
                perm2, valid2, total2 = compact_mask(masks, k_ev)
                pos = ev_count + jnp.arange(k_ev, dtype=jnp.int32)
                ok = valid2 & (pos < ev_cap)
                posc = jnp.where(ok, pos, ev_cap)
                ev = ev_data
                ev = ev.at[0, posc].set(
                    jnp.where(ok, codes[perm2], 0), mode="drop")
                ev = ev.at[1, posc].set(
                    jnp.where(ok, actors[perm2], 0), mode="drop")
                ev = ev.at[2, posc].set(
                    jnp.full((k_ev,), st.step_no[0] + 1), mode="drop")
                return (ev, jnp.minimum(ev_count + total2, ev_cap),
                        ev_dropped + jnp.maximum(
                            0, ev_count + total2 - ev_cap))

            ev_data, ev_count, ev_dropped = lax.cond(
                jnp.any(masks), record,
                lambda _: (ev_data, ev_count, ev_dropped), operand=None)

        # --- 5c. per-behaviour profiler lanes (analysis level >= 1 only;
        # the gate is PYTHON-level, so level 0 traces none of this —
        # tests trap profile_lanes to assert exactly that).
        if opts.analysis >= 1:
            (beh_runs2, beh_del2, beh_rej2, coh_mt2, qw_hist2,
             qw_enq2) = profile_lanes(program, opts, st, tail0, res,
                                      drain_facts, muted2)
            phase_cost2 = phase_cost_lanes(st, all_e, drain_facts,
                                           nproc_total, n_spawned,
                                           n_destroyed)
        else:
            beh_runs2, beh_del2, beh_rej2 = (st.beh_runs,
                                             st.beh_delivered,
                                             st.beh_rejected)
            coh_mt2, qw_hist2 = st.coh_mute_ticks, st.qwait_hist
            qw_enq2 = dict(st.qwait_enq)
            phase_cost2 = st.phase_cost

        nrej_new = st.n_rejected[0] + res.n_rejected
        nbad_new = st.n_badmsg[0] + nbad_total
        ndl_new = st.n_deadletter[0] + res.n_deadletter
        nmut_new = st.n_mutes[0] + jnp.sum(became_muted.astype(jnp.int32))
        if opts.analysis >= 1:
            occ_sum = jnp.sum(occ_after)
            occ_max = jnp.max(occ_after)
            n_muted_now = jnp.sum(muted2.astype(jnp.int32))
            n_over_now = jnp.sum(
                (occ_after > opts.overload_occ).astype(jnp.int32))
            nrej_all, nbad_all, ndl_all, nmut_all = (
                nrej_new, nbad_new, ndl_new, nmut_new)
            # Worst-cohort queue-wait p99 of the cumulative histograms —
            # in-trace twin of analysis.hist_percentile (bucket k holds
            # waits in [2^k, 2^(k+1)); the reported value is the lower
            # bound of the first bucket whose cumulative count reaches
            # ceil(0.99 * total)). Rides the aux so the host's window
            # controller sees queue-wait pressure with no extra fetch.
            nd_prof = qw_hist2.shape[0] // QW_BUCKETS
            if nd_prof > 0:
                h2 = qw_hist2.reshape(nd_prof, QW_BUCKETS)
                tot = jnp.sum(h2, axis=1)
                need = jnp.maximum(1, (tot * 99 + 99) // 100)
                first = jnp.argmax(
                    jnp.cumsum(h2, axis=1) >= need[:, None],
                    axis=1).astype(jnp.int32)
                qw_p99 = jnp.max(jnp.where(
                    tot > 0, jnp.left_shift(jnp.int32(1), first),
                    jnp.int32(0)))
            else:
                qw_p99 = jnp.int32(0)
        else:
            occ_sum = occ_max = n_muted_now = n_over_now = jnp.int32(0)
            nrej_all = nbad_all = ndl_all = nmut_all = jnp.int32(0)
            qw_p99 = jnp.int32(0)
        local_pending = (jnp.any(occ_after[:fh] > 0)
                         | (res.spill_count > 0) | (rsp_count > 0))
        any_muted_local = jnp.any(muted2)
        host_pending = (jnp.any(occ_after[fh:] > 0) if fh < nl
                        else jnp.bool_(False))
        # Sticky: once any step overflowed, every later aux reports it, so
        # the host catches it whatever its fetch cadence (quiesce_interval).
        overflow = st.spill_overflow[0] | res.spill_overflow | rsp_over
        # End-of-tick facts feeding the next tick's gather gates (exact,
        # not conservative: `pressured`/`muted2` are post-destroy finals,
        # `rsp_count` is the post-route spill count).
        any_pressured_local = jnp.any(pressured)
        any_rspill_local = rsp_count > 0
        if p > 1:
            # ONE packed psum + ONE packed pmax replace the former ~17
            # separate collectives (≙ the CNF/ACK token protocol being a
            # single token, not one message per fact, scheduler.c:303-480).
            # Booleans ride as 0/1 counts ("any" = sum > 0); cumulative
            # counters wrap mod 2^32 exactly as the per-shard counters do.
            i32c = lambda x: jnp.asarray(x, jnp.int32)  # noqa: E731
            summed = lax.psum(jnp.stack([
                i32c(spawn_fail), i32c(local_pending),
                i32c(any_muted_local), i32c(host_pending),
                i32c(exit_f), i32c(overflow),
                i32c(any_pressured_local), i32c(any_rspill_local),
                st.n_processed[0] + nproc_total,
                st.n_delivered[0] + res.n_delivered,
                occ_sum, n_muted_now, n_over_now,
                nrej_all, nbad_all, ndl_all, nmut_all,
                i32c(blob_fail), i32c(blob_budget)]), "actors")
            spawn_fail_any = summed[0] > 0
            device_pending = summed[1] > 0
            any_muted_all = summed[2] > 0
            host_pending = summed[3] > 0
            exit_any = summed[4] > 0
            overflow_any = summed[5] > 0
            any_pressured_all = summed[6] > 0
            any_rspill_all = summed[7] > 0
            nproc_all = summed[8]
            ndel_all = summed[9]
            blob_fail_any = summed[17] > 0
            blob_budget_any = summed[18] > 0
            if opts.analysis >= 1:
                occ_sum, n_muted_now, n_over_now = (summed[10], summed[11],
                                                    summed[12])
                nrej_all, nbad_all, ndl_all, nmut_all = (
                    summed[13], summed[14], summed[15], summed[16])
            maxed = lax.pmax(jnp.stack([
                jnp.where(exit_f, exit_c, jnp.int32(-2**31)), occ_max,
                qw_p99]), "actors")
            exit_code_all = jnp.where(exit_any, maxed[0], exit_c)
            if opts.analysis >= 1:
                occ_max = maxed[1]
                qw_p99 = maxed[2]
        else:
            spawn_fail_any = spawn_fail
            device_pending = local_pending
            any_muted_all = any_muted_local
            exit_any = exit_f
            exit_code_all = exit_c
            overflow_any = overflow
            any_pressured_all = any_pressured_local
            any_rspill_all = any_rspill_local
            nproc_all = st.n_processed[0] + nproc_total
            ndel_all = st.n_delivered[0] + res.n_delivered
            blob_fail_any = blob_fail
            blob_budget_any = blob_budget
        wb_new =(any_pressured_all.astype(jnp.int32)
                  | (any_muted_all.astype(jnp.int32) << 1)
                  | (any_rspill_all.astype(jnp.int32) << 2))

        def vec(x, dtype=None):   # per-shard "scalar" → [1]
            return jnp.asarray(x, dtype).reshape(1)

        st2 = RtState(
            buf=res.buf, head=new_head, tail=new_tail,
            alive=alive, muted=muted2, mute_refs=mute_refs2,
            mute_age=mute_age2,
            mute_ovf=mute_ovf2, pinned=pinned, pressured=pressured,
            dspill_tgt=res.spill.tgt, dspill_sender=res.spill.sender,
            dspill_words=res.spill.words,
            dspill_count=vec(res.spill_count),
            rspill_tgt=new_rspill.tgt, rspill_sender=new_rspill.sender,
            rspill_words=new_rspill.words,
            rspill_count=vec(rsp_count),
            spill_overflow=vec(overflow, jnp.bool_),
            exit_flag=vec(exit_f, jnp.bool_), exit_code=vec(exit_c),
            step_no=vec(st.step_no[0] + 1),
            n_processed=vec(st.n_processed[0] + nproc_total),
            n_delivered=vec(st.n_delivered[0] + res.n_delivered),
            n_rejected=vec(nrej_new),
            n_badmsg=vec(nbad_new),
            n_deadletter=vec(ndl_new),
            n_mutes=vec(nmut_new),
            n_spawned=vec(st.n_spawned[0] + n_spawned),
            n_destroyed=vec(st.n_destroyed[0] + n_destroyed),
            spawn_fail=vec(spawn_fail, jnp.bool_),
            n_collected=st.n_collected,
            last_error=last_error, last_error_loc=last_error_loc,
            n_errors=vec(st.n_errors[0] + n_errors),
            ev_data=ev_data, ev_count=vec(ev_count),
            ev_dropped=vec(ev_dropped),
            beh_runs=beh_runs2, beh_delivered=beh_del2,
            beh_rejected=beh_rej2, coh_mute_ticks=coh_mt2,
            qwait_hist=qw_hist2, qwait_enq=qw_enq2,
            phase_cost=phase_cost2,
            trace_buf=res.trace_buf,
            span_data=span_data2 if tracing else st.span_data,
            span_count=(vec(span_count2) if tracing else st.span_count),
            span_dropped=(vec(span_dropped2) if tracing
                          else st.span_dropped),
            span_next=(vec(span_next2) if tracing else st.span_next),
            plan_key=res.plan_key, plan_perm=res.plan_perm,
            plan_bounds=res.plan_bounds,
            world_bits=vec(wb_new),
            blob_data=blob_cur[0], blob_used=blob_cur[1],
            blob_len=blob_cur[2], blob_gen=blob_cur[3],
            blob_fail=vec(blob_fail, jnp.bool_),
            blob_budget_fail=vec(blob_budget, jnp.bool_),
            n_blob_alloc=vec(st.n_blob_alloc[0] + nb_alloc),
            n_blob_free=vec(st.n_blob_free[0] + nb_free),
            n_blob_remote=vec(st.n_blob_remote[0] + nb_remote),
            n_blob_moved=vec(st.n_blob_moved[0] + nb_moved),
            type_state=new_type_state,
        )
        aux = StepAux(
            device_pending=device_pending,
            host_pending=host_pending,
            any_muted=any_muted_all,
            exit_flag=exit_any, exit_code=exit_code_all,
            spill_overflow=overflow_any,
            spawn_fail=spawn_fail_any,
            blob_fail=blob_fail_any,
            blob_budget_fail=blob_budget_any,
            n_processed=nproc_all,
            n_delivered=ndel_all,
            occ_sum=occ_sum, occ_max=occ_max,
            n_muted_now=n_muted_now, n_overloaded_now=n_over_now,
            n_rejected=nrej_all, n_badmsg=nbad_all,
            n_deadletter=ndl_all, n_mutes=nmut_all,
            qw_p99=qw_p99,
        )
        return st2, aux

    return local_step


def aux_go(aux: StepAux):
    """The window-continue vote: device work remains and no fact that
    demands host attention (host mailboxes, exit, fatal flags) is up.
    Shared by the in-window while condition and the tick-0 gate of the
    pipelined dispatch (build_multi_step_gated) so the two can never
    disagree about what "host attention" means."""
    return (aux.device_pending & ~aux.host_pending & ~aux.exit_flag
            & ~aux.spill_overflow & ~aux.spawn_fail
            & ~aux.blob_fail & ~aux.blob_budget_fail)


def build_multi_step_gated(program: Program, opts: RuntimeOptions):
    """Fuse up to `limit` scheduler ticks into ONE device dispatch, with
    tick 0 gated ON DEVICE by the PREVIOUS window's aux.

    ≙ the reference amortising scheduler-queue traffic by letting an actor
    drain up to `batch` messages per visit (actor.c:20): here the *host*
    is the expensive queue hop — each jitted call costs a fixed dispatch/
    RPC overhead that dwarfs a tick's compute (the round-2 flat 60ms/tick)
    — so one call advances many ticks under `lax.while_loop`.

    The window ends early the moment the host must act: a host-cohort
    mailbox became non-empty (main-thread actors, scheduler.c:179-190),
    a behaviour exited, a fatal flag rose, or the device quiesced. Host
    reaction latency therefore stays one tick, exactly as unfused.

    The gate (the pipelined run loop, runtime.py): `prev_aux` is the aux
    of the window dispatched just before this one, fed back WITHOUT a
    host round-trip. Tick 0 runs iff `force` (the host KNOWS there is
    work: a sync-point dispatch after host-side writes) or `prev_aux`
    voted clean-busy (aux_go). Otherwise the whole window is an identity
    pass returning `prev_aux` unchanged and ticks_run == 0 — so a window
    speculatively dispatched behind an in-flight one can never advance
    the world past an exit/fatal/host-attention boundary the host has
    not yet observed, and a stale "quiet" vote never runs a tick. That
    keeps the CNF/ACK quiescence semantics (scheduler.c:303-480) exact:
    quiescence is only ever declared from an aux that no later tick has
    invalidated.

    Injections land on the first tick only (the host refills next
    window); a gated-out window consumes none (ticks_run == 0 tells the
    host to re-queue them).
    Returns (state, last_aux, ticks_run).

    delivery="pallas_mega" (PROFILE.md §14): the whole window body runs
    as ONE persistent Pallas kernel (ops/megakernel.py) instead of the
    XLA while-loop below — same step closure, same gate, bit-equivalent
    by construction; ineligible programs (mesh shards, nested Pallas
    kernels on) fall through to the XLA spelling with plan-formulation
    delivery.
    """
    step = build_step(program, opts)
    if opts.delivery == "pallas_mega":
        from ..ops import megakernel
        if megakernel.eligible(program, opts):
            return megakernel.build_mega_window(program, opts, step,
                                                aux_go)

    def multi(st: RtState, inject_tgt, inject_words, limit, force,
              prev_aux: StepAux):
        # BENCH_r05 fix: the run loop redispatches this executable with
        # the SAME inject sentinels / limit every window, and XLA was
        # observed re-running constant folding over the window body per
        # dispatch when those operands fold to literals (the r05 tail
        # stall). The barrier pins them as runtime values — the loop
        # body compiles once, folding stops at this line.
        inject_tgt, inject_words, limit, force = lax.optimization_barrier(
            (inject_tgt, inject_words, limit, force))

        def cond(carry):
            _st, aux, i = carry
            first = i == 0
            return (first & (force | aux_go(aux))) | \
                (~first & (i < limit) & aux_go(aux))

        def body(carry):
            s, _aux, i = carry
            first = i == 0
            it = jnp.where(first, inject_tgt, jnp.int32(-1))
            iw = jnp.where(first, inject_words, jnp.int32(0))
            s2, aux2 = step(s, it, iw)
            return (s2, aux2, i + 1)

        stf, auxf, k = lax.while_loop(cond, body,
                                      (st, prev_aux, jnp.int32(0)))
        return stf, auxf, k

    return multi


def build_multi_step(program: Program, opts: RuntimeOptions):
    """The ungated window: `build_multi_step_gated` with tick 0 forced
    (the pre-pipelining signature — bench.py and the profiling harnesses
    drive it directly; zero_aux as prev keeps the carry well-typed)."""
    gated = build_multi_step_gated(program, opts)

    def multi(st: RtState, inject_tgt, inject_words, limit):
        return gated(st, inject_tgt, inject_words, limit,
                     jnp.bool_(True), zero_aux())

    return multi


def zero_aux() -> StepAux:
    """The pre-first-tick aux template (device_pending=True so a window's
    while condition admits tick 0; everything else zero/false)."""
    i32, b = jnp.int32, jnp.bool_
    return StepAux(
        device_pending=b(True), host_pending=b(False),
        any_muted=b(False),
        exit_flag=b(False), exit_code=i32(0),
        spill_overflow=b(False), spawn_fail=b(False),
        blob_fail=b(False), blob_budget_fail=b(False),
        n_processed=i32(0), n_delivered=i32(0),
        occ_sum=i32(0), occ_max=i32(0),
        n_muted_now=i32(0), n_overloaded_now=i32(0),
        n_rejected=i32(0), n_badmsg=i32(0),
        n_deadletter=i32(0), n_mutes=i32(0), qw_p99=i32(0))


def build_forced_window(program: Program, opts: RuntimeOptions):
    """`limit` ticks of the real step in ONE executable, unconditionally.

    The calibration harness (tuning.py): a `lax.fori_loop` over
    build_step that — unlike build_multi_step's while — ignores every
    early-exit fact (host_pending, exit, sticky failure flags), so a
    synthetic workload's odd corners (spawn-capable cohorts finding no
    free slot, behaviours exiting on zero-filled state) cannot shorten
    the trip count. Wall time / `limit` is then a trustworthy per-tick
    cost: the only timing methodology PROFILE.md §4b admits (per-call
    timings carry an ~11 ms launch floor on the tunnelled chip).
    Injections are applied every tick (the tuner passes the empty
    inject). Same signature family as build_multi_step so
    _jit_over_mesh wraps it identically.

    delivery="pallas_mega" delegates to the megakernel's forced
    spelling (ops/megakernel.py) so calibration times the kernel on
    exactly the trip count every other variant runs."""
    step = build_step(program, opts)
    if opts.delivery == "pallas_mega":
        from ..ops import megakernel
        if megakernel.eligible(program, opts):
            mega = megakernel.build_mega_window(program, opts, step,
                                                aux_go, forced=True)

            def forced_mega(st: RtState, inject_tgt, inject_words,
                            limit):
                return mega(st, inject_tgt, inject_words, limit,
                            jnp.bool_(True), zero_aux())

            return forced_mega

    def forced(st: RtState, inject_tgt, inject_words, limit):
        def body(_i, carry):
            s, _aux = carry
            return step(s, inject_tgt, inject_words)

        stf, auxf = lax.fori_loop(0, limit, body, (st, zero_aux()))
        return stf, auxf, limit

    return forced


def jit_forced_window(program: Program, opts: RuntimeOptions, mesh=None):
    """Jit the calibration window (extra replicated input: trip count;
    extra replicated output: the same count, for signature symmetry
    with jit_multi_step)."""
    return _jit_over_mesh(build_forced_window(program, opts), program,
                          opts, mesh, n_extra=1)


def _jit_over_mesh(fn, program: Program, opts: RuntimeOptions, mesh,
                   n_extra: int, extra_in=None):
    """Jit `fn(state, inject_tgt, inject_words, *extras) → (state, aux,
    *outs)` where len(outs) == n_extra; with a mesh, shard_map over the
    'actors' axis first. State is sharded and donated; injections, extras
    and aux are replicated (aux values are each tick's psum votes,
    identical on every shard). `extra_in` names the extra INPUTS' spec
    kinds — "repl" (scalar) or "aux" (a replicated StepAux pytree, the
    gated window's fed-back prev_aux); defaults to n_extra scalars.

    ≙ ponyint_sched_start picking how many schedulers run
    (scheduler.c:1273-1309) — except "schedulers" are mesh shards and the
    assignment is static.
    """
    if program.shards == 1:
        return jax.jit(fn, donate_argnums=(0,))

    from jax.sharding import PartitionSpec as P
    from .state import state_partition_specs
    assert mesh is not None, "sharded program needs a mesh"
    repl = P()
    state_spec = state_partition_specs(program, opts)
    aux_spec = StepAux(*([repl] * len(StepAux._fields)))
    if extra_in is None:
        extra_in = ("repl",) * n_extra
    in_extra = tuple(aux_spec if kind == "aux" else repl
                     for kind in extra_in)
    from ..compat import shard_map
    mapped = shard_map(
        fn, mesh=mesh,
        in_specs=(state_spec, repl, repl) + in_extra,
        out_specs=(state_spec, aux_spec) + (repl,) * n_extra)
    return jax.jit(mapped, donate_argnums=(0,))


def jit_multi_step(program: Program, opts: RuntimeOptions, mesh=None):
    """Jit the fused window (extra replicated input: tick limit; extra
    replicated output: ticks run — so the while condition and the host's
    step accounting are shard-uniform)."""
    return _jit_over_mesh(build_multi_step(program, opts), program, opts,
                          mesh, n_extra=1)


def jit_multi_step_gated(program: Program, opts: RuntimeOptions,
                         mesh=None):
    """Jit the PIPELINED window (build_multi_step_gated): extra
    replicated inputs (tick limit, force bit, previous aux — all
    shard-uniform by construction), extra replicated output ticks_run.
    The run loop feeds each window's aux straight into the next
    dispatch, so the gate costs no host round-trip."""
    return _jit_over_mesh(build_multi_step_gated(program, opts), program,
                          opts, mesh, n_extra=1,
                          extra_in=("repl", "repl", "aux"))


def jit_step(program: Program, opts: RuntimeOptions, mesh=None):
    """Jit one tick (see _jit_over_mesh for the mesh wrapping)."""
    return _jit_over_mesh(build_step(program, opts), program, opts, mesh,
                          n_extra=0)


def _state_structure(program, opts):
    """A pytree with the same structure as RtState for building specs."""
    from .state import init_state
    return jax.eval_shape(lambda: init_state(program, opts))
