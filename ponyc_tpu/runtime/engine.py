"""The dispatch step: one scheduler tick over the whole actor world, jitted.

≙ the reference's hot loop (SURVEY.md §3.3): scheduler `run`
(src/libponyrt/sched/scheduler.c:953-1090) popping actors and
`ponyint_actor_run` (src/libponyrt/actor/actor.c:383-549) draining up to
`batch` messages per actor through `type->dispatch`. On TPU there is no
work-stealing — the entire world advances in lockstep:

  per device cohort (actors of one type, contiguous ids):
      gather  ≤batch messages per actor from the mailbox table
      scan    over batch slots; per slot a `lax.switch` over the type's
              behaviours (≙ the generated dispatch switch, genfun.c),
              vmapped over the cohort's actors
      collect sends / exit / yield effects functionally
  then one global `deliver` (see delivery.py) routes every produced
  message, and flag updates implement mute/unmute and quiescence bits.

Work-stealing, victim selection and scaling-sleep (scheduler.c:485-935)
have no TPU analog — idle actors cost one masked lane, not a core; the
*quiescence protocol* (CNF/ACK tokens, scheduler.c:303-480) collapses to a
reduction over mailbox occupancies returned to the host every tick.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..api import Context
from ..config import RuntimeOptions
from ..ops import pack
from ..ops.segment import counts_by_key
from ..program import Cohort, Program
from .delivery import Entries, deliver
from .state import RtState


class StepAux(NamedTuple):
    """Small per-step scalars fetched by the host driver (≙ the scheduler's
    control-message reads + quiescence vote, scheduler.c:303-480)."""
    device_pending: jnp.ndarray  # bool — any device mailbox/spill work left
    host_pending: jnp.ndarray    # bool — host-cohort mailboxes non-empty
    exit_flag: jnp.ndarray       # bool — some behaviour called ctx.exit
    exit_code: jnp.ndarray       # int32
    spill_overflow: jnp.ndarray  # bool — fatal: spill buffer exceeded
    n_processed: jnp.ndarray     # int32 — *cumulative* behaviours run
    n_delivered: jnp.ndarray     # int32 — *cumulative* deliveries
    # (cumulative = state counters; the host accumulates mod-2^32 deltas,
    # so fetches may be arbitrarily far apart as long as fewer than 2^31
    # events occur between two fetches.)


def _make_branch(bdef, msg_words: int, max_sends: int, field_dtypes):
    """Wrap one behaviour into a switch branch with canonical outputs."""
    w1 = 1 + msg_words

    def branch(operand):
        st, payload, actor_id = operand
        ctx = Context(actor_id, msg_words)
        args = pack.unpack_args(bdef.arg_specs, payload)
        st2 = bdef.fn(ctx, dict(st), *args)
        if st2 is None:
            raise TypeError(
                f"behaviour {bdef} must return the (possibly updated) state "
                "dict")
        if set(st2.keys()) != set(st.keys()):
            raise TypeError(
                f"behaviour {bdef} changed the state fields: "
                f"{sorted(st2)} vs {sorted(st)}")
        st2 = {k: jnp.asarray(v, field_dtypes[k]) for k, v in st2.items()}
        if len(ctx.sends) > max_sends:
            raise RuntimeError(
                f"behaviour {bdef} performs {len(ctx.sends)} sends but the "
                f"type's send budget is {max_sends}; set MAX_SENDS = "
                f"{len(ctx.sends)} on the actor class")
        tgts, words = [], []
        for (t, w, when) in ctx.sends:
            tgts.append(jnp.where(when, t, jnp.int32(-1)))
            words.append(w)
        for _ in range(max_sends - len(ctx.sends)):
            tgts.append(jnp.int32(-1))
            words.append(jnp.zeros((w1,), jnp.int32))
        tgt_arr = jnp.stack(tgts) if tgts else jnp.zeros((0,), jnp.int32)
        words_arr = (jnp.stack(words) if words
                     else jnp.zeros((0, w1), jnp.int32))
        return (st2, (tgt_arr, words_arr),
                (ctx.exit_flag, ctx.exit_code), ctx.yield_flag)

    return branch


def _make_noop_branch(msg_words: int, max_sends: int):
    w1 = 1 + msg_words

    def branch(operand):
        st, _payload, _actor_id = operand
        return (dict(st),
                (jnp.full((max_sends,), -1, jnp.int32),
                 jnp.zeros((max_sends, w1), jnp.int32)),
                (jnp.bool_(False), jnp.int32(0)),
                jnp.bool_(False))

    return branch


def _cohort_dispatch(cohort: Cohort, opts: RuntimeOptions, noyield: bool):
    """Build the vmapped per-actor drain loop for one cohort.

    ≙ ponyint_actor_run (actor.c:383-549): pop ≤batch app messages,
    dispatch each, honour yield (fork: actor.c:675-679), count consumption.
    """
    msg_words = opts.msg_words
    ms = cohort.max_sends
    batch = cohort.batch
    field_dtypes = {}
    for fname, spec in cohort.atype.field_specs.items():
        field_dtypes[fname] = (jnp.float32 if spec is pack.F32
                               else jnp.int32)
    branches = [_make_branch(b, msg_words, ms, field_dtypes)
                for b in cohort.behaviours]
    branches.append(_make_noop_branch(msg_words, ms))
    nb = len(cohort.behaviours)
    base = cohort.behaviours[0].global_id if nb else 0

    def actor_fn(st_row, msgs, valids, actor_id):
        # msgs: [batch, 1+W]; valids: [batch] bool.
        def scan_body(carry, x):
            st, stopped, ef, ec, nproc, nbad = carry
            msg, valid = x
            local = msg[0] - base
            in_range = (local >= 0) & (local < nb)
            do = valid & ~stopped
            bid = jnp.where(do & in_range, local, nb)
            st2, (stgt, swords), (bef, bec), yf = lax.switch(
                bid, branches, (st, msg[1:], actor_id))
            new_ef = ef | bef
            new_ec = jnp.where(bef & ~ef, bec, ec)
            stopped2 = stopped if noyield else (stopped | yf)
            return ((st2, stopped2, new_ef, new_ec,
                     nproc + (do & in_range).astype(jnp.int32),
                     nbad + (do & ~in_range).astype(jnp.int32)),
                    (stgt, swords, do))

        carry0 = (st_row, jnp.bool_(False), jnp.bool_(False), jnp.int32(0),
                  jnp.int32(0), jnp.int32(0))
        (stf, _, ef, ec, nproc, nbad), (stgt, swords, consumed) = lax.scan(
            scan_body, carry0, (msgs, valids))
        n_consumed = jnp.sum(consumed.astype(jnp.int32))
        return stf, (stgt, swords), ef, ec, nproc, nbad, n_consumed

    vfn = jax.vmap(actor_fn)

    def run_cohort(type_state_row, buf_rows, head_rows, occ_rows,
                   runnable_rows):
        n_run = jnp.where(runnable_rows,
                          jnp.minimum(occ_rows, batch), 0)
        k = jnp.arange(batch, dtype=jnp.int32)
        idx = (head_rows[:, None] + k[None, :]) % opts.mailbox_cap
        msgs = jnp.take_along_axis(buf_rows, idx[:, :, None], axis=1)
        valids = k[None, :] < n_run[:, None]
        ids = (cohort.start +
               jnp.arange(cohort.capacity, dtype=jnp.int32))
        stf, (stgt, swords), ef, ec, nproc, nbad, n_consumed = vfn(
            type_state_row, msgs, valids, ids)
        # Flatten the outbox: [cap*batch*ms] entries in (actor, slot, send)
        # order — exactly a sender's causal emission order.
        e = cohort.capacity * batch * ms
        sender = jnp.repeat(ids, batch * ms)
        out = Entries(tgt=stgt.reshape(e),
                      sender=sender,
                      words=swords.reshape(e, -1))
        any_exit = jnp.any(ef)
        code = ec[jnp.argmax(ef)]
        return (stf, out, head_rows + n_consumed, any_exit, code,
                jnp.sum(nproc), jnp.sum(nbad))

    return run_cohort


def build_step(program: Program, opts: RuntimeOptions):
    """Trace one whole-world scheduler tick; returns a jittable fn
    step(state, inject_tgt, inject_words) → (state, StepAux)."""
    assert program.frozen
    n = program.total
    c = opts.mailbox_cap
    fh = program.first_host_id
    dev_cohorts = program.device_cohorts
    dispatchers = [(_cohort_dispatch(ch, opts, opts.noyield), ch)
                   for ch in dev_cohorts]

    def step(st: RtState, inject_tgt, inject_words
             ) -> Tuple[RtState, StepAux]:
        occ0 = st.tail - st.head

        # --- 1. unmute pass (≙ ponyint_sched_unmute_senders,
        # scheduler.c:1552-1635: receiver recovered → senders released).
        sp_valid = st.spill_tgt >= 0
        spill_pending = counts_by_key(
            jnp.minimum(jnp.maximum(st.spill_tgt, 0), n - 1),
            sp_valid.astype(jnp.int32), n)
        has_ref = st.mute_ref >= 0
        mr = jnp.minimum(jnp.maximum(st.mute_ref, 0), n - 1)
        release = st.muted & (
            ~has_ref | ((occ0[mr] <= opts.unmute_occ)
                        & (spill_pending[mr] == 0)))
        muted = st.muted & ~release
        mute_ref = jnp.where(release, -1, st.mute_ref)

        # --- 2. drain + dispatch per cohort (≙ actor run loop).
        runnable = st.alive & ~muted
        new_type_state: Dict[str, Dict[str, Any]] = dict(st.type_state)
        head_segments: List[jnp.ndarray] = []
        out_entries: List[Entries] = []
        exit_f = st.exit_flag
        exit_c = st.exit_code
        nproc_total = jnp.int32(0)
        nbad_total = jnp.int32(0)
        for run_cohort, ch in dispatchers:
            s0, s1 = ch.start, ch.stop
            stf, out, new_head_rows, ef, ec, nproc, nbad = run_cohort(
                st.type_state[ch.atype.__name__],
                st.buf[s0:s1], st.head[s0:s1], occ0[s0:s1],
                runnable[s0:s1])
            new_type_state[ch.atype.__name__] = stf
            head_segments.append(new_head_rows)
            out_entries.append(out)
            exit_c = jnp.where(ef & ~exit_f, ec, exit_c)
            exit_f = exit_f | ef
            nproc_total = nproc_total + nproc
            nbad_total = nbad_total + nbad
        if fh < n:  # host-cohort heads unchanged by device dispatch
            head_segments.append(st.head[fh:n])
        new_head = (jnp.concatenate(head_segments) if head_segments
                    else st.head)

        # --- 3. assemble this tick's in-flight messages:
        # oldest spill first, then host injections, then fresh outbox.
        spill_e = Entries(st.spill_tgt, st.spill_sender, st.spill_words)
        inject_e = Entries(inject_tgt,
                           jnp.full_like(inject_tgt, n), inject_words)
        all_e = Entries(
            tgt=jnp.concatenate([spill_e.tgt, inject_e.tgt]
                                + [o.tgt for o in out_entries]),
            sender=jnp.concatenate([spill_e.sender, inject_e.sender]
                                   + [o.sender for o in out_entries]),
            words=jnp.concatenate([spill_e.words, inject_e.words]
                                  + [o.words for o in out_entries]),
        )
        # Sends to dead slots are dropped (the reference's type system makes
        # this unrepresentable — ORCA keeps receivers alive; here it is a
        # counted dynamic error: n_deadletter).
        tgt_clip = jnp.minimum(jnp.maximum(all_e.tgt, 0), n - 1)
        to_dead = (all_e.tgt >= 0) & (all_e.tgt < n) & ~st.alive[tgt_clip]
        n_dead = jnp.sum(to_dead.astype(jnp.int32))
        all_e = all_e._replace(tgt=jnp.where(to_dead, -1, all_e.tgt))

        # --- 4. delivery (the batched pony_sendv; see delivery.py).
        res = deliver(st.buf, new_head, st.tail, all_e,
                      num_actors=n, mailbox_cap=c,
                      spill_cap=opts.spill_cap,
                      overload_occ=opts.overload_occ)

        # --- 5. mute bookkeeping (≙ ponyint_mute_actor, actor.c:1171-1207).
        became_muted = res.newly_muted & ~muted
        muted2 = muted | res.newly_muted
        mute_ref2 = jnp.where(res.newly_muted, res.new_mute_ref, mute_ref)

        occ_after = res.tail - new_head
        device_pending = jnp.any(occ_after[:fh] > 0) | (res.spill_count > 0)
        host_pending = (jnp.any(occ_after[fh:] > 0) if fh < n
                        else jnp.bool_(False))

        st2 = RtState(
            buf=res.buf, head=new_head, tail=res.tail,
            alive=st.alive, muted=muted2, mute_ref=mute_ref2,
            spill_tgt=res.spill.tgt, spill_sender=res.spill.sender,
            spill_words=res.spill.words, spill_count=res.spill_count,
            spill_overflow=st.spill_overflow | res.spill_overflow,
            exit_flag=exit_f, exit_code=exit_c,
            step_no=st.step_no + 1,
            n_processed=st.n_processed + nproc_total,
            n_delivered=st.n_delivered + res.n_delivered,
            n_rejected=st.n_rejected + res.n_rejected,
            n_badmsg=st.n_badmsg + nbad_total,
            n_deadletter=st.n_deadletter + n_dead,
            n_mutes=st.n_mutes + jnp.sum(became_muted.astype(jnp.int32)),
            type_state=new_type_state,
        )
        aux = StepAux(
            device_pending=device_pending,
            host_pending=host_pending,
            exit_flag=exit_f, exit_code=exit_c,
            spill_overflow=st2.spill_overflow,
            n_processed=st2.n_processed,
            n_delivered=st2.n_delivered,
        )
        return st2, aux

    return step


def jit_step(program: Program, opts: RuntimeOptions):
    return jax.jit(build_step(program, opts), donate_argnums=(0,))
