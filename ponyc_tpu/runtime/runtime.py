"""Host driver: runtime construction, spawning, host↔device messaging and
the run-to-quiescence loop.

≙ the reference's runtime bootstrap and lifecycle
(src/libponyrt/sched/start.c: pony_init parses flags and sizes the world,
pony_start runs schedulers until quiescence, pony_get_exitcode returns the
program's code) plus the host side of actor creation
(pony_create, actor/actor.c:688-734) and external sends (pony_sendv from
non-actor context).

The host loop is deliberately thin: it issues ONE fused device dispatch
per iteration (engine.build_multi_step — a lax.while_loop advancing up to
`quiesce_interval` ticks that self-terminates the moment host attention
is needed), then reads back a handful of scalars to decide termination —
the TPU analog of the CNF/ACK quiescence vote (scheduler.c:303-480).
Host-resident actors (HOST=True types — the main-thread/ASIO-side actors
of the reference, scheduler.c:179-190, asio/asio.c) are drained at those
window boundaries; the early window stop keeps their reaction latency at
one tick, as if steps were dispatched singly.
"""

from __future__ import annotations

import collections
import os
import signal
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..api import ActorTypeMeta, BehaviourDef
from ..config import RuntimeOptions
from ..errors import ERROR_CODES, PonyError, PonyStallError, error_code
from ..ops import pack
from ..program import Program
from . import engine
from .controller import WindowController
from .state import RtState, init_state

# Window-length histogram buckets (power-of-two, like state.QW_BUCKETS):
# bucket k counts retired windows that ran [2^k, 2^(k+1)) ticks.
WIN_BUCKETS = 16


class SpillOverflowError(RuntimeError):
    """The bounded overflow spill was exceeded — raise mailbox_cap or
    spill_cap, or let backpressure mute faster (lower overload_threshold)."""

    code = ERROR_CODES["SpillOverflowError"]


class AmbientAuth:
    """Root authority (≙ env.root: AmbientAuth). Obtained only from
    Runtime.ambient_auth(); narrower capability tokens check for it.
    The sentinel token (same pattern as files.FilesAuth) makes direct
    construction impossible, so holding `rt` alone does not mint it."""

    _token = object()

    def __init__(self, rt, token=None):
        if token is not AmbientAuth._token:
            raise PermissionError(
                "obtain AmbientAuth via rt.ambient_auth(), not directly")
        self._rt = rt


class SpawnCapacityError(RuntimeError):
    """A device-side ctx.spawn() wanted a slot but its cohort window had
    none free — raise the target cohort's declared capacity (or let GC /
    destroy() return slots faster)."""

    code = ERROR_CODES["SpawnCapacityError"]


class BlobCapacityError(RuntimeError):
    """A device-side ctx.blob_alloc() wanted a pool slot but its window
    had none free — raise RuntimeOptions.blob_slots, or free blobs
    (ctx.blob_free) faster. ≙ pony_alloc exhausting the heap."""

    code = ERROR_CODES["BlobCapacityError"]


class HostContext:
    """Effect collector for host-resident behaviours (≙ running an actor on
    the main-thread scheduler, scheduler.c:1030-1035)."""

    def __init__(self, rt: "Runtime", actor_id: int):
        self.rt = rt
        self.actor_id = actor_id
        self.exit_flag = False
        self.exit_code = 0
        self.yield_flag = False
        self.trace_ctx = None   # (trace_id, span_id) of this dispatch
        #   when causal tracing followed the message here — sends
        #   below continue the chain (PROFILE.md §10)

    def send(self, target, behaviour_def, *args, when=True):
        if when:
            self.rt.send(int(target), behaviour_def, *args,
                         trace=self.trace_ctx)

    def exit(self, code=0, when=True):
        if when:
            self.exit_flag = True
            self.exit_code = int(code)

    def yield_(self, when=True):
        if when:
            self.yield_flag = True


def _host_pack_args(specs, args, msg_words):
    words = np.zeros((msg_words,), np.int32)
    if len(args) != len(specs):
        raise TypeError(f"behaviour takes {len(specs)} args, got {len(args)}")
    off = 0
    for spec, v in zip(specs, args):
        if isinstance(spec, pack._VecSpec):
            dt = np.float32 if spec.base is pack.F32 else np.int32
            arr = np.asarray(v, dt).reshape(-1)
            if arr.shape[0] != spec.n:
                raise TypeError(f"argument for {spec.__name__} must have "
                                f"{spec.n} elements, got {arr.shape[0]}")
            words[off:off + spec.n] = arr.view(np.int32)
            off += spec.n
        elif spec is pack.F32:
            words[off] = np.float32(v).view(np.int32)
            off += 1
        elif spec is pack.Bool:
            words[off] = np.int32(bool(v))
            off += 1
        elif spec is pack.U32:
            words[off] = np.asarray(v, np.int64).astype(
                np.uint32).view(np.int32)
            off += 1
        elif spec in pack._NARROW_JNP:
            dt = pack.narrow_np_map()[spec]
            # astype wraps out-of-range values to the declared width
            # (np scalar constructors would raise instead).
            words[off] = np.asarray(v, np.int64).astype(dt).astype(np.int32)
            off += 1
        else:
            words[off] = np.int32(v)
            off += 1
    return words


def _host_unpack_args(specs, words):
    out = []
    off = 0
    for spec in specs:
        if isinstance(spec, pack._VecSpec):
            blk = np.asarray(words[off:off + spec.n], np.int32)
            out.append(blk.view(np.float32) if spec.base is pack.F32
                       else blk)
            off += spec.n
            continue
        w = np.int32(words[off])
        off += 1
        if spec is pack.F32:
            out.append(float(w.view(np.float32)))
        elif spec is pack.Bool:
            out.append(bool(w))
        elif spec is pack.U32:
            out.append(int(w.view(np.uint32)))
        elif spec in pack._NARROW_JNP:
            out.append(int(w.astype(pack.narrow_np_map()[spec])))
        else:
            out.append(int(w))
    return tuple(out)


class Runtime:
    """A live actor world bound to one program layout.

    Typical use::

        rt = Runtime(opts)
        rt.declare(RingNode, 1024)
        rt.start()                       # ≙ pony_init: freeze + allocate
        refs = rt.spawn_many(RingNode, next_ref=..., passes=...)
        rt.send(refs[0], RingNode.token, 1000)
        code = rt.run()                  # ≙ pony_start: run to quiescence
    """

    def __init__(self, opts: Optional[RuntimeOptions] = None):
        self._opts_defaulted = opts is None
        self.opts = opts or RuntimeOptions()
        self.program = Program(self.opts)
        self.state: Optional[RtState] = None  # via the property below
        self._step = None
        self._inject_q: collections.deque = collections.deque()
        # Host fast lane (opts.host_fastpath): host-sender → host-target
        # messages, dispatched at host boundaries without touching the
        # device mailbox table (≙ inject_main, scheduler.c:179-190).
        self._host_fast_q: collections.deque = collections.deque()
        # Device-pool blob handles the HOST currently owns (blob_store
        # not yet sent/freed) — GC roots for the blob sweep (gc.py).
        self._host_blobs: set = set()
        self._free: Dict[str, List[int]] = {}
        self._host_state: Dict[int, Dict[str, Any]] = {}
        self._exit_code = 0
        self._exit_requested = False
        self._device_dirty = True     # force the first window of a run
        self._idle_boundaries = 0     # lifetime skipped host-only
        #   boundaries; feeds the cd_interval GC cadence so host-heavy
        #   phases still collect (steps_run freezes while skipping)
        self._noisy = 0          # ≙ asio noisy_count keeping runtime alive
        self._bridge_pollers: List[Any] = []   # asio backends (bridge/)
        self.steps_run = 0
        self.totals = collections.Counter()    # lifetime stats (host ints)
        # Host-cohort behaviour runs by global id (the host twin of the
        # device beh_runs matrix — host behaviours dispatch here, so the
        # device counters never see them; profile() merges both).
        self._beh_host_runs: collections.Counter = collections.Counter()
        self._last_counters: Dict[str, int] = {}
        self._gc_fn = None
        self._freelist_key = None   # None = stale; "synced" = cache valid
        self._ref_mask = None
        self._ever_released = False
        self._last_gc_step = 0
        self._next_gc = self.opts.gc_initial   # ≙ heap.c next_gc
        self._host_errors: Dict[int, int] = {}
        self._host_error_locs: Dict[int, str] = {}
        self._tracer = None      # tracing.Tracer, set by start() when
        #   opts.tracing (analysis >= 3 and trace_sample > 0)
        self.tuning_record: Optional[Dict[str, Any]] = None   # set by
        #   start() when any option is "auto" (tuning.resolve): source
        #   (cache/calibrated/default), per-variant tick_ms table,
        #   winner — bench.py publishes it as the A/B record
        # ---- adaptive run loop (PROFILE.md §9) ----
        self._controller: Optional[WindowController] = None  # window
        #   sizer, created at start() (fixed lo==hi when
        #   quiesce_interval is a concrete int)
        self._qi_auto = False         # quiesce_interval was "auto"
        self._qi_loaded = 0           # the initial window resolve() gave
        self._state_epoch = 0         # monotonic state-write stamp: the
        #   pipelined retire clears _device_dirty only when NO host
        #   write landed since that window's dispatch (a write after
        #   dispatch is invisible to the window's aux)
        self._last_retire_t: Optional[float] = None
        # Run-loop telemetry (run_loop_stats()): windows retired, how
        #   many dispatches rode behind an in-flight window, cumulative
        #   host-imposed device-idle gap, re-queued gated-out injects,
        #   window-length histogram.
        self._rl_windows = 0
        self._rl_pipelined = 0
        self._rl_synced = 0
        self._rl_gap_ns = 0
        self._rl_requeued = 0
        self._win_hist = np.zeros((WIN_BUCKETS,), np.int64)
        # ---- operational observability (PROFILE.md §11) ----
        self._flight = None           # flight.FlightRecorder (start())
        self._watchdog = None         # flight.Watchdog when watchdog_s
        self._metrics = None          # metrics.MetricsServer when
        #   metrics_port is not None
        self._ckpt = None             # serialise.Checkpointer when
        #   checkpoint_every_s is set (durable worlds, PROFILE.md §12)
        self._costs = None            # costs.capture memo — measured
        #   cost/memory analysis of the compiled executables (ISSUE 19)
        self._last_run_crashed = False  # run() exited exceptionally:
        #   stop() must NOT overwrite the ring's newest snapshot with
        #   the post-crash world (the supervisor restores the last
        #   intact PRE-crash checkpoint)
        self._wd_epoch = 0            # phase-stamp progress counter
        self._wd_stamp = ("idle", 0, time.monotonic())  # (phase,
        #   epoch, t): one tuple assignment per transition — the cheap
        #   progress evidence the watchdog thread reads
        # Coded runtime errors raised/caught on this runtime, keyed
        # (class_name, int code) — the errors.ERROR_CODES metrics label
        # and the postmortem's error section.
        self._error_counts: collections.Counter = collections.Counter()
        self._last_aux = None         # newest RETIRED window's host-side
        #   StepAux (numpy scalars): the zero-extra-fetch telemetry feed
        #   for edge consumers — the serving tier's admission controller
        #   (serve.py) reads qw_p99/n_muted_now here
        self._serve = None            # serve.Server when a front door is
        #   attached (metrics/flight surface the serving block)

    # Any state assignment — including a driver pushing rt._step results
    # back, as bench.py does — conservatively invalidates the cached
    # freelists; internal writers that provably keep them consistent
    # restore _freelist_key after assigning.
    @property
    def state(self) -> Optional[RtState]:
        return self._state

    @state.setter
    def state(self, v) -> None:
        self._state = v
        self._freelist_key = None
        # Any host-side state write may have created device work the
        # last window's aux cannot know about (bulk_send's direct
        # mailbox writes, restore(), flag flips) — the run loop's
        # host-only-boundary skip must not trust stale quiescence.
        self._device_dirty = True
        # Write stamp for the pipelined run loop: a window's aux is
        # authoritative at retire only if this counter still matches
        # its at-dispatch value (no write raced the in-flight window).
        self._state_epoch = getattr(self, "_state_epoch", 0) + 1

    # ---- construction (≙ pony_init) ----
    def declare(self, atype: ActorTypeMeta, capacity: int) -> "Runtime":
        self.program.declare(atype, capacity)
        return self

    def start(self) -> "Runtime":
        # ≙ pony_init, split so the operational pieces (the always-on
        # flight recorder + optional stall watchdog, PROFILE.md §11)
        # arm BEFORE the first device-touching call: a hung backend
        # init (the jax.devices() wedge that silently degraded BENCH
        # r03–r05 to CPU) then trips the watchdog — postmortem on disk,
        # int-coded PonyStallError raised — instead of hanging forever.
        self._apply_defaults_and_pin()
        from .. import flight as _flight
        self._flight = _flight.FlightRecorder(
            self, self.opts.flight_windows)
        self._stamp("backend-init")
        if self.opts.watchdog_s is not None:
            self._watchdog = _flight.Watchdog(self, self.opts.watchdog_s)
            self._watchdog.start()
        try:
            self._start_world()
        except KeyboardInterrupt:
            stall = self._stall_from_interrupt()
            if stall is not None:
                raise stall from None
            raise
        if self.opts.cost_capture:
            # Device-cost observatory (ISSUE 19): record XLA's own
            # cost/memory analysis of the just-built executables so
            # every BENCH json / postmortem / metrics scrape carries
            # measured numbers next to the modelled ones. Opt-in: it
            # AOT-compiles step+window once more (lower() only — the
            # world does not advance).
            from .. import costs as _costs
            _costs.capture(self, force=True)
            _costs.measured_block(self)
        if self.opts.metrics_port is not None:
            from .. import metrics as _metrics
            self._metrics = _metrics.MetricsServer(
                self, self.opts.metrics_port)
            self._metrics.update_now(self)
        if self.opts.checkpoint_every_s is not None:
            from .. import serialise as _serialise
            self._ckpt = _serialise.Checkpointer(self)
        self._stamp("idle")
        return self

    def _apply_defaults_and_pin(self) -> None:
        # ≙ Main_runtime_override_defaults_oo (start.c:99,214): a declared
        # actor type may override runtime defaults — applied only when the
        # caller didn't pass explicit options (explicit flags win, exactly
        # like the reference's CLI > Main-override > default ordering).
        if self._opts_defaulted:
            import dataclasses as _dc
            overrides = {}
            for atype, _cap in self.program._declared:
                overrides.update(getattr(atype, "RUNTIME_DEFAULTS", {}))
            if overrides:
                self.opts = _dc.replace(self.opts, **overrides)
                self.program.opts = self.opts
                self.program.shards = max(1, self.opts.mesh_shards)
        if self.opts.pin >= 0:   # ≙ --ponypin (start.c:75-94): pin the
            # host driver thread (the "scheduler" of this runtime)
            try:
                self._pre_pin_affinity = os.sched_getaffinity(0)
                os.sched_setaffinity(0, {self.opts.pin})
            except OSError as e:
                raise ValueError(
                    f"cannot pin host thread to core {self.opts.pin}: "
                    f"{e}") from None

    def _start_world(self) -> None:
        # Persistent compile cache (tuning.enable_compile_cache): lands
        # before the first jit of this runtime so warm starts reload
        # executables instead of re-lowering (PROFILE.md §4b's 11.8 s).
        from .. import tuning
        from ..config import auto_fields
        tuning.enable_compile_cache(self.opts.compile_cache)
        self.program.finalize()
        self.state = init_state(self.program, self.opts)
        if self.program.shards > 1:
            from ..parallel.mesh import make_mesh, shard_state
            self.mesh = make_mesh(self.program.shards)
            self.state = shard_state(self.state, self.mesh)
        else:
            self.mesh = None
        if auto_fields(self.opts):
            # Resolve "auto" formulation choices to measured winners
            # BEFORE the engine traces (it only ever sees concrete
            # opts). Calibration runs on throwaway copies of the fresh
            # state; only delivery/pallas/pallas_fused may change, none
            # of which affect Program layout or state shapes.
            self.opts, self.tuning_record = tuning.resolve(
                self.program, self.opts, self.mesh, self.state)
            self.program.opts = self.opts
        # Adaptive quiesce window (runtime/controller.py): resolve the
        # "auto" initial value through the tuning cache (a previous
        # run's converged window for this layout), then hand the bounds
        # to the controller. A concrete int pins lo == hi — the fixed
        # pre-adaptive window through the same code path.
        qi = self.opts.quiesce_interval
        self._qi_auto = qi == "auto"
        if self._qi_auto:
            qi, qi_rec = tuning.resolve_quiesce_interval(
                self.program, self.opts)
            lo = self.opts.quiesce_interval_min
            hi = self.opts.quiesce_interval_max
            self.tuning_record = {**(self.tuning_record or {}),
                                  "quiesce_interval": qi_rec}
        else:
            qi = max(1, int(qi))
            lo = hi = qi
        self._qi_loaded = qi
        self._controller = WindowController(qi, lo, hi)
        import dataclasses as _dc
        self.opts = _dc.replace(self.opts, quiesce_interval=qi)
        self.program.opts = self.opts
        self._step = engine.jit_step(self.program, self.opts, self.mesh)
        self._multi = engine.jit_multi_step(self.program, self.opts,
                                            self.mesh)
        # The PIPELINED window (tick 0 gated on-device by the previous
        # window's aux) — only the executable the run loop actually
        # calls gets compiled (jit is lazy), so drivers that use
        # self._multi directly (bench.py, profiling/) pay nothing here.
        self._multi_g = engine.jit_multi_step_gated(
            self.program, self.opts, self.mesh)
        self._zero_aux = engine.zero_aux()
        # Inject buffers carry the trace side lanes when causal tracing
        # is on (two trailing rows: trace_id, parent_span — PROFILE §10).
        w1 = 1 + self.opts.msg_words + self.opts.trace_lanes
        k = self.opts.inject_slots
        self._empty_inject = (jnp.full((k,), -1, jnp.int32),
                              jnp.zeros((w1, k), jnp.int32))
        if self.opts.tracing:
            from ..tracing import Tracer
            self._tracer = Tracer(
                self.opts.trace_sample, self.opts.trace_seed,
                beh_names=[f"{b.actor_type.__name__}.{b.name}"
                           for b in self.program.behaviour_table])
        else:
            self._tracer = None
        for cohort in self.program.cohorts:
            self._free[cohort.atype.__name__] = list(
                range(cohort.capacity - 1, -1, -1))

    # ---- spawning (≙ pony_create, actor.c:688-734) ----
    def spawn(self, atype: ActorTypeMeta, **fields) -> int:
        return int(self.spawn_many(atype, 1, **{
            k: np.asarray([v]) for k, v in fields.items()})[0])

    def spawn_many(self, atype: ActorTypeMeta, count: int,
                   **fields) -> np.ndarray:
        """Allocate `count` slots of a cohort and set initial state columns.

        Field values may be scalars (broadcast) or [count] arrays. Returns
        the global actor ids. This is the host-side mass-create path the
        benchmarks use (the reference creates actors one pony_create at a
        time; batch creation is the idiomatic TPU equivalent).
        """
        if self.state is None:
            raise RuntimeError("call start() before spawn()")
        cohort = self.program.by_type[atype]
        unknown = set(fields) - set(atype.field_specs)
        if unknown:
            raise TypeError(f"{atype.__name__} has no fields {unknown}")
        self._check_ref_fields(atype, fields)
        if not cohort.host and (self.program.has_device_spawns
                                or self.steps_run):
            # Device-side spawn/destroy/GC may have claimed or freed slots
            # behind the host freelist's back. Sync from device truth at
            # most once per world mutation (the state setter invalidates
            # _freelist_key): a setup loop of spawn calls with no steps in
            # between pays one device fetch, not one per call.
            if self._freelist_key is None:
                self._rebuild_freelists()
        fkey = self._freelist_key
        free = self._free[atype.__name__]
        if len(free) < count:
            raise RuntimeError(
                f"cohort {atype.__name__} capacity exhausted "
                f"({cohort.capacity} declared)")
        slots = np.array([free.pop() for _ in range(count)], np.int32)
        ids = np.asarray(cohort.slot_to_gid(slots), np.int32)
        cols = np.asarray(cohort.slot_to_col(slots), np.int32)
        if cohort.host:
            for i, gid in enumerate(ids):
                st = {}
                for fname in atype.field_specs:
                    default = (-1 if pack.is_ref(atype.field_specs[fname])
                               else 0)
                    v = fields.get(fname, default)
                    v = np.asarray(v)
                    st[fname] = v.reshape(-1)[i % max(v.size, 1)].item() \
                        if v.ndim else v.item()
                self._host_state[int(gid)] = st
        else:
            ts = dict(self.state.type_state[atype.__name__])
            for fname, spec in atype.field_specs.items():
                if fname in fields:
                    val = jnp.asarray(fields[fname]).astype(ts[fname].dtype)
                    val = jnp.broadcast_to(val, (count,) if val.ndim == 0
                                           else val.shape)
                else:
                    # Reused slots must not leak a previous life's state.
                    val = jnp.full((count,),
                                   pack.null_word(spec),
                                   ts[fname].dtype)
                ts[fname] = ts[fname].at[cols].set(val)
            new_ts = dict(self.state.type_state)
            new_ts[atype.__name__] = ts
            self.state = self._replace(type_state=new_ts)
        self.state = self._replace(
            alive=self.state.alive.at[ids].set(True),
            # The caller now holds these refs: GC roots until release().
            pinned=self.state.pinned.at[ids].set(True))
        # Our own pops/sets kept the cached freelists consistent.
        self._freelist_key = fkey
        return ids

    def _rebuild_freelists(self) -> None:
        """Refresh every device cohort's freelist from device truth.

        A slot is free only if it is dead, its queue is drained, AND no
        message addressed to it is parked in either spill tier — the same
        free_ok condition the device spawn path enforces (engine.py step
        1b). Reclaiming a row with a stale spilled message would deliver a
        previous life's message to the newborn."""
        st = self.state
        alive, head, tail, dsp, rsp = (
            np.asarray(x) for x in jax.device_get(
                (st.alive, st.head, st.tail, st.dspill_tgt, st.rspill_tgt)))
        n = self.program.total
        nl = self.program.n_local
        s_cap = self.opts.spill_cap
        spill_hit = np.zeros((n,), bool)
        shard = np.arange(dsp.shape[0]) // s_cap   # dspill targets: local
        ok = dsp >= 0
        spill_hit[shard[ok] * nl + dsp[ok]] = True
        ok = (rsp >= 0) & (rsp < n)                # rspill targets: global
        spill_hit[rsp[ok]] = True
        free_ok = ~alive & (tail - head == 0) & ~spill_hit
        for cohort in self.program.cohorts:
            if cohort.host:
                continue
            # Highest slot first, matching the initial freelist order.
            all_slots = np.arange(cohort.capacity - 1, -1, -1)
            gids = np.asarray(cohort.slot_to_gid(all_slots))
            self._free[cohort.atype.__name__] = [
                int(s) for s, g in zip(all_slots, gids) if free_ok[g]]
        self._freelist_key = "synced"

    # ---- GC pinning (≙ ORCA's external rc: an actor is born with one
    # reference owned by its creator, actor.c:688-734) ----
    def _set_flag_column(self, column: str, ids, value: bool) -> None:
        """Set a per-actor bool flag column host-side. Flag flips never
        affect slot freedom, so the spawn freelist cache survives."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        fkey = self._freelist_key
        col = getattr(self.state, column)
        self.state = self._replace(**{column: col.at[ids].set(value)})
        self._freelist_key = fkey

    def release(self, ids) -> None:
        """Drop the host's reference(s): the actors become collectable as
        soon as they are unreachable and message-quiet (gc.py)."""
        self._set_flag_column("pinned", ids, False)
        self._ever_released = True

    def pin(self, ids) -> None:
        """(Re-)pin actors as host-held GC roots."""
        self._set_flag_column("pinned", ids, True)

    def apply_backpressure(self, ids) -> None:
        """Mark actors UNDER_PRESSURE (≙ pony_apply_backpressure,
        src/libponyrt/actor/actor.c:1137-1162): senders to these actors
        mute on send until release_backpressure(), regardless of mailbox
        occupancy — the hook for pressure the runtime cannot see (a
        stalled socket, a full external queue). stdlib/backpressure.py
        wraps this with the reference package's auth-token surface."""
        self._set_flag_column("pressured", ids, True)
        # Raise the mesh-wide "any pressure" gate bit on every shard so
        # the next tick's (otherwise-skipped) pressured all_gather runs;
        # the per-tick vote keeps it honest from then on (engine.py).
        self.state = self._replace(world_bits=self.state.world_bits | 1)

    def release_backpressure(self, ids) -> None:
        """Clear UNDER_PRESSURE (≙ pony_release_backpressure); muted
        senders release on the next unmute pass once the receiver is
        also under the occupancy threshold."""
        self._set_flag_column("pressured", ids, False)

    def gc(self) -> int:
        """Run one collection: trace reachability from the roots, free
        everything unreached (≙ ORCA + the cycle detector in one pass —
        see gc.py). Returns the number of actors collected."""
        if self.state is None:
            raise RuntimeError("call start() first")
        if self._gc_fn is None:
            from . import gc as gc_mod
            self._gc_fn = gc_mod.jit_gc(self.program, self.opts, self.mesh)
            self._ref_mask = gc_mod.build_ref_arg_mask(
                self.program, self.opts.msg_words)
            self._blob_mask = gc_mod.build_blob_arg_mask(
                self.program, self.opts.msg_words)
        # Host-side roots: refs in host-actor state dicts and in pending
        # inject messages (they will reach the device eventually).
        extra = np.zeros((self.program.total,), bool)
        for aid, stt in self._host_state.items():
            cohort = self.program.cohort_of(aid)
            for fname, spec in cohort.atype.field_specs.items():
                if pack.is_ref(spec):
                    v = int(stt.get(fname, -1))
                    if 0 <= v < self.program.total:
                        extra[v] = True
        import itertools
        n_blob_total = self.program.shards * self.opts.blob_slots
        blob_roots = np.zeros((n_blob_total,), bool)
        for h in self._host_blobs:
            slot = pack.blob_slot(int(h))
            if h >= 0 and 0 <= slot < n_blob_total:
                blob_roots[slot] = True
        for t, w, *_ in itertools.chain(self._inject_q,
                                        self._host_fast_q):
            if 0 <= t < self.program.total:
                extra[t] = True
            gid = int(w[0])
            if 0 <= gid < self._ref_mask.shape[0]:
                for i in np.nonzero(self._ref_mask[gid])[0]:
                    v = int(w[1 + i])
                    if 0 <= v < self.program.total:
                        extra[v] = True
                for i in np.nonzero(self._blob_mask[gid])[0]:
                    v = int(w[1 + i])
                    slot = pack.blob_slot(v)
                    if v >= 0 and 0 <= slot < n_blob_total:
                        blob_roots[slot] = True
        before = self.counter("n_collected")
        self.state, (n, converged, iters, n_swept) = self._gc_fn(
            self.state, jnp.asarray(extra), jnp.asarray(blob_roots))
        self.totals["gc_runs"] += 1
        # GC window stats for the profiler (analysis.window / profile()):
        # passes run, trace iterations, blob slots reclaimed; actors
        # collected ride the device n_collected counter.
        self.totals["gc_iters"] += int(iters)
        self.totals["gc_swept_blobs"] += int(n_swept)
        if not bool(converged):
            self.totals["gc_aborted"] += 1
        if self._flight is not None:
            self._flight.event("gc", collected=int(n), iters=int(iters),
                               swept=int(n_swept),
                               converged=bool(converged))
        # Growth-triggered accounting reset (≙ heap.c's next_gc update
        # after a collection) — here so every collection path, manual
        # included, clears the allocation-pressure signal consistently.
        heap = getattr(self, "_heap", None)
        if heap is not None:
            heap.bytes_since_gc = 0
            self._next_gc = max(self.opts.gc_initial,
                                int(heap.bytes_live * self.opts.gc_factor))
        return self.counter("n_collected") - before

    def _replace(self, **kw) -> RtState:
        import dataclasses as _dc
        return _dc.replace(self.state, **kw)

    def set_fields(self, atype: ActorTypeMeta, ids, **fields):
        """Overwrite state columns for existing actors (host-side poke,
        e.g. wiring refs once ids are known). ids are global actor ids."""
        cohort = self.program.by_type[atype]
        self._check_ref_fields(atype, fields)
        if cohort.host:
            for i, aid in enumerate(np.asarray(ids).reshape(-1)):
                st = self._host_state.setdefault(int(aid), {})
                for fname, v in fields.items():
                    v = np.asarray(v).reshape(-1)
                    st[fname] = v[i % v.size].item()
            return
        cols = jnp.asarray(cohort.gid_to_col(np.asarray(ids)))
        ts = dict(self.state.type_state[atype.__name__])
        for fname, v in fields.items():
            col = ts[fname]
            val = jnp.asarray(v).astype(col.dtype)
            ts[fname] = col.at[cols].set(val)
        new_ts = dict(self.state.type_state)
        new_ts[atype.__name__] = ts
        fkey = self._freelist_key
        self.state = self._replace(type_state=new_ts)
        self._freelist_key = fkey   # column writes don't affect freedom

    # ---- sendability checks (capability-lite; ≙ type/safeto.c +
    # expr/call.c: a send must name a behaviour the receiver's type has,
    # and Ref[T]-typed slots may only hold ids of T's cohort). Device-side
    # wiring is verified at trace time (engine._make_branch /
    # api.Context.send); these are the host-boundary twins. Out-of-range
    # ids stay permissive — they dead-letter on device, as documented. ----
    def _check_send_target(self, target: int, bdef: BehaviourDef) -> None:
        if 0 <= target < self.program.total:
            owner = self.program.cohort_of(int(target)).atype.__name__
            want = bdef.actor_type.__name__
            if owner != want:
                raise TypeError(
                    f"sendability: actor {target} is a {owner}; it cannot "
                    f"receive {want}.{bdef.name}")

    def _check_ids_in_cohort(self, v, want: str, what: str) -> None:
        """Vectorised membership: every in-world id in `v` must fall in
        cohort `want`'s rows. Cohorts are contiguous per-shard local-row
        ranges (shard-major slots), so this is two compares on id % nl —
        array speed even for benchmark-scale wiring."""
        v = np.asarray(v, np.int64).reshape(-1)
        nl = self.program.n_local
        c = self.program.by_type_name(want)
        lid = v % max(nl, 1)
        bad = ((v >= 0) & (v < self.program.total)
               & ((lid < c.local_start) | (lid >= c.local_stop)))
        if bad.any():
            x = int(v[bad][0])
            owner = self.program.cohort_of(x).atype.__name__
            raise TypeError(
                f"sendability: {what} expects Ref[{want}] but id {x} "
                f"is a {owner}")

    def _check_ref_args(self, specs, args, what: str) -> None:
        for spec, v in zip(specs, args):
            want = pack.ref_target(spec)
            if want is not None:
                self._check_ids_in_cohort(v, want, what)

    def _check_ref_fields(self, atype: ActorTypeMeta, fields) -> None:
        for fname, v in fields.items():
            want = pack.ref_target(atype.field_specs.get(fname))
            if want is not None:
                self._check_ids_in_cohort(
                    v, want, f"field {atype.__name__}.{fname}")

    def _check_host_iso_blob(self, h: int) -> None:
        """An iso Blob handle leaving the host must be host-OWNED
        (present in _host_blobs): blob_store() mints ownership, host
        delivery of an iso Blob arg transfers it. Anything else —
        double-send, a stale handle, a forged int — is an aliased move,
        rejected loudly like HostHeap.send_iso and the device trace's
        use-after-move (null/-1 rides freely)."""
        if h >= 0 and h not in self._host_blobs:
            from ..hostmem import CapabilityError
            raise CapabilityError(
                f"capability: aliased move — iso blob handle {h} is not "
                "owned by the host (already sent, freed, or never "
                "obtained via blob_store/host delivery); an iso is "
                "moved-unique — use a BlobVal parameter for shared "
                "payloads")

    # ---- external sends (≙ pony_sendv from outside the runtime) ----
    def _trace_context(self, trace):
        """Resolve a send's causal-trace context to (trace_id,
        parent_span) or (-1, 0) (untraced). `trace` spellings: None =
        the deterministic sampler decides (1-in-trace_sample); an int =
        an explicit caller trace id (the bridge/ingress tier tying a
        socket request to its device spans — always traced, root span
        get-or-created); a (trace_id, span_id) tuple = continue an
        existing span (host-behaviour propagation)."""
        tr = self._tracer
        if tr is None:
            return -1, 0
        step = self.steps_run
        if isinstance(trace, tuple):
            return int(trace[0]), int(trace[1])
        if trace is not None:
            tid = int(trace)
            return tid, tr.root_span(tid, step)
        if tr.sample():
            return tr.begin(step)
        return -1, 0

    def send(self, target: int, behaviour_def: BehaviourDef, *args,
             trace=None):
        if behaviour_def.global_id is None:
            raise RuntimeError(f"{behaviour_def} not part of this program")
        self._check_send_target(int(target), behaviour_def)
        self._check_ref_args(behaviour_def.arg_specs, args,
                             f"{behaviour_def.actor_type.__name__}."
                             f"{behaviour_def.name}")
        tlanes = self.opts.trace_lanes
        words = np.zeros((1 + self.opts.msg_words + tlanes,), np.int32)
        words[0] = behaviour_def.global_id
        words[1:1 + self.opts.msg_words] = _host_pack_args(
            behaviour_def.arg_specs, args, self.opts.msg_words)
        tctx = None
        if tlanes:
            tid, psid = self._trace_context(trace)
            words[-2], words[-1] = tid, psid
            if tid >= 0:
                tctx = (tid, psid)
        # Iso payload discipline at the host boundary (≙ the gc.c send
        # handler moving ownership with the message): mark the handle in
        # flight — peeking it now is use-after-send, re-sending it is an
        # aliased move (hostmem.HostHeap). AFTER packing validated, so a
        # failed send can never poison the handle.
        heap = getattr(self, "_heap", None)
        if heap is not None:
            for spec, a in zip(behaviour_def.arg_specs, args):
                # Blob handles share the iso MODE but live in the device
                # pool, not the HostHeap — their move discipline is the
                # trace/device side (api.BlobPoolView), never send_iso.
                if (pack.cap_mode(spec) == "iso"
                        and not pack.is_blob(spec) and int(a) > 0):
                    heap.send_iso(int(a))
        if self.opts.blob_slots > 0:
            # A sent ISO blob handle is MOVED off the host: it stops
            # being a GC root here (the in-flight message keeps it
            # alive until the receiver owns it — gc.py's marks). A VAL
            # (shared) handle ALIASES: the host keeps its root until
            # rt.blob_release(h), so it can keep sending/fetching it.
            # Moving a handle the host does NOT own (double-send, stale
            # or forged int) is an aliased move — loud, matching
            # HostHeap.send_iso and the device path's use-after-move
            # (every legitimately host-sendable iso blob is in
            # _host_blobs: blob_store() puts it there, and host
            # delivery of an iso Blob arg transfers it there).
            for spec, a in zip(behaviour_def.arg_specs, args):
                if pack.is_blob(spec) and not pack.is_blob_val(spec):
                    self._check_host_iso_blob(int(a))
                    self._host_blobs.discard(int(a))
        # Host senders (the API and host behaviours both run here) to
        # host targets take the fast lane; everything else rides the
        # device inject path. Per-sender-pair FIFO holds: a given
        # sender's messages to a given receiver always take ONE lane.
        if (self.opts.host_fastpath
                and 0 <= int(target) < self.program.total
                and self.program.cohort_of(int(target)).host):
            # Fast-lane messages never touch the device, so the trace
            # context rides the queue entry instead of word lanes.
            self._host_fast_q.append((int(target), words, tctx))
        else:
            self._inject_q.append((int(target), words))

    def bulk_send(self, targets, behaviour_def: BehaviourDef, *arg_cols,
                  trace=None):
        """Mass-enqueue one message per (distinct) target directly into the
        device mailboxes — the setup path for benchmark-scale seeding
        (injecting 1M messages through the per-step inject buffer would
        take thousands of steps). Targets must be unique within one call.

        `trace` (causal tracing on only): an explicit caller trace id —
        every seeded message joins that trace (one root, N branches;
        the ingress tier's batched-request hook). None = untraced (the
        sampler never fires here: sampling one message of a bulk seed
        would attribute the whole batch's cost to it).
        """
        targets = np.asarray(targets, np.int64)
        if len(np.unique(targets)) != len(targets):
            raise ValueError("bulk_send targets must be distinct; use "
                             "send() for repeated targets")
        self._check_ids_in_cohort(
            targets, behaviour_def.actor_type.__name__,
            f"bulk_send target of {behaviour_def.actor_type.__name__}."
            f"{behaviour_def.name}")
        self._check_ref_args(behaviour_def.arg_specs, arg_cols,
                             f"{behaviour_def.actor_type.__name__}."
                             f"{behaviour_def.name}")
        # ISO blob columns MOVE off the host exactly like send() args
        # (the handles stop being GC roots; in-flight mailbox words keep
        # the blobs alive until the receivers own them); VAL columns
        # alias — the host keeps its roots until rt.blob_release. Same
        # ownership check as send(): moving a handle the host does not
        # own raises before any column is consumed.
        if self.opts.blob_slots > 0:
            for spec, col in zip(behaviour_def.arg_specs, arg_cols):
                if pack.is_blob(spec) and not pack.is_blob_val(spec):
                    for a in np.asarray(col).reshape(-1):
                        self._check_host_iso_blob(int(a))
            for spec, col in zip(behaviour_def.arg_specs, arg_cols):
                if pack.is_blob(spec) and not pack.is_blob_val(spec):
                    for a in np.asarray(col).reshape(-1):
                        self._host_blobs.discard(int(a))
        k = len(targets)
        words = np.zeros((k, 1 + self.opts.msg_words), np.int32)
        words[:, 0] = behaviour_def.global_id
        specs = behaviour_def.arg_specs
        if len(arg_cols) != len(specs):
            raise TypeError(
                f"behaviour takes {len(specs)} args, got {len(arg_cols)}")
        off = 1
        for spec, col in zip(specs, arg_cols):
            col = np.asarray(col)
            if isinstance(spec, pack._VecSpec):
                # One [count, n] column block per vector argument; the
                # layout is validated, not reinterpreted — a transposed
                # block would silently interleave components otherwise.
                if col.shape != (k, spec.n):
                    raise TypeError(
                        f"bulk_send column for {spec.__name__} must have "
                        f"shape ({k}, {spec.n}), got {col.shape}")
                dt = np.float32 if spec.base is pack.F32 else np.int32
                blk = np.ascontiguousarray(col.astype(dt))
                words[:, off:off + spec.n] = blk.view(np.int32)
                off += spec.n
            elif spec is pack.F32:
                words[:, off] = col.astype(np.float32).view(np.int32)
                off += 1
            else:
                words[:, off] = col.astype(np.int32)
                off += 1
        tail = self.state.tail
        t_at = np.asarray(tail[targets])
        occ = t_at - np.asarray(self.state.head[targets])
        if (occ >= self.opts.mailbox_cap).any():
            full = targets[occ >= self.opts.mailbox_cap]
            raise RuntimeError(
                f"bulk_send would overflow {len(full)} full mailbox(es) "
                f"(first target {int(full[0])}); drain with run() first or "
                "raise mailbox_cap")
        slot = t_at % self.opts.mailbox_cap
        # Per-cohort mailbox tables (state.py): all targets live in ONE
        # cohort (checked above); write its table at its own width (the
        # packed words beyond it are zeros by construction — this
        # behaviour's args fit the cohort's width). Advanced indices
        # (slot, col) pair up, the word axis rides.
        cname = behaviour_def.actor_type.__name__
        cohort = self.program.by_type_name(cname)
        cols = np.asarray(cohort.gid_to_col(targets))
        w1c = 1 + cohort.msg_words
        new_cbuf = self.state.buf[cname].at[slot, :, cols].set(
            jnp.asarray(words[:, :w1c]))
        extra = {}
        if self._tracer is not None:
            # Stamp (or CLEAR — ring slots are recycled, a stale lane
            # would adopt a previous message's trace) the trace side
            # lanes for every written slot.
            lanes = np.full((k, 2), -1, np.int32)
            lanes[:, 1] = 0
            if trace is not None:
                tid = int(trace)
                lanes[:, 0] = tid
                lanes[:, 1] = self._tracer.root_span(tid, self.steps_run)
            extra["trace_buf"] = {
                **self.state.trace_buf,
                cname: self.state.trace_buf[cname].at[slot, :, cols].set(
                    jnp.asarray(lanes))}
        if cname in self.state.qwait_enq:
            # Profiler enqueue stamp (analysis >= 1): bulk_send bypasses
            # the in-step delivery that normally writes it, so stamp the
            # current tick here — queue-wait deltas for host-seeded
            # messages then measure from the seeding boundary.
            extra["qwait_enq"] = {
                **self.state.qwait_enq,
                cname: self.state.qwait_enq[cname].at[slot, cols].set(
                    jnp.int32(self.steps_run))}
        self.state = self._replace(
            buf={**self.state.buf, cname: new_cbuf},
            tail=tail.at[targets].add(1), **extra)

    def _drain_inject(self):
        tgt, words, _consumed = self._drain_inject_tracked()
        return tgt, words

    def _drain_inject_tracked(self):
        """Like _drain_inject, but also returns the consumed (target,
        words) pairs IN ORDER, so the pipelined run loop can re-queue
        them verbatim when a gated-out window (ticks_run == 0) never
        applied its injections."""
        if not self._inject_q:
            return (*self._empty_inject, [])
        k = self.opts.inject_slots
        w1 = 1 + self.opts.msg_words + self.opts.trace_lanes
        tgt = np.full((k,), -1, np.int32)
        words = np.zeros((w1, k), np.int32)   # planar: word-major
        # Host-side flow control: at most one drain-batch per target per
        # step, so a burst (e.g. timer events queued during a long XLA
        # compile) can never outrun the receiver and trip the bounded
        # device spill. Held-back messages keep their per-target FIFO
        # order in the deque — the host queue is the unbounded tier the
        # reference gets from pool-backed mailboxes (messageq.c).
        taken: Dict[int, int] = {}
        quota: Dict[int, int] = {}
        held: List[Any] = []
        consumed: List[Any] = []
        i = 0
        while i < k and self._inject_q:
            t, w = self._inject_q.popleft()
            q = quota.get(t)
            if q is None:
                # Out-of-world targets have no cohort: any batch quota
                # works — the device path drops them (sends stay
                # permissive out of range; they dead-letter, as
                # _check_send_target documents).
                q = quota[t] = (self.program.cohort_of(t).batch
                                if 0 <= t < self.program.total
                                else self.opts.batch)
            c = taken.get(t, 0)
            if c >= q:
                held.append((t, w))
                continue
            taken[t] = c + 1
            consumed.append((t, w))
            tgt[i] = t
            words[:, i] = w
            i += 1
        self._inject_q.extendleft(reversed(held))
        return jnp.asarray(tgt), jnp.asarray(words), consumed

    # ---- asio bridge hooks (≙ asio/asio.c noisy accounting) ----
    def add_noisy(self):
        self._noisy += 1

    def remove_noisy(self):
        self._noisy = max(0, self._noisy - 1)

    def register_poller(self, poller):
        """poller.poll(rt) is called at every host boundary; it may inject
        messages (timers/sockets/stdin — the bridge package uses this)."""
        self._bridge_pollers.append(poller)

    def attach_bridge(self):
        """Create (once) and register the ASIO bridge for this runtime
        (≙ ponyint_asio_start, asio/asio.c:47-56)."""
        if getattr(self, "bridge", None) is None:
            from ..bridge import Bridge
            self.bridge = Bridge(self)
            self.register_poller(self.bridge)
        return self.bridge

    def attach_net(self):
        """Create (once) the TCP/UDP layer (≙ packages/net over
        lang/socket.c) on top of the bridge."""
        if getattr(self, "net", None) is None:
            from ..net import Net
            self.net = Net(self)
        return self.net

    def attach_resolver(self):
        """Create (once) the async DNS resolver (≙ the addrinfo surface
        of lang/socket.c, delivered as actor messages)."""
        if getattr(self, "resolver", None) is None:
            from ..net.dns import Resolver
            self.resolver = Resolver(self)
        return self.resolver

    def attach_processes(self):
        """Create (once) the child-process monitor (≙ packages/process
        over lang/process.c)."""
        if getattr(self, "procs", None) is None:
            from ..process import Processes
            self.procs = Processes(self)
        return self.procs

    @property
    def heap(self):
        """Host object heap for rich message payloads (hostmem.py)."""
        h = getattr(self, "_heap", None)
        if h is None:
            from ..hostmem import HostHeap
            h = self._heap = HostHeap()
        return h

    def files_auth(self):
        """Root file-system capability (≙ env.root AmbientAuth handed to
        the Main actor; see files.py)."""
        from ..files import FilesAuth
        return FilesAuth(FilesAuth._token)

    def ambient_auth(self) -> "AmbientAuth":
        """The root authority object (≙ env.root: AmbientAuth,
        packages/builtin/ambient_auth.pony). Narrower tokens —
        stdlib.backpressure.ApplyReleaseBackpressureAuth,
        stdlib.signals auth, capsicum rights — derive from it so a
        library can be handed only the power it needs."""
        return AmbientAuth(self, AmbientAuth._token)

    # ---- host-cohort dispatch (≙ main-thread scheduler path; on a mesh,
    # each shard's host-row tail range is gathered and drained here — the
    # multi-chip analog of inject_main, scheduler.c:179-190) ----
    @property
    def _host_rows(self) -> np.ndarray:
        """Global ids of all host-cohort mailbox rows (every shard's tail
        range), cached after start()."""
        rows = getattr(self, "_host_rows_cache", None)
        if rows is None:
            fh, nl = self.program.first_host_row, self.program.n_local
            p = self.program.shards
            rows = np.concatenate(
                [s * nl + np.arange(fh, nl) for s in range(p)]) \
                if fh < nl else np.zeros((0,), np.int64)
            self._host_rows_cache = rows
        return rows

    def _drain_host(self) -> bool:
        rows = self._host_rows
        if rows.size == 0:
            return False
        rows_j = jnp.asarray(rows)
        head = np.asarray(self.state.head[rows_j])
        tail = np.asarray(self.state.tail[rows_j])
        pending = tail - head
        if not pending.any():
            return False
        # Per-cohort mailbox tables: fetch each HOST cohort's table once
        # (at its own width) and read messages via cohort-local columns.
        host_bufs: Dict[str, np.ndarray] = {}
        host_tbufs: Dict[str, np.ndarray] = {}   # trace side lanes
        c = self.opts.mailbox_cap
        new_head = head.copy()
        for i in np.nonzero(pending)[0]:
            aid = int(rows[int(i)])
            cohort = self.program.cohort_of(aid)
            cname = cohort.atype.__name__
            cbuf = host_bufs.get(cname)
            if cbuf is None:
                cbuf = host_bufs[cname] = np.asarray(
                    self.state.buf[cname])       # [cap, w1_c, capacity]
                if self._tracer is not None:
                    host_tbufs[cname] = np.asarray(
                        self.state.trace_buf[cname])  # [cap, 2, cap_c]
            col = int(cohort.gid_to_col(aid))
            consumed = 0
            for k in range(int(pending[i])):
                slot = (head[i] + k) % c
                msg = cbuf[slot, :, col]
                tctx = None
                if self._tracer is not None:
                    tlane = host_tbufs[cname][slot, :, col]
                    if int(tlane[0]) >= 0:
                        tctx = (int(tlane[0]), int(tlane[1]))
                consumed += 1
                ctx = self._dispatch_host_msg(aid, cohort, int(msg[0]),
                                              msg[1:], trace_ctx=tctx)
                if ctx is not None and ctx.yield_flag:
                    break
            new_head[i] = head[i] + consumed
        self.state = self._replace(
            head=self.state.head.at[rows_j].set(jnp.asarray(new_head)))
        return True

    def _dispatch_host_msg(self, aid: int, cohort, gid: int, payload,
                           trace_ctx=None):
        """Dispatch ONE message to a host-resident actor — shared by the
        device-mailbox drain above and the fast lane below so their
        semantics (iso receive, PonyError residue, exit/yield flags,
        counters) cannot drift. Returns the HostContext, or None for a
        badmsg. `trace_ctx` = the message's (trace_id, parent_span)
        when causal tracing followed it here: the dispatch becomes a
        HOST span and the behaviour's sends continue the chain."""
        bdef = (self.program.behaviour_table[gid]
                if 0 <= gid < len(self.program.behaviour_table)
                else None)
        if bdef is None or bdef.actor_type is not cohort.atype:
            self.totals["badmsg"] += 1
            return None
        ctx = HostContext(self, aid)
        if trace_ctx is not None and self._tracer is not None:
            tid, psid = trace_ctx
            sid = self._tracer.host_span(tid, psid, gid, aid,
                                         self.steps_run)
            ctx.trace_ctx = (tid, sid)
        st = self._host_state.get(aid, {})
        args = _host_unpack_args(bdef.arg_specs, payload)
        heap = getattr(self, "_heap", None)
        if heap is not None:
            # Delivery completes the iso move: the receiver may
            # peek/unbox now (≙ the gc.c recv handler).
            for spec, a in zip(bdef.arg_specs, args):
                if pack.cap_mode(spec) == "iso" and int(a) > 0:
                    heap.receive(int(a))
        if self.opts.blob_slots > 0:
            # An iso Blob delivered to a HOST actor completes its move
            # HERE: the host now owns the handle (GC root; legitimately
            # re-sendable — _check_host_iso_blob accepts it).
            for spec, a in zip(bdef.arg_specs, args):
                if (pack.is_blob(spec) and not pack.is_blob_val(spec)
                        and int(a) >= 0):
                    self._host_blobs.add(int(a))
        if self._flight is not None:
            # Recent-host-mail lane of the black box (bounded ring).
            self._flight.mail(aid, f"{cohort.atype.__name__}."
                                   f"{bdef.name}")
        try:
            st2 = bdef.fn(ctx, st, *args)
        except PonyError as e:
            # ≙ a behaviour-local `try...else` (fork int-coded
            # errors): record the code, actor continues.
            self._host_errors[aid] = e.code
            self._host_error_locs[aid] = e.loc
            self.totals["host_errors"] += 1
            self._error_counts[("PonyError", e.code)] += 1
            st2 = st
        self._host_state[aid] = st2 if st2 is not None else st
        self.totals["host_processed"] += 1
        if self.opts.analysis >= 1:
            self._beh_host_runs[int(gid)] += 1
        if ctx.exit_flag:
            self._exit_code = ctx.exit_code
            self._exit_requested = True
        return ctx

    def _drain_host_fast(self, budget: int) -> bool:
        """Dispatch queued fast-lane messages (host→host sends) up to
        `budget`; leftovers keep the run loop busy. A target with no
        host state was never spawned — dead-letter, matching the device
        path's to-dead drop."""
        q = self._host_fast_q
        if not q:
            return False
        n = 0
        yielded = set()      # actors that yield_()ed: stop their batch
        held = []            # their remaining messages, order preserved
        while q and n < budget:
            aid, w, tctx = q.popleft()
            if aid in yielded:
                held.append((aid, w, tctx))
                continue
            n += 1
            if aid not in self._host_state:
                self.totals["deadletter_host"] += 1
                continue
            cohort = self.program.cohort_of(aid)
            ctx = self._dispatch_host_msg(
                aid, cohort, int(w[0]),
                w[1:1 + self.opts.msg_words], trace_ctx=tctx)
            if ctx is not None and ctx.yield_flag:
                # ≙ the device drain honouring yield mid-batch
                # (actor.c:675-679): this actor processes nothing more
                # this boundary; its queue order is preserved.
                yielded.add(aid)
            if self._exit_requested:
                break
        q.extendleft(reversed(held))
        return True

    # ---- the run loop (≙ pony_start → scheduler run → quiescence) ----
    #
    # PIPELINED + ADAPTIVE since PROFILE.md §9: the loop keeps ONE
    # window in flight and dispatches the next one BEHIND it before
    # fetching its aux, so the host boundary (outbox drain, host
    # behaviours, pollers, GC cadence, the analysis writer) overlaps
    # device compute instead of serialising against it. Exactness is
    # the device's job, not the host's: the speculative window's tick 0
    # is gated ON DEVICE by the in-flight window's aux
    # (engine.build_multi_step_gated), so when the one-window-stale aux
    # turns out to demand host attention — host mail, exit, fatal
    # flags, or quiescence — the speculated window is an identity pass
    # (0 ticks, aux passed through, injections re-queued) and the loop
    # falls back to the synchronous confirm dispatch. A "quiet" vote
    # therefore never terminates the run unless no tick ran after it —
    # the CNF/ACK semantics (scheduler.c:303-480) are unchanged and the
    # differential/FIFO oracles hold message-for-message
    # (tests/test_run_loop.py proves it against the forced synchronous
    # loop). Window length adapts via self._controller
    # (runtime/controller.py): grow on full-budget quiet windows,
    # shrink on host-attention cuts and queue-wait p99 pressure.

    def _stamp(self, phase: str) -> None:
        """Advance the watchdog phase stamp (flight.py): one int bump +
        one tuple assignment, readable atomically from any thread. The
        run loop stamps every phase transition (dispatching / in-flight
        / host-work / quiescent / idle), so 'no stamp within the
        deadline' is exactly 'no progress'."""
        self._wd_epoch += 1
        self._wd_stamp = (phase, self._wd_epoch, time.monotonic())

    def _fatal(self, exc):
        """Record a coded runtime error (metrics label + postmortem
        evidence) on its way out; returns `exc` so raise sites stay
        one-liners."""
        self._error_counts[(type(exc).__name__, error_code(exc))] += 1
        if self._flight is not None:
            self._flight.event("error", cls=type(exc).__name__,
                               code=error_code(exc), message=str(exc))
        return exc

    def _stall_from_interrupt(self):
        """A pending KeyboardInterrupt may be the watchdog's doing
        (flight.Watchdog.trip interrupts the main thread after dumping
        the postmortem): convert it to the int-coded stall error, or
        return None for a genuine Ctrl-C."""
        wd = self._watchdog
        if wd is None or wd.tripped is None:
            return None
        t = wd.tripped
        return self._fatal(PonyStallError(
            f"runtime stalled: phase {t['phase']!r} made no progress "
            f"for {t['age_s']}s (deadline {t['deadline_s']}s; "
            f"postmortem: {t.get('postmortem') or '(unwritten)'})",
            phase=t["phase"], postmortem=t.get("postmortem", "")))

    def _defer_signals(self):
        """Block SIGINT/SIGTERM delivery across the donation-critical
        dispatch region: `self._multi_g` consumes (donates) the current
        state buffers, so an interrupt raised between the call and the
        state re-assignment would leave self.state pointing at deleted
        buffers — the classic donated-buffer-reuse crash. Blocked
        signals deliver the instant the mask is restored (a Ctrl-C
        still lands within one dispatch call). Returns the previous
        mask, or None where masking is unavailable."""
        try:
            return signal.pthread_sigmask(
                signal.SIG_BLOCK, {signal.SIGINT, signal.SIGTERM})
        except (AttributeError, ValueError, OSError):
            return None

    def _restore_signals(self, prev) -> None:
        if prev is not None:
            signal.pthread_sigmask(signal.SIG_SETMASK, prev)

    def _dispatch_window(self, budget: int, force: bool, prev_aux,
                         pipelined: bool) -> Dict[str, Any]:
        """Dispatch one gated window and start the non-blocking host
        copy of its control scalars; returns the in-flight record for
        _retire_window. `pipelined` windows ride behind an unretired
        one (gate live, host exposed no device idle); sync-point
        windows are accounted against the host gap — the wall time
        from the previous retire to this dispatch's START (from then on
        the window is the device's; the call itself may run the compute
        inline on XLA:CPU's synchronous path, which must not read as
        host-imposed idle), the quantity bench.py's host_gap_us
        records."""
        now = time.perf_counter()
        if pipelined:
            self._rl_pipelined += 1
            gap_ns = 0      # dispatched while the previous window ran
        else:
            self._rl_synced += 1
            gap_ns = 0 if self._last_retire_t is None else \
                max(0, int((now - self._last_retire_t) * 1e9))
        inj_t, inj_w, consumed = self._drain_inject_tracked()
        self._stamp("dispatching")
        mask = self._defer_signals()
        try:
            st2, aux, kdev = self._multi_g(
                self.state, inj_t, inj_w, jnp.int32(max(1, budget)),
                np.bool_(force), prev_aux)
            self.state = st2
            epoch = self._state_epoch
        finally:
            self._restore_signals(mask)
        # From here the window is the device's: the watchdog deadline
        # now covers device completion, not host dispatch latency.
        self._stamp("in-flight")
        # Start the device→host DMA of the control scalars now; the
        # retire's device_get then waits on data already in motion
        # instead of issuing the request after the window completes.
        for leaf in jax.tree.leaves((aux, kdev)):
            try:
                leaf.copy_to_host_async()
            except AttributeError:
                pass
        return {"aux": aux, "k": kdev, "budget": int(budget),
                "consumed": consumed, "gap_ns": gap_ns, "epoch": epoch,
                "pipelined": pipelined}

    def _retire_window(self, win: Dict[str, Any]):
        """Fetch an in-flight window's (ticks_run, aux) and fold it into
        host accounting. A gated-out window (0 ticks) changed nothing:
        its injections go back to the FRONT of the queue in order, and
        no counters/controller/analysis state moves. Returns (k, aux as
        host scalars)."""
        k, a = jax.device_get((win["k"], win["aux"]))
        self._last_retire_t = time.perf_counter()
        # The fetch returned: the device answered, the host boundary
        # work for this window starts now (watchdog phase evidence).
        self._stamp("host-work")
        k = int(k)
        if k == 0:
            if win["consumed"]:
                self._inject_q.extendleft(reversed(win["consumed"]))
                self._rl_requeued += len(win["consumed"])
            return 0, a
        # The window just observed (and advanced) true device state;
        # its aux is authoritative for the quiescence-skip decision
        # UNLESS a host-side write landed after its dispatch (the
        # epoch moved) — such a write is invisible to this aux.
        if self._state_epoch == win["epoch"]:
            self._device_dirty = False
        self._last_aux = a
        self.steps_run += k
        if self.opts.debug_checks:
            self.check_invariants()
        # aux counters are cumulative int32; accumulate mod-2^32
        # deltas so fetch cadence doesn't matter (< 2^31 events per
        # window).
        for key, cur in (("processed", int(a.n_processed) & 0xFFFFFFFF),
                         ("delivered", int(a.n_delivered) & 0xFFFFFFFF)):
            last = self._last_counters.get(key, 0)
            self.totals[key] += (cur - last) & 0xFFFFFFFF
            self._last_counters[key] = cur
        self._rl_windows += 1
        self._rl_gap_ns += win["gap_ns"]
        self._win_hist[min(WIN_BUCKETS - 1,
                           max(0, k.bit_length() - 1))] += 1
        # Controller: a full-budget exit with no host attention grows
        # the window; a host-attention cut (or queue-wait pressure via
        # the qw_p99 aux lane) shrinks it; early quiescence holds.
        attention = bool(a.host_pending) or bool(a.exit_flag) \
            or bool(a.spill_overflow) or bool(a.spawn_fail) \
            or bool(a.blob_fail) or bool(a.blob_budget_fail)
        self._controller.observe(k, win["budget"], attention,
                                 qw_p99=int(a.qw_p99))
        # Flight recorder (PROFILE.md §11): the black box retains this
        # window's already-fetched control scalars — host ints only,
        # one bounded-deque append; no extra device traffic.
        if self._flight is not None:
            self._flight.window(self.steps_run, k, win["budget"],
                                win["gap_ns"] / 1e3,
                                win.get("pipelined", False), a)
        if getattr(self, "_analysis", None) is not None:
            self._analysis.window(a, ticks=k,
                                  gap_us=win["gap_ns"] / 1e3)
        if self._metrics is not None:
            self._metrics.maybe_update(self)
        return k, a

    def _fatal_checks(self, a) -> None:
        if bool(a.spill_overflow):
            raise self._fatal(SpillOverflowError(
                f"spill overflow at step {self.steps_run}"))
        if bool(a.spawn_fail):
            raise self._fatal(SpawnCapacityError(
                f"device spawn found no free slot by step "
                f"{self.steps_run}"))
        if bool(a.blob_fail):
            raise self._fatal(BlobCapacityError(
                f"device blob_alloc found no free pool slot by step "
                f"{self.steps_run} — the pool is exhausted: raise "
                "RuntimeOptions.blob_slots, or free blobs "
                "(ctx.blob_free) faster"))
        if bool(a.blob_budget_fail):
            raise self._fatal(BlobCapacityError(
                f"device blob_alloc exceeded its per-tick reservation "
                f"budget by step {self.steps_run} — more allocating "
                "dispatches than BLOB_DISPATCHES in one tick (free "
                "pool slots may remain): raise the actor class's "
                "BLOB_DISPATCHES (or lower its batch)"))

    @staticmethod
    def _clean_busy(a) -> bool:
        """Host-side twin of engine.aux_go: the retired aux votes
        "device busy, zero host attention" — the only state worth
        speculating a window behind."""
        return (bool(a.device_pending) and not bool(a.host_pending)
                and not bool(a.exit_flag) and not bool(a.spill_overflow)
                and not bool(a.spawn_fail) and not bool(a.blob_fail)
                and not bool(a.blob_budget_fail))

    def run(self, max_steps: Optional[int] = None) -> int:
        if self.state is None:
            raise RuntimeError("call start() first")
        if self.opts.analysis >= 1 and getattr(self, "_analysis",
                                               None) is None:
            from .. import analysis as _analysis_mod
            _analysis_mod.attach(self)
        # A request_exit() fired BEFORE run() (signal handler, input
        # callback between runs) must be honoured, not discarded — the
        # flag is consumed at the break below, never cleared on entry.
        max_steps = max_steps or self.opts.max_steps
        ctrl = self._controller
        pipelining = bool(self.opts.pipeline)
        idle_polls = 0
        steps_this_run = 0
        skipped_boundaries = 0
        a = None          # newest RETIRED aux; None forces a first window
        win = None        # the one in-flight (unretired) window
        self._last_retire_t = None
        self._last_run_crashed = False
        # SIGQUIT = dump the flight recorder and keep running (the
        # operator's "what is it doing RIGHT NOW" key, ^\ on a tty;
        # SIGTERM/SIGUSR1 stay the analysis dump's, PROFILE.md §8).
        prev_quit = None
        if self._flight is not None and hasattr(signal, "SIGQUIT"):
            def _quit_dump(_signum, _frame):
                self._flight.dump(reason="SIGQUIT")
            try:
                prev_quit = signal.signal(signal.SIGQUIT, _quit_dump)
            except ValueError:      # not the main thread: skip
                prev_quit = None
        try:
            while True:
                if win is None:
                    # A boundary where the device is provably quiescent
                    # and nothing needs injecting is HOST-ONLY: skip the
                    # device dispatch entirely (≙ idle schedulers
                    # staying asleep while the main-thread scheduler
                    # works, scheduler.c:527-746). Sound because with no
                    # injects and no pending device work, a window could
                    # neither dispatch nor deliver anything — device
                    # facts in `a` cannot change. Skipped boundaries
                    # count against max_steps so a runaway host program
                    # stays bounded exactly like a device one.
                    if (a is not None and not bool(a.device_pending)
                            and not bool(a.host_pending)
                            and not self._inject_q
                            and not getattr(self, "_device_dirty", True)):
                        skipped_boundaries += 1
                        self._idle_boundaries += 1
                        # fall through to the host boundary below
                    else:
                        # Sync-point dispatch: the host knows everything
                        # it needs (force=True runs tick 0 whatever the
                        # carried aux says — host-side writes may have
                        # created work the previous aux cannot see).
                        budget = ctrl.window
                        if max_steps is not None:
                            budget = min(budget, max_steps - steps_this_run
                                         - skipped_boundaries)
                        win = self._dispatch_window(
                            max(1, budget), force=True,
                            prev_aux=a if a is not None else self._zero_aux,
                            pipelined=False)
                        continue    # top: pipeline behind it, then retire
                else:
                    # Pipeline refill: dispatch the NEXT window behind
                    # the in-flight one BEFORE fetching its aux — the
                    # device never idles across the boundary. Safe at
                    # any speed: its tick 0 is gated on-device by the
                    # in-flight aux, so it self-cancels if that window
                    # ends needing host attention or quiet.
                    spec = None
                    # A due checkpoint suppresses the next speculation:
                    # the following boundary then has no in-flight
                    # window, which is exactly the quiescent-consistent
                    # point the snapshot needs (delay bounded by ONE
                    # window).
                    ckpt_due = (self._ckpt is not None
                                and self._ckpt.due())
                    if pipelining and not ckpt_due \
                            and a is not None and self._clean_busy(a):
                        budget = ctrl.window
                        if max_steps is not None:
                            budget = min(budget,
                                         max_steps - steps_this_run
                                         - skipped_boundaries
                                         - win["budget"])
                        if budget >= 1:
                            spec = self._dispatch_window(
                                budget, force=False, prev_aux=win["aux"],
                                pipelined=True)
                    k, a = self._retire_window(win)
                    steps_this_run += k
                    win = spec
                # ---- host boundary for `a` (overlaps `win`'s device
                # execution when the pipeline kept one in flight) ----
                self._stamp("host-work")
                self._fatal_checks(a)
                if bool(a.exit_flag):
                    self._exit_code = int(a.exit_code)
                    break
                if bool(a.host_pending):
                    self._drain_host()
                for p in self._bridge_pollers:
                    p.poll(self)
                # Fast lane: host→host messages (including any the drains
                # and pollers just produced) dispatch NOW, without waiting
                # a device window per hop (≙ inject_main staying on the
                # main-thread scheduler).
                self._drain_host_fast(self.opts.host_fastpath_budget)
                # Periodic collection (≙ the cycle detector triggered off
                # the scheduler-0 idle path every --ponycdinterval,
                # scheduler.c:976-989) — only when something can actually
                # be garbage: a host ref was released or actors spawn on
                # device. Host-heap allocation pressure schedules a
                # collection EARLY (≙ the per-actor heap's
                # growth-triggered GC, heap.c next_gc with
                # --ponygcinitial/--ponygcfactor, start.c:204-209).
                heap = getattr(self, "_heap", None)
                heap_pressure = (heap is not None
                                 and heap.bytes_since_gc > self._next_gc)
                # Cadence counts device steps + skipped host-only
                # boundaries (steps_run freezes while boundaries are
                # skipped; host-heavy phases must still collect
                # periodically).
                eff_step = self.steps_run + self._idle_boundaries
                if (not self.opts.noblock
                        and (self._ever_released
                             or self.program.has_device_spawns)
                        and (heap_pressure
                             or (self.opts.cd_interval > 0
                                 and eff_step - self._last_gc_step
                                 >= self.opts.cd_interval))):
                    self._last_gc_step = eff_step
                    self.gc()
                # Periodic crash-safe checkpoint (PROFILE.md §12): the
                # world is quiescent-consistent here whenever no window
                # is in flight (retired state + host queues = exactly
                # what serialise captures); the device→host copy runs
                # now, the file write rides the background writer
                # behind the next window. Never lets a checkpointing
                # failure take down the run it exists to protect.
                if self._ckpt is not None and win is None:
                    try:
                        self._ckpt.tick(self, in_flight=False)
                    except Exception as e:          # noqa: BLE001
                        self.totals["checkpoint_errors"] += 1
                        if self._flight is not None:
                            self._flight.event(
                                "checkpoint_failed",
                                error=f"{type(e).__name__}: {e}")
                if self._exit_requested:
                    self._exit_requested = False    # consume the request
                    break
                # A dirty device (host-side state write since the last
                # window — e.g. bulk_send's direct mailbox writes from a
                # host behaviour) is not provably quiet: stay busy so the
                # next iteration runs a window before quiescence can hold.
                busy = (bool(a.device_pending) or bool(a.host_pending)
                        or bool(self._inject_q) or bool(self._host_fast_q)
                        or getattr(self, "_device_dirty", False))
                if not busy:
                    if win is not None:
                        # A speculated window may still be in flight; `a`
                        # voted quiet, so its gate closed it to an
                        # identity pass — retire (cheap) before deciding
                        # termination from a fully-synced world.
                        k2, a2 = self._retire_window(win)
                        steps_this_run += k2
                        win = None
                        if k2 or self._inject_q:
                            # Device disagreed (ticks ran), or the
                            # gated-out window handed back injections:
                            # not quiet after all.
                            if k2:
                                a = a2
                            continue
                    terminating = (self._noisy == 0
                                   and (not self._bridge_pollers
                                        or idle_polls > 2))
                    if terminating:
                        # Cleanup ticks ON THE TERMINATION PATH ONLY: the
                        # unmute pass lags the drain that satisfies it by
                        # one tick, so a program can quiesce with cosmetic
                        # mute-flag residue. Bounded — pressure a host
                        # never released legitimately holds mutes and must
                        # not livelock termination; a merely-waiting
                        # (noisy) program never pays these ticks. These
                        # are the SYNCHRONOUS CONFIRM dispatches the
                        # pipelined loop falls back to at quiescence.
                        cleanup = 0
                        while (bool(a.any_muted) and cleanup < 3
                               and (max_steps is None
                                    or steps_this_run + skipped_boundaries
                                    < max_steps)):
                            cw = self._dispatch_window(
                                1, force=True, prev_aux=a, pipelined=False)
                            k2, a = self._retire_window(cw)
                            steps_this_run += k2
                            cleanup += 1
                        break  # quiescent: terminate (≙ ACK'd CNF token)
                    idle_polls += 1
                    # Waiting on external events (timers/fds): BLOCK on
                    # the asio queue when a bridge is attached — the
                    # native epoll thread wakes us the instant an event
                    # lands (≙ a suspended scheduler woken by the ASIO
                    # thread, scheduler.c:1427-1476) — else back off
                    # exponentially (≙ the fork's scaling_sleep,
                    # scheduler.c:918-935). The cap only bounds non-asio
                    # pollers' cadence (process reaping, resolver
                    # completions).
                    waiter = next((p for p in self._bridge_pollers
                                   if hasattr(p, "wait")), None)
                    # Waiting on the outside world is a HEALTHY steady
                    # state: the watchdog disarms on this phase (a
                    # quiet timer-driven service is not a stall).
                    self._stamp("quiescent")
                    if waiter is not None:
                        waiter.wait(0.02)
                    else:
                        time.sleep(min(0.002,
                                       2e-5 * (1 << min(idle_polls, 7))))
                else:
                    idle_polls = 0
                if max_steps is not None \
                        and steps_this_run + skipped_boundaries >= max_steps:
                    break
        except KeyboardInterrupt:
            # The interrupt may be the watchdog's (flight.Watchdog
            # trips by signalling the main thread after dumping the
            # postmortem): surface the int-coded stall, not a bare ^C.
            stall = self._stall_from_interrupt()
            if stall is not None:
                raise stall from None
            raise
        finally:
            # Interrupt safety (KeyboardInterrupt/SIGTERM mid-pipeline,
            # and every fatal raise above): an in-flight window's output
            # IS self.state — sync it, fold its aux into the counters,
            # and drain any host-cohort mail it surfaced, so a stopped
            # run loses no host-outbox messages and the runtime stays
            # consistent for a restart (no donated-buffer reuse).
            import sys as _sys
            # A tripped watchdog means the device (or a host phase) is
            # WEDGED: retiring the in-flight window or refreshing the
            # metrics snapshot would block on the very hang we are
            # converting to an error — skip device-touching teardown
            # and let the PonyStallError out (the runtime is not
            # restartable after a stall; the postmortem is the value).
            stalled = (self._watchdog is not None
                       and self._watchdog.tripped is not None)
            if win is not None and not stalled:
                k2, a2 = self._retire_window(win)
                steps_this_run += k2
                if bool(a2.host_pending):
                    self._drain_host()
            if _sys.exc_info()[0] is not None \
                    and not isinstance(_sys.exc_info()[1], PonyStallError):
                # Interrupted between boundaries: host→host messages
                # already queued on the fast lane would otherwise be
                # stranded until the next run() — deliver them now
                # (bounded by the normal per-boundary budget). Normal
                # exits skip this: quiescent termination proves the
                # lane empty, and an exit() break stops the world as
                # the synchronous loop always has. A watchdog STALL
                # also skips it: the wedged behaviour may be ON this
                # lane, and re-dispatching it would hang the unwind.
                self._drain_host_fast(self.opts.host_fastpath_budget)
            if prev_quit is not None:
                try:
                    signal.signal(signal.SIGQUIT, prev_quit)
                except ValueError:
                    pass
            self._stamp("idle")
            # Crash postmortem (PROFILE.md §11): any exceptional exit
            # dumps the black box. Stall trips already dumped (the
            # watchdog thread wrote it before interrupting us).
            exc = _sys.exc_info()[1]
            self._last_run_crashed = (exc is not None
                                      and not isinstance(exc, SystemExit))
            if (exc is not None and self._flight is not None
                    and not isinstance(exc, (SystemExit,
                                             PonyStallError))):
                self._flight.dump(
                    reason=f"crash: {type(exc).__name__}: {exc}",
                    error_code=error_code(exc))
            if self._metrics is not None and not stalled:
                self._metrics.update_now(self)
        # Persist a converged adaptive window for warm starts (PR 1
        # tuning-cache machinery): only a steady controller with real
        # evidence writes, and only when the value actually moved.
        if (self._qi_auto and ctrl.state == "steady"
                and self._rl_windows >= 8
                and ctrl.window != self._qi_loaded):
            from .. import tuning
            tuning.store_quiesce_interval(self.program, self.opts,
                                          ctrl.window)
            self._qi_loaded = ctrl.window
        return self._exit_code

    def run_loop_stats(self) -> Dict[str, Any]:
        """Observable run-loop telemetry (dump(), `top`, bench.py):
        windows retired, pipelined vs sync-point dispatches, the
        cumulative host-imposed device-idle gap, re-queued gated-out
        injections, the window-length histogram (power-of-two buckets)
        and the controller snapshot."""
        n = max(1, self._rl_windows)
        return {
            "windows": self._rl_windows,
            "pipelined_dispatches": self._rl_pipelined,
            "sync_dispatches": self._rl_synced,
            "host_gap_us_total": self._rl_gap_ns / 1e3,
            "host_gap_us_mean": self._rl_gap_ns / 1e3 / n,
            "injects_requeued": self._rl_requeued,
            "window_hist": [int(x) for x in self._win_hist],
            "controller": (self._controller.snapshot()
                           if self._controller is not None else None),
        }

    def checkpoint(self, path: Optional[str] = None) -> Optional[str]:
        """Write one on-demand snapshot: to `path` (synchronous,
        serialise.save) or into the periodic ring (async write;
        requires checkpoint_every_s — returns the queued file's path).
        Call between runs/steps only, like serialise.save."""
        from .. import serialise as _serialise
        if path is not None:
            _serialise.save(self, path)
            return path
        if self._ckpt is None:
            raise RuntimeError(
                "no checkpoint ring configured: pass path=, or set "
                "RuntimeOptions.checkpoint_every_s/checkpoint_path")
        seq = self._ckpt.checkpoint(self, force=True)
        return _serialise.checkpoint_file(self._ckpt.prefix, seq)

    def checkpoint_stats(self) -> Optional[Dict[str, Any]]:
        """Checkpointer telemetry (PROFILE.md §12): capture/write costs
        and the newest restorable snapshot; None when checkpointing is
        off."""
        return self._ckpt.stats() if self._ckpt is not None else None

    def request_exit(self, code: int = 0) -> None:
        """Ask the run loop to stop at the next host boundary (≙
        pony_exitcode + the quiescent stop, start.c:345 — but callable
        from host-side code outside any behaviour, e.g. an input
        handler or signal callback)."""
        self._exit_code = int(code)
        self._exit_requested = True

    def stop(self, postmortem: bool = False) -> int:
        """Tear down auxiliaries (≙ pony_stop, start.c:332-351): emit the
        analysis summary, stop the writer thread, close the bridge, and
        stop the watchdog/metrics threads. ``postmortem=True``
        additionally dumps the flight recorder (the on-demand black-box
        read — path lands in ``rt._flight.last_dump``)."""
        if postmortem and self._flight is not None:
            self._flight.dump(reason="stop(postmortem=True)")
        a = getattr(self, "_analysis", None)
        if a is not None:
            a.summary()
            a.close()
            self._analysis = None
        b = getattr(self, "bridge", None)
        if b is not None:
            b.close()
            self.bridge = None
            self._bridge_pollers = [p for p in self._bridge_pollers
                                    if p is not b]
        wd = self._watchdog
        stalled_wd = wd is not None and wd.tripped is not None
        if self._ckpt is not None:
            if not stalled_wd and not self._last_run_crashed:
                # Final checkpoint on clean teardown — the fast-start
                # restore source. Skipped after a stall (capture would
                # hang on the wedged device) and after ANY crashed
                # run: the ring's newest snapshot must stay the last
                # intact PRE-crash world, or the supervisor would
                # restore straight back into the failure.
                try:
                    self._ckpt.checkpoint(self, force=True)
                except Exception:                  # noqa: BLE001
                    self.totals["checkpoint_errors"] += 1
            self._ckpt.close()
            self._ckpt = None
        if wd is not None:
            wd.close()
            self._watchdog = None
        if self._metrics is not None:
            if wd is None or wd.tripped is None:
                # A stalled device would hang this last snapshot fetch.
                self._metrics.update_now(self)
            self._metrics.close()
            self._metrics = None
        return self._exit_code

    # ---- introspection (≙ ponyint_actor_num_messages, actor.c:666; and
    # the analysis dump hooks, analysis.c) ----
    def queue_depth(self, actor_id: int) -> int:
        return int(self.state.tail[actor_id] - self.state.head[actor_id])

    def last_error(self, actor_id: int) -> int:
        """Latest int-coded error on an actor, 0 = none (≙ the fork's
        __error_code(); device via ctx.error_int, host via PonyError)."""
        if self.program.cohort_of(actor_id).host:
            return self._host_errors.get(int(actor_id), 0)
        return int(self.state.last_error[actor_id])

    def last_error_loc(self, actor_id: int) -> str:
        """Source location of the latest error (≙ the fork's
        __error_loc): the Python file:line of the ctx.error_int call
        site (device) or the PonyError raise site (host); "?" = none."""
        from ..errors import error_site
        if self.program.cohort_of(actor_id).host:
            return self._host_error_locs.get(int(actor_id), "?")
        return error_site(int(self.state.last_error_loc[actor_id]))

    def total_memory(self) -> Dict[str, int]:
        """Process + device memory accounting (≙ the fork's
        @ponyint_total_memory, DIVERGENCE.md: the runtime knows its
        OS-visible memory use). Returns bytes: host RSS, device state
        (the actor world's HBM footprint), and the native pool's live
        block count."""
        try:    # current RSS (Linux); peak via getrusage as fallback
            with open("/proc/self/statm") as f:
                rss_bytes = (int(f.read().split()[1])
                             * (os.sysconf("SC_PAGE_SIZE")))
        except OSError:
            import resource
            rss_bytes = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss * 1024
        dev = 0
        if self.state is not None:
            dev = sum(leaf.nbytes for leaf in jax.tree.leaves(self.state))
        try:
            from .. import native
            pool_live, pool_recycled = native.pool_stats()
        except Exception:                     # noqa: BLE001 — lib unbuilt
            pool_live = pool_recycled = 0
        return {"host_rss_bytes": int(rss_bytes),
                "device_state_bytes": dev,
                "pool_live_blocks": int(pool_live),
                "pool_recycled_blocks": int(pool_recycled)}

    def check_invariants(self) -> None:
        """Debug-build queue/flag invariants (≙ well_formed_msg_chain +
        messageq_size_debug, actor.c:57-92 / messageq.c:15-27 — the
        reference compiles these in for debug builds; call this from
        tests or enable opts.debug_checks to run it at every aux fetch).
        Raises AssertionError with the first violated invariant."""
        st = jax.device_get(self.state)
        occ = st.tail - st.head
        c = self.opts.mailbox_cap
        assert (occ >= 0).all(), "mailbox occupancy negative (head>tail)"
        assert (occ <= c).all(), "mailbox occupancy exceeds capacity"
        alive = np.asarray(st.alive)
        muted = np.asarray(st.muted)
        assert not (muted & ~alive).any(), "dead actor still muted"
        assert (np.asarray(st.mute_refs)[:, ~muted] == -1).all(), \
            "unmuted actor holds a mute ref"
        dead_occ = occ[~alive]
        assert (dead_occ == 0).all(), "dead actor with queued messages"
        for name in ("dspill", "rspill"):
            tgts = np.asarray(getattr(st, name + "_tgt"))
            cnt = int(np.asarray(getattr(st, name + "_count")).sum())
            assert cnt <= tgts.shape[0], f"{name} count exceeds capacity"

    @staticmethod
    def _fetch(arr) -> np.ndarray:
        """Host-read a runtime array. On a multi-PROCESS mesh the shards
        live on other hosts, so fetching is a collective
        (process_allgather) — every rank must read at the same program
        point, which the SPMD host-driver contract already requires
        (tests/_dist_worker.py)."""
        if (hasattr(arr, "is_fully_addressable")
                and not arr.is_fully_addressable):
            from jax.experimental import multihost_utils
            arr = multihost_utils.process_allgather(arr, tiled=True)
        return np.asarray(arr)

    def counter(self, name: str) -> int:
        """Sum a per-shard runtime counter (n_processed, n_delivered,
        n_rejected, n_badmsg, n_deadletter, n_mutes) over the mesh."""
        return int(self._fetch(getattr(self.state, name)).sum())

    def profile(self) -> Dict[str, Any]:
        """Structured per-behaviour/per-cohort telemetry report — the
        host face of the on-device profiler matrix (engine.profile_lanes;
        ≙ reading back the fork's per-actor --ponyanalysis records).
        Requires opts.analysis >= 1 (at level 0 the lanes compile away
        and there is nothing to read). One small device fetch; call it
        at window boundaries, not per tick.

        Returns::

            {"steps": int,
             "behaviours": {"Type.beh": {"runs", "delivered",
                                         "rejected"}},   # cumulative
             "cohorts": {"Type": {"queue_wait_hist": [QW_BUCKETS ints],
                                  "queue_wait_p50": int,   # ticks (2^k
                                  "queue_wait_p99": int,   #  bucket lo)
                                  "mute_ticks": int}},
             "phases": {"delivery": int, "drain": int, "dispatch": int,
                        "gc_mark": int},      # cumulative work units
             "totals": {"processed", "delivered", "rejected", "badmsg",
                        "deadletter", "mutes", "host_processed"},
             "gc": {"passes", "collected", "blob_slots_reclaimed",
                    "trace_iters", "aborted"}}

        Device behaviours' runs sum to counter("n_processed") and
        delivered sums to counter("n_delivered") for well-formed traffic
        (badmsg deliveries are attributable to no behaviour); host
        behaviours report their host-dispatch counts."""
        if self.opts.analysis < 1:
            raise RuntimeError(
                "Runtime.profile() needs RuntimeOptions.analysis >= 1 "
                "(the telemetry lanes compile to constants at level 0)")
        if self.state is None:
            raise RuntimeError("call start() first")
        from ..analysis import hist_percentile
        from .state import N_PHASES, PHASE_NAMES, QW_BUCKETS
        p = self.program.shards
        nb = len(self.program.behaviour_table)
        nd = len(self.program.device_cohorts)
        runs = self._fetch(self.state.beh_runs).reshape(p, nb).sum(0)
        deliv = self._fetch(
            self.state.beh_delivered).reshape(p, nb).sum(0)
        rej = self._fetch(self.state.beh_rejected).reshape(p, nb).sum(0)
        mt = self._fetch(
            self.state.coh_mute_ticks).reshape(p, nd).sum(0)
        hist = self._fetch(self.state.qwait_hist).reshape(
            p, nd, QW_BUCKETS).sum(0)
        behaviours = {}
        for g, bdef in enumerate(self.program.behaviour_table):
            name = f"{bdef.actor_type.__name__}.{bdef.name}"
            behaviours[name] = {
                "runs": int(runs[g]) + self._beh_host_runs.get(g, 0),
                "delivered": int(deliv[g]),
                "rejected": int(rej[g]),
            }
        cohorts = {}
        for di, ch in enumerate(self.program.device_cohorts):
            h = [int(x) for x in hist[di]]
            cohorts[ch.atype.__name__] = {
                "queue_wait_hist": h,
                "queue_wait_p50": hist_percentile(h, 0.50),
                "queue_wait_p99": hist_percentile(h, 0.99),
                "mute_ticks": int(mt[di]),
            }
        ph = self._fetch(self.state.phase_cost).reshape(
            p, N_PHASES).sum(0)
        return {
            "steps": self.steps_run,
            "behaviours": behaviours,
            "cohorts": cohorts,
            "phases": {name: int(ph[i])
                       for i, name in enumerate(PHASE_NAMES)},
            "totals": {
                "processed": self.counter("n_processed"),
                "delivered": self.counter("n_delivered"),
                "rejected": self.counter("n_rejected"),
                "badmsg": self.counter("n_badmsg"),
                "deadletter": self.counter("n_deadletter"),
                "mutes": self.counter("n_mutes"),
                "host_processed": self.totals.get("host_processed", 0),
            },
            "gc": {
                "passes": self.totals.get("gc_runs", 0),
                "collected": self.counter("n_collected"),
                "blob_slots_reclaimed": self.totals.get(
                    "gc_swept_blobs", 0),
                "trace_iters": self.totals.get("gc_iters", 0),
                "aborted": self.totals.get("gc_aborted", 0),
            },
        }

    def measured_costs(self, force: bool = False) -> Dict[str, Any]:
        """Measured, not modelled (costs.capture, ISSUE 19): XLA's own
        ``cost_analysis()`` / ``memory_analysis()`` of this runtime's
        REAL compiled step and pipelined-window executables — flops,
        bytes accessed, argument/output/temp/peak bytes per executable.
        Lazy and memoized (first call AOT-compiles each executable once
        more; the world does not advance); ``opts.cost_capture=True``
        runs it eagerly at start(). Works on CPU and TPU — fields a
        backend doesn't report degrade to None."""
        from .. import costs as _costs
        return _costs.capture(self, force=force)

    def profile_device(self, windows: int = 1, path: str | None = None,
                       ticks: int | None = None) -> str:
        """Wrap N real retired fused windows in a ``jax.profiler``
        trace (xprof / tensorboard / perfetto-compatible, ISSUE 19) for
        op-level device wall attribution — the measurement the modelled
        bytes/msg numbers are judged against on silicon. Drives
        ``windows`` forced fused windows of ``ticks`` ticks each (the
        controller's current window by default) through the runtime's
        own executable — the world genuinely advances and the retired
        steps count in ``steps_run``. The first window runs OUTSIDE the
        trace to absorb compilation. Returns the trace directory
        (default ``<analysis_path or ponyc_xprof>.xprof``)."""
        if self.state is None:
            raise RuntimeError("call start() first")
        import jax
        from jax import profiler as _prof
        if path is None:
            base = self.opts.analysis_path or "ponyc_xprof"
            path = base + ".xprof"
        n = int(ticks if ticks is not None else self._controller.window)
        limit = jnp.int32(max(1, n))
        inj_t, inj_w = self._empty_inject
        # Warm-up window outside the trace: compilation (or cache
        # lookup) must not pollute the device timeline.
        st, _aux, k = self._multi(self.state, inj_t, inj_w, limit)
        self.state = st
        self.steps_run += int(k)
        with _prof.trace(path):
            for _ in range(max(1, int(windows))):
                st, _aux, k = self._multi(self.state, inj_t, inj_w,
                                          limit)
                jax.block_until_ready(st)
                self.state = st
                self.steps_run += int(k)
        return path

    def traces(self) -> Dict[int, Dict[str, Any]]:
        """Reassembled causal traces (PROFILE.md §10): drains the
        device span ring, merges host spans (injection roots, host-
        cohort dispatches) and returns one causal tree per trace id —
        ``{trace_id: {"roots", "spans", "n_spans", "latency",
        "critical_path"}}`` with latency in device ticks (max retire −
        min enqueue over the trace). Requires tracing on
        (``analysis >= 3`` and ``trace_sample > 0``); sample with
        ``RuntimeOptions(trace_sample=N)`` or pass an explicit id via
        ``send(..., trace=...)`` / ``bulk_send(..., trace=...)``."""
        if self._tracer is None:
            raise RuntimeError(
                "Runtime.traces() needs causal tracing on: "
                "RuntimeOptions(analysis=3, trace_sample=N) (the trace "
                "lanes compile away otherwise)")
        from ..tracing import reassemble
        self._tracer.drain(self)
        return reassemble(self._tracer.spans)

    def state_of(self, actor_id: int) -> Dict[str, Any]:
        cohort = self.program.cohort_of(actor_id)
        if cohort.host:
            return dict(self._host_state.get(actor_id, {}))
        col = int(cohort.gid_to_col(actor_id))
        ts = self.state.type_state[cohort.atype.__name__]
        # Addressable arrays: slice on device (one element crosses the
        # wire, not the column); only a multi-process mesh pays the
        # collective whole-array fetch.
        return {k: (np.asarray(v[col]).item()
                    if getattr(v, "is_fully_addressable", True)
                    else self._fetch(v)[col].item())
                for k, v in ts.items()}

    def _blob_slot_of(self, handle: int, what: str) -> int:
        """Decode + validate a handle host-side (range, allocation,
        generation — a stale handle to a recycled slot rejects)."""
        bsl = self.opts.blob_slots
        slot = pack.blob_slot(int(handle))
        if handle < 0 or not (0 <= slot < self.program.shards * bsl):
            raise IndexError(f"{what}: blob handle {handle} out of range")
        if not bool(self._fetch(self.state.blob_used)[slot]):
            raise KeyError(f"{what}: blob handle {handle} is not "
                           "allocated")
        if (int(self._fetch(self.state.blob_gen)[slot])
                & pack.BLOB_GEN_MASK) != pack.blob_gen_of(int(handle)):
            raise KeyError(f"{what}: blob handle {handle} is STALE — "
                           "its slot was recycled (generation mismatch)")
        return slot

    def blob_fetch(self, handle: int) -> np.ndarray:
        """Host-side read of a device blob's logical words (≙ receiving
        a message payload on the main-thread scheduler). Raises on null/
        unallocated/stale handles."""
        slot = self._blob_slot_of(handle, "blob_fetch")
        ln = int(self._fetch(self.state.blob_len)[slot])
        return self._fetch(self.state.blob_data)[:ln, slot]

    def blob_store(self, words, length: Optional[int] = None,
                   near: Optional[int] = None) -> int:
        """Host-side blob allocation between steps (≙ the embedder
        building a message payload, pony.h pony_alloc_msg): claims a
        free pool slot, writes `words` (i32, ≤ blob_words), returns the
        handle — typically then sent as a Blob argument. The HOST owns
        the blob until the send moves it.

        `near`: an actor id whose SHARD should own the slot. Host
        INJECTIONS bypass the routing that migrates device-to-device
        blobs, so allocate on the receiver's shard or the handle
        arrives unreadable (null + n_blob_remote)."""
        if self.opts.blob_slots <= 0:
            raise RuntimeError("blob pool disabled: set "
                               "RuntimeOptions.blob_slots/blob_words")
        w = np.asarray(words, np.int32).reshape(-1)
        if w.shape[0] > self.opts.blob_words:
            raise ValueError(
                f"{w.shape[0]} words > blob_words={self.opts.blob_words}")
        used = self._fetch(self.state.blob_used)
        bsl = self.opts.blob_slots
        if near is not None:
            tgt_shard = int(near) // self.program.n_local
            used = used[tgt_shard * bsl:(tgt_shard + 1) * bsl]
            off = tgt_shard * bsl
        else:
            off = 0
        free = np.flatnonzero(~used)
        if free.size == 0:
            raise BlobCapacityError(
                "host blob_store: pool exhausted"
                + (f" on shard {near // self.program.n_local}"
                   if near is not None else ""))
        slot = off + int(free[0])
        full = np.zeros((self.opts.blob_words,), np.int32)
        full[:w.shape[0]] = w
        ln = w.shape[0] if length is None else int(length)
        if not 0 <= ln <= self.opts.blob_words:
            raise ValueError(
                f"length={ln} outside [0, blob_words="
                f"{self.opts.blob_words}]")
        shard = slot // self.opts.blob_slots
        st = self.state
        gen = (int(self._fetch(st.blob_gen)[slot]) + 1) \
            & pack.BLOB_GEN_MASK
        self.state = self._replace(
            blob_data=st.blob_data.at[:, slot].set(jnp.asarray(full)),
            blob_used=st.blob_used.at[slot].set(True),
            blob_len=st.blob_len.at[slot].set(jnp.int32(ln)),
            blob_gen=st.blob_gen.at[slot].set(jnp.int32(gen)),
            n_blob_alloc=st.n_blob_alloc.at[shard].add(1))
        handle = pack.blob_handle(slot, gen)
        self._host_blobs.add(handle)    # GC root until sent/freed
        return handle

    def blob_free_host(self, handle: int) -> None:
        """Host-side release of a blob the host owns (e.g. fetched and
        finished with). Double frees and stale handles reject (counter
        integrity + ABA guard)."""
        slot = self._blob_slot_of(handle, "blob_free_host")
        shard = slot // self.opts.blob_slots
        st = self.state
        self.state = self._replace(
            blob_used=st.blob_used.at[slot].set(False),
            blob_len=st.blob_len.at[slot].set(0),
            n_blob_free=st.n_blob_free.at[shard].add(1))
        self._host_blobs.discard(int(handle))

    def blob_store_str(self, text: str, near: Optional[int] = None
                       ) -> int:
        """Store a UTF-8 string as a device blob (4 bytes/word): the
        `String val`-style payload path; pair with blob_fetch_str.
        blob_len records WORDS (the pool's logical unit); the byte
        count is recovered by stripping the zero-padding of the final
        word, so U+0000 in the text is rejected here rather than
        silently truncated on the way back."""
        raw = text.encode("utf-8")
        if b"\x00" in raw:
            raise ValueError(
                "blob_store_str: NUL (U+0000) in text is "
                "indistinguishable from word padding; store raw words "
                "with blob_store instead")
        if len(raw) > 4 * self.opts.blob_words:
            raise ValueError(
                f"{len(raw)} bytes > 4*blob_words="
                f"{4 * self.opts.blob_words}")
        padded = raw + b"\x00" * (-len(raw) % 4)
        words = np.frombuffer(padded, np.int32) if padded else \
            np.zeros((0,), np.int32)
        return self.blob_store(words, near=near)

    def blob_fetch_str(self, handle: int) -> str:
        """Read back a blob_store_str payload."""
        words = np.ascontiguousarray(self.blob_fetch(handle), np.int32)
        return words.tobytes().rstrip(b"\x00").decode("utf-8")

    def blob_release(self, handle: int) -> None:
        """Drop the host's GC ROOT on a handle without freeing the
        slot — the val-blob release path (device readers may still hold
        it; the next gc() reclaims it once nobody does). For a handle
        the host exclusively owns, blob_free_host frees immediately."""
        self._host_blobs.discard(int(handle))

    @property
    def blobs_in_use(self) -> int:
        """Currently allocated pool slots (leak diagnostic: orphaned
        blobs — owner died, or handle moved off-shard — persist only
        until the next rt.gc(), whose mark pass sweeps them)."""
        return int(self._fetch(self.state.blob_used).sum())

    def cohort_state(self, atype: ActorTypeMeta) -> Dict[str, np.ndarray]:
        """State columns in *slot order* (spawn order), whatever the shard
        layout."""
        cohort = self.program.by_type[atype]
        cols = np.asarray(
            cohort.slot_to_col(np.arange(cohort.capacity)), np.int64)
        return {k: self._fetch(v)[cols]
                for k, v in self.state.type_state[atype.__name__].items()}

    @property
    def exit_code(self) -> int:
        return self._exit_code
