"""Batched message delivery: the vectorised send path (shard-local).

≙ the reference's pony_sendv → ponyint_maybe_mute → messageq_push →
ponyint_sched_add chain (src/libponyrt/actor/actor.c:773-968,
actor/messageq.c:102-160), executed for *every in-flight message at once*
within one shard of the actor world:

  1. the engine hands over all candidate messages for this tick whose
     target rows live on this shard — receiver-side spill (oldest first),
     host injections, then freshly routed/produced messages;
  2. stable-sort by target row: per-target arrival order is then
     [older spill → inject → new-in-emission-order], which preserves the
     per-sender→receiver FIFO guarantee Pony gives (messageq FIFO + causal
     send order; SURVEY.md §7 hard part (c)) because a sender whose message
     was rejected is muted until its spill drains, so it can never emit a
     *newer* message that would overtake an older spilled one;
  3. per-target segment bounds come from a vectorised binary search over
     the sorted keys; each target accepts min(count, free-space), so
     rejections are always the newest suffix per target, keeping FIFO safe;
  4. the mailbox table is rebuilt slot-plane by slot-plane: ring slot c of
     every actor at once takes sorted entry seg_start + (c - tail) % cap.
     TPU-first design notes: (a) XLA lowers large scatters to serial
     loops on TPU, so the one scatter the CPU-obvious design would use
     was the whole step's bottleneck — the gather form is fully
     vectorised; (b) the mailbox table is laid out [cap, words, N] with
     the actor axis minor-most, so each plane op is a full-width
     128-lane vector op and the per-plane pull from the sorted entries
     is a plain 1-D lane gather (see state.py's layout note — the
     actor-major form ran ~30× slower on real TPU from tile padding);
  5. rejections compact into the next spill buffer and their locally
     resident senders mute (≙ ponyint_maybe_mute: mute on sending to an
     overloaded/muted receiver, actor.c:898-921). Both are *pressure
     paths*: they run under `lax.cond` and cost nothing in the steady
     state where nothing rejects and nobody is overloaded (≙ the
     reference only walking mute maps when senders actually muted,
     scheduler.c:1478-1494).

Megakernel boundary (PR 11, ops/megakernel.py): under
delivery="pallas_mega" this module still formulates every pass above —
the megakernel stages the whole window (this gather-form delivery
included) to a jaxpr and replays it inside one persistent Pallas
kernel, so the in-window while no longer round-trips through XLA
between ticks. The int32 plan/cosort formulations here stay the oracle
the kernel is differentially tested against; the int16+escape record
packing (the bandwidth diet) happens only at the kernel operand
boundary, never in these tables.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from ..ops.segment import compact_mask, stable_sort_by


class Entries(NamedTuple):
    """A flat batch of in-flight messages (targets in *local rows* here;
    the routing layer in engine.py deals in global ids)."""
    tgt: jnp.ndarray      # [E] int32 target row; -1 = empty slot
    sender: jnp.ndarray   # [E] int32 sender *global* id; -1 = host/no sender
    words: jnp.ndarray    # [1+W, E] int32 (word0 = behaviour gid)


class DeliveryResult(NamedTuple):
    buf: dict                  # {type: [cap, 1+W_c, rows_c]} per cohort
    trace_buf: dict            # {type: [cap, 2, rows_c]} causal-trace
    #                               side lanes (tracing on only; {}
    #                               when off) — rebuilt with the SAME
    #                               gather as buf so a delivered
    #                               message and its context can never
    #                               land in different slots
    tail: jnp.ndarray
    spill: Entries             # rejected entries, compacted, oldest first
    spill_count: jnp.ndarray   # [] int32
    spill_overflow: jnp.ndarray
    newly_muted: jnp.ndarray   # [n_local] bool (local senders only)
    new_mute_refs: jnp.ndarray  # [K, n_local] global refs slotted by
    #                               ref % K (-1 = empty)
    new_mute_ovf: jnp.ndarray  # [n_local] bool — distinct refs collided
    #                               in one slot this tick
    n_delivered: jnp.ndarray
    n_rejected: jnp.ndarray
    n_deadletter: jnp.ndarray
    plan_key: jnp.ndarray      # [E] the key vector this plan sorts
    plan_perm: jnp.ndarray     # [E] cached stable-sort permutation
    plan_bounds: jnp.ndarray   # [n_local+1] cached segment bounds


def mute_ref_slots(trig, mute_row, refs, *, n: int, k: int):
    """Scatter triggered (sender-row, receiver-ref) mute pairs into the
    K-slot-per-sender ref table (slot = ref % K). Returns (refs [k, n],
    ovf [n]) where ovf marks senders where two *distinct* refs collided
    in one slot this tick (≙ a mutemap set outgrowing its fixed width)."""
    big = jnp.int32(2**31 - 1)
    slot = jnp.where(trig, refs % k, 0)
    row = jnp.where(trig, mute_row, n)
    rmax = jnp.full((k, n), -1, jnp.int32).at[slot, row].max(
        jnp.where(trig, refs, -1), mode="drop")
    rmin = jnp.full((k, n), big, jnp.int32).at[slot, row].min(
        jnp.where(trig, refs, big), mode="drop")
    ovf = jnp.any((rmax >= 0) & (rmin != rmax), axis=0)
    return rmax, ovf


def empty_mute_slots(n: int, k: int):
    return jnp.full((k, n), -1, jnp.int32), jnp.zeros((n,), jnp.bool_)


def deliver(buf, head, tail, alive, entries: Entries, *, n_local: int,
            mailbox_cap: int, spill_cap: int, overload_occ: int,
            shard_base, cohort_layout, mute_slots: int = 4, level=None,
            n_levels: int = 1, plan=None, pressured=None,
            cosort: bool = False, trace_buf=None) -> DeliveryResult:
    """`buf` is the per-cohort mailbox dict {type: [cap, 1+W_c, rows_c]};
    `cohort_layout` = [(type, s0, s1, w1_c)] tiles the local row space
    [0, n_local) in cohort order — bookkeeping (tails, segments, spill)
    stays global over rows, only the table rebuild is per cohort at its
    own width (≙ per-type pony_msg_t sizes, genfun.c).

    `level` ([E] int32, 0 = most urgent) folds the fork's actor
    *priorities* (actor.h priority hint; scheduler.c:1053-1078 priority
    inject) into the one sort: the composite key (target, level, arrival)
    keeps per-target segments contiguous while ordering contenders by
    priority — when a mailbox can't take everything this tick, higher
    priority wins the slots and lower priority spills. Level 0 is
    reserved for receiver-spill entries (FIFO: older must land first),
    level 1 for host injections.

    `trace_buf` (causal tracing on only): the per-cohort (trace_id,
    parent_span) side-lane tables; `words` then carries TWO extra
    trailing rows (the in-flight context) that both formulations move
    with the payload — the plan path through the cached permutation,
    the cosort path inside the one multi-operand sort — and the
    per-cohort rebuild writes `trace_buf` with the same masks/sources
    as `buf`. Spilled entries keep their trailing context rows (the
    spill tables are trace-width, state.init_state)."""
    n, c = n_local, mailbox_cap
    tgt, sender, words = entries
    e = tgt.shape[0]

    in_range = (tgt >= 0) & (tgt < n)
    tgt_c = jnp.minimum(jnp.maximum(tgt, 0), n - 1)
    # Sends to dead slots drop with a counter (the reference's type system
    # makes this unrepresentable — ORCA keeps receivers alive).
    to_dead = in_range & ~alive[tgt_c]
    valid = in_range & ~to_dead

    if level is None:
        level = jnp.zeros((e,), jnp.int32)
        n_levels = 1
    key = jnp.where(valid, tgt * n_levels + level,
                    n * n_levels).astype(jnp.int32)

    # --- the delivery plan: stable-sort permutation + per-target segment
    # bounds (one vectorised binary search replaces the scatter-add
    # histogram — see module docstring, point 4; queries at target
    # boundaries of the composite key span all priority levels).
    #
    # Topology-stable traffic (every sustained benchmark's steady state:
    # ubench's in-flight cycle, fan-in's hot edges) produces the *same*
    # key vector tick after tick — the same actors firing along the same
    # refs at the same priorities. The plan is therefore cached in the
    # runtime state and revalidated with one cheap vector compare; the
    # O(E log² E) sort re-runs under `lax.cond` only when traffic
    # actually changes shape. ≙ the reference's O(1) pointer-based
    # messageq push (messageq.c:102-160): its "plan" is the receiver
    # pointer each sender holds; ours is the sort amortised across ticks.
    def _bounds(sorted_key):
        """Per-target segment bounds over an already-sorted key vector
        (shared by both delivery formulations so the key/level encoding
        lives once)."""
        return jnp.searchsorted(
            sorted_key, jnp.arange(n + 1, dtype=jnp.int32) * n_levels,
            side="left").astype(jnp.int32)

    def _compute_plan(k):
        p_ = stable_sort_by(k)
        return p_, _bounds(k[p_])

    w1 = words.shape[0]
    if cosort:
        # Alternative formulation (opts.delivery == "cosort"): ONE stable
        # multi-operand sort carries the payload words WITH the key — no
        # cached plan, no permutation gathers afterwards. On hardware
        # where arbitrary lane gathers lower poorly this trades the
        # (plan-cached sort skip + two gathers) for a single native sort
        # per tick. Same FIFO guarantee: lax.sort is_stable preserves
        # arrival order within a (target, level) segment. The sort runs
        # inside the with_msgs cond below (idle ticks stay free); the
        # returned plan fields are placeholders cosort never reads.
        perm = jnp.arange(e, dtype=jnp.int32)
        bounds = jnp.zeros((n + 1,), jnp.int32)
    elif plan is None:
        perm, bounds = _compute_plan(key)
    else:
        plan_key, plan_perm, plan_bounds = plan
        perm, bounds = lax.cond(
            jnp.all(key == plan_key),
            lambda _: (plan_perm, plan_bounds),
            lambda _: _compute_plan(key),
            operand=None)

    def _empty_spill():
        refs, ovf = empty_mute_slots(n, mute_slots)
        return (Entries(tgt=jnp.full((spill_cap,), -1, jnp.int32),
                        sender=jnp.full((spill_cap,), -1, jnp.int32),
                        words=jnp.zeros((w1, spill_cap), jnp.int32)),
                jnp.zeros((n,), jnp.bool_), refs, ovf)

    # Everything below only matters when at least one message exists this
    # tick, so it all sits under one cond: an *idle* world's step touches
    # no mailbox memory at all (≙ the fork's idle-cost fix is the reason
    # it exists, README.md:8-10 — a waiting scheduler must cost ~nothing).
    def with_msgs(_):
        if cosort:
            ops = lax.sort((key, tgt, sender) + tuple(words),
                           num_keys=1, is_stable=True)
            key_s, tgt_s, snd_s = ops[0], ops[1], ops[2]
            wds = jnp.stack(ops[3:])
            seg_bounds = _bounds(key_s)
            kt = jnp.where(key_s < n * n_levels, tgt_s, n).astype(jnp.int32)
        else:
            snd_s = None
            seg_bounds = bounds
            kt = jnp.where(valid, tgt, n).astype(jnp.int32)[perm]
            wds = words[:, perm]                 # [w1, E] sorted
        ktc = jnp.minimum(kt, n - 1)
        seg_start = seg_bounds[:-1]              # [n]
        cnt = seg_bounds[1:] - seg_start         # [n] msgs per target
        occ = tail - head
        space = jnp.maximum(c - occ, 0)
        acc = jnp.minimum(cnt, space)            # accepted per target
        new_tail = tail + acc

        # Slot-plane ring rebuild: plane c (ring slot c of every actor)
        # pulls sorted entry seg_start + (c - tail) % cap. Per COHORT,
        # at the cohort's own word width: each table's gather touches
        # [w1_c, cap*rows_c] — a narrow type's rebuild never moves the
        # widest type's words (the HBM win of per-cohort widths). Within
        # a cohort all planes' indices still concatenate into ONE gather.
        rels = (jnp.arange(c, dtype=jnp.int32)[:, None]
                - tail[None, :]) % c                 # [cap, n]
        wmasks = rels < acc[None, :]
        srcs = jnp.minimum(seg_start[None, :] + rels, e - 1)
        buf2 = {}
        for cname, s0, s1, w1c in cohort_layout:
            nn = s1 - s0
            pulled = jnp.take(wds[:w1c], srcs[:, s0:s1].reshape(c * nn),
                              axis=1).reshape(w1c, c, nn)
            buf2[cname] = jnp.where(wmasks[:, None, s0:s1],
                                    pulled.transpose(1, 0, 2),
                                    buf[cname])
        # Trace side lanes (causal tracing on): the trailing two word
        # rows land in trace_buf through the SAME (mask, source) pair
        # as the payload — context and message are inseparable.
        tbuf2 = {}
        if trace_buf is not None:
            w1f = wds.shape[0]
            for cname, s0, s1, _w1c in cohort_layout:
                nn = s1 - s0
                pulled = jnp.take(wds[w1f - 2:],
                                  srcs[:, s0:s1].reshape(c * nn),
                                  axis=1).reshape(2, c, nn)
                tbuf2[cname] = jnp.where(wmasks[:, None, s0:s1],
                                         pulled.transpose(1, 0, 2),
                                         trace_buf[cname])

        n_delivered = jnp.sum(acc)
        nrej = jnp.sum(cnt - acc)
        occ_after = new_tail - head

        # --- pressure paths, traced under a nested cond so the quiet
        # busy state pays nothing (≙ mute bookkeeping only on overload).
        def pressure(_):
            rank = jnp.arange(e, dtype=jnp.int32) - seg_start[ktc]
            ok = kt < n
            rej = ok & (rank >= acc[ktc])
            perm2, vspill, _ = compact_mask(rej, spill_cap)
            snd = snd_s if cosort else sender[perm]
            spill = Entries(
                tgt=jnp.where(vspill, kt[perm2], -1),
                sender=jnp.where(vspill, snd[perm2], -1),
                words=jnp.where(vspill[None, :], wds[:, perm2], 0),
            )
            # Mute triggers (≙ actor.c:898-921 + mute rules
            # actor.c:1171-1235): a valid send whose receiver rejected it,
            # is now over the overload threshold, or has DECLARED pressure
            # (pony_apply_backpressure, actor.c:1137-1162) mutes the
            # sender — unless the sender is itself overloaded (the
            # reference's !OVERLOADED/UNDER_PRESSURE guard, which prevents
            # mute deadlocks among hot actors). Only senders resident on
            # this shard can be muted here.
            recv_hot = occ_after[ktc] > overload_occ
            if pressured is not None:
                recv_hot = recv_hot | pressured[ktc]
            lsnd = snd - shard_base
            sender_local = (lsnd >= 0) & (lsnd < n)
            sc = jnp.minimum(jnp.maximum(lsnd, 0), n - 1)
            sender_hot = occ_after[sc] > overload_occ
            if pressured is not None:
                # ≙ the UNDER_PRESSURE half of the sender exemption: a
                # sender that itself declared pressure never mutes.
                sender_hot = sender_hot | pressured[sc]
            trig = ok & sender_local & (rej | recv_hot) & ~sender_hot
            mute_row = jnp.where(trig, sc, n)
            newly_muted = jnp.zeros((n,), jnp.bool_).at[mute_row].max(
                trig, mode="drop")
            refs, ovf = mute_ref_slots(trig, mute_row, kt + shard_base,
                                       n=n, k=mute_slots)
            return spill, newly_muted, refs, ovf

        any_pressure = (nrej > 0) | jnp.any(occ_after > overload_occ)
        if pressured is not None:
            # Only when a send actually TARGETS a pressured receiver —
            # an unrelated actor's long-lived pressure (a stalled socket)
            # must not make every tick pay the pressure branch.
            any_pressure = any_pressure | jnp.any(pressured[ktc] & (kt < n))
        spill, newly_muted, new_refs, new_ovf = lax.cond(
            any_pressure, pressure, lambda _: _empty_spill(), operand=None)
        return (buf2, tbuf2, new_tail, spill, newly_muted, new_refs,
                new_ovf, n_delivered, nrej)

    def no_msgs(_):
        spill, newly_muted, new_refs, new_ovf = _empty_spill()
        return (buf, dict(trace_buf) if trace_buf is not None else {},
                tail, spill, newly_muted, new_refs, new_ovf,
                jnp.int32(0), jnp.int32(0))

    (buf_out, tbuf_out, new_tail, spill, newly_muted, new_refs, new_ovf,
     n_delivered, nrej) = lax.cond(jnp.any(valid), with_msgs, no_msgs,
                                   operand=None)

    n_deadletter = jnp.sum(to_dead.astype(jnp.int32))
    return DeliveryResult(
        buf=buf_out, trace_buf=tbuf_out, tail=new_tail,
        spill=spill, spill_count=jnp.minimum(nrej, spill_cap),
        spill_overflow=nrej > spill_cap,
        newly_muted=newly_muted, new_mute_refs=new_refs,
        new_mute_ovf=new_ovf,
        n_delivered=n_delivered,
        n_rejected=nrej,
        n_deadletter=n_deadletter,
        plan_key=key, plan_perm=perm, plan_bounds=bounds,
    )
