"""Batched message delivery: the vectorised send path (shard-local).

≙ the reference's pony_sendv → ponyint_maybe_mute → messageq_push →
ponyint_sched_add chain (src/libponyrt/actor/actor.c:773-968,
actor/messageq.c:102-160), executed for *every in-flight message at once*
within one shard of the actor world:

  1. the engine hands over all candidate messages for this tick whose
     target rows live on this shard — receiver-side spill (oldest first),
     host injections, then freshly routed/produced messages;
  2. stable-sort by target row: per-target arrival order is then
     [older spill → inject → new-in-emission-order], which preserves the
     per-sender→receiver FIFO guarantee Pony gives (messageq FIFO + causal
     send order; SURVEY.md §7 hard part (c)) because a sender whose message
     was rejected is muted until its spill drains, so it can never emit a
     *newer* message that would overtake an older spilled one;
  3. rank each message within its target segment; accept while
     rank < free-space (rejections are therefore always the newest suffix
     per target, keeping FIFO safe);
  4. one scatter writes all accepted payloads into the mailbox table;
  5. rejections are stably compacted into the next spill buffer, and their
     *locally resident* senders muted (≙ ponyint_maybe_mute: mute on
     sending to an overloaded/muted receiver, actor.c:898-921 — here
     "receiver rejected or is over the occupancy threshold", the
     static-shape analog of the reference's batch-exhaustion OVERLOADED
     flag, actor.c:369-381). Remote senders are not muted by receiver-side
     rejection yet; their messages still park in this shard's spill, so no
     ordering guarantee is lost — only the throttling hint is weaker.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..ops.segment import (compact_mask, counts_by_key, segment_ranks,
                           stable_sort_by)


class Entries(NamedTuple):
    """A flat batch of in-flight messages (targets in *local rows* here;
    the routing layer in engine.py deals in global ids)."""
    tgt: jnp.ndarray      # [E] int32 target row; -1 = empty slot
    sender: jnp.ndarray   # [E] int32 sender *global* id; -1 = host/no sender
    words: jnp.ndarray    # [E, 1+W] int32 (word0 = behaviour gid)


class DeliveryResult(NamedTuple):
    buf: jnp.ndarray
    tail: jnp.ndarray
    spill: Entries             # rejected entries, compacted, oldest first
    spill_count: jnp.ndarray   # [] int32
    spill_overflow: jnp.ndarray
    newly_muted: jnp.ndarray   # [n_local] bool (local senders only)
    new_mute_ref: jnp.ndarray  # [n_local] int32 global ref (-1 none)
    n_delivered: jnp.ndarray
    n_rejected: jnp.ndarray
    n_deadletter: jnp.ndarray


def deliver(buf, head, tail, alive, entries: Entries, *, n_local: int,
            mailbox_cap: int, spill_cap: int, overload_occ: int,
            shard_base) -> DeliveryResult:
    n, c = n_local, mailbox_cap
    tgt, sender, words = entries

    in_range = (tgt >= 0) & (tgt < n)
    tgt_c = jnp.minimum(jnp.maximum(tgt, 0), n - 1)
    # Sends to dead slots drop with a counter (the reference's type system
    # makes this unrepresentable — ORCA keeps receivers alive).
    to_dead = in_range & ~alive[tgt_c]
    valid = in_range & ~to_dead

    key = jnp.where(valid, tgt, n).astype(jnp.int32)
    perm = stable_sort_by(key)
    kt = key[perm]
    snd = sender[perm]
    wds = words[perm]
    ok = kt < n

    rank = segment_ranks(kt)
    ktc = jnp.minimum(kt, n - 1)
    occ = tail - head
    space = c - occ[ktc]
    accept = ok & (rank < space)

    slot = (tail[ktc] + rank) % c
    scatter_row = jnp.where(accept, kt, n)          # row n → dropped
    buf = buf.at[scatter_row, slot].set(wds, mode="drop")
    acc_counts = counts_by_key(ktc, accept.astype(jnp.int32), n)
    new_tail = tail + acc_counts
    occ_after = new_tail - head

    # Rejections → next spill, stable order (per-target order preserved).
    rej = ok & ~accept
    perm2, vspill, nrej = compact_mask(rej, spill_cap)
    spill = Entries(
        tgt=jnp.where(vspill, kt[perm2], -1),
        sender=jnp.where(vspill, snd[perm2], -1),
        words=jnp.where(vspill[:, None], wds[perm2], 0),
    )
    spill_overflow = nrej > spill_cap

    # Mute triggers (≙ actor.c:898-921 + mute rules actor.c:1171-1235):
    # a valid send whose receiver rejected it or is now over the overload
    # threshold mutes the sender — unless the sender is itself overloaded
    # (the reference's !OVERLOADED/UNDER_PRESSURE guard, which prevents
    # mute deadlocks among hot actors). Only senders resident on this
    # shard can be muted here.
    recv_hot = occ_after[ktc] > overload_occ
    lsnd = snd - shard_base
    sender_local = (lsnd >= 0) & (lsnd < n)
    sc = jnp.minimum(jnp.maximum(lsnd, 0), n - 1)
    sender_hot = (new_tail[sc] - head[sc]) > overload_occ
    trig = ok & sender_local & (rej | recv_hot) & ~sender_hot
    mute_row = jnp.where(trig, sc, n)
    newly_muted = jnp.zeros((n,), jnp.bool_).at[mute_row].max(
        trig, mode="drop")
    new_mute_ref = jnp.full((n,), -1, jnp.int32).at[mute_row].max(
        jnp.where(trig, kt + shard_base, -1), mode="drop")

    return DeliveryResult(
        buf=buf, tail=new_tail,
        spill=spill, spill_count=jnp.minimum(nrej, spill_cap),
        spill_overflow=spill_overflow,
        newly_muted=newly_muted, new_mute_ref=new_mute_ref,
        n_delivered=jnp.sum(accept.astype(jnp.int32)),
        n_rejected=nrej,
        n_deadletter=jnp.sum(to_dead.astype(jnp.int32)),
    )
