"""ponyc_tpu — a TPU-native actor framework.

A from-scratch re-design of the Pony actor runtime's capabilities
(reference: KittyMac/ponyc, src/libponyrt — work-stealing scheduler,
per-actor MPSC mailboxes, ORCA GC, backpressure, async I/O) for TPU
hardware: actor state and mailboxes are struct-of-arrays in HBM, behaviour
dispatch is a vmapped `lax.switch` kernel draining batched messages in
lockstep across actor cohorts, message routing is one sort+scatter per
tick (ICI collectives across chips), and I/O + bookkeeping stay host-side.

See SURVEY.md at the repo root for the full mapping to the reference.
"""

from .api import (Actor, Blob, BlobVal, Bool, Box, Context, F32, I8, I16, I32,
                  Iso, Mut, Ref, Tag, Trn, TypeParam, U8, U16, U32, Val,
                  VecF32, VecI32, actor, be, behaviour)
from .config import RuntimeOptions, options_from_env, strip_runtime_flags
from .program import Program
from .runtime.runtime import (BlobCapacityError, Runtime,
                              SpawnCapacityError, SpillOverflowError)

__version__ = "0.1.0"

__all__ = [
    "Actor", "Blob", "BlobVal", "Bool", "Box", "Context", "F32", "I8", "I16", "I32", "Iso",
    "Mut", "Ref", "Tag", "Trn", "TypeParam", "U8", "U16", "U32", "Val",
    "VecF32", "VecI32", "actor", "be",
    "behaviour", "RuntimeOptions", "options_from_env",
    "strip_runtime_flags", "Program", "Runtime", "SpillOverflowError",
    "SpawnCapacityError", "BlobCapacityError",
]
