"""Platform forcing for CPU smoke/test runs.

The environment's axon TPU plugin re-asserts itself over the
``JAX_PLATFORMS`` env var at import time; the only reliable way to get
the CPU backend is the config knob *after* importing jax. The
``xla_force_host_platform_device_count`` flag must land before the CPU
client is created (first ``jax.devices()`` / trace), which calling this
helper early guarantees.

One definition, four callers: tests/conftest.py (8-device virtual mesh),
__graft_entry__.dryrun_multichip (driver validation), bench.py (smoke
runs / TPU-init fallback), and every example via ``auto_backend`` —
first contact must never hang on a wedged accelerator plugin (the
reference binary runs wherever it was compiled for; a TPU program's
equivalent courtesy is falling back to CPU loudly).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys


def force_cpu(n_devices: int | None = None) -> None:
    """Force the CPU backend, optionally with n virtual devices."""
    if n_devices is not None:
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        # Replace any pre-existing value (a stale =1 from the environment
        # would silently win and shrink every virtual mesh).
        flags, n_subs = re.subn(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
        if not n_subs:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")


_PROBE_SRC = "import jax; d = jax.devices(); print('PLAT:' + d[0].platform)"


def probe_accelerator(timeout_s: float = 30.0):
    """Initialise JAX in a THROWAWAY subprocess and report the default
    platform, or None if init fails/hangs/resolves to CPU.

    A hung backend init (observed: the axon TPU plugin blocking
    ``jax.devices()`` for 25+ minutes when the tunnel is wedged) must
    only ever cost the subprocess — probing in-process would wedge this
    process's backend lock forever. Returns (platform_or_None, error).
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, (f"jax.devices() did not return within {timeout_s:.0f}s "
                      "(backend init hang)")
    except OSError as e:                      # no child processes allowed
        return None, f"probe subprocess failed to launch: {e}"
    plat = None
    for line in (r.stdout or "").splitlines():
        if line.startswith("PLAT:"):
            plat = line[5:].strip()
    if r.returncode == 0 and plat and plat != "cpu":
        return plat, None
    if r.returncode == 0:
        return None, f"backend initialised as {plat!r}, not an accelerator"
    return None, ((r.stderr or r.stdout or "").strip()[-1000:]
                  or f"probe exited rc={r.returncode}")


def auto_backend(probe_timeout_s: float = 20.0, *, quiet: bool = False):
    """First-contact backend selection for examples and small programs.

    Probes the accelerator with a bounded subprocess; on failure or
    hang, forces the CPU backend so the program runs NOW instead of
    blocking inside a wedged plugin init. Override with
    ``PONY_TPU_PLATFORM=tpu`` (no fallback — init errors surface
    in-process) or ``PONY_TPU_PLATFORM=cpu`` (skip the probe).
    Returns the chosen platform name.
    """
    want = os.environ.get("PONY_TPU_PLATFORM", "auto").lower()
    if want == "cpu" or os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # Already pinned to CPU — don't pay a probe subprocess (or warn)
        # on the common dev/test path.
        force_cpu()
        return "cpu"
    if want in ("tpu", "accel"):
        return "tpu"          # trust the env: no forcing, fail loudly
    plat, err = probe_accelerator(probe_timeout_s)
    if plat is None:
        if not quiet:
            print(f"ponyc_tpu: accelerator unavailable ({err}); "
                  "running on CPU. Set PONY_TPU_PLATFORM=tpu to wait "
                  "for the accelerator instead.", file=sys.stderr)
        force_cpu()
        return "cpu"
    return plat
