"""Platform forcing for CPU smoke/test runs.

The environment's axon TPU plugin re-asserts itself over the
``JAX_PLATFORMS`` env var at import time; the only reliable way to get
the CPU backend is the config knob *after* importing jax. The
``xla_force_host_platform_device_count`` flag must land before the CPU
client is created (first ``jax.devices()`` / trace), which calling this
helper early guarantees.

One definition, three callers: tests/conftest.py (8-device virtual mesh),
__graft_entry__.dryrun_multichip (driver validation), bench.py (smoke
runs / TPU-init fallback).
"""

from __future__ import annotations

import os
import re


def force_cpu(n_devices: int | None = None) -> None:
    """Force the CPU backend, optionally with n virtual devices."""
    if n_devices is not None:
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        # Replace any pre-existing value (a stale =1 from the environment
        # would silently win and shrink every virtual mesh).
        flags, n_subs = re.subn(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
        if not n_subs:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
