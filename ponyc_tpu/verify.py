"""Verify pass: per-behaviour effect signatures, discovered by probe
tracing.

≙ the reference's verify stage (src/libponyc/verify/fun.c: after type
checking, every function's partial-call/error behaviour is analysed and
mismatches rejected). Errors here are VALUES (ctx.error_int — the
fork's pony_error_int), so there is no caller-must-handle obligation to
enforce; what the pass delivers instead is the same ANALYSIS made
queryable: which behaviours can error/destroy/exit/yield, how many
sends they perform against the type's budget, and what they spawn —
surfaced programmatically (`verify_program`), in generated docs
(docgen marks behaviours like Pony marks partial functions with `?`),
and as hard failures for budget violations at verify time instead of
first dispatch.

Probe tracing uses jax.eval_shape (abstract values, no compilation), so
verifying a program costs milliseconds.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .api import ActorTypeMeta, BehaviourDef, Context
from .errors import ERROR_CODES
from .ops import pack


def when_const(when) -> Optional[bool]:
    """Classify a ``when=`` mask at trace time: True/False if it is a
    compile-time constant (the send/spawn provably always/never
    happens), None if data-dependent (a traced value). The lint rules
    key on this — only *unconditional* edges prove amplification or
    pool exhaustion, and a constant-False send is a guaranteed
    dead letter."""
    if isinstance(when, bool):
        return when
    if isinstance(when, jax.core.Tracer):
        return None
    try:
        return bool(when)
    except Exception:                       # noqa: BLE001 — traced/array
        return None


@dataclasses.dataclass(frozen=True)
class SendFact:
    """One send/spawn site observed by the probe — the unit fact the
    whole-program lint pass (ponyc_tpu.lint) assembles into the
    message-flow graph. `dst_*` name the TARGET behaviour; the owning
    (source) behaviour is implied by which probe recorded the fact."""

    kind: str                         # "send" | "spawn" | "spawn_sync"
    dst_type: str                     # target behaviour's actor type
    dst_behaviour: str                # target behaviour name
    when: Optional[bool]              # when_const() of the mask
    target_ref: Optional[str]         # typed provenance of the target
    arg_caps: Tuple[Optional[str], ...]   # declared param cap modes
    arg_src_caps: Tuple[Optional[str], ...]  # provenance of the values


@dataclasses.dataclass(frozen=True)
class Effects:
    """What one behaviour DOES, beyond its state update."""

    sends: int                    # ctx.send call sites
    max_sends: int                # the type's declared budget
    can_error: bool               # ctx.error_int reachable
    can_destroy: bool             # ctx.destroy reachable
    can_exit: bool                # ctx.exit reachable
    can_yield: bool               # ctx.yield_ reachable
    spawns: Tuple[Tuple[str, int], ...]   # (target type, claim sites)
    sync_spawns: Tuple[str, ...]  # targets constructed synchronously
    blob_allocs: int = 0          # ctx.blob_alloc call sites (≤ MAX_BLOBS)

    def marks(self) -> str:
        """Compact docgen suffix (≙ Pony's `?` partial mark)."""
        out = []
        if self.sends:
            # Observed count against the type's budget — `3/4`, not the
            # old `sends≤3`, which mislabelled the observed count as the
            # budget.
            out.append(f"sends {self.sends}/{self.max_sends}")
        for t, n in self.spawns:
            out.append(f"spawns {t}×{n}")
        if self.sync_spawns:
            out.append("sync-constructs "
                       + ",".join(sorted(set(self.sync_spawns))))
        if self.blob_allocs:
            out.append(f"allocs blobs×{self.blob_allocs}")
        if self.can_error:
            out.append("may error")      # ≙ the `?` mark
        if self.can_destroy:
            out.append("may destroy")
        if self.can_exit:
            out.append("may exit")
        if self.can_yield:
            out.append("may yield")
        return ", ".join(out)


class VerifyError(TypeError):
    """A behaviour violates its type's declared budgets (≙ the verify
    pass rejecting a method body, verify/fun.c)."""

    code = ERROR_CODES["VerifyError"]


def behaviour_location(bdef: BehaviourDef
                       ) -> Tuple[Optional[str], Optional[int]]:
    """(source file, first line) of a behaviour's definition, where
    derivable — captured at decoration time (api.BehaviourDef) from
    the function's __code__, so lint findings and verify failures can
    point at real source. (None, None) for functions without source
    (exec'd strings, builtins)."""
    file = getattr(bdef, "source_file", None)
    line = getattr(bdef, "source_line", None)
    if file is None:
        code = getattr(bdef.fn, "__code__", None)
        file = getattr(code, "co_filename", None)
        line = getattr(code, "co_firstlineno", None)
    if file is not None and not os.path.exists(file):
        return None, None
    return file, line


class _ProbeContext(Context):
    """A Context usable BEFORE any Program exists: send() records the
    call (plus the rich per-send facts lint consumes) without requiring
    registered behaviour ids or packing against a concrete msg_words
    (the verify pass runs on bare actor classes, like the reference
    verifying a method body before reachability).

    The probe runs the SAME trace-time sendability/capability checks as
    the real Context (api.Context._send_checks) — the whole-program
    lint pass (ponyc_tpu.lint R3) lifts those trace failures into
    findings instead of first-dispatch crashes."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.send_facts: List[SendFact] = []
        self.blob_alloc_whens: List[Optional[bool]] = []
        self.blob_free_sites = 0
        self.blob_freeze_sites = 0
        self._in_spawn = False            # inside ctx.spawn()
        self._spawn_when: Optional[bool] = None   # its user mask

    def _record(self, kind, behaviour_def, target, args, when):
        self.send_facts.append(SendFact(
            kind=kind,
            dst_type=behaviour_def.actor_type.__name__,
            dst_behaviour=behaviour_def.name,
            when=when,
            target_ref=self.ref_types.lookup(target),
            arg_caps=tuple(pack.cap_mode(s)
                           for s in behaviour_def.arg_specs),
            arg_src_caps=tuple(self.cap_types.lookup(a) for a in args),
        ))

    def send(self, target, behaviour_def, *args, when=True):
        if not isinstance(behaviour_def, BehaviourDef):
            raise TypeError(
                "second argument to send() must be a behaviour "
                "(e.g. SomeActor.some_behaviour)")
        self._send_checks(target, behaviour_def, args)
        if self._in_spawn:
            # The ctor message ctx.spawn() emits: conditionality is the
            # USER's mask (the slot-claim `ok` it pipes through here is
            # always traced — it folds in the reservation's validity).
            self._record("spawn", behaviour_def, target, args,
                         self._spawn_when)
        else:
            self._record("send", behaviour_def, target, args,
                         when_const(when))
        self.sends.append((target, None, when))

    def spawn(self, ctor, *args, when=True):
        self._in_spawn, self._spawn_when = True, when_const(when)
        try:
            return super().spawn(ctor, *args, when=when)
        finally:
            self._in_spawn, self._spawn_when = False, None

    def spawn_sync(self, ctor, *args, when=True):
        """Claim-only: the ctor does not RUN during effect probing (it
        must be pure construction anyway — the real path enforces
        that), so string-form SPAWNS targets need no field specs. The
        constructor ARGUMENTS still face the full sendability +
        capability discipline (api.Context._ctor_arg_checks)."""
        tname, ref, ok = self._claim_slot(ctor, when, "spawn_sync")
        self._ctor_arg_checks(ctor, args, tname)
        self._record("spawn_sync", ctor, None, args, when_const(when))
        self.sync_inits.setdefault(tname, {})
        return self.ref_types.tag(ref, tname)

    # Blob-op site facts (R5 pool-feasibility inputs): count sites and
    # keep each alloc's when-mask constness; then defer to the real ops.
    def blob_alloc(self, length=None, when=True):
        self.blob_alloc_whens.append(when_const(when))
        return super().blob_alloc(length=length, when=when)

    def blob_free(self, h, when=True):
        self.blob_free_sites += 1
        return super().blob_free(h, when=when)

    def blob_freeze(self, h):
        self.blob_freeze_sites += 1
        return super().blob_freeze(h)


def probe_behaviour(bdef: BehaviourDef,
                    atype: Optional[ActorTypeMeta] = None,
                    msg_words: int = 8) -> _ProbeContext:
    """Probe-trace one DEVICE behaviour on abstract 1-lane values and
    return the probe context carrying everything it observed: the
    effect counters behind Effects plus the per-send SendFacts the lint
    pass consumes. Raises (TypeError/RuntimeError) exactly where the
    engine's real trace would — sendability, capability, and budget
    shape violations."""
    atype = atype or bdef.actor_type
    field_specs = atype.field_specs
    spawn_budget = {
        (t if isinstance(t, str) else t.__name__): n
        for t, n in getattr(atype, "SPAWNS", {}).items()}
    box: Dict[str, Context] = {}

    def probe(st, args):
        resv = {t: jnp.full((max(1, n),), -1, jnp.int32)
                for t, n in spawn_budget.items()}
        # A tiny stand-in blob pool so blob-using behaviours probe
        # (handles resolve to -1/no-op; budgets enforce exactly like
        # the engine's MAX_BLOBS window).
        from .api import BlobPoolView
        mb = int(getattr(atype, "MAX_BLOBS", 0) or 0)
        bv = BlobPoolView(
            jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.bool_),
            jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
            jnp.int32(0), jnp.bool_(True),
            jnp.full((mb,), -1, jnp.int32) if mb else None)
        ctx = _ProbeContext(jnp.int32(0), msg_words, spawn_resv=resv,
                            spawn_meta={t: {} for t in spawn_budget},
                            blob=bv)
        for k, v in st.items():
            ctx.ref_types.tag(v, pack.ref_target(field_specs[k]))
            ctx.cap_types.tag(v, pack.cap_mode(field_specs[k]))
        for spec, a in zip(bdef.arg_specs, args):
            ctx.ref_types.tag(a, pack.ref_target(spec))
            ctx.cap_types.tag(a, pack.cap_mode(spec))
        box["ctx"] = ctx
        st2 = bdef.fn(ctx, dict(st), *args)
        return st2

    st = {k: jnp.zeros((), jnp.float32 if s is pack.F32 else jnp.int32)
          for k, s in field_specs.items()}
    args = []
    for spec in bdef.arg_specs:
        if isinstance(spec, pack._VecSpec):
            dt = jnp.float32 if spec.base is pack.F32 else jnp.int32
            args.append(jnp.zeros((spec.n,), dt))
        elif spec is pack.F32:
            args.append(jnp.zeros((), jnp.float32))
        elif spec is pack.Bool:
            args.append(jnp.zeros((), jnp.bool_))
        elif spec in pack._NARROW_JNP:
            args.append(jnp.zeros((), pack._NARROW_JNP[spec]))
        else:
            args.append(jnp.zeros((), jnp.int32))
    jax.eval_shape(probe, st, tuple(args))
    return box["ctx"]


def behaviour_effects(bdef: BehaviourDef,
                      atype: Optional[ActorTypeMeta] = None,
                      msg_words: int = 8,
                      default_max_sends: int = 2) -> Effects:
    """Probe-trace one behaviour and collect its effect signature.
    Host behaviours (HOST=True types) run real Python — they are not
    traced and report zero device effects.

    `default_max_sends` is the RuntimeOptions.max_sends fallback; the
    budget resolves EXACTLY as program build does
    (`MAX_SENDS or opts.max_sends`, program.py) so verify enforces the
    budget the engine actually uses."""
    atype = atype or bdef.actor_type
    max_sends = (getattr(atype, "MAX_SENDS", None)
                 or int(default_max_sends))
    if getattr(atype, "HOST", False):
        return Effects(sends=0, max_sends=0, can_error=False,
                       can_destroy=False, can_exit=False,
                       can_yield=False, spawns=(), sync_spawns=())
    ctx = probe_behaviour(bdef, atype, msg_words=msg_words)
    return Effects(
        sends=len(ctx.sends),
        max_sends=int(max_sends),
        can_error=ctx.error_called,
        can_destroy=ctx.destroy_called,
        can_exit=ctx.exit_called,
        can_yield=ctx.yield_called,
        spawns=tuple(sorted((t, len(c))
                            for t, c in ctx.spawn_claims.items() if c)),
        sync_spawns=tuple(sorted(ctx.sync_inits.keys())),
        blob_allocs=(ctx._blob.claims if ctx._blob is not None else 0),
    )


def verify_behaviour(bdef: BehaviourDef,
                     default_max_sends: int = 2) -> Effects:
    """Effects + budget enforcement for one behaviour."""
    eff = behaviour_effects(bdef, default_max_sends=default_max_sends)
    if eff.sends > eff.max_sends:
        raise VerifyError(
            f"verify: behaviour {bdef} performs {eff.sends} sends but "
            f"the type's budget is MAX_SENDS={eff.max_sends} "
            "(≙ verify/fun.c rejecting the body)")
    return eff


def verify_program(program, lint: bool = True
                   ) -> Dict[str, Dict[str, Effects]]:
    """The verify pass over every cohort: {type: {behaviour: Effects}};
    raises VerifyError on budget violations. Budgets come from the
    program's OWN resolution (cohort.max_sends), so the pass enforces
    exactly what the engine will run.

    Host cohorts are REPORTED too (zero-effect entries — host
    behaviours run real Python, not traced) rather than silently
    skipped, so whole-program consumers (the lint pass's message-flow
    graph) see the host nodes messages land on.

    With ``lint=True`` (default) the whole-program lint pass
    (ponyc_tpu.lint.lint_program) runs after the per-behaviour budgets:
    error-severity findings — provably-broken wiring like sends to
    types outside the program or capability violations — raise
    VerifyError; warnings/info are left to `lint_program` callers."""
    report: Dict[str, Dict[str, Effects]] = {}
    for cohort in program.cohorts:
        ents: Dict[str, Effects] = {}
        for bdef in cohort.behaviours:
            eff = behaviour_effects(
                bdef, cohort.atype,
                default_max_sends=program.opts.max_sends)
            if not cohort.host and eff.sends > cohort.max_sends:
                raise VerifyError(
                    f"verify: behaviour {bdef} performs {eff.sends} "
                    f"sends but the cohort's budget is "
                    f"{cohort.max_sends} (≙ verify/fun.c)")
            ents[bdef.name] = eff
        report[cohort.atype.__name__] = ents
    if lint:
        from .lint import lint_program
        errors = [f for f in lint_program(program)
                  if f.severity == "error"]
        if errors:
            lines = "\n".join(f"  {f}" for f in errors)
            raise VerifyError(
                f"verify: lint found {len(errors)} error-severity "
                f"finding(s) (≙ reach/paint + safeto rejecting the "
                f"program):\n{lines}")
    return report
