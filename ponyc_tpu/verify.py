"""Verify pass: per-behaviour effect signatures, discovered by probe
tracing.

≙ the reference's verify stage (src/libponyc/verify/fun.c: after type
checking, every function's partial-call/error behaviour is analysed and
mismatches rejected). Errors here are VALUES (ctx.error_int — the
fork's pony_error_int), so there is no caller-must-handle obligation to
enforce; what the pass delivers instead is the same ANALYSIS made
queryable: which behaviours can error/destroy/exit/yield, how many
sends they perform against the type's budget, and what they spawn —
surfaced programmatically (`verify_program`), in generated docs
(docgen marks behaviours like Pony marks partial functions with `?`),
and as hard failures for budget violations at verify time instead of
first dispatch.

Probe tracing uses jax.eval_shape (abstract values, no compilation), so
verifying a program costs milliseconds.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .api import ActorTypeMeta, BehaviourDef, Context
from .ops import pack


@dataclasses.dataclass(frozen=True)
class Effects:
    """What one behaviour DOES, beyond its state update."""

    sends: int                    # ctx.send call sites
    max_sends: int                # the type's declared budget
    can_error: bool               # ctx.error_int reachable
    can_destroy: bool             # ctx.destroy reachable
    can_exit: bool                # ctx.exit reachable
    can_yield: bool               # ctx.yield_ reachable
    spawns: Tuple[Tuple[str, int], ...]   # (target type, claim sites)
    sync_spawns: Tuple[str, ...]  # targets constructed synchronously
    blob_allocs: int = 0          # ctx.blob_alloc call sites (≤ MAX_BLOBS)

    def marks(self) -> str:
        """Compact docgen suffix (≙ Pony's `?` partial mark)."""
        out = []
        if self.sends:
            out.append(f"sends≤{self.sends}")
        for t, n in self.spawns:
            out.append(f"spawns {t}×{n}")
        if self.sync_spawns:
            out.append("sync-constructs "
                       + ",".join(sorted(set(self.sync_spawns))))
        if self.blob_allocs:
            out.append(f"allocs blobs×{self.blob_allocs}")
        if self.can_error:
            out.append("may error")      # ≙ the `?` mark
        if self.can_destroy:
            out.append("may destroy")
        if self.can_exit:
            out.append("may exit")
        if self.can_yield:
            out.append("may yield")
        return ", ".join(out)


class VerifyError(TypeError):
    """A behaviour violates its type's declared budgets (≙ the verify
    pass rejecting a method body, verify/fun.c)."""


class _ProbeContext(Context):
    """A Context usable BEFORE any Program exists: send() counts the
    call and keeps the when-mask effect, without requiring registered
    behaviour ids or packing against a concrete msg_words (the verify
    pass runs on bare actor classes, like the reference verifying a
    method body before reachability)."""

    def send(self, target, behaviour_def, *args, when=True):
        if not isinstance(behaviour_def, BehaviourDef):
            raise TypeError(
                "second argument to send() must be a behaviour "
                "(e.g. SomeActor.some_behaviour)")
        self.sends.append((target, None, when))

    def spawn_sync(self, ctor, *args, when=True):
        """Claim-only: the ctor does not RUN during effect probing (it
        must be pure construction anyway — the real path enforces
        that), so string-form SPAWNS targets need no field specs."""
        tname, ref, ok = self._claim_slot(ctor, when, "spawn_sync")
        self.sync_inits.setdefault(tname, {})
        return self.ref_types.tag(ref, tname)


def behaviour_effects(bdef: BehaviourDef,
                      atype: Optional[ActorTypeMeta] = None,
                      msg_words: int = 8,
                      default_max_sends: int = 2) -> Effects:
    """Probe-trace one behaviour on abstract 1-lane values and collect
    its effect signature. Host behaviours (HOST=True types) run real
    Python — they are not traced and report zero device effects.

    `default_max_sends` is the RuntimeOptions.max_sends fallback; the
    budget resolves EXACTLY as program build does
    (`MAX_SENDS or opts.max_sends`, program.py) so verify enforces the
    budget the engine actually uses."""
    atype = atype or bdef.actor_type
    field_specs = atype.field_specs
    max_sends = (getattr(atype, "MAX_SENDS", None)
                 or int(default_max_sends))
    if getattr(atype, "HOST", False):
        return Effects(0, 0, False, False, False, False, (), ())
    spawn_budget = {
        (t if isinstance(t, str) else t.__name__): n
        for t, n in getattr(atype, "SPAWNS", {}).items()}
    box: Dict[str, Context] = {}

    def probe(st, args):
        resv = {t: jnp.full((max(1, n),), -1, jnp.int32)
                for t, n in spawn_budget.items()}
        # A tiny stand-in blob pool so blob-using behaviours probe
        # (handles resolve to -1/no-op; budgets enforce exactly like
        # the engine's MAX_BLOBS window).
        from .api import BlobPoolView
        mb = int(getattr(atype, "MAX_BLOBS", 0) or 0)
        bv = BlobPoolView(
            jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.bool_),
            jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
            jnp.int32(0), jnp.bool_(True),
            jnp.full((mb,), -1, jnp.int32) if mb else None)
        ctx = _ProbeContext(jnp.int32(0), msg_words, spawn_resv=resv,
                            spawn_meta={t: {} for t in spawn_budget},
                            blob=bv)
        for k, v in st.items():
            ctx.ref_types.tag(v, pack.ref_target(field_specs[k]))
            ctx.cap_types.tag(v, pack.cap_mode(field_specs[k]))
        for spec, a in zip(bdef.arg_specs, args):
            ctx.ref_types.tag(a, pack.ref_target(spec))
            ctx.cap_types.tag(a, pack.cap_mode(spec))
        box["ctx"] = ctx
        st2 = bdef.fn(ctx, dict(st), *args)
        return st2

    st = {k: jnp.zeros((), jnp.float32 if s is pack.F32 else jnp.int32)
          for k, s in field_specs.items()}
    args = []
    for spec in bdef.arg_specs:
        if isinstance(spec, pack._VecSpec):
            dt = jnp.float32 if spec.base is pack.F32 else jnp.int32
            args.append(jnp.zeros((spec.n,), dt))
        elif spec is pack.F32:
            args.append(jnp.zeros((), jnp.float32))
        elif spec is pack.Bool:
            args.append(jnp.zeros((), jnp.bool_))
        elif spec in pack._NARROW_JNP:
            args.append(jnp.zeros((), pack._NARROW_JNP[spec]))
        else:
            args.append(jnp.zeros((), jnp.int32))
    jax.eval_shape(probe, st, tuple(args))
    ctx = box["ctx"]
    return Effects(
        sends=len(ctx.sends),
        max_sends=int(max_sends),
        can_error=ctx.error_called,
        can_destroy=ctx.destroy_called,
        can_exit=ctx.exit_called,
        can_yield=ctx.yield_called,
        spawns=tuple(sorted((t, len(c))
                            for t, c in ctx.spawn_claims.items() if c)),
        sync_spawns=tuple(sorted(ctx.sync_inits.keys())),
        blob_allocs=(ctx._blob.claims if ctx._blob is not None else 0),
    )


def verify_behaviour(bdef: BehaviourDef,
                     default_max_sends: int = 2) -> Effects:
    """Effects + budget enforcement for one behaviour."""
    eff = behaviour_effects(bdef, default_max_sends=default_max_sends)
    if eff.sends > eff.max_sends:
        raise VerifyError(
            f"verify: behaviour {bdef} performs {eff.sends} sends but "
            f"the type's budget is MAX_SENDS={eff.max_sends} "
            "(≙ verify/fun.c rejecting the body)")
    return eff


def verify_program(program) -> Dict[str, Dict[str, Effects]]:
    """The verify pass over every device cohort: {type: {behaviour:
    Effects}}; raises VerifyError on budget violations. Budgets come
    from the program's OWN resolution (cohort.max_sends), so the pass
    enforces exactly what the engine will run."""
    report: Dict[str, Dict[str, Effects]] = {}
    for cohort in program.cohorts:
        if cohort.host:
            continue
        ents: Dict[str, Effects] = {}
        for bdef in cohort.behaviours:
            eff = behaviour_effects(
                bdef, cohort.atype,
                default_max_sends=program.opts.max_sends)
            if eff.sends > cohort.max_sends:
                raise VerifyError(
                    f"verify: behaviour {bdef} performs {eff.sends} "
                    f"sends but the cohort's budget is "
                    f"{cohort.max_sends} (≙ verify/fun.c)")
            ents[bdef.name] = eff
        report[cohort.atype.__name__] = ents
    return report
