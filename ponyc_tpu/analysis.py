"""Runtime analysis/telemetry — ≙ the fork's `--ponyanalysis` subsystem
(src/libponyrt/analysis/analysis.{c,h}; DIVERGENCE.md "--ponyanalysis").

The reference streams per-event records (mute/overload/pressure/run/gc/
msg-send, analysis.h:16-31) from every scheduler onto a dedicated
analysis thread that writes CSV to /tmp/pony.ponyrt_analytics, with
level 1 adding a SIGTERM live-world dump. The TPU re-design keeps the
same three levels and the same dedicated-writer-thread shape, but the
unit of record is a *step window*, not a message: per-event host
callbacks would serialise the device, while window aggregates
(counters + occupancy/mute/overload reductions computed in the jitted
step when analysis >= 1) cost nothing observable.

  level 0 — off (default; the aux telemetry lanes compile to constants)
  level 1 — summary on run() end + SIGTERM/SIGUSR1 live-world dump
            (≙ sigintHandler analysis.c:55 + cycle.c:874-954 dump_views)
            + the per-behaviour profiler matrix (Runtime.profile():
            runs/deliveries/rejects per behaviour, queue-wait latency
            histograms and mute-ticks per cohort, GC window stats —
            ≙ the fork's per-actor records, computed in the jitted step
            by engine.profile_lanes and fetched only at boundaries)
  level 2 — level 1 + one CSV row per quiesce window to
            RuntimeOptions.analysis_path via a writer thread
            (≙ analysis.c:41-167 thread + CSV format); the window CSV
            carries the static columns below PLUS dynamic per-behaviour
            `run:<Type.beh>` delta columns and per-cohort
            `qw50:<Type>`/`qw99:<Type>` queue-wait percentiles

Wire-up: ``analysis.attach(rt)`` (Runtime.run calls the hook
automatically when opts.analysis >= 1 and nothing is attached yet).
`python -m ponyc_tpu top <csv>` renders the window stream as a live
terminal view (top_frame below).
"""

from __future__ import annotations

import math
import os
import queue
import signal
import sys
import threading
import time
from typing import Optional

import numpy as np

CSV_COLUMNS = [
    "time_ms", "step", "processed", "delivered", "rejected", "badmsg",
    "deadletter", "mutes", "occ_sum", "occ_max", "muted_now",
    "overloaded_now", "host_processed", "inject_queue", "fast_queue",
    "ev_dropped", "gc_runs", "gc_collected", "gc_swept",
    "rss_kb", "cpu_ms",
    # Adaptive run loop (PROFILE.md §9): ticks this window actually ran,
    # the host-imposed device-idle gap before its dispatch (µs; 0 for
    # windows dispatched behind an in-flight one), and the controller's
    # next window length + state (grow/shrink/steady).
    "window_ticks", "host_gap_us", "ctrl_window", "ctrl_state",
    # Mailbox bandwidth diet (ops/megakernel.py): bytes per ring record
    # at this run's delivery formulation — 2 bytes/word inside the
    # pallas_mega packed kernel boundary, 4 bytes/word on the int32 XLA
    # paths. Static per run; rides every row so downstream tooling can
    # turn msgs/s into bytes/s without re-deriving the layout.
    "bytes_msg",
]


def _host_usage():
    """Current host RSS (KB) + cumulative CPU time (ms) of this process
    (≙ ponyint_update_memory_usage, sched/cpu.c — the reference samples
    /proc RSS for analysis; we add CPU time since the host loop IS a
    scheduler here)."""
    import resource
    ru = resource.getrusage(resource.RUSAGE_SELF)
    cpu_ms = round((ru.ru_utime + ru.ru_stime) * 1e3, 1)
    try:
        with open("/proc/self/statm") as f:
            rss_kb = int(f.read().split()[1]) * (
                os.sysconf("SC_PAGE_SIZE") // 1024)
    except OSError:
        # Non-Linux fallback: ru_maxrss is the HIGH-WATER mark, and its
        # unit is bytes on macOS vs KB on Linux/BSD.
        rss_kb = int(ru.ru_maxrss // 1024) if sys.platform == "darwin" \
            else int(ru.ru_maxrss)
    return rss_kb, cpu_ms


def hist_percentile(hist, q: float) -> int:
    """Lower-bound tick value (2^k) of the q-quantile bucket of a
    power-of-two queue-wait histogram (state.QW_BUCKETS buckets, bucket
    k ↔ [2^k, 2^(k+1)) ticks); 0 when the histogram is empty."""
    total = int(sum(int(v) for v in hist))
    if total <= 0:
        return 0
    need = max(1, int(math.ceil(q * total)))
    seen = 0
    for k, v in enumerate(hist):
        seen += int(v)
        if seen >= need:
            return 1 << k
    return 1 << (len(hist) - 1)


# Level-3 per-event lane (≙ analysis.h:16-31 event enum; the device
# records transition events in a bounded ring, engine.py §5b).
EVENT_NAMES = {1: "MUTE", 2: "UNMUTE", 3: "OVERLOAD", 4: "SPAWN",
               5: "DESTROY", 6: "ERROR"}
EVENT_COLUMNS = ["time_ms", "step", "event", "actor"]


class Analysis:
    """Per-runtime telemetry collector + writer thread (level 2)."""

    def __init__(self, rt):
        self.rt = rt
        self.level = rt.opts.analysis
        self.t0 = time.time()
        self._rows: "queue.Queue" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev = {}
        self._saved_handlers = {}   # signum → handler to restore on close
        self._warned_drops = False
        # Window CSV schema: the static columns + one `run:` delta
        # column per behaviour + per-device-cohort queue-wait
        # percentiles (the per-behaviour profiler's window stream).
        self.beh_names = [f"{b.actor_type.__name__}.{b.name}"
                          for b in rt.program.behaviour_table]
        self.dev_names = [c.atype.__name__
                          for c in rt.program.device_cohorts]
        from .runtime.state import PHASE_NAMES, QW_BUCKETS
        self.columns = (CSV_COLUMNS
                        + [f"run:{n}" for n in self.beh_names]
                        + [c for n in self.dev_names
                           for c in (f"qw50:{n}", f"qw99:{n}")]
                        # Per-phase window telemetry (ISSUE 19): one
                        # work-unit delta column per scheduler phase
                        # (engine.phase_cost_lanes).
                        + [f"ph:{n}" for n in PHASE_NAMES])
        self._prev_hist = np.zeros((len(self.dev_names), QW_BUCKETS),
                                   np.int64)
        # Packed-record width for the bytes_msg column (see
        # CSV_COLUMNS): int16 lanes inside the megakernel boundary,
        # int32 words everywhere else.
        from .ops.megakernel import record_words
        self.bytes_msg = record_words(rt.opts) * (
            2 if rt.opts.delivery == "pallas_mega" else 4)
        if self.level >= 2:
            self._writer = threading.Thread(target=self._write_loop,
                                            daemon=True)
            self._writer.start()

    def _telemetry(self):
        """One host read of the cumulative profiler matrix: returns
        (runs [NB] incl. host-dispatch counts, hist [ND, QW_BUCKETS],
        ev_dropped total, gc-collected total, phases [N_PHASES])."""
        rt = self.rt
        from .runtime.state import N_PHASES, QW_BUCKETS
        p = rt.program.shards
        nb = len(rt.program.behaviour_table)
        nd = len(rt.program.device_cohorts)
        st = rt.state
        runs = np.asarray(
            rt._fetch(st.beh_runs), np.int64).reshape(p, nb).sum(0)
        for g, n in rt._beh_host_runs.items():
            runs[g] += n
        hist = np.asarray(rt._fetch(st.qwait_hist), np.int64).reshape(
            p, nd, QW_BUCKETS).sum(0)
        dropped = int(np.asarray(rt._fetch(st.ev_dropped)).sum())
        collected = int(np.asarray(rt._fetch(st.n_collected)).sum())
        phases = np.asarray(
            rt._fetch(st.phase_cost), np.int64).reshape(
                p, N_PHASES).sum(0)
        return runs, hist, dropped, collected, phases

    # -- window hook (called by Runtime.run after each window retire;
    # under the pipelined loop the writer runs while the next window is
    # already in flight on device) --
    def window(self, aux, ticks=None, gap_us=None) -> None:
        if self.level >= 3:
            self._drain_events()
            self._drain_spans()
        if self.level < 2:
            return
        rt = self.rt
        # Counters ride the StepAux the run loop already fetched; the
        # profiler matrix is one extra small host read per window
        # boundary (never per tick).
        runs, hist, dropped, collected, phases = self._telemetry()
        if dropped and not self._warned_drops:
            # One-time loudness (satellite fix): a too-small event ring
            # used to lose level-3 trace events silently unless someone
            # read dump().
            self._warned_drops = True
            print(f"ponyc_tpu analysis: device event ring dropped "
                  f"{dropped} event(s) so far — raise "
                  "RuntimeOptions.analysis_events", file=sys.stderr)
        row = [
            round((time.time() - self.t0) * 1e3, 3),
            rt.steps_run,
            self._delta("processed", rt.totals["processed"]),
            self._delta("delivered", rt.totals["delivered"]),
            self._delta("rejected", int(aux.n_rejected)),
            self._delta("badmsg", int(aux.n_badmsg)),
            self._delta("deadletter", int(aux.n_deadletter)),
            self._delta("mutes", int(aux.n_mutes)),
            int(aux.occ_sum), int(aux.occ_max),
            int(aux.n_muted_now), int(aux.n_overloaded_now),
            self._delta("host_processed",
                        rt.totals.get("host_processed", 0)),
            len(rt._inject_q),
            len(rt._host_fast_q),
            self._delta("ev_dropped", dropped),
            self._delta("gc_runs", rt.totals.get("gc_runs", 0)),
            self._delta("gc_collected", collected),
            self._delta("gc_swept", rt.totals.get("gc_swept_blobs", 0)),
        ]
        row.extend(_host_usage())
        ctrl = getattr(rt, "_controller", None)
        row.extend([
            0 if ticks is None else int(ticks),
            0 if gap_us is None else round(float(gap_us), 1),
            0 if ctrl is None else int(ctrl.window),
            "-" if ctrl is None else ctrl.state,
            self.bytes_msg,
        ])
        for g in range(runs.shape[0]):
            row.append(self._delta(f"run:{g}", int(runs[g])))
        for di in range(hist.shape[0]):
            dh = hist[di] - self._prev_hist[di]
            self._prev_hist[di] = hist[di]
            row.append(hist_percentile(dh, 0.50))
            row.append(hist_percentile(dh, 0.99))
        for i in range(phases.shape[0]):
            row.append(self._delta(f"ph:{i}", int(phases[i])))
        self._rows.put(row)

    def _delta(self, key, cur) -> int:
        prev = self._prev.get(key, 0)
        self._prev[key] = cur
        return int(cur - prev)

    def _drain_events(self) -> None:
        """Pull the device event ring (engine §5b) and reset it. Rows go
        through the same writer thread, tagged for the events CSV."""
        import dataclasses as _dc

        import jax.numpy as jnp

        rt = self.rt
        st = rt.state
        counts = np.asarray(st.ev_count)
        if counts.sum() == 0:
            return
        data = np.asarray(st.ev_data)            # [3, P*EV]
        ev_cap = rt.opts.analysis_events
        now = round((time.time() - self.t0) * 1e3, 3)
        for shard, cnt in enumerate(counts):
            seg = data[:, shard * ev_cap: shard * ev_cap + int(cnt)]
            for i in range(seg.shape[1]):
                self._rows.put(("ev", [
                    now, int(seg[2, i]),
                    EVENT_NAMES.get(int(seg[0, i]), "?"),
                    int(seg[1, i])]))
        fkey = rt._freelist_key
        rt.state = _dc.replace(st, ev_count=jnp.zeros_like(st.ev_count))
        rt._freelist_key = fkey       # count reset frees no slots

    def _drain_spans(self) -> None:
        """Pull the device span ring through the runtime's Tracer
        (causal tracing, PROFILE.md §10) and stream any fresh spans —
        device AND host — to `<analysis_path>.spans.jsonl` as one-line
        JSON records via the writer thread."""
        tracer = getattr(self.rt, "_tracer", None)
        if tracer is None:
            return
        tracer.drain(self.rt)
        if self.level < 2:
            return
        from .tracing import span_jsonl_line
        for rec in tracer.take_fresh():
            self._rows.put(("span", span_jsonl_line(rec)))

    def _write_loop(self) -> None:
        opts = self.rt.opts
        # Batched flushing (satellite fix): flush-per-row serialised the
        # writer under level-3 event bursts. Rows now flush when the
        # queue drains (a quiet stream stays promptly visible to `top`)
        # or every opts.analysis_flush_ms while a burst is in flight;
        # close() joins the thread and closing the files flushes the
        # tail.
        flush_s = max(0.0, getattr(opts, "analysis_flush_ms", 200) / 1e3)
        ev_f = open(opts.analysis_path + ".events.csv", "w") \
            if self.level >= 3 else None
        sp_f = open(opts.analysis_path + ".spans.jsonl", "w") \
            if getattr(self.rt, "_tracer", None) is not None else None
        dirty = []
        last_flush = time.monotonic()

        def _flush():
            nonlocal last_flush
            for fh in dirty:
                fh.flush()
            dirty.clear()
            last_flush = time.monotonic()

        try:
            if ev_f is not None:
                ev_f.write(",".join(EVENT_COLUMNS) + "\n")
            with open(opts.analysis_path, "w") as f:
                f.write(",".join(self.columns) + "\n")
                while not (self._stop.is_set() and self._rows.empty()):
                    try:
                        row = self._rows.get(timeout=0.1)
                    except queue.Empty:
                        if dirty:
                            _flush()
                        continue
                    if isinstance(row, tuple) and row[0] == "ev":
                        ev_f.write(",".join(str(x) for x in row[1])
                                   + "\n")
                        if ev_f not in dirty:
                            dirty.append(ev_f)
                    elif isinstance(row, tuple) and row[0] == "span":
                        sp_f.write(row[1] + "\n")
                        if sp_f not in dirty:
                            dirty.append(sp_f)
                    else:
                        f.write(",".join(str(x) for x in row) + "\n")
                        if f not in dirty:
                            dirty.append(f)
                    if (self._rows.empty()
                            or time.monotonic() - last_flush >= flush_s):
                        _flush()
        finally:
            if ev_f is not None:
                ev_f.close()
            if sp_f is not None:
                sp_f.close()

    # -- live-world dump (level >= 1; SIGTERM/SIGUSR1 and run() end) --
    def dump(self, out=None) -> str:
        rt = self.rt
        lines = ["=== ponyc_tpu analysis dump ==="]
        lines.append(f"steps_run={rt.steps_run} "
                     f"uptime_ms={round((time.time()-self.t0)*1e3, 1)}")
        for name in ("n_processed", "n_delivered", "n_rejected",
                     "n_badmsg", "n_deadletter", "n_mutes"):
            lines.append(f"{name}={rt.counter(name)}")
        lines.append(f"host_processed={rt.totals.get('host_processed', 0)} "
                     f"inject_queue={len(rt._inject_q)} "
                     f"fast_queue={len(rt._host_fast_q)}")
        rss_kb, cpu_ms = _host_usage()
        lines.append(f"host_rss_kb={rss_kb} host_cpu_ms={cpu_ms}")
        # Adaptive run loop (PROFILE.md §9): live window length +
        # controller state + cumulative host-gap exposure.
        rl = rt.run_loop_stats() if hasattr(rt, "run_loop_stats") else None
        if rl is not None and rl["controller"] is not None:
            c = rl["controller"]
            lines.append(
                f"run_loop window={c['window']} ctrl={c['state']} "
                f"[{c['lo']},{c['hi']}] grows={c['grows']} "
                f"shrinks={c['shrinks']} windows={rl['windows']} "
                f"pipelined={rl['pipelined_dispatches']}"
                f"/{rl['pipelined_dispatches'] + rl['sync_dispatches']} "
                f"host_gap_ms={rl['host_gap_us_total'] / 1e3:.2f}")
        if self.level >= 3 and rt.state is not None:
            lines.append(
                f"events_pending={int(np.asarray(rt.state.ev_count).sum())} "
                f"events_dropped={int(np.asarray(rt.state.ev_dropped).sum())}")
        # Causal tracing (PROFILE.md §10): the per-trace rows — how many
        # traces are live, their span counts, and the latest trace's
        # critical-path latency in device ticks.
        tracer = getattr(rt, "_tracer", None)
        if tracer is not None:
            try:
                trees = rt.traces()
            except Exception:           # mid-teardown: degrade
                trees = None
            if trees is not None:
                lines.append(
                    f"traces={len(trees)} "
                    f"spans={sum(t['n_spans'] for t in trees.values())} "
                    f"span_dropped={tracer.dropped}")
                for tid in sorted(trees)[-3:]:
                    t = trees[tid]
                    lines.append(
                        f"  trace {tid}: spans={t['n_spans']} "
                        f"latency={t['latency']} ticks  "
                        + " -> ".join(t["critical_path"][:6]))
        # Memory accounting (≙ USE_MEMTRACK counters, scheduler.h:52-66):
        # native pool blocks + host-heap handles.
        try:
            from . import native as _native
            allocated, recycled = _native.pool_stats()
            lines.append(f"pool_allocated={allocated} "
                         f"pool_recycled={recycled}")
        except Exception:               # native lib absent: skip silently
            pass
        heap = getattr(rt, "_heap", None)
        if heap is not None:
            s = heap.stats()
            lines.append(
                f"host_heap boxed={s['boxed']} unboxed={s['unboxed']} "
                f"live={s['live']} peak={s['peak_live']}")
        bridge = getattr(rt, "bridge", None)
        if bridge is not None:
            lines.append(f"asio_noisy={bridge.loop.noisy} "
                         f"asio_pending={bridge.loop.pending()}")
        # The per-behaviour profiler (analysis >= 1): GC window stats,
        # the hottest behaviours, and per-cohort queue-wait percentiles
        # woven into the cohort rows below — the live-world analog of
        # the fork's per-actor dump_views rows (cycle.c:874-954).
        prof = None
        if (rt.opts.analysis >= 1 and rt.state is not None
                and rt.state.beh_runs.size):
            try:
                prof = rt.profile()
            except Exception:           # mid-teardown: degrade to basics
                prof = None
        if prof is not None:
            g = prof["gc"]
            lines.append(f"gc passes={g['passes']} "
                         f"collected={g['collected']} "
                         f"blob_swept={g['blob_slots_reclaimed']} "
                         f"aborted={g['aborted']}")
            ph = prof.get("phases")
            if ph:
                lines.append("phases " + " ".join(
                    f"{n}={v}" for n, v in ph.items()))
            hot = sorted(prof["behaviours"].items(),
                         key=lambda kv: -kv[1]["runs"])
            for name, b in hot[:8]:
                lines.append(f"  beh {name}: runs={b['runs']} "
                             f"delivered={b['delivered']} "
                             f"rejected={b['rejected']}")
        if rt.state is not None:
            occ = np.asarray(rt.state.tail) - np.asarray(rt.state.head)
            alive = np.asarray(rt.state.alive)
            muted = np.asarray(rt.state.muted)
            lines.append(f"actors_alive={int(alive.sum())} "
                         f"muted={int(muted.sum())} "
                         f"queued_msgs={int(occ.sum())} "
                         f"deepest_queue={int(occ.max())}")
            # Per-cohort queue depth summary (≙ per-actor tag rows in the
            # reference's dump; cohorts are the TPU grouping).
            for cohort in rt.program.cohorts:
                cols = np.asarray(cohort.slot_to_gid(
                    np.arange(cohort.capacity)), np.int64)
                co = occ[cols]
                extra = ""
                cinf = (prof or {}).get("cohorts", {}).get(
                    cohort.atype.__name__)
                if cinf is not None:
                    extra = (f" qw_p50={cinf['queue_wait_p50']}"
                             f" qw_p99={cinf['queue_wait_p99']}"
                             f" mute_ticks={cinf['mute_ticks']}")
                lines.append(
                    f"  cohort {cohort.atype.__name__}: "
                    f"cap={cohort.capacity} queued={int(co.sum())} "
                    f"max={int(co.max()) if co.size else 0} "
                    f"muted={int(muted[cols].sum())}" + extra)
        text = "\n".join(lines)
        print(text, file=out or sys.stderr)
        return text

    def install_signal_dump(self, signums=(signal.SIGTERM,
                                           signal.SIGUSR1)) -> None:
        """Install dump-on-signal handlers (main thread only; ≙ the
        reference installing its SIGTERM handler when analysis > 0).
        SIGUSR1 (and any other signal passed) is dump-and-continue;
        SIGTERM dumps, RESTORES the previous disposition and re-raises
        so the process still terminates — the handler must observe the
        world on the way out, not cancel the shutdown (the old lambda
        swallowed SIGTERM forever). Previous handlers are restored by
        close()."""
        def _handler(signum, _frame):
            self.dump()
            if signum == signal.SIGTERM:
                prev = self._saved_handlers.get(signum, signal.SIG_DFL)
                try:
                    signal.signal(signum, prev)
                except (TypeError, ValueError):
                    # prev came from outside Python (None) or we're off
                    # the main thread: fall back to the default action.
                    signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)
        for s in signums:
            try:
                prev = signal.signal(s, _handler)
            except ValueError:   # not the main thread: skip
                return
            self._saved_handlers.setdefault(s, prev)

    def summary(self) -> None:
        if self.level >= 1:
            self.dump()

    def close(self) -> None:
        try:
            self._drain_spans()    # tail spans after the last window
        except Exception:          # teardown must never raise here
            pass
        self._stop.set()
        if self._writer is not None:
            self._writer.join(timeout=2.0)
            self._writer = None
        # Restore pre-attach signal dispositions so a torn-down runtime
        # neither swallows SIGTERM nor stays alive via handler closures.
        for s, prev in self._saved_handlers.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._saved_handlers.clear()


def attach(rt) -> Analysis:
    """Create and register the Analysis hook on a runtime."""
    a = Analysis(rt)
    rt._analysis = a
    if a.level >= 1:
        a.install_signal_dump()
    return a


# ---- tolerant CSV reading (shared by chrome_trace and top_frame) ----
#
# A run killed mid-flush (crash, watchdog trip, kill -9) leaves the
# window/event CSVs with a truncated final line; a run killed during
# warmup leaves them header-only. Every reader parses what is whole and
# warns ONCE per file per process instead of raising — crash artefacts
# exist precisely to be read after ungraceful exits.

_warned_truncated: set = set()


def _warn_truncated(path: str, n: int) -> None:
    if path in _warned_truncated:
        return
    _warned_truncated.add(path)
    print(f"ponyc_tpu analysis: {path}: skipped {n} incomplete row(s) "
          "(run killed mid-flush?)", file=sys.stderr)


def _int0(v) -> int:
    """Int of a CSV cell; 0 for missing/truncated/garbled cells."""
    try:
        return int(float(v)) if v not in (None, "") else 0
    except (TypeError, ValueError):
        return 0


def _whole_rows(rows):
    """Keep only whole rows: time_ms parses AND no trailing column is
    missing (csv.DictReader fills short — truncated — lines with None).
    Returns (rows, dropped)."""
    ok = []
    dropped = 0
    for r in rows:
        try:
            float(r.get("time_ms") or "")
        except (TypeError, ValueError):
            dropped += 1
            continue
        if any(v is None for v in r.values()):
            dropped += 1
            continue
        ok.append(r)
    return ok, dropped


def chrome_trace(csv_path: str, out_path: str,
                 events_path: Optional[str] = None,
                 spans_path: Optional[str] = None) -> str:
    """Convert the analysis CSVs into a Chrome-trace / Perfetto JSON.

    ≙ the reference's DTrace/SystemTap scripts turning USDT probes into
    a timeline (examples/dtrace/telemetry.d — SURVEY §5's third tracing
    mechanism): the step-window CSV becomes counter tracks (queued
    messages, deepest mailbox, muted/overloaded actors, throughput per
    window, anomalies), the dynamic per-behaviour `run:` columns become
    one counter track per HOT behaviour (any nonzero window — the
    per-op attribution timeline), the `qw50:`/`qw99:` columns one
    queue-wait track per cohort, the level-3 event CSV becomes instant
    events (MUTE/UNMUTE/OVERLOAD/SPAWN/DESTROY/ERROR, one thread lane
    per class), and the causal-trace span stream (PROFILE.md §10)
    becomes duration slices with sender→receiver FLOW ARROWS on a
    second, device-tick-timebased process — load the output in
    chrome://tracing or ui.perfetto.dev. Every process and thread lane
    carries name (and sort-index) metadata so Perfetto labels tracks
    instead of showing bare pids/tids; pre-profiler CSVs (no dynamic
    columns) still convert. `events_path` defaults to
    `<csv_path>.events.csv` and `spans_path` to
    `<csv_path>.spans.jsonl` when those files exist."""
    import csv as _csv
    import json
    import os

    pid = 1
    out = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "ponyc_tpu runtime"}},
        {"ph": "M", "pid": pid, "name": "process_sort_index",
         "args": {"sort_index": 0}},
        {"ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
         "args": {"name": "step windows"}},
    ]
    with open(csv_path) as f:
        rows = list(_csv.DictReader(f))
    # A run killed mid-flush leaves a truncated final row (and a
    # killed-at-open run an empty file): parse what is whole, warn
    # once, never raise (satellite fix — the postmortem workflow reads
    # exactly these files after a crash).
    rows, dropped = _whole_rows(rows)
    if dropped:
        _warn_truncated(csv_path, dropped)
    header = list(rows[0].keys()) if rows else []
    run_cols = [c for c in header if c and c.startswith("run:")
                and any(_int0(r.get(c)) for r in rows)]
    qw_cohorts = [c[5:] for c in header if c and c.startswith("qw50:")]
    ph_cols = [c for c in header if c and c.startswith("ph:")]
    for row in rows:
        ts = float(row["time_ms"]) * 1e3          # µs
        for track, cols in (
                ("queue", {"queued": "occ_sum",
                           "deepest": "occ_max"}),
                ("actors", {"muted": "muted_now",
                            "overloaded": "overloaded_now"}),
                ("window throughput", {"processed": "processed",
                                       "delivered": "delivered"}),
                ("anomalies", {"rejected": "rejected",
                               "badmsg": "badmsg",
                               "deadletter": "deadletter"})):
            out.append({"ph": "C", "pid": pid, "ts": ts,
                        "name": track,
                        "args": {k: _int0(row.get(c))
                                 for k, c in cols.items()}})
        for c in run_cols:
            out.append({"ph": "C", "pid": pid, "ts": ts,
                        "name": f"behaviour {c[4:]}",
                        "args": {"runs": _int0(row.get(c))}})
        for cn in qw_cohorts:
            out.append({"ph": "C", "pid": pid, "ts": ts,
                        "name": f"queue-wait {cn}",
                        "args": {"p50": _int0(row.get(f"qw50:{cn}")),
                                 "p99": _int0(row.get(f"qw99:{cn}"))}})
        # Per-phase window telemetry (ISSUE 19): one counter track per
        # scheduler phase — the per-window work-unit attribution lane.
        for c in ph_cols:
            out.append({"ph": "C", "pid": pid, "ts": ts,
                        "name": f"phase {c[3:]}",
                        "args": {"work": _int0(row.get(c))}})
    if events_path is None:
        cand = csv_path + ".events.csv"
        events_path = cand if os.path.exists(cand) else None
    if events_path is not None:
        tids = {}
        evs = []
        with open(events_path) as f:
            ev_rows, ev_dropped = _whole_rows(list(_csv.DictReader(f)))
        if ev_dropped:
            _warn_truncated(events_path, ev_dropped)
        for row in ev_rows:
            name = row.get("event") or "?"
            tid = tids.setdefault(name, len(tids) + 1)
            evs.append({"ph": "i", "pid": pid, "tid": tid, "s": "t",
                        "ts": float(row["time_ms"]) * 1e3,
                        "name": f"{name} a{row.get('actor', '?')}",
                        "args": {"actor": _int0(row.get("actor")),
                                 "step": _int0(row.get("step"))}})
        # Metadata BEFORE the events they label: Perfetto resolves
        # track names on first sight of a tid (the satellite fix —
        # bare-pid tracks came from late/absent name records).
        for name, tid in tids.items():
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": f"events:{name}"}})
        out.extend(evs)
    if spans_path is None:
        cand = csv_path + ".spans.jsonl"
        spans_path = cand if os.path.exists(cand) else None
    if spans_path is not None:
        from .tracing import load_spans, perfetto_events
        out.extend(perfetto_events(load_spans(spans_path)))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": out,
                   "displayTimeUnit": "ms"}, f)
    return out_path


def top_frame(csv_path: str) -> str:
    """Render one frame of the live `top` view from the window CSV
    stream (the writer thread's analysis_path file). Pure text — the
    CLI (`python -m ponyc_tpu top`) clears the screen and reprints it
    every interval; tests call it directly. ≙ watching the fork's
    analytics CSV with `watch`, but pre-digested: window rates, queue
    pressure, GC, the per-behaviour run table and per-cohort
    queue-wait percentiles."""
    import csv as _csv
    import os as _os
    head = f"ponyc_tpu top — {csv_path}"
    try:
        with open(csv_path) as f:
            rows = list(_csv.DictReader(f))
    except OSError:
        rows = []
    # Satellite fix: a fresh run's CSV is empty or header-only until
    # the writer thread's first flush (analysis_flush_ms), and the
    # last row can be a half-written line mid-append — neither may
    # crash the live view. Keep only whole rows (shared tolerant
    # reader; `top` refreshes every interval, so no warning here);
    # with none left, render a calm waiting frame instead.
    rows, _dropped = _whole_rows(rows)
    if not rows:
        return (head + "\n(waiting for samples — no windows written "
                "yet; is a runtime with analysis>=2 running?)")

    def iv(row, k):
        v = row.get(k)
        try:
            return int(float(v)) if v not in (None, "") else 0
        except (TypeError, ValueError):
            return 0

    last = rows[-1]
    prev = rows[-2] if len(rows) > 1 else None
    dt_ms = (float(last["time_ms"]) - float(prev["time_ms"])) if prev \
        else float(last["time_ms"])
    dt_s = max(dt_ms, 1e-3) / 1e3
    lines = [head]
    lines.append(f"step {last['step']}   "
                 f"uptime {float(last['time_ms']) / 1e3:.1f}s   "
                 f"windows {len(rows)}")
    lines.append(f"window: processed {iv(last, 'processed')} "
                 f"({iv(last, 'processed') / dt_s:,.0f}/s)  "
                 f"delivered {iv(last, 'delivered')}  "
                 f"rejected {iv(last, 'rejected')}  "
                 f"deadletter {iv(last, 'deadletter')}"
                 + (f"  bytes/msg {iv(last, 'bytes_msg')}"
                    if iv(last, "bytes_msg") else ""))
    lines.append(f"queue:  occ_sum {iv(last, 'occ_sum')}  "
                 f"occ_max {iv(last, 'occ_max')}  "
                 f"muted {iv(last, 'muted_now')}  "
                 f"overloaded {iv(last, 'overloaded_now')}  "
                 f"inject {iv(last, 'inject_queue')}  "
                 f"fast {iv(last, 'fast_queue')}")
    if "gc_runs" in last:
        lines.append(
            f"gc:     passes {sum(iv(r, 'gc_runs') for r in rows)}  "
            f"collected {sum(iv(r, 'gc_collected') for r in rows)}  "
            f"blob_swept {sum(iv(r, 'gc_swept') for r in rows)}   "
            f"ev_dropped {sum(iv(r, 'ev_dropped') for r in rows)}")
    if "window_ticks" in last:
        gaps = [float(r.get("host_gap_us") or 0) for r in rows]
        lines.append(
            f"loop:   window {iv(last, 'window_ticks')} ticks  "
            f"ctrl {iv(last, 'ctrl_window')}"
            f" ({last.get('ctrl_state', '-')})  "
            f"host_gap {gaps[-1]:.0f}us "
            f"(mean {sum(gaps) / max(1, len(gaps)):.0f}us)")
    beh_cols = [c for c in (rows[0].keys() or [])
                if c and c.startswith("run:")]
    if beh_cols:
        totals = {c: sum(iv(r, c) for r in rows) for c in beh_cols}
        lines.append("")
        lines.append(f"{'behaviour':<36}{'win':>9}{'runs/s':>12}"
                     f"{'total':>12}")
        mx = max(iv(last, c) for c in beh_cols) or 1
        for c in sorted(beh_cols, key=lambda c: -totals[c]):
            win = iv(last, c)
            bar = "#" * int(round(10 * win / mx))
            lines.append(f"{c[4:]:<36}{win:>9}{win / dt_s:>12,.0f}"
                         f"{totals[c]:>12}  {bar}")
    qw_names = [c[5:] for c in (rows[0].keys() or [])
                if c and c.startswith("qw50:")]
    if qw_names:
        lines.append("")
        lines.append("queue-wait (ticks): " + "  ".join(
            f"{n} p50={iv(last, 'qw50:' + n)} "
            f"p99={iv(last, 'qw99:' + n)}" for n in qw_names))
    ph_cols = [c for c in (rows[0].keys() or [])
               if c and c.startswith("ph:")]
    if ph_cols:
        lines.append("phases (work/win):  " + "  ".join(
            f"{c[3:]}={iv(last, c)}" for c in ph_cols))
    # Causal traces (PROFILE.md §10): one row per recent trace from the
    # writer's .spans.jsonl stream, newest last.
    spans_path = csv_path + ".spans.jsonl"
    if _os.path.exists(spans_path):
        try:
            from .tracing import load_spans, reassemble
            trees = reassemble(load_spans(spans_path))
        except Exception:
            trees = {}
        if trees:
            lines.append("")
            lines.append(f"traces: {len(trees)}")
            for tid in sorted(trees)[-5:]:
                t = trees[tid]
                lines.append(
                    f"  trace {tid}: spans={t['n_spans']} "
                    f"latency={t['latency']} ticks  "
                    + " -> ".join(t["critical_path"][:5]))
    return "\n".join(lines)
