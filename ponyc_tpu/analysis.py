"""Runtime analysis/telemetry — ≙ the fork's `--ponyanalysis` subsystem
(src/libponyrt/analysis/analysis.{c,h}; DIVERGENCE.md "--ponyanalysis").

The reference streams per-event records (mute/overload/pressure/run/gc/
msg-send, analysis.h:16-31) from every scheduler onto a dedicated
analysis thread that writes CSV to /tmp/pony.ponyrt_analytics, with
level 1 adding a SIGTERM live-world dump. The TPU re-design keeps the
same three levels and the same dedicated-writer-thread shape, but the
unit of record is a *step window*, not a message: per-event host
callbacks would serialise the device, while window aggregates
(counters + occupancy/mute/overload reductions computed in the jitted
step when analysis >= 1) cost nothing observable.

  level 0 — off (default; the aux telemetry lanes compile to constants)
  level 1 — summary on run() end + SIGTERM/SIGUSR1 live-world dump
            (≙ sigintHandler analysis.c:55 + cycle.c:874-954 dump_views)
  level 2 — level 1 + one CSV row per quiesce window to
            RuntimeOptions.analysis_path via a writer thread
            (≙ analysis.c:41-167 thread + CSV format)

Wire-up: ``analysis.attach(rt)`` (Runtime.run calls the hook
automatically when opts.analysis >= 1 and nothing is attached yet).
"""

from __future__ import annotations

import os
import queue
import signal
import sys
import threading
import time
from typing import Optional

import numpy as np

CSV_COLUMNS = [
    "time_ms", "step", "processed", "delivered", "rejected", "badmsg",
    "deadletter", "mutes", "occ_sum", "occ_max", "muted_now",
    "overloaded_now", "host_processed", "inject_queue", "fast_queue",
    "rss_kb", "cpu_ms",
]


def _host_usage():
    """Current host RSS (KB) + cumulative CPU time (ms) of this process
    (≙ ponyint_update_memory_usage, sched/cpu.c — the reference samples
    /proc RSS for analysis; we add CPU time since the host loop IS a
    scheduler here)."""
    import resource
    ru = resource.getrusage(resource.RUSAGE_SELF)
    cpu_ms = round((ru.ru_utime + ru.ru_stime) * 1e3, 1)
    try:
        with open("/proc/self/statm") as f:
            rss_kb = int(f.read().split()[1]) * (
                os.sysconf("SC_PAGE_SIZE") // 1024)
    except OSError:
        # Non-Linux fallback: ru_maxrss is the HIGH-WATER mark, and its
        # unit is bytes on macOS vs KB on Linux/BSD.
        rss_kb = int(ru.ru_maxrss // 1024) if sys.platform == "darwin" \
            else int(ru.ru_maxrss)
    return rss_kb, cpu_ms

# Level-3 per-event lane (≙ analysis.h:16-31 event enum; the device
# records transition events in a bounded ring, engine.py §5b).
EVENT_NAMES = {1: "MUTE", 2: "UNMUTE", 3: "OVERLOAD", 4: "SPAWN",
               5: "DESTROY", 6: "ERROR"}
EVENT_COLUMNS = ["time_ms", "step", "event", "actor"]


class Analysis:
    """Per-runtime telemetry collector + writer thread (level 2)."""

    def __init__(self, rt):
        self.rt = rt
        self.level = rt.opts.analysis
        self.t0 = time.time()
        self._rows: "queue.Queue" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev = {}
        self._saved_handlers = {}   # signum → handler to restore on close
        if self.level >= 2:
            self._writer = threading.Thread(target=self._write_loop,
                                            daemon=True)
            self._writer.start()

    # -- window hook (called by Runtime.run after each aux fetch) --
    def window(self, aux) -> None:
        if self.level >= 3:
            self._drain_events()
        if self.level < 2:
            return
        # All counters ride the StepAux the run loop already fetched —
        # no extra device round-trips on the hot path.
        row = [
            round((time.time() - self.t0) * 1e3, 3),
            self.rt.steps_run,
            self._delta("processed", self.rt.totals["processed"]),
            self._delta("delivered", self.rt.totals["delivered"]),
            self._delta("rejected", int(aux.n_rejected)),
            self._delta("badmsg", int(aux.n_badmsg)),
            self._delta("deadletter", int(aux.n_deadletter)),
            self._delta("mutes", int(aux.n_mutes)),
            int(aux.occ_sum), int(aux.occ_max),
            int(aux.n_muted_now), int(aux.n_overloaded_now),
            self._delta("host_processed",
                        self.rt.totals.get("host_processed", 0)),
            len(self.rt._inject_q),
            len(self.rt._host_fast_q),
        ]
        row.extend(_host_usage())
        self._rows.put(row)

    def _delta(self, key, cur) -> int:
        prev = self._prev.get(key, 0)
        self._prev[key] = cur
        return int(cur - prev)

    def _drain_events(self) -> None:
        """Pull the device event ring (engine §5b) and reset it. Rows go
        through the same writer thread, tagged for the events CSV."""
        import dataclasses as _dc

        import jax.numpy as jnp

        rt = self.rt
        st = rt.state
        counts = np.asarray(st.ev_count)
        if counts.sum() == 0:
            return
        data = np.asarray(st.ev_data)            # [3, P*EV]
        ev_cap = rt.opts.analysis_events
        now = round((time.time() - self.t0) * 1e3, 3)
        for shard, cnt in enumerate(counts):
            seg = data[:, shard * ev_cap: shard * ev_cap + int(cnt)]
            for i in range(seg.shape[1]):
                self._rows.put(("ev", [
                    now, int(seg[2, i]),
                    EVENT_NAMES.get(int(seg[0, i]), "?"),
                    int(seg[1, i])]))
        fkey = rt._freelist_key
        rt.state = _dc.replace(st, ev_count=jnp.zeros_like(st.ev_count))
        rt._freelist_key = fkey       # count reset frees no slots

    def _write_loop(self) -> None:
        opts = self.rt.opts
        ev_f = open(opts.analysis_path + ".events.csv", "w") \
            if self.level >= 3 else None
        try:
            if ev_f is not None:
                ev_f.write(",".join(EVENT_COLUMNS) + "\n")
            with open(opts.analysis_path, "w") as f:
                f.write(",".join(CSV_COLUMNS) + "\n")
                while not (self._stop.is_set() and self._rows.empty()):
                    try:
                        row = self._rows.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if isinstance(row, tuple) and row[0] == "ev":
                        ev_f.write(",".join(str(x) for x in row[1]) + "\n")
                        ev_f.flush()
                    else:
                        f.write(",".join(str(x) for x in row) + "\n")
                        f.flush()
        finally:
            if ev_f is not None:
                ev_f.close()

    # -- live-world dump (level >= 1; SIGTERM/SIGUSR1 and run() end) --
    def dump(self, out=None) -> str:
        rt = self.rt
        lines = ["=== ponyc_tpu analysis dump ==="]
        lines.append(f"steps_run={rt.steps_run} "
                     f"uptime_ms={round((time.time()-self.t0)*1e3, 1)}")
        for name in ("n_processed", "n_delivered", "n_rejected",
                     "n_badmsg", "n_deadletter", "n_mutes"):
            lines.append(f"{name}={rt.counter(name)}")
        lines.append(f"host_processed={rt.totals.get('host_processed', 0)} "
                     f"inject_queue={len(rt._inject_q)} "
                     f"fast_queue={len(rt._host_fast_q)}")
        rss_kb, cpu_ms = _host_usage()
        lines.append(f"host_rss_kb={rss_kb} host_cpu_ms={cpu_ms}")
        if self.level >= 3 and rt.state is not None:
            lines.append(
                f"events_pending={int(np.asarray(rt.state.ev_count).sum())} "
                f"events_dropped={int(np.asarray(rt.state.ev_dropped).sum())}")
        # Memory accounting (≙ USE_MEMTRACK counters, scheduler.h:52-66):
        # native pool blocks + host-heap handles.
        try:
            from . import native as _native
            allocated, recycled = _native.pool_stats()
            lines.append(f"pool_allocated={allocated} "
                         f"pool_recycled={recycled}")
        except Exception:               # native lib absent: skip silently
            pass
        heap = getattr(rt, "_heap", None)
        if heap is not None:
            s = heap.stats()
            lines.append(
                f"host_heap boxed={s['boxed']} unboxed={s['unboxed']} "
                f"live={s['live']} peak={s['peak_live']}")
        bridge = getattr(rt, "bridge", None)
        if bridge is not None:
            lines.append(f"asio_noisy={bridge.loop.noisy} "
                         f"asio_pending={bridge.loop.pending()}")
        if rt.state is not None:
            occ = np.asarray(rt.state.tail) - np.asarray(rt.state.head)
            alive = np.asarray(rt.state.alive)
            muted = np.asarray(rt.state.muted)
            lines.append(f"actors_alive={int(alive.sum())} "
                         f"muted={int(muted.sum())} "
                         f"queued_msgs={int(occ.sum())} "
                         f"deepest_queue={int(occ.max())}")
            # Per-cohort queue depth summary (≙ per-actor tag rows in the
            # reference's dump; cohorts are the TPU grouping).
            for cohort in rt.program.cohorts:
                cols = np.asarray(cohort.slot_to_gid(
                    np.arange(cohort.capacity)), np.int64)
                co = occ[cols]
                lines.append(
                    f"  cohort {cohort.atype.__name__}: "
                    f"cap={cohort.capacity} queued={int(co.sum())} "
                    f"max={int(co.max()) if co.size else 0} "
                    f"muted={int(muted[cols].sum())}")
        text = "\n".join(lines)
        print(text, file=out or sys.stderr)
        return text

    def install_signal_dump(self, signums=(signal.SIGTERM,
                                           signal.SIGUSR1)) -> None:
        """Install dump-on-signal handlers (main thread only; ≙ the
        reference installing its SIGTERM handler when analysis > 0).
        Previous handlers are restored by close()."""
        for s in signums:
            try:
                prev = signal.signal(s, lambda *_: self.dump())
            except ValueError:   # not the main thread: skip
                return
            self._saved_handlers.setdefault(s, prev)

    def summary(self) -> None:
        if self.level >= 1:
            self.dump()

    def close(self) -> None:
        self._stop.set()
        if self._writer is not None:
            self._writer.join(timeout=2.0)
            self._writer = None
        # Restore pre-attach signal dispositions so a torn-down runtime
        # neither swallows SIGTERM nor stays alive via handler closures.
        for s, prev in self._saved_handlers.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._saved_handlers.clear()


def attach(rt) -> Analysis:
    """Create and register the Analysis hook on a runtime."""
    a = Analysis(rt)
    rt._analysis = a
    if a.level >= 1:
        a.install_signal_dump()
    return a


def chrome_trace(csv_path: str, out_path: str,
                 events_path: Optional[str] = None) -> str:
    """Convert the analysis CSVs into a Chrome-trace / Perfetto JSON.

    ≙ the reference's DTrace/SystemTap scripts turning USDT probes into
    a timeline (examples/dtrace/telemetry.d — SURVEY §5's third tracing
    mechanism): the step-window CSV becomes counter tracks (queued
    messages, deepest mailbox, muted/overloaded actors, throughput per
    window) and the level-3 event CSV becomes instant events
    (MUTE/UNMUTE/OVERLOAD/SPAWN/DESTROY/ERROR, one thread lane per
    class) — load the output in chrome://tracing or ui.perfetto.dev.
    `events_path` defaults to `<csv_path>.events.csv` when that file
    exists."""
    import csv as _csv
    import json
    import os

    pid = 1
    out = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "ponyc_tpu runtime"}},
        {"ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
         "args": {"name": "step windows"}},
    ]
    with open(csv_path) as f:
        for row in _csv.DictReader(f):
            ts = float(row["time_ms"]) * 1e3          # µs
            for track, cols in (
                    ("queue", {"queued": "occ_sum",
                               "deepest": "occ_max"}),
                    ("actors", {"muted": "muted_now",
                                "overloaded": "overloaded_now"}),
                    ("window throughput", {"processed": "processed",
                                           "delivered": "delivered"}),
                    ("anomalies", {"rejected": "rejected",
                                   "badmsg": "badmsg",
                                   "deadletter": "deadletter"})):
                out.append({"ph": "C", "pid": pid, "ts": ts,
                            "name": track,
                            "args": {k: int(row[c])
                                     for k, c in cols.items()}})
    if events_path is None:
        cand = csv_path + ".events.csv"
        events_path = cand if os.path.exists(cand) else None
    if events_path is not None:
        tids = {}
        with open(events_path) as f:
            for row in _csv.DictReader(f):
                name = row["event"]
                tid = tids.setdefault(name, len(tids) + 1)
                out.append({"ph": "i", "pid": pid, "tid": tid, "s": "t",
                            "ts": float(row["time_ms"]) * 1e3,
                            "name": f"{name} a{row['actor']}",
                            "args": {"actor": int(row["actor"]),
                                     "step": int(row["step"])}})
        for name, tid in tids.items():
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": f"events:{name}"}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": out,
                   "displayTimeUnit": "ms"}, f)
    return out_path
