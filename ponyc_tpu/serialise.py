"""World serialisation: checkpoint, restore and re-layout a running
actor world.

≙ the reference's serialisation subsystem (src/libponyrt/gc/serialise.c:
`pony_serialise`/`pony_deserialise` flatten an object graph to an
offset-encoded buffer using per-type trace hooks; `packages/serialise`
is the stdlib surface). The reference has no built-in checkpoint/resume
(SURVEY.md §5) — serialisation is its building block, and here it is
promoted to a first-class feature: the *entire world* (device SoA state,
mailboxes in flight, host-actor state, allocator freelists, counters) is
one snapshot, because the TPU runtime's whole point is that world state
is a single pytree.

Type identity is structural: a fingerprint over cohort order, field
specs and behaviour signatures (≙ the descriptor table registered at
pony_start, start.c:286-292, which makes serialised ids stable between
runs of the same binary). Restoring into a runtime whose fingerprint
differs is an error — the same guarantee the reference gets from "same
binary". GEOMETRY (capacities, mailbox/spill/blob/shard sizes) is NOT
part of identity since format v3: a snapshot restores into a different
layout by re-laying-out the SoA arrays (see `restore` below) — the
lever for elastic resize and fast-start benches (ROADMAP item 5; the
PGAS actor-runtime paper's redistribution, PAPERS.md).

Snapshots are written at host boundaries (between jitted steps), where
device state is quiescent-consistent — no in-flight step, exactly like
serialising between behaviours in Pony.

Format v3: one .npz holding every state array BY NAME (``st.<field>``,
``st.buf.<Type>``, ``st.ts.<Type>.<field>``, queue lanes ``q.*``) plus
a JSON header carrying the geometry descriptor, host-side runtime
state, and a per-array + header CRC32 table. Writes go tmp → flush →
fsync → atomic rename, so a crash mid-flush can only ever leave a
garbage ``.tmp`` beside an intact previous snapshot, never a torn
snapshot under the real name. `Checkpointer` (below) maintains a
bounded ring of such snapshots on a cadence, driven by the run loop.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import hashlib
import io
import json
import os
import queue as _queue
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .errors import ERROR_CODES

# v2 (round 5): adds the host fast-lane queue (fastq_tgt/fastq_words).
# v3 (round 13): arrays stored BY NAME with a geometry descriptor and
# per-array + header checksums, so snapshots (a) survive layout-
# preserving refactors of RtState field order, (b) restore into a
# DIFFERENT geometry, (c) detect truncation/bit-rot loudly, and (d)
# carry the PR 6/7 telemetry state (trace side-lanes + span ring,
# profiler lanes, error counters) — a restored world keeps its
# telemetry. v1/v2 snapshots restore through the legacy index path
# (same geometry only, telemetry lanes as saved); UNKNOWN future
# versions raise SnapshotFormatError, never a silent partial restore.
FORMAT_VERSION = 3
_ACCEPTED_FORMATS = (1, 2, 3)

_CKPT_SUFFIX = ".ckpt"


class FingerprintMismatch(RuntimeError):
    """Snapshot was taken by a structurally different program."""


class SnapshotFormatError(FingerprintMismatch):
    """Snapshot written by an unknown FUTURE format version — refuse
    loudly instead of silently dropping lanes we cannot understand."""

    code = ERROR_CODES["SnapshotFormatError"]


class SnapshotCorruptError(RuntimeError):
    """Snapshot failed checksum/structure verification (truncated file,
    bit flip, torn write) — the coded replacement for a raw numpy/zlib
    traceback; the supervisor falls back past these."""

    code = ERROR_CODES["SnapshotCorruptError"]


class SnapshotGeometryError(RuntimeError):
    """A geometry-changing restore found occupancy that does not fit
    the new layout (live actor above the new capacity, mailbox deeper
    than the new ring, more live blobs than pool slots, ...)."""

    code = ERROR_CODES["SnapshotGeometryError"]


def fingerprint(program, geometry: bool = False) -> str:
    """Structural hash of the program (≙ the per-type descriptor table
    identity; serialise.c relies on same-binary type ids): cohort order,
    host placement, field specs, behaviour signatures. `geometry=True`
    additionally folds in capacities and the shard count — the v2-era
    identity, kept for exact-layout assertions."""
    h = hashlib.sha256()
    for cohort in program.cohorts:
        atype = cohort.atype
        h.update(atype.__name__.encode())
        if geometry:
            h.update(str(cohort.capacity).encode())
        h.update(b"H" if cohort.host else b"D")
        for fname, spec in sorted(atype.field_specs.items()):
            h.update(fname.encode())
            h.update(spec.__name__.encode())
        for b in cohort.behaviours:
            h.update(b.name.encode())
            h.update(str(b.global_id).encode())
            for spec in b.arg_specs:
                h.update(spec.__name__.encode())
    # NOTE: geometry=True reproduces the v1/v2 fingerprint byte-for-byte
    # (capacity folded per cohort, nothing else) so legacy snapshots
    # still verify; the shard count rides the v3 geometry descriptor.
    return h.hexdigest()[:32]


def _opts_dict(opts) -> Dict[str, Any]:
    return dataclasses.asdict(opts)


# ---------------------------------------------------------------------------
# array naming: the v3 snapshot stores every RtState leaf by a stable
# name derived from the dataclass field (+ dict key), not by flatten
# index — the property the geometry-changing restore stands on.

def _named_state_arrays(state) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for f in dataclasses.fields(state):
        v = getattr(state, f.name)
        if f.name == "type_state":
            for tname, fields in v.items():
                for fname, arr in fields.items():
                    out[f"st.ts.{tname}.{fname}"] = arr
        elif isinstance(v, dict):
            for tname, arr in v.items():
                out[f"st.{f.name}.{tname}"] = arr
        else:
            out[f"st.{f.name}"] = v
    return out


def _state_from_named(template, arrays: Dict[str, np.ndarray]):
    """Rebuild an RtState from named arrays into `template`'s exact
    geometry (the same-layout fast path): every template leaf must have
    a shape-identical named twin."""
    kw: Dict[str, Any] = {}
    for f in dataclasses.fields(template):
        v = getattr(template, f.name)
        if f.name == "type_state":
            kw[f.name] = {
                tname: {fname: _take(arrays, f"st.ts.{tname}.{fname}", arr)
                        for fname, arr in fields.items()}
                for tname, fields in v.items()}
        elif isinstance(v, dict):
            kw[f.name] = {tname: _take(arrays, f"st.{f.name}.{tname}", arr)
                          for tname, arr in v.items()}
        else:
            kw[f.name] = _take(arrays, f"st.{f.name}", v)
    return dataclasses.replace(template, **kw)


def _is_word_table(name: str) -> bool:
    from .runtime.state import PACKED_WORD_FIELDS
    return any(name == f"st.{f}" or name.startswith(f"st.{f}.")
               for f in PACKED_WORD_FIELDS)


def pack_snapshot_arrays(arrays: Dict[str, np.ndarray],
                         ) -> Dict[str, np.ndarray]:
    """The snapshot spelling of the mailbox bandwidth diet
    (ops/megakernel.py): every int32 word table (mailbox rings, spill
    words, trace lanes — state.PACKED_WORD_FIELDS) is stored as an
    int16 lane plane (`<name>.lo16`) plus an int32 escape plane
    (`<name>.esc32`). The codec is lossless, so a packed snapshot
    restores bit-identically; the escape plane compresses to almost
    nothing when payloads are narrow (savez_compressed). `_load_raw`
    decodes transparently — readers never see the planes."""
    from .ops.megakernel import pack_words_np
    out: Dict[str, np.ndarray] = {}
    for name, a in arrays.items():
        if _is_word_table(name) and a.dtype == np.int32:
            lo16, esc32 = pack_words_np(a)
            out[name + ".lo16"] = lo16
            out[name + ".esc32"] = esc32
        else:
            out[name] = a
    return out


def _unpack_snapshot_arrays(arrays: Dict[str, np.ndarray],
                            ) -> Dict[str, np.ndarray]:
    """Decode `pack_snapshot_arrays` planes back into int32 tables
    (no-op for unpacked snapshots — v3 stays one format, packing is an
    encoding choice per save)."""
    from .ops.megakernel import unpack_words_np
    out: Dict[str, np.ndarray] = {}
    for name, a in arrays.items():
        if name.endswith(".lo16"):
            base = name[:-len(".lo16")]
            esc = arrays.get(base + ".esc32")
            if esc is None:
                raise SnapshotCorruptError(
                    f"packed array {base!r} is missing its escape "
                    "plane")
            out[base] = unpack_words_np(a, esc)
        elif name.endswith(".esc32") and (name[:-len(".esc32")]
                                          + ".lo16") in arrays:
            continue
        else:
            out[name] = a
    return out


# State fields added after a snapshot format was already in the wild:
# a same-layout restore treats a missing named twin as zeros (cumulative
# telemetry starts over) instead of rejecting the whole snapshot.
_ZERO_IF_ABSENT = frozenset({"st.phase_cost"})


def _take(arrays, name, like):
    arr = arrays.get(name)
    if arr is None and name in _ZERO_IF_ABSENT:
        return jnp.zeros(like.shape, like.dtype)
    if arr is None:
        raise FingerprintMismatch(f"snapshot is missing array {name!r}")
    if tuple(arr.shape) != tuple(like.shape):
        raise FingerprintMismatch(
            f"array {name!r} shape {tuple(arr.shape)} != "
            f"{tuple(like.shape)}")
    return jnp.asarray(arr, like.dtype)


# ---------------------------------------------------------------------------
# capture / write / save

def capture(rt) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Snapshot the world into host memory: (header, arrays). Splitting
    capture from `write_snapshot` lets the Checkpointer run the
    device→host copy on the run-loop thread (started async, so the
    wait overlaps any in-flight transfer) while compression/fsync ride
    the background writer thread."""
    if rt.state is None:
        raise RuntimeError("runtime not started")
    from .runtime.state import geometry_descriptor
    named = _named_state_arrays(rt.state)
    for leaf in named.values():       # start every D2H copy in motion
        try:
            leaf.copy_to_host_async()
        except AttributeError:
            pass
    # np.array (not asarray): device_get on the CPU backend returns a
    # zero-copy VIEW of the device buffer, which the next window's
    # donation would reuse while the background writer still reads it —
    # the snapshot must own its bytes.
    arrays: Dict[str, np.ndarray] = {
        k: np.array(jax.device_get(v)) for k, v in named.items()}
    inject = list(rt._inject_q)
    w1 = 1 + rt.opts.msg_words + rt.opts.trace_lanes
    arrays["q.inject_tgt"] = np.asarray([t for t, _ in inject], np.int32)
    arrays["q.inject_words"] = (np.stack([w for _, w in inject])
                                if inject else np.zeros((0, w1), np.int32))
    # Fast-lane entries are (target, words[, trace_ctx]); the host
    # trace bookkeeping (tracing.Tracer) is per-process and not
    # snapshotted — a restored queue's messages deliver untraced.
    fast = list(rt._host_fast_q)
    arrays["q.fastq_tgt"] = np.asarray([e[0] for e in fast], np.int32)
    arrays["q.fastq_words"] = (np.stack([e[1] for e in fast])
                               if fast else np.zeros((0, w1), np.int32))
    header = {
        "format": FORMAT_VERSION,
        "time": time.time(),
        "fingerprint": fingerprint(rt.program),
        "fingerprint_geo": fingerprint(rt.program, geometry=True),
        "opts": _opts_dict(rt.opts),
        "geometry": geometry_descriptor(rt.program, rt.opts),
        "free": rt._free,
        "host_state": {str(k): v for k, v in rt._host_state.items()},
        "totals": dict(rt.totals),
        "last_counters": rt._last_counters,
        "steps_run": rt.steps_run,
        "exit_code": rt._exit_code,
        "noisy": rt._noisy,
        # Host-owned device-blob handles (GC roots for the blob sweep):
        # without them a restored world's first gc() would sweep blobs
        # the host legitimately holds.
        "host_blobs": sorted(rt._host_blobs),
        # PR 4/6/7 host-side telemetry residue, so a restored world
        # keeps its operational history (satellite: snapshot format v3).
        "host_errors": {str(k): v for k, v in rt._host_errors.items()},
        "host_error_locs": {str(k): v
                            for k, v in rt._host_error_locs.items()},
        "beh_host_runs": {str(k): int(v)
                          for k, v in rt._beh_host_runs.items()},
        "error_counts": [[cls, int(code), int(n)]
                         for (cls, code), n in sorted(
                             rt._error_counts.items())],
        "idle_boundaries": rt._idle_boundaries,
        "last_gc_step": rt._last_gc_step,
    }
    return header, arrays


def write_snapshot(header: Dict[str, Any], arrays: Dict[str, np.ndarray],
                   path: str, compress: bool = True) -> int:
    """Checksum + serialise + durably write a captured snapshot:
    per-array CRC32s and a header CRC land in the file (corruption
    detection), the bytes are flushed AND fsync'd before the atomic
    rename (crash mid-flush leaves the previous snapshot intact).
    Returns the byte size written."""
    header = dict(header)
    header["arrays"] = {
        k: {"crc": zlib.crc32(np.ascontiguousarray(a).tobytes()),
            "shape": list(a.shape), "dtype": str(a.dtype)}
        for k, a in arrays.items()}
    hbytes = json.dumps(header).encode()
    buf = io.BytesIO()
    savez = np.savez_compressed if compress else np.savez
    savez(buf, header=np.frombuffer(hbytes, np.uint8),
          header_crc=np.asarray([zlib.crc32(hbytes)], np.uint32),
          **arrays)
    data = buf.getvalue()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        # Two-part write with a chaos point between them: the fault-
        # injection harness (testing.py) can SIGKILL the process mid-
        # flush here, proving the tmp+fsync+rename discipline means a
        # torn write can never surface under the real name.
        half = len(data) // 2
        f.write(data[:half])
        _chaos_point("snapshot-mid-flush")
        f.write(data[half:])
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:        # directory durability: the rename itself must survive
        dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    return len(data)


def _chaos_point(point: str) -> None:
    from . import testing
    testing.chaos.fire(point)


def save(rt, path: str, packed: bool = False) -> None:
    """Snapshot the full world to `path` (.npz). Call between runs/steps
    only (any queued-but-uninjected host sends are included).
    `packed=True` stores the word tables in the narrow-dtype form
    (pack_snapshot_arrays) — restore is transparent and bit-exact."""
    header, arrays = capture(rt)
    if packed:
        arrays = pack_snapshot_arrays(arrays)
    write_snapshot(header, arrays, path)


# ---------------------------------------------------------------------------
# loading / verification

_CORRUPT_EXC = (OSError, EOFError, ValueError, KeyError, zlib.error)


def _load_raw(path: str):
    """Open + structurally verify a snapshot: returns (header, arrays
    dict). Every member read is CRC-checked (the zip layer's own CRC
    plus our per-array table); any truncation/bit-flip raises the coded
    SnapshotCorruptError, an unknown future format SnapshotFormatError."""
    import zipfile
    try:
        with np.load(path, allow_pickle=False) as z:
            try:
                hbytes = bytes(z["header"])
                header = json.loads(hbytes.decode())
            except _CORRUPT_EXC + (json.JSONDecodeError,
                                   UnicodeDecodeError) as e:
                raise SnapshotCorruptError(
                    f"{path}: snapshot header unreadable ({e})") from e
            if "header_crc" in z.files:
                if int(z["header_crc"][0]) != zlib.crc32(hbytes):
                    raise SnapshotCorruptError(
                        f"{path}: header checksum mismatch")
            fmt = header.get("format")
            if fmt not in _ACCEPTED_FORMATS:
                raise SnapshotFormatError(
                    f"{path}: snapshot format {fmt} not in "
                    f"{_ACCEPTED_FORMATS} — written by a newer build? "
                    "(refusing to restore partially)")
            arrays: Dict[str, np.ndarray] = {}
            crcs = header.get("arrays", {})
            for name in z.files:
                if name in ("header", "header_crc"):
                    continue
                try:
                    arr = z[name]
                except _CORRUPT_EXC as e:
                    raise SnapshotCorruptError(
                        f"{path}: array {name!r} unreadable ({e})") from e
                meta = crcs.get(name)
                if meta is not None:
                    if (list(arr.shape) != meta["shape"]
                            or str(arr.dtype) != meta["dtype"]
                            or zlib.crc32(np.ascontiguousarray(arr)
                                          .tobytes()) != meta["crc"]):
                        raise SnapshotCorruptError(
                            f"{path}: array {name!r} failed its "
                            "checksum (bit flip or torn write)")
                arrays[name] = arr
            missing = set(crcs) - set(arrays)
            if missing:
                raise SnapshotCorruptError(
                    f"{path}: snapshot truncated — missing arrays "
                    f"{sorted(missing)[:4]}")
            # Narrow-dtype stored snapshots (save(packed=True)) decode
            # here, AFTER the CRC table verified the stored planes —
            # every reader downstream sees plain int32 word tables.
            return header, _unpack_snapshot_arrays(arrays)
    except (zipfile.BadZipFile, *_CORRUPT_EXC) as e:
        if isinstance(e, (SnapshotCorruptError, SnapshotFormatError)):
            raise
        if isinstance(e, OSError) and not os.path.exists(path):
            raise
        raise SnapshotCorruptError(
            f"{path}: not a readable snapshot ({e})") from e


def verify_snapshot(path: str) -> Dict[str, Any]:
    """Full integrity check (header + every array CRC); returns the
    header. Raises SnapshotCorruptError / SnapshotFormatError."""
    header, _arrays = _load_raw(path)
    return header


# ---------------------------------------------------------------------------
# restore

def restore(rt, path: str, opts=None) -> None:
    """Load a snapshot into a started runtime with the same program
    STRUCTURE (actor classes, behaviours, declaration order).

    The runtime's geometry — per-cohort capacity, mailbox_cap,
    spill_cap, blob_slots/words, mesh_shards, telemetry lane sizes —
    may differ from the snapshot's: the SoA arrays are re-laid-out
    (actor ids remapped slot-for-slot, mailbox rings re-rung, parked
    spill entries re-queued through the inject lane at their FIFO
    priority, blob handles re-encoded), with occupancy validated
    against the new layout (SnapshotGeometryError when it cannot fit).
    `opts` is an optional cross-check: the RuntimeOptions the TARGET
    runtime is expected to be running (≙ spelling the new geometry at
    the restore site); a mismatch with rt.opts raises ValueError."""
    if rt.state is None:
        raise RuntimeError("call start() before restore()")
    if opts is not None:
        # start() rewrites the "auto" fields (tuning.resolve /
        # resolve_quiesce_interval) — compare everything else.
        auto = {"quiesce_interval", "delivery", "pallas", "pallas_fused"}
        a = {k: v for k, v in _opts_dict(opts).items() if k not in auto}
        b = {k: v for k, v in _opts_dict(rt.opts).items()
             if k not in auto}
        if a != b:
            raise ValueError(
                "restore(opts=...) names a different geometry than the "
                "target runtime was started with — build the Runtime "
                "with those options first (geometry is fixed at "
                "start())")
    header, arrays = _load_raw(path)
    if header["format"] < 3:
        _restore_legacy(rt, header, arrays)
        return
    fp = fingerprint(rt.program)
    if header["fingerprint"] != fp:
        raise FingerprintMismatch(
            "snapshot was taken by a structurally different program "
            f"({header['fingerprint']} != {fp})")
    from .runtime.state import geometry_descriptor
    same_geometry = (header["geometry"]
                     == geometry_descriptor(rt.program, rt.opts))
    if same_geometry:
        state = _state_from_named(rt.state, arrays)
        if rt.mesh is not None:
            from .parallel.mesh import shard_state
            state = shard_state(state, rt.mesh)
        rt.state = state
        _restore_queues_exact(rt, arrays)
        _restore_host_side(rt, header)
        rt._free = {k: [int(x) for x in v]
                    for k, v in header["free"].items()}
    else:
        _restore_relayout(rt, header, arrays)


def _restore_queues_exact(rt, arrays) -> None:
    rt._inject_q.clear()
    tgts, words = arrays["q.inject_tgt"], arrays["q.inject_words"]
    for i in range(len(tgts)):
        rt._inject_q.append((int(tgts[i]), words[i]))
    rt._host_fast_q.clear()
    ftgts, fwords = arrays["q.fastq_tgt"], arrays["q.fastq_words"]
    for i in range(len(ftgts)):
        rt._host_fast_q.append((int(ftgts[i]), fwords[i], None))


def _restore_host_side(rt, header) -> None:
    import collections
    rt._host_state = {int(k): v for k, v in header["host_state"].items()}
    rt._host_blobs = set(int(h) for h in header.get("host_blobs", ()))
    rt.totals.clear()
    rt.totals.update(header["totals"])
    rt._last_counters = dict(header["last_counters"])
    rt.steps_run = int(header["steps_run"])
    rt._exit_code = int(header["exit_code"])
    rt._noisy = int(header["noisy"])
    rt._host_errors = {int(k): v
                       for k, v in header.get("host_errors", {}).items()}
    rt._host_error_locs = {
        int(k): v for k, v in header.get("host_error_locs", {}).items()}
    rt._beh_host_runs = collections.Counter(
        {int(k): int(v)
         for k, v in header.get("beh_host_runs", {}).items()})
    rt._error_counts = collections.Counter(
        {(cls, int(code)): int(n)
         for cls, code, n in header.get("error_counts", ())})
    rt._idle_boundaries = int(header.get("idle_boundaries", 0))
    rt._last_gc_step = int(header.get("last_gc_step", 0))


def _restore_legacy(rt, header, arrays) -> None:
    """v1/v2 snapshots: arrays stored by flatten INDEX — restorable
    into the exact same geometry only (the pre-v3 contract). v1
    restores with an empty fast queue; telemetry lanes restore as
    saved (zero-length when the snapshot was taken without them)."""
    fp = fingerprint(rt.program, geometry=True)
    if header["fingerprint"] != fp:
        raise FingerprintMismatch(
            "v<3 snapshot was taken by a structurally different program "
            f"or geometry ({header['fingerprint']} != {fp}; legacy "
            "snapshots cannot re-layout)")
    flat, treedef = jax.tree_util.tree_flatten(rt.state)
    if header["n_state_leaves"] != len(flat):
        raise FingerprintMismatch("state leaf count mismatch")
    new_flat = []
    for i, leaf in enumerate(flat):
        arr = arrays[f"state_{i}"]
        if arr.shape != leaf.shape:
            raise FingerprintMismatch(
                f"state leaf {i} shape {arr.shape} != {leaf.shape} "
                "(options geometry must match a legacy snapshot)")
        new_flat.append(jnp.asarray(arr, leaf.dtype))
    state = jax.tree_util.tree_unflatten(treedef, new_flat)
    if rt.mesh is not None:
        from .parallel.mesh import shard_state
        state = shard_state(state, rt.mesh)
    rt.state = state
    rt._inject_q.clear()
    tgts, words = arrays["inject_tgt"], arrays["inject_words"]
    for i in range(len(tgts)):
        rt._inject_q.append((int(tgts[i]), words[i]))
    rt._host_fast_q.clear()
    if "fastq_tgt" in arrays:      # absent in pre-fast-lane snapshots
        ftgts, fwords = arrays["fastq_tgt"], arrays["fastq_words"]
        for i in range(len(ftgts)):
            rt._host_fast_q.append((int(ftgts[i]), fwords[i], None))
    _restore_host_side(rt, header)
    rt._free = {k: [int(x) for x in v] for k, v in header["free"].items()}


# ---------------------------------------------------------------------------
# geometry-changing restore (the re-layout pass)

class _OldLayout:
    """Vectorised slot/gid/col math for the SNAPSHOT's geometry,
    reconstructed from the header's descriptor (mirrors program.Cohort
    without needing the old Program object)."""

    def __init__(self, g: Dict[str, Any]):
        self.shards = int(g["shards"])
        self.n_local = int(g["n_local"])
        self.total = int(g["total"])
        self.mailbox_cap = int(g["mailbox_cap"])
        self.msg_words = int(g["msg_words"])
        self.trace_lanes = int(g["trace_lanes"])
        self.spill_cap = int(g["spill_cap"])
        self.mute_slots = int(g["mute_slots"])
        self.blob_slots = int(g["blob_slots"])
        self.blob_words = int(g["blob_words"])
        self.cohorts = g["cohorts"]

    def slot_to_gid(self, co, slot):
        slot = np.asarray(slot, np.int64)
        shard = slot % self.shards
        row = int(co["local_start"]) + slot // self.shards
        return shard * self.n_local + row

    def slot_to_col(self, co, slot):
        slot = np.asarray(slot, np.int64)
        shard = slot % self.shards
        return (shard * int(co["local_capacity"])
                + slot // self.shards)


def _restore_relayout(rt, header, Z: Dict[str, np.ndarray]) -> None:
    """Re-lay-out a v3 snapshot into the target runtime's (different)
    geometry. The actor identity that survives is the cohort SLOT
    (spawn order); everything derived from layout — global ids, state
    columns, ring positions, spill parking, blob handles — is remapped.
    Parked spill entries re-enter through the host inject lane, which
    delivers at a strictly higher priority than fresh sends
    (delivery.py level 1 < emission levels), so per-edge FIFO is
    preserved exactly; the differential corpus crosses this boundary
    (tests/test_durability.py)."""
    from .ops import pack
    from .runtime import gc as gc_mod
    from .runtime.state import N_PHASES, QW_BUCKETS, init_state

    prog, opts = rt.program, rt.opts
    old = _OldLayout(header["geometry"])
    p_old, nl_old, n_old = old.shards, old.n_local, old.total
    p_new, n_new = prog.shards, prog.total

    old_cohorts = {c["name"]: c for c in old.cohorts}
    if [c["name"] for c in old.cohorts] != \
            [c.atype.__name__ for c in prog.cohorts]:
        raise FingerprintMismatch("cohort order/name mismatch")
    for c in prog.cohorts:
        if old_cohorts[c.atype.__name__]["msg_words"] != c.msg_words:
            raise SnapshotGeometryError(
                f"cohort {c.atype.__name__} message width changed "
                f"({old_cohorts[c.atype.__name__]['msg_words']} -> "
                f"{c.msg_words}): msg_words must cover the cohort's "
                "widest behaviour on both sides")

    alive_o = Z["st.alive"]
    head_o = Z["st.head"].astype(np.int64)
    tail_o = Z["st.tail"].astype(np.int64)

    # ---- actor id map (slot-preserving) + occupancy-fit validation ----
    gid_map = np.full((n_old,), -1, np.int64)
    kept_pairs: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
    for c in prog.cohorts:
        co = old_cohorts[c.atype.__name__]
        slots = np.arange(int(co["capacity"]), dtype=np.int64)
        old_gids = old.slot_to_gid(co, slots)
        keep = slots < c.capacity
        dropped = old_gids[~keep]
        if dropped.size:
            occ_d = tail_o[dropped] - head_o[dropped]
            bad = alive_o[dropped] | (occ_d != 0)
            if bad.any():
                raise SnapshotGeometryError(
                    f"cohort {c.atype.__name__}: slot "
                    f"{int(slots[~keep][np.argmax(bad)])} is live "
                    f"(or has queued mail) but the new capacity is "
                    f"{c.capacity} — occupancy does not fit")
        new_gids = np.asarray(c.slot_to_gid(slots[keep]), np.int64)
        gid_map[old_gids[keep]] = new_gids
        kept_pairs[c.atype.__name__] = (slots[keep], old_gids[keep],
                                        new_gids)

    def map_gids(v: np.ndarray) -> np.ndarray:
        v = np.asarray(v, np.int64)
        out = v.copy()
        inw = (v >= 0) & (v < n_old)
        out[inw] = gid_map[v[inw]]
        return out

    # ---- blob slot/handle map ----
    bs_old, bs_new = old.blob_slots, opts.blob_slots
    bw_old, bw_new = old.blob_words, opts.blob_words
    nbs_old, nbs_new = p_old * bs_old, p_new * bs_new
    blob_slot_map = np.full((max(nbs_old, 1),), -1, np.int64)
    used_o = Z.get("st.blob_used", np.zeros((nbs_old,), bool))
    gen_o = Z.get("st.blob_gen", np.zeros((nbs_old,), np.int32))
    if bs_old and used_o.any():
        if bs_new == 0:
            raise SnapshotGeometryError(
                "snapshot holds live blobs but the target runtime has "
                "blob_slots=0")
        len_o = Z["st.blob_len"]
        if bw_new < bw_old and (len_o[used_o] > bw_new).any():
            raise SnapshotGeometryError(
                f"a live blob is longer ({int(len_o[used_o].max())} "
                f"words) than the new blob_words={bw_new}")
        fill = np.zeros((p_new,), np.int64)
        for g in np.flatnonzero(used_o):
            want = (g // bs_old) % p_new
            shard = next((s for s in [want] + list(range(p_new))
                          if fill[s] < bs_new), None)
            if shard is None:
                raise SnapshotGeometryError(
                    f"{int(used_o.sum())} live blobs do not fit "
                    f"{p_new}x{bs_new} pool slots")
            blob_slot_map[g] = shard * bs_new + fill[shard]
            fill[shard] += 1

    def map_handles(h: np.ndarray) -> np.ndarray:
        h = np.asarray(h, np.int64)
        out = h.copy()
        pos = h >= 0
        if not pos.any():
            return out
        slots = h[pos] & ((1 << pack.BLOB_GEN_SHIFT) - 1)
        gens = (h[pos] >> pack.BLOB_GEN_SHIFT) & pack.BLOB_GEN_MASK
        ok = slots < max(nbs_old, 1)
        slots_c = np.where(ok, slots, 0)
        valid = (ok & used_o[slots_c]
                 & ((gen_o[slots_c] & pack.BLOB_GEN_MASK) == gens))
        ns = blob_slot_map[slots_c]
        # A stale/invalid handle maps to null (-1): it would have read
        # null in the old world too (generation mismatch), so this is
        # semantics-preserving, never data loss.
        out[pos] = np.where(valid & (ns >= 0),
                            (gens << pack.BLOB_GEN_SHIFT) | ns, -1)
        return out

    # ---- payload-word remap masks (ref/blob argument positions) ----
    mw_wide = max(old.msg_words, opts.msg_words)
    ref_mask = gc_mod.build_ref_arg_mask(prog, mw_wide)
    blob_mask = gc_mod.build_blob_arg_mask(prog, mw_wide)

    def remap_payload(words2d: np.ndarray) -> np.ndarray:
        """[M, 1+W] message block: word0 = behaviour gid; remap every
        ref-typed and blob-typed argument word in place."""
        if words2d.size == 0:
            return words2d
        g = words2d[:, 0].astype(np.int64)
        w = words2d.shape[1] - 1
        ok = (g >= 0) & (g < ref_mask.shape[0])
        gc_ = np.where(ok, g, 0)
        rm = ref_mask[gc_, :w] & ok[:, None]
        bm = blob_mask[gc_, :w] & ok[:, None]
        pay = words2d[:, 1:]
        if rm.any():
            pay[rm] = map_gids(pay[rm]).astype(pay.dtype)
        if bm.any():
            pay[bm] = map_handles(pay[bm]).astype(pay.dtype)
        words2d[:, 1:] = pay
        return words2d

    # ---- fresh template in the NEW geometry (writable host copies:
    # np.asarray of a jax buffer is a read-only view) ----
    tmpl = jax.tree.map(lambda x: np.array(x), init_state(prog, opts))
    st: Dict[str, Any] = {f.name: getattr(tmpl, f.name)
                          for f in dataclasses.fields(tmpl)}

    # per-actor scatter columns
    for name in ("alive", "muted", "mute_age", "mute_ovf", "pinned",
                 "pressured", "last_error", "last_error_loc"):
        dst = st[name].copy()
        src = Z[f"st.{name}"]
        for _slots, og, ng in kept_pairs.values():
            dst[ng] = src[og]
        st[name] = dst

    # ---- mailbox re-ring (head=0, tail=occ in the new ring) ----
    c_old, c_new = old.mailbox_cap, opts.mailbox_cap
    head_n = np.zeros((n_new,), np.int64)
    tail_n = np.zeros((n_new,), np.int64)
    new_bufs: Dict[str, np.ndarray] = {}
    new_qw: Dict[str, np.ndarray] = dict(st["qwait_enq"])
    new_tb: Dict[str, np.ndarray] = dict(st["trace_buf"])
    for c in prog.cohorts:
        name = c.atype.__name__
        co = old_cohorts[name]
        slots, og, ng = kept_pairs[name]
        occ = tail_o[og] - head_o[og]
        if (occ > c_new).any():
            raise SnapshotGeometryError(
                f"cohort {name}: a mailbox holds {int(occ.max())} "
                f"messages but the new mailbox_cap is {c_new}")
        tail_n[ng] = occ
        old_cols = old.slot_to_col(co, slots)
        new_cols = np.asarray(c.slot_to_col(slots), np.int64)
        buf_o = Z[f"st.buf.{name}"]
        buf_n = st["buf"][name].copy()
        qw_o = Z.get(f"st.qwait_enq.{name}")
        tb_o = Z.get(f"st.trace_buf.{name}")
        for k in range(min(c_old, int(occ.max(initial=0)))):
            m = occ > k
            if not m.any():
                break
            src = ((head_o[og[m]] + k) % c_old).astype(np.int64)
            oc, nc = old_cols[m], new_cols[m]
            block = buf_o[src, :, oc]            # [M, w1c]
            buf_n[k][:, nc] = remap_payload(block.copy()).T
            if name in new_qw and qw_o is not None:
                new_qw[name][k][nc] = qw_o[src, oc]
            if name in new_tb and tb_o is not None:
                new_tb[name][k][:, nc] = tb_o[src, :, oc].T
        new_bufs[name] = buf_n
    st["buf"] = new_bufs
    st["qwait_enq"] = new_qw
    st["trace_buf"] = new_tb
    st["head"] = head_n.astype(st["head"].dtype)
    st["tail"] = tail_n.astype(st["tail"].dtype)

    # ---- mute receiver-set re-slot (values are gids; position is
    # ref % K, which moves when ids move — collisions go conservative
    # via the sticky overflow bit, never an early unmute) ----
    k_new = opts.mute_slots
    mr_o = Z["st.mute_refs"]
    mr_n = st["mute_refs"]
    ovf = st["mute_ovf"]
    for g in np.flatnonzero((mr_o >= 0).any(axis=0)):
        ng = gid_map[g]
        if ng < 0:
            continue
        for r in mr_o[:, g]:
            if r < 0:
                continue
            nr = int(map_gids(np.asarray([r]))[0])
            if nr < 0:
                continue
            sl = nr % k_new
            if mr_n[sl, ng] in (-1, nr):
                mr_n[sl, ng] = nr
            else:
                ovf[ng] = True
    st["mute_refs"], st["mute_ovf"] = mr_n, ovf

    # ---- blob pool scatter ----
    if bs_old and bs_new:
        data_o = Z["st.blob_data"]
        len_o = Z["st.blob_len"]
        for g in np.flatnonzero(used_o):
            ns = int(blob_slot_map[g])
            w = min(bw_old, bw_new)
            st["blob_data"][:w, ns] = data_o[:w, g]
            st["blob_used"][ns] = True
            st["blob_len"][ns] = len_o[g]
            st["blob_gen"][ns] = gen_o[g]

    # ---- per-shard reductions: counter sums to shard 0, sticky flags
    # OR-broadcast, monotonic scalars max-broadcast ----
    for name in ("n_processed", "n_delivered", "n_rejected", "n_badmsg",
                 "n_deadletter", "n_mutes", "n_spawned", "n_destroyed",
                 "n_collected", "n_errors", "ev_dropped", "span_dropped",
                 "n_blob_alloc", "n_blob_free", "n_blob_remote",
                 "n_blob_moved"):
        dst = st[name].copy()
        dst[:] = 0
        dst[0] = int(Z[f"st.{name}"].astype(np.int64).sum())
        st[name] = dst
    for name in ("spill_overflow", "spawn_fail", "blob_fail",
                 "blob_budget_fail", "exit_flag"):
        st[name] = np.full_like(st[name], bool(Z[f"st.{name}"].any()))
    st["exit_code"] = np.full_like(
        st["exit_code"], int(Z["st.exit_code"].max(initial=0)))
    st["step_no"] = np.full_like(
        st["step_no"], int(Z["st.step_no"].max(initial=0)))
    st["span_next"] = np.full_like(
        st["span_next"], int(Z["st.span_next"].max(initial=0)))

    # ---- profiler matrices (cumulative; summed into shard 0 so
    # profile()'s mesh-sum is exact whatever the shard count) ----
    nb = len(prog.behaviour_table)
    nd = len(prog.device_cohorts)
    for name, cols in (("beh_runs", nb), ("beh_delivered", nb),
                       ("beh_rejected", nb), ("coh_mute_ticks", nd),
                       ("qwait_hist", nd * QW_BUCKETS),
                       ("phase_cost", N_PHASES)):
        src = Z.get(f"st.{name}")
        if st[name].size and src is not None and src.size:
            dst = st[name].copy()
            dst[:] = 0
            dst[:cols] = src.reshape(-1, cols).sum(0)
            st[name] = dst

    # world facts for the first restored tick: recompute from the
    # restored columns (route spill is empty by construction).
    bits = (1 * bool(st["pressured"].any())
            | 2 * bool(st["muted"].any()))
    st["world_bits"] = np.full_like(st["world_bits"], bits)

    # ---- type_state scatter (+ ref/blob field value remap) ----
    new_ts: Dict[str, Dict[str, np.ndarray]] = {}
    for c in prog.cohorts:
        name = c.atype.__name__
        if c.host:
            new_ts[name] = dict(st["type_state"].get(name, {}))
            continue
        slots, _og, _ng = kept_pairs[name]
        co = old_cohorts[name]
        old_cols = old.slot_to_col(co, slots)
        new_cols = np.asarray(c.slot_to_col(slots), np.int64)
        fields = {}
        for fname, spec in c.atype.field_specs.items():
            dst = st["type_state"][name][fname].copy()
            vals = Z[f"st.ts.{name}.{fname}"][old_cols]
            if pack.ref_target(spec) is not None:
                vals = map_gids(vals).astype(dst.dtype)
            elif pack.is_blob(spec):
                vals = map_handles(vals).astype(dst.dtype)
            dst[new_cols] = vals
            fields[fname] = dst
        new_ts[name] = fields
    st["type_state"] = new_ts

    # ---- parked spill entries -> the inject lane (level 1: after any
    # surviving spill — there is none — and BEFORE fresh emissions, so
    # per-edge FIFO holds; see delivery.py's level encoding) ----
    w1_new = 1 + opts.msg_words + opts.trace_lanes
    tl_old, tl_new = old.trace_lanes, opts.trace_lanes
    mw_old, mw_new = old.msg_words, opts.msg_words

    def convert_words(w: np.ndarray) -> np.ndarray:
        out = np.zeros((w1_new,), np.int32)
        out[0] = w[0]
        n = min(mw_old, mw_new)
        out[1:1 + n] = w[1:1 + n]
        if mw_new < mw_old and np.any(w[1 + mw_new:1 + mw_old]):
            raise SnapshotGeometryError(
                "a parked message's payload does not fit the new "
                f"msg_words={mw_new}")
        if tl_new and tl_old:
            out[-2:] = w[-2:]
        elif tl_new:
            out[-2], out[-1] = -1, 0
        block = out[None, :1 + mw_new].copy()
        out[:1 + mw_new] = remap_payload(block)[0]
        return out

    converted: List[Tuple[int, np.ndarray]] = []
    for pref in ("dspill", "rspill"):
        tgt_a = Z[f"st.{pref}_tgt"].astype(np.int64)
        words_a = Z[f"st.{pref}_words"]
        for pos in np.flatnonzero(tgt_a >= 0):
            if pref == "dspill":
                shard = pos // old.spill_cap
                old_gid = shard * nl_old + tgt_a[pos]
            else:
                old_gid = tgt_a[pos]
            ngid = (gid_map[old_gid]
                    if 0 <= old_gid < n_old else -1)
            if ngid < 0:
                rt.totals["deadletter_host"] += 1
                continue
            converted.append((int(ngid), convert_words(words_a[:, pos])))

    # ---- assemble + assign ----
    import dataclasses as _dc
    state = _dc.replace(
        tmpl, **{k: (v if isinstance(v, dict)
                     else jnp.asarray(v, getattr(tmpl, k).dtype))
                 for k, v in st.items()})
    state = jax.tree.map(
        lambda leaf: jnp.asarray(leaf), state,
        is_leaf=lambda x: isinstance(x, np.ndarray))
    if rt.mesh is not None:
        from .parallel.mesh import shard_state
        state = shard_state(state, rt.mesh)
    rt.state = state

    # queues: converted spill entries FIRST (they are older than any
    # host send still in the saved queues), then the saved inject/fast
    # lanes, all remapped to new ids/widths.
    rt._inject_q.clear()
    for e in converted:
        rt._inject_q.append(e)
    inj_t = Z["q.inject_tgt"].astype(np.int64)
    inj_w = Z["q.inject_words"]
    for i in range(len(inj_t)):
        t = int(map_gids(inj_t[i:i + 1])[0]) \
            if 0 <= inj_t[i] < n_old else int(inj_t[i])
        rt._inject_q.append((t, convert_words(inj_w[i])))
    rt._host_fast_q.clear()
    f_t = Z["q.fastq_tgt"].astype(np.int64)
    f_w = Z["q.fastq_words"]
    for i in range(len(f_t)):
        t = int(map_gids(f_t[i:i + 1])[0]) \
            if 0 <= f_t[i] < n_old else int(f_t[i])
        rt._host_fast_q.append((t, convert_words(f_w[i]), None))

    _restore_host_side(rt, header)
    # host ids moved: remap host-state keys, ref/blob field values and
    # the host-owned blob roots.
    hs = {}
    for aid, fields in rt._host_state.items():
        ng = int(map_gids(np.asarray([aid]))[0]) \
            if 0 <= aid < n_old else aid
        if ng < 0:
            continue
        cohort = prog.cohort_of(ng)
        f2 = dict(fields)
        for fname, spec in cohort.atype.field_specs.items():
            if fname not in f2:
                continue
            if pack.ref_target(spec) is not None:
                f2[fname] = int(map_gids(np.asarray([f2[fname]]))[0])
            elif pack.is_blob(spec):
                f2[fname] = int(map_handles(np.asarray([f2[fname]]))[0])
        hs[ng] = f2
    rt._host_state = hs
    rt._host_errors = {
        int(map_gids(np.asarray([k]))[0]): v
        for k, v in rt._host_errors.items()
        if 0 <= k < n_old and gid_map[k] >= 0}
    rt._host_error_locs = {
        int(map_gids(np.asarray([k]))[0]): v
        for k, v in rt._host_error_locs.items()
        if 0 <= k < n_old and gid_map[k] >= 0}
    rt._host_blobs = set(
        int(h) for h in map_handles(np.asarray(sorted(rt._host_blobs),
                                               np.int64))
        if h >= 0) if rt._host_blobs else set()

    # freelists: device cohorts rebuild from device truth (slots freed
    # by growth are discovered there); host cohorts re-derive from the
    # saved lists plus the grown slot range.
    saved_free = {k: [int(x) for x in v]
                  for k, v in header["free"].items()}
    for c in prog.cohorts:
        name = c.atype.__name__
        old_cap = int(old_cohorts[name]["capacity"])
        kept = [s for s in saved_free.get(name, []) if s < c.capacity]
        grown = list(range(c.capacity - 1, old_cap - 1, -1))
        rt._free[name] = grown + kept
    rt._freelist_key = None
    if any(not c.host for c in prog.cohorts):
        rt._rebuild_freelists()


# ---------------------------------------------------------------------------
# checkpoint ring

def checkpoint_file(prefix: str, seq: int) -> str:
    return f"{prefix}-{seq:08d}{_CKPT_SUFFIX}"


def list_checkpoints(prefix: str) -> List[Tuple[int, str]]:
    """(seq, path) for every ring file under `prefix`, oldest first."""
    out = []
    for p in _glob.glob(prefix + "-*" + _CKPT_SUFFIX):
        tail = p[len(prefix) + 1:-len(_CKPT_SUFFIX)]
        if tail.isdigit():
            out.append((int(tail), p))
    return sorted(out)


def newest_intact(prefix: str,
                  log: Optional[Callable[[str], None]] = None
                  ) -> Optional[str]:
    """Newest ring snapshot that passes full verification, falling back
    past corrupt/truncated ones (the supervisor's recovery source)."""
    for _seq, path in reversed(list_checkpoints(prefix)):
        try:
            verify_snapshot(path)
            return path
        except (SnapshotCorruptError, SnapshotFormatError) as e:
            if log is not None:
                log(f"skipping corrupt checkpoint {path}: {e}")
    return None


class Checkpointer:
    """Periodic crash-safe checkpointing for one runtime (PROFILE.md
    §12): the run loop calls `tick()` at host boundaries; when the
    cadence (`RuntimeOptions.checkpoint_every_s`) is due AND no window
    is in flight, `checkpoint()` captures the world (device→host copy
    started async) on the run-loop thread and hands the write —
    checksums, optional compression, fsync, atomic rename, ring
    rotation — to a background writer thread, so steady-state overhead
    is the capture alone (recorded, PROFILE-style, in `stats()`)."""

    def __init__(self, rt, prefix: Optional[str] = None,
                 every_s: Optional[float] = None,
                 keep: Optional[int] = None, compress: bool = False):
        opts = rt.opts
        self.rt = rt
        self.every_s = float(every_s if every_s is not None
                             else (opts.checkpoint_every_s or 0.0))
        self.prefix = prefix or (opts.checkpoint_path
                                 or opts.analysis_path + _CKPT_SUFFIX)
        self.keep = int(keep if keep is not None else opts.checkpoint_keep)
        self.compress = compress
        existing = list_checkpoints(self.prefix)
        self.seq = (existing[-1][0] + 1) if existing else 0
        self._last_t = time.monotonic()
        self._lock = threading.Lock()
        self._stats = {
            "checkpoints": 0, "written": 0, "failures": 0, "skipped": 0,
            "capture_ms_last": 0.0, "capture_ms_total": 0.0,
            "write_ms_last": 0.0, "write_ms_total": 0.0,
            "bytes_last": 0, "last_path": None, "last_seq": None,
            "last_time": None, "last_verified": False,
        }
        self._q: _queue.Queue = _queue.Queue(maxsize=1)
        self._writer = threading.Thread(
            target=self._write_loop, name="pony-tpu-checkpointer",
            daemon=True)
        self._writer.start()

    # -- run-loop surface --
    def due(self) -> bool:
        return (self.every_s > 0
                and time.monotonic() - self._last_t >= self.every_s)

    def tick(self, rt, in_flight: bool) -> bool:
        """Called at host boundaries: checkpoint when due and the world
        is at a quiescent-consistent point (no in-flight window).
        Returns True when a checkpoint was captured this boundary."""
        if not self.due() or in_flight:
            return False
        self.checkpoint(rt)
        return True

    def checkpoint(self, rt, force: bool = False) -> Optional[int]:
        """Capture now and queue the write; returns the sequence number
        (None when skipped because the writer is still busy with the
        previous snapshot — cadence pressure never stalls the loop)."""
        t0 = time.perf_counter()
        header, arrays = capture(rt)
        capture_ms = (time.perf_counter() - t0) * 1e3
        self._last_t = time.monotonic()
        with self._lock:
            seq = self.seq
            try:
                self._q.put_nowait((seq, header, arrays))
            except _queue.Full:
                if not force:
                    self._stats["skipped"] += 1
                    return None
                self._q.put((seq, header, arrays))
            self.seq += 1
            self._stats["checkpoints"] += 1
            self._stats["capture_ms_last"] = capture_ms
            self._stats["capture_ms_total"] += capture_ms
        fr = getattr(rt, "_flight", None)
        if fr is not None:
            fr.event("checkpoint", seq=seq,
                     capture_ms=round(capture_ms, 3))
        return seq

    def flush(self, timeout: float = 30.0) -> None:
        """Block until every queued write has landed (tests/stop())."""
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.005)
        self._q.join()

    def close(self) -> None:
        try:
            self.flush()
        finally:
            self._q.put(None)
            self._writer.join(timeout=10.0)

    # -- background writer --
    def _write_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            seq, header, arrays = item
            path = checkpoint_file(self.prefix, seq)
            t0 = time.perf_counter()
            try:
                nbytes = write_snapshot(header, arrays, path,
                                        compress=self.compress)
                write_ms = (time.perf_counter() - t0) * 1e3
                with self._lock:
                    s = self._stats
                    s["written"] += 1
                    s["write_ms_last"] = write_ms
                    s["write_ms_total"] += write_ms
                    s["bytes_last"] = nbytes
                    s["last_path"] = path
                    s["last_seq"] = seq
                    s["last_time"] = time.time()
                    s["last_verified"] = True    # CRCs computed on write
                for _old_seq, old_path in list_checkpoints(
                        self.prefix)[:-self.keep]:
                    try:
                        os.remove(old_path)
                    except OSError:
                        pass
                fr = getattr(self.rt, "_flight", None)
                if fr is not None:
                    fr.event("checkpoint_written", seq=seq, path=path,
                             write_ms=round(write_ms, 3), bytes=nbytes)
            except Exception as e:               # noqa: BLE001
                with self._lock:
                    self._stats["failures"] += 1
                fr = getattr(self.rt, "_flight", None)
                if fr is not None:
                    fr.event("checkpoint_failed", seq=seq,
                             error=f"{type(e).__name__}: {e}")
            finally:
                self._q.task_done()

    # -- observability --
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._stats)

    def info(self) -> Dict[str, Any]:
        """The postmortem/doctor/healthz block: where the newest
        restorable snapshot lives, how old it is, and whether its
        checksums were verified on the way out."""
        s = self.stats()
        path = s["last_path"]
        if path is None:       # nothing written this run — on-disk ring?
            existing = list_checkpoints(self.prefix)
            if existing:
                seq, path = existing[-1]
                try:
                    age = time.time() - os.path.getmtime(path)
                except OSError:
                    age = None
                return {"path": path, "seq": seq,
                        "age_s": round(age, 3) if age is not None
                        else None,
                        "verified": None, "writes": s["written"],
                        "failures": s["failures"]}
            return {"path": None, "seq": None, "age_s": None,
                    "verified": None, "writes": 0,
                    "failures": s["failures"]}
        return {"path": path, "seq": s["last_seq"],
                "age_s": round(time.time() - s["last_time"], 3)
                if s["last_time"] else None,
                "verified": bool(s["last_verified"]),
                "writes": s["written"], "failures": s["failures"],
                "capture_ms_last": round(s["capture_ms_last"], 3),
                "write_ms_last": round(s["write_ms_last"], 3)}
