"""World serialisation: checkpoint and resume a running actor world.

≙ the reference's serialisation subsystem (src/libponyrt/gc/serialise.c:
`pony_serialise`/`pony_deserialise` flatten an object graph to an
offset-encoded buffer using per-type trace hooks; `packages/serialise`
is the stdlib surface). The reference has no built-in checkpoint/resume
(SURVEY.md §5) — serialisation is its building block, and here it is
promoted to a first-class feature: the *entire world* (device SoA state,
mailboxes in flight, host-actor state, allocator freelists, counters) is
one snapshot, because the TPU runtime's whole point is that world state
is a single pytree.

Type identity is structural: a fingerprint over cohort layout, field
specs and behaviour signatures (≙ the descriptor table registered at
pony_start, start.c:286-292, which makes serialised ids stable between
runs of the same binary). Restoring into a runtime whose fingerprint
differs is an error — the same guarantee the reference gets from "same
binary".

Snapshots are written at host boundaries (between jitted steps), where
device state is quiescent-consistent — no in-flight step, exactly like
serialising between behaviours in Pony.

Format: one .npz (numpy archive) holding every array plus a JSON header;
written atomically via temp-file rename.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

# v2 (round 5): adds the host fast-lane queue (fastq_tgt/fastq_words) —
# bumped so a pre-fast-lane build REJECTS v2 snapshots loudly instead of
# silently dropping queued host→host messages.
FORMAT_VERSION = 2
_ACCEPTED_FORMATS = (1, 2)     # v1 restores with an empty fast queue


class FingerprintMismatch(RuntimeError):
    """Snapshot was taken by a structurally different program."""


def fingerprint(program) -> str:
    """Structural hash of the program layout (≙ the per-type descriptor
    table identity; serialise.c relies on same-binary type ids)."""
    h = hashlib.sha256()
    for cohort in program.cohorts:
        atype = cohort.atype
        h.update(atype.__name__.encode())
        h.update(str(cohort.capacity).encode())
        h.update(b"H" if cohort.host else b"D")
        for fname, spec in sorted(atype.field_specs.items()):
            h.update(fname.encode())
            h.update(spec.__name__.encode())
        for b in cohort.behaviours:
            h.update(b.name.encode())
            h.update(str(b.global_id).encode())
            for spec in b.arg_specs:
                h.update(spec.__name__.encode())
    return h.hexdigest()[:32]


def _opts_dict(opts) -> Dict[str, Any]:
    return dataclasses.asdict(opts)


def save(rt, path: str) -> None:
    """Snapshot the full world to `path` (.npz). Call between runs/steps
    only (any queued-but-uninjected host sends are included)."""
    if rt.state is None:
        raise RuntimeError("runtime not started")
    arrays: Dict[str, np.ndarray] = {}
    flat, treedef = jax.tree_util.tree_flatten(rt.state)
    for i, leaf in enumerate(flat):
        arrays[f"state_{i}"] = np.asarray(jax.device_get(leaf))
    inject = list(rt._inject_q)
    arrays["inject_tgt"] = np.asarray([t for t, _ in inject], np.int32)
    if inject:
        arrays["inject_words"] = np.stack([w for _, w in inject])
    else:
        arrays["inject_words"] = np.zeros(
            (0, 1 + rt.opts.msg_words), np.int32)
    # Fast-lane entries are (target, words[, trace_ctx]); the host
    # trace bookkeeping (tracing.Tracer) is per-process and not
    # snapshotted — a restored queue's messages deliver untraced.
    fast = list(rt._host_fast_q)
    arrays["fastq_tgt"] = np.asarray([e[0] for e in fast], np.int32)
    if fast:
        arrays["fastq_words"] = np.stack([e[1] for e in fast])
    else:
        arrays["fastq_words"] = np.zeros(
            (0, 1 + rt.opts.msg_words), np.int32)

    header = {
        "format": FORMAT_VERSION,
        "fingerprint": fingerprint(rt.program),
        "opts": _opts_dict(rt.opts),
        "n_state_leaves": len(flat),
        "free": rt._free,
        "host_state": {str(k): v for k, v in rt._host_state.items()},
        "totals": dict(rt.totals),
        "last_counters": rt._last_counters,
        "steps_run": rt.steps_run,
        "exit_code": rt._exit_code,
        "noisy": rt._noisy,
        # Host-owned device-blob handles (GC roots for the blob sweep):
        # without them a restored world's first gc() would sweep blobs
        # the host legitimately holds.
        "host_blobs": sorted(rt._host_blobs),
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, header=np.frombuffer(
        json.dumps(header).encode(), np.uint8), **arrays)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)


def restore(rt, path: str) -> None:
    """Load a snapshot into a started runtime with the same program
    structure (actor classes, capacities, options geometry)."""
    if rt.state is None:
        raise RuntimeError("call start() before restore()")
    with np.load(path, allow_pickle=False) as z:
        header = json.loads(bytes(z["header"]).decode())
        if header["format"] not in _ACCEPTED_FORMATS:
            raise FingerprintMismatch(
                f"snapshot format {header['format']} not in "
                f"{_ACCEPTED_FORMATS}")
        fp = fingerprint(rt.program)
        if header["fingerprint"] != fp:
            raise FingerprintMismatch(
                "snapshot was taken by a structurally different program "
                f"({header['fingerprint']} != {fp})")
        flat, treedef = jax.tree_util.tree_flatten(rt.state)
        if header["n_state_leaves"] != len(flat):
            raise FingerprintMismatch("state leaf count mismatch")
        new_flat = []
        for i, leaf in enumerate(flat):
            arr = z[f"state_{i}"]
            if arr.shape != leaf.shape:
                raise FingerprintMismatch(
                    f"state leaf {i} shape {arr.shape} != {leaf.shape} "
                    "(options geometry must match the snapshot)")
            new_flat.append(jnp.asarray(arr, leaf.dtype))
        state = jax.tree_util.tree_unflatten(treedef, new_flat)
        if rt.mesh is not None:
            from .parallel.mesh import shard_state
            state = shard_state(state, rt.mesh)
        rt.state = state
        rt._inject_q.clear()
        tgts = z["inject_tgt"]
        words = z["inject_words"]
        for i in range(len(tgts)):
            rt._inject_q.append((int(tgts[i]), words[i]))
        rt._host_fast_q.clear()
        if "fastq_tgt" in z:       # absent in pre-fast-lane snapshots
            ftgts = z["fastq_tgt"]
            fwords = z["fastq_words"]
            for i in range(len(ftgts)):
                rt._host_fast_q.append((int(ftgts[i]), fwords[i], None))
    rt._free = {k: [int(x) for x in v] for k, v in header["free"].items()}
    rt._host_state = {int(k): v for k, v in header["host_state"].items()}
    rt._host_blobs = set(int(h) for h in header.get("host_blobs", ()))
    rt.totals.clear()
    rt.totals.update(header["totals"])
    rt._last_counters = dict(header["last_counters"])
    rt.steps_run = int(header["steps_run"])
    rt._exit_code = int(header["exit_code"])
    rt._noisy = int(header["noisy"])
