"""File system access with object-capability discipline — ≙
packages/files over lang/{paths,directory,stat}.c.

Pony's files package is *synchronous* (unlike net/process): File and
Directory do blocking FFI into lang/directory.c / lang/stat.c, guarded
by the object-capability chain AmbientAuth → FileAuth → FilePath, so a
library can only touch paths it was handed a capability for. The TPU
twin keeps both properties: synchronous host-side ops (file IO from a
host actor between steps is exactly how the reference's scheduler runs
file code on a scheduler thread) and the capability chain:

    root = rt.files_auth()              # ≙ env.root (AmbientAuth)
    fp   = FilePath(root, "/tmp/data")  # ≙ FilePath(FileAuth(root), ...)
    f    = File(fp)                     # create/read/write/seek
    sub  = fp.join("logs")              # capability narrows with the path

A FilePath derived by join() can never escape its parent's subtree
(".." is resolved then checked) — the reference's path-capability rule
(packages/files/file_path.pony).
"""

from __future__ import annotations

import os
import shutil
import stat as _stat
from typing import Iterator, List, Optional


class FilesAuth:
    """Root capability (≙ AmbientAuth/FileAuth). Obtained from the
    runtime so ambient authority is explicit."""

    _token = object()

    def __init__(self, token):
        if token is not FilesAuth._token:
            raise PermissionError(
                "obtain FilesAuth via rt.files_auth(), not directly")


def _auth() -> FilesAuth:
    return FilesAuth(FilesAuth._token)


class FilePath:
    """A capability to one path and everything beneath it
    (≙ packages/files/file_path.pony)."""

    def __init__(self, auth, path: str):
        if isinstance(auth, FilesAuth):
            self.path = os.path.realpath(path)
        elif isinstance(auth, FilePath):
            joined = os.path.realpath(
                os.path.join(auth.path, path))
            if not (joined == auth.path
                    or joined.startswith(auth.path + os.sep)):
                raise PermissionError(
                    f"{path!r} escapes the {auth.path!r} capability")
            self.path = joined
        else:
            raise PermissionError(
                "FilePath needs a FilesAuth or parent FilePath capability")

    def join(self, rel: str) -> "FilePath":
        return FilePath(self, rel)

    # -- queries (≙ FileInfo / lang/stat.c) --
    def exists(self) -> bool:
        return os.path.exists(self.path)

    def info(self) -> Optional[os.stat_result]:
        try:
            return os.stat(self.path)
        except OSError:
            return None

    def is_file(self) -> bool:
        st = self.info()
        return st is not None and _stat.S_ISREG(st.st_mode)

    def is_dir(self) -> bool:
        st = self.info()
        return st is not None and _stat.S_ISDIR(st.st_mode)

    # -- mutations (≙ FilePath.mkdir/remove/rename + directory.c) --
    def mkdir(self, recursive: bool = True) -> bool:
        try:
            if recursive:
                os.makedirs(self.path, exist_ok=True)
            else:
                os.mkdir(self.path)
            return True
        except OSError:
            return False

    def remove(self) -> bool:
        """File or directory tree (≙ FilePath.remove)."""
        try:
            if self.is_dir():
                shutil.rmtree(self.path)
            else:
                os.remove(self.path)
            return True
        except OSError:
            return False

    def rename(self, to: "FilePath") -> bool:
        if not isinstance(to, FilePath):
            raise PermissionError("rename target must be a FilePath")
        try:
            os.rename(self.path, to.path)
            return True
        except OSError:
            return False


class File:
    """Buffered read/write file (≙ packages/files/file.pony)."""

    def __init__(self, fp: FilePath, mode: str = "a+b"):
        if not isinstance(fp, FilePath):
            raise PermissionError("File needs a FilePath capability")
        self.fp = fp
        self._f = open(fp.path, mode)

    def write(self, data) -> "File":
        self._f.write(data if isinstance(data, bytes) else
                      str(data).encode())
        return self

    def print(self, line) -> "File":
        return self.write(str(line).encode() + b"\n")

    def read(self, n: int = -1) -> bytes:
        return self._f.read(n)

    def lines(self) -> List[bytes]:
        self.seek_start()
        return self._f.read().split(b"\n")

    def seek_start(self, offset: int = 0) -> "File":
        self._f.seek(offset, os.SEEK_SET)
        return self

    def seek_end(self, offset: int = 0) -> "File":
        self._f.seek(-offset if offset else 0, os.SEEK_END)
        return self

    def position(self) -> int:
        return self._f.tell()

    def size(self) -> int:
        pos = self._f.tell()
        self._f.seek(0, os.SEEK_END)
        n = self._f.tell()
        self._f.seek(pos, os.SEEK_SET)
        return n

    def flush(self) -> "File":
        self._f.flush()
        return self

    def dispose(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.dispose()


class Directory:
    """Directory listing/walking (≙ packages/files/directory.pony over
    lang/directory.c)."""

    def __init__(self, fp: FilePath):
        if not isinstance(fp, FilePath):
            raise PermissionError("Directory needs a FilePath capability")
        if not fp.is_dir():
            raise NotADirectoryError(fp.path)
        self.fp = fp

    def entries(self) -> List[str]:
        return sorted(os.listdir(self.fp.path))

    def walk(self) -> Iterator:
        """(dirpath: FilePath, dirnames, filenames) ≙ FilePath.walk."""
        for root, dirs, fnames in os.walk(self.fp.path):
            rel = os.path.relpath(root, self.fp.path)
            fp = self.fp if rel == "." else self.fp.join(rel)
            yield fp, sorted(dirs), sorted(fnames)

    def open_file(self, name: str, mode: str = "a+b") -> File:
        return File(self.fp.join(name), mode)

    def mkdir(self, name: str) -> "Directory":
        sub = self.fp.join(name)
        sub.mkdir()
        return Directory(sub)
