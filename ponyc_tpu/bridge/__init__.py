"""Host↔runtime ASIO bridge: OS events become actor messages.

≙ the reference's ASIO wiring end to end (SURVEY.md §3.4): the epoll
backend thread turns fd/timer/signal readiness into `pony_asio_event_send`
→ a message in the owning actor's mailbox (src/libponyrt/asio/event.c,
asio/epoll.c:207-230). Here the native loop (ponyc_tpu/native) stages
events on an MPSC queue and the Bridge — registered as a Runtime poller —
drains them at step boundaries into ordinary actor sends.

Subscribed behaviours use one uniform signature, mirroring Pony's
``_event_notify(event, flags, arg)`` (packages/builtin/asio_event.pony)::

    @behaviour
    def on_event(self, st, kind: I32, arg: I32, flags: I32): ...

kind: 1=timer 2=signal 3=fd-read 4=fd-write 5=fd-hup (native module
constants); arg: expiry count / signum / fd.

Liveness: while any noisy subscription exists the runtime will not
terminate on quiescence (≙ asio.c:80-91 noisy_count and the scheduler's
asio hooks at scheduler.c:448-471).
"""

from __future__ import annotations

import signal as _signal
import sys
from typing import Dict, Optional

from .. import native
from ..api import BehaviourDef


class Bridge:
    """One native event loop bound to one Runtime (register via
    ``rt.attach_bridge()``)."""

    def __init__(self, rt):
        self.rt = rt
        self.loop = native.AsioLoop()
        self._subs: Dict[int, BehaviourDef] = {}
        self._cbs: Dict[int, object] = {}   # internal callback subscribers
        self._noisy_given = 0     # noisy holds mirrored into the runtime
        try:
            if rt.opts.pin_asio >= 0:  # ≙ --ponypinasio (start.c:75-94)
                self.loop.pin(rt.opts.pin_asio)
            elif rt.opts.pin >= 0:
                # The driver thread is pinned but the I/O thread was
                # asked to stay free: new threads INHERIT the creator's
                # mask, so restore the pre-pin mask explicitly.
                mask = getattr(rt, "_pre_pin_affinity", None)
                if mask:
                    self.loop.set_affinity(sorted(mask))
        except OSError:
            self.loop.close()      # don't leak the epoll thread + fds
            raise

    # -- subscriptions (≙ pony_asio_event_create/subscribe) --
    def _check(self, owner: int, bdef: BehaviourDef) -> None:
        if not isinstance(bdef, BehaviourDef) or bdef.global_id is None:
            raise TypeError("subscribe with a program-registered behaviour")
        if len(bdef.arg_specs) != 3:
            raise TypeError(
                f"{bdef} must take (kind, arg, flags) — the uniform asio "
                "event signature")

    def timer(self, owner: int, bdef: BehaviourDef, interval_s: float,
              *, first_s: Optional[float] = None, oneshot: bool = False,
              noisy: bool = True) -> int:
        self._check(owner, bdef)
        first = interval_s if first_s is None else first_s
        sid = self.loop.timer(max(1, int(first * 1e9)),
                              max(1, int(interval_s * 1e9)),
                              int(owner), bdef.global_id,
                              oneshot=oneshot, noisy=noisy)
        self._subs[sid] = bdef
        return sid

    def signal(self, owner: int, bdef: BehaviourDef, signum: int,
               *, noisy: bool = False) -> int:
        self._check(owner, bdef)
        sid = self.loop.signal(int(signum), int(owner), bdef.global_id,
                               noisy=noisy)
        self._subs[sid] = bdef
        return sid

    def fd(self, owner: int, bdef: BehaviourDef, fd: int, *,
           read: bool = True, write: bool = False, oneshot: bool = False,
           noisy: bool = True) -> int:
        self._check(owner, bdef)
        sid = self.loop.fd(int(fd), int(owner), bdef.global_id,
                           read=read, write=write, oneshot=oneshot,
                           noisy=noisy)
        self._subs[sid] = bdef
        return sid

    def stdin(self, owner: int, bdef: BehaviourDef, *,
              noisy: bool = True) -> int:
        """Readiness events for standard input (≙ lang/stdfd.c +
        packages/builtin/std_stream.pony input wiring)."""
        return self.fd(owner, bdef, sys.stdin.fileno(), noisy=noisy)

    def sigterm_dump(self, owner: int, bdef: BehaviourDef) -> int:
        """Convenience: SIGTERM → a diagnostic behaviour (≙ the fork's
        SIGTERM live-actor dump, analysis.c:55, cycle.c:874-954)."""
        return self.signal(owner, bdef, _signal.SIGTERM)

    def timer_callback(self, fn, interval_s: float, *,
                       first_s: Optional[float] = None,
                       oneshot: bool = False, noisy: bool = True) -> int:
        """Timer whose expiries invoke a host-side callback `fn(event)` at
        poll boundaries (runtime-internal twin of timer(); the stdlib
        Timers hub uses it for count-limited timers)."""
        first = interval_s if first_s is None else first_s
        sid = self.loop.timer(max(1, int(first * 1e9)),
                              max(1, int(interval_s * 1e9)),
                              -1, -1, oneshot=oneshot, noisy=noisy)
        self._cbs[sid] = fn
        return sid

    def fd_callback(self, fd: int, fn, *, read: bool = True,
                    write: bool = False, noisy: bool = True) -> int:
        """Subscribe an fd whose events are handled by a host-side Python
        callback `fn(event)` at poll boundaries instead of an actor
        behaviour — used by runtime-internal subsystems (the net layer's
        accept/recv plumbing ≙ the reference doing the syscalls inside
        lang/socket.c before the stdlib actor sees data)."""
        sid = self.loop.fd(fd, -1, -1, read=read, write=write,
                           oneshot=False, noisy=noisy)
        self._cbs[sid] = fn
        return sid

    def unsubscribe(self, sub_id: int) -> bool:
        self._subs.pop(sub_id, None)
        self._cbs.pop(sub_id, None)
        return self.loop.unsubscribe(sub_id)

    # -- poller protocol (called by Runtime.run at host boundaries) --
    def poll(self, rt) -> int:
        n = 0
        for ev in self.loop.drain():
            cb = self._cbs.get(ev.sub_id)
            if cb is not None:
                # A raising fd/timer callback must not kill the run
                # loop's host-work phase (≙ the reference's ASIO thread
                # surviving a notify that traps): count it per
                # (class, code), leave flight-recorder evidence, and
                # keep draining — the subscription stays live, exactly
                # like a host behaviour's PonyError residue.
                try:
                    cb(ev)
                except Exception as e:            # noqa: BLE001
                    from ..errors import error_code
                    rt._error_counts[
                        (type(e).__name__, error_code(e))] += 1
                    fl = getattr(rt, "_flight", None)
                    if fl is not None:
                        fl.event("bridge_callback_error",
                                 cls=type(e).__name__,
                                 code=error_code(e), sub=ev.sub_id,
                                 message=str(e))
                n += 1
                continue
            bdef = self._subs.get(ev.sub_id)
            if bdef is None:      # unsubscribed with events still queued
                continue
            rt.send(ev.owner, bdef, ev.kind, ev.arg, ev.flags)
            n += 1
        # Mirror the loop's noisy count into the runtime's liveness hold.
        want = self.loop.noisy + (1 if self.loop.pending() else 0)
        while self._noisy_given < want:
            rt.add_noisy()
            self._noisy_given += 1
        while self._noisy_given > want:
            rt.remove_noisy()
            self._noisy_given -= 1
        return n

    def wait(self, timeout_s: float) -> bool:
        """Block until an asio event is queued or the timeout passes —
        the run loop calls this instead of backoff-sleeping when the
        only pending work is external I/O (≙ a suspended scheduler
        woken by the ASIO thread, scheduler.c:1427-1476)."""
        return self.loop.wait(timeout_s)

    def close(self) -> None:
        while self._noisy_given > 0:
            self.rt.remove_noisy()
            self._noisy_given -= 1
        self.loop.close()
