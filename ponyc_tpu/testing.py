"""Async test harness — ≙ packages/ponytest.

The reference's ponytest runs each `UnitTest` as its own actor under a
`PonyTest` runner with per-test timeouts, assert helpers that *record*
failures rather than abort, exclusion filters, expected-failure support,
and (fork addition, DIVERGENCE.md) a `testsFinished` callback once the
last test completes. The TPU framework's tests are actor *programs* (a
Runtime run to quiescence), so the runner here drives one runtime per
test with a watchdog timeout — the same structure, host-side.

    class RingTest(UnitTest):
        name = "ring/one-token"
        def apply(self, h):
            rt = build_ring(...)
            h.assert_eq(rt.run(), 0)
            h.assert_true(...)

    runner = TestRunner()
    runner.add(RingTest())
    ok = runner.run()          # prints ponytest-style per-test lines
"""

from __future__ import annotations

import fnmatch
import sys
import threading
import time
import traceback
from typing import Callable, List, Optional


class TestHelper:
    """Per-test context (≙ ponytest's TestHelper): assertions record
    failures; `fail`/`complete` finish the test explicitly; `log` lines
    surface only when the test fails (ponytest semantics)."""

    __test__ = False      # not a pytest collection target

    def __init__(self, name: str):
        self.name = name
        self.failures: List[str] = []
        self.logs: List[str] = []
        self._completed: Optional[bool] = None

    # -- assertions (≙ TestHelper.assert_*) --
    def assert_true(self, cond, msg: str = "") -> bool:
        if not cond:
            self._fail(f"assert_true failed {msg}")
        return bool(cond)

    def assert_false(self, cond, msg: str = "") -> bool:
        if cond:
            self._fail(f"assert_false failed {msg}")
        return not cond

    def assert_eq(self, a, b, msg: str = "") -> bool:
        if not (a == b):
            self._fail(f"assert_eq: {a!r} != {b!r} {msg}")
            return False
        return True

    def assert_ne(self, a, b, msg: str = "") -> bool:
        if a == b:
            self._fail(f"assert_ne: both {a!r} {msg}")
            return False
        return True

    def assert_error(self, fn: Callable, msg: str = "") -> bool:
        """≙ assert_error: the callable must raise."""
        try:
            fn()
        except Exception:
            return True
        self._fail(f"assert_error: no exception raised {msg}")
        return False

    def _fail(self, text: str) -> None:
        self.failures.append(text)

    def fail(self, text: str = "explicit fail") -> None:
        self.failures.append(text)

    def log(self, line: str) -> None:
        self.logs.append(str(line))

    def complete(self, success: bool) -> None:
        """≙ TestHelper.complete for long tests."""
        self._completed = bool(success)

    @property
    def ok(self) -> bool:
        if self._completed is not None:
            return self._completed and not self.failures
        return not self.failures


class UnitTest:
    """≙ ponytest's UnitTest trait."""

    name: str = ""
    #: ≙ ponytest label/exclusion-group string
    label: str = ""
    #: Test passes only if apply() raises or records failures
    #: (≙ ponytest's expected-failure pattern).
    expect_failure: bool = False
    #: Per-test timeout override in seconds (≙ long_test timeout).
    timeout: Optional[float] = None

    def apply(self, h: TestHelper) -> None:
        raise NotImplementedError


class TestResult:
    __test__ = False      # not a pytest collection target
    __slots__ = ("name", "ok", "elapsed_s", "failures", "logs", "timed_out")

    def __init__(self, name, ok, elapsed_s, failures, logs, timed_out):
        self.name = name
        self.ok = ok
        self.elapsed_s = elapsed_s
        self.failures = failures
        self.logs = logs
        self.timed_out = timed_out


class TestRunner:
    """≙ the PonyTest runner actor (packages/ponytest/pony_test.pony):
    sequential by default (runtimes share the process-global XLA client),
    per-test timeout watchdog, `--only`-style filtering, summary line, and
    the fork's testsFinished callback."""

    __test__ = False      # not a pytest collection target

    def __init__(self, *, default_timeout: float = 120.0,
                 tests_finished: Optional[Callable] = None,
                 out=None):
        self.tests: List[UnitTest] = []
        self.default_timeout = default_timeout
        self.tests_finished = tests_finished
        self.out = out or sys.stdout
        self.results: List[TestResult] = []

    def add(self, test: UnitTest) -> "TestRunner":
        if not test.name:
            test.name = type(test).__name__
        self.tests.append(test)
        return self

    def _run_one(self, t: UnitTest) -> TestResult:
        h = TestHelper(t.name)
        timeout = t.timeout or self.default_timeout
        err: List[str] = []
        done = threading.Event()

        def body():
            try:
                t.apply(h)
            except Exception:
                err.append(traceback.format_exc())
            finally:
                done.set()

        t0 = time.time()
        th = threading.Thread(target=body, daemon=True)
        th.start()
        timed_out = not done.wait(timeout)
        elapsed = time.time() - t0
        failures = list(h.failures)
        if err:
            failures.append(err[0])
        if timed_out:
            failures.append(f"timed out after {timeout}s")
        ok = h.ok and not err and not timed_out
        if t.expect_failure:
            ok = not ok
            failures = [] if ok else ["expected failure but test passed"]
        return TestResult(t.name, ok, elapsed, failures, h.logs, timed_out)

    def run(self, only: str = "*", exclude: str = "",
            sequential: bool = True) -> bool:
        """Run matching tests; returns overall success. `only`/`exclude`
        are glob patterns on test names (≙ ponytest --only/--exclude)."""
        selected = [t for t in self.tests
                    if fnmatch.fnmatch(t.name, only)
                    and not (exclude and fnmatch.fnmatch(t.name, exclude))]
        w = self.out
        print(f"{len(selected)} test(s) starting", file=w)
        self.results = []
        for t in selected:
            r = self._run_one(t)
            self.results.append(r)
            mark = "OK  " if r.ok else "FAIL"
            print(f"---- {mark} {r.name} ({r.elapsed_s*1e3:.0f} ms)",
                  file=w)
            if not r.ok:
                for line in r.logs:
                    print(f"       log: {line}", file=w)
                for f in r.failures:
                    print(f"       {f}", file=w)
        n_ok = sum(1 for r in self.results if r.ok)
        n_fail = len(self.results) - n_ok
        print(f"---- {len(self.results)} test(s) ran: "
              f"{n_ok} ok, {n_fail} failed", file=w)
        if self.tests_finished is not None:
            # ≙ the fork's testsFinished() hook (DIVERGENCE.md ponytest).
            self.tests_finished(self.results)
        return n_fail == 0


def run_tests(*tests: UnitTest, **kw) -> bool:
    """One-liner entry (≙ PonyTest's Main pattern)."""
    r = TestRunner(**kw)
    for t in tests:
        r.add(t)
    return r.run()


# ---------------------------------------------------------------------------
# Chaos / fault-injection harness (ISSUE 8; ≙ nothing in the reference —
# its test suite has no fault injector, SURVEY.md §4). Small, explicit
# hooks the durability acceptance tests use to prove kill → restart →
# restore → identical outcomes end-to-end: wedge a behaviour (the
# watchdog's code-7 path), raise a coded fatal at a chosen host
# boundary, corrupt/truncate a snapshot file, and SIGKILL the process —
# including deterministically MID-FLUSH inside a checkpoint write (the
# serialise.py chaos point). Hooks are one-shot by default so a
# supervised restart runs clean; subprocess tests arm them through the
# PONY_TPU_CHAOS env var ("<point>[@<nth>]", comma-separated).

class ChaosHooks:
    """Process-global registry of armed fault points. `fire(point)` is
    called from instrumented runtime sites and is a no-op unless that
    point was armed; an armed point triggers on its Nth firing and then
    disarms (one-shot), so recovery paths run unfaulted."""

    KILL = "kill"          # SIGKILL self — the mid-flush crash
    _ACTIONS = (KILL,)

    def __init__(self):
        self._armed = {}           # point -> {"after": n, "seen": k,
        #                             "action": callable|KILL}
        self._env_loaded = False

    def arm(self, point: str, action="kill", after: int = 1) -> None:
        if after < 1:
            raise ValueError("after must be >= 1 (fires on the Nth hit)")
        if isinstance(action, str) and action not in self._ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}")
        self._armed[point] = {"after": int(after), "seen": 0,
                              "action": action}

    def disarm(self, point: str) -> None:
        self._armed.pop(point, None)

    def reset(self) -> None:
        self._armed.clear()
        self._env_loaded = True    # a reset also cancels env arming

    def _load_env(self) -> None:
        # "snapshot-mid-flush@2,other-point" — subprocess arming channel
        # (a supervised child cannot be reached through Python calls).
        self._env_loaded = True
        import os
        spec = os.environ.get("PONY_TPU_CHAOS", "")
        for part in (p.strip() for p in spec.split(",") if p.strip()):
            point, _, nth = part.partition("@")
            self.arm(point, after=int(nth) if nth else 1)

    def fire(self, point: str) -> None:
        if not self._env_loaded:
            self._load_env()
        hook = self._armed.get(point)
        if hook is None:
            return
        hook["seen"] += 1
        if hook["seen"] < hook["after"]:
            return
        del self._armed[point]
        if hook["action"] == self.KILL:
            import os
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        else:
            hook["action"]()


chaos = ChaosHooks()


def wedge_behaviour(bdef, at_dispatch: int = 1, sleep_s: float = 600.0):
    """Wedge a HOST behaviour: its `at_dispatch`-th call sleeps
    `sleep_s` (the stall watchdog's code-7 evidence), then the original
    body is restored — one-shot, so a supervised restart completes.
    Returns an undo callable."""
    import time as _time
    orig = bdef.fn
    state = {"n": 0}

    def wedged(ctx, st, *args):
        state["n"] += 1
        if state["n"] == at_dispatch:
            bdef.fn = orig             # disarm BEFORE sleeping: the
            _time.sleep(sleep_s)       # interrupted retry runs clean
        return orig(ctx, st, *args)

    bdef.fn = wedged

    def undo():
        bdef.fn = orig
    return undo


class FatalAtBoundary:
    """Bridge poller raising a coded PonyError at its Nth host boundary
    — a deterministic coded fatal mid-run (one-shot unless
    `every=True`, the poison-rule fixture)."""

    def __init__(self, boundary: int = 2, code: int = 99,
                 every: bool = False):
        self.boundary = int(boundary)
        self.code = int(code)
        self.every = every
        self.polls = 0
        self.fired = 0

    def poll(self, rt) -> None:
        from .errors import PonyError
        self.polls += 1
        if self.polls == self.boundary or (self.every
                                           and self.polls >= self.boundary):
            self.fired += 1
            raise PonyError(self.code,
                            f"chaos: injected fatal at boundary "
                            f"{self.polls}")


def fatal_at_boundary(rt, boundary: int = 2, code: int = 99,
                      every: bool = False) -> "FatalAtBoundary":
    hook = FatalAtBoundary(boundary, code, every)
    rt.register_poller(hook)
    return hook


def corrupt_snapshot(path: str, mode: str = "truncate") -> None:
    """Damage a snapshot file in a controlled way: "truncate" keeps the
    first half (torn write), "bitflip" flips one byte INSIDE the
    largest zip member's array payload (real bit rot — a flip in zip
    bookkeeping slack would be benign) — restore() must answer with the
    coded SnapshotCorruptError, never a raw numpy/zlib traceback."""
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if mode == "truncate":
        data = data[:max(1, len(data) // 2)]
    elif mode == "bitflip":
        import io
        import struct
        import zipfile
        with zipfile.ZipFile(io.BytesIO(bytes(data))) as zf:
            zi = max(zf.infolist(), key=lambda i: i.compress_size)
        # local header: sig4 ver2 flag2 method2 time2 date2 crc4
        # csize4 usize4 fnlen2 extralen2, then filename+extra, then data
        fnlen, extralen = struct.unpack_from(
            "<HH", data, zi.header_offset + 26)
        data_off = zi.header_offset + 30 + fnlen + extralen
        data[data_off + zi.compress_size // 2] ^= 0x40
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    with open(path, "wb") as f:
        f.write(bytes(data))


def sigkill_after(delay_s: float) -> threading.Thread:
    """Arm a hard SIGKILL of THIS process after `delay_s` — the
    unclean-death fixture (no atexit, no finally, exactly like the OOM
    killer). Returns the (daemon) timer thread."""
    import os
    import signal

    def _kill():
        time.sleep(delay_s)
        os.kill(os.getpid(), signal.SIGKILL)

    t = threading.Thread(target=_kill, name="chaos-sigkill", daemon=True)
    t.start()
    return t
