"""Mixed-behaviour ubench: the dispatch-heterogeneity stressor.

≙ the reference's mixed workload benchmark (`examples/mixed/main.pony`
runs rings + workers + mailboxes concurrently) reduced to the variable
that matters on TPU: BEHAVIOUR COUNT per type. The generated dispatch
switch costs one indirect jump regardless of how many behaviours a type
has (src/libponyc/codegen/genfun.c); the planar dispatch evaluates
every behaviour of a cohort per batch slot (engine.py scan_body), so a
B-behaviour type pays ~B× — this model measures that cliff
(profiling/_hetero.py) and A/Bs the branch-gating countermeasure
(RuntimeOptions.dispatch_gating).

One cohort of N workers; behaviour k bumps a counter and forwards to
the next worker's behaviour (k+1) % B, so sustained traffic exercises
every behaviour every tick (the all-hot worst case). `hot=1` builds the
other extreme: traffic stays on behaviour 0 (one-hot — the case branch
gating rescues).
"""

from __future__ import annotations

import numpy as np

from .. import F32, I32, Ref, Runtime, RuntimeOptions
from ..api import ActorTypeMeta, BehaviourDef


def make_worker_type(n_behaviours: int, hot: int | None = None,
                     work: int = 0):
    """Build a Worker actor type with `n_behaviours` behaviours
    step0..step{B-1}; each forwards to the target's next behaviour
    (or always step0 when hot=1 traffic is requested at seed time).
    `work` > 0 adds that many dependent fma rounds to each behaviour
    body — the heavy-body case where the planar O(B) evaluation term
    actually shows (trivial bodies are swamped by delivery)."""
    ns = {"__annotations__": {"next_ref": Ref, "done": I32, "acc": F32},
          "MAX_SENDS": 1}
    defs = {}                    # name → BehaviourDef (closed over below)

    def mk(k: int):
        nxt = k + 1 if k + 1 < n_behaviours else 0
        if hot == 1:
            nxt = 0

        def step(self, st, n: I32):
            # Forward to the NEXT behaviour id of the next worker —
            # round-robin over all B behaviours (all-hot), or pinned to
            # step0 (one-hot). `self` is the trace Context; the target
            # BehaviourDef comes from the enclosing defs map.
            self.send(st["next_ref"], defs[f"step{nxt}"], n - 1,
                      when=n > 0)
            acc = st["acc"]
            # Dependent NON-affine chain, distinct per behaviour (the
            # k-term): an affine chain with constant coefficients folds
            # to one fma and identical bodies CSE across branches —
            # measured flat, round 5 — so a heavy-body probe must be
            # neither.
            for _ in range(work):
                acc = acc + 1.0 / (acc * acc + 2.0 + k)
            return {**st, "done": st["done"] + 1, "acc": acc}

        step.__name__ = f"step{k}"
        return BehaviourDef(step)

    for k in range(n_behaviours):
        ns[f"step{k}"] = mk(k)
    cls = ActorTypeMeta(
        f"Worker{n_behaviours}" + ("H" if hot == 1 else ""), (), ns)
    for k in range(n_behaviours):
        defs[f"step{k}"] = getattr(cls, f"step{k}")
    return cls


def build(n_workers: int, n_behaviours: int,
          opts: RuntimeOptions | None = None, pings: int = 1,
          hot: int | None = None, seed: int = 0, work: int = 0):
    opts = opts or RuntimeOptions(mailbox_cap=max(4, pings), batch=pings,
                                  max_sends=1, msg_words=1)
    wt = make_worker_type(n_behaviours, hot=hot, work=work)
    rt = Runtime(opts)
    rt.declare(wt, n_workers)
    rt.start()
    ids = rt.spawn_many(wt, n_workers)
    rng = np.random.default_rng(seed)
    order = rng.permutation(n_workers)
    nxt = np.empty(n_workers, np.int64)
    nxt[order] = ids[np.roll(order, -1)]
    rt.set_fields(wt, ids, next_ref=nxt)
    return rt, ids, wt


def seed_all(rt: Runtime, ids, wt, hops: int, pings: int = 1,
             mix: bool = False):
    """Default seeding puts every token on step0 → the round-robin wave
    stays PHASE-SYNCHRONIZED (each tick all lanes carry one behaviour
    id — the case dispatch gating collapses to O(1)). mix=True spreads
    lanes across all B behaviours → every tick carries every id (the
    gating worst case: nothing can be skipped)."""
    steps = [getattr(wt, f"step{k}")
             for k in range(len(wt.behaviour_defs))]
    for _ in range(pings):
        if not mix:
            rt.bulk_send(ids, wt.step0, np.full(len(ids), hops, np.int64))
            continue
        ids_a = np.asarray(ids)
        for k, bd in enumerate(steps):
            sel = ids_a[k::len(steps)]
            if len(sel):
                rt.bulk_send(sel, bd, np.full(len(sel), hops, np.int64))
