"""message-ubench — port of the reference's headline throughput benchmark
(`examples/message-ubench/main.pony`: N pinger actors continuously
exchanging ping messages; the metric is actor-messages/sec).

TPU shape: pingers are one cohort; each pinger holds a `next_ref` (a
shuffled permutation so traffic is irregular, like the reference's random
pings) and on `ping(n)` forwards `ping(n-1)` while n > 0. Seeding every
pinger once yields a sustained load of exactly N in-flight messages — one
dispatched message per actor per tick, which is the framework's peak
message throughput (BASELINE.md north star: ≥10× a 32-core CPU at 1M
actors on one chip).
"""

from __future__ import annotations

import numpy as np

from .. import I32, Ref, Runtime, RuntimeOptions, actor, behaviour


@actor
class Pinger:
    next_ref: Ref
    pings: I32

    BATCH = 1
    MAX_SENDS = 1

    @behaviour
    def ping(self, st, n: I32):
        self.send(st["next_ref"], Pinger.ping, n - 1, when=n > 0)
        return {**st, "pings": st["pings"] + 1}


def build(n_pingers: int, opts: RuntimeOptions | None = None,
          permute: bool = True, seed: int = 0):
    opts = opts or RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                  msg_words=1)
    rt = Runtime(opts)
    rt.declare(Pinger, n_pingers)
    rt.start()
    ids = rt.spawn_many(Pinger, n_pingers)
    if permute:
        rng = np.random.default_rng(seed)
        # A single random cycle over all pingers: irregular traffic but
        # every mailbox receives exactly one message per tick (sustained,
        # no hotspots — the steady state the reference's ubench reaches).
        order = rng.permutation(n_pingers)
        nxt = np.empty(n_pingers, np.int64)
        nxt[order] = ids[np.roll(order, -1)]
    else:
        nxt = np.roll(ids, -1)
    rt.set_fields(Pinger, ids, next_ref=nxt)
    return rt, ids


def seed_all(rt: Runtime, ids, hops: int):
    """Give every pinger an initial ping carrying `hops` remaining."""
    rt.bulk_send(ids, Pinger.ping, np.full(len(ids), hops, np.int64))
