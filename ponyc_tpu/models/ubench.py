"""message-ubench — port of the reference's headline throughput benchmark
(`examples/message-ubench/main.pony`: N pinger actors continuously
exchanging ping messages; the metric is actor-messages/sec).

TPU shape: pingers are one cohort; each pinger holds a `next_ref` (a
shuffled permutation so traffic is irregular, like the reference's random
pings) and on `ping(n)` forwards `ping(n-1)` while n > 0. Seeding every
pinger with `pings` messages (≙ the reference's --initial-pings, default
5 there) yields a sustained load of exactly N×pings in-flight messages —
`pings` dispatches per actor per tick with the drain batch widened to
match, so msgs/sec = N × pings / tick (BASELINE.md north star: ≥10× a
32-core CPU at 1M actors on one chip).
"""

from __future__ import annotations

import numpy as np

from .. import I32, Ref, Runtime, RuntimeOptions, actor, behaviour


@actor
class Pinger:
    next_ref: Ref
    pings: I32

    MAX_SENDS = 1      # drain batch comes from opts.batch (>= pings)

    @behaviour
    def ping(self, st, n: I32):
        self.send(st["next_ref"], Pinger.ping, n - 1, when=n > 0)
        return {**st, "pings": st["pings"] + 1}


def cap_for_pings(pings: int, floor: int = 4) -> int:
    """Smallest power-of-two mailbox_cap that holds `pings` in-flight
    messages (shared by build() and bench.py so the sizing rule lives
    once)."""
    return max(floor, 1 << max(0, pings - 1).bit_length())


def build(n_pingers: int, opts: RuntimeOptions | None = None,
          permute: bool = True, seed: int = 0, pings: int = 1):
    """`pings` > 1 sustains that many in-flight messages per pinger (≙ the
    reference's --initial-pings, default 5 there: main.pony OptionSpec);
    opts.batch must be >= pings to drain them and mailbox_cap >= pings to
    hold them."""
    opts = opts or RuntimeOptions(
        mailbox_cap=cap_for_pings(pings, floor=8),
        batch=max(1, pings), max_sends=1, msg_words=1)
    if opts.mailbox_cap < pings:
        raise ValueError("mailbox_cap must be >= pings")
    if opts.batch < pings:
        raise ValueError("opts.batch must be >= pings to sustain them")
    rt = Runtime(opts)
    rt.declare(Pinger, n_pingers)
    rt.start()
    ids = rt.spawn_many(Pinger, n_pingers)
    if permute:
        rng = np.random.default_rng(seed)
        # A single random cycle over all pingers: irregular traffic but
        # every mailbox receives exactly one message per tick (sustained,
        # no hotspots — the steady state the reference's ubench reaches).
        order = rng.permutation(n_pingers)
        nxt = np.empty(n_pingers, np.int64)
        nxt[order] = ids[np.roll(order, -1)]
    else:
        nxt = np.roll(ids, -1)
    rt.set_fields(Pinger, ids, next_ref=nxt)
    return rt, ids


def seed_all(rt: Runtime, ids, hops: int, pings: int = 1):
    """Give every pinger `pings` initial pings carrying `hops` remaining."""
    for _ in range(pings):
        rt.bulk_send(ids, Pinger.ping, np.full(len(ids), hops, np.int64))
