"""records — a variable-length-payload pipeline over the device blob
pool (sources build records as blobs, workers reduce them, a sink
accumulates), with a NumPy oracle.

≙ the reference's rich-message workloads: a Pony behaviour freely ships
`String iso` / `Array[U32] iso` payloads (pony_alloc_msg object graphs,
pony.h:332-360; examples pass around strings/arrays constantly). This
model is the framework's demonstration that payloads BIGGER than a
mailbox word travel device-resident end to end:

  RecSource.emit   allocates a blob of data-dependent logical length
                   (1..W words), fills it, and MOVES it to its worker
                   (when-masked alloc/write/send on the final record);
  RecWorker.work   reads blob_length + every word, frees the input, and
                   forwards the reduced value;
  RecSink.collect  accumulates count and checksum.

Every blob is freed by its consumer, so a run leaves blobs_in_use == 0 —
and the whole pipeline is oracle-checked word for word.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import Blob, I32, Ref, Runtime, RuntimeOptions, actor, behaviour

W = 8                 # pool word width; logical lengths are 1..W


@actor
class RecSource:
    out: Ref["RecWorker"]
    seed: I32
    left: I32

    BATCH = 1
    MAX_SENDS = 2
    MAX_BLOBS = 1
    BLOB_DISPATCHES = 1

    @behaviour
    def emit(self, st, _: I32):
        r = st["left"]
        go = r > 0
        ln = 1 + (st["seed"] + r) % W
        h = self.blob_alloc(length=ln, when=go)
        for i in range(W):
            self.blob_set(h, i, st["seed"] * (i + 1) + r,
                          when=go & (i < ln))
        self.send(st["out"], RecWorker.work, h, when=go)
        self.send(self.actor_id, RecSource.emit, 0, when=r > 1)
        return {**st, "left": r - 1}


@actor
class RecWorker:
    sink: Ref["RecSink"]
    mult: I32

    MAX_SENDS = 1

    @behaviour
    def work(self, st, h: Blob):
        ln = self.blob_length(h)
        s = jnp.int32(0)
        for i in range(W):
            s = s + jnp.where(i < ln, self.blob_get(h, i), 0)
        self.blob_free(h)
        self.send(st["sink"], RecSink.collect, s * st["mult"])
        return st


@actor
class RecSink:
    total: I32
    n: I32

    @behaviour
    def collect(self, st, v: I32):
        return {"total": st["total"] + v, "n": st["n"] + 1}


def oracle(n_sources: int, n_records: int) -> tuple[int, int]:
    """(expected record count, expected i32-wrapped checksum)."""
    total = np.int32(0)
    for k in range(n_sources):
        seed, mult = k + 1, k % 3 + 1
        for r in range(1, n_records + 1):
            ln = (seed + r) % W + 1
            words = np.int32(seed) * np.arange(1, ln + 1, dtype=np.int32) \
                + np.int32(r)
            with np.errstate(over="ignore"):
                total = np.int32(total + np.int32(words.sum()) * mult)
    return n_sources * n_records, int(total)


def build(n_sources: int = 32, n_records: int = 8,
          opts: RuntimeOptions | None = None):
    # Pool sizing: a blob is live from alloc until its CONSUMER frees
    # it, so in-flight depth is bounded by the consumers' queue depth,
    # not the producers' rate — the single fan-in sink throttles the
    # workers (mute backpressure), and every parked worker message
    # holds a live handle: up to n_sources × (mailbox_cap + spillage).
    # Undersizing surfaces as BlobCapacityError (sticky, raised
    # host-side) — backpressure reaches the pool before the sources.
    opts = opts or RuntimeOptions(
        mailbox_cap=8, batch=2, max_sends=2, msg_words=2,
        inject_slots=max(8, n_sources),
        blob_slots=max(64, 16 * n_sources), blob_words=W)
    rt = Runtime(opts)
    rt.declare(RecSource, n_sources)
    rt.declare(RecWorker, n_sources)
    rt.declare(RecSink, 1)
    rt.start()
    sink = rt.spawn(RecSink, total=0, n=0)
    workers = [rt.spawn(RecWorker, sink=int(sink), mult=k % 3 + 1)
               for k in range(n_sources)]
    sources = [rt.spawn(RecSource, out=int(workers[k]), seed=k + 1,
                        left=n_records)
               for k in range(n_sources)]
    return rt, sink, sources


def run_records(n_sources: int = 32, n_records: int = 8,
                opts: RuntimeOptions | None = None):
    """Build, run to quiescence, assert against the oracle; returns
    (rt, sink_state)."""
    rt, sink, sources = build(n_sources, n_records, opts)
    for s in sources:
        rt.send(int(s), RecSource.emit, 0)
    rt.run()
    st = rt.state_of(int(sink))
    want_n, want_total = oracle(n_sources, n_records)
    assert st["n"] == want_n, (st["n"], want_n)
    assert np.int32(st["total"]) == np.int32(want_total), (
        st["total"], want_total)
    assert rt.blobs_in_use == 0, rt.blobs_in_use
    return rt, st
