"""Benchmark/actor-program families mirroring the reference's examples/
(ring, message-ubench, fan-in, gups, n-body) — the workloads BASELINE.md
tracks."""
