"""gups — port of the reference benchmark `examples/gups_basic/main.pony`
(RandomAccess/GUPS: random xor-updates scattered over a distributed table
held by actors).

TPU shape: the table is one cohort with *one word per actor* (the
actor-per-element limit case of the reference's actor-partitioned table —
scatter delivery IS the random-access operation), plus an updater cohort.
Each updater carries a xorshift32 PRNG in its state, picks a random table
actor every tick and fires an `update(val)` at it; delivery's sort+scatter
performs the GUP. Throughput in updates/sec ≙ GUPS.
"""

from __future__ import annotations

import numpy as np

from .. import I32, Runtime, RuntimeOptions, actor, behaviour


@actor
class TableCell:
    value: I32

    @behaviour
    def update(self, st, v: I32):
        return {**st, "value": st["value"] ^ v}


@actor
class Updater:
    rng: I32
    # TableCell id layout (shard-major; see Cohort.slot_to_gid): cell slot s
    # lives at gid (s % n_shards) * n_local + cell_start + s // n_shards.
    cell_start: I32
    n_shards: I32
    n_local: I32
    table_size: I32
    done: I32

    BATCH = 1
    MAX_SENDS = 2

    @behaviour
    def tick(self, st, n: I32):
        # xorshift32 (public-domain Marsaglia generator).
        x = st["rng"]
        x = x ^ (x << 13)
        x = x ^ ((x >> 17) & 0x7FFF)
        x = x ^ (x << 5)
        slot = x % st["table_size"]     # jnp %: non-negative for divisor > 0
        gid = ((slot % st["n_shards"]) * st["n_local"]
               + st["cell_start"] + slot // st["n_shards"])
        self.send(gid, TableCell.update, x, when=n > 0)
        self.send(self.actor_id, Updater.tick, n - 1, when=n > 1)
        return {**st, "rng": x, "done": st["done"] + (n > 0)}


def build(table_size: int = 4096, n_updaters: int = 64,
          opts: RuntimeOptions | None = None):
    opts = opts or RuntimeOptions(mailbox_cap=16, batch=2, msg_words=1,
                                  spill_cap=1024)
    rt = Runtime(opts)
    rt.declare(TableCell, table_size).declare(Updater, n_updaters)
    rt.start()
    cells = rt.spawn_many(TableCell, table_size)
    cell_cohort = rt.program.by_type[TableCell]
    rng = np.random.default_rng(7)
    upd = rt.spawn_many(
        Updater, n_updaters,
        rng=rng.integers(1, 2**31 - 1, n_updaters),
        cell_start=cell_cohort.local_start,
        n_shards=rt.program.shards,
        n_local=rt.program.n_local,
        table_size=table_size)
    return rt, cells, upd


def run(table_size: int = 4096, n_updaters: int = 64, updates_each: int = 32,
        opts: RuntimeOptions | None = None) -> Runtime:
    rt, cells, upd = build(table_size, n_updaters, opts)
    rt.bulk_send(upd, Updater.tick, [updates_each] * n_updaters)
    rt.run(max_steps=updates_each * 4 + 200)
    return rt


@actor
class OptUpdater:
    """≙ examples/gups_opt: the optimised variant amortises per-message
    overhead by issuing K updates per dispatch (the reference batches
    updates into array messages; here K parallel sends saturate the
    delivery sort instead — the TPU cost is per-*tick*, not per-message,
    so fan-out per dispatch is the analogous lever)."""

    rng: I32
    cell_start: I32
    n_shards: I32
    n_local: I32
    table_size: I32
    done: I32

    BATCH = 1
    K = 8
    MAX_SENDS = 9        # K updates + self-retrigger

    @behaviour
    def tick(self, st, n: I32):
        x = st["rng"]
        go = n > 0
        for _ in range(OptUpdater.K):
            x = x ^ (x << 13)
            x = x ^ ((x >> 17) & 0x7FFF)
            x = x ^ (x << 5)
            slot = x % st["table_size"]
            gid = ((slot % st["n_shards"]) * st["n_local"]
                   + st["cell_start"] + slot // st["n_shards"])
            self.send(gid, TableCell.update, x, when=go)
        self.send(self.actor_id, OptUpdater.tick, n - 1, when=n > 1)
        return {**st, "rng": x,
                "done": st["done"] + OptUpdater.K * go}


def build_opt(table_size: int = 4096, n_updaters: int = 64,
              opts: RuntimeOptions | None = None):
    opts = opts or RuntimeOptions(mailbox_cap=16, batch=2, msg_words=1,
                                  spill_cap=4096)
    rt = Runtime(opts)
    rt.declare(TableCell, table_size).declare(OptUpdater, n_updaters)
    rt.start()
    cells = rt.spawn_many(TableCell, table_size)
    cell_cohort = rt.program.by_type[TableCell]
    rng = np.random.default_rng(11)
    upd = rt.spawn_many(
        OptUpdater, n_updaters,
        rng=rng.integers(1, 2**31 - 1, n_updaters),
        cell_start=cell_cohort.local_start,
        n_shards=rt.program.shards,
        n_local=rt.program.n_local,
        table_size=table_size)
    return rt, cells, upd


def run_opt(table_size: int = 4096, n_updaters: int = 64,
            ticks_each: int = 8,
            opts: RuntimeOptions | None = None) -> Runtime:
    rt, cells, upd = build_opt(table_size, n_updaters, opts)
    rt.bulk_send(upd, OptUpdater.tick, [ticks_each] * n_updaters)
    rt.run(max_steps=ticks_each * 4 + 200)
    return rt
