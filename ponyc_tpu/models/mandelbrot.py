"""mandelbrot — port of the reference example `examples/mandelbrot/
mandelbrot.pony` (Worker actors compute 8-pixel groups of the escape-time
fractal; the compute-dense F32 workload).

The reference's Worker iterates z := z² + c over groups of 8 pixels,
clearing bits of a byte as pixels escape, and pushes bytes into a PBM
row view (mandelbrot.pony:5-66). TPU shape: one Worker actor per
8-pixel group; `compute` receives the group's 8 real coordinates as a
VecF32[8] payload and the shared imaginary coordinate, runs the escape
iteration as a `lax.fori_loop` over [8, lanes] planes (all groups of
the cohort iterate together — the whole image advances per tick), and
stores the finished bitmap byte in actor state. The host assembles the
PBM from the SoA byte column in one bulk read — the TPU-idiomatic
"collect" (a column gather instead of W*H/8 host messages).
"""

from __future__ import annotations

import numpy as np
from jax import lax

from .. import F32, I32, Runtime, RuntimeOptions, VecF32, actor, behaviour

ITERATIONS = 64          # static trace bound (≙ --iterations, default 50)
LIMIT_SQ = 4.0           # escape when |z|² > limit² (≙ limit 4.0)


@actor
class Worker:
    byte: I32            # finished 8-pixel bitmap byte (MSB = leftmost)
    done: I32

    MAX_SENDS = 0
    BATCH = 1

    @behaviour
    def compute(self, st, cr: VecF32[8], ci: F32):
        # cr is a planar [8, lanes] block (pack._VecSpec); every group of
        # the cohort iterates in lockstep on the VPU.
        def body(_i, carry):
            zr, zi, alive = carry
            zr2, zi2 = zr * zr, zi * zi
            nzr = (zr2 - zi2) + cr
            nzi = (2.0 * zr * zi) + ci
            alive = alive & ((zr2 + zi2) <= LIMIT_SQ)
            return nzr, nzi, alive

        zr0 = cr
        zi0 = cr * 0.0 + ci
        alive0 = (zr0 * 0.0) < 1.0            # all True, [8, lanes]
        _, _, alive = lax.fori_loop(0, ITERATIONS, body,
                                    (zr0, zi0, alive0))
        weights = (2 ** np.arange(7, -1, -1)).astype(np.int32)
        byte = (alive.astype("int32")
                * weights.reshape((8,) + (1,) * (alive.ndim - 1))).sum(0)
        return {**st, "byte": byte, "done": 1}


def build(width: int = 64, height: int = 64,
          opts: RuntimeOptions | None = None):
    """One Worker per 8-pixel group, row-major (width must be a multiple
    of 8 — the reference has the same constraint via its byte packing)."""
    if width % 8:
        raise ValueError("width must be a multiple of 8")
    groups = (width // 8) * height
    opts = opts or RuntimeOptions(mailbox_cap=4, batch=1, max_sends=0,
                                  msg_words=9, spill_cap=64,
                                  inject_slots=64)
    rt = Runtime(opts)
    rt.declare(Worker, groups)
    rt.start()
    ids = rt.spawn_many(Worker, groups)
    return rt, ids


def render(width: int = 64, height: int = 64,
           opts: RuntimeOptions | None = None) -> np.ndarray:
    """Compute the full image; returns the [height, width//8] byte grid
    (bit set = pixel in the set, as in the reference's PBM bitmap)."""
    rt, ids = build(width, height, opts)
    gw = width // 8
    # ≙ Main seeding one Worker message per row-band: coordinates ride
    # as message payloads, computed host-side exactly like the
    # reference's precomputed real/imaginary arrays (mandelbrot.pony
    # create()).
    xs = np.arange(width, dtype=np.float32)
    ys = np.arange(height, dtype=np.float32)
    # ≙ the reference's coordinate arrays (mandelbrot.pony:147-155):
    # real[j] = (2/width)*j - 1.5, imaginary[j] = (2/width)*j - 1.0
    # (the reference renders square images; we use 2/height for rows).
    real = (2.0 / width) * xs - 1.5
    imag = (2.0 / height) * ys - 1.0
    cr_cols = real.reshape(gw, 8)               # [gw, 8]
    cr = np.tile(cr_cols, (height, 1))          # [groups, 8] row-major
    ci = np.repeat(imag, gw)                    # [groups]
    rt.bulk_send(ids, Worker.compute, cr, ci)
    rt.run(max_steps=200)
    st = rt.cohort_state(Worker)
    assert int(st["done"].sum()) == len(ids), "not all groups computed"
    return st["byte"].astype(np.uint8).reshape(height, gw)


def reference_bytes(width: int, height: int) -> np.ndarray:
    """NumPy oracle with identical iteration/limit semantics."""
    xs = np.arange(width, dtype=np.float32)
    ys = np.arange(height, dtype=np.float32)
    real = (2.0 / width) * xs - 1.5
    imag = (2.0 / height) * ys - 1.0
    c = real[None, :] + 1j * imag[:, None]
    z = c.astype(np.complex64)
    alive = np.ones(c.shape, bool)
    for _ in range(ITERATIONS):
        alive &= (z.real * z.real + z.imag * z.imag) <= LIMIT_SQ
        z = np.where(alive, z * z + c, z)
    bits = alive.reshape(height, width // 8, 8)
    weights = (2 ** np.arange(7, -1, -1)).astype(np.int32)
    return (bits * weights).sum(-1).astype(np.uint8)


def write_pbm(path: str, bytes_grid: np.ndarray, width: int) -> None:
    """P4 PBM writer (≙ the reference writing the bitmap via files)."""
    height = bytes_grid.shape[0]
    with open(path, "wb") as f:
        f.write(b"P4\n%d %d\n" % (width, height))
        f.write(bytes_grid.tobytes())
