"""n-body — port of the reference benchmark `examples/n-body/` (gravity
between bodies; the compute-heavy-float-behaviour workload).

TPU shape: a *systolic ring* of body actors. Each body launches a token
carrying its (position, mass); tokens hop the ring, and every body a token
visits accumulates that body's gravitational contribution into its own
acceleration (≈20 flops per message — behaviour bodies are where the VPU
work lands). After B-1 hops the token expires and the visited body count
completes one interaction round: B tokens in flight give B messages/tick
and the full all-pairs sum after B-1 ticks, without any B²-wide outbox.
"""

from __future__ import annotations

import numpy as np

from .. import F32, I32, Ref, Runtime, RuntimeOptions, VecF32, actor, \
    behaviour

G = 6.674e-3          # scaled constant (unit system is arbitrary here)
SOFTEN = 1e-2


@actor
class Body:
    next_ref: Ref[Body]
    x: F32
    y: F32
    m: F32
    ax: F32
    ay: F32
    seen: I32

    MAX_SENDS = 1
    BATCH = 4

    @behaviour
    def token(self, st, hops: I32, pos: VecF32[2], pm: F32):
        # The visitor's position travels as ONE device-side float vector
        # (pack._VecSpec: k words inside the message — ≙ pony_alloc_msg
        # rich payloads, pony.h:332-360). pos is a [2, lanes] planar
        # block; component reads index axis 0.
        dx = pos[0] - st["x"]
        dy = pos[1] - st["y"]
        r2 = dx * dx + dy * dy + SOFTEN
        inv_r = 1.0 / (r2 ** 0.5)
        f = G * pm * inv_r * inv_r * inv_r
        self.send(st["next_ref"], Body.token, hops - 1, pos, pm,
                  when=hops > 1)
        return {**st,
                "ax": st["ax"] + f * dx,
                "ay": st["ay"] + f * dy,
                "seen": st["seen"] + 1}


def build(n_bodies: int = 256, opts: RuntimeOptions | None = None,
          seed: int = 3):
    opts = opts or RuntimeOptions(mailbox_cap=16, batch=4, max_sends=1,
                                  msg_words=4, spill_cap=1024)
    rt = Runtime(opts)
    rt.declare(Body, n_bodies)
    rt.start()
    rng = np.random.default_rng(seed)
    ids = rt.spawn_many(
        Body, n_bodies,
        x=rng.uniform(-1, 1, n_bodies).astype(np.float32),
        y=rng.uniform(-1, 1, n_bodies).astype(np.float32),
        m=rng.uniform(0.5, 2.0, n_bodies).astype(np.float32))
    rt.set_fields(Body, ids, next_ref=np.roll(ids, -1))
    return rt, ids


def run_round(n_bodies: int = 256,
              opts: RuntimeOptions | None = None) -> Runtime:
    """One full all-pairs interaction round (every token hops B-1 times)."""
    rt, ids = build(n_bodies, opts)
    st = rt.cohort_state(Body)
    # Each body's token starts at its ring successor.
    nxt = np.roll(ids, -1)
    rt.bulk_send(nxt, Body.token,
                 np.full(n_bodies, n_bodies - 1),
                 np.stack([st["x"], st["y"]], axis=1),   # [count, 2] vec col
                 st["m"])
    rt.run(max_steps=4 * n_bodies + 100)
    return rt


def reference_accels(xs, ys, ms):
    """NumPy all-pairs oracle for verification."""
    dx = xs[None, :] - xs[:, None]
    dy = ys[None, :] - ys[:, None]
    r2 = dx * dx + dy * dy + SOFTEN
    inv_r3 = 1.0 / np.sqrt(r2) ** 3
    np.fill_diagonal(inv_r3, 0.0)
    f = G * ms[None, :] * inv_r3
    return (f * dx).sum(1), (f * dy).sum(1)
