"""Token ring — port of the reference benchmark `examples/ring/main.pony`:
N ring actors each hold a reference to the next; a token message carries a
remaining-pass count and hops around the ring until it reaches zero.

In the reference each hop is one mailbox push + one scheduler pop; here a
full ring of R tokens advances every actor one hop per *step* (the ring is
embarrassingly parallel at width R). With a single token the ring measures
pure per-hop dispatch latency, the same thing the Pony example measures.
"""

from __future__ import annotations

import numpy as np

from .. import I32, Ref, Runtime, RuntimeOptions, actor, behaviour


@actor
class RingNode:
    next_ref: Ref[RingNode]   # typed: wiring checked at build (pack._RefTo)
    passes: I32     # hops observed by this node (for verification)

    @behaviour
    def token(self, st, hops: I32):
        self.send(st["next_ref"], RingNode.token, hops - 1, when=hops > 1)
        self.exit(0, when=hops <= 1)
        return {**st, "passes": st["passes"] + 1}


def build(n_nodes: int = 1024, opts: RuntimeOptions | None = None
          ) -> tuple[Runtime, np.ndarray]:
    rt = Runtime(opts or RuntimeOptions(mailbox_cap=8, batch=1,
                                        max_sends=1, msg_words=1))
    rt.declare(RingNode, n_nodes)
    rt.start()
    ids = rt.spawn_many(RingNode, n_nodes)
    nxt = np.roll(ids, -1)
    # Wire next_ref after spawn (ids are only known once allocated).
    rt.set_fields(RingNode, ids, next_ref=nxt)
    return rt, ids


def run(n_nodes: int = 1024, hops: int = 4096, n_tokens: int = 1,
        opts: RuntimeOptions | None = None) -> Runtime:
    rt, ids = build(n_nodes, opts)
    step = max(1, n_nodes // max(1, n_tokens))
    for t in range(n_tokens):
        rt.send(int(ids[(t * step) % n_nodes]), RingNode.token, hops)
    rt.run()
    return rt
