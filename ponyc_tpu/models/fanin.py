"""fan-in — port of the reference benchmark `examples/fan-in/main.pony`:
many producer actors hammer one aggregator to exercise the
overload → mute → unmute backpressure chain (actor.c:369-381, 1103-1235).

Each producer self-drives: on `produce(n)` it sends one item to the
aggregator and one `produce(n-1)` to itself. With an aggregator batch of 1
and many producers, the aggregator's mailbox saturates immediately; the
engine must (a) reject the overflow into spill, (b) mute the producers,
(c) unmute them as the aggregator drains, and (d) deliver *every* item
exactly once — the conservation property the reference checks by watching
its analytics mute counters.
"""

from __future__ import annotations

from .. import I32, Ref, Runtime, RuntimeOptions, actor, behaviour


@actor
class Producer:
    out: Ref
    sent: I32

    MAX_SENDS = 2

    @behaviour
    def produce(self, st, n: I32):
        self.send(st["out"], Aggregator.consume, 1, when=n > 0)
        self.send(self.actor_id, Producer.produce, n - 1, when=n > 0)
        return {**st, "sent": st["sent"] + (n > 0)}


@actor
class Aggregator:
    total: I32

    BATCH = 1      # deliberately slow consumer (≙ the fan-in example's
    #                single aggregator swamped by producers)

    @behaviour
    def consume(self, st, v: I32):
        return {**st, "total": st["total"] + v}


def run(n_producers: int = 32, items_each: int = 64,
        opts: RuntimeOptions | None = None) -> Runtime:
    opts = opts or RuntimeOptions(mailbox_cap=8, batch=2, msg_words=1,
                                  spill_cap=256)
    rt = Runtime(opts)
    rt.declare(Producer, n_producers).declare(Aggregator, 1)
    rt.start()
    agg = rt.spawn(Aggregator)
    ids = rt.spawn_many(Producer, n_producers, out=agg)
    rt.bulk_send(ids, Producer.produce,
                 [items_each] * n_producers)
    rt.run(max_steps=items_each * n_producers * 4 + 100)
    return rt
