"""Name resolution for net actors — ≙ the reference's DNS surface
(src/libponyrt/lang/socket.c pony_os_addrinfo/pony_os_nextaddr/
pony_os_nameinfo/pony_os_ip_string/pony_os_host_ip4/pony_os_host_ip6
+ packages/net/dns.pony).

Two shapes:

- ``DNS`` — the synchronous primitive, exactly like the reference
  (dns.pony performs a blocking getaddrinfo on the calling scheduler
  thread): resolve/ip4/ip6/nameinfo/is_ip4/is_ip6. The underlying
  call IS the same libc getaddrinfo the reference binds.
- ``Resolver`` — the async upgrade the reference lacks: resolution runs
  on a worker thread and the result arrives as an ACTOR MESSAGE at a
  poll boundary: owner's on_resolved(token, handle, n) with a
  HostHeap-boxed list of (family, ip, port) tuples; n = entry count,
  or a NEGATIVE resolver error (-abs(gaierror errno), or -1 for other
  failures) with an empty list. A slow DNS server can never stall the
  host loop.
"""

from __future__ import annotations

import socket as _socket
import threading
from typing import List, Optional, Tuple

from ..api import BehaviourDef

AddrList = List[Tuple[int, str, int]]     # (family: 4|6, ip, port)


class DNS:
    """Synchronous resolution (≙ packages/net DNS primitive)."""

    @staticmethod
    def resolve(host: str, port: int = 0, *,
                family: Optional[int] = None) -> AddrList:
        """All addresses for host:port (both families unless pinned) —
        ≙ DNS.apply / pony_os_addrinfo + the nextaddr iteration."""
        fam = (_socket.AF_INET if family == 4 else
               _socket.AF_INET6 if family == 6 else _socket.AF_UNSPEC)
        try:
            infos = _socket.getaddrinfo(host, port, fam,
                                        _socket.SOCK_STREAM)
        except _socket.gaierror:
            return []
        out: AddrList = []
        for af, _kind, _proto, _canon, sa in infos:
            out.append((4 if af == _socket.AF_INET else 6, sa[0], sa[1]))
        return out

    @staticmethod
    def ip4(host: str, port: int = 0) -> AddrList:
        """IPv4 only (≙ DNS.ip4 / pony_os_addrinfo with AF_INET)."""
        return DNS.resolve(host, port, family=4)

    @staticmethod
    def ip6(host: str, port: int = 0) -> AddrList:
        """IPv6 only (≙ DNS.ip6)."""
        return DNS.resolve(host, port, family=6)

    @staticmethod
    def is_ip4(host: str) -> bool:
        """≙ pony_os_host_ip4: is the string a literal v4 address?"""
        try:
            _socket.inet_pton(_socket.AF_INET, host)
            return True
        except OSError:
            return False

    @staticmethod
    def is_ip6(host: str) -> bool:
        """≙ pony_os_host_ip6."""
        try:
            _socket.inet_pton(_socket.AF_INET6, host)
            return True
        except OSError:
            return False

    @staticmethod
    def nameinfo(ip: str, port: int = 0) -> Optional[Tuple[str, str]]:
        """Reverse lookup: (host, service) or None (≙ pony_os_nameinfo)."""
        fam = _socket.AF_INET6 if DNS.is_ip6(ip) else _socket.AF_INET
        sa = (ip, port, 0, 0) if fam == _socket.AF_INET6 else (ip, port)
        try:
            return _socket.getnameinfo(sa, 0)
        except (OSError, _socket.gaierror):
            return None


class Resolver:
    """Asynchronous resolution delivering actor messages (register via
    ``rt.attach_resolver()``). One worker thread per in-flight lookup;
    results cross back at poll boundaries through the runtime's poller
    protocol (the same boundary every bridge event crosses)."""

    def __init__(self, rt):
        self.rt = rt
        self._lock = threading.Lock()
        self._ready = []          # (owner, bdef, token, addrs, n)
        rt.register_poller(self)

    def resolve(self, host: str, port: int, owner: int, *,
                on_resolved: BehaviourDef, token: int = 0,
                family: Optional[int] = None) -> None:
        """Kick off a lookup. The owner receives
        on_resolved(token, handle, n): handle boxes the (family, ip,
        port) list (iso — unbox it); n = entry count (0 = host exists
        but no addresses), or a negative resolver error.
        """
        if not isinstance(on_resolved, BehaviourDef) \
                or on_resolved.global_id is None:
            raise TypeError(
                "on_resolved must be a program-registered behaviour")
        if len(on_resolved.arg_specs) != 3:
            raise TypeError("on_resolved must take (token, handle, n)")
        if not on_resolved.actor_type.HOST:
            raise TypeError("on_resolved must live on a HOST actor "
                            "(the address list is a host object)")
        # Validate the target NOW — a bad owner must fail at the call
        # site, not inside a later poll() where it would drop queued
        # results.
        self.rt._check_send_target(int(owner), on_resolved)
        self.rt.add_noisy()        # a pending lookup keeps the world up

        def work():
            addrs: AddrList = []
            n = 0
            try:
                fam = (_socket.AF_INET if family == 4 else
                       _socket.AF_INET6 if family == 6 else
                       _socket.AF_UNSPEC)
                infos = _socket.getaddrinfo(host, port, fam,
                                            _socket.SOCK_STREAM)
                for af, _k, _p, _c, sa in infos:
                    addrs.append((4 if af == _socket.AF_INET else 6,
                                  sa[0], sa[1]))
                n = len(addrs)
            except _socket.gaierror as e:
                n = -abs(e.errno or 1)
            except Exception:                     # noqa: BLE001 —
                n = -1     # e.g. UnicodeError on overlong IDNA labels
            finally:
                # ALWAYS enqueue: a lost result would leak the noisy
                # hold and the runtime would never quiesce.
                with self._lock:
                    self._ready.append((owner, on_resolved, token,
                                        addrs, n))

        threading.Thread(target=work, daemon=True).start()

    # -- poller protocol (Runtime host boundary) --
    def poll(self, rt) -> int:
        with self._lock:
            ready, self._ready = self._ready, []
        for owner, bdef, token, addrs, n in ready:
            try:
                h = rt.heap.box(addrs)
                rt.send(owner, bdef, token, h, n)
            finally:
                rt.remove_noisy()
        return len(ready)
