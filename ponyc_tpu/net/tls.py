"""TLS for net actors — ≙ the reference's SSL hooks
(src/libponyrt/lang/ssl.c:1, deliberately thin there too: the reference
keeps protocol logic out of the runtime and lets a stdlib layer drive
OpenSSL; here the host-side record layer is Python's ``ssl`` module
driven through memory BIOs, non-blocking end to end).

Usage — pass a config to the existing net entry points:

    tls = TLSClientConfig(server_hostname="example.com")     # verifying
    cid = net.connect_tcp(host, port, owner, ..., tls=tls)

    srv = TLSServerConfig(certfile="cert.pem", keyfile="key.pem")
    lid = net.listen_tcp(host, port, owner, ..., tls=srv)

Semantics (matching the reference stdlib's SSL-connection filter model):
``on_connect`` fires only after the HANDSHAKE completes (err=0), or with
err=-1 on handshake failure; ``on_data`` delivers DECRYPTED bytes;
``Net.send`` encrypts transparently; plaintext queued before the
handshake finishes is flushed right after it.
"""

from __future__ import annotations

import ssl as _ssl
from typing import Optional


class TLSError(RuntimeError):
    pass


class TLSClientConfig:
    """Client-side TLS parameters. `verify=False` (or no cafile +
    check_hostname off) degrades gracefully for self-signed peers."""

    def __init__(self, server_hostname: Optional[str] = None, *,
                 cafile: Optional[str] = None, verify: bool = True):
        self.server_hostname = server_hostname
        self.cafile = cafile
        self.verify = verify

    def context(self) -> _ssl.SSLContext:
        ctx = _ssl.create_default_context(cafile=self.cafile)
        if not self.verify:
            ctx.check_hostname = False
            ctx.verify_mode = _ssl.CERT_NONE
        return ctx

    def make(self):
        ctx = self.context()
        inc, out = _ssl.MemoryBIO(), _ssl.MemoryBIO()
        obj = ctx.wrap_bio(inc, out, server_side=False,
                           server_hostname=self.server_hostname)
        return _TLSState(obj, inc, out)


class TLSServerConfig:
    """Server-side TLS parameters (certificate + key required, exactly
    like any TLS server)."""

    def __init__(self, certfile: str, keyfile: Optional[str] = None):
        self.certfile = certfile
        self.keyfile = keyfile

    def context(self) -> _ssl.SSLContext:
        ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certfile, self.keyfile)
        return ctx

    def make(self):
        ctx = self.context()
        inc, out = _ssl.MemoryBIO(), _ssl.MemoryBIO()
        obj = ctx.wrap_bio(inc, out, server_side=True)
        return _TLSState(obj, inc, out)


class _TLSState:
    """Per-connection record layer: an SSLObject over a memory-BIO pair,
    pumped by the net layer at poll boundaries. Pure state machine — no
    fd, no blocking (the net layer owns the socket)."""

    __slots__ = ("obj", "inc", "out", "done", "failed",
                 "pending_app", "notified")

    def __init__(self, obj, inc, out):
        self.obj = obj
        self.inc = inc
        self.out = out
        self.done = False          # handshake complete
        self.failed = False
        self.pending_app = []      # plaintext queued pre-handshake
        self.notified = False      # client on_connect delivered

    # -- driving --
    def start(self):
        """Kick off the client hello (or server wait)."""
        self._step_handshake()

    def feed(self, data: bytes):
        """Raw ciphertext from the socket → BIO."""
        self.inc.write(data)
        if not self.done:
            self._step_handshake()

    def _step_handshake(self):
        if self.done or self.failed:
            return
        try:
            self.obj.do_handshake()
            self.done = True
        except _ssl.SSLWantReadError:
            pass                   # needs more peer bytes
        except _ssl.SSLError:
            self.failed = True

    def read_app(self) -> bytes:
        """Drain decrypted application bytes (b'' if none yet)."""
        if not self.done:
            return b""
        chunks = []
        while True:
            try:
                chunk = self.obj.read(65536)
            except (_ssl.SSLWantReadError, _ssl.SSLWantWriteError):
                break
            except _ssl.SSLZeroReturnError:    # close_notify
                break
            except _ssl.SSLError:
                self.failed = True
                break
            if not chunk:
                break
            chunks.append(chunk)
        return b"".join(chunks)

    def write_app(self, data: bytes):
        """Encrypt plaintext (buffered until the handshake is done)."""
        if not self.done:
            self.pending_app.append(data)
            return
        self.obj.write(data)

    def flush_pending(self):
        for d in self.pending_app:
            self.obj.write(d)
        self.pending_app.clear()

    def take_out(self) -> bytes:
        """Ciphertext the socket should transmit now."""
        return self.out.read() if self.out.pending else b""
