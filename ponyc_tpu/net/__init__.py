"""TCP/UDP networking as actor messages — ≙ packages/net over
lang/socket.c.

The reference splits networking into the native syscall layer
(src/libponyrt/lang/socket.c: pony_os_listen_tcp/accept/connect/recv/
send, all non-blocking and ASIO-subscribed) and the stdlib actors
(packages/net/tcp_listener.pony, tcp_connection.pony, udp_socket.pony)
that turn readiness events into notify callbacks. This package keeps the
same split: syscalls live in native/src/socket.cc; this layer owns the
fds, does the accept/recv/send loops at poll boundaries, and delivers
*actor messages* to the owning (host-cohort) actors:

    on_accept(conn: I32)                      ≙ TCPListenNotify.connected
    on_connect(conn: I32, err: I32)           ≙ ConnectionNotify.connected/
                                                connect_failed (err=errno)
    on_data(conn: I32, data: I32, n: I32)     ≙ TCPConnectionNotify.received
        `data` is a HostHeap handle — rt.heap.unbox(data) yields bytes
        (move semantics ≙ the Array[U8] iso the reference passes)
    on_closed(conn: I32)                      ≙ ...closed
    on_datagram(sock: I32, data: I32, n: I32) ≙ UDPNotify.received
        unbox → (bytes, host, port)

Writes buffer host-side when the kernel refuses; write-readiness is armed
only while the buffer is non-empty (≙ pony_os_writev + the reference's
resubscribe-on-EAGAIN dance) and `pending(conn)` exposes the backlog so
applications can throttle (≙ packages/net throttled/unthrottled).
"""

from __future__ import annotations

import collections

from typing import Dict, Tuple

from .. import native
from ..api import BehaviourDef
from ..native import sockets as S


class _Conn:
    __slots__ = ("fd", "sub", "owner", "on_connect", "on_data", "on_closed",
                 "outbuf", "outbuf_len", "connecting", "closed", "tls")

    def __init__(self, fd, owner, on_connect, on_data, on_closed,
                 connecting, tls=None):
        self.fd = fd
        self.sub = None
        self.owner = owner
        self.on_connect = on_connect
        self.on_data = on_data
        self.on_closed = on_closed
        self.outbuf = collections.deque()   # chunks (writev scatter-gather)
        self.outbuf_len = 0
        self.connecting = connecting
        self.closed = False
        self.tls = tls        # net.tls._TLSState or None (≙ ssl.c hooks)


class Net:
    """One runtime's network layer (create via rt.attach_net())."""

    RECV_CHUNK = 65536

    def __init__(self, rt):
        self.rt = rt
        self.bridge = rt.attach_bridge()
        self._listeners: Dict[int, Tuple[int, int, int,
                                         Tuple[BehaviourDef, BehaviourDef,
                                               BehaviourDef]]] = {}
        self._conns: Dict[int, _Conn] = {}
        self._udp: Dict[int, Tuple[int, int, BehaviourDef]] = {}
        self._next = 1

    def _check(self, bdef, n_args, what):
        if not isinstance(bdef, BehaviourDef) or bdef.global_id is None:
            raise TypeError(f"{what} must be a program-registered behaviour")
        if not bdef.actor_type.HOST:
            raise TypeError(
                f"{what} must live on a HOST=True actor type (network "
                "payload handles are host objects; forward parsed words "
                "to device actors from there)")
        if len(bdef.arg_specs) != n_args:
            raise TypeError(f"{what} must take {n_args} i32 args")

    # -- listeners (≙ TCPListener + pony_os_listen_tcp) --
    def listen_tcp(self, host: str, port: int, owner: int, *,
                   on_accept: BehaviourDef, on_data: BehaviourDef,
                   on_closed: BehaviourDef, backlog: int = 64,
                   tls=None) -> int:
        """`tls=TLSServerConfig(...)` upgrades every accepted connection
        to TLS (net/tls.py ≙ the ssl.c hook surface)."""
        self._check(on_accept, 1, "on_accept")
        self._check(on_data, 3, "on_data")
        self._check(on_closed, 1, "on_closed")
        fd = S.listen_tcp(host, port, backlog)
        lid = self._next
        self._next += 1
        sub = self.bridge.fd_callback(fd, lambda ev: self._accept_ready(lid),
                                      read=True, noisy=True)
        self._listeners[lid] = (fd, sub, owner,
                                (on_accept, on_data, on_closed), tls)
        return lid

    def listen_port(self, lid: int) -> int:
        """The bound port (for ephemeral listens; ≙ pony_os_sockname)."""
        if lid in self._listeners:
            return S.sockname_port(self._listeners[lid][0])
        if lid in self._udp:
            return S.sockname_port(self._udp[lid][0])
        raise KeyError(lid)

    def _accept_ready(self, lid: int) -> None:
        ent = self._listeners.get(lid)
        if ent is None:
            return
        fd, _sub, owner, (on_accept, on_data, on_closed), tls_cfg = ent
        while True:
            nfd = S.accept(fd)
            if nfd is None:
                break
            tls = tls_cfg.make() if tls_cfg is not None else None
            cid = self._register_conn(nfd, owner, None, on_data, on_closed,
                                      connecting=False, tls=tls)
            if tls is not None:
                tls.start()                     # await ClientHello
                self._tls_pump(cid, self._conns[cid])
            self.rt.send(owner, on_accept, cid)

    # -- connections (≙ TCPConnection + pony_os_connect_tcp) --
    def connect_tcp(self, host: str, port: int, owner: int, *,
                    on_connect: BehaviourDef, on_data: BehaviourDef,
                    on_closed: BehaviourDef, tls=None) -> int:
        """`tls=TLSClientConfig(...)`: on_connect fires AFTER the TLS
        handshake (err=0), or err=-1 on handshake failure."""
        self._check(on_connect, 2, "on_connect")
        self._check(on_data, 3, "on_data")
        self._check(on_closed, 1, "on_closed")
        fd = S.connect_tcp(host, port)
        return self._register_conn(fd, owner, on_connect, on_data,
                                   on_closed, connecting=True,
                                   tls=tls.make() if tls else None)

    def _register_conn(self, fd, owner, on_connect, on_data, on_closed,
                       *, connecting, tls=None) -> int:
        cid = self._next
        self._next += 1
        c = _Conn(fd, owner, on_connect, on_data, on_closed, connecting,
                  tls)
        # A connecting socket arms write interest to learn the outcome.
        c.sub = self.bridge.fd_callback(
            fd, lambda ev: self._conn_ready(cid, ev),
            read=True, write=connecting, noisy=True)
        self._conns[cid] = c
        return cid

    def _conn_ready(self, cid: int, ev) -> None:
        c = self._conns.get(cid)
        if c is None or c.closed:
            return
        if ev.kind == native.FD_WRITE:
            if c.connecting:
                c.connecting = False
                err = S.connect_result(c.fd)
                if err != 0:          # TCP failed (before TLS, if any)
                    if c.on_connect is not None:
                        self.rt.send(c.owner, c.on_connect, cid, err)
                    self._teardown(cid, notify=False)
                    return
                if c.tls is None:
                    if c.on_connect is not None:
                        self.rt.send(c.owner, c.on_connect, cid, err)
                else:
                    c.tls.start()     # ClientHello → outbuf
                    self._tls_pump(cid, c)
                self._arm(c)
            if c.outbuf:
                self._flush(cid, c)
            return
        if ev.kind == native.FD_READ:
            while True:
                data = S.recv(c.fd, self.RECV_CHUNK)
                if data is None:      # drained
                    break
                if data == b"":       # orderly EOF
                    self._teardown(cid, notify=True)
                    return
                if c.tls is not None:
                    c.tls.feed(data)
                    if not self._tls_pump(cid, c):
                        return        # handshake failure tore down
                    app = c.tls.read_app()
                    if app:
                        h = self.rt.heap.box(app)
                        self.rt.send(c.owner, c.on_data, cid, h, len(app))
                    if c.tls.failed and not self._tls_pump(cid, c):
                        return        # record failure (bad MAC …)
                    continue
                h = self.rt.heap.box(data)
                self.rt.send(c.owner, c.on_data, cid, h, len(data))
                # Edge-triggered subscription: always drain to EAGAIN.
            return
        if ev.kind == native.FD_HUP:
            self._teardown(cid, notify=True)

    def _tls_pump(self, cid: int, c: _Conn) -> bool:
        """Move the record layer forward: transmit pending ciphertext,
        complete the handshake (flush pre-handshake plaintext, deliver
        the deferred on_connect), surface failures. False = torn down."""
        tls = c.tls
        if tls.failed:
            if (not tls.done and c.on_connect is not None
                    and not tls.notified):
                # Handshake never completed: the client learns via
                # on_connect(-1); on_closed would be about a connection
                # it was never told is up.
                tls.notified = True
                self.rt.send(c.owner, c.on_connect, cid, -1)
                self._teardown(cid, notify=False)
            else:
                # Established connection died (record failure) — or a
                # server-side handshake failure on a conn the owner
                # already saw via on_accept: on_closed either way.
                self._teardown(cid, notify=True)
            return False
        if tls.done and tls.pending_app:
            tls.flush_pending()
        out = tls.take_out()
        if out:
            c.outbuf.append(out)
            c.outbuf_len += len(out)
            if not c.connecting:
                self._flush(cid, c)
        if tls.done and not tls.notified:
            tls.notified = True
            if c.on_connect is not None:
                self.rt.send(c.owner, c.on_connect, cid, 0)
        return True

    def _arm(self, c: _Conn) -> None:
        self.bridge.loop.fd_interest(c.sub, read=True,
                                     write=bool(c.outbuf))

    def _flush(self, cid: int, c: _Conn) -> None:
        # Scatter-gather flush: one writev per round sends the whole
        # chunk list without flattening (≙ the reference's iovec write
        # path, lang/socket.c pony_os_writev).
        while c.outbuf:
            n = S.writev(c.fd, list(c.outbuf))
            if n <= 0:
                break
            c.outbuf_len -= n
            while n > 0 and c.outbuf:
                head = c.outbuf[0]
                if n >= len(head):
                    n -= len(head)
                    c.outbuf.popleft()
                else:
                    c.outbuf[0] = head[n:]
                    n = 0
        self._arm(c)

    # -- user API on connections --
    def send(self, cid: int, data: bytes) -> None:
        """Queue bytes; the layer writes as the socket allows (≙
        TCPConnection.write with host-side pending buffer)."""
        self.sendv(cid, (data,))

    def sendv(self, cid: int, chunks) -> None:
        """Queue a chunk LIST (e.g. buffered.Writer.done()) — sent with
        scatter-gather writev, no flattening (≙ TCPConnection.writev)."""
        c = self._conns.get(cid)
        if c is None or c.closed:
            raise KeyError(f"connection {cid} is closed")
        if c.tls is not None:
            # Plaintext → record layer; ciphertext rides the outbuf.
            for ch in chunks:
                ch = bytes(ch)
                if ch:
                    c.tls.write_app(ch)
            self._tls_pump(cid, c)
            return
        for ch in chunks:
            ch = bytes(ch)
            if ch:
                c.outbuf.append(ch)
                c.outbuf_len += len(ch)
        if not c.connecting:
            self._flush(cid, c)

    def pending(self, cid: int) -> int:
        """Unflushed outgoing bytes (backpressure signal ≙ throttled)."""
        c = self._conns.get(cid)
        return c.outbuf_len if c is not None else 0

    def pending_total(self) -> int:
        """Unflushed outgoing bytes across ALL connections — the
        layer-wide egress-backpressure gauge (`/metrics` exports it as
        pony_tpu_net_pending_bytes; /healthz degrades when it grows
        monotonically across snapshots: a consumer has stopped
        reading)."""
        return sum(c.outbuf_len for c in self._conns.values())

    def set_conn_owner(self, cid: int, owner: int, *,
                       on_data: BehaviourDef,
                       on_closed: BehaviourDef) -> None:
        """Hand a connection to another actor (≙ the reference pattern of
        the listener's notify creating a fresh TCPConnectionNotify)."""
        self._check(on_data, 3, "on_data")
        self._check(on_closed, 1, "on_closed")
        c = self._conns[cid]
        c.owner, c.on_data, c.on_closed = owner, on_data, on_closed

    def nodelay(self, cid: int, on: bool = True) -> None:
        S.nodelay(self._conns[cid].fd, on)

    def close(self, cid: int) -> None:
        """Graceful local close (flush refused; pending data dropped —
        call after acks, like the reference's dispose)."""
        self._teardown(cid, notify=False)

    def _teardown(self, cid: int, *, notify: bool) -> None:
        c = self._conns.pop(cid, None)
        if c is None or c.closed:
            return
        c.closed = True
        self.bridge.unsubscribe(c.sub)
        S.close(c.fd)
        if notify and c.on_closed is not None:
            self.rt.send(c.owner, c.on_closed, cid)

    def close_listener(self, lid: int) -> None:
        ent = self._listeners.pop(lid, None)
        if ent is None:
            return
        fd, sub, _owner, _b, _tls = ent
        self.bridge.unsubscribe(sub)
        S.close(fd)

    # -- UDP (≙ packages/net UDPSocket + pony_os_listen_udp) --
    def udp_bind(self, host: str, port: int, owner: int, *,
                 on_datagram: BehaviourDef) -> int:
        self._check(on_datagram, 3, "on_datagram")
        fd = S.udp(host, port)
        uid = self._next
        self._next += 1
        sub = self.bridge.fd_callback(
            fd, lambda ev: self._udp_ready(uid), read=True, noisy=True)
        self._udp[uid] = (fd, sub, (owner, on_datagram))
        return uid

    def _udp_ready(self, uid: int) -> None:
        ent = self._udp.get(uid)
        if ent is None:
            return
        fd, _sub, (owner, on_datagram) = ent
        while True:
            r = S.recvfrom(fd, self.RECV_CHUNK)
            if r is None:
                break
            data, host, port = r
            h = self.rt.heap.box((data, host, port))
            self.rt.send(owner, on_datagram, uid, h, len(data))

    def sendto(self, uid: int, data: bytes, host: str, port: int) -> None:
        fd, _sub, _b = self._udp[uid]
        S.sendto(fd, bytes(data), host, port)

    def close_udp(self, uid: int) -> None:
        ent = self._udp.pop(uid, None)
        if ent is None:
            return
        fd, sub, _b = ent
        self.bridge.unsubscribe(sub)
        S.close(fd)

    def close_all(self) -> None:
        for cid in list(self._conns):
            self._teardown(cid, notify=False)
        for lid in list(self._listeners):
            self.close_listener(lid)
        for uid in list(self._udp):
            self.close_udp(uid)
