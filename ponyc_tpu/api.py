"""Front-end API: actor types, behaviours, and the per-dispatch Context.

This is the TPU framework's equivalent of the Pony *language surface* for
actors: an ``actor`` class with ``be`` behaviours (reference: the compiler
lowers each behaviour into a message-send stub + a dispatch case,
src/libponyc/codegen/genfun.c; actor hints tag/priority/batch/main-thread
are lazily read from per-type hint functions, src/libponyrt/actor/
actor.c:398-423 — here they are plain class attributes, resolved at program
build time because the whole actor world is compiled as one XLA program,
the same way reach.c assumes whole-program knowledge).

Behaviours are *pure traced functions*::

    @actor
    class RingNode:
        next_ref: Ref            # per-actor state field (annotation = dtype)
        passes:   I32

        @behaviour
        def token(self, st, hops: I32):
            self.send(st["next_ref"], RingNode.token, hops - 1,
                      when=hops > 0)
            self.exit(0, when=hops <= 0)
            return st

``self`` inside a behaviour is a Context, not the object: it carries the
actor's global id and collects the side effects (sends, exit, yield) that
the engine turns into batched device operations. The state dict ``st`` is
functional — return the updated dict.

The number of ``self.send(...)`` calls per behaviour must be static (it is
traced once); data-dependent sends use ``when=`` masks, exactly as XLA
requires (`lax.cond` under vmap selects, it does not branch).
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp

from .ops import pack
from .ops.pack import (Blob, BlobVal, Bool, Box, F32, I8, I16, I32,  # noqa
                       Iso, Mut, Ref, Tag, Trn, TypeParam, U8, U16,
                       U32, Val, VecF32, VecI32)  # re-exported


class BehaviourDef:
    """A behaviour declaration: dispatch id + typed argument spec.

    ≙ a Pony behaviour's (message id, param list); global ids are assigned
    at program build (≙ reach/paint vtable colouring, reach/paint.c:8-60).
    """

    def __init__(self, fn):
        self.fn = fn
        self.name = fn.__name__
        sig = inspect.signature(fn)
        params = list(sig.parameters.values())[2:]  # drop (self, st)
        self.arg_specs = tuple(
            pack.normalize_annotation(
                p.annotation if p.annotation is not inspect.Parameter.empty
                else I32)
            for p in params)
        self.arg_names = tuple(p.name for p in params)
        # Sendability (≙ safeto.c: behaviour/ctor parameters must be in
        # CAP_SEND {iso, val, tag}, type/cap.c:90): a behaviour call IS
        # a message, so a Trn/Mut/Box parameter could smuggle
        # write-aliased state across an actor boundary.
        for p, spec in zip(params, self.arg_specs):
            m = pack.cap_mode(spec)
            if not pack.cap_sendable(m):
                raise TypeError(
                    f"behaviour {fn.__name__}: parameter {p.name!r} is "
                    f"{spec.__name__} — not sendable; only Iso, Val and "
                    "Tag payloads may cross an actor boundary "
                    "(CAP_SEND, type/cap.c:90; safeto.c)")
        # Source capture (the lint body rules + verify failures point
        # at real file:line; None for exec'd/builtin functions):
        code = getattr(fn, "__code__", None)
        self.source_file: Optional[str] = getattr(code, "co_filename",
                                                  None)
        self.source_line: Optional[int] = getattr(code,
                                                  "co_firstlineno", None)
        # Behaviour-level lint suppressions (``@behaviour(lint_ignore=
        # ("R6",))`` sets fn.LINT_IGNORE so inherited/reified copies —
        # which re-wrap the same fn — keep the suppression).
        self.lint_ignore: Tuple[str, ...] = tuple(
            str(r) for r in getattr(fn, "LINT_IGNORE", ()) or ())
        # Filled in by program build:
        self.global_id: Optional[int] = None
        self.local_id: Optional[int] = None
        self.actor_type: Optional["ActorTypeMeta"] = None

    def __repr__(self):
        owner = self.actor_type.__name__ if self.actor_type else "?"
        return f"<behaviour {owner}.{self.name} gid={self.global_id}>"


def behaviour(fn=None, *, lint_ignore=()):
    """Mark a method as an actor behaviour (≙ Pony ``be``).

    ``@behaviour(lint_ignore=("R6", ...))`` suppresses those lint
    rules for findings attributed to this behaviour (the
    behaviour-level sibling of the type-level ``LINT_IGNORE``)."""
    if fn is None:
        def deco(f):
            if lint_ignore:
                f.LINT_IGNORE = tuple(str(r) for r in lint_ignore)
            return BehaviourDef(f)
        return deco
    return BehaviourDef(fn)


# Alias matching Pony's keyword.
be = behaviour


class ActorTypeMeta(type):
    """Metaclass collecting state fields + behaviours from the class body."""

    def __new__(mcs, name, bases, ns):
        fields: Dict[str, Any] = {}
        inherited: List[BehaviourDef] = []
        for base in bases:
            fields.update(getattr(base, "_fields", {}))
            inherited.extend(getattr(base, "_behaviours", []))
        for key, val in list(ns.get("__annotations__", {}).items()):
            if key.startswith("_") or key.isupper():
                continue
            spec = pack.normalize_annotation(val)
            if spec in pack._NARROW_JNP:
                # State columns are i32/f32 only; letting a narrow marker
                # through would silently give the field signed-i32
                # semantics while the same marker on a message argument
                # arrives at its declared width.
                raise TypeError(
                    f"{name}.{key}: narrow/unsigned widths "
                    f"({spec.__name__}) are message-argument types; "
                    "declare state fields as I32 (or F32) and wrap "
                    "explicitly in the behaviour")
            fields[key] = spec
        own = [val for val in ns.values() if isinstance(val, BehaviourDef)]
        cls = super().__new__(mcs, name, bases, ns)
        # Inherited behaviours get a *fresh* BehaviourDef per subclass:
        # dispatch ids are per-(type, behaviour) slots (≙ paint.c vtable
        # colouring), so sharing one def across types would let finalize()
        # clobber ids. The copy is also set as a class attribute so
        # `Sub.ping` resolves to Sub's slot, not the base's.
        behaviours: List[BehaviourDef] = []
        own_names = {b.name for b in own}
        for b in inherited:
            if b.name in own_names:   # overridden in this class body
                continue
            copy = BehaviourDef(b.fn)
            setattr(cls, copy.name, copy)
            behaviours.append(copy)
        behaviours.extend(own)
        cls._fields = fields
        cls._behaviours = behaviours
        for b in behaviours:
            b.actor_type = cls
        # Scheduling hints (≙ actor.c:398-423 lazy hint fns):
        cls.BATCH = ns.get("BATCH", None)        # msgs per step override
        cls.PRIORITY = ns.get("PRIORITY", 0)     # ≙ fork's priority hint
        cls.HOST = ns.get("HOST", False)         # ≙ use_main_thread: runs on host
        cls.TAG = ns.get("TAG", 0)               # ≙ fork's analysis tag
        # Spawn budget (≙ pony_create from behaviour code, actor.c:688):
        # {TargetType_or_name: max ctx.spawn() sites per dispatch}. Spawning
        # is opt-in because reservations cost free-slot compaction per step.
        cls.SPAWNS = ns.get("SPAWNS", {})
        # How many of an actor's ≤batch dispatches per step may spawn
        # (default: all of them). Lowering it shrinks the free-slot window
        # each runnable actor reserves; a step that exceeds it raises
        # SpawnCapacityError (safe, no corruption).
        cls.SPAWN_DISPATCHES = ns.get("SPAWN_DISPATCHES", None)
        # Blob budgets (≙ per-behaviour heap allocations, heap.c):
        # MAX_BLOBS = ctx.blob_alloc sites per dispatch; BLOB_DISPATCHES
        # bounds how many of an actor's ≤batch dispatches per step may
        # allocate (default: all) — each runnable actor statically
        # reserves BLOB_DISPATCHES × MAX_BLOBS pool slots per tick, so
        # lowering it lets a small pool serve many actors.
        cls.MAX_BLOBS = ns.get("MAX_BLOBS", 0)
        cls.BLOB_DISPATCHES = ns.get("BLOB_DISPATCHES", None)
        # Generic actor types (≙ formal type parameters; reify.c):
        # collect TypeParams across fields + behaviour args in first-
        # appearance order. Non-empty → the class must be reified
        # (Cls[Concrete]) before declare/spawn.
        all_specs = list(fields.values())
        for b in behaviours:
            all_specs.extend(b.arg_specs)
        cls._type_params = pack.type_params_of(all_specs)
        cls._reifications = {}
        return cls

    def __getitem__(cls, item):
        """Reify a generic actor type: Cell[I32] substitutes the type
        parameters and yields a CONCRETE actor type with its own cohort
        and behaviour ids (≙ reify.c — each reification is its own
        type; reach.c only ever sees concrete ones). Reifications are
        cached so Cell[I32] is Cell[I32]."""
        params = cls._type_params
        if not params:
            raise TypeError(f"{cls.__name__} is not generic "
                            "(no TypeParam annotations)")
        args = item if isinstance(item, tuple) else (item,)
        args = tuple(pack.normalize_annotation(a)
                     if not isinstance(a, ActorTypeMeta) else a
                     for a in args)
        if len(args) != len(params):
            raise TypeError(
                f"{cls.__name__} takes {len(params)} type argument(s) "
                f"({', '.join(p.name for p in params)}), got {len(args)}")
        # Cache key: actor/marker CLASSES key by object identity (two
        # distinct classes sharing a name must not collide); spec
        # instances key by their canonical name.
        def _key_of(a):
            if isinstance(a, type):
                return a
            if isinstance(a, pack._RefTo) and not isinstance(a.target,
                                                             str):
                return ("Ref", a.target)
            return a.__name__ if hasattr(a, "__name__") else str(a)
        key = tuple(_key_of(a) for a in args)
        hit = cls._reifications.get(key)
        if hit is not None:
            return hit
        mapping = dict(zip(params, args))
        disp = tuple(a.__name__ if hasattr(a, "__name__") else str(a)
                     for a in args)
        name = f"{cls.__name__}[{', '.join(disp)}]"
        ns = {"__annotations__": {}, "__qualname__": name}
        for attr in ("BATCH", "PRIORITY", "HOST", "TAG", "SPAWNS",
                     "SPAWN_DISPATCHES", "MAX_SENDS", "MAX_BLOBS",
                     "BLOB_DISPATCHES"):
            if attr in cls.__dict__:
                ns[attr] = cls.__dict__[attr]
        new = ActorTypeMeta(name, (Actor,), ns)
        new.__name__ = name
        new._fields = {k: pack.substitute(s, mapping)
                       for k, s in cls._fields.items()}
        behaviours = []
        for b in cls._behaviours:
            copy = BehaviourDef(b.fn)
            # Substitute from the CURRENT class's specs (b.arg_specs),
            # not the freshly re-derived signature specs: re-reifying a
            # partial application (Cell[U][I32]) must start from U, not
            # from the template's original parameter.
            copy.arg_specs = tuple(pack.substitute(s, mapping)
                                   for s in b.arg_specs)
            copy.actor_type = new
            setattr(new, copy.name, copy)
            behaviours.append(copy)
        new._behaviours = behaviours
        # Recompute from the SUBSTITUTED specs: a type argument that is
        # itself a TypeParam (partial application, Cell[U]) leaves the
        # result generic — it must still refuse declare().
        sub_specs = list(new._fields.values())
        for b in behaviours:
            sub_specs.extend(b.arg_specs)
        new._type_params = pack.type_params_of(sub_specs)
        cls._reifications[key] = new
        return new

    @property
    def field_specs(cls):
        return cls._fields

    @property
    def behaviour_defs(cls):
        return cls._behaviours


class Actor(metaclass=ActorTypeMeta):
    """Base class for actor types (subclass + annotate fields)."""


def actor(cls):
    """Class decorator: turn a plain class into an actor type."""
    ns = dict(cls.__dict__)
    ns.pop("__dict__", None)
    ns.pop("__weakref__", None)
    return ActorTypeMeta(cls.__name__, (Actor,), ns)


class BlobPoolView:
    """Trace-time working view of the device blob pool for ONE behaviour
    evaluation (see ops.pack.Blob; pool arrays live in runtime.state).

    The planar engine hands each behaviour branch the CURRENT pool
    arrays plus `take` — the lane mask "this lane's batch slot selected
    this behaviour". Every mutation (alloc/set/free) applies eagerly,
    masked by `take & when`, to this view's working copies; because one
    blob has exactly one owner and the take masks of a cohort's
    behaviours are disjoint, sequential application across branches is
    exact — no cross-branch selects, and reads observe this dispatch's
    own earlier writes (read-your-writes).

    ≙ the reference's actor heap + pony_alloc_msg payloads
    (pony.h:332-360): alloc on the owning actor, move by message."""

    __slots__ = ("data", "used", "len_", "gen", "base", "nslots", "take",
                 "resv", "claims", "fail", "budget_fail", "n_alloc",
                 "n_free", "n_remote", "alloced", "budget_over")

    def __init__(self, data, used, len_, gen, base, take, resv,
                 budget_over=None):
        self.data = data            # [W, B] i32 (working copy)
        self.used = used            # [B] bool
        self.len_ = len_            # [B] i32
        self.gen = gen              # [B] i32 slot generations (ABA guard)
        self.base = base            # traced i32: this shard's first handle
        self.nslots = used.shape[0]
        self.take = take            # [lanes] bool
        self.resv = resv            # [sites, lanes] i32 handles, or None
        self.claims = 0             # trace-time alloc-site counter
        self.fail = jnp.bool_(False)     # sticky: wanted a slot, pool empty
        self.budget_fail = jnp.bool_(False)  # sticky: wanted a slot but
        #   the dispatch was past its BLOB_DISPATCHES reservation budget
        self.budget_over = budget_over   # [lanes] bool or None — lanes
        #   whose reservation window was withheld for budget (engine's
        #   used-counter walk), used to blame alloc failures on the
        #   right knob (blob_slots vs BLOB_DISPATCHES)
        self.n_alloc = jnp.int32(0)
        self.n_free = jnp.int32(0)
        self.n_remote = jnp.int32(0)     # Blob args that arrived off-shard
        self.alloced = self.take & False   # [lanes] did this dispatch alloc
        #   (drives the engine's blob_dispatches used-counter walk)

    def local(self, h):
        """(local slot index, validity mask). The handle's generation
        bits must match the slot's current generation (ABA guard: a
        stale handle to a recycled slot is dead, ops.pack encoding).
        Invalid handles map to the UPPER sentinel `nslots` — JAX
        normalises negative indices NumPy-style even under
        mode="drop"/"fill", so -1 would silently address the last slot;
        an out-of-range-high index is what those modes actually
        drop/fill."""
        hl = pack.blob_slot(h) - self.base
        ok = (h >= 0) & (hl >= 0) & (hl < self.nslots)
        hs = jnp.where(ok, hl, self.nslots)
        ok = ok & (jnp.take(self.gen, hs, mode="fill", fill_value=-1)
                   == pack.blob_gen_of(h))
        return jnp.where(ok, hl, self.nslots), ok


class Context:
    """Per-dispatch effect collector, passed as ``self`` to behaviours.

    ≙ pony_ctx_t + the send/exit runtime entry points (pony_sendv
    actor.c:773, pony_exitcode start.c:345). All effects are masked arrays;
    the engine pads them to the type's static send budget.
    """

    __slots__ = ("actor_id", "msg_words", "sends", "exit_flag", "exit_code",
                 "yield_flag", "destroy_flag", "spawn_fail", "_spawn_resv",
                 "spawn_claims", "destroy_called", "error_flag",
                 "error_code", "error_loc", "error_called", "ref_types",
                 "_spawn_meta", "sync_inits", "_effected", "cap_moves",
                 "cap_types", "exit_called", "yield_called", "_blob")

    def __init__(self, actor_id, msg_words: int, spawn_resv=None,
                 spawn_meta=None, blob=None):
        self.actor_id = actor_id          # traced i32 scalar (global id)
        self.msg_words = msg_words
        self.sends: List[Tuple[Any, Any, Any]] = []   # (target, words, when)
        self.exit_flag = jnp.bool_(False)
        self.exit_code = jnp.int32(0)
        self.yield_flag = jnp.bool_(False)
        self.destroy_flag = jnp.bool_(False)
        self.spawn_fail = jnp.bool_(False)
        self.destroy_called = False      # trace-time: did destroy() run?
        self.error_flag = jnp.bool_(False)
        self.error_code = jnp.int32(0)
        self.error_loc = jnp.int32(0)
        self.error_called = False        # trace-time: did error_int() run?
        self.exit_called = False         # trace-time: did exit() run?
        self.yield_called = False        # trace-time: did yield_() run?
        # {target type name: [n_sites] i32 reserved global ids} for this
        # dispatch; None entries = -1 (no free slot was available).
        self._spawn_resv = spawn_resv or {}
        # {target type name: [claimed refs so far]} (engine canonicalises).
        self.spawn_claims: Dict[str, List[Any]] = {
            t: [] for t in self._spawn_resv}
        # Trace-time typed-ref provenance; the engine tags the typed
        # state fields and typed args into it before dispatch.
        self.ref_types = pack.RefTypes()
        # Trace-time iso-move discipline (≙ type/alias.c consume rules).
        self.cap_moves = pack.CapMoves()
        # Capability provenance of traced values (≙ the cap half of the
        # type checker; engine tags declared Iso/Val/Tag fields + args).
        self.cap_types = pack.CapTypes()
        # {target type name: field_specs} for sync construction.
        self._spawn_meta = spawn_meta or {}
        # {target type name: {site index: (state dict, ok mask)}}.
        self.sync_inits: Dict[str, Dict[int, Any]] = {}
        self._effected = False    # trace-time: any exit()/yield_() call
        # Device blob pool view (None = pool disabled or host dispatch).
        self._blob: Optional[BlobPoolView] = blob

    # -- messaging (≙ pony_sendv, actor.c:773-834) --
    def _send_checks(self, target, behaviour_def: BehaviourDef, args):
        """Trace-time sendability + capability discipline for one send,
        shared by the real send and the verify/lint probe
        (verify._ProbeContext) so whole-program lint enforces exactly
        what the engine's trace would.

        Sendability (≙ type/safeto.c + expr/call.c): a behaviour call
        must exist on the receiver's type, and ref-typed params only
        accept matching refs. Typed provenance rides on tracer identity
        (pack.RefTypes) — a directly-forwarded typed field or argument
        is checked; derived values are untyped (gradual). Fails the
        TRACE (build time), not as a runtime badmsg."""
        owner = behaviour_def.actor_type.__name__
        tn = self.ref_types.lookup(target)
        if tn is not None and tn != owner:
            raise TypeError(
                f"sendability: ref typed Ref[{tn}] cannot receive "
                f"{owner}.{behaviour_def.name} — declare the field/arg "
                f"as Ref[{owner}] or fix the wiring")
        for spec, a in zip(behaviour_def.arg_specs, args):
            want = pack.ref_target(spec)
            got = self.ref_types.lookup(a)
            if want is not None and got is not None and got != want:
                raise TypeError(
                    f"sendability: {owner}.{behaviour_def.name} expects "
                    f"Ref[{want}] but was passed a Ref[{got}]")
        # Iso move discipline (≙ cap.c/alias.c/safeto.c consume rules):
        # a moved handle may never be used again this dispatch, and an
        # Iso-parameter send IS a move. Capability provenance must also
        # cover the parameter's declared mode (≙ is_cap_sub_cap: a
        # shared val cannot be passed where a unique iso is required).
        where = f"{owner}.{behaviour_def.name} send"
        for spec, a in zip(behaviour_def.arg_specs, args):
            if pack.concrete_null_handle(a):
                continue                  # 0/-1 sentinel: no payload
            prev = self.cap_moves.was_moved(a)
            if prev is not None:
                raise TypeError(
                    f"capability: use-after-move — payload already moved "
                    f"by {prev} is passed to {where}")
            src = self.cap_types.lookup(a)
            want = pack.cap_mode(spec)
            if not pack.cap_store_ok(src, want):
                raise TypeError(
                    f"capability: {where} declares its parameter "
                    f"{want.capitalize()} but was passed a {src} "
                    f"payload — a {src} value cannot grant the rights "
                    f"{want} requires (is_cap_sub_cap, type/cap.c)")
        for spec, a in zip(behaviour_def.arg_specs, args):
            if pack.concrete_null_handle(a):
                continue
            want = pack.cap_mode(spec)
            # The payload SHIPS whenever it rides a capability-typed
            # parameter; if the sender's value is unique (iso — by
            # declared parameter mode or by provenance), shipping it is
            # a MOVE, including the legal iso→val/tag downgrades. The
            # sender provably loses it either way.
            if want == "iso" or (want is not None
                                 and self.cap_types.lookup(a) == "iso"):
                self.cap_moves.move(a, where)

    def send(self, target, behaviour_def: BehaviourDef, *args, when=True):
        if not isinstance(behaviour_def, BehaviourDef):
            raise TypeError("second argument to send() must be a behaviour "
                            "(e.g. SomeActor.some_behaviour)")
        if behaviour_def.global_id is None:
            raise RuntimeError(
                f"{behaviour_def} not registered in a Program yet")
        self._send_checks(target, behaviour_def, args)
        payload = pack.pack_args(behaviour_def.arg_specs, args, self.msg_words)
        # Planar-aware: payload is [W] (all-constant args) or [W, R]
        # (lane vectors); the gid row matches its trailing shape.
        gid_row = jnp.full((1,) + payload.shape[1:],
                           behaviour_def.global_id, jnp.int32)
        words = jnp.concatenate([gid_row, payload], axis=0)
        self.sends.append((jnp.asarray(target, jnp.int32), words,
                           jnp.asarray(when, jnp.bool_)))

    # -- lifecycle --
    def spawn(self, ctor: BehaviourDef, *args, when=True):
        """Create an actor of the constructor's type and send it `ctor` as
        its first message (≙ pony_create, actor.c:688-734 — in Pony
        ``create`` *is* an async behaviour, so construction here is exactly
        "claim a slot, deliver the constructor message").

        Returns the new actor's ref (traced i32), usable immediately in
        this behaviour's sends/state. The spawner's class must declare
        ``SPAWNS = {TargetType: n_sites}``; slots come from the *same
        shard* as the spawner (≙ pony_create allocating on the creating
        scheduler's thread). If no free slot was available the ref is -1,
        the sticky `spawn_fail` flag raises host-side, and the masked
        constructor send drops harmlessly.
        """
        tname, ref, ok = self._claim_slot(ctor, when, "spawn")
        self.send(ref, ctor, *args, when=ok)
        # The returned ref is typed (provenance-tagged): storing it in a
        # mistyped Ref[T] field or sending it a foreign behaviour fails
        # at build.
        return self.ref_types.tag(jnp.where(ok, ref, jnp.int32(-1)), tname)

    def _claim_slot(self, ctor, when, what: str):
        """Shared spawn preamble: budget checks + slot claim bookkeeping
        (≙ pony_create's allocation, actor.c:688-734). Returns
        (target type name, reserved ref, ok mask)."""
        if not isinstance(ctor, BehaviourDef):
            raise TypeError(f"{what}() takes a constructor behaviour "
                            "(e.g. Worker.init)")
        tname = ctor.actor_type.__name__
        resv = self._spawn_resv.get(tname)
        if resv is None:
            raise RuntimeError(
                f"{tname} is not in this actor type's SPAWNS declaration; "
                f"add SPAWNS = {{{tname}: n}} to the spawning class")
        used = len(self.spawn_claims[tname])
        if used >= resv.shape[0]:
            raise RuntimeError(
                f"more than SPAWNS[{tname}]={resv.shape[0]} spawns in one "
                "behaviour dispatch; raise the declared budget")
        ref = resv[used]
        w = jnp.asarray(when, jnp.bool_)
        ok = w & (ref >= 0)
        self.spawn_claims[tname].append(jnp.where(ok, ref, jnp.int32(-1)))
        self.spawn_fail = self.spawn_fail | (w & (ref < 0))
        return tname, ref, ok

    def spawn_sync(self, ctor: BehaviourDef, *args, when=True):
        """Spawn with a SYNCHRONOUS constructor (≙ the fork's
        pony_sendv_synchronous_constructor, actor.c:836-848): the
        constructor behaviour runs *inside this dispatch* on the
        newborn's zeroed state, and the resulting fields are written when
        the slot is claimed — so same-step sends to the new ref find a
        fully constructed actor next tick, with no ordering convention.

        The constructor must be PURE construction: returning the initial
        state only. Effects inside it (send/spawn/exit/destroy/yield/
        error) raise at build — an effectful create needs the async
        `spawn`, whose constructor message is a real dispatch.
        """
        tname, ref, ok = self._claim_slot(ctor, when, "spawn_sync")
        specs = self._spawn_meta.get(tname)
        if specs is None:
            raise RuntimeError(
                "spawn_sync is only available in device behaviours")
        used = len(self.spawn_claims[tname]) - 1   # site just claimed
        self._ctor_arg_checks(ctor, args, tname)
        # Run the constructor NOW on zeroed defaults (≙ the synchronous
        # field assignment), in a throwaway context that must stay inert.
        cctx = Context(ref, self.msg_words)
        zero = {f: (jnp.int32(-1) if pack.is_ref(s) else
                    jnp.float32(0) if s is pack.F32 else jnp.int32(0))
                for f, s in specs.items()}
        st2 = ctor.fn(cctx, zero, *args)
        if st2 is None or set(st2.keys()) != set(specs.keys()):
            raise TypeError(
                f"sync constructor {ctor} must return the full state dict "
                f"({sorted(specs)})")
        if (cctx.sends or cctx.destroy_called or cctx.error_called
                or any(cctx.spawn_claims.values()) or cctx._effected):
            raise TypeError(
                f"sync constructor {ctor} performs effects; effects need a "
                "real dispatch — use ctx.spawn (async constructor message)")
        for f, s in specs.items():
            want = pack.ref_target(s)
            got = self.ref_types.lookup(st2[f])
            if want is not None and got is not None and got != want:
                raise TypeError(
                    f"sendability: sync constructor {ctor} stores a "
                    f"Ref[{got}] into field {f!r} declared Ref[{want}]")
            # Cap lattice applies to the newborn's fields too (the
            # OUTER provenance map: values flow from the spawner's
            # args/fields through the constructor).
            if pack.concrete_null_handle(st2[f]):
                continue
            src = self.cap_types.lookup(st2[f])
            dst = pack.cap_mode(s)
            if not pack.cap_store_ok(src, dst):
                raise TypeError(
                    f"capability: sync constructor {ctor} stores a "
                    f"{src} payload into field {f!r} declared "
                    f"{dst.capitalize()} — a {src} value cannot grant "
                    f"the rights {dst} requires (is_cap_sub_cap)")
            # The newborn is ANOTHER actor: a spawner-provenance value
            # landing in its fields crosses an actor boundary, so it
            # must be sendable — a trn/ref/box could otherwise smuggle
            # a write-aliased payload out (CAP_SEND, safeto.c).
            if src is not None and not pack.cap_sendable(src):
                raise TypeError(
                    f"capability: sync constructor {ctor} moves a "
                    f"{src} payload into the newborn's field {f!r} — "
                    f"{src} is not sendable; only iso/val/tag cross an "
                    "actor boundary (CAP_SEND, type/cap.c:90)")
        self.sync_inits.setdefault(tname, {})[used] = (st2, ok)
        return self.ref_types.tag(jnp.where(ok, ref, jnp.int32(-1)), tname)

    def _ctor_arg_checks(self, ctor: BehaviourDef, args, tname: str):
        """Constructor arguments obey the same sendability + capability
        rules as a send (≙ expr/call.c parameter checks): a typed ref
        arg must match, a cap-typed arg must satisfy the store lattice,
        and handing a unique to the newborn is a MOVE. Shared with the
        verify/lint probe (verify._ProbeContext.spawn_sync), which
        claims the slot but never runs the constructor."""
        where = f"{tname}.{ctor.name} spawn_sync"
        for spec, a in zip(ctor.arg_specs, args):
            want = pack.ref_target(spec)
            got = self.ref_types.lookup(a)
            if want is not None and got is not None and got != want:
                raise TypeError(
                    f"sendability: {tname}.{ctor.name} expects Ref[{want}] "
                    f"but was passed a Ref[{got}]")
            if pack.concrete_null_handle(a):
                continue
            prev = self.cap_moves.was_moved(a)
            if prev is not None:
                raise TypeError(
                    f"capability: use-after-move — payload already moved "
                    f"by {prev} is passed to {where}")
            cwant = pack.cap_mode(spec)
            src = self.cap_types.lookup(a)
            if not pack.cap_store_ok(src, cwant):
                raise TypeError(
                    f"capability: {where} declares its parameter "
                    f"{cwant.capitalize()} but was passed a {src} "
                    f"payload — a {src} value cannot grant the rights "
                    f"{cwant} requires (is_cap_sub_cap, type/cap.c)")
        for spec, a in zip(ctor.arg_specs, args):
            if pack.concrete_null_handle(a):
                continue
            cwant = pack.cap_mode(spec)
            if cwant == "iso" or (cwant is not None
                                  and self.cap_types.lookup(a) == "iso"):
                self.cap_moves.move(a, where)

    def destroy(self, when=True):
        """Mark *this* actor for destruction at the end of the step: slot
        freed, queued messages discarded, later sends dead-letter.

        The reference never destroys explicitly — ORCA/cycle GC collects
        (gc/cycle.c); this framework has that too (runtime.gc()). destroy()
        is the cheap opt-out for protocols that know their own lifetime.
        Refs held elsewhere dangle (and the slot may be reused by a later
        spawn) — the documented divergence from ORCA's safety.
        """
        self.destroy_called = True
        self.destroy_flag = self.destroy_flag | jnp.asarray(when, jnp.bool_)

    def exit(self, code=0, when=True):
        """Request program termination (≙ pony_exitcode + quiescent stop)."""
        self._effected = True
        self.exit_called = True
        w = jnp.asarray(when, jnp.bool_)
        self.exit_flag = self.exit_flag | w
        self.exit_code = jnp.where(w, jnp.asarray(code, jnp.int32),
                                   self.exit_code)

    def yield_(self, when=True):
        """Stop draining this actor's mailbox for the rest of the step
        (≙ the fork's ponyint_actor_yield, actor.c:675-679)."""
        self._effected = True
        self.yield_called = True
        self.yield_flag = self.yield_flag | jnp.asarray(when, jnp.bool_)

    def error_int(self, code, when=True):
        """Record an int-coded error on this actor (≙ the fork's
        pony_error_int / pony_error_code, pony.h:622-665 — errors are
        *values*, not unwinding). The actor keeps running (a Pony
        behaviour must handle its own errors; the code here is the
        observable residue): the latest nonzero code is queryable via
        Runtime.last_error() and surfaces in the analysis dump."""
        self.error_called = True
        # Trace-time raise site (≙ the fork's __error_loc): the Python
        # call site interns into a host-side table; the device carries
        # only the i32 site id.
        from .errors import caller_loc, register_error_site
        site = register_error_site(caller_loc())
        w = jnp.asarray(when, jnp.bool_)
        self.error_flag = self.error_flag | w
        self.error_code = jnp.where(w, jnp.asarray(code, jnp.int32),
                                    self.error_code)
        self.error_loc = jnp.where(w, jnp.int32(site), self.error_loc)

    # -- device blob pool (≙ actor-heap message payloads; see
    # ops.pack.Blob and BlobPoolView) --
    def _require_blob(self, what: str) -> "BlobPoolView":
        if self._blob is None:
            raise RuntimeError(
                f"{what}: the device blob pool is disabled — set "
                "RuntimeOptions.blob_slots and blob_words (> 0); host "
                "behaviours have no device pool")
        return self._blob

    def _blob_guard(self, h, what: str):
        """Trace-time iso discipline shared by the blob ops: touching a
        handle after it was moved (sent, or freed) is use-after-move."""
        prev = self.cap_moves.was_moved(h)
        if prev is not None:
            raise TypeError(
                f"capability: use-after-move — blob handle already moved "
                f"by {prev} is passed to {what}")

    def blob_alloc(self, length=None, when=True):
        """Claim a fresh device blob; returns its handle ([lanes] i32,
        -1 where `when` is false or the pool had no free slot — the
        sticky blob-fail flag then raises host-side, like spawn_fail).
        The slot's words are zeroed; `length` (default: the pool width)
        records the logical word count read back by blob_length().
        The class must declare ``MAX_BLOBS = n`` (allocs per dispatch).
        ≙ pony_alloc / pony_alloc_msg on the owning actor's heap."""
        b = self._require_blob("blob_alloc")
        if b.resv is None:
            raise RuntimeError(
                "blob_alloc: declare MAX_BLOBS = n on the allocating "
                "actor class (the per-dispatch alloc budget)")
        if b.claims >= b.resv.shape[0]:
            raise RuntimeError(
                f"more than MAX_BLOBS={b.resv.shape[0]} blob_alloc calls "
                "in one behaviour dispatch; raise the declared budget")
        slot = b.resv[b.claims]                # reserved global SLOT ids
        b.claims += 1
        w = jnp.asarray(when, jnp.bool_)
        ok = w & b.take & (slot >= 0)
        wanted = w & b.take & (slot < 0)
        # Blame the right knob: a lane whose whole reservation window
        # was withheld (dispatch count past BLOB_DISPATCHES) failed on
        # BUDGET; a lane holding a real window that still read -1 found
        # the POOL's compacted free list exhausted.
        if b.budget_over is not None:
            b.budget_fail = b.budget_fail | jnp.any(wanted & b.budget_over)
            b.fail = b.fail | jnp.any(wanted & ~b.budget_over)
        else:
            b.fail = b.fail | jnp.any(wanted)
        idx = jnp.where(ok, slot - b.base, b.nslots)  # OOB-high → dropped
        # Bump the slot generation and bake it into the handle (ABA
        # guard): any still-circulating handle from the slot's previous
        # life now mismatches and reads null.
        newgen = (jnp.take(b.gen, idx, mode="fill", fill_value=0)
                  + 1) & pack.BLOB_GEN_MASK
        b.gen = b.gen.at[idx].set(newgen, mode="drop")
        h = pack.blob_handle(slot, newgen)
        b.used = b.used.at[idx].set(True, mode="drop")
        wpool = b.data.shape[0]
        ln = (jnp.int32(wpool) if length is None
              else jnp.clip(jnp.asarray(length, jnp.int32), 0, wpool))
        b.len_ = b.len_.at[idx].set(
            jnp.broadcast_to(ln, idx.shape), mode="drop")
        b.data = b.data.at[:, idx].set(0, mode="drop")
        b.n_alloc = b.n_alloc + jnp.sum(ok.astype(jnp.int32))
        b.alloced = b.alloced | ok
        h2 = jnp.where(ok, h, jnp.int32(-1))
        self.cap_types.tag(h2, "iso")
        return h2

    def blob_get(self, h, i):
        """Read word `i` of blob `h` ([lanes] i32; 0 for null/-1 handles,
        out-of-range words, or handles owned by another shard). Floats:
        ``ctx.blob_get(h, i).view(jnp.float32)``."""
        b = self._require_blob("blob_get")
        self._blob_guard(h, "blob_get")
        h = jnp.asarray(h, jnp.int32)
        hl, ok = b.local(h)
        # Reads of unallocated (freed/stale/forged) slots yield 0, not
        # another blob's leftover words — the same used-gate writes have.
        ok = ok & jnp.take(b.used, hl, mode="fill", fill_value=False)
        i = jnp.asarray(i, jnp.int32)
        nflat = b.data.shape[0] * b.nslots
        flat = jnp.where(ok & (i >= 0) & (i < b.data.shape[0]),
                         jnp.minimum(i, b.data.shape[0] - 1) * b.nslots
                         + jnp.minimum(hl, b.nslots - 1), nflat)
        return jnp.take(b.data.reshape(-1), flat, mode="fill",
                        fill_value=0)

    def blob_length(self, h):
        """Logical word count recorded at blob_alloc ([lanes] i32; 0 for
        null/remote handles)."""
        b = self._require_blob("blob_length")
        self._blob_guard(h, "blob_length")
        h = jnp.asarray(h, jnp.int32)
        hl, _ok = b.local(h)
        return jnp.take(b.len_, hl, mode="fill", fill_value=0)

    def blob_freeze(self, h):
        """Freeze an owned (iso) blob into shared-immutable VAL (≙
        Pony's consume-to-val — `recover val` / trn→val freeze): the
        returned handle aliases freely, so one dispatch may send it to
        MANY readers (declare the parameter ``BlobVal``); writes and
        frees reject at trace; the slot is reclaimed by the GC mark
        pass once no live field/message/host root references it.
        Idempotent on already-val handles."""
        self._require_blob("blob_freeze")
        self._blob_guard(h, "blob_freeze")
        src = self.cap_types.lookup(h)
        if src == "val":
            return h
        self.cap_types.tag(h, "val")
        return h

    def blob_set(self, h, i, v, when=True):
        """Write word `i` of blob `h` (i32; masked by `when`). Only the
        owner holds the handle (iso), so lanes never collide; writes are
        visible to this dispatch's later blob_get calls and to the
        handle's next owner after a send. Floats: pass
        ``value.view(jnp.int32)``."""
        b = self._require_blob("blob_set")
        self._blob_guard(h, "blob_set")
        if self.cap_types.lookup(h) == "val":
            raise TypeError(
                "capability: blob_set on a frozen (val) blob — "
                "shared-immutable payloads cannot be written "
                "(≙ val's deny-write, type/cap.c)")
        h = jnp.asarray(h, jnp.int32)
        hl, okh = b.local(h)
        i = jnp.asarray(i, jnp.int32)
        ok = (jnp.asarray(when, jnp.bool_) & b.take & okh
              & (i >= 0) & (i < b.data.shape[0])
              & jnp.take(b.used, hl, mode="fill", fill_value=False))
        flat = jnp.where(ok, jnp.minimum(i, b.data.shape[0] - 1)
                         * b.nslots + jnp.minimum(hl, b.nslots - 1),
                         b.data.shape[0] * b.nslots)   # OOB-high → dropped
        v = jnp.broadcast_to(jnp.asarray(v, jnp.int32), flat.shape)
        b.data = b.data.reshape(-1).at[flat].set(
            v, mode="drop").reshape(b.data.shape)

    def blob_free(self, h, when=True):
        """Release blob `h` back to the pool. Explicit free is the fast
        path; blobs whose owner died (or whose handle moved off-shard)
        are swept by the next Runtime.gc() mark pass (≙ the owner's
        heap dying with the actor, gc.c/heap.c). Freeing is a MOVE:
        later use of the handle in this dispatch is rejected at trace."""
        b = self._require_blob("blob_free")
        self._blob_guard(h, "blob_free")
        if self.cap_types.lookup(h) == "val":
            raise TypeError(
                "capability: blob_free on a frozen (val) blob — shared "
                "payloads have no single owner to free them; the GC "
                "mark pass reclaims unreferenced val blobs")
        h = jnp.asarray(h, jnp.int32)
        hl, okh = b.local(h)
        ok = (jnp.asarray(when, jnp.bool_) & b.take & okh
              & jnp.take(b.used, hl, mode="fill", fill_value=False))
        idx = jnp.where(ok, hl, b.nslots)           # OOB-high → dropped
        b.used = b.used.at[idx].set(False, mode="drop")
        b.len_ = b.len_.at[idx].set(0, mode="drop")
        b.n_free = b.n_free + jnp.sum(ok.astype(jnp.int32))
        self.cap_moves.move(h, "blob_free")
