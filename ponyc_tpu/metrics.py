"""Metrics / health export — a scrapeable operational surface for a
runtime that serves traffic (PROFILE.md §11; ≙ the production-telemetry
posture of the PGAS actor-runtime paper in PAPERS.md: a serving runtime
exposes counters and a health verdict, it does not wait to be profiled).

``RuntimeOptions(metrics_port=N)`` starts a stdlib-only HTTP thread on
127.0.0.1:N (0 = ephemeral — read ``rt._metrics.port`` back) serving:

- ``/metrics`` — Prometheus text exposition of the PR 4/5/6 counters:
  processed/delivered/rejected/badmsg/deadletter/mutes, per-behaviour
  runs, per-cohort queue-wait p50/p99 + mute ticks, GC passes, window
  length and controller state, host gap, event-/span-ring drops, and
  coded errors by class (``pony_tpu_errors_total{class=...,code=...}``,
  errors.ERROR_CODES).
- ``/healthz`` — a JSON verdict: ``ok`` / ``degraded`` (drops or coded
  errors recorded) / ``stalled`` (the flight.py watchdog tripped, or an
  armed phase stamp has gone silent past the deadline), with the reason.

Scrapes NEVER touch the device: the run loop pushes a snapshot at
window boundaries (``MetricsServer.maybe_update`` — the same
already-fetched-values posture as the analysis writer thread) and the
HTTP thread renders the latest one. The health verdict reads only host
attributes (the phase stamp tuple, the watchdog trip record), so
``/healthz`` keeps answering — and flips to ``stalled`` — while the
device is wedged solid. With ``metrics_port=None`` nothing starts and
(at analysis=0) the step jaxpr is bit-identical to a metrics-free
build (tests/test_metrics.py asserts it PR-4 style).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from .flight import ARMED_PHASES

# Minimum seconds between full snapshot refreshes pushed by the run
# loop (a busy pipelined loop retires windows every few tens of µs;
# re-fetching the behaviour matrix per window would tax the boundary).
REFRESH_S = 0.5

# Consecutive snapshots over which monotonically-growing net egress
# backlog (Net.pending_total) flips /healthz to degraded: a consumer
# has stopped reading and the per-connection buffers only grow.
PENDING_WINDOW = 5


# ---- snapshotting (run-loop thread only: may fetch device counters) ----

def snapshot(rt) -> Dict[str, Any]:
    """One metrics snapshot from a runtime, taken at a host boundary.
    Uses Runtime.profile()'s device fetch when the profiler lanes exist
    (analysis >= 1); degrades to host-side totals at level 0."""
    snap: Dict[str, Any] = {
        "time": time.time(),
        "steps": int(rt.steps_run),
        "behaviours": {},
        "cohorts": {},
        "gc": {},
        "drops": {},
    }
    prof = None
    if rt.opts.analysis >= 1 and rt.state is not None \
            and rt.state.beh_runs.size:
        try:
            prof = rt.profile()
        except Exception:        # noqa: BLE001 — mid-teardown: degrade
            prof = None
    if prof is not None:
        snap["totals"] = dict(prof["totals"])
        snap["behaviours"] = prof["behaviours"]
        snap["cohorts"] = prof["cohorts"]
        snap["gc"] = dict(prof["gc"])
        snap["phases"] = dict(prof.get("phases") or {})
    else:
        snap["totals"] = {
            "processed": int(rt.totals.get("processed", 0)),
            "delivered": int(rt.totals.get("delivered", 0)),
            "host_processed": int(rt.totals.get("host_processed", 0)),
        }
        snap["gc"] = {"passes": int(rt.totals.get("gc_runs", 0))}
    if rt.opts.analysis >= 3 and rt.state is not None:
        import numpy as np
        try:
            snap["drops"]["events"] = int(
                np.asarray(rt._fetch(rt.state.ev_dropped)).sum())
        except Exception:        # noqa: BLE001
            pass
    tracer = getattr(rt, "_tracer", None)
    if tracer is not None:
        snap["drops"]["spans"] = int(tracer.dropped)
    snap["run_loop"] = rt.run_loop_stats()
    snap["queues"] = {"inject": len(rt._inject_q),
                      "fast": len(rt._host_fast_q)}
    net = getattr(rt, "net", None)
    if net is not None:
        # Egress backpressure (ISSUE 9 satellite): unflushed bytes
        # across every live connection — host attribute walk, no device.
        snap["net"] = {"pending_bytes": int(net.pending_total()),
                       "conns": len(net._conns)}
    srv = getattr(rt, "_serve", None)
    if srv is not None:
        snap["serving"] = srv.stats()
    # Measured device costs (ISSUE 19): captured once at start()
    # (opts.cost_capture) or via Runtime.measured_costs() — a host
    # attribute read here, never a compile.
    costs = getattr(rt, "_costs", None)
    if costs is not None:
        snap["measured"] = costs
    snap["errors"] = [
        {"class": cls, "code": int(code), "count": int(n)}
        for (cls, code), n in sorted(rt._error_counts.items())]
    return snap


# ---- health verdict (any thread: host attributes only) ----

def health(rt) -> Dict[str, Any]:
    """The /healthz verdict. `stalled` when the watchdog tripped or an
    armed phase stamp is silent past 2x the effective deadline (belt
    and braces: the trip should land first); `degraded` when coded
    errors or ring drops are on record; else `ok`."""
    wd = getattr(rt, "_watchdog", None)
    phase, epoch, t = getattr(rt, "_wd_stamp", ("idle", 0, 0.0))
    age = max(0.0, time.monotonic() - t) if t else 0.0
    mx = getattr(rt, "_metrics", None)
    snap = mx._snap if mx is not None else {}
    status, reason = "ok", ""
    if wd is not None and wd.tripped is not None:
        status = "stalled"
        reason = (f"watchdog tripped: phase {wd.tripped['phase']!r} "
                  f"silent for {wd.tripped['age_s']}s")
    elif wd is not None and phase in ARMED_PHASES \
            and age > 2 * wd.effective_deadline():
        status = "stalled"
        reason = f"phase {phase!r} stamp silent for {age:.1f}s"
    else:
        errs = snap.get("errors") or [
            {"class": cls, "code": code, "count": n}
            for (cls, code), n in getattr(rt, "_error_counts",
                                          {}).items()]
        drops = snap.get("drops") or {}
        pend = list(mx._pending_hist) if mx is not None else []
        pend_growing = (len(pend) >= PENDING_WINDOW
                        and all(b > a for a, b in zip(pend, pend[1:]))
                        and pend[-1] > 0)
        if errs:
            e = errs[-1]
            status = "degraded"
            reason = (f"{sum(x['count'] for x in errs)} coded error(s) "
                      f"recorded (latest {e['class']}, code {e['code']})")
        elif pend_growing:
            status = "degraded"
            reason = (f"egress backpressure: net pending bytes grew "
                      f"monotonically across {len(pend)} snapshots "
                      f"(now {pend[-1]}) — a consumer stopped reading")
        elif any(int(v) for v in drops.values()):
            status = "degraded"
            reason = "telemetry ring drops: " + ", ".join(
                f"{k}={v}" for k, v in drops.items() if int(v))
    ck = getattr(rt, "_ckpt", None)
    ck_info = ck.info() if ck is not None else None
    return {
        "status": status,
        "reason": reason,
        "phase": phase,
        "phase_age_s": round(age, 3),
        "steps": int(getattr(rt, "steps_run", 0)),
        "snapshot_age_s": (round(time.time() - snap["time"], 3)
                           if snap.get("time") else None),
        # Durable worlds (ISSUE 8): how stale a crash-restore would be.
        # None = checkpointing off; alert on staleness > 2-3 cadences.
        "last_checkpoint_age_s": (ck_info.get("age_s")
                                  if ck_info is not None else None),
        "last_checkpoint_path": (ck_info.get("path")
                                 if ck_info is not None else None),
        "watchdog": wd.snapshot() if wd is not None else None,
    }


# ---- Prometheus text exposition ----

def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def prometheus_text(snap: Dict[str, Any],
                    hz: Optional[Dict[str, Any]] = None) -> str:
    """Render a snapshot (+ optional health verdict) as Prometheus
    text exposition (one metric family per HELP/TYPE pair)."""
    out = []

    def fam(name, kind, help_, rows):
        # rows: [(labels_dict_or_None, value)]
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {kind}")
        for labels, v in rows:
            lab = ""
            if labels:
                lab = "{" + ",".join(
                    f'{k}="{_esc(x)}"'
                    for k, x in sorted(labels.items())) + "}"
            out.append(f"{name}{lab} {int(v) if float(v).is_integer() else v}")

    t = snap.get("totals", {})
    for key, help_ in (
            ("processed", "Behaviours dispatched (device)"),
            ("delivered", "Messages delivered to mailboxes"),
            ("rejected", "Deliveries rejected (backpressure)"),
            ("badmsg", "Malformed messages dropped"),
            ("deadletter", "Messages to dead actors dropped"),
            ("mutes", "Sender mute transitions"),
            ("host_processed", "Host-cohort behaviours dispatched")):
        if key in t:
            fam(f"pony_tpu_{key}_total", "counter", help_,
                [(None, t[key])])
    fam("pony_tpu_steps_total", "counter", "Device ticks advanced",
        [(None, snap.get("steps", 0))])
    beh = snap.get("behaviours", {})
    if beh:
        fam("pony_tpu_behaviour_runs_total", "counter",
            "Dispatches per behaviour (profiler matrix)",
            [({"behaviour": n}, b["runs"]) for n, b in sorted(beh.items())])
        fam("pony_tpu_behaviour_rejected_total", "counter",
            "Rejected deliveries per behaviour",
            [({"behaviour": n}, b["rejected"])
             for n, b in sorted(beh.items())])
    coh = snap.get("cohorts", {})
    if coh:
        fam("pony_tpu_queue_wait_ticks", "gauge",
            "Queue-wait percentiles per cohort (2^k bucket low, ticks)",
            [({"cohort": c, "quantile": q}, v[key])
             for c, v in sorted(coh.items())
             for q, key in (("0.5", "queue_wait_p50"),
                            ("0.99", "queue_wait_p99"))])
        fam("pony_tpu_mute_ticks_total", "counter",
            "Muted actor-ticks per cohort",
            [({"cohort": c}, v["mute_ticks"])
             for c, v in sorted(coh.items())])
    phases = snap.get("phases") or {}
    if phases:
        fam("pony_tpu_phase_work_total", "counter",
            "Per-phase work units (delivery/drain/dispatch/gc_mark "
            "tick-cost lanes, state.PHASE_NAMES)",
            [({"phase": k}, v) for k, v in sorted(phases.items())])
    measured = snap.get("measured") or {}
    if measured:
        rows_b, rows_f, rows_p = [], [], []
        for exe, rec in sorted((measured.get("executables")
                                or {}).items()):
            if rec.get("bytes_accessed") is not None:
                rows_b.append(({"executable": exe},
                               rec["bytes_accessed"]))
            if rec.get("flops") is not None:
                rows_f.append(({"executable": exe}, rec["flops"]))
            if rec.get("peak_bytes") is not None:
                rows_p.append(({"executable": exe}, rec["peak_bytes"]))
        if rows_b:
            fam("pony_tpu_measured_bytes_accessed", "gauge",
                "XLA cost_analysis bytes accessed per compiled "
                "executable (costs.capture)", rows_b)
        if rows_f:
            fam("pony_tpu_measured_flops", "gauge",
                "XLA cost_analysis flops per compiled executable",
                rows_f)
        if rows_p:
            fam("pony_tpu_measured_peak_bytes", "gauge",
                "Device working set per compiled executable "
                "(memory_analysis: args+outputs+temps+code-aliased)",
                rows_p)
        div = measured.get("model_divergence") or {}
        if div.get("ratio") is not None:
            fam("pony_tpu_model_divergence_ratio", "gauge",
                "Measured/modelled bytes-per-message ratio "
                "(1.0 = the model holds)", [(None, div["ratio"])])
            fam("pony_tpu_model_divergence", "gauge",
                "1 when measured bytes/msg disagrees with the model "
                "past tolerance", [(None, 1 if div.get("diverged")
                                    else 0)])
    g = snap.get("gc", {})
    if g:
        fam("pony_tpu_gc_passes_total", "counter", "GC passes run",
            [(None, g.get("passes", 0))])
        if "collected" in g:
            fam("pony_tpu_gc_collected_total", "counter",
                "Actors collected", [(None, g["collected"])])
    rl = snap.get("run_loop") or {}
    if rl:
        fam("pony_tpu_windows_total", "counter", "Windows retired",
            [(None, rl.get("windows", 0))])
        fam("pony_tpu_pipelined_dispatches_total", "counter",
            "Windows dispatched behind an in-flight one",
            [(None, rl.get("pipelined_dispatches", 0))])
        fam("pony_tpu_injects_requeued_total", "counter",
            "Gated-out window injections re-queued",
            [(None, rl.get("injects_requeued", 0))])
        fam("pony_tpu_host_gap_us_total", "counter",
            "Cumulative host-imposed device idle (us)",
            [(None, round(rl.get("host_gap_us_total", 0.0), 1))])
        ctrl = rl.get("controller")
        if ctrl:
            fam("pony_tpu_window_length", "gauge",
                "Adaptive quiesce-window length (ticks)",
                [(None, ctrl["window"])])
    q = snap.get("queues") or {}
    if q:
        fam("pony_tpu_queue_depth", "gauge", "Host-side queue depths",
            [({"queue": k}, v) for k, v in sorted(q.items())])
    net = snap.get("net") or {}
    if net:
        fam("pony_tpu_net_pending_bytes", "gauge",
            "Unflushed egress bytes across all connections "
            "(Net.pending backpressure signal)",
            [(None, net.get("pending_bytes", 0))])
        fam("pony_tpu_net_conns", "gauge", "Live net-layer connections",
            [(None, net.get("conns", 0))])
    srv = snap.get("serving") or {}
    if srv:
        fam("pony_tpu_serve_frames_total", "counter",
            "Request frames received by the front door",
            [(None, srv.get("frames", 0))])
        fam("pony_tpu_serve_accepted_total", "counter",
            "Requests admitted past the edge",
            [(None, srv.get("accepted", 0))])
        fam("pony_tpu_serve_replied_total", "counter",
            "OK replies delivered", [(None, srv.get("replied", 0))])
        fam("pony_tpu_serve_shed_total", "counter",
            "Requests shed at the edge, by reason",
            [({"reason": k}, v)
             for k, v in sorted((srv.get("shed") or {}).items())])
        fam("pony_tpu_serve_badframe_total", "counter",
            "Malformed ingress frames",
            [(None, srv.get("badframe", 0))])
        fam("pony_tpu_serve_inflight", "gauge",
            "Requests on the device right now",
            [(None, srv.get("inflight", 0))])
        fam("pony_tpu_serve_queue_depth", "gauge",
            "Admitted requests awaiting a worker",
            [(None, srv.get("queue", 0))])
        adm = srv.get("admission") or {}
        if adm:
            fam("pony_tpu_serve_admit_limit", "gauge",
                "Admission controller concurrency limit",
                [(None, adm.get("limit", 0))])
        lat = srv.get("latency_us") or {}
        if lat.get("n"):
            fam("pony_tpu_serve_latency_us", "gauge",
                "End-to-end request latency percentiles (us, host "
                "clock, bounded reservoir)",
                [({"quantile": "0.5"}, lat["p50"]),
                 ({"quantile": "0.99"}, lat["p99"])])
    drops = snap.get("drops") or {}
    if drops:
        fam("pony_tpu_ring_drops_total", "counter",
            "Bounded telemetry ring drops (events/spans)",
            [({"ring": k}, v) for k, v in sorted(drops.items())])
    errs = snap.get("errors") or []
    if errs:
        fam("pony_tpu_errors_total", "counter",
            "Coded runtime errors (errors.ERROR_CODES)",
            [({"class": e["class"], "code": str(e["code"])}, e["count"])
             for e in errs])
    if hz is not None:
        fam("pony_tpu_health", "gauge",
            "Health verdict: 1 ok, 0.5 degraded, 0 stalled",
            [(None, {"ok": 1, "degraded": 0.5}.get(hz["status"], 0))])
    return "\n".join(out) + "\n"


def parse_prometheus(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                         ...]], float]:
    """Tiny exposition-format parser (tests, doctor, bench smoke):
    {(metric_name, sorted_label_items): value}. Ignores comments."""
    import re
    lab_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        labels: Tuple[Tuple[str, str], ...] = ()
        name = head
        if "{" in head:
            name, _, rest = head.partition("{")
            body = rest.rsplit("}", 1)[0]
            labels = tuple(sorted(
                (k, v.replace('\\"', '"').replace("\\n", "\n")
                    .replace("\\\\", "\\"))
                for k, v in lab_re.findall(body)))
        try:
            out[(name, labels)] = float(val)
        except ValueError:
            continue
    return out


# ---- the HTTP thread ----

class _Handler(BaseHTTPRequestHandler):
    server_version = "ponyc-tpu-metrics/1"

    def do_GET(self):          # noqa: N802 — http.server API
        srv: MetricsServer = self.server.metrics   # type: ignore[attr-defined]
        if self.path.split("?")[0] in ("/metrics", "/"):
            hz = health(srv.rt)
            body = prometheus_text(srv._snap, hz).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.split("?")[0] == "/healthz":
            hz = health(srv.rt)
            body = (json.dumps(hz) + "\n").encode()
            ctype = "application/json"
        else:
            self.send_error(404, "unknown path (try /metrics, /healthz)")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):     # scrapes must not spam stderr
        pass


class MetricsServer:
    """Per-runtime exporter. Constructed by Runtime.start() when
    opts.metrics_port is not None; `update*` is called from the
    run-loop thread only (it may fetch device counters), the HTTP
    thread only ever reads the last snapshot reference."""

    def __init__(self, rt, port: int):
        self.rt = rt
        self._snap: Dict[str, Any] = {}
        self._last_full = 0.0
        # Net egress-backlog trail: one reading per snapshot refresh;
        # health() flips to degraded when it grows monotonically
        # across the whole window (a consumer stopped reading).
        import collections as _c
        self._pending_hist: "_c.deque" = _c.deque(maxlen=PENDING_WINDOW)
        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._httpd.metrics = self    # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="pony-tpu-metrics", daemon=True)
        self._thread.start()

    def update_now(self, rt) -> None:
        """Force a full snapshot refresh (run start/end, stop())."""
        try:
            self._snap = snapshot(rt)
            if "net" in self._snap:
                self._pending_hist.append(
                    int(self._snap["net"]["pending_bytes"]))
        except Exception:        # noqa: BLE001 — teardown must not raise
            pass
        self._last_full = time.monotonic()

    def maybe_update(self, rt) -> None:
        """Boundary hook: refresh at most every REFRESH_S — the scrape
        surface trails the run by <1s without taxing a pipelined loop
        that retires windows every few tens of µs."""
        now = time.monotonic()
        if now - self._last_full >= REFRESH_S:
            self.update_now(rt)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:        # noqa: BLE001
            pass
        self._thread.join(timeout=2.0)


# ---- doctor's live-endpoint reading ----

def fetch_endpoint(url: str, timeout_s: float = 5.0
                   ) -> Tuple[Dict[str, Any], str]:
    """GET /healthz + /metrics from a live exporter. `url` may be
    'host:port', 'http://host:port' or either endpoint path. Returns
    (healthz_dict, metrics_text)."""
    import urllib.request
    base = url if "://" in url else "http://" + url
    for suffix in ("/healthz", "/metrics"):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
    with urllib.request.urlopen(base + "/healthz",
                                timeout=timeout_s) as r:
        hz = json.loads(r.read().decode())
    with urllib.request.urlopen(base + "/metrics",
                                timeout=timeout_s) as r:
        mx = r.read().decode()
    return hz, mx


def diagnose_endpoint(url: str, timeout_s: float = 5.0
                      ) -> Tuple[str, str, str]:
    """(status, one_line, detail) for a live exporter — the doctor's
    live half. Raises OSError when the endpoint is unreachable."""
    hz, mx = fetch_endpoint(url, timeout_s)
    parsed = parse_prometheus(mx)
    status = hz.get("status", "?")
    bits = [f"phase {hz.get('phase', '?')!r}",
            f"steps {hz.get('steps', '?')}"]
    if hz.get("reason"):
        bits.append(hz["reason"])
    line = f"{status.upper()}: " + "; ".join(bits)
    keys = ("pony_tpu_processed_total", "pony_tpu_delivered_total",
            "pony_tpu_windows_total", "pony_tpu_window_length",
            # Serving front door (serve.py), when attached.
            "pony_tpu_serve_frames_total",
            "pony_tpu_serve_accepted_total",
            "pony_tpu_serve_replied_total",
            "pony_tpu_serve_admit_limit",
            "pony_tpu_net_pending_bytes")
    detail_lines = [f"endpoint: {url}"]
    for k in keys:
        v = parsed.get((k, ()))
        if v is not None:
            detail_lines.append(f"{k} = {int(v)}")
    # Serving verdict colour: shed volume by reason + the shed rate —
    # the first thing an overload postmortem wants to know.
    sheds = {lab: v for (name, lab), v in parsed.items()
             if name == "pony_tpu_serve_shed_total"}
    if sheds:
        total_shed = int(sum(sheds.values()))
        frames = parsed.get(("pony_tpu_serve_frames_total", ()), 0)
        rate = total_shed / frames if frames else 0.0
        detail_lines.append(
            f"serve shed: {total_shed} ({rate:.1%} of frames; "
            + ", ".join(f"{dict(lab).get('reason', '?')}={int(v)}"
                        for lab, v in sorted(sheds.items())) + ")")
    for (name, labels), v in sorted(parsed.items()):
        if name == "pony_tpu_errors_total":
            lab = ", ".join(f"{k}={x}" for k, x in labels)
            detail_lines.append(f"{name}{{{lab}}} = {int(v)}")
    return status, line, "\n".join(detail_lines)
