"""Program build: whole-world actor-type registry → cohorts + dispatch table.

≙ the reference compiler's reachability + vtable painting stage
(src/libponyc/reach/reach.c builds the whole-program reachable type/method
set from Main; reach/paint.c colours method names into dispatch-table slots).
On TPU the same whole-program knowledge is what makes behaviour dispatch
vectorisable: actors are grouped into *cohorts by type* so each cohort's
dispatch is a `lax.switch` over only that type's behaviours (SURVEY.md §7
hard part (b) — heterogeneity kills vectorisation, cohorts bound it).

Global actor ids are a single [0, N) range; each type owns a contiguous
slice, so a message's routing needs only the id (the mailbox table is one
dense array) while dispatch semantics come from the owning cohort.
Behaviour ids are *global* (word 0 of every message); each cohort's switch
re-bases them and treats out-of-range ids as a traced no-op — the dynamic
analog of the type check Pony does statically.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .api import ActorTypeMeta
from .config import RuntimeOptions


class Cohort:
    """A contiguous id-range of actors of one type (≙ one reach_type_t)."""

    def __init__(self, atype: ActorTypeMeta, start: int, capacity: int,
                 opts: RuntimeOptions):
        self.atype = atype
        self.start = start
        self.capacity = capacity            # max live actors of this type
        self.batch = atype.BATCH or opts.batch
        self.priority = atype.PRIORITY
        self.host = bool(atype.HOST)
        # Static send budget: max ctx.send() calls across this type's
        # behaviours is discovered at trace time; the declared bound here is
        # the engine's outbox width. Behaviours exceeding it fail loudly at
        # trace, not silently at run.
        self.max_sends = getattr(atype, "MAX_SENDS", None) or opts.max_sends
        self.behaviours = list(atype.behaviour_defs)

    @property
    def stop(self) -> int:
        return self.start + self.capacity

    def __repr__(self):
        return (f"<cohort {self.atype.__name__} ids=[{self.start},"
                f"{self.stop}) batch={self.batch}>")


class Program:
    """The compiled actor world: types, capacities, id layout, dispatch ids.

    Build order (≙ pass pipeline tail, pass.h:208-231 reach→paint→codegen):
      1. declare(Type, capacity) for every actor type
      2. finalize() assigns cohort id ranges + global behaviour ids
      3. the engine traces one dispatch step over the frozen layout
    """

    def __init__(self, opts: Optional[RuntimeOptions] = None):
        self.opts = opts or RuntimeOptions()
        self._declared: List[Tuple[ActorTypeMeta, int]] = []
        self.cohorts: List[Cohort] = []
        self.by_type: Dict[ActorTypeMeta, Cohort] = {}
        self.behaviour_table: List = []   # global id → BehaviourDef
        self.total = 0
        self.frozen = False

    def declare(self, atype: ActorTypeMeta, capacity: int):
        if self.frozen:
            raise RuntimeError("Program already finalized")
        if not isinstance(atype, ActorTypeMeta):
            raise TypeError(f"{atype!r} is not an actor type (use @actor)")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._declared.append((atype, capacity))
        return self

    def finalize(self) -> "Program":
        if self.frozen:
            return self
        # Host cohorts last: their ids sit in a contiguous tail range so the
        # device delivery can classify "host-bound" with one compare
        # (≙ inject_main diverting use_main_thread actors, scheduler.c:179).
        self._declared.sort(key=lambda tc: bool(tc[0].HOST))
        offset = 0
        for atype, cap in self._declared:
            cohort = Cohort(atype, offset, cap, self.opts)
            self.cohorts.append(cohort)
            self.by_type[atype] = cohort
            offset += cap
        self.total = offset
        gid = 0
        for cohort in self.cohorts:
            for local, bdef in enumerate(cohort.behaviours):
                bdef.global_id = gid
                bdef.local_id = local
                self.behaviour_table.append(bdef)
                gid += 1
        self.frozen = True
        return self

    @property
    def device_cohorts(self) -> List[Cohort]:
        return [c for c in self.cohorts if not c.host]

    @property
    def host_cohorts(self) -> List[Cohort]:
        return [c for c in self.cohorts if c.host]

    @property
    def first_host_id(self) -> int:
        """Ids >= this are host-resident actors (tail range), or total if
        there are none."""
        for c in self.cohorts:
            if c.host:
                return c.start
        return self.total

    def cohort_of(self, actor_id: int) -> Cohort:
        for c in self.cohorts:
            if c.start <= actor_id < c.stop:
                return c
        raise IndexError(f"actor id {actor_id} out of range [0,{self.total})")
