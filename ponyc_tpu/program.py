"""Program build: whole-world actor-type registry → cohorts + dispatch table.

≙ the reference compiler's reachability + vtable painting stage
(src/libponyc/reach/reach.c builds the whole-program reachable type/method
set from Main; reach/paint.c colours method names into dispatch-table slots).
On TPU the same whole-program knowledge is what makes behaviour dispatch
vectorisable: actors are grouped into *cohorts by type* so each cohort's
dispatch is a `lax.switch` over only that type's behaviours (SURVEY.md §7
hard part (b) — heterogeneity kills vectorisation, cohorts bound it).

Global actor ids are a single [0, N) range; each type owns a contiguous
slice, so a message's routing needs only the id (the mailbox table is one
dense array) while dispatch semantics come from the owning cohort.
Behaviour ids are *global* (word 0 of every message); each cohort's switch
re-bases them and treats out-of-range ids as a traced no-op — the dynamic
analog of the type check Pony does statically.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .api import ActorTypeMeta
from .config import RuntimeOptions


class Cohort:
    """The actors of one type (≙ one reach_type_t).

    Id layout is *shard-major, cohort-minor* so the same static per-shard
    slicing works on every mesh shard (see Program docstring): global actor
    id = shard * n_local + local_start + (slot // shards), where `slot` is
    the cohort-relative slot (slot % shards picks the shard, round-robin
    for balance). With shards == 1 this degenerates to the contiguous
    [start, stop) range.
    """

    def __init__(self, atype: ActorTypeMeta, capacity: int,
                 opts: RuntimeOptions, shards: int):
        self.atype = atype
        self.shards = shards
        # Round capacity up so every shard holds the same number of rows.
        self.capacity = -(-capacity // shards) * shards
        self.local_capacity = self.capacity // shards
        self.local_start = 0        # per-shard row offset; set by finalize()
        self.batch = atype.BATCH or opts.batch
        self.priority = atype.PRIORITY
        self.host = bool(atype.HOST)
        # Static send budget: max ctx.send() calls across this type's
        # behaviours is discovered at trace time; the declared bound here is
        # the engine's outbox width. Behaviours exceeding it fail loudly at
        # trace, not silently at run.
        self.max_sends = getattr(atype, "MAX_SENDS", None) or opts.max_sends
        self.behaviours = list(atype.behaviour_defs)
        # Per-cohort mailbox word width (≙ per-type pony_msg_t sizes —
        # genfun.c packs exactly each behaviour's params; the reference
        # never pays one type's width for another's messages). The
        # cohort's mailbox table holds only what its own behaviours can
        # receive: min(opts.msg_words, widest behaviour). opts.msg_words
        # stays the program-wide declared maximum (outbox/spill/inject
        # width); narrower cohorts just stop paying HBM for it.
        from .ops.pack import spec_width
        need = max((sum(spec_width(s) for s in b.arg_specs)
                    for b in self.behaviours), default=0)
        self.msg_words = min(opts.msg_words, need)
        self.n_local_total = 0      # rows per shard over all cohorts (set later)
        # Resolved by Program.finalize():
        self.spawns: Dict[str, int] = {}     # target type name → sites/dispatch
        self.spawn_offsets: Dict[str, int] = {}  # target name → offset into
        #   the target cohort's compacted free-row list (static partition)
        sd = getattr(atype, "SPAWN_DISPATCHES", None)
        self.spawn_dispatches = min(self.batch, sd) if sd else self.batch
        # Device blob pool (≙ actor-heap payloads; ops.pack.Blob):
        # MAX_BLOBS = per-dispatch ctx.blob_alloc budget; blob_offset is
        # this cohort's static window into the compacted free-slot list
        # (set by Program._resolve_blobs).
        self.blob_sites = int(getattr(atype, "MAX_BLOBS", 0) or 0)
        self.blob_offset = 0
        bdk = getattr(atype, "BLOB_DISPATCHES", None)
        if bdk is not None and int(bdk) < 0:
            raise TypeError(
                f"{atype.__name__}.BLOB_DISPATCHES must be >= 0")
        # 0 is a real value (this type reserves nothing this config);
        # only None means "default: every dispatch may allocate".
        self.blob_dispatches = (min(self.batch, int(bdk))
                                if bdk is not None else self.batch)

    @property
    def uses_blobs(self) -> bool:
        """Does this cohort touch the device blob pool (allocates, or
        holds/receives Blob handles)? Decides whether the dispatch
        threads the pool arrays (engine._cohort_dispatch)."""
        from .ops.pack import is_blob
        if self.blob_sites:
            return True
        if any(is_blob(s) for s in self.atype.field_specs.values()):
            return True
        return any(is_blob(s) for b in self.behaviours
                   for s in b.arg_specs)

    def slot_to_gid(self, slot):
        """Cohort slot → global actor id (vectorised, numpy-friendly)."""
        shard = slot % self.shards
        row = self.local_start + slot // self.shards
        return shard * self.n_local_total + row

    def slot_to_col(self, slot):
        """Cohort slot → row in this cohort's [capacity] state columns
        (shard-major so the column array shards cleanly on its leading
        axis)."""
        shard = slot % self.shards
        return shard * self.local_capacity + slot // self.shards

    def gid_to_col(self, gid):
        """Global actor id → state-column row (vectorised)."""
        shard = gid // self.n_local_total
        row = gid % self.n_local_total - self.local_start
        return shard * self.local_capacity + row

    @property
    def local_stop(self) -> int:
        return self.local_start + self.local_capacity

    def __repr__(self):
        return (f"<cohort {self.atype.__name__} cap={self.capacity}"
                f"×{self.shards}sh batch={self.batch}>")


class Program:
    """The compiled actor world: types, capacities, id layout, dispatch ids.

    Build order (≙ pass pipeline tail, pass.h:208-231 reach→paint→codegen):
      1. declare(Type, capacity) for every actor type
      2. finalize() assigns cohort id ranges + global behaviour ids
      3. the engine traces one dispatch step over the frozen layout
    """

    def __init__(self, opts: Optional[RuntimeOptions] = None):
        self.opts = opts or RuntimeOptions()
        self.shards = max(1, self.opts.mesh_shards)
        self._declared: List[Tuple[ActorTypeMeta, int]] = []
        self.cohorts: List[Cohort] = []
        self.by_type: Dict[ActorTypeMeta, Cohort] = {}
        self.behaviour_table: List = []   # global id → BehaviourDef
        self.total = 0
        self.n_local = 0                  # actor rows per shard
        self.frozen = False

    def declare(self, atype: ActorTypeMeta, capacity: int):
        if self.frozen:
            raise RuntimeError("Program already finalized")
        if not isinstance(atype, ActorTypeMeta):
            raise TypeError(f"{atype!r} is not an actor type (use @actor)")
        if getattr(atype, "_type_params", ()):
            params = ", ".join(p.name for p in atype._type_params)
            raise TypeError(
                f"{atype.__name__} is generic over [{params}] — declare "
                f"a reification (e.g. {atype.__name__}[I32]) instead; "
                "only concrete types have a layout (≙ reify.c: codegen "
                "sees reified types only)")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._declared.append((atype, capacity))
        return self

    def finalize(self) -> "Program":
        if self.frozen:
            return self
        # Host cohorts last: their rows sit in a contiguous per-shard tail
        # range so delivery can classify "host-bound" with one compare
        # (≙ inject_main diverting use_main_thread actors, scheduler.c:179).
        # On a mesh each shard carries its share of every host cohort's
        # mailbox rows (shard-major slots, like device cohorts); the host
        # driver drains them all at poll boundaries — the mesh analog of
        # the main-thread scheduler (scheduler.c:179-190, 1030-1035).
        self._declared.sort(key=lambda tc: bool(tc[0].HOST))
        offset = 0
        for atype, cap in self._declared:
            cohort = Cohort(atype, cap, self.opts, self.shards)
            cohort.local_start = offset
            offset += cohort.local_capacity
            self.cohorts.append(cohort)
            self.by_type[atype] = cohort
        self.n_local = offset
        self.total = offset * self.shards
        for cohort in self.cohorts:
            cohort.n_local_total = self.n_local
        gid = 0
        for cohort in self.cohorts:
            for local, bdef in enumerate(cohort.behaviours):
                bdef.global_id = gid
                bdef.local_id = local
                self.behaviour_table.append(bdef)
                gid += 1
        # Verify pass (≙ the compiler's post-typecheck verify/, and
        # type/safeto.c's sendability): every typed Ref[T] field or
        # behaviour argument must name a type declared in this program —
        # a miswired program fails HERE, at build, not as runtime badmsg.
        # Payload geometry is verified too: a behaviour's total argument
        # width (vector args count their k words) must fit msg_words, and
        # vector specs are message-payload-only (state columns are
        # scalar by design — use one field per component).
        from .ops.pack import _VecSpec, ref_target, spec_width
        declared = {c.atype.__name__ for c in self.cohorts}
        for cohort in self.cohorts:
            for fname, spec in cohort.atype.field_specs.items():
                if isinstance(spec, _VecSpec):
                    raise TypeError(
                        f"{cohort.atype.__name__}.{fname}: {spec.__name__} "
                        "is a message-payload annotation; state fields are "
                        "scalar columns — declare one field per component")
                t = ref_target(spec)
                if t is not None and t not in declared:
                    raise TypeError(
                        f"{cohort.atype.__name__}.{fname} is Ref[{t}] but "
                        f"{t} is not declared in this program")
            for b in cohort.behaviours:
                total = sum(spec_width(s) for s in b.arg_specs)
                if total > self.opts.msg_words:
                    raise TypeError(
                        f"{cohort.atype.__name__}.{b.name} needs {total} "
                        f"payload words but msg_words="
                        f"{self.opts.msg_words}; raise "
                        "RuntimeOptions.msg_words")
                for i, spec in enumerate(b.arg_specs):
                    t = ref_target(spec)
                    if t is not None and t not in declared:
                        raise TypeError(
                            f"{cohort.atype.__name__}.{b.name} arg "
                            f"{b.arg_names[i]!r} is Ref[{t}] but {t} is "
                            "not declared in this program")
        self._resolve_spawns()
        self._resolve_blobs()
        self.frozen = True
        from . import plugin as _plugin
        if _plugin.active():
            _plugin.run_build_hooks(self)
        return self

    def _resolve_spawns(self) -> None:
        """Resolve SPAWNS declarations and statically partition each target
        cohort's free-slot list among its spawner cohorts.

        ≙ pony_create's allocation (actor.c:688) done ahead of time: each
        (spawner, target) pair owns a window of the target's compacted
        free rows; within the window, each *runnable* actor gets
        spawn_dispatches × sites disjoint slots (ranked by a cumsum over
        the runnable mask at step time), so concurrent vmapped spawns can
        never collide while idle actors reserve nothing. The static
        partition *between* spawner cohorts is worst-case
        (capacity × spawn_dispatches × sites) — the TPU-static price: a
        second spawner cohort can exhaust its window while the first's
        still has slots. Reservations unused at the end of a step simply
        remain free.
        """
        by_name = {c.atype.__name__: c for c in self.cohorts}
        offsets: Dict[str, int] = {n: 0 for n in by_name}
        for cohort in self.cohorts:
            raw = getattr(cohort.atype, "SPAWNS", {}) or {}
            for key, sites in raw.items():
                tname = key if isinstance(key, str) else key.__name__
                target = by_name.get(tname)
                if target is None:
                    raise TypeError(
                        f"{cohort.atype.__name__}.SPAWNS names {tname!r}, "
                        "which is not declared in this Program")
                if target.host or cohort.host:
                    raise TypeError(
                        "device-side spawn between host cohorts is not "
                        "supported; spawn host actors from the host API")
                if sites < 1:
                    continue
                cohort.spawns[tname] = int(sites)
                cohort.spawn_offsets[tname] = offsets[tname]
                offsets[tname] += (cohort.local_capacity
                                   * cohort.spawn_dispatches * int(sites))

    def _resolve_blobs(self) -> None:
        """Validate blob-pool usage and statically partition the free
        list among allocating cohorts (the _resolve_spawns pattern for
        the "actor heap"): each allocating cohort owns a
        capacity × BLOB_DISPATCHES × MAX_BLOBS window; unused
        reservations simply stay free. Blob handles are device-side values — host cohorts
        cannot hold or receive them (the host touches blob words via
        Runtime.blob_fetch/blob_store between steps)."""
        from .ops.pack import is_blob
        offset = 0
        for cohort in self.cohorts:
            if not cohort.uses_blobs:
                continue
            if self.opts.blob_slots <= 0:
                raise TypeError(
                    f"{cohort.atype.__name__} uses the device blob pool "
                    "(MAX_BLOBS or Blob annotations) but the pool is "
                    "disabled — set RuntimeOptions.blob_slots and "
                    "blob_words")
            if cohort.host:
                raise TypeError(
                    f"host actor type {cohort.atype.__name__} declares "
                    "blob usage; blobs are device-resident — use "
                    "Runtime.blob_fetch/blob_store host-side")
            cohort.blob_offset = offset
            offset += (cohort.local_capacity * cohort.blob_dispatches
                       * cohort.blob_sites)

    def lint(self, roots=None):
        """Whole-program static analysis over this program's world
        (≙ running reach/paint + safeto ahead of codegen): returns the
        list of lint Findings — see ponyc_tpu.lint for the rules
        (R1 reachability … R5 budget feasibility), roots, and
        suppressions. Callable before or after finalize(); probes with
        this program's own msg_words/max_sends resolution."""
        from .lint import lint_types
        declared = (self._declared if not self.frozen
                    else [(c.atype, 0) for c in self.cohorts])
        return lint_types(*(t for t, _ in declared), roots=roots,
                          msg_words=self.opts.msg_words,
                          default_max_sends=self.opts.max_sends)

    @property
    def has_device_spawns(self) -> bool:
        return any(c.spawns for c in self.cohorts)

    @property
    def spawn_target_names(self):
        out = []
        for c in self.cohorts:
            for t in c.spawns:
                if t not in out:
                    out.append(t)
        return out

    @property
    def device_cohorts(self) -> List[Cohort]:
        return [c for c in self.cohorts if not c.host]

    @property
    def host_cohorts(self) -> List[Cohort]:
        return [c for c in self.cohorts if c.host]

    @property
    def first_host_row(self) -> int:
        """Per-shard rows >= this belong to host-resident actors (tail
        range), or n_local if there are none."""
        for c in self.cohorts:
            if c.host:
                return c.local_start
        return self.n_local

    def by_type_name(self, name: str) -> Cohort:
        for c in self.cohorts:
            if c.atype.__name__ == name:
                return c
        raise KeyError(name)

    def cohort_of(self, actor_id: int) -> Cohort:
        if not 0 <= actor_id < self.total:
            raise IndexError(
                f"actor id {actor_id} out of range [0,{self.total})")
        row = actor_id % self.n_local
        for c in self.cohorts:
            if c.local_start <= row < c.local_stop:
                return c
        raise IndexError(f"actor id {actor_id} maps to no cohort")

    def gid_to_slot(self, actor_id: int) -> int:
        """Inverse of Cohort.slot_to_gid."""
        c = self.cohort_of(actor_id)
        shard, row = divmod(actor_id, self.n_local)
        return (row - c.local_start) * self.shards + shard
