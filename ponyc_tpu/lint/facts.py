"""Fact gathering for the whole-program lint pass.

Probes every behaviour of the analysed world with the verify pass's
probe tracer (verify.probe_behaviour — jax.eval_shape only, no
compilation, milliseconds per behaviour) and collects per-behaviour
facts: the effect signature, one SendFact per send/spawn site (target
behaviour, when-mask constness, argument capability tags), blob-op
sites, and — crucially — probe FAILURES. A behaviour whose trace
raises a capability/sendability TypeError is not a crash here: the
failure is itself a fact, which rules.py lifts into an R3 finding
(the whole-program version of the trace-time checks).

Host behaviours (HOST=True types) run real Python and are not traced;
they contribute zero-effect node facts so the message-flow graph sees
the host cohorts device messages land on (≙ inject_main's
use_main_thread actors, scheduler.c:179).
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Dict, List, Optional, Tuple

from ..api import ActorTypeMeta
from ..ops import pack
from ..verify import (Effects, SendFact, behaviour_effects,
                      behaviour_location, probe_behaviour)


@dataclasses.dataclass(frozen=True)
class BehaviourFacts:
    """Everything the probe learned about one behaviour."""

    type_name: str
    behaviour: str
    host: bool
    effects: Effects
    sends: Tuple[SendFact, ...] = ()
    blob_alloc_whens: Tuple[Optional[bool], ...] = ()   # per alloc site
    blob_free_sites: int = 0
    blob_freeze_sites: int = 0
    error: Optional[str] = None        # probe raised: the message
    error_kind: Optional[str] = None   # "capability"|"sendability"|"trace"
    file: Optional[str] = None         # def site (behaviour_location)
    line: Optional[int] = None
    ignore: Tuple[str, ...] = ()       # behaviour-level LINT_IGNORE

    @property
    def node(self) -> Tuple[str, str]:
        return (self.type_name, self.behaviour)


@dataclasses.dataclass(frozen=True)
class TypeFacts:
    """One actor type's static declarations + its behaviours' facts."""

    atype: ActorTypeMeta
    name: str
    host: bool
    spawns_declared: Dict[str, int]      # SPAWNS, names normalised
    max_blobs: int
    ignore: Tuple[str, ...]              # LINT_IGNORE rule ids
    roots_declared: Tuple[str, ...]      # LINT_ROOTS behaviour names
    behaviours: Tuple[BehaviourFacts, ...]
    file: Optional[str] = None           # class def site, if derivable
    line: Optional[int] = None

    def blob_specs(self):
        """(where, spec) for every Blob/BlobVal field or parameter —
        rules.py's R3 host-blob scan."""
        out = []
        for fname, spec in self.atype.field_specs.items():
            if pack.is_blob(spec):
                out.append((None, fname, spec))
        for b in self.atype.behaviour_defs:
            for aname, spec in zip(b.arg_names, b.arg_specs):
                if pack.is_blob(spec):
                    out.append((b.name, aname, spec))
        return out


def _classify(msg: str) -> str:
    if "capability:" in msg:
        return "capability"
    if "sendability:" in msg:
        return "sendability"
    return "trace"


def gather_type(atype: ActorTypeMeta, msg_words: int = 8,
                default_max_sends: int = 2) -> TypeFacts:
    """Probe one actor type's behaviours into TypeFacts."""
    name = atype.__name__
    host = bool(getattr(atype, "HOST", False))
    spawns = {(t if isinstance(t, str) else t.__name__): int(n)
              for t, n in (getattr(atype, "SPAWNS", {}) or {}).items()}
    ignore = tuple(str(r) for r in getattr(atype, "LINT_IGNORE", ()) or ())
    roots = tuple(
        (r.name if hasattr(r, "name") else str(r))
        for r in getattr(atype, "LINT_ROOTS", ()) or ())
    bfs: List[BehaviourFacts] = []
    for bdef in atype.behaviour_defs:
        bfile, bline = behaviour_location(bdef)
        bignore = tuple(getattr(bdef, "lint_ignore", ()) or ()) + tuple(
            str(r) for r in getattr(bdef, "LINT_IGNORE", ()) or ())
        if host:
            bfs.append(BehaviourFacts(
                type_name=name, behaviour=bdef.name, host=True,
                effects=behaviour_effects(bdef, atype),
                file=bfile, line=bline, ignore=bignore))
            continue
        try:
            ctx = probe_behaviour(bdef, atype, msg_words=msg_words)
        except (TypeError, RuntimeError, ValueError) as e:
            bfs.append(BehaviourFacts(
                type_name=name, behaviour=bdef.name, host=False,
                effects=Effects(sends=0, max_sends=0, can_error=False,
                                can_destroy=False, can_exit=False,
                                can_yield=False, spawns=(),
                                sync_spawns=()),
                error=str(e), error_kind=_classify(str(e)),
                file=bfile, line=bline, ignore=bignore))
            continue
        max_sends = (getattr(atype, "MAX_SENDS", None)
                     or int(default_max_sends))
        eff = Effects(
            sends=len(ctx.sends),
            max_sends=int(max_sends),
            can_error=ctx.error_called,
            can_destroy=ctx.destroy_called,
            can_exit=ctx.exit_called,
            can_yield=ctx.yield_called,
            spawns=tuple(sorted(
                (t, len(c)) for t, c in ctx.spawn_claims.items() if c)),
            sync_spawns=tuple(sorted(ctx.sync_inits.keys())),
            blob_allocs=(ctx._blob.claims if ctx._blob is not None
                         else 0),
        )
        bfs.append(BehaviourFacts(
            type_name=name, behaviour=bdef.name, host=False,
            effects=eff, sends=tuple(ctx.send_facts),
            blob_alloc_whens=tuple(ctx.blob_alloc_whens),
            blob_free_sites=ctx.blob_free_sites,
            blob_freeze_sites=ctx.blob_freeze_sites,
            file=bfile, line=bline, ignore=bignore))
    try:
        tfile = inspect.getsourcefile(atype)
        tline = inspect.getsourcelines(atype)[1]
    except (OSError, TypeError):         # reified/exec'd types
        tfile, tline = (bfs[0].file, bfs[0].line) if bfs else (None, None)
    return TypeFacts(atype=atype, name=name, host=host,
                     spawns_declared=spawns, max_blobs=int(
                         getattr(atype, "MAX_BLOBS", 0) or 0),
                     ignore=ignore, roots_declared=roots,
                     behaviours=tuple(bfs), file=tfile, line=tline)


def gather(atypes, msg_words: int = 8,
           default_max_sends: int = 2) -> Dict[str, TypeFacts]:
    """The analysed world: {type name: TypeFacts}, insertion-ordered.
    Generic templates have no layout (≙ reify.c) and are rejected —
    pass reifications (Cls[I32])."""
    world: Dict[str, TypeFacts] = {}
    for atype in atypes:
        if not isinstance(atype, ActorTypeMeta):
            raise TypeError(f"{atype!r} is not an actor type (use @actor)")
        if getattr(atype, "_type_params", ()):
            params = ", ".join(p.name for p in atype._type_params)
            raise TypeError(
                f"{atype.__name__} is generic over [{params}] — lint a "
                f"reification (e.g. {atype.__name__}[I32]) instead")
        if atype.__name__ in world:
            continue
        world[atype.__name__] = gather_type(
            atype, msg_words=msg_words,
            default_max_sends=default_max_sends)
    return world
