"""Lint rule passes over the message-flow graph.

Rule ids are STABLE (suppressions and machine diffs key on them):

  R0  analysis failure — a behaviour failed to probe-trace for a
      reason that is not a capability/sendability violation; the lint
      result for it is incomplete.                          [error]
  R1  reachability (≙ libponyc reach/paint): behaviours/types no
      root or host inject site can reach. Only runs when roots are
      declared (LINT_ROOTS / roots=) — without them any behaviour may
      legally be injected from the host.                    [warning]
  R2  dead-letter: sends that provably cannot deliver — target type
      outside the analysed program [error]; a when=False-masked site
      [warning]; in rooted mode, a device type nothing ever spawns
      [warning].
  R3  capability/race lint: the whole-program lift of the trace-time
      iso/val discipline (an iso aliased into two sends, writes to
      val-frozen blobs, sendability breaks), plus device blob handles
      declared on HOST cohorts.                             [error]
  R4  amplification/overflow: an unconditional message cycle whose
      send multiplicity exceeds 1 with no yield pressure point on the
      cycle — a static mailbox-overflow risk (mailbox_cap). [warning]
  R5  budget feasibility: unconditional spawn/blob-alloc sites on a
      message cycle exhaust the SPAWNS / blob pools [warning];
      declared budgets no site ever uses reserve pool slots for
      nothing [info].

Rules R6–R9 are the behaviour-body SOURCE rules (bodycheck.py — pure
AST, no trace, no import of the target): R6 traced-value control flow,
R7 non-static effect sites, R8 state-key discipline, R9 host impurity
and linear-handle misuse. Their findings carry exact file/line/col;
the graph rules here attach the behaviour's def site where derivable.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, FrozenSet, List, Optional, Sequence

from .graph import FlowGraph, Node

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. Stable, machine-diffable identity: (rule,
    type, behaviour, message); `file`/`line`/`col` locate the finding
    in source where derivable (None = unknown)."""

    rule: str                    # "R0".."R9"
    severity: str                # "error" | "warning" | "info"
    type_name: str               # subject actor type (suppression key)
    behaviour: Optional[str]     # None = type-level finding
    message: str
    file: Optional[str] = None
    line: Optional[int] = None
    col: Optional[int] = None    # 1-based column, body rules only

    def __str__(self) -> str:
        loc = self.type_name + (f".{self.behaviour}" if self.behaviour
                                else "")
        src = (f"{self.file}:{self.line}: " if self.file and self.line
               else "")
        return f"{src}{self.rule} {self.severity:<7} {loc}: {self.message}"

    def to_obj(self) -> Dict[str, Optional[str]]:
        return {"rule": self.rule, "severity": self.severity,
                "type": self.type_name, "behaviour": self.behaviour,
                "message": self.message, "file": self.file,
                "line": self.line}

    def json_line(self) -> str:
        return json.dumps(self.to_obj(), sort_keys=True)

    def github_line(self) -> str:
        """One GitHub Actions workflow annotation
        (``::warning file=…,line=…::message``) — the `--format github`
        CLI output; severities map error/warning/notice."""
        level = {"error": "error", "warning": "warning",
                 "info": "notice"}[self.severity]
        props = [f"title=lint {self.rule}"]
        if self.file:
            props.insert(0, f"file={self.file}")
            if self.line:
                props.insert(1, f"line={self.line}")
            if self.col:
                props.insert(2, f"col={self.col}")
        loc = self.type_name + (f".{self.behaviour}" if self.behaviour
                                else "")
        text = f"{self.rule} {loc}: {self.message}"
        text = (text.replace("%", "%25").replace("\r", "%0D")
                .replace("\n", "%0A"))
        return f"::{level} {','.join(props)}::{text}"


# ``# lint: ignore`` (all rules) / ``# lint: ignore[R6]`` /
# ``# lint: ignore[R6, R8]`` — trailing-comment line suppressions,
# honoured for every rule that can attach a source line.
_IGNORE_RE = re.compile(
    r"#\s*lint:\s*ignore(?:\s*\[([A-Za-z0-9_,\s]+)\])?")


def ignored_rules_on_line(src_line: str) -> Optional[FrozenSet[str]]:
    """Parse a source line's trailing lint-suppression comment:
    None = no suppression; empty frozenset = suppress ALL rules;
    otherwise the rule ids listed in the brackets."""
    m = _IGNORE_RE.search(src_line)
    if m is None:
        return None
    if m.group(1) is None:
        return frozenset()
    return frozenset(r.strip() for r in m.group(1).split(",")
                     if r.strip())


def line_suppressed(f: Finding, src_line: str) -> bool:
    """Does this source line's comment suppress this finding?"""
    rules = ignored_rules_on_line(src_line)
    return rules is not None and (not rules or f.rule in rules)


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    """Stable report order: severity first, then rule/location — and
    dedupe (Finding is frozen/hashable)."""
    rank = {s: i for i, s in enumerate(SEVERITIES)}
    return sorted(set(findings),
                  key=lambda f: (rank[f.severity], f.rule, f.type_name,
                                 f.behaviour or "", f.line or 0,
                                 f.message))


def _node_str(n: Node) -> str:
    return f"{n[0]}.{n[1]}"


def rule_probe_failures(graph: FlowGraph) -> List[Finding]:
    """R3 for capability/sendability trace failures, R0 otherwise."""
    out = []
    for bf in graph.nodes.values():
        if bf.error is None:
            continue
        if bf.error_kind in ("capability", "sendability"):
            out.append(Finding(
                "R3", "error", bf.type_name, bf.behaviour,
                f"{bf.error_kind} violation at trace: {bf.error}"))
        else:
            out.append(Finding(
                "R0", "error", bf.type_name, bf.behaviour,
                f"behaviour failed to probe-trace ({bf.error}); lint "
                "analysis for it is incomplete"))
    return out


def rule_r1_reachability(graph: FlowGraph,
                         roots: Optional[List[Node]]) -> List[Finding]:
    """≙ reach.c/paint.c: with declared roots, everything a root cannot
    reach through live edges is dead code."""
    if roots is None:
        return []
    reach = graph.reachable(roots)
    dead_by_type: Dict[str, List[Node]] = {}
    for n in graph.nodes:
        if n not in reach:
            dead_by_type.setdefault(n[0], []).append(n)
    out = []
    for tname, dead in dead_by_type.items():
        total = sum(1 for n in graph.nodes if n[0] == tname)
        if len(dead) == total:
            out.append(Finding(
                "R1", "warning", tname, None,
                f"actor type is unreachable: none of its {total} "
                "behaviour(s) can be reached from any lint root "
                "(≙ a type reach.c would prune)"))
        else:
            for n in sorted(dead):
                out.append(Finding(
                    "R1", "warning", n[0], n[1],
                    "behaviour is unreachable from the lint roots — no "
                    "live send/spawn path leads here (≙ a method "
                    "reach.c would prune)"))
    return out


def rule_r2_dead_letter(graph: FlowGraph,
                        roots: Optional[List[Node]]) -> List[Finding]:
    out = []
    seen = set()
    for e in graph.edges:
        if e.external:
            key = (e.src, e.dst[0])
            if key not in seen:
                seen.add(key)
                out.append(Finding(
                    "R2", "error", e.src[0], e.src[1],
                    f"send targets {_node_str(e.dst)} but {e.dst[0]} is "
                    "not part of the analysed program — the message can "
                    "only dead-letter (declare the type, or lint the "
                    "full module)"))
        elif e.when is False:
            key = (e.src, e.dst, "false")
            if key not in seen:
                seen.add(key)
                out.append(Finding(
                    "R2", "warning", e.src[0], e.src[1],
                    f"{e.kind} to {_node_str(e.dst)} is masked "
                    "when=False — the site is provably dead"))
    if roots is not None:
        # Rooted mode: the host is assumed to inject only into roots
        # and spawn only root/host types; a device type that neither a
        # root owns nor any spawn site creates can never hold a live
        # ref — sends to it dead-letter against empty slots.
        root_types = {r[0] for r in roots}
        spawned = graph.spawn_target_types()
        flagged = set()
        for e in graph.edges:
            t = e.dst[0]
            if (e.kind == "send" and not e.external
                    and e.when is not False and t not in flagged
                    and t not in root_types and t not in spawned
                    and t in graph.types and not graph.types[t].host):
                flagged.add(t)
                senders = sorted({_node_str(x.src) for x in graph.edges
                                  if x.dst[0] == t and x.kind == "send"})
                out.append(Finding(
                    "R2", "warning", t, None,
                    "type receives sends (from "
                    + ", ".join(senders)
                    + ") but no spawn site ever creates it and it owns "
                    "no lint root — every such send can only "
                    "dead-letter"))
    return out


def rule_r3_host_blobs(graph: FlowGraph) -> List[Finding]:
    """Device blob handles on HOST cohorts: blobs are device-resident;
    a host behaviour can neither own nor read one (program build
    rejects the cohort — lint catches it before any Program exists)."""
    out = []
    for tf in graph.types.values():
        if not tf.host:
            continue
        for bname, aname, spec in tf.blob_specs():
            what = (f"parameter {aname!r}" if bname
                    else f"state field {aname!r}")
            out.append(Finding(
                "R3", "error", tf.name, bname,
                f"HOST actor type declares a device blob {what} "
                f"({spec.__name__}) — blob handles cannot cross to "
                "host cohorts (use Runtime.blob_fetch/blob_store "
                "between steps)"))
    return out


def rule_r4_amplification(graph: FlowGraph) -> List[Finding]:
    """Unconditional send cycles with multiplicity product > 1: every
    traversal multiplies the messages in flight, and with no yield
    pressure point on the cycle the mailboxes breach mailbox_cap in
    O(log) steps — a static overflow risk the runtime can only answer
    with spill/mute pressure."""
    out = []
    uncond = lambda e: e.kind == "send" and e.when is True  # noqa: E731
    for comp in graph.sccs(uncond):
        members = set(comp)
        if any(graph.nodes[n].effects.can_yield for n in members):
            continue        # a yield on the cycle is a pressure point
        for n in sorted(members):
            m = len(graph.edges_between(n, members, uncond))
            if m >= 2:
                cyc = " ↔ ".join(sorted({t for t, _ in members}))
                out.append(Finding(
                    "R4", "warning", n[0], n[1],
                    f"amplifying message cycle: each dispatch feeds {m} "
                    f"unconditional messages back into the cycle "
                    f"[{cyc}] with no yield pressure point — mailbox "
                    "overflow (mailbox_cap) is a matter of steps; mask "
                    "the sends (when=), or yield on the cycle"))
    return out


def rule_r5_budgets(graph: FlowGraph) -> List[Finding]:
    out = []
    # (a) unconditional spawn / net blob-alloc sites on an unconditional
    # message cycle: each traversal claims pool slots forever.
    uncond_all = lambda e: e.when is True  # noqa: E731
    cyclic: set = set()
    for comp in graph.sccs(uncond_all):
        cyclic.update(comp)
    for n in sorted(cyclic):
        bf = graph.nodes[n]
        spawn_edges = [e for e in graph.out_edges.get(n, ())
                       if e.kind in ("spawn", "spawn_sync")
                       and e.when is True]
        if spawn_edges:
            targets = sorted({e.dst[0] for e in spawn_edges})
            out.append(Finding(
                "R5", "warning", n[0], n[1],
                f"unconditional spawn of {', '.join(targets)} on an "
                "unconditional message cycle: every traversal claims a "
                "slot, so the target capacity/SPAWNS pool provably "
                "exhausts — gate the spawn with when="))
        net = (sum(1 for w in bf.blob_alloc_whens if w is True)
               - bf.blob_free_sites)
        if net > 0 and bf.blob_freeze_sites == 0:
            out.append(Finding(
                "R5", "warning", n[0], n[1],
                f"behaviour on an unconditional message cycle allocates "
                f"{net} more blob(s) than it frees (and freezes none "
                "for GC) — the blob pool (blob_slots) provably "
                "exhausts"))
    # (b) declared budgets nothing uses: each reserves real pool slots
    # (capacity × dispatches × sites windows, program._resolve_*).
    for tf in graph.types.values():
        claimed = set()
        allocs = 0
        for bf in tf.behaviours:
            for f in bf.sends:
                if f.kind in ("spawn", "spawn_sync"):
                    claimed.add(f.dst_type)
            allocs += len(bf.blob_alloc_whens)
        for target in tf.spawns_declared:
            if target not in claimed and not any(
                    bf.error for bf in tf.behaviours):
                out.append(Finding(
                    "R5", "info", tf.name, None,
                    f"SPAWNS declares {target!r} but no behaviour ever "
                    "spawns it — the reservation window "
                    "(capacity × SPAWN_DISPATCHES × sites) is paid for "
                    "nothing"))
        if tf.max_blobs and not allocs and not tf.host and not any(
                bf.error for bf in tf.behaviours):
            out.append(Finding(
                "R5", "info", tf.name, None,
                f"MAX_BLOBS={tf.max_blobs} is declared but no behaviour "
                "ever blob_allocs — the per-dispatch pool reservation "
                "is paid for nothing"))
    return out


def attach_locations(findings: Sequence[Finding],
                     graph: FlowGraph) -> List[Finding]:
    """Fill in file/line on graph-rule findings from the probe facts
    (behaviour def sites via fn.__code__; class sites for type-level
    findings). Findings that already carry a location keep it."""
    out = []
    for f in findings:
        if f.file is None:
            file = line = None
            bf = graph.nodes.get((f.type_name, f.behaviour))
            if f.behaviour is not None and bf is not None:
                file, line = bf.file, bf.line
            else:
                tf = graph.types.get(f.type_name)
                if tf is not None:
                    file, line = tf.file, tf.line
            if file is not None:
                f = dataclasses.replace(f, file=file, line=line)
        out.append(f)
    return out


def run_rules(graph: FlowGraph,
              roots: Optional[List[Node]]) -> List[Finding]:
    findings: List[Finding] = []
    findings += rule_probe_failures(graph)
    findings += rule_r1_reachability(graph, roots)
    findings += rule_r2_dead_letter(graph, roots)
    findings += rule_r3_host_blobs(graph)
    findings += rule_r4_amplification(graph)
    findings += rule_r5_budgets(graph)
    return sort_findings(attach_locations(findings, graph))
